"""Throughput benchmark: frames/sec through the jitted ResNet-50 feature step.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` compares against
a locally recorded reference-equivalent torch-CPU measurement when available
(``BASELINE.json`` key ``measured.resnet50_fps``), else 0.0.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main() -> None:
    import jax
    import jax.numpy as jnp

    from video_features_tpu.models.resnet import ResNet50, preprocess_frames

    batch, size = 64, 224
    model = ResNet50()
    params = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, size, size, 3)), features=False
    )["params"]

    @jax.jit
    def step(params, frames_u8):
        x = preprocess_frames(frames_u8)
        return model.apply({"params": params}, x, features=True).astype(jnp.float32)

    frames = jnp.asarray(
        np.random.default_rng(0).integers(0, 256, (batch, size, size, 3), dtype=np.uint8)
    )
    step(params, frames).block_until_ready()  # compile

    n_iters = 10
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = step(params, frames)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    fps = batch * n_iters / dt

    baseline = 0.0
    try:
        with open(os.path.join(os.path.dirname(__file__), "BASELINE.json")) as f:
            baseline = float(json.load(f).get("measured", {}).get("resnet50_fps", 0.0))
    except Exception:
        pass
    print(
        json.dumps(
            {
                "metric": "resnet50_features_throughput",
                "value": round(fps, 2),
                "unit": "frames/sec",
                "vs_baseline": round(fps / baseline, 3) if baseline else 0.0,
            }
        )
    )


if __name__ == "__main__":
    main()
