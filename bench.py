"""North-star throughput bench: clips/sec/chip for I3D-rgb (headline), I3D-flow(RAFT),
RAFT dense flow, and ResNet-50 — through the REAL extractor device steps.

Prints the headline JSON line {"metric", "value", "unit", "vs_baseline"} (the
I3D-rgb number, per BASELINE.json's metric) TWICE on a full run: once
immediately after the headline config (so a mid-sweep kill still leaves a
parseable record) and again at exit — parsers should take the LAST line.
Every measured config, achieved
TFLOP/s (from XLA's compiled cost analysis), and fp32-vs-bf16 deltas are written to
``bench_details.json``. ``vs_baseline`` compares against the torch reference
computation measured on this host by ``tools/measure_reference.py``
(BASELINE.json key ``measured.i3d_rgb_clips_per_sec``), else 0.0.

Methodology (addresses the round-1 review): inputs VARY across iterations (4
distinct random buffers cycled), every iteration's output is retained and synced
at the end (nothing elided), timing is the median of 3 repeats after a compile +
warmup pass, and FLOPs come from the compiled executable — not hand math.
"""

from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys
import time

import numpy as np


def _log(msg: str) -> None:
    print(f"[bench +{time.perf_counter() - _T0:8.1f}s] {msg}", file=sys.stderr, flush=True)


_T0 = time.perf_counter()

os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")

REPO = os.path.dirname(os.path.abspath(__file__))


def _flops_of(step, *args) -> float:
    """Total FLOPs of one compiled step per XLA cost analysis (0.0 if unavailable)."""
    try:
        cost = step.lower(*args).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float(cost.get("flops", 0.0))
    except Exception:
        return 0.0


def _force(outs) -> float:
    """Force execution of every output with ONE host fetch.

    Methodology note (round-2 finding): the axon tunnel backend memoizes
    identical (executable, args) calls AND returns from ``block_until_ready``
    without waiting, so naive timing measures dispatch, not compute. A scalar
    that data-depends on every output leaf, fetched to host, cannot be faked.
    """
    import jax
    import jax.numpy as jnp

    leaves = [l for l in jax.tree_util.tree_leaves(outs)
              if l is not None and getattr(l, "size", 1)]
    acc = None
    for l in leaves:
        v = l.ravel()[0].astype(jnp.float32)
        acc = v if acc is None else acc + v
    return float(acc) if acc is not None else 0.0


def _time_step(step, make_inputs, iters: int, repeats: int = 3, _retry: bool = True):
    """Median seconds/iteration over ``repeats`` rounds.

    ``make_inputs()`` must return FRESH input arrays every call (unique args
    defeat the backend's result memoization); the per-round host-sync latency
    is measured separately and subtracted. ``iters`` is a lower bound — it is
    auto-raised until one round's compute is ≥ ~6× the sync latency (capped at
    128 iterations / ~1 GB of unique per-call inputs per round), else the
    subtraction is noise-dominated (observed: a fast config reporting 0.0
    s/iter). Returns (sec_per_iter, sync_sec, iters_run) — ``iters_run`` feeds
    the ``noise_limited`` flag in ``record()``.

    The sync baseline is the MIN of 5 samples: a shared-chip stall during the
    baseline can only inflate a sample, and an inflated median once produced a
    negative subtraction → a 76e9-clips/s garbage entry. If the measured round
    still doesn't clear the baseline, the whole measurement retries once with
    a fresh baseline before accepting the floor.
    """
    warm_in = make_inputs()
    warm = step(*warm_in)
    _force(warm)  # compile + first execution
    syncs = sorted(_timeit(lambda: _force(warm)) for _ in range(5))
    sync_min, sync = syncs[0], syncs[2]  # min: subtraction floor; median: typical
    # single-iteration estimate (inputs pre-built: the estimate must not count
    # host RNG/transfer time, which would undersize iters for fast configs).
    # Median of 3 with distinct inputs (memoization!): one noisy estimate
    # OVERestimating a fast config under-sizes the auto-raise below and the
    # measurement lands noise-limited (observed on a ~5 ms resnet step
    # against a ~100 ms sync)
    ests = []
    for _ in range(3):
        est_in = make_inputs()
        _force(est_in)
        ests.append(_timeit(lambda: _force(step(*est_in))))  # noqa: B023
    est = max(statistics.median(ests) - sync, 1e-4)
    # the unique-input budget counts only args rebuilt per call (same-object
    # args — pinned replicated params — transfer once, not per iteration)
    fresh = [i for i, (a, w) in enumerate(zip(est_in, warm_in)) if a is not w]
    in_bytes = sum(getattr(est_in[i], "nbytes", 0) for i in fresh) or 1
    # ~1 GB unique inputs per round: enough for the 51 MB i3d batches to clear
    # the 3x-sync noise bar (record() flags entries that still fall short)
    iters = max(iters, min(int(np.ceil(6 * max(sync, 0.05) / est)),
                           max(int(1e9 / in_bytes), 1), 128))
    raw = []
    for _ in range(repeats):
        ins = [make_inputs() for _ in range(iters)]  # built outside the clock
        _force(ins)  # ALL input transfers completed pre-clock
        t0 = time.perf_counter()
        outs = [step(*ins[i]) for i in range(iters)]
        _force(outs)
        raw.append(time.perf_counter() - t0)
    med = statistics.median(raw)
    if med <= sync_min * 1.05 and _retry:
        # the rounds ran faster than the sync baseline claims possible — the
        # baseline (or the rounds) hit a chip stall; measure again from scratch
        return _time_step(step, make_inputs, iters, repeats, _retry=False)
    # subtract the MIN sync: conservative (a typical-sync subtraction once went
    # negative off a stall-polluted baseline → a 76e9-clips/s garbage entry)
    return max(med - sync_min, 1e-9) / iters, sync, iters


def _timeit(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _git_rev() -> str | None:
    """Short git revision of the code being measured (None outside a repo)."""
    try:
        out = subprocess.run(["git", "-C", REPO, "rev-parse", "--short", "HEAD"],
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip() or None
    except Exception:
        return None


def _apply_platform_redirect() -> None:
    """Apply JAX_PLATFORMS through the config API — the image's sitecustomize
    pins the axon platform there, so the env var alone does not redirect. A
    failed redirect is LOGGED (not swallowed): falling through silently would
    initialize the pinned platform in-process, the unbounded tunnel hang this
    file's probe architecture exists to prevent."""
    want = os.environ.get("JAX_PLATFORMS")
    if not want:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want)
    except Exception as e:  # noqa: BLE001
        _log(f"WARNING: could not redirect jax_platforms to {want!r} "
             f"({e}); the pinned platform may be initialized instead")


def _backend_or_none(retries: int, wait_sec: float,
                     probe_timeout: float | None = None) -> str | None:
    """Establish the JAX backend within a bounded wall-clock window.

    The axon TPU tunnel has produced two driver-run outages in a row
    (BENCH_r03 rc=124, BENCH_r04 rc=1), and a round-5 measurement showed a
    DOWN tunnel takes ~50 minutes to raise from ``jax.default_backend()`` —
    an in-process retry loop would multiply that past any driver budget. So
    each attempt PROBES in a subprocess under a hard timeout (the kill is
    the bound jax's own init doesn't offer); only after a probe succeeds is
    the backend initialized in-process (the tunnel is then known up, so the
    real init is seconds). The in-process init runs under the SAME
    wall-clock watchdog (ADVICE r5): a tunnel drop in the probe→init window
    otherwise re-created the unbounded ~50 min hang — the init happens on a
    daemon thread and an overrun counts as a failed attempt (the wedged
    thread is abandoned; process exit reclaims it). Returns the platform
    string, or None once the retry budget is spent — the caller then emits
    a structured stale record instead of a traceback.
    """
    import threading

    def _init_in_process() -> tuple[str | None, str, bool]:
        """(backend, error, wedged) — jax.default_backend() bounded by
        probe_timeout. ``wedged``: the init thread is still alive past the
        deadline — it holds jax's internal backend-init lock, so EVERY later
        in-process attempt would block behind it; the caller must give up
        (emit the stale record) rather than burn the retry budget on
        attempts that can no longer succeed in this process."""
        box: dict = {}

        def target():
            try:
                import jax

                # same redirect the probe subprocess applied
                _apply_platform_redirect()
                box["backend"] = jax.default_backend()
            except Exception as e:  # noqa: BLE001
                box["err"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=target, daemon=True, name="bench-backend-init")
        t.start()
        t.join(probe_timeout)
        if "backend" in box:
            return box["backend"], "", False
        if t.is_alive():
            return None, (f"in-process init exceeded {probe_timeout:.0f}s "
                          "after a successful probe (tunnel dropped between "
                          "probe and init?); the wedged thread poisons any "
                          "further in-process init"), True
        return None, box.get("err", "in-process init produced no backend"), False
    if probe_timeout is None:
        probe_timeout = float(os.environ.get("VFT_BENCH_INIT_TIMEOUT", 180))
    for attempt in range(retries):
        why = ""
        try:
            # the sitecustomize pins the axon platform through the config
            # API, so the probe must apply JAX_PLATFORMS the same way main()
            # does — the env var alone doesn't redirect a cpu smoke run
            probe_code = (
                "import os, jax\n"
                "w = os.environ.get('JAX_PLATFORMS')\n"
                "if w:\n"
                "    jax.config.update('jax_platforms', w)\n"
                "print('BACKEND=' + jax.default_backend())\n")
            out = subprocess.run(
                [sys.executable, "-c", probe_code],
                capture_output=True, text=True, timeout=probe_timeout)
            if any(line.startswith("BACKEND=") for line in out.stdout.splitlines()):
                # probe ok → watchdogged real init
                backend, why, wedged = _init_in_process()
                if backend is not None:
                    return backend
                if wedged:
                    _log(f"backend init wedged after a successful probe: {why}")
                    return None  # retrying cannot recover in this process
            else:
                why = (out.stderr.strip().splitlines() or ["no backend line"])[-1]
        except subprocess.TimeoutExpired:
            why = f"probe timed out after {probe_timeout:.0f}s"
        except Exception as e:  # noqa: BLE001
            why = str(e)
        if attempt + 1 >= retries:
            _log(f"backend probe failed after {retries} attempts: {why[:200]}")
            return None
        _log(f"backend probe failed (attempt {attempt + 1}/{retries}), "
             f"retrying in {wait_sec:.0f}s: {why[:160]}")
        time.sleep(wait_sec)
    return None


def _read_baseline() -> tuple[float, dict]:
    """(headline baseline, full measured dict) from BASELINE.json — the one
    reader both the live headline and the stale fallback share."""
    try:
        with open(os.path.join(REPO, "BASELINE.json")) as f:
            measured = json.load(f).get("measured", {})
        return float(measured.get("i3d_rgb_clips_per_sec", 0.0)), measured
    except Exception:
        return 0.0, {}


def _emit_stale_record(reason: str) -> None:
    """TPU unreachable: print a VALID headline line (rc=0) explicitly marked
    stale. A bench harness whose record can be sunk by a tunnel outage has
    failed at its one job — the driver's parser takes the last JSON line
    either way. The headline ``value`` is 0.0 (ADVICE r5): this run measured
    NOTHING, and a consumer that parses only value/vs_baseline must never
    credit the current revision with an old revision's throughput. The last
    committed clean number rides along as ``last_known_value``."""
    last_known = 0.0
    stale_rev = None
    try:
        with open(os.path.join(REPO, "bench_details.json")) as f:
            prev = json.load(f)
        last_known = float(prev.get("i3d_rgb_float32", {}).get("value", 0.0))
        stale_rev = prev.get("code_rev")
    except Exception:
        pass
    baseline, _ = _read_baseline()
    print(json.dumps({
        "metric": "i3d_rgb_clips_per_sec_per_chip",
        "value": 0.0,
        "unit": "clips/sec/chip (64-frame 224² stacks)",
        "vs_baseline": 0.0,
        "error": reason,
        "stale": True,
        "last_known_value": last_known,
        "last_known_vs_baseline": (round(last_known / baseline, 3)
                                   if baseline else 0.0),
        "stale_source": "bench_details.json i3d_rgb_float32"
                        + (f" @ {stale_rev}" if stale_rev else ""),
    }), flush=True)


def _repeats(on_cpu: bool) -> int:
    return 1 if on_cpu else 3  # 1-core CPU smoke run vs real measurement


def main() -> None:
    import jax

    # the image's sitecustomize pins the axon TPU platform; honor an explicit
    # JAX_PLATFORMS=cpu (CPU smoke run) the way main.py does
    _apply_platform_redirect()
    # persistent compilation cache: TPU compiles go over the tunnel and dominate
    # bench wall time; cache them so reruns (and the driver's run) skip straight
    # to execution
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache"),
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        pass

    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.extractors.i3d import ExtractI3D
    from video_features_tpu.extractors.resnet import ExtractResNet50

    for flag in ("VFT_I3D_TAP_FP32", "VFT_I3D_S2D"):
        if os.environ.pop(flag, None) is not None:
            # a pre-set flag would silently re-lower every fp32 I3D config,
            # including the bit-parity headline; bench entries must be
            # single-lowering — each flag applies only to its own
            # i3d_rgb_float32_{tapconv,s2d} config
            _log(f"{flag} was set in the environment; cleared — bench "
                 f"applies it only to its dedicated stem config")

    backend = _backend_or_none(
        retries=int(os.environ.get("VFT_BENCH_INIT_RETRIES", 3)),
        wait_sec=float(os.environ.get("VFT_BENCH_INIT_WAIT", 45)))
    if backend is None:
        _emit_stale_record("tpu_unavailable")
        return
    on_cpu = backend == "cpu"
    n_chips = jax.local_device_count()  # extractors mesh over all local devices
    rng = np.random.default_rng(0)
    code_rev = _git_rev()
    details = {"backend": backend, "device": str(jax.devices()[0]),
               "code_rev": code_rev}
    peak_tflops = float(os.environ.get("VFT_PEAK_TFLOPS", 0)) or None
    if peak_tflops is None:
        # published bf16 peaks per chip (the MFU denominator for MXU work),
        # keyed by the parsed (generation, variant) — not substring matching,
        # which could false-match future device strings (e.g. 'v4' in 'v40')
        import re

        known = {("4", ""): 275.0, ("5", "lite"): 197.0, ("5", "e"): 197.0,
                 ("5", "p"): 459.0, ("6", "lite"): 918.0, ("6", "e"): 918.0}
        m = re.search(r"v(\d+)\s*(lite|p|e)?", details["device"].lower())
        peak_tflops = known.get((m.group(1), m.group(2) or "")) if m else None
        if peak_tflops:
            details["peak_tflops_bf16_assumed"] = peak_tflops
        else:
            _log(f"no published peak-TFLOPs entry for device "
                 f"{details['device']!r}; MFU columns will be omitted "
                 f"(override with VFT_PEAK_TFLOPS)")

    def cfg(feature_type, **kw):
        return ExtractionConfig(
            feature_type=feature_type,
            output_path=os.path.join("/tmp/vft_bench", "out"),
            tmp_path=os.path.join("/tmp/vft_bench", "tmp"),
            **kw,
        )

    # details are flushed after EVERY entry: a late-section failure (e.g. an
    # OOM compiling one e2e config) must not lose the whole run's record
    details_name = "bench_details_cpu_smoke.json" if on_cpu else "bench_details.json"

    # merge-update: start from the committed record so a partial run (budget
    # skip or a kill) REFINES the file instead of clobbering entries it never
    # re-measured (round 3: a timed-out driver run overwrote the 26-entry
    # record with a 10-entry partial)
    try:
        with open(os.path.join(REPO, details_name)) as f:
            prev = json.load(f)
        if prev.get("device") == details["device"]:
            # a stale skip-list must not survive into this run's flushes (the
            # final block recomputes it; a kill before that would otherwise
            # leave entries claiming configs this run actually re-measured)
            prev.pop("budget_skipped", None)
            # provenance (round-4 advisor): retained entries measured under an
            # older code revision must not read as current data — stamp each
            # with the rev it was measured at. record() overwrites the stamp
            # (and the run_failures slot) when THIS run re-measures a config.
            # a pre-code_rev record stamps "unknown": leaving it unstamped
            # would let a LATER run mis-attribute these entries to its own
            # predecessor's rev (the "code_rev" not in v guard only works
            # if every pass stamps something truthful)
            prev_rev = prev.get("code_rev") or "unknown"
            for k, v in prev.items():
                if isinstance(v, dict) and "code_rev" not in v and (
                        "value" in v or "videos_per_sec" in v or "failed" in v):
                    v["code_rev"] = prev_rev
            prev.update(details)
            details = prev
        # a different device invalidates old entries — start fresh
    except Exception:
        pass

    # wall-clock budget (docs/budgets.md): the driver kills overlong runs with
    # nothing parsed; skipping the remaining configs gracefully keeps the
    # summary line printable and the measured entries recorded
    deadline = _T0 + float(os.environ.get("VFT_BENCH_BUDGET", 1500))
    skipped: list = []

    def over_budget(name: str) -> bool:
        if time.perf_counter() > deadline:
            if name not in skipped:
                skipped.append(name)
                _log(f"{name}: SKIPPED (over VFT_BENCH_BUDGET; committed entry "
                     "retained)")
            return True
        return False

    def flush_details():
        # atomic swap: a kill mid-write must not truncate the record the
        # incremental flushing exists to protect
        path = os.path.join(REPO, details_name)
        with open(path + ".tmp", "w") as f:
            json.dump(details, f, indent=2)
        os.replace(path + ".tmp", path)

    import contextlib

    def clear_failure(name):
        # a fresh measurement supersedes a stale failure note for this config
        if name in details.get("run_failures", {}):
            del details["run_failures"][name]
            if not details["run_failures"]:
                del details["run_failures"]

    @contextlib.contextmanager
    def guarded(name):
        """Per-config fault barrier: a compile failure (e.g. a Mosaic helper
        crash on one shape) records the failure and the sweep continues — one
        bad config must not sink the remaining record. Failures land under
        ``run_failures`` so a transient error cannot clobber a committed good
        entry for the same config (the merge-update contract); the headline
        fp32 config is deliberately NOT guarded — with no headline there is
        no record, and the driver must see the nonzero exit."""
        try:
            yield
        except Exception as e:  # noqa: BLE001
            details.setdefault("run_failures", {})[name] = str(e)[:300]
            flush_details()
            _log(f"{name}: FAILED — {str(e)[:160]}")

    def record(name, timing, units_per_iter, unit, flops_per_iter, chips=None):
        secs_per_iter, sync, iters_run = timing
        tflops = flops_per_iter / secs_per_iter / 1e12 if flops_per_iter else None
        entry = {
            # `chips`: the entry's actual mesh size when it differs from the
            # host's device count (the flow benches pin num_devices=1)
            "value": round(units_per_iter / secs_per_iter / (chips or n_chips), 3),
            "unit": unit,
            "sec_per_iter": round(secs_per_iter, 5),
            "host_sync_sec": round(sync, 4),
            "achieved_tflops_per_sec": round(tflops, 2) if tflops else None,
        }
        if iters_run * secs_per_iter < 3 * sync:
            # signal below 3× the (jittery) sync latency: the subtraction can
            # dominate the measurement — do not trust this entry's magnitude
            entry["noise_limited"] = True
        if tflops and peak_tflops:
            entry["mfu_vs_peak"] = round(tflops / peak_tflops, 4)
        entry["code_rev"] = code_rev
        details[name] = entry
        clear_failure(name)
        flush_details()
        _log(f"{name}: {entry['value']} {unit} "
             f"({entry['sec_per_iter']}s/iter, {entry['achieved_tflops_per_sec']} TFLOP/s, "
             f"sync {sync * 1e3:.0f}ms)")
        return entry

    baseline, measured = _read_baseline()
    if measured:
        details["reference_measured"] = measured

    headline = None

    def print_summary():
        # printed right after the headline config (so a later kill loses
        # nothing) and re-printed at exit
        if headline is None:
            return
        value = headline["value"]
        print(
            json.dumps(
                {
                    "metric": "i3d_rgb_clips_per_sec_per_chip",
                    "value": value,
                    "unit": "clips/sec/chip (64-frame 224² stacks)",
                    "vs_baseline": round(value / baseline, 3) if baseline else 0.0,
                }
            ),
            flush=True,
        )

    # ---- I3D-rgb (headline): clips/sec/chip, 64-frame 256→224 stacks ----------
    # default 4 clips/step: across clean runs on the shared v5e tunnel, 8-clip
    # batches never beat 4 per-clip (run-to-run variance on this chip is large;
    # see BASELINE.md)
    clips = int(os.environ.get("VFT_BENCH_CLIPS", 1 if on_cpu else 4))
    stack = 16 if on_cpu else 64  # CPU smoke run shrinks the clip, same code path
    iters = 2 if on_cpu else 8
    for dtype in ("float32",) if on_cpu else ("float32", "bfloat16"):
        if dtype != "float32" and over_budget(f"i3d_rgb_{dtype}"):
            continue
        # the fp32 HEADLINE config is unguarded on purpose: if it fails there
        # is no summary line and the driver must see the nonzero exit
        barrier = (contextlib.nullcontext() if dtype == "float32"
                   else guarded(f"i3d_rgb_{dtype}"))
        with barrier:
            ex = ExtractI3D(cfg("i3d", streams=("rgb",), stack_size=stack,
                                step_size=stack, clips_per_batch=clips, dtype=dtype))
            _log(f"i3d_rgb_{dtype}: built extractor "
                 f"({ex.clips_per_batch} clips × {stack + 1} frames × 256², mesh-rounded)")

            def mk(ex=ex):
                return (ex.i3d_params["rgb"],
                        ex.runner.put(rng.integers(0, 256,
                                                   (ex.clips_per_batch, stack + 1, 256, 256, 3),
                                                   dtype=np.uint8)))

            _log(f"i3d_rgb_{dtype}: compiling + timing")
            timing = _time_step(ex._rgb_step, mk, iters, _repeats(on_cpu))
            e = record(f"i3d_rgb_{dtype}", timing, ex.clips_per_batch * stack / 64.0,
                       "clips/sec/chip", _flops_of(ex._rgb_step, *mk()))
            if dtype == "float32":
                headline = e
                print_summary()  # headline secured — a later kill loses nothing

    # fp32 stem lowering candidates (the stem is 21 of 33 ms —
    # docs/architecture.md): TapConv3D (VFT_I3D_TAP_FP32 — reassociates the
    # temporal sum) and the space-to-depth stem (VFT_I3D_S2D — folded taps
    # add only zero products, ~1e-5 drift). Neither is the bit-parity
    # headline; whichever wins informs the default-flip decision.
    for tag, env_key in (("tapconv", "VFT_I3D_TAP_FP32"), ("s2d", "VFT_I3D_S2D")):
        name = f"i3d_rgb_float32_{tag}"
        if on_cpu or over_budget(name):
            continue
        os.environ[env_key] = "1"
        try:
            with guarded(name):
                ex = ExtractI3D(cfg("i3d", streams=("rgb",), stack_size=stack,
                                    step_size=stack, clips_per_batch=clips,
                                    dtype="float32"))

                def mk_stem(ex=ex):
                    return (ex.i3d_params["rgb"],
                            ex.runner.put(rng.integers(
                                0, 256, (ex.clips_per_batch, stack + 1, 256, 256, 3),
                                dtype=np.uint8)))

                timing = _time_step(ex._rgb_step, mk_stem, iters, _repeats(on_cpu))
                record(name, timing,
                       ex.clips_per_batch * stack / 64.0, "clips/sec/chip",
                       _flops_of(ex._rgb_step, *mk_stem()))
        finally:
            del os.environ[env_key]

    # ---- I3D-flow composites: flow net + transform sandwich + I3D, one step ----
    # pwc is the reference's default flow for i3d (main.py:72-73); raft is the
    # north-star accuracy path. On multi-chip hosts these flow-only 1-clip
    # configs route through the encode-once FRAME-sharded step (PR 2): one
    # clip's 64 source frames sharded across the mesh + the replicated final
    # frame, instead of padding the clip axis to the mesh size.
    def i3d_flow_step_and_inputs(ex):
        if getattr(ex, "_flow_frame_sharded", False):
            def mk(ex=ex):
                stack = rng.integers(0, 256, (65, 256, 256, 3), dtype=np.uint8)
                return (ex.i3d_params["flow"], ex.runner.put(stack[:-1]),
                        ex.runner.put_replicated(stack[-1:]))

            return ex._flow_step_sharded, mk

        def mk(ex=ex):
            return (ex.i3d_params["flow"],
                    ex.runner.put(rng.integers(
                        0, 256, (ex.clips_per_batch, 65, 256, 256, 3),
                        dtype=np.uint8)))

        return ex._flow_step, mk

    if not on_cpu:
        for flow_type in ("pwc", "raft"):
            for flow_dtype in ("float32", "bfloat16"):
                if over_budget(f"i3d_flow_{flow_type}_{flow_dtype}"):
                    continue
                with guarded(f"i3d_flow_{flow_type}_{flow_dtype}"):
                    _log(f"i3d_flow_{flow_type}_{flow_dtype}: building extractor + inputs")
                    ex = ExtractI3D(cfg("i3d", streams=("flow",), flow_type=flow_type,
                                        stack_size=64, step_size=64, clips_per_batch=1,
                                        flow_dtype=flow_dtype))
                    step, mk_flow = i3d_flow_step_and_inputs(ex)
                    timing = _time_step(step, mk_flow, iters=2)
                    record(f"i3d_flow_{flow_type}_{flow_dtype}", timing,
                           ex.clips_per_batch, "clips/sec/chip",
                           _flops_of(step, *mk_flow()))

        # performance-max two-stream flow step: BOTH the flow net and the I3D
        # conv stack in bf16 (the configs above keep the I3D side fp32)
        if not over_budget("i3d_flow_pwc_allbf16"):
            with guarded("i3d_flow_pwc_allbf16"):
                ex = ExtractI3D(cfg("i3d", streams=("flow",), flow_type="pwc",
                                    stack_size=64, step_size=64, clips_per_batch=1,
                                    dtype="bfloat16", flow_dtype="bfloat16"))
                step, mk_flow_ab = i3d_flow_step_and_inputs(ex)
                timing = _time_step(step, mk_flow_ab, iters=2)
                record("i3d_flow_pwc_allbf16", timing, ex.clips_per_batch,
                       "clips/sec/chip", _flops_of(step, *mk_flow_ab()))

    # ---- RAFT dense flow: pairs/sec at 256² (20 GRU iterations) ---------------
    # production single-chip path: the shared-frame step (each frame encoded
    # once); the multi-chip encode-once step has its own entry below
    pairs, side = (1, 128) if on_cpu else (16, 256)
    for flow_dtype in ("float32",) if on_cpu else ("float32", "bfloat16"):
        if over_budget(f"raft_pairs_{flow_dtype}"):
            continue
        with guarded(f"raft_pairs_{flow_dtype}"):
            _log(f"raft_pairs_{flow_dtype}: building extractor + inputs "
                 f"({pairs} pairs × {side}²)")
            ex = ExtractFlow(cfg("raft", batch_size=pairs, num_devices=1,
                                 flow_dtype=flow_dtype))

            def mk_pairs(ex=ex):
                fr = rng.uniform(0, 255, (ex.batch_size + 1, side, side, 3)).astype(np.float32)
                return (ex.params, ex.runner.put(fr))

            timing = _time_step(ex._frames_step, mk_pairs, iters=1 if on_cpu else 6,
                                repeats=_repeats(on_cpu))
            record(f"raft_pairs_{flow_dtype}", timing, ex.batch_size, "pairs/sec/chip",
                   _flops_of(ex._frames_step, *mk_pairs()), chips=ex.runner.num_devices)

    # ---- RAFT dense flow, encode-once across the whole mesh (PR 2) ------------
    # the production multi-device ExtractFlow path: B source frames sharded on
    # the frame axis + the replicated final frame, pairs formed on device by
    # halo exchange — vs the retired pair-split step that encoded every
    # interior frame twice on meshes > 1 chip
    if not on_cpu and n_chips > 1 and not over_budget("raft_pairs_float32_sharded"):
        with guarded("raft_pairs_float32_sharded"):
            ex = ExtractFlow(cfg("raft", batch_size=max(16, n_chips)))
            _log(f"raft_pairs_float32_sharded: {ex.batch_size} pairs × {side}² "
                 f"over {n_chips} chips")

            def mk_sharded(ex=ex):
                fr = rng.uniform(0, 255, (ex.batch_size + 1, side, side, 3)
                                 ).astype(np.float32)
                return (ex.params, ex.runner.put(fr[:-1]),
                        ex.runner.put_replicated(fr[-1:]))

            timing = _time_step(ex._frames_step_sharded, mk_sharded, iters=6)
            record("raft_pairs_float32_sharded", timing, ex.batch_size,
                   "pairs/sec/chip", _flops_of(ex._frames_step_sharded, *mk_sharded()))

    # ---- PWC dense flow: pairs/sec at 256², xla vs auto cost volume -----------
    # auto = the production default: tiled/single-block Pallas volume kernels
    # where the VMEM gates admit the shape, fused-XLA elsewhere (the fused
    # warp+corr kernel stays opt-in — ops/pallas_corr._fused_compile_ok).
    # The b2 pair preserves round-3 continuity.
    pwc_configs = [("xla", pairs, "float32")]
    if not on_cpu:
        pwc_configs += [("auto", pairs, "float32"),
                        ("xla", pairs, "bfloat16"), ("auto", pairs, "bfloat16"),
                        ("xla", 2, "float32"), ("pallas", 2, "float32")]
    for corr, b, flow_dtype in pwc_configs:
        if over_budget(f"pwc_pairs_{flow_dtype}_{corr}_b{b}"):
            continue
        with guarded(f"pwc_pairs_{flow_dtype}_{corr}_b{b}"):
            _log(f"pwc_pairs_{flow_dtype}_{corr}_b{b}: building extractor + inputs "
                 f"({b} pairs × {side}²)")
            ex = ExtractFlow(cfg("pwc", batch_size=b, pwc_corr=corr, num_devices=1,
                                 flow_dtype=flow_dtype))

            def mk_pwc(ex=ex):
                fr = rng.uniform(0, 255, (ex.batch_size + 1, side, side, 3)).astype(np.float32)
                return (ex.params, ex.runner.put(fr))

            timing = _time_step(ex._frames_step, mk_pwc, iters=1 if on_cpu else 6,
                                repeats=_repeats(on_cpu))
            record(f"pwc_pairs_{flow_dtype}_{corr}_b{b}", timing, ex.batch_size,
                   "pairs/sec/chip", _flops_of(ex._frames_step, *mk_pwc()),
                   chips=ex.runner.num_devices)

    # ---- R(2+1)D: clips/sec, 16-frame 112² slices (reference r21d geometry) ---
    if not on_cpu:
        from video_features_tpu.extractors.r21d import ExtractR21D

        for dtype in ("float32", "bfloat16"):
            if over_budget(f"r21d_{dtype}"):
                continue
            with guarded(f"r21d_{dtype}"):
                _log(f"r21d_{dtype}: building extractor + inputs")
                ex = ExtractR21D(cfg("r21d_rgb", clips_per_batch=8, dtype=dtype))

                def mk_r21d(ex=ex):
                    return (ex.params,
                            ex.runner.put(rng.integers(
                                0, 256, (ex.clips_per_batch, 16, 128, 171, 3),
                                dtype=np.uint8)))

                timing = _time_step(ex._step, mk_r21d, iters=8, repeats=_repeats(on_cpu))
                record(f"r21d_{dtype}", timing, ex.clips_per_batch, "clips/sec/chip",
                       _flops_of(ex._step, *mk_r21d()))

    # ---- VGGish: 0.96s examples/sec --------------------------------------------
    if not on_cpu and not over_budget("vggish_float32"):
        with guarded("vggish_float32"):
            from video_features_tpu.extractors.vggish import ExtractVGGish

            _log("vggish: building extractor + inputs")
            ex = ExtractVGGish(cfg("vggish"))

            def mk_vggish(ex=ex):
                return (ex.params,
                        ex.runner.put(rng.standard_normal(
                            (ex.example_batch, 96, 64)).astype(np.float32)))

            timing = _time_step(ex._step, mk_vggish, iters=8, repeats=_repeats(on_cpu))
            record("vggish_float32", timing, ex.example_batch, "examples/sec/chip",
                   _flops_of(ex._step, *mk_vggish()))

    # ---- ResNet-50 frames/sec (round-1 metric, kept for continuity) -----------
    batch = 4 if on_cpu else 64
    for dtype in ("float32",) if on_cpu else ("float32", "bfloat16"):
        if over_budget(f"resnet50_{dtype}"):
            continue
        with guarded(f"resnet50_{dtype}"):
            _log(f"resnet50_{dtype}: building extractor + inputs")
            ex = ExtractResNet50(cfg("resnet50", batch_size=batch, dtype=dtype))

            def mk_frames(ex=ex):
                return (ex.params,
                        ex.runner.put(rng.integers(0, 256, (ex.batch_size, 224, 224, 3),
                                                   dtype=np.uint8)))

            timing = _time_step(ex._step, mk_frames, iters=2 if on_cpu else 16,
                                repeats=_repeats(on_cpu))
            record(f"resnet50_{dtype}", timing, ex.batch_size, "frames/sec/chip",
                   _flops_of(ex._step, *mk_frames()))

    # ---- packed-corpus continuous batching (--pack_corpus) --------------------
    # Many SHORT videos: the per-video loop pays a zero-padded tail batch per
    # video and drains the mesh between videos; the packer fills every device
    # batch across videos. packing_occupancy = real slots / dispatched device
    # slots; the same corpus's per-video tail-padding occupancy is recorded
    # alongside as the baseline it must beat. The packer covers every feature
    # type: resnet50 frame slots, flow frame-pair slots chained through the
    # collate seam, vggish log-mel slabs, and mixed-resolution corpora
    # bucketed into ≤ --pack_buckets padded shapes (that entry adds the
    # per-bucket breakdown). Headline I3D metric untouched. A down TPU tunnel
    # is handled upstream: the stale headline record is emitted before any
    # scenario runs, and the committed entries below are retained by the
    # merge-update contract.
    import shutil

    def write_corpus(subdir, sizes_frames):
        import cv2

        d = os.path.join("/tmp/vft_bench", subdir)
        shutil.rmtree(d, ignore_errors=True)
        os.makedirs(d, exist_ok=True)
        rng_c = np.random.default_rng(11)
        paths = []
        for i, (size, n_frames) in enumerate(sizes_frames):
            p = os.path.join(d, f"clip{i:02d}.mp4")
            wr = cv2.VideoWriter(p, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
            for _ in range(n_frames):
                wr.write(rng_c.integers(0, 256, (size[1], size[0], 3),
                                        dtype=np.uint8))
            wr.release()
            paths.append(p)
        return paths

    def bench_packed(name, ex, corpus, slots_unit, batch_size, warm=None,
                     record_buckets=False):
        if warm is not None:
            warm()  # compile outside the timed pass
        shutil.rmtree(ex.output_dir, ignore_errors=True)
        t0 = time.perf_counter()
        ok = ex.run(corpus)
        wall = time.perf_counter() - t0
        stats = ex._pack_stats
        # per-video tail baseline from the ACTUAL per-video clip counts
        unpacked_slots = sum(-(-c // batch_size) * batch_size
                             for c in stats["video_clips"].values()
                             if c) or 1
        entry = {
            "videos_per_sec": round(ok / wall, 3),
            "videos": ok,
            "wall_sec": round(wall, 3),
            "unit": slots_unit,
            "packing_occupancy": stats["occupancy"],
            "real_slots": stats["real_slots"],
            "dispatched_slots": stats["dispatched_slots"],
            "unpacked_tail_occupancy": round(
                stats["real_slots"] / unpacked_slots, 4),
            "code_rev": code_rev,
        }
        if record_buckets or len(stats["buckets"]) > 1:
            entry["buckets"] = stats["buckets"]
            entry["n_buckets"] = len(stats["buckets"])
        details[name] = entry
        clear_failure(name)
        flush_details()
        _log(f"{name}: {entry['videos_per_sec']} videos/s, occupancy "
             f"{entry['packing_occupancy']} (unpacked tail baseline "
             f"{entry['unpacked_tail_occupancy']})")
        return entry

    if not over_budget("packed_corpus_resnet50"):
        with guarded("packed_corpus_resnet50"):
            n_videos = 4 if on_cpu else 16
            corpus = write_corpus(
                "short_corpus",
                [((64, 48), 3 + (i % 4) if on_cpu else 6 + (i % 10))
                 for i in range(n_videos)])
            ex = ExtractResNet50(cfg("resnet50",
                                     batch_size=4 if on_cpu else 64,
                                     pack_corpus=True,
                                     on_extraction="save_numpy",
                                     decode_workers=1 if on_cpu else 4))
            _log(f"packed_corpus_resnet50: {n_videos} short videos, "
                 f"batch {ex.batch_size}")

            def warm_resnet(ex=ex):
                # warm the single jit signature outside the timed pass
                _force(ex._step(ex.params, ex.runner.put(
                    rng.integers(0, 256, (ex.batch_size, 224, 224, 3),
                                 dtype=np.uint8))))

            bench_packed("packed_corpus_resnet50", ex, corpus, "frame slots",
                         ex.batch_size, warm=warm_resnet)

    # ---- telemetry overhead (--telemetry_dir, docs/observability.md) ----------
    # The observability acceptance gate: the span journal must cost <2%
    # wall-clock. Same packed resnet50 corpus with the journal ON vs OFF —
    # each mode's extractor warmed outside the timed pass, best of 3 runs
    # per mode (small corpora make single runs scheduler-noisy) — plus the
    # journal's bytes/video footprint and its drop counter (a bounded
    # journal that dropped events would make the wall number a lie).
    if not over_budget("telemetry_overhead"):
        with guarded("telemetry_overhead"):
            n_videos = 4 if on_cpu else 16
            corpus = write_corpus(
                "telemetry_corpus",
                [((64, 48), 3 + (i % 4) if on_cpu else 6 + (i % 10))
                 for i in range(n_videos)])
            tdir = os.path.join("/tmp/vft_bench", "telemetry")
            shutil.rmtree(tdir, ignore_errors=True)
            tel_passes = 3

            def run_telemetry_mode(telemetry_dir):
                ex = ExtractResNet50(cfg(
                    "resnet50", batch_size=4 if on_cpu else 64,
                    pack_corpus=True, on_extraction="save_numpy",
                    decode_workers=1 if on_cpu else 4,
                    telemetry_dir=telemetry_dir))
                _force(ex._step(ex.params, ex.runner.put(
                    rng.integers(0, 256, (ex.batch_size, 224, 224, 3),
                                 dtype=np.uint8))))  # warm outside the clock
                best = float("inf")
                dropped = write_errors = 0
                for _ in range(tel_passes):
                    shutil.rmtree(ex.output_dir, ignore_errors=True)
                    t0 = time.perf_counter()
                    ok = ex.run(corpus)
                    best = min(best, time.perf_counter() - t0)
                    if ok != n_videos:
                        raise RuntimeError(
                            f"telemetry_overhead: {ok}/{n_videos} succeeded")
                    if ex._journal is not None:
                        # SUMMED across passes: each run closes and reopens
                        # the journal, and the drop guard must cover the
                        # pass whose wall time the min() selected
                        jstats_pass = ex._journal.stats()
                        dropped += jstats_pass["dropped"]
                        write_errors += jstats_pass["write_errors"]
                return best, dropped, write_errors

            _log(f"telemetry_overhead: {n_videos} packed videos, journal "
                 f"off vs on ({tel_passes} passes each)")
            wall_off, _d, _e = run_telemetry_mode(None)
            wall_on, tel_dropped, tel_write_errors = run_telemetry_mode(tdir)
            journal_path = os.path.join(tdir, "events.jsonl")
            journal_bytes = os.path.getsize(journal_path)
            overhead = (wall_on - wall_off) / wall_off * 100.0
            entry = {
                "videos": n_videos,
                "wall_off_sec": round(wall_off, 3),
                "wall_on_sec": round(wall_on, 3),
                "overhead_pct": round(overhead, 2),
                # acceptance: <2% wall-clock with the journal enabled
                "within_2pct_budget": bool(overhead < 2.0),
                # the file accumulates across the passes (append mode)
                "journal_bytes_per_video": round(
                    journal_bytes / (tel_passes * n_videos), 1),
                "journal_dropped": tel_dropped,
                "journal_write_errors": tel_write_errors,
                "code_rev": code_rev,
            }
            details["telemetry_overhead"] = entry
            clear_failure("telemetry_overhead")
            flush_details()
            _log(f"telemetry_overhead: {entry['overhead_pct']}% wall delta "
                 f"({wall_off:.3f}s → {wall_on:.3f}s), "
                 f"{entry['journal_bytes_per_video']} journal bytes/video, "
                 f"{tel_dropped} dropped")

    flow_size = (32, 24) if on_cpu else (64, 48)
    flow_batch = 2 if on_cpu else 16
    flow_geom = (flow_size[1], flow_size[0])  # (H, W), /8-aligned already

    def warm_flow(ex):
        import jax

        # wire dtype (uint8 unless --float32_wire): warm the EXACT program
        # the packed dispatch runs
        window = np.zeros((ex.batch_size + 1, *flow_geom, 3), ex._wire)
        jax.block_until_ready(ex._device_call(window))

    if not over_budget("packed_flow_raft"):
        with guarded("packed_flow_raft"):
            n = 3 if on_cpu else 12
            corpus = write_corpus(
                "flow_corpus",
                [(flow_size, 4 + (i % 4) if on_cpu else 8 + (i % 12))
                 for i in range(n)])
            ex = ExtractFlow(cfg("raft", batch_size=flow_batch,
                                 num_devices=1, pack_corpus=True,
                                 on_extraction="save_numpy"))
            _log(f"packed_flow_raft: {n} short videos, "
                 f"{ex.batch_size}-pair windows at {flow_geom}")
            bench_packed("packed_flow_raft", ex, corpus, "pair slots",
                         ex.batch_size, warm=lambda: warm_flow(ex))

    if not over_budget("packed_mixed_geometry"):
        with guarded("packed_mixed_geometry"):
            small = (24, 16) if on_cpu else (48, 32)
            n = 4 if on_cpu else 10
            corpus = write_corpus(
                "mixed_corpus",
                [(flow_size if i % 2 else small, 4 + (i % 3) if on_cpu
                  else 8 + (i % 8)) for i in range(n)])
            # --pack_buckets 1 merges both probed geometries into ONE padded
            # bucket — the merged bucket equals packed_flow_raft's geometry,
            # so the warmed program is reused (no extra compile)
            ex = ExtractFlow(cfg("raft", batch_size=flow_batch,
                                 num_devices=1, pack_corpus=True,
                                 pack_buckets=1, on_extraction="save_numpy"))
            _log(f"packed_mixed_geometry: {n} videos over 2 geometries "
                 f"→ ≤1 bucket at {flow_geom}")
            bench_packed("packed_mixed_geometry", ex, corpus,
                         "pair slots", ex.batch_size,
                         warm=lambda: warm_flow(ex), record_buckets=True)

    # ---- ragged paged dispatch (--paged_batching, docs/performance.md) --------
    # The SAME mixed-geometry corpus through the default depth-2 paged
    # dispatch vs the bucketed loop (--no_paged_batching): pad-waste ratio =
    # padded rows / dispatched rows. The paged flush tail is bounded by one
    # partial PAGE (≤ page_rows - 1 rows) instead of one partial batch, so on
    # a corpus whose slot total is ≡ page_rows (mod batch) the paged waste
    # lands strictly below the bucketed waste; the observed in-flight ring
    # depth (≥ 2 under paged dispatch, exactly 1 bucketed) is recorded
    # alongside. Stale-record protocol unchanged: rides guarded()/
    # clear_failure like every packed scenario.
    if not over_budget("paged_mixed_geometry"):
        with guarded("paged_mixed_geometry"):
            from video_features_tpu.parallel.pages import build_row_table

            pg_batch = 4 if on_cpu else 64
            n = 5 if on_cpu else 16
            # two source geometries; the resnet host path normalizes both
            # into the one 224² page family. Slot totals: CPU 4+5+4+5+4 = 22
            # ≡ 2 (mod 4), TPU 16×14 = 224 ≡ 32 (mod 64) — the bucketed
            # flush pads batch/2 rows, the paged flush pads zero
            corpus = write_corpus(
                "paged_corpus",
                [(((64, 48) if i % 2 else (48, 32)),
                  (4 + (i % 2)) if on_cpu else 14) for i in range(n)])
            entry = {"unit": "frame slots", "videos": n, "code_rev": code_rev}
            for paged_mode, key in ((True, "paged"), (False, "bucketed")):
                ex = ExtractResNet50(cfg(
                    "resnet50", batch_size=pg_batch, pack_corpus=True,
                    on_extraction="save_numpy", paged_batching=paged_mode,
                    decode_workers=1 if on_cpu else 4))
                if paged_mode:
                    # warm the memoized paged program outside the clock
                    spec = ex.pack_spec()
                    _force(spec.paged_step(
                        np.zeros((spec.page_rows, 224, 224, 3), np.uint8),
                        build_row_table([(0, 0)], spec.page_rows))[0])
                    entry["page_rows"] = spec.page_rows
                    entry["pages_in_flight"] = spec.pages_in_flight
                else:
                    _force(ex._step(ex.params, ex.runner.put(
                        np.zeros((pg_batch, 224, 224, 3), np.uint8))))
                shutil.rmtree(ex.output_dir, ignore_errors=True)
                t0 = time.perf_counter()
                ok = ex.run(corpus)
                wall = time.perf_counter() - t0
                if ok != n:
                    raise RuntimeError(f"{key} pass extracted {ok}/{n}")
                stats = ex._pack_stats
                entry[key] = {
                    "videos_per_sec": round(ok / wall, 3),
                    "wall_sec": round(wall, 3),
                    "real_slots": stats["real_slots"],
                    "dispatched_slots": stats["dispatched_slots"],
                    "pad_waste_ratio": round(
                        1.0 - stats["real_slots"]
                        / max(stats["dispatched_slots"], 1), 4),
                    "batches_in_flight": stats["max_in_flight"],
                }
                if paged_mode:
                    entry[key]["pages_dispatched"] = stats["pages_dispatched"]
            entry["paged_waste_strictly_below_bucketed"] = bool(
                entry["paged"]["pad_waste_ratio"]
                < entry["bucketed"]["pad_waste_ratio"])
            details["paged_mixed_geometry"] = entry
            clear_failure("paged_mixed_geometry")
            flush_details()
            _log(f"paged_mixed_geometry: paged waste "
                 f"{entry['paged']['pad_waste_ratio']} at depth "
                 f"{entry['paged']['batches_in_flight']} vs bucketed "
                 f"{entry['bucketed']['pad_waste_ratio']} "
                 f"(strictly below: "
                 f"{entry['paged_waste_strictly_below_bucketed']})")

    # ---- segmented intra-video decode (--decode_segments, docs/performance.md)
    # A decode-bound corpus: few LONG videos on a pool with spare workers —
    # the shape where cross-video parallelism cannot help and sequential
    # decode pins the pipeline at single-stream speed. Same corpus through
    # sequential decode (--decode_segments 1) and forced 4-way segmentation;
    # decode critical-path s/video comes from the telemetry journal's decode
    # spans (a segmented video's decode wall is max(span end) − min(span
    # start) across its segment streams). Acceptance: segmented decode
    # s/video strictly lower, packing occupancy no worse, and the two modes'
    # saved features byte-identical (the parity invariant, checked end to
    # end — a non-parity stitch fails the scenario outright).
    if not over_budget("long_video_segmented"):
        with guarded("long_video_segmented"):
            n_long = 2 if on_cpu else 4
            frames_long = 360 if on_cpu else 900
            corpus = write_corpus(
                "long_corpus",
                [((160, 120) if on_cpu else (320, 240), frames_long)] * n_long)
            seg_workers = 4 if on_cpu else 8

            def seg_decode_walls(tdir):
                """(mean decode critical-path sec/video, segment span count)."""
                starts: dict = {}
                ends: dict = {}
                seg_spans = 0
                with open(os.path.join(tdir, "events.jsonl")) as f:
                    for line in f:
                        try:
                            ev = json.loads(line)
                        except ValueError:
                            continue
                        if ev.get("event") == "decode_start":
                            starts.setdefault(ev["video"], []).append(ev["ts"])
                            seg_spans += "segment" in ev
                        elif ev.get("event") == "decode_end":
                            ends.setdefault(ev["video"], []).append(ev["ts"])
                walls = [max(ends[v]) - min(starts[v])
                         for v in starts if v in ends]
                return sum(walls) / max(len(walls), 1), seg_spans

            def run_seg_mode(key, segs):
                tdir = os.path.join("/tmp/vft_bench", f"segdec_{key}")
                shutil.rmtree(tdir, ignore_errors=True)
                ex = ExtractResNet50(cfg(
                    "resnet50", batch_size=4 if on_cpu else 64,
                    pack_corpus=True, on_extraction="save_numpy",
                    decode_workers=seg_workers, decode_segments=segs,
                    # native resampler: the ffmpeg re-encode path is never
                    # segmented, and parity must compare like against like
                    extraction_fps=1, use_ffmpeg="never",
                    telemetry_dir=tdir))
                _force(ex._step(ex.params, ex.runner.put(
                    rng.integers(0, 256, (ex.batch_size, 224, 224, 3),
                                 dtype=np.uint8))))  # warm outside the clock
                shutil.rmtree(ex.output_dir, ignore_errors=True)
                t0 = time.perf_counter()
                ok = ex.run(corpus)
                wall = time.perf_counter() - t0
                if ok != n_long:
                    raise RuntimeError(f"{key} pass extracted {ok}/{n_long}")
                decode_wall, seg_spans = seg_decode_walls(tdir)
                outputs = {
                    name: open(os.path.join(ex.output_dir, name), "rb").read()
                    for name in sorted(os.listdir(ex.output_dir))
                    if name.endswith(".npy")}
                return {
                    "videos_per_sec": round(ok / wall, 3),
                    "wall_sec": round(wall, 3),
                    "decode_sec_per_video": round(decode_wall, 4),
                    "segment_spans": seg_spans,
                    "occupancy": ex._pack_stats["occupancy"],
                }, outputs

            _log(f"long_video_segmented: {n_long} videos × {frames_long} "
                 f"frames, {seg_workers} decode workers, sequential vs "
                 f"4-way segments")
            entry = {"videos": n_long, "frames_per_video": frames_long,
                     "decode_workers": seg_workers, "unit": "videos",
                     "code_rev": code_rev}
            entry["sequential"], seq_outs = run_seg_mode("sequential", 1)
            entry["segmented"], seg_outs = run_seg_mode("segmented", 4)
            entry["byte_parity"] = bool(seq_outs == seg_outs)
            entry["decode_strictly_faster"] = bool(
                entry["segmented"]["decode_sec_per_video"]
                < entry["sequential"]["decode_sec_per_video"])
            entry["occupancy_no_worse"] = bool(
                entry["segmented"]["occupancy"]
                >= entry["sequential"]["occupancy"])
            details["long_video_segmented"] = entry
            clear_failure("long_video_segmented")
            flush_details()
            if not entry["byte_parity"]:
                raise RuntimeError(
                    "long_video_segmented: segmented features are NOT "
                    "byte-identical to sequential decode")
            _log(f"long_video_segmented: decode "
                 f"{entry['sequential']['decode_sec_per_video']}s → "
                 f"{entry['segmented']['decode_sec_per_video']}s per video "
                 f"(strictly faster: {entry['decode_strictly_faster']}), "
                 f"occupancy {entry['sequential']['occupancy']} → "
                 f"{entry['segmented']['occupancy']}, byte parity: "
                 f"{entry['byte_parity']}")

    if not over_budget("packed_vggish"):
        with guarded("packed_vggish"):
            from scipy.io import wavfile

            from video_features_tpu.extractors.vggish import ExtractVGGish

            d = os.path.join("/tmp/vft_bench", "wav_corpus")
            shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d, exist_ok=True)
            rng_w = np.random.default_rng(13)
            n = 4 if on_cpu else 16
            corpus = []
            for i in range(n):
                p = os.path.join(d, f"audio{i:02d}.wav")
                secs = 1.0 + (i % 5)
                wav = (rng_w.uniform(-0.5, 0.5, int(16000 * secs))
                       * 32767).astype(np.int16)
                wavfile.write(p, 16000, wav)
                corpus.append(p)
            ex = ExtractVGGish(cfg("vggish", pack_corpus=True,
                                   on_extraction="save_numpy"))
            _log(f"packed_vggish: {n} wavs, {ex.example_batch}-example batches")

            def warm_vggish():
                _force(ex._step(ex.params, ex.runner.put(
                    rng.standard_normal(
                        (ex.example_batch, 96, 64)).astype(np.float32))))

            bench_packed("packed_vggish", ex, corpus, "example slots",
                         ex.example_batch, warm=warm_vggish)

    # ---- uint8 ingest fast path (PR 8) ---------------------------------------
    # The same packed flow corpus through the production uint8 wire vs the
    # --float32_wire escape hatch (the retired host-side fp32 staging):
    # outputs are byte-identical (the u8->fp32 cast is the step's first
    # traced op — tests/test_ingest.py pins it), so the delta is pure ingest
    # cost — staged host->device bytes per video (4x by construction, read
    # from the packer's staged_bytes counter) and videos/s. Stale-record
    # protocol unchanged: rides guarded()/clear_failure like every scenario.
    if not over_budget("uint8_ingest_flow"):
        with guarded("uint8_ingest_flow"):
            n = 3 if on_cpu else 12
            corpus = write_corpus(
                "ingest_corpus",
                [(flow_size, 4 + (i % 4) if on_cpu else 8 + (i % 12))
                 for i in range(n)])
            entry = {"unit": "videos", "code_rev": code_rev}
            for wire32, key in ((False, "uint8"), (True, "float32_wire")):
                ex = ExtractFlow(cfg("raft", batch_size=flow_batch,
                                     num_devices=1, pack_corpus=True,
                                     on_extraction="save_numpy",
                                     float32_wire=wire32))
                warm_flow(ex)  # compile outside the timed pass (wire dtype)
                shutil.rmtree(ex.output_dir, ignore_errors=True)
                t0 = time.perf_counter()
                ok = ex.run(corpus)
                wall = time.perf_counter() - t0
                if ok != n:
                    raise RuntimeError(f"{key} pass extracted {ok}/{n}")
                stats = ex._pack_stats
                entry[key] = {
                    "videos_per_sec": round(ok / wall, 3),
                    "wall_sec": round(wall, 3),
                    "staged_bytes": stats["staged_bytes"],
                    "staged_bytes_per_video": stats["staged_bytes"] // ok,
                    "packing_occupancy": stats["occupancy"],
                }
            entry["bytes_ratio_f32_over_u8"] = round(
                entry["float32_wire"]["staged_bytes"]
                / max(entry["uint8"]["staged_bytes"], 1), 2)
            entry["speedup_u8_over_f32"] = round(
                entry["float32_wire"]["wall_sec"]
                / max(entry["uint8"]["wall_sec"], 1e-9), 3)
            details["uint8_ingest_flow"] = entry
            clear_failure("uint8_ingest_flow")
            flush_details()
            _log(f"uint8_ingest_flow: {entry['uint8']['videos_per_sec']} "
                 f"videos/s at {entry['uint8']['staged_bytes_per_video']} "
                 f"staged B/video vs float32_wire "
                 f"{entry['float32_wire']['videos_per_sec']} videos/s "
                 f"({entry['bytes_ratio_f32_over_u8']}x the bytes)")

    # ---- device-side preprocessing (--device_preproc) -------------------------
    # A transform-heavy mixed-geometry resnet50 corpus with the host PIL
    # resize+crop vs the raw-pixels wire (resize+crop fused into the jitted
    # step). Outputs are tolerance-pinned (tests/test_device_preproc.py), so
    # the A/B delta is WHERE the per-frame transform cost lives: VFT_METRICS
    # is forced on so the packer's StageClock lands corpus-level per-stage
    # seconds in _pack_stats["stage_seconds"], and the decode stage — the
    # pool does PIL work on the host path, plain decode on the device path —
    # must come out strictly lower with the flag on, at no-worse packing
    # occupancy (raw wire queues key per decoded geometry; the corpus fills
    # whole pages per geometry either way). staged bytes/video is recorded
    # honestly: sources larger than the 224² crop ship MORE bytes raw — the
    # win is decode-pool relief, not wire shrink (docs/performance.md). Each
    # mode runs twice and records its second pass so per-geometry paged
    # compiles never pollute the stage split.
    if not over_budget("device_preproc"):
        with guarded("device_preproc"):
            n = 4 if on_cpu else 12
            frames_per = 8 if on_cpu else 10
            dp_corpus = write_corpus(
                "device_preproc_corpus",
                [((360, 270) if i % 2 else (400, 300), frames_per)
                 for i in range(n)])
            entry = {"unit": "videos", "code_rev": code_rev}
            prev_metrics = os.environ.get("VFT_METRICS")
            os.environ["VFT_METRICS"] = "1"
            try:
                for flag, key in ((False, "host_preproc"),
                                  (True, "device_preproc")):
                    ex = ExtractResNet50(cfg(
                        "resnet50", batch_size=4 if on_cpu else 64,
                        pack_corpus=True, on_extraction="save_numpy",
                        decode_workers=1 if on_cpu else 4,
                        device_preproc=flag))
                    wall = None
                    for _ in range(2):  # first pass = compile warm
                        shutil.rmtree(ex.output_dir, ignore_errors=True)
                        t0 = time.perf_counter()
                        ok = ex.run(dp_corpus)
                        wall = time.perf_counter() - t0
                        if ok != n:
                            raise RuntimeError(f"{key} pass extracted {ok}/{n}")
                    stats = ex._pack_stats
                    stages = stats.get("stage_seconds", {})
                    entry[key] = {
                        "videos_per_sec": round(ok / wall, 3),
                        "wall_sec": round(wall, 3),
                        "decode_sec_per_video": round(
                            stages.get("decode", 0.0) / ok, 4),
                        "transfer_sec_per_video": round(
                            stages.get("transfer", 0.0) / ok, 4),
                        "staged_bytes_per_video": stats["staged_bytes"] // ok,
                        "packing_occupancy": stats["occupancy"],
                        "n_geometry_queues": len(stats["buckets"]),
                    }
            finally:
                if prev_metrics is None:
                    os.environ.pop("VFT_METRICS", None)
                else:
                    os.environ["VFT_METRICS"] = prev_metrics
            host, dev = entry["host_preproc"], entry["device_preproc"]
            entry["decode_sec_ratio_dev_over_host"] = round(
                dev["decode_sec_per_video"]
                / max(host["decode_sec_per_video"], 1e-9), 3)
            # the acceptance gates: the decode pool sheds the PIL work, and
            # per-geometry queues cost no packing occupancy
            entry["decode_strictly_lower"] = (
                dev["decode_sec_per_video"] < host["decode_sec_per_video"])
            entry["occupancy_no_worse"] = (
                dev["packing_occupancy"] >= host["packing_occupancy"])
            details["device_preproc"] = entry
            clear_failure("device_preproc")
            flush_details()
            _log(f"device_preproc: decode "
                 f"{dev['decode_sec_per_video']}s/video vs host "
                 f"{host['decode_sec_per_video']}s/video "
                 f"(ratio {entry['decode_sec_ratio_dev_over_host']}, "
                 f"strictly lower: {entry['decode_strictly_lower']}), "
                 f"occupancy {dev['packing_occupancy']} vs "
                 f"{host['packing_occupancy']}")

    # ---- always-on service (--serve) steady state -----------------------------
    # A stream of staggered small requests through the daemon's warm slot
    # queues vs the SAME corpus as one batch --pack_corpus run: the serving
    # loop's scheduling/idle-flush overhead shows up as occupancy lost to
    # pad-flushes between bursts, and videos_per_sec quantifies the cost of
    # request-at-a-time arrival. Stale-record protocol unchanged: the entry
    # rides guarded()/clear_failure like every packed scenario.
    if not over_budget("service_steady_state"):
        with guarded("service_steady_state"):
            import threading as _threading

            from video_features_tpu.serve import ExtractionService

            n_videos = 6 if on_cpu else 24
            per_request = 2
            corpus = write_corpus(
                "service_corpus",
                [((64, 48), 3 + (i % 4) if on_cpu else 6 + (i % 10))
                 for i in range(n_videos)])
            batch = 4 if on_cpu else 64

            def service_cfg(sub, **kw):
                # not the shared cfg() helper: the daemon and the baseline
                # need DISTINCT output trees (the shared one would dedupe
                # the second run via its done-manifest)
                return ExtractionConfig(
                    feature_type="resnet50", batch_size=batch,
                    pack_corpus=True, on_extraction="save_numpy",
                    output_path=os.path.join("/tmp/vft_bench", sub),
                    tmp_path=os.path.join("/tmp/vft_bench", "tmp"), **kw)

            ex_b = ExtractResNet50(service_cfg("svc_batch"))

            def warm_svc(ex=ex_b):
                _force(ex._step(ex.params, ex.runner.put(
                    rng.integers(0, 256, (batch, 224, 224, 3),
                                 dtype=np.uint8))))

            # svc_baseline, NOT baseline: this scope sees main's headline
            # baseline float, and rebinding it to this entry dict made the
            # final print_summary() divide a float by a dict
            svc_baseline = bench_packed("service_batch_baseline", ex_b, corpus,
                                        "frame slots", batch, warm=warm_svc)

            shutil.rmtree(os.path.join("/tmp/vft_bench", "svc_serve"),
                          ignore_errors=True)  # fresh manifests per sweep
            # admission WAL on, with the production fsync-batching window:
            # the serving number carries the durability tax (docs/serving.md
            # "Crash recovery" budgets it under 2% of wall)
            ex_s = ExtractResNet50(service_cfg(
                "svc_serve",
                wal_path=os.path.join("/tmp/vft_bench", "svc_serve",
                                      "admission.wal"),
                wal_fsync_sec=0.05))
            svc = ExtractionService(ex_s, poll_interval=0.005)
            requests = [corpus[i:i + per_request]
                        for i in range(0, len(corpus), per_request)]
            stagger = 0.15 if on_cpu else 0.05

            feed_err = []

            def feed():
                try:
                    for i, vids in enumerate(requests):
                        svc.submit({"tenant": f"t{i % 2}", "videos": vids,
                                    "request_id": f"bench-{i}"})
                        time.sleep(stagger)
                except Exception as e:  # noqa: BLE001 — re-raised on the bench thread after join
                    feed_err.append(e)
                finally:
                    # a submit failure must still drain, or run() blocks the
                    # bench forever; guarded() records the re-raised error
                    svc.request_drain()

            _log(f"service_steady_state: {len(requests)} staggered requests "
                 f"× {per_request} videos, batch {batch}")
            feeder = _threading.Thread(target=feed, daemon=True)
            t0 = time.perf_counter()
            feeder.start()
            rc = svc.run()
            wall = time.perf_counter() - t0
            feeder.join()
            if feed_err:
                raise feed_err[0]
            if rc != 0:
                raise RuntimeError(f"service run exited {rc}")
            packer = svc.packer
            entry = {
                "videos_per_sec": round(n_videos / wall, 3),
                "videos": n_videos,
                "requests": len(requests),
                "stagger_sec": stagger,
                "wall_sec": round(wall, 3),
                "unit": "frame slots",
                "packing_occupancy": round(packer.occupancy, 4),
                "real_slots": packer.real_slots,
                "dispatched_slots": packer.dispatched_slots,
                "batch_occupancy_baseline": svc_baseline["packing_occupancy"],
                "batch_videos_per_sec": svc_baseline["videos_per_sec"],
                "wal": svc.stats().get("wal"),
                "code_rev": code_rev,
            }
            details["service_steady_state"] = entry
            clear_failure("service_steady_state")
            flush_details()
            _log(f"service_steady_state: {entry['videos_per_sec']} videos/s, "
                 f"occupancy {entry['packing_occupancy']} (one-batch-run "
                 f"baseline {entry['batch_occupancy_baseline']})")

    # ---- co-resident models on one mesh (--serve_models) ----------------------
    # Mixed two-model traffic through ONE daemon vs each model's single-model
    # daemon serving its half of the corpus at the same per-model request
    # rate: a single-model daemon idle-pad-flushes its partial queues
    # whenever its own traffic lulls (the mesh drains between its requests),
    # while the two-model daemon keeps the queue non-idle because the other
    # model's requests fill the gaps — so aggregate packed occupancy on
    # mixed traffic should beat what either single-model daemon achieves on
    # its half. Per-model occupancy comes from the shared packer's
    # (model, geometry) buckets (docs/serving.md). Stale-record protocol
    # unchanged: rides guarded()/clear_failure like every scenario.
    if not over_budget("multi_model_service"):
        with guarded("multi_model_service"):
            import threading as _threading

            from video_features_tpu.serve import ExtractionService

            n_per_model = 6 if on_cpu else 12
            per_request = 2
            batch = 4 if on_cpu else 32
            # frame counts chosen to never divide the batch: every request
            # tails a partial queue an idle daemon would pad-flush
            corpus_a = write_corpus(
                "mm_resnet",
                [((64, 48), 3 + (i % 3)) for i in range(n_per_model)])
            corpus_b = write_corpus(
                "mm_r21d",
                [((64, 48), 17 + 2 * (i % 2)) for i in range(n_per_model)])
            # the timing triangle that makes the comparison meaningful:
            # idle_flush must EXCEED the mixed daemon's idle window
            # (stagger − processing) so interleaved traffic keeps partials
            # alive, and FALL SHORT of the single daemons' window
            # (2·stagger − processing) so a single-model daemon's lulls
            # pad-flush — the drain the mixed mesh no longer pays
            stagger = 0.5 if on_cpu else 0.25
            idle_flush = 0.4 if on_cpu else 0.15

            def mm_cfg(sub, feature="resnet50", **kw):
                spool = os.path.join("/tmp/vft_bench", sub, "spool")
                os.makedirs(spool, exist_ok=True)
                return ExtractionConfig(
                    feature_type=feature, batch_size=batch, serve=True,
                    clips_per_batch=batch,  # r21d packs by clips_per_batch
                    on_extraction="save_numpy", spool_dir=spool,
                    idle_flush_sec=idle_flush,
                    compilation_cache=os.path.join("/tmp/vft_bench",
                                                   "xla_cache"),
                    output_path=os.path.join("/tmp/vft_bench", sub),
                    tmp_path=os.path.join("/tmp/vft_bench", "tmp"), **kw)

            def run_daemon(sub, reqs, gap, **cfg_kw):
                """One in-process daemon fed staggered requests; returns
                (wall, packer) after a clean drain."""
                shutil.rmtree(os.path.join("/tmp/vft_bench", sub),
                              ignore_errors=True)
                from video_features_tpu.extractors import get_extractor

                svc = ExtractionService(
                    get_extractor(mm_cfg(sub, **cfg_kw)),
                    poll_interval=0.005)
                feed_err = []

                def feed():
                    try:
                        for i, (vids, ft) in enumerate(reqs):
                            payload = {"tenant": f"t{i % 2}",
                                       "videos": vids,
                                       "request_id": f"{sub}-{i}"}
                            if ft is not None:
                                payload["feature_type"] = ft
                            svc.submit(payload)
                            time.sleep(gap)
                    except Exception as e:  # noqa: BLE001 — re-raised on the bench thread after join
                        feed_err.append(e)
                    finally:
                        svc.request_drain()

                feeder = _threading.Thread(target=feed, daemon=True)
                t0 = time.perf_counter()
                feeder.start()
                rc = svc.run()
                wall = time.perf_counter() - t0
                feeder.join()
                if feed_err:
                    raise feed_err[0]
                if rc != 0:
                    raise RuntimeError(f"{sub} daemon exited {rc}")
                return wall, svc.packer

            def chunk(vids):
                return [vids[i:i + per_request]
                        for i in range(0, len(vids), per_request)]

            _log(f"multi_model_service: {n_per_model} videos/model, "
                 f"batch {batch}, stagger {stagger}s")
            # warm daemons fill the persistent XLA cache so first-request
            # compile stalls don't swallow the singles' idle windows
            run_daemon("mm_warm_a", [(chunk(corpus_a)[0], None)], 0.01)
            run_daemon("mm_warm_b", [(chunk(corpus_b)[0], None)], 0.01,
                       feature="r21d_rgb")
            # singles: each model's half at its own arrival rate (gap 2×:
            # the mixed stream delivers each model a request every 2×stagger)
            wall_a, packer_a = run_daemon(
                "mm_single_a", [(v, None) for v in chunk(corpus_a)],
                2 * stagger)
            wall_b, packer_b = run_daemon(
                "mm_single_b", [(v, None) for v in chunk(corpus_b)],
                2 * stagger, feature="r21d_rgb")
            # mixed: the SAME per-model traffic interleaved into one daemon
            mixed_reqs = []
            for va, vb in zip(chunk(corpus_a), chunk(corpus_b)):
                mixed_reqs.append((va, None))
                mixed_reqs.append((vb, "r21d_rgb"))
            wall_m, packer_m = run_daemon(
                "mm_mixed", mixed_reqs, stagger,
                serve_models=("r21d_rgb",))

            def svc_entry(wall, packer, videos):
                return {
                    "wall_sec": round(wall, 3),
                    "videos_per_sec": round(videos / wall, 3),
                    "packing_occupancy": round(packer.occupancy, 4),
                    "real_slots": packer.real_slots,
                    "dispatched_slots": packer.dispatched_slots,
                }
            entry = {
                "videos": 2 * n_per_model,
                "requests": len(mixed_reqs),
                "stagger_sec": stagger,
                "unit": "device slots",
                "mixed": dict(svc_entry(wall_m, packer_m, 2 * n_per_model),
                              models=packer_m.model_stats()),
                "single_resnet50": svc_entry(wall_a, packer_a, n_per_model),
                "single_r21d_rgb": svc_entry(wall_b, packer_b, n_per_model),
                "code_rev": code_rev,
            }
            best_single = max(
                entry["single_resnet50"]["packing_occupancy"],
                entry["single_r21d_rgb"]["packing_occupancy"])
            entry["occupancy_gain_vs_best_single"] = round(
                entry["mixed"]["packing_occupancy"] - best_single, 4)
            details["multi_model_service"] = entry
            clear_failure("multi_model_service")
            flush_details()
            _log(f"multi_model_service: mixed occupancy "
                 f"{entry['mixed']['packing_occupancy']} vs singles "
                 f"{entry['single_resnet50']['packing_occupancy']} / "
                 f"{entry['single_r21d_rgb']['packing_occupancy']} "
                 f"(gain {entry['occupancy_gain_vs_best_single']}), "
                 f"{entry['mixed']['videos_per_sec']} videos/s aggregate")

    # ---- content-addressed feature cache (--cache_dir) ------------------------
    # Duplicate-heavy corpus (each unique video uploaded `dups` times, the
    # "millions of users" traffic shape): a cold pass measures in-run dedup
    # (later copies of a video hit the entry its first copy published) and a
    # warm pass over the same cache measures the steady state — hit rate and
    # wall-clock speedup vs the cold pass, zero device steps on hits
    # (docs/caching.md). Stale-record protocol unchanged: rides guarded()/
    # clear_failure like every scenario; the headline is untouched.
    if not over_budget("cache_hit_rate"):
        with guarded("cache_hit_rate"):
            n_unique = 2 if on_cpu else 6
            dups = 3 if on_cpu else 4
            unique = write_corpus(
                "cache_corpus",
                [((64, 48), 4 + i if on_cpu else 8 + i)
                 for i in range(n_unique)])
            corpus = list(unique)
            for src in unique:
                for j in range(dups - 1):
                    dst = src.replace(".mp4", f"_dup{j}.mp4")
                    shutil.copyfile(src, dst)
                    corpus.append(dst)
            cache_dir = os.path.join("/tmp/vft_bench", "feature_cache")
            shutil.rmtree(cache_dir, ignore_errors=True)

            def cache_cfg(sub):
                return ExtractionConfig(
                    feature_type="resnet50", batch_size=4 if on_cpu else 64,
                    on_extraction="save_numpy", cache_dir=cache_dir,
                    output_path=os.path.join("/tmp/vft_bench", sub),
                    tmp_path=os.path.join("/tmp/vft_bench", "tmp"))

            ex_cold = ExtractResNet50(cache_cfg("cache_cold"))
            # compile the one jit signature outside the timed passes
            _force(ex_cold._step(ex_cold.params, ex_cold.runner.put(
                rng.integers(0, 256, (ex_cold.batch_size, 224, 224, 3),
                             dtype=np.uint8))))
            shutil.rmtree(ex_cold.output_dir, ignore_errors=True)
            _log(f"cache_hit_rate: {len(corpus)} videos "
                 f"({n_unique} unique × {dups} uploads), cold pass")
            t0 = time.perf_counter()
            ok = ex_cold.run(corpus)
            cold_wall = time.perf_counter() - t0
            if ok != len(corpus):
                raise RuntimeError(f"cold pass extracted {ok}/{len(corpus)}")
            cold_stats = ex_cold._cache.stats()

            ex_warm = ExtractResNet50(cache_cfg("cache_warm"))
            shutil.rmtree(ex_warm.output_dir, ignore_errors=True)
            t0 = time.perf_counter()
            ok = ex_warm.run(corpus)
            warm_wall = time.perf_counter() - t0
            if ok != len(corpus):
                raise RuntimeError(f"warm pass extracted {ok}/{len(corpus)}")
            warm_stats = ex_warm._cache.stats()
            entry = {
                "videos": len(corpus),
                "unique_videos": n_unique,
                "cold_wall_sec": round(cold_wall, 3),
                "warm_wall_sec": round(warm_wall, 3),
                "warm_speedup": round(cold_wall / warm_wall, 2),
                "cold_hit_rate": cold_stats["hit_rate"],  # in-run dedup
                "warm_hit_rate": warm_stats["hit_rate"],  # steady state: 1.0
                "cache_entries": warm_stats["entries"],
                "cache_bytes": warm_stats["total_bytes"],
                "unit": "videos",
                "code_rev": code_rev,
            }
            details["cache_hit_rate"] = entry
            clear_failure("cache_hit_rate")
            flush_details()
            _log(f"cache_hit_rate: cold {entry['cold_hit_rate']:.0%} hits in "
                 f"{cold_wall:.2f}s, warm {entry['warm_hit_rate']:.0%} in "
                 f"{warm_wall:.2f}s ({entry['warm_speedup']}x speedup)")

    # ---- end-to-end extract(): decode → transform → device → collect ----------
    # The reference's real workload is whole videos through the full pipeline
    # (SURVEY §3.1 hot loop); device-step benches above exclude decode. Stage
    # attribution comes from the production StageClock. Methodology: each
    # config's device programs are pre-compiled on SYNTHETIC batches (different
    # content from the video, so the tunnel backend's (executable, args)
    # memoization cannot serve the timed pass), then ONE timed pass runs both
    # sample videos with fresh (real) frames.
    if not on_cpu:
        from video_features_tpu.utils.metrics import StageClock

        videos = [os.path.join(REPO, "sample", "v_GGSY1Qvo990.mp4"),
                  os.path.join(REPO, "sample", "v_ZNVhz7ctTq0.mp4")]
        videos = [v for v in videos if os.path.exists(v)]

        def bench_e2e(name, ex, warm_fn, feat_key, unit_key=None):
            _log(f"{name}: compiling on synthetic batches")
            try:
                warm_fn()
                clock = StageClock()
                ex.clock = clock
                if ex.cfg.decode_workers > 1 and ex.uses_frame_stream:
                    # the pool is normally created by run(); replicate its
                    # schedule-ahead window for the direct extract() calls
                    from video_features_tpu.parallel.pipeline import DecodePrefetcher

                    ex._decode_pool = DecodePrefetcher(ex._open_inline,
                                                       ex.cfg.decode_workers)
                    for v in videos:
                        ex._decode_pool.schedule(v)
                total_units = 0
                t0 = time.perf_counter()
                for v in videos:
                    try:
                        out = ex.extract(v)
                    finally:
                        if ex._decode_pool is not None:
                            ex._decode_pool.release(v)
                    n = out[feat_key].shape[0]
                    total_units += n
                wall = time.perf_counter() - t0
            # no except here: every call site wraps in `with guarded(name)`,
            # whose run_failures routing is the single fault barrier — a
            # transient outage must not clobber a committed good e2e entry
            finally:
                if ex._decode_pool is not None:
                    ex._decode_pool.shutdown()
                    ex._decode_pool = None
                ex.clock = None
            entry = {
                "videos_per_sec": round(len(videos) / wall, 4),
                "unit": unit_key or f"{feat_key} rows",
                "units_per_sec": round(total_units / wall, 2),
                "wall_sec": round(wall, 3),
                "decode_sec": round(clock.seconds.get("decode", 0.0), 3),
                "device_wait_sec": round(clock.seconds.get("device_wait", 0.0), 3),
                "code_rev": code_rev,
            }
            details[name] = entry
            clear_failure(name)
            flush_details()
            _log(f"{name}: {entry['videos_per_sec']} videos/s "
                 f"({entry['units_per_sec']} {entry['unit']}/s; decode "
                 f"{entry['decode_sec']}s, device_wait {entry['device_wait_sec']}s "
                 f"of {entry['wall_sec']}s)")

        if videos:
            # budget checks sit BEFORE each extractor construction: building
            # one costs weight resolution + tunnel transfers, exactly the
            # wall-clock the budget bounds
            for workers in (1, 4):
                if over_budget(f"e2e_resnet50_float32_w{workers}"):
                    continue
                with guarded(f"e2e_resnet50_float32_w{workers}"):
                    ex = ExtractResNet50(cfg("resnet50", batch_size=64,
                                             decode_workers=workers))
                    bench_e2e(
                        f"e2e_resnet50_float32_w{workers}", ex,
                        lambda ex=ex: _force(ex._step(ex.params, ex.runner.put(
                            rng.integers(0, 256, (ex.batch_size, 224, 224, 3),
                                         dtype=np.uint8)))),
                        "resnet50", "frames")

            # flagship two-stream I3D at the reference default (flow via PWC);
            # sample videos decode to 256×341 after the 256-edge resize
            if not over_budget("e2e_i3d_two_stream_pwc_float32_w1"):
                with guarded("e2e_i3d_two_stream_pwc_float32_w1"):
                    ex = ExtractI3D(cfg("i3d", streams=("rgb", "flow"),
                                        flow_type="pwc", stack_size=64,
                                        step_size=64, clips_per_batch=1))

                    def warm_i3d(ex=ex):
                        stacks = ex.runner.put(rng.integers(
                            0, 256, (ex.clips_per_batch, 65, 256, 341, 3),
                            dtype=np.uint8))
                        _force(ex._rgb_step(ex.i3d_params["rgb"], stacks))
                        _force(ex._flow_step(ex.i3d_params["flow"], stacks))

                    bench_e2e("e2e_i3d_two_stream_pwc_float32_w1", ex, warm_i3d,
                              "rgb", "stacks")

            def warm_raft(ex):
                # both sample geometries: v1 decodes 240x320, v2 360x480 — a
                # miss would put a 20-100 s tunnel compile inside the timed pass
                for h, w in ((240, 320), (360, 480)):
                    _force(ex._frames_step(ex.params, ex.runner.put(
                        rng.uniform(0, 255, (ex.batch_size + 1, h, w, 3))
                        .astype(np.float32))))

            # tx16: --transfer_dtype float16 halves the D2H bytes; paired with
            # the async double-buffered fetch this is the round-4 answer to
            # the 82 %-device_wait e2e_raft profile
            for workers, tdt, tag in ((1, "float32", ""), (4, "float32", ""),
                                      (4, "float16", "_tx16")):
                name = f"e2e_raft_float32_w{workers}{tag}"
                if over_budget(name):
                    continue
                with guarded(name):
                    ex = ExtractFlow(cfg("raft", batch_size=16, num_devices=1,
                                         decode_workers=workers,
                                         transfer_dtype=tdt))
                    bench_e2e(name, ex, lambda ex=ex: warm_raft(ex),
                              "raft", "pairs")

    # ---- headline line (re-print; first printed right after i3d_rgb) ----------
    if skipped:
        details["budget_skipped"] = skipped
    elif "budget_skipped" in details:
        del details["budget_skipped"]  # full sweep: clear a stale partial note
    # CPU smoke runs write a separate file (see details_name above)
    flush_details()
    if skipped:
        _log(f"budget: skipped {len(skipped)} configs "
             f"(VFT_BENCH_BUDGET={deadline - _T0:.0f}s): {', '.join(skipped)}")
    print_summary()


if __name__ == "__main__":
    main()
