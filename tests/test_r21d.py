"""Flax R(2+1)D-18 parity vs torch functional mirror + e2e extraction."""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import jax
import jax.numpy as jnp
import torch

from torch_mirrors import r21d_forward, r21d_random_state_dict
from video_features_tpu.models.r21d import R2Plus1D18, midplanes, r21d_preprocess
from video_features_tpu.weights.convert_torch import convert_r21d


def test_midplanes_matches_torchvision_formula():
    assert midplanes(64, 64) == (64 * 64 * 27) // (64 * 9 + 3 * 64)
    assert midplanes(3, 45) == (3 * 45 * 27) // (3 * 9 + 3 * 45)


def test_state_dict_shapes_match_real_torchvision():
    """Known shapes transcribed from an actual torchvision r2plus1d_18 state_dict
    (independent of our shape table — guards the shared-table circularity).
    Torchvision computes midplanes once per block from (inplanes, planes) and
    reuses it for conv2, so downsampling blocks have 230/460/921 mids on conv2."""
    from video_features_tpu.models.r21d import r21d_conv_shapes

    shapes = r21d_conv_shapes()
    expected = {
        "stem.0": (45, 3, 1, 7, 7),
        "layer1.0.conv1.0.0": (144, 64, 1, 3, 3),
        "layer1.0.conv2.0.0": (144, 64, 1, 3, 3),
        "layer2.0.conv1.0.0": (230, 64, 1, 3, 3),
        "layer2.0.conv2.0.0": (230, 128, 1, 3, 3),
        "layer2.0.conv2.0.3": (128, 230, 3, 1, 1),
        "layer2.1.conv1.0.0": (288, 128, 1, 3, 3),
        "layer3.0.conv2.0.0": (460, 256, 1, 3, 3),
        "layer4.0.conv2.0.0": (921, 512, 1, 3, 3),
        "layer4.1.conv2.0.0": (1152, 512, 1, 3, 3),
    }
    for name, shape in expected.items():
        assert shapes[name] == shape, f"{name}: {shapes[name]} != torchvision {shape}"


@pytest.fixture(scope="module")
def converted():
    sd = r21d_random_state_dict(seed=13)
    return sd, convert_r21d(sd)


def test_param_tree_matches_model(converted):
    _, params = converted
    model = R2Plus1D18()
    init = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 32, 32, 3)), features=False)["params"]
    p1 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(init)[0]}
    p2 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
    assert p1 == p2


def test_features_parity(converted):
    sd, params = converted
    rng = np.random.default_rng(0)
    x = rng.standard_normal((1, 8, 48, 48, 3)).astype(np.float32)
    ref = r21d_forward(sd, torch.from_numpy(x).permute(0, 4, 1, 2, 3), features=True).numpy()
    out = np.asarray(R2Plus1D18().apply({"params": params}, jnp.asarray(x), features=True))
    assert out.shape == ref.shape == (1, 512)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)


def test_logits_parity(converted):
    sd, params = converted
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 8, 48, 48, 3)).astype(np.float32)
    ref = r21d_forward(sd, torch.from_numpy(x).permute(0, 4, 1, 2, 3), features=False).numpy()
    out = np.asarray(R2Plus1D18().apply({"params": params}, jnp.asarray(x), features=False))
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)


def test_preprocess_matches_torch_pipeline():
    """/255 → bilinear (128,171) → normalize → crop 112, exact order."""
    rng = np.random.default_rng(2)
    u8 = rng.integers(0, 256, (3, 96, 128, 3), dtype=np.uint8)
    vid = torch.from_numpy(u8).permute(3, 0, 1, 2).float() / 255  # CFHW
    vid = torch.nn.functional.interpolate(vid, size=(128, 171), mode="bilinear",
                                          align_corners=False)
    mean = torch.tensor([0.43216, 0.394666, 0.37645]).reshape(-1, 1, 1, 1)
    std = torch.tensor([0.22803, 0.22145, 0.216989]).reshape(-1, 1, 1, 1)
    vid = (vid - mean) / std
    i = int(round((128 - 112) / 2.0))
    j = int(round((171 - 112) / 2.0))
    ref = vid[..., i : i + 112, j : j + 112].permute(1, 2, 3, 0).numpy()
    out = np.asarray(r21d_preprocess(jnp.asarray(u8)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_extract_sample(tmp_path, sample_video):
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.r21d import ExtractR21D

    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    try:
        cfg = ExtractionConfig(
            feature_type="r21d_rgb",
            on_extraction="save_numpy",
            output_path=str(tmp_path),
            clips_per_batch=4,
        )
        ex = ExtractR21D(cfg)
        feats = ex.extract(sample_video)
        # 355 frames → 22 full 16-frame slices; features-only output (reference parity)
        assert set(feats.keys()) == {"r21d_rgb"}
        assert feats["r21d_rgb"].shape == (22, 512)
        assert np.isfinite(feats["r21d_rgb"]).all()
    finally:
        mp.undo()
