"""Window/slice math vs hand-computed values and reference semantics."""
# fast-registry: default tier — pre-dates the fast registry; re-tier on the next sweep

import numpy as np
import pytest

from video_features_tpu.utils.windows import (
    flow_stack_plan,
    form_slices,
    frame_batch_plan,
    pair_batch_plan,
    slice_starts,
)


def test_form_slices_exact_fit():
    # 100 frames, stack 15, step 15 → 6 full stacks ending at 90 (reference docstring example)
    assert form_slices(100, 15, 15) == [
        (0, 15), (15, 30), (30, 45), (45, 60), (60, 75), (75, 90)
    ]


def test_form_slices_overlap():
    assert form_slices(10, 4, 2) == [(0, 4), (2, 6), (4, 8), (6, 10)]


def test_form_slices_short_video():
    assert form_slices(3, 16, 16) == []


def test_form_slices_single():
    assert form_slices(16, 16, 16) == [(0, 16)]


def test_slice_starts_dtype():
    s = slice_starts(100, 15, 15)
    assert s.dtype == np.int32
    assert s.tolist() == [0, 15, 30, 45, 60, 75]


def test_flow_stack_plan_needs_extra_frame():
    # 65 frames exactly fills one 64-stack (64 pairs need 65 frames)
    assert flow_stack_plan(65, 64, 64).tolist() == [0]
    # 64 frames: not enough
    assert flow_stack_plan(64, 64, 64).tolist() == []
    # 130 frames: stacks at 0 and 64 (needs frame 128 inclusive)
    assert flow_stack_plan(130, 64, 64).tolist() == [0, 64]


def test_flow_stack_plan_overlapping_steps():
    # step < stack keeps overlap, mirroring stack = stack[step:] in the reference loop
    assert flow_stack_plan(11, 4, 2).tolist() == [0, 2, 4, 6]


def test_pair_batch_plan_reference_carry():
    # 10 frames, batch 4: reference runs on 5 frames (4 pairs), carries the last
    # → ranges (0,4), (4,8), final partial (8,9)
    assert pair_batch_plan(10, 4) == [(0, 4), (4, 8), (8, 9)]
    # exact fit: 9 frames, batch 4 → (0,4), (4,8) and no partial
    assert pair_batch_plan(9, 4) == [(0, 4), (4, 8)]
    # single frame: no pairs
    assert pair_batch_plan(1, 4) == []
    # two frames: one pair
    assert pair_batch_plan(2, 4) == [(0, 1)]


def test_pair_batch_plan_covers_all_pairs():
    for n in range(2, 40):
        for b in (1, 3, 7):
            ranges = pair_batch_plan(n, b)
            total = sum(e - s for s, e in ranges)
            assert total == n - 1
            # contiguity with carry
            for (s1, e1), (s2, e2) in zip(ranges, ranges[1:]):
                assert e1 == s2


def test_frame_batch_plan():
    assert frame_batch_plan(10, 4) == [(0, 4), (4, 8), (8, 10)]
    assert frame_batch_plan(4, 4) == [(0, 4)]
    assert frame_batch_plan(0, 4) == []


def test_invalid_args():
    with pytest.raises(ValueError):
        form_slices(10, 0, 1)
    with pytest.raises(ValueError):
        pair_batch_plan(10, 0)
