"""Window/slice math vs hand-computed values and reference semantics."""
# fast-registry: default tier — pre-dates the fast registry; re-tier on the next sweep

import numpy as np
import pytest

from video_features_tpu.utils.windows import (
    flow_stack_plan,
    form_slices,
    frame_batch_plan,
    pair_batch_plan,
    slice_starts,
)


def test_form_slices_exact_fit():
    # 100 frames, stack 15, step 15 → 6 full stacks ending at 90 (reference docstring example)
    assert form_slices(100, 15, 15) == [
        (0, 15), (15, 30), (30, 45), (45, 60), (60, 75), (75, 90)
    ]


def test_form_slices_overlap():
    assert form_slices(10, 4, 2) == [(0, 4), (2, 6), (4, 8), (6, 10)]


def test_form_slices_short_video():
    assert form_slices(3, 16, 16) == []


def test_form_slices_single():
    assert form_slices(16, 16, 16) == [(0, 16)]


def test_slice_starts_dtype():
    s = slice_starts(100, 15, 15)
    assert s.dtype == np.int32
    assert s.tolist() == [0, 15, 30, 45, 60, 75]


def test_flow_stack_plan_needs_extra_frame():
    # 65 frames exactly fills one 64-stack (64 pairs need 65 frames)
    assert flow_stack_plan(65, 64, 64).tolist() == [0]
    # 64 frames: not enough
    assert flow_stack_plan(64, 64, 64).tolist() == []
    # 130 frames: stacks at 0 and 64 (needs frame 128 inclusive)
    assert flow_stack_plan(130, 64, 64).tolist() == [0, 64]


def test_flow_stack_plan_overlapping_steps():
    # step < stack keeps overlap, mirroring stack = stack[step:] in the reference loop
    assert flow_stack_plan(11, 4, 2).tolist() == [0, 2, 4, 6]


def test_pair_batch_plan_reference_carry():
    # 10 frames, batch 4: reference runs on 5 frames (4 pairs), carries the last
    # → ranges (0,4), (4,8), final partial (8,9)
    assert pair_batch_plan(10, 4) == [(0, 4), (4, 8), (8, 9)]
    # exact fit: 9 frames, batch 4 → (0,4), (4,8) and no partial
    assert pair_batch_plan(9, 4) == [(0, 4), (4, 8)]
    # single frame: no pairs
    assert pair_batch_plan(1, 4) == []
    # two frames: one pair
    assert pair_batch_plan(2, 4) == [(0, 1)]


def test_pair_batch_plan_covers_all_pairs():
    for n in range(2, 40):
        for b in (1, 3, 7):
            ranges = pair_batch_plan(n, b)
            total = sum(e - s for s, e in ranges)
            assert total == n - 1
            # contiguity with carry
            for (s1, e1), (s2, e2) in zip(ranges, ranges[1:]):
                assert e1 == s2


def test_frame_batch_plan():
    assert frame_batch_plan(10, 4) == [(0, 4), (4, 8), (8, 10)]
    assert frame_batch_plan(4, 4) == [(0, 4)]
    assert frame_batch_plan(0, 4) == []


def test_invalid_args():
    with pytest.raises(ValueError):
        form_slices(10, 0, 1)
    with pytest.raises(ValueError):
        pair_batch_plan(10, 0)


# ---- plan invariants the corpus packer relies on (--pack_corpus): every
# clip yielded exactly once, tails covered or deliberately dropped ----------


def test_form_slices_tail_coverage_invariants():
    for n in range(0, 60):
        for stack, step in ((4, 4), (4, 2), (5, 3), (16, 16)):
            slices = form_slices(n, stack, step)
            # every slice is a full, in-range stack (no short or overrun clip)
            assert all(e - s == stack and 0 <= s and e <= n for s, e in slices)
            # starts advance by exactly `step`: no window skipped or duplicated
            assert [s for s, _ in slices] == [i * step for i in range(len(slices))]
            # maximality: the NEXT window would overrun the frame count
            if slices:
                assert slices[-1][0] + step + stack > n
            else:
                assert n < stack


def test_frame_batch_plan_partitions_every_frame():
    for n in range(0, 40):
        for b in (1, 2, 5):
            plan = frame_batch_plan(n, b)
            # exact partition: no frame dropped, none duplicated, order kept
            assert [i for s, e in plan for i in range(s, e)] == list(range(n))
            # no range exceeds the batch (the packer's slot budget per dispatch)
            assert all(0 < e - s <= b for s, e in plan)


def test_pair_batch_plan_tail_never_exceeds_batch():
    for n in range(2, 40):
        for b in (1, 3, 7):
            assert all(1 <= e - s <= b for s, e in pair_batch_plan(n, b))


# ---- pad_batch edge cases (the packer's corpus-flush padding) --------------


def test_pad_batch_full_batch_is_identity():
    from video_features_tpu.extractors.base import pad_batch

    arr = np.arange(8, dtype=np.uint8).reshape(4, 2)
    assert pad_batch(arr, 4) is arr  # no copy on the hot full-batch path


def test_pad_batch_empty_input_pads_to_all_zeros():
    from video_features_tpu.extractors.base import pad_batch

    out = pad_batch(np.zeros((0, 3), np.float32), 4)
    assert out.shape == (4, 3) and out.dtype == np.float32
    assert not out.any()


def test_pad_batch_preserves_rows_and_dtype():
    from video_features_tpu.extractors.base import pad_batch

    arr = np.arange(6, dtype=np.uint8).reshape(3, 2)
    padded = pad_batch(arr[:1], 4)
    assert padded.shape == (4, 2) and padded.dtype == np.uint8
    np.testing.assert_array_equal(padded[0], arr[0])
    assert not padded[1:].any()


def test_pad_batch_overfull_raises():
    from video_features_tpu.extractors.base import pad_batch

    with pytest.raises(ValueError, match="exceeds batch_size"):
        pad_batch(np.zeros((5, 2)), 4)
