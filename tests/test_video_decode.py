"""Decoder behavior on the real sample videos."""

import itertools

import numpy as np

from video_features_tpu.io.video import decode_all, open_video, probe_video
from video_features_tpu.ops.image import edge_resize_size, pil_edge_resize


def test_probe(sample_video):
    meta = probe_video(sample_video)
    assert meta.width == 320 and meta.height == 240
    assert abs(meta.fps - 19.62) < 0.01
    assert meta.frame_count == 355


def test_decode_first_frames(sample_video):
    meta, frames = open_video(sample_video)
    first = list(itertools.islice(frames, 3))
    assert len(first) == 3
    rgb, pos = first[0]
    assert rgb.shape == (240, 320, 3) and rgb.dtype == np.uint8
    assert pos >= 0.0 and first[1][1] > first[0][1]  # monotone POS_MSEC


def test_decode_all_counts(sample_video):
    meta, frames, ts = decode_all(sample_video)
    assert frames.shape == (355, 240, 320, 3)
    assert ts.shape == (355,)
    assert np.all(np.diff(ts) > 0)


def test_native_fps_resampling(sample_video):
    meta, frames, ts = decode_all(sample_video, extraction_fps=10, use_ffmpeg="never")
    # 355 frames @19.62fps ≈ 18.1s → ~181 frames at 10fps
    assert meta.fps == 10.0
    assert 178 <= len(frames) <= 184
    assert np.allclose(np.diff(ts), 100.0)


def test_transform_applied(sample_video):
    meta, frames = open_video(sample_video, transform=lambda f: pil_edge_resize(f, 64))
    rgb, _ = next(iter(frames))
    # 240x320: smaller edge (h) → 64, w = int(64 * 320 / 240) = 85
    assert rgb.shape == (64, 85, 3)


def test_edge_resize_size_semantics():
    # smaller edge
    assert edge_resize_size(320, 240, 256, True) == (341, 256)
    assert edge_resize_size(240, 320, 256, True) == (256, 341)
    # larger edge
    assert edge_resize_size(320, 240, 256, False) == (256, 192)
    # no-op when the matched edge already equals size
    assert edge_resize_size(256, 300, 256, True) == (256, 300)
    # int truncation (not round): 320*100/240 = 133.33 → 133
    assert edge_resize_size(320, 240, 100, True) == (133, 100)
