"""Encode-once sharded flow vs the pair-split path (virtual multi-device mesh).

The PR-2 tentpole acceptance tests: on a ≥2-device mesh (conftest's
``--xla_force_host_platform_device_count`` loopback mesh) the sharded
shared-frame forwards encode every frame of a (B+1)-frame window EXACTLY
once — the pair-split step encoded every interior frame twice — while the
flow matches the pair-split path within the repo's batch-variant tolerance.
Also covers the extractor routing, the --precompile geometry warmup, and the
padded-geometry arithmetic it relies on.

Wall-clock note: XLA compiles dominate these tests on CPU, so the default
(tier-1) subset is organized to compile as few programs as possible; the
heavier model-level PWC parity and the full I3D sandwich parity are
slow-marked (the fast subset still proves sharded-vs-pair parity for both
model families — PWC through the extractor routing test).
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.parallel import local_mesh


@pytest.fixture(autouse=True)
def _random_weights(monkeypatch):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")


def _cfg(tmp_path, feature_type, num_devices, **kw):
    return ExtractionConfig(
        feature_type=feature_type, num_devices=num_devices,
        output_path=str(tmp_path / f"out{num_devices}"),
        tmp_path=str(tmp_path / f"tmp{num_devices}"), **kw)


def test_raft_sharded_matches_pair_and_encodes_each_frame_once(monkeypatch):
    """The tentpole acceptance test, both halves on one pair of compiles:

    1. parity — the encode-once sharded forward matches the pair-split
       forward within the repo's batch-variant tolerance;
    2. instrumentation — a counting wrapper around RAFT's encoder records
       the frames entering it at trace time. The sharded program's fnet sees
       each shard's k = B/D main frames ONCE plus the single replicated
       final frame (k+1 per shard, B+D globally for B+1 distinct frames);
       the pair-split forward's fnet sees 2·B rows — every interior frame
       encoded twice. cnet runs on the k source frames only (no halo).
    """
    from video_features_tpu.models import raft

    counts = []
    real_encoder = raft._encoder

    def counting_encoder(p, x, kind):
        counts.append((kind, int(x.shape[0])))
        return real_encoder(p, x, kind)

    monkeypatch.setattr(raft, "_encoder", counting_encoder)
    n_dev, pairs = 4, 8
    rng = np.random.default_rng(3)
    params = raft.raft_init_params(0)
    frames = rng.uniform(0, 255, (pairs + 1, 32, 40, 3)).astype(np.float32)
    mesh = local_mesh(n_dev)
    shard = np.asarray(raft.raft_forward_frames_sharded(
        params, jnp.asarray(frames[:-1]), jnp.asarray(frames[-1:]), mesh,
        iters=4))
    sharded_counts, counts[:] = list(counts), []
    pair = np.asarray(raft.raft_forward(
        params, jnp.asarray(frames[:-1]), jnp.asarray(frames[1:]), iters=4))
    pair_counts = list(counts)

    assert shard.shape == (pairs, 32, 40, 2)
    # Tolerance: conv reduction order varies across the shard/batch layouts
    # and RAFT's recurrent iterations amplify it under random weights
    # (observed 1.5e-4 abs / 4e-3 rel on <0.03% of elements at |flow|≈15 px;
    # the repo bounds the full 20-iteration extractor runs at 5e-2,
    # tests/test_parallel.py). A wrong pairing — the bug class this test
    # exists for — errs by whole pixels.
    np.testing.assert_allclose(shard, pair, rtol=1e-3, atol=1e-3)

    k = pairs // n_dev
    # shard_map traces the per-shard program once: fnet = [k main, 1 last]
    fnet = sorted(n for kind, n in sharded_counts if kind == "instance")
    assert fnet == [1, k], f"fnet encode batches {fnet}; expected [1, {k}]"
    assert [n for kind, n in sharded_counts if kind == "batch"] == [k]
    # globally: B + D fnet rows for B+1 distinct frames — each encoded
    # exactly once (the final frame replicated, not re-derived per pair) —
    # where the pair-split forward encodes 2·B rows
    assert sum(fnet) * n_dev == pairs + n_dev
    pair_fnet = sum(n for kind, n in pair_counts if kind == "instance")
    assert pair_fnet == 2 * pairs
    assert sum(fnet) * n_dev < pair_fnet


@pytest.mark.slow  # model-level PWC parity; the fast subset covers PWC via
# the extractor routing test below (same sharded program, same reference)
def test_pwc_sharded_frames_matches_pair_forward():
    from video_features_tpu.models import pwc

    rng = np.random.default_rng(4)
    params = pwc.pwc_init_params(0)
    frames = rng.uniform(0, 255, (9, 64, 64, 3)).astype(np.float32)
    mesh = local_mesh(4)
    shard = np.asarray(pwc.pwc_forward_frames_sharded(
        params, jnp.asarray(frames[:-1]), jnp.asarray(frames[-1:]), mesh))
    pair = np.asarray(pwc.pwc_forward(
        params, jnp.asarray(frames[:-1]), jnp.asarray(frames[1:])))
    assert shard.shape == (8, 64, 64, 2)
    np.testing.assert_allclose(shard, pair, rtol=1e-4, atol=1e-4)


def test_sharded_path_rejects_undivisible_pair_count():
    from video_features_tpu.models import pwc, raft

    mesh = local_mesh(4)
    frames = jnp.zeros((6, 64, 64, 3), jnp.float32)  # 6 pairs % 4 devices
    last = jnp.zeros((1, 64, 64, 3), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        raft.raft_forward_frames_sharded(raft.raft_init_params(0), frames,
                                         last, mesh, iters=1)
    with pytest.raises(ValueError, match="divisible"):
        pwc.pwc_forward_frames_sharded(pwc.pwc_init_params(0), frames, last,
                                       mesh)


def test_extract_flow_routes_sharded_precompiles_and_matches_pair(tmp_path):
    """ExtractFlow on a multi-device mesh: --precompile warms the encode-once
    sharded program in the background from the video's native geometry, the
    dispatched windows route through it (the pair-split program is never
    built), and the output matches the pair-split forward on the same
    weights. One PWC compile total — this is the fast tier's PWC parity
    coverage (the model-level twin above is slow-marked)."""
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.models.pwc import pwc_forward, pwc_init_params

    ex = ExtractFlow(_cfg(tmp_path, "pwc", 2, batch_size=2, precompile=True))
    ex._start_precompile(width=40, height=32)
    deadline = time.monotonic() + 300
    while (time.monotonic() < deadline
           and ex._frames_step_sharded._cache_size() < 1):
        time.sleep(0.05)
    assert ex._frames_step_sharded._cache_size() == 1  # warmed in background
    # duplicate geometry: second call is a set-lookup no-op
    ex._start_precompile(width=40, height=32)
    assert ex._precompiled == {(32, 40)}

    # uint8 frames: the wire dtype the precompile warmed — a float32 window
    # would compile a SECOND (non-production) program and fail the
    # cache-size assertions below
    frames = np.random.default_rng(5).integers(
        0, 256, (3, 32, 40, 3), dtype=np.uint8)
    flow = ex._run_pairs(frames)
    assert flow.shape == (2, 2, 32, 40)
    assert ex._frames_step_sharded._cache_size() == 1  # no second compile
    assert "_step" not in ex.__dict__  # pair-split program never compiled

    # parity: VFT_ALLOW_RANDOM_WEIGHTS resolves 'pwc-sintel' to
    # pwc_init_params(0), so the reference pair forward shares the weights
    ref = np.asarray(pwc_forward(
        pwc_init_params(0), jnp.asarray(frames[:-1]), jnp.asarray(frames[1:])
    )).transpose(0, 3, 1, 2)
    np.testing.assert_allclose(flow, ref, rtol=1e-4, atol=1e-4)


def test_i3d_flow_frame_sharding_gate(tmp_path):
    """The frame-sharding gate: flow-only single-clip multi-device configs
    (with the mesh dividing the stack) opt in; two-stream and clip-batched
    configs keep clip sharding. Constructor-only — the sandwich parity twin
    is slow-marked below."""
    from video_features_tpu.extractors.i3d import ExtractI3D

    kw = dict(streams=("flow",), stack_size=16, step_size=16,
              clips_per_batch=1, flow_type="pwc",
              i3d_pre_crop_size=64, i3d_crop_size=32)
    exs = ExtractI3D(_cfg(tmp_path, "i3d", 4, **kw))
    assert exs._flow_frame_sharded and exs.clips_per_batch == 1
    two = ExtractI3D(_cfg(tmp_path / "two", "i3d", 4, **{
        **kw, "streams": ("rgb", "flow")}))
    assert not two._flow_frame_sharded and two.clips_per_batch == 4
    multi = ExtractI3D(_cfg(tmp_path / "multi", "i3d", 4, **{
        **kw, "clips_per_batch": 8}))
    assert not multi._flow_frame_sharded
    # a mesh that does not divide the stack falls back to clip sharding
    odd = ExtractI3D(_cfg(tmp_path / "odd", "i3d", 3, **kw))
    assert not odd._flow_frame_sharded
    # an explicit --flow_pair_chunk keeps the clip-sharded step that honors
    # it (the frame-sharded step decodes each shard's pairs in one piece)
    chunked = ExtractI3D(_cfg(tmp_path / "chunk", "i3d", 4, **{
        **kw, "flow_pair_chunk": 4}))
    assert not chunked._flow_frame_sharded and chunked.clips_per_batch == 4


@pytest.mark.slow  # full flow-net + I3D sandwich twice: multi-minute on CPU
def test_i3d_flow_frame_sharded_matches_clip_sharded(tmp_path):
    """Flow-only single-clip multi-device I3D: the stack's frame axis shards
    across the mesh (encode-once + halo) and matches the clip-sharded
    single-device sandwich."""
    from video_features_tpu.extractors.i3d import ExtractI3D

    kw = dict(streams=("flow",), stack_size=16, step_size=16,
              clips_per_batch=1, flow_type="pwc",
              i3d_pre_crop_size=64, i3d_crop_size=32)
    exs = ExtractI3D(_cfg(tmp_path, "i3d", 4, **kw))
    exb = ExtractI3D(_cfg(tmp_path / "base", "i3d", 1, **kw))
    stack = np.random.default_rng(6).integers(
        0, 256, (1, 17, 64, 64, 3), dtype=np.uint8)
    fs, _ = exs._flow_step_sharded(
        exs.i3d_params["flow"], exs.runner.put(stack[0, :-1]),
        exs.runner.put_replicated(stack[0, -1:]))
    fb, _ = exb._flow_step(exb.i3d_params["flow"], exb.runner.put(stack))
    fs, fb = np.asarray(fs), np.asarray(fb)
    assert fs.shape == fb.shape == (1, 1024)
    # Tolerance note: the sandwich QUANTIZES flow to uint8 levels before the
    # I3D stack (reference behavior), so last-ulp reduction-order differences
    # between the sharded and clip-sharded flow nets occasionally flip a
    # quantization bin — observed ≤3e-4 abs / ≤1% rel on ~2% of features
    # (data-seed dependent); bound at ~3× that
    np.testing.assert_allclose(fs, fb, rtol=3e-2, atol=1e-3)


def test_padded_geometry_arithmetic(tmp_path):
    """--precompile's geometry prediction must equal what dispatch pads to."""
    from video_features_tpu.extractors.flow import ExtractFlow

    # RAFT, no bucket: /8 contract on the native size
    raft_ex = ExtractFlow(_cfg(tmp_path, "raft", 1, batch_size=2))
    assert raft_ex._padded_geometry(width=170, height=128) == (128, 176)
    # PWC pads nothing without a bucket (the /64 resize happens in-model)
    pwc_ex = ExtractFlow(_cfg(tmp_path / "p", "pwc", 1, batch_size=2))
    assert pwc_ex._padded_geometry(width=170, height=128) == (128, 170)
    # side_size applies the host edge resize first, then the bucket rounds
    # both axes up: 320×240 → smaller-edge 96 → 96×128 → bucket 64 → 128×128
    bucket = ExtractFlow(_cfg(tmp_path / "b", "raft", 1, batch_size=2,
                              shape_bucket=64, side_size=96))
    assert bucket._padded_geometry(width=320, height=240) == (128, 128)
