"""Shape-bucket planning and ragged packing (--pack_corpus PR 6): probe
clustering and the K-cap, smallest-covering bucket lookup, the collate seam's
partial-consumption contract, anti-starvation flush timing, per-bucket
occupancy accounting, the decode-starvation heuristic, and — through a tiny
jitted extractor — the mixed-geometry acceptance path (≤K buckets, a poisoned
video in a co-packed bucket fails only itself, --retry_failed reprocesses it).
Real-model packed parity lives in tests/test_packer_models.py."""

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import Extractor
from video_features_tpu.io.output import load_done_set
from video_features_tpu.io.video import probe_geometries
from video_features_tpu.models.raft import pad_to_shape, unpad
from video_features_tpu.parallel.packer import (
    CorpusPacker,
    PackSpec,
    ShapeBuckets,
)
from video_features_tpu.reliability import load_failures, reset_faults
from video_features_tpu.utils.metrics import decode_starvation_warning


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


# ---- ShapeBuckets: probe clustering and the K-cap ---------------------------


def test_buckets_under_cap_stay_exact():
    sb = ShapeBuckets([(240, 320), (360, 480)], max_buckets=4)
    assert sb.buckets == [(240, 320), (360, 480)]
    assert sb.bucket_for((240, 320)) == (240, 320)


def test_buckets_merge_to_cap_by_least_padding():
    # (240,320)×3 and (264,352)×1 are the cheap merge; (720,1280) stays alone
    geoms = [(240, 320)] * 3 + [(264, 352), (720, 1280)]
    sb = ShapeBuckets(geoms, max_buckets=2)
    assert sb.buckets == [(264, 352), (720, 1280)]
    assert sb.bucket_for((240, 320)) == (264, 352)
    assert sb.bucket_for((264, 352)) == (264, 352)


def test_buckets_merge_weights_by_video_count():
    # the union must grow over the POPULAR geometry as cheaply as possible:
    # merging (100,100)×9 with (110,110) costs 9 videos' padding; merging the
    # two rare tall/wide shapes costs only their own
    geoms = [(100, 100)] * 9 + [(110, 90), (90, 110)]
    sb = ShapeBuckets(geoms, max_buckets=2)
    assert (100, 100) in sb.buckets
    assert (110, 110) in sb.buckets


def test_bucket_for_picks_smallest_covering_and_adhoc_falls_through():
    sb = ShapeBuckets([(240, 320), (360, 480)], max_buckets=2)
    # covered by both → the smaller-area bucket wins
    assert sb.bucket_for((200, 300)) == (240, 320)
    # no planned bucket covers (failed probe / surprise geometry): own bucket
    assert sb.bucket_for((1080, 1920)) == (1080, 1920)
    # taller than one dim of the small bucket → only the big one covers
    assert sb.bucket_for((300, 320)) == (360, 480)


def test_buckets_cap_validation():
    with pytest.raises(ValueError):
        ShapeBuckets([(8, 8)], max_buckets=0)


def test_probe_geometries_skips_unprobeable_paths(tmp_path):
    vid = _write_video(str(tmp_path / "ok.mp4"), 3, (32, 24))
    bogus = str(tmp_path / "missing.mp4")
    geoms = probe_geometries([vid, bogus])
    assert geoms == {vid: (32, 24)}  # (width, height); bogus skipped, not failed


def test_pad_to_shape_round_trips_and_rejects_shrink():
    frames = np.arange(2 * 5 * 7 * 3, dtype=np.uint8).reshape(2, 5, 7, 3)
    padded, pads = pad_to_shape(frames, (8, 8))
    assert padded.shape == (2, 8, 8, 3)
    np.testing.assert_array_equal(unpad(padded, pads), frames)
    same, pads0 = pad_to_shape(frames, (5, 7))
    assert same is frames and pads0 == (0, 0, 0, 0)
    with pytest.raises(ValueError):
        pad_to_shape(frames, (4, 8))


# ---- engine: collate seam ---------------------------------------------------


def _sum_step(batch):
    arr = np.asarray(batch, np.float32)
    return arr.reshape(arr.shape[0], -1).sum(axis=1, keepdims=True)


def test_engine_collate_partial_consumption_and_row_map():
    """A collate may consume fewer slots than offered; the row map routes
    each consumed slot to its own output row (flow windows burn a frame
    position per video boundary — modeled here as 'only 2 slots per batch,
    read rows in reverse')."""
    taken = []

    def collate(clips, stream_keys):
        taken.append([k for k in stream_keys[:2]])
        batch = np.stack(clips[:2] + clips[:1])  # 3 rows; row 2 is garbage
        return batch, 2, [1, 0]  # slot 0 ← row 1, slot 1 ← row 0

    spec = PackSpec(batch_size=3, empty_row_shape=(1,), open_clips=None,
                    step=_sum_step, finalize=None, collate=collate)
    packer = CorpusPacker(spec, wait=np.asarray)
    packer.begin("a", {})
    for v in (1.0, 2.0, 3.0, 4.0):
        packer.add("a", np.full((2,), v, np.float32))
    packer.finish("a")
    packer.flush()
    (done,) = packer.pop_completed()
    # row map: slot i fetched row_of[i] — values swap pairwise
    np.testing.assert_array_equal(
        done.stacked((1,)), [[4.0], [2.0], [8.0], [6.0]])
    # continuity keys are (stream_id, clip_idx) with consecutive idx
    (k0, k1), (k2, k3) = taken
    assert k0[0] == k1[0] and k1[1] == k0[1] + 1
    assert k3[1] == k2[1] + 1
    # occupancy accounting: 4 real slots over 2 dispatches × batch_size 3
    assert packer.real_slots == 4 and packer.dispatched_slots == 6


# ---- engine: anti-starvation flush ------------------------------------------


def test_engine_stale_flush_frees_a_rare_bucket_mid_corpus():
    """flush_age=2: a rare geometry's partial queue dispatches (and its video
    completes) once two videos finish while it waits — not at corpus end."""
    packer = CorpusPacker(PackSpec(batch_size=4, empty_row_shape=(1,),
                                   open_clips=None, step=_sum_step,
                                   finalize=None),
                          wait=np.asarray, flush_age=2)
    packer.begin("rare", {})
    packer.add("rare", np.ones((3, 3), np.float32))  # lone odd-geometry slot
    packer.finish("rare")
    assert packer.pop_completed() == []
    # two common-geometry videos finish; their batches never fill either
    for name in ("a", "b"):
        packer.begin(name, {})
        packer.add(name, np.ones((2, 2), np.float32))
        packer.finish(name)
    done = {a.video for a in packer.pop_completed()}
    assert "rare" in done  # freed by the age flush, without packer.flush()
    assert packer.stale_flushes >= 1
    stats = packer.bucket_stats()
    assert stats["3x3"]["stale_flushes"] == 1
    assert stats["3x3"]["real_slots"] == 1
    assert stats["3x3"]["dispatched_slots"] == 4
    assert stats["3x3"]["occupancy"] == 0.25


def test_engine_active_bucket_is_not_stale_flushed():
    """A bucket that keeps dispatching is being served: its age resets per
    dispatch, so a persistent partial remainder does not trigger the flush."""
    packer = CorpusPacker(PackSpec(batch_size=2, empty_row_shape=(1,),
                                   open_clips=None, step=_sum_step,
                                   finalize=None),
                          wait=np.asarray, flush_age=1)
    packer.begin("long", {})
    packer.add("long", np.ones((2,), np.float32))
    # short videos finish while `long` keeps its queue busy with full batches
    for i in range(3):
        packer.begin(f"s{i}", {})
        packer.add(f"s{i}", np.ones((2,), np.float32))  # fills → dispatch
        packer.finish(f"s{i}")
        packer.add("long", np.ones((2,), np.float32))
    # three videos finished against flush_age=1, yet the shared bucket kept
    # dispatching full batches — age resets per dispatch, no stale flush
    assert packer.stale_flushes == 0
    assert packer.real_slots == packer.dispatched_slots == 6
    packer.finish("long")
    packer.flush()
    assert {a.video for a in packer.pop_completed()} == {
        "long", "s0", "s1", "s2"}


def test_engine_slowly_fed_bucket_is_not_stale_flushed():
    """A common bucket gaining slots every video is being fed, not stranded:
    age counts from the last slot arrival, so a corpus of short videos that
    fills a batch only every several videos never pays a padded mid-corpus
    flush (the corpus-end-only occupancy is preserved)."""
    packer = CorpusPacker(PackSpec(batch_size=16, empty_row_shape=(1,),
                                   open_clips=None, step=_sum_step,
                                   finalize=None),
                          wait=np.asarray, flush_age=2)
    # 8 videos × 3 clips vs batch 16: the single bucket holds a partial
    # queue across more than flush_age completions between fills
    for i in range(8):
        packer.begin(f"v{i}", {})
        for _ in range(3):
            packer.add(f"v{i}", np.ones((2, 2), np.float32))
        packer.finish(f"v{i}")
    assert packer.stale_flushes == 0
    assert packer.real_slots == packer.dispatched_slots  # only full batches
    packer.flush()
    assert len(packer.pop_completed()) == 8


def test_engine_corpus_flush_isolates_failing_bucket():
    """A device failure dispatching one bucket's corpus-end tail must not
    abort the other buckets' flush: healthy buckets still resolve, and only
    the failing bucket's contributors drain incomplete, wearing its cause."""
    def step(batch):
        if batch.shape[1:] == (3, 3):
            raise RuntimeError("dead bucket program")
        return batch.sum(axis=(1, 2), keepdims=True)[:, 0]

    packer = CorpusPacker(PackSpec(batch_size=4, empty_row_shape=(1,),
                                   open_clips=None, step=step,
                                   finalize=None),
                          wait=np.asarray, flush_age=0)
    packer.begin("bad", {})
    packer.add("bad", np.ones((3, 3), np.float32))
    packer.finish("bad")
    packer.begin("good", {})
    packer.add("good", np.ones((2, 2), np.float32))
    packer.finish("good")
    packer.flush()  # must not raise: the failure is contained per bucket
    assert {a.video for a in packer.pop_completed()} == {"good"}
    (victim,) = packer.drain_incomplete()
    assert victim.video == "bad"
    (cause,) = packer.flush_causes("bad")
    assert "dead bucket program" in cause
    assert packer.flush_causes("good") == []


def test_engine_stale_flush_failure_blames_victims_not_finisher():
    """A device failure during the anti-starvation flush is contained: the
    (healthy) video whose finish() triggered it is NOT failed or retried —
    the flushed bucket's contributors drain incomplete with the cause."""
    calls = {"n": 0}

    def step(batch):
        calls["n"] += 1
        if batch.shape[1:] == (3, 3):  # the rare bucket's program "dies"
            raise RuntimeError("halt on rare bucket")
        return batch.sum(axis=(1, 2), keepdims=True)[:, 0]

    packer = CorpusPacker(PackSpec(batch_size=4, empty_row_shape=(1,),
                                   open_clips=None, step=step,
                                   finalize=None),
                          wait=np.asarray, flush_age=2)
    packer.begin("rare", {})
    packer.add("rare", np.ones((3, 3), np.float32))
    packer.finish("rare")  # age 1 < 2: no flush yet
    packer.begin("ok", {})
    packer.add("ok", np.ones((2, 2), np.float32))
    # `ok`'s finish trips the rare bucket's age flush — a batch holding zero
    # of `ok`'s slots fails, and `ok`'s (healthy) stream must not wear it
    packer.finish("ok")
    assert calls["n"] >= 1
    # causes are attributed per bucket: `rare` wears the failure, the healthy
    # co-resident video whose finish() merely triggered the flush does not
    (cause,) = packer.flush_causes("rare")
    assert "halt on rare bucket" in cause
    assert packer.flush_causes("ok") == []
    assert packer.stale_flushes == 0  # the failed attempt is not counted
    packer.flush()  # corpus end: the healthy bucket still resolves
    done = {a.video for a in packer.pop_completed()}
    assert done == {"ok"}
    (victim,) = packer.drain_incomplete()
    assert victim.video == "rare"


def test_engine_flush_age_zero_keeps_corpus_end_semantics():
    packer = CorpusPacker(PackSpec(batch_size=4, empty_row_shape=(1,),
                                   open_clips=None, step=_sum_step,
                                   finalize=None),
                          wait=np.asarray, flush_age=0)
    packer.begin("rare", {})
    packer.add("rare", np.ones((3, 3), np.float32))
    packer.finish("rare")
    for name in ("a", "b", "c", "d"):
        packer.begin(name, {})
        packer.finish(name)
    assert {a.video for a in packer.pop_completed()} == {"a", "b", "c", "d"}
    packer.flush()  # only the corpus flush frees it
    assert {a.video for a in packer.pop_completed()} == {"rare"}


# ---- decode-starvation heuristic --------------------------------------------


def test_decode_starvation_warning_thresholds():
    assert decode_starvation_warning(0.95, 9.0, 10.0) is None  # well packed
    assert decode_starvation_warning(0.5, 1.0, 10.0) is None  # not decode-bound
    msg = decode_starvation_warning(0.5, 6.0, 10.0, stale_flushes=3)
    assert msg and "--decode_workers" in msg and "3 anti-starvation" in msg
    assert decode_starvation_warning(0.5, 6.0, 0.0) is None  # degenerate wall


# ---- mixed-geometry acceptance: toy extractor over real videos --------------


def _write_video(path, frames, size):
    import cv2

    w = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(frames + size[0])
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return str(path)


class ToyBucketed(Extractor):
    """Frame-slot extractor whose PackSpec plans shape buckets from the
    container probes — the flow extractors' prepare/open_clips wiring with a
    one-compile jitted step (mean/max per frame, geometry-independent after
    the bucket pad)."""

    uses_frame_stream = True
    BATCH = 4
    K = 2

    def __init__(self, cfg):
        super().__init__(cfg)

        def fwd(params, frames_u8):
            x = frames_u8.astype(jnp.float32)
            return jnp.stack([x.mean(axis=(1, 2, 3)), x.max(axis=(1, 2, 3))],
                             axis=-1)

        self._step = self.runner.jit(fwd)
        self._params = self.runner.put_replicated(
            {"w": np.zeros((1,), np.float32)})
        self._buckets = None

    def extract(self, video_path):  # per-video loop unused in these tests
        raise NotImplementedError

    def pack_spec(self):
        def prepare(paths):
            geoms = [(h, w) for w, h in probe_geometries(paths).values()]
            self._buckets = ShapeBuckets(geoms, self.K) if geoms else None

        def open_clips(path):
            meta, frames = self._open_video(path)
            bucket = (self._buckets.bucket_for((meta.height, meta.width))
                      if self._buckets is not None
                      else (meta.height, meta.width))
            info = {"timestamps_ms": []}

            def clips():
                for rgb, pos in self._timed_frames(frames):
                    info["timestamps_ms"].append(pos)
                    yield pad_to_shape(rgb, bucket)[0]

            return info, clips()

        def step(batch):
            return self._step(self._params, self.runner.put(batch))

        def finalize(path, rows, info):
            return {"feat": rows,
                    "timestamps_ms": np.array(info["timestamps_ms"])}

        return PackSpec(batch_size=self.BATCH, empty_row_shape=(2,),
                        open_clips=open_clips, step=step, finalize=finalize,
                        prepare=prepare)


@pytest.fixture(scope="module")
def mixed_corpus(tmp_path_factory):
    """Five videos over three geometries: 32×24 (common), 24×16 (merges into
    the 32×24 bucket under K=2), 64×48 (its own bucket)."""
    d = tmp_path_factory.mktemp("mixed")
    return [_write_video(d / "a0.mp4", 5, (32, 24)),
            _write_video(d / "a1.mp4", 3, (24, 16)),
            _write_video(d / "a2.mp4", 6, (32, 24)),
            _write_video(d / "b0.mp4", 4, (64, 48)),
            _write_video(d / "b1.mp4", 2, (64, 48))]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 0)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        pack_corpus=True, output_path=str(tmp_path / sub),
        tmp_path=str(tmp_path / "t"), **kw)


def test_mixed_geometry_corpus_packs_into_at_most_k_buckets(
        tmp_path, mixed_corpus):
    ex = ToyBucketed(_cfg(tmp_path, "m"))
    assert ex.run(mixed_corpus) == len(mixed_corpus)
    stats = ex._pack_stats
    buckets = stats["buckets"]
    # 3 probed geometries clustered into ≤K=2 slot shapes, each with its own
    # measured occupancy
    assert len(buckets) <= ToyBucketed.K
    assert set(buckets) == {"24x32x3", "48x64x3"}
    for b in buckets.values():
        assert b["dispatched_slots"] >= b["real_slots"] > 0
        assert 0.0 < b["occupancy"] <= 1.0
    # per-bucket totals reconcile with the corpus totals
    assert sum(b["real_slots"] for b in buckets.values()) == stats["real_slots"]
    assert (sum(b["dispatched_slots"] for b in buckets.values())
            == stats["dispatched_slots"])
    # the merged 24×16 video decodes 3 frames into the 24x32 bucket
    assert stats["video_clips"][mixed_corpus[1]] == 3
    feats = np.load(str(tmp_path / "m" / "resnet50" / "a1_feat.npy"))
    assert feats.shape == (3, 2)


def test_poisoned_video_in_a_co_packed_bucket_fails_only_itself(
        tmp_path, mixed_corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:a2")
    ex = ToyBucketed(_cfg(tmp_path, "pz"))
    assert ex.run(mixed_corpus) == len(mixed_corpus) - 1
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(mixed_corpus[2])}
    assert len(load_done_set(ex.output_dir)) == len(mixed_corpus) - 1
    # co-packed bucket neighbours completed with full outputs
    ok = {os.path.basename(p)
          for p in glob.glob(str(tmp_path / "pz" / "resnet50" / "*_feat.npy"))}
    assert ok == {"a0_feat.npy", "a1_feat.npy", "b0_feat.npy", "b1_feat.npy"}

    # --retry_failed semantics: reprocess exactly the manifest set
    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    failed = sorted(load_failures(ex.output_dir))
    assert ex.run(failed) == 1
    assert load_failures(ex.output_dir) == {}
    assert len(load_done_set(ex.output_dir)) == len(mixed_corpus)
