"""bench.py record-keeping helpers: the stale-headline fallback and baseline
reader that keep a tunnel outage from sinking the round's bench record
(BENCH_r03 rc=124, BENCH_r04 rc=1 — the failure mode these exist to end)."""
# fast-registry: default tier — drives jitted extractor paths; compile-heavy for the fast pre-commit tier

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)  # imports nothing heavy at module scope
    return mod


def test_stale_record_is_valid_parseable_headline(bench, capsys):
    bench._emit_stale_record("tpu_unavailable")
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "i3d_rgb_clips_per_sec_per_chip"
    assert rec["error"] == "tpu_unavailable" and rec["stale"] is True
    # an outage run measured NOTHING: value must be 0.0 so a parser that
    # ignores the stale flag can never score the run as a measurement
    # (ADVICE r5); the last committed clean number rides along separately
    assert rec["value"] == 0.0
    assert rec["vs_baseline"] == 0.0
    assert rec["last_known_value"] > 0  # bench_details.json is in-repo
    assert rec["last_known_vs_baseline"] > 0


def test_read_baseline_matches_headline_math(bench):
    baseline, measured = bench._read_baseline()
    with open(os.path.join(REPO, "BASELINE.json")) as f:
        raw = json.load(f)["measured"]
    assert measured == raw
    assert baseline == float(raw["i3d_rgb_clips_per_sec"])


def test_git_rev_is_short_hex(bench):
    rev = bench._git_rev()
    assert rev and 6 <= len(rev) <= 16
    int(rev, 16)  # hex


def test_backend_probe_honors_cpu_quickly(bench, monkeypatch):
    """With JAX_PLATFORMS=cpu the subprocess probe must resolve in seconds —
    round 5 found the env var alone does NOT redirect (the sitecustomize
    pins the platform through the config API), which sent a cpu smoke run
    into a 3×180 s tunnel-probe spiral."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert bench._backend_or_none(retries=1, wait_sec=0,
                                  probe_timeout=120) == "cpu"
