"""Kaiser-sinc resampler: vectorized implementation vs a literal transcription
of the published per-sample kernel, plus signal-quality properties.

The reference resamples non-16 kHz wavs with resampy's kaiser_best filter
(``/root/reference/models/vggish/vggish_src/vggish_input.py:84``); resampy is
not installed here, so the spec oracle is a direct, loop-for-loop rendering of
that kernel's arithmetic (two interpolated-window wings around an accumulating
fractional read time).
"""

import numpy as np
import pytest

from video_features_tpu.audio.resample import FILTERS, resample, sinc_window


def kernel_loop(x, sr_orig, sr_new, filter="kaiser_best"):
    """Per-sample transcription of the band-limited interpolation kernel."""
    num_zeros, precision, rolloff, beta = FILTERS[filter]
    num_table = 2 ** precision
    interp_win = sinc_window(num_zeros, precision, rolloff, beta)
    sample_ratio = sr_new / sr_orig
    scale = min(1.0, sample_ratio)
    if sample_ratio < 1.0:
        interp_win = interp_win * sample_ratio
    interp_delta = np.zeros_like(interp_win)
    interp_delta[:-1] = np.diff(interp_win)
    index_step = int(scale * num_table)
    nwin = len(interp_win)
    n_out = int(len(x) * sample_ratio)
    y = np.zeros(n_out)
    time_register = 0.0
    for t in range(n_out):
        n = int(time_register)
        frac = scale * (time_register - n)
        index_frac = frac * num_table
        offset = int(index_frac)
        eta = index_frac - offset
        for i in range(min(n + 1, (nwin - offset) // index_step)):
            w = interp_win[offset + i * index_step] + eta * interp_delta[offset + i * index_step]
            y[t] += w * x[n - i]
        frac = scale - frac
        index_frac = frac * num_table
        offset = int(index_frac)
        eta = index_frac - offset
        for k in range(min(len(x) - n - 1, (nwin - offset) // index_step)):
            w = interp_win[offset + k * index_step] + eta * interp_delta[offset + k * index_step]
            y[t] += w * x[n + k + 1]
        time_register += 1.0 / sample_ratio
    return y


@pytest.mark.parametrize("sr_orig,sr_new", [(44100, 16000), (8000, 16000), (22050, 16000)])
@pytest.mark.parametrize("filt", ["kaiser_best", "kaiser_fast"])
def test_matches_kernel_loop(sr_orig, sr_new, filt):
    rng = np.random.default_rng(0)
    x = rng.standard_normal(sr_orig // 10)  # 100 ms
    got = resample(x, sr_orig, sr_new, filter=filt)
    want = kernel_loop(x, sr_orig, sr_new, filter=filt)
    assert got.shape == want.shape == (int(len(x) * sr_new / sr_orig),)
    # identical arithmetic up to tap-summation order (einsum vs sequential)
    np.testing.assert_allclose(got, want, rtol=1e-10, atol=1e-12)


def test_sine_preserved_through_downsample():
    sr_orig, sr_new, f0 = 48000, 16000, 440.0
    t = np.arange(sr_orig) / sr_orig
    y = resample(np.sin(2 * np.pi * f0 * t), sr_orig, sr_new)
    t2 = np.arange(len(y)) / sr_new
    ideal = np.sin(2 * np.pi * f0 * t2)
    core = slice(200, len(y) - 200)  # ignore filter edge transients
    err = np.abs(y[core] - ideal[core]).max()
    assert err < 5e-3, err


def test_dc_gain_near_unity():
    y = resample(np.ones(8000), 8000, 16000)
    core = y[200:-200]
    assert abs(core.mean() - 1.0) < 1e-3
    assert np.abs(core - 1.0).max() < 2e-3


def test_upsample_then_downsample_roundtrip():
    rng = np.random.default_rng(1)
    # band-limit the test signal well below the downsample cutoff
    from scipy.signal import butter, filtfilt

    x = filtfilt(*butter(6, 0.2), rng.standard_normal(4000))
    y = resample(resample(x, 16000, 32000), 32000, 16000)
    core = slice(300, len(x) - 300)
    assert np.abs(y[core] - x[core]).max() < 5e-3


def test_output_length_floor_semantics():
    assert resample(np.zeros(1001), 44100, 16000).shape[0] == int(1001 * 16000 / 44100)


def test_same_rate_is_identity():
    x = np.random.default_rng(2).standard_normal(100)
    np.testing.assert_array_equal(resample(x, 16000, 16000), x)


def test_melspec_uses_kaiser_path():
    """waveform_to_examples on a 44.1 kHz sine == examples of the resampled signal."""
    from video_features_tpu.audio import melspec

    t = np.arange(44100) / 44100.0
    x = 0.5 * np.sin(2 * np.pi * 440.0 * t)
    got = melspec.waveform_to_examples(x, 44100)
    want = melspec.waveform_to_examples(resample(x, 44100, 16000), 16000)
    np.testing.assert_allclose(got, want, atol=1e-9)
    assert got.shape[1:] == (96, 64)
