"""Flax ResNet-50 numerical parity vs a torch mirror (random weights)."""
# fast-registry: default tier — resnet50 forward parity (heavy compile)

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import jax
import jax.numpy as jnp
import torch

from torch_mirrors import ResNet50 as TorchResNet50, random_init_
from video_features_tpu.models.resnet import ResNet50, preprocess_frames
from video_features_tpu.weights.convert_torch import convert_resnet50


@pytest.fixture(scope="module")
def converted():
    tm = random_init_(TorchResNet50(), seed=3)
    params = convert_resnet50(tm.state_dict())
    return tm, params


def test_param_tree_matches_model(converted):
    tm, params = converted
    model = ResNet50()
    # features=False so the fc head is created too
    init = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3)), features=False)["params"]
    init_paths = {tuple(p) for p, _ in jax.tree_util.tree_flatten_with_path(init)[0]}
    conv_paths = {tuple(p) for p, _ in jax.tree_util.tree_flatten_with_path(
        jax.tree_util.tree_map(jnp.asarray, params))[0]}
    assert {str(p) for p in init_paths} == {str(p) for p in conv_paths}
    # shapes agree everywhere
    jax.tree_util.tree_map(lambda a, b: None if a.shape == b.shape else (_ for _ in ()).throw(
        AssertionError(f"{a.shape} vs {b.shape}")), init, jax.tree_util.tree_map(jnp.asarray, params))


def test_features_parity(converted):
    tm, params = converted
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 32, 32, 3), dtype=np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x).permute(0, 3, 1, 2), features=True).numpy()
    out = ResNet50().apply({"params": params}, jnp.asarray(x), features=True)
    out = np.asarray(out)
    assert out.shape == ref.shape == (2, 2048)
    # fp32 accumulation order differs between XLA and torch conv kernels; after
    # 53 convs the divergence is ~1e-4 absolute. Track closeness via atol+cosine.
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)
    cos = np.sum(out * ref, -1) / (np.linalg.norm(out, axis=-1) * np.linalg.norm(ref, axis=-1))
    assert np.all(cos > 1 - 1e-6), cos


def test_logits_parity(converted):
    tm, params = converted
    rng = np.random.default_rng(1)
    x = rng.standard_normal((1, 32, 32, 3), dtype=np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x).permute(0, 3, 1, 2), features=False).numpy()
    out = np.asarray(ResNet50().apply({"params": params}, jnp.asarray(x), features=False))
    assert out.shape == (1, 1000)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)


def test_preprocess_matches_torch_normalize():
    rng = np.random.default_rng(2)
    u8 = rng.integers(0, 256, (3, 8, 8, 3), dtype=np.uint8)
    mean = torch.tensor([0.485, 0.456, 0.406]).view(3, 1, 1)
    std = torch.tensor([0.229, 0.224, 0.225]).view(3, 1, 1)
    ref = ((torch.from_numpy(u8).permute(0, 3, 1, 2).float() / 255.0) - mean) / std
    out = np.asarray(preprocess_frames(jnp.asarray(u8)))
    np.testing.assert_allclose(out, ref.permute(0, 2, 3, 1).numpy(), rtol=1e-6, atol=1e-6)
