"""Observability: stage clock semantics and the opt-in per-video report."""
# fast-registry: default tier — stage-clock tests with real sleeps

import time


from video_features_tpu.utils.metrics import StageClock, maybe_profiler, metrics_enabled


def test_stage_clock_accumulates():
    c = StageClock()
    with c.stage("decode"):
        time.sleep(0.01)
    with c.stage("decode"):
        pass
    assert c.counts["decode"] == 2
    assert c.seconds["decode"] >= 0.01


def test_timed_iter_attributes_blocking_time():
    c = StageClock()

    def slow_gen():
        for i in range(3):
            time.sleep(0.005)
            yield i

    assert list(c.timed_iter(slow_gen(), "decode")) == [0, 1, 2]
    assert c.counts["decode"] == 3
    assert c.seconds["decode"] >= 0.015


def test_report_format():
    c = StageClock()
    with c.stage("decode"):
        pass
    line = c.report("vid.mp4", wall=1.0)
    assert "vid.mp4" in line and "decode" in line and "overlapped/other" in line


def test_metrics_enabled_gates():
    assert metrics_enabled("/tmp/x")
    assert not metrics_enabled(None)


def test_maybe_profiler_noop():
    with maybe_profiler(None):
        pass  # must not require jax


def test_run_prints_stage_report(tmp_path, sample_video, monkeypatch, capsys):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    monkeypatch.setenv("VFT_METRICS", "1")
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.resnet import ExtractResNet50

    cfg = ExtractionConfig(
        feature_type="resnet50", batch_size=64, extraction_fps=2, num_devices=1,
        on_extraction="save_numpy", output_path=str(tmp_path / "o"),
        tmp_path=str(tmp_path / "t"),
    )
    ex = ExtractResNet50(cfg)
    assert ex.run([sample_video]) == 1
    out = capsys.readouterr().out
    assert "decode" in out and "device_wait" in out
    assert "videos/sec" in out


def test_distributed_noop_without_env(monkeypatch):
    monkeypatch.delenv("VFT_MULTIHOST", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    from video_features_tpu.parallel import maybe_initialize_distributed

    assert maybe_initialize_distributed() is False
