"""Observability: stage clock semantics (incl. thread safety and the
telemetry-registry feed), the decode-starvation heuristic wired end-to-end
from registry-fed values, histogram bucket/percentile math, and the opt-in
per-video report."""
# fast-registry: default tier — stage-clock tests with real sleeps

import threading
import time


from video_features_tpu.obs import Histogram, MetricsRegistry
from video_features_tpu.utils.metrics import (
    StageClock,
    decode_starvation_warning,
    maybe_profiler,
    metrics_enabled,
)


def test_stage_clock_accumulates():
    c = StageClock()
    with c.stage("decode"):
        time.sleep(0.01)
    with c.stage("decode"):
        pass
    assert c.counts["decode"] == 2
    assert c.seconds["decode"] >= 0.01


def test_timed_iter_attributes_blocking_time():
    c = StageClock()

    def slow_gen():
        for i in range(3):
            time.sleep(0.005)
            yield i

    assert list(c.timed_iter(slow_gen(), "decode")) == [0, 1, 2]
    assert c.counts["decode"] == 3
    assert c.seconds["decode"] >= 0.015


def test_report_format():
    c = StageClock()
    with c.stage("decode"):
        pass
    line = c.report("vid.mp4", wall=1.0)
    assert "vid.mp4" in line and "decode" in line and "overlapped/other" in line


def test_stage_clock_increments_are_thread_safe():
    """add_seconds/add_bytes/add_units arrive from staging-ring commit hooks
    and the writer thread while timed_iter runs on the daemon thread — a
    torn += would silently skew the report, so every mutation locks."""
    c = StageClock()
    n, per = 4, 5000

    def work():
        for _ in range(per):
            c.add_seconds("decode", 1.0)
            c.add_bytes("decode", 3)
            c.add_units("clips", 2)

    threads = [threading.Thread(target=work) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.seconds["decode"] == float(n * per)
    assert c.bytes["decode"] == 3 * n * per
    assert c.units["clips"] == 2 * n * per


def test_stage_clock_feeds_the_registry():
    reg = MetricsRegistry()
    c = StageClock(registry=reg, labels={"model": "resnet50"})
    c.add_seconds("decode", 1.5)
    with c.stage("device_wait"):
        pass
    c.add_bytes("transfer", 1024)
    c.add_units("packed_slots", 8)
    assert reg.counter_value("stage_seconds_total", stage="decode",
                             model="resnet50") == 1.5
    # the stage() context-manager arm must CREATE the labeled series (a
    # bare >= 0.0 check would pass on the missing-series default of 0.0)
    fed_stages = {tuple(sorted(c["labels"].items()))
                  for c in reg.snapshot()["counters"]
                  if c["name"] == "stage_seconds_total"}
    assert (("model", "resnet50"), ("stage", "device_wait")) in fed_stages
    assert reg.counter_value("stage_bytes_total", stage="transfer",
                             model="resnet50") == 1024
    assert reg.counter_value("stage_units_total", stage="packed_slots",
                             model="resnet50") == 8


def test_timed_iter_feeds_registry_bytes():
    reg = MetricsRegistry()
    c = StageClock(registry=reg)
    items = [b"abcd", b"xy"]
    assert list(c.timed_iter(iter(items), "decode", bytes_of=len)) == items
    assert reg.counter_value("stage_bytes_total", stage="decode") == 6
    assert c.bytes["decode"] == 6


def test_starvation_warning_wired_from_registry_fed_values():
    """The decode-starvation heuristic driven end-to-end from values READ
    BACK out of the registry the stage clock fed — not hand-passed floats:
    the same path the serving daemon's autoscaler/stats consumers take."""
    reg = MetricsRegistry()
    clock = StageClock(registry=reg, labels={"model": "resnet50"})
    clock.add_seconds("decode", 4.5)  # decode-bound interval
    clock.add_units("packed_slots", 100)
    clock.add_units("packed_clips", 60)  # occupancy 0.6 < 0.8

    def counter(metric, stage):
        return reg.counter_value(metric, stage=stage, model="resnet50")

    occupancy = (counter("stage_units_total", "packed_clips")
                 / counter("stage_units_total", "packed_slots"))
    msg = decode_starvation_warning(
        occupancy=occupancy,
        decode_seconds=counter("stage_seconds_total", "decode"),
        wall=10.0,
        transfer_seconds=counter("stage_seconds_total", "transfer"))
    assert msg is not None and "--decode_workers" in msg

    # transfer-bound interval: same registry path, other branch
    reg2 = MetricsRegistry()
    clock2 = StageClock(registry=reg2, labels={"model": "raft"})
    clock2.add_seconds("decode", 0.2)
    clock2.add_seconds("transfer", 4.5)
    clock2.add_units("packed_slots", 100)
    clock2.add_units("packed_clips", 60)
    msg2 = decode_starvation_warning(
        occupancy=0.6,
        decode_seconds=reg2.counter_value("stage_seconds_total",
                                          stage="decode", model="raft"),
        wall=10.0,
        transfer_seconds=reg2.counter_value("stage_seconds_total",
                                            stage="transfer", model="raft"))
    assert msg2 is not None and "float32_wire" in msg2

    # healthy occupancy read back from the registry: no warning
    reg3 = MetricsRegistry()
    clock3 = StageClock(registry=reg3)
    clock3.add_units("packed_slots", 100)
    clock3.add_units("packed_clips", 95)
    assert decode_starvation_warning(
        occupancy=reg3.counter_value("stage_units_total",
                                     stage="packed_clips")
        / reg3.counter_value("stage_units_total", stage="packed_slots"),
        decode_seconds=9.0, wall=10.0) is None


def test_histogram_bucket_boundaries_are_le_inclusive():
    h = Histogram(bounds=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 1.5, 2.0, 3.0, 4.0, 9.0):
        h.observe(v)
    # Prometheus le semantics: a value ON a bound lands in that bucket
    assert h.counts == [2, 2, 2, 1]
    assert h.bucket_index(1.0) == 0 and h.bucket_index(1.0000001) == 1
    assert h.bucket_index(100.0) == 3  # overflow bucket


def test_histogram_percentiles_interpolate_within_buckets():
    h = Histogram(bounds=(1.0, 2.0))
    for k in range(1, 101):
        h.observe(k / 100)  # uniform over (0, 1]
    assert abs(h.quantile(0.5) - 0.5) < 1e-9
    assert abs(h.quantile(0.99) - 0.99) < 1e-9
    # overflow values clamp to the last finite bound
    h_over = Histogram(bounds=(1.0, 2.0))
    for _ in range(10):
        h_over.observe(50.0)
    assert h_over.quantile(0.5) == 2.0
    # empty histogram quantiles are 0 (nothing observed, nothing claimed)
    assert Histogram().quantile(0.99) == 0.0
    # sum/count bookkeeping
    assert h.count == 100 and abs(h.sum - 50.5) < 1e-9


def test_metrics_enabled_gates():
    assert metrics_enabled("/tmp/x")
    assert not metrics_enabled(None)


def test_maybe_profiler_noop():
    with maybe_profiler(None):
        pass  # must not require jax


def test_run_prints_stage_report(tmp_path, sample_video, monkeypatch, capsys):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    monkeypatch.setenv("VFT_METRICS", "1")
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.resnet import ExtractResNet50

    cfg = ExtractionConfig(
        feature_type="resnet50", batch_size=64, extraction_fps=2, num_devices=1,
        on_extraction="save_numpy", output_path=str(tmp_path / "o"),
        tmp_path=str(tmp_path / "t"),
    )
    ex = ExtractResNet50(cfg)
    assert ex.run([sample_video]) == 1
    out = capsys.readouterr().out
    assert "decode" in out and "device_wait" in out
    assert "videos/sec" in out


def test_distributed_noop_without_env(monkeypatch):
    monkeypatch.delenv("VFT_MULTIHOST", raising=False)
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    from video_features_tpu.parallel import maybe_initialize_distributed

    assert maybe_initialize_distributed() is False
