"""Cross-video decode prefetcher: equivalence with inline decode, memory
bounding, and error isolation through the per-video fault barrier."""
# fast-registry: default tier — real-sleep concurrency tests on the decode pool

import threading
import time

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.resnet import ExtractResNet50
from video_features_tpu.parallel.pipeline import DecodePrefetcher


def _fake_open(path):
    if path == "bad.mp4":
        raise RuntimeError("corrupt container")
    n = int(path.split("_")[1].split(".")[0])
    meta = {"path": path, "fps": 25.0}
    frames = ((np.full((4, 4, 3), i + n, np.uint8), float(i)) for i in range(n))
    return meta, frames


def test_prefetched_matches_inline():
    pool = DecodePrefetcher(_fake_open, workers=2)
    paths = [f"v_{n}.mp4" for n in (3, 5, 2)]
    for p in paths:
        pool.schedule(p)
    try:
        for p in paths:
            meta, frames = pool.get(p)
            want_meta, want_frames = _fake_open(p)
            assert meta == want_meta
            got = list(frames)
            want = list(want_frames)
            assert len(got) == len(want)
            for (ga, gp), (wa, wp) in zip(got, want):
                np.testing.assert_array_equal(ga, wa)
                assert gp == wp
    finally:
        pool.shutdown()


def test_unscheduled_path_decodes_inline():
    pool = DecodePrefetcher(_fake_open, workers=1)
    try:
        meta, frames = pool.get("v_4.mp4")  # never scheduled
        assert len(list(frames)) == 4
    finally:
        pool.shutdown()


def test_decode_error_raised_at_consume():
    pool = DecodePrefetcher(_fake_open, workers=2)
    pool.schedule("bad.mp4")
    pool.schedule("v_3.mp4")
    try:
        with pytest.raises(RuntimeError, match="corrupt"):
            meta, frames = pool.get("bad.mp4")
            list(frames)
        meta, frames = pool.get("v_3.mp4")  # others unaffected
        assert len(list(frames)) == 3
    finally:
        pool.shutdown()


def test_buffer_bound_blocks_worker():
    """A slow consumer must not let the worker buffer more than max_buffered."""
    produced = []

    def open_counting(path):
        def gen():
            for i in range(100):
                produced.append(i)
                yield np.zeros((2, 2, 3), np.uint8), float(i)
        return {"path": path}, gen()

    pool = DecodePrefetcher(open_counting, workers=1, max_buffered=8)
    pool.schedule("x")
    try:
        time.sleep(0.5)  # worker runs ahead until the queue bound stops it
        assert len(produced) <= 8 + 2  # queue cap + one in-flight + epsilon
        meta, frames = pool.get("x")
        assert len(list(frames)) == 100  # and everything still arrives
    finally:
        pool.shutdown()


def test_byte_cap_bounds_buffered_frames_tighter_than_count():
    """Big frames: the byte bound (not the 512-frame count bound) must stop
    the worker — a mixed 1080p corpus must not pin GBs under the count cap."""
    produced = []
    frame = np.zeros((64, 64, 3), np.uint8)  # 12 KB

    def open_big(path):
        def gen():
            for i in range(100):
                produced.append(i)
                yield frame.copy(), float(i)

        return {"path": path}, gen()

    pool = DecodePrefetcher(open_big, workers=1, max_buffered=512,
                            max_buffered_bytes=frame.nbytes * 4)
    pool.schedule("x")
    try:
        time.sleep(0.6)  # worker runs ahead until the byte bound stops it
        assert len(produced) <= 4 + 2  # ~4 frames of budget + one in flight
        meta, frames = pool.get("x")
        assert len(list(frames)) == 100  # and everything still arrives
    finally:
        pool.shutdown()


def test_byte_cap_admits_single_oversized_frame():
    """A frame larger than the whole byte budget must still flow (an empty
    buffer always admits one item) — never a livelock."""
    frame = np.zeros((32, 32, 3), np.uint8)

    def open_one(path):
        return {"path": path}, iter([(frame, 0.0), (frame, 1.0)])

    pool = DecodePrefetcher(open_one, workers=1, max_buffered_bytes=16)
    pool.schedule("x")
    try:
        meta, frames = pool.get("x")
        assert len(list(frames)) == 2
    finally:
        pool.shutdown()


def test_shutdown_joins_threads():
    pool = DecodePrefetcher(_fake_open, workers=2, max_buffered=2)
    for n in (50, 60):
        pool.schedule(f"v_{n}.mp4")
    time.sleep(0.2)
    pool.shutdown()  # workers blocked on full queues must exit
    assert all(not t.is_alive() for t in pool._threads)
    assert threading.active_count() < 20


def test_extractor_run_with_decode_workers(tmp_path, sample_video, monkeypatch):
    """End-to-end: --decode_workers 2 produces the same features as inline."""
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")

    def run(workers, sub):
        cfg = ExtractionConfig(
            feature_type="resnet50", on_extraction="save_numpy",
            output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"),
            batch_size=8, extraction_fps=2, decode_workers=workers)
        ex = ExtractResNet50(cfg)
        assert ex.run([sample_video, sample_video.replace(
            "v_GGSY1Qvo990", "v_ZNVhz7ctTq0")]) == 2
        import glob
        return {p.split("/")[-1]: np.load(p)
                for p in sorted(glob.glob(str(tmp_path / sub / "resnet50" / "*.npy")))}

    inline = run(1, "a")
    pooled = run(2, "b")
    assert set(inline) == set(pooled) and len(inline) >= 4
    for k in inline:
        np.testing.assert_array_equal(inline[k], pooled[k])


def test_release_frees_worker_after_abandoned_drain():
    """A compute failure abandons the drain mid-video; release() must free the
    worker's semaphore permit so later videos still decode (regression: with
    one permit pinned per abandoned video, `workers` failures deadlocked the
    whole run)."""
    pool = DecodePrefetcher(_fake_open, workers=1, max_buffered=4)
    paths = [f"v_{n}.mp4" for n in (100, 90, 80)]
    for p in paths[:2]:
        pool.schedule(p)
    try:
        for k, p in enumerate(paths):
            pool.schedule(paths[min(k + 1, len(paths) - 1)])
            meta, frames = pool.get(p)
            next(frames)  # consume one frame...
            pool.release(p)  # ...then the fault barrier abandons the video
        # reaching here without hanging IS the assertion; also verify a fresh
        # full video still streams end-to-end afterwards
        pool.schedule("v_7.mp4")
        meta, frames = pool.get("v_7.mp4")
        assert len(list(frames)) == 7
        pool.release("v_7.mp4")
    finally:
        pool.shutdown()
