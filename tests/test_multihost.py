"""Real two-process jax.distributed bootstrap over the loopback "DCN".

The reference's only multi-machine mechanism is manually split file lists
(``/root/reference/gen_file_list.py:6-21``); here the equivalent is
``maybe_initialize_distributed`` + ``shard_video_list``. This test launches TWO
actual Python processes that join one JAX distributed job via a localhost
coordinator (the same code path a TPU pod uses over DCN), then asserts the
processes agree on the world size and take disjoint, exhaustive, round-robin
video shards.
"""
# fast-registry: default tier — loopback two-process jax.distributed init

import json
import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r"""
import json, os, re, sys
os.environ["JAX_PLATFORMS"] = "cpu"
# one local device per process (the parent pytest env forces 8 for the
# single-process mesh tests; here the two processes ARE the mesh)
os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()
sys.path.insert(0, os.environ["VFT_REPO"])
import jax
# the env var alone is not enough under the axon sitecustomize (see
# tests/conftest.py); multiprocess CPU additionally needs gloo collectives
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
from video_features_tpu.parallel.pipeline import (
    maybe_initialize_distributed, shard_video_list)

multi = maybe_initialize_distributed()

# one cross-process collective over the federated 2-device mesh: the actual
# DCN communication path (psum of rank+1 over both processes -> 3.0 on each)
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("hosts",))  # 2 global devices, 1 per process
local = jnp.full((1,), float(jax.process_index() + 1), jnp.float32)
summed = jax.jit(
    shard_map(lambda x: jax.lax.psum(x, "hosts"), mesh=mesh,
              in_specs=P("hosts"), out_specs=P("hosts")),
)(jax.make_array_from_single_device_arrays(
    (2,), jax.NamedSharding(mesh, P("hosts")), [local]))
psum_val = float(summed.addressable_data(0)[0])

paths = [f"v{i:02d}.mp4" for i in range(7)]
print("RESULT " + json.dumps({
    "multi": bool(multi),
    "process_index": jax.process_index(),
    "process_count": jax.process_count(),
    "global_devices": len(jax.devices()),
    "psum": psum_val,
    "shard": shard_video_list(paths),
}), flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_bootstrap_and_disjoint_shards():
    port = _free_port()
    env_base = {
        **os.environ,
        "VFT_REPO": REPO,
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
    }
    procs = []
    for rank in (0, 1):
        env = {**env_base, "JAX_PROCESS_ID": str(rank)}
        procs.append(subprocess.Popen(
            [sys.executable, "-c", WORKER], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    results = {}
    for rank, p in enumerate(procs):
        out, err = p.communicate(timeout=220)
        assert p.returncode == 0, f"rank {rank} failed:\n{err[-2000:]}"
        line = [l for l in out.splitlines() if l.startswith("RESULT ")][-1]
        results[rank] = json.loads(line[len("RESULT "):])

    for rank, r in results.items():
        assert r["multi"] is True
        assert r["process_count"] == 2
        assert r["process_index"] == rank
        assert r["global_devices"] == 2
        assert r["psum"] == 3.0  # 1 + 2 across processes: the collective ran
    paths = [f"v{i:02d}.mp4" for i in range(7)]
    s0, s1 = results[0]["shard"], results[1]["shard"]
    assert s0 == paths[0::2] and s1 == paths[1::2]  # round-robin, gen_file_list semantics
    assert not (set(s0) & set(s1))
    assert sorted(s0 + s1) == sorted(paths)
