"""End-to-end fault-injection suite: the run loop under injected failures.

Drives the full per-video barrier — retry/backoff, watchdog, failure
manifest, circuit breaker, decode-pool crash propagation, kill-mid-write —
through the ``VFT_FAULTS`` harness (``reliability/faults.py``) against a
lightweight frame-stream extractor, plus one real ``run.main`` job for the
exit-code contract.
"""
# fast-registry: default tier — e2e extraction under injected faults (compiles)

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import Extractor
from video_features_tpu.io.output import load_done_set
from video_features_tpu.reliability import (
    CircuitBreakerTripped,
    failed_manifest_path,
    load_failures,
    reset_faults,
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


def _write_video(path, frames=4, size=(32, 24)):
    import cv2

    w = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(0)
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return str(path)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Six decodable tiny videos vid0..vid5."""
    d = tmp_path_factory.mktemp("corpus")
    return [_write_video(d / f"vid{i}.mp4") for i in range(6)]


class StreamCounter(Extractor):
    """Minimal frame-stream consumer: exercises the run loop, not a model."""

    uses_frame_stream = True

    def extract(self, video_path):
        meta, frames = self._open_video(video_path)
        total, n = 0.0, 0
        for rgb, _pos in frames:
            total += float(rgb.mean())
            n += 1
        return {"feat": np.asarray([total, float(n)], np.float32)}


def _cfg(tmp_path, **kw):
    kw.setdefault("retries", 2)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"), **kw)


def test_transient_failure_retried_with_backoff_and_succeeds(
        tmp_path, corpus, monkeypatch, capsys):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_transient:vid2:1")
    ex = StreamCounter(_cfg(tmp_path))
    assert ex.run(corpus) == len(corpus)
    assert load_failures(ex.output_dir) == {}
    assert len(load_done_set(ex.output_dir)) == len(corpus)
    out = capsys.readouterr().out
    assert "attempt 1 failed" in out and "retrying in" in out


def test_permanent_failures_recorded_and_job_completes(tmp_path, corpus):
    """~30% of the corpus is corrupt; the job finishes with correct counts and
    every failure lands classified in the failure manifest."""
    bad = [str(tmp_path / f"bad{i}.mp4") for i in range(3)]
    for p in bad:
        with open(p, "wb") as f:
            f.write(b"\x13garbage" * 512)
    paths = corpus[:1] + bad[:1] + corpus[1:4] + bad[1:] + corpus[4:]
    ex = StreamCounter(_cfg(tmp_path, retries=1))
    assert ex.run(paths) == len(corpus)
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(p) for p in bad}
    for rec in failures.values():
        assert rec["error_class"] == "DecodeError"
        assert rec["transient"] is False
        assert rec["attempts"] == 1  # permanent: no retry burned
    assert len(load_done_set(ex.output_dir)) == len(corpus)


def test_watchdog_cancels_injected_hang(tmp_path, corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:hang(30):vid1:1")
    ex = StreamCounter(_cfg(tmp_path, video_timeout=0.5, retries=1))
    t0 = time.monotonic()
    assert ex.run(corpus) == len(corpus) - 1
    assert time.monotonic() - t0 < 15.0  # the 30s hang did not run out
    failures = load_failures(ex.output_dir)
    (rec,) = failures.values()
    assert rec["video"] == os.path.abspath(corpus[1])
    assert rec["error_class"] == "VideoTimeoutError"
    assert rec["attempts"] == 1  # timeouts are permanent: not retried


def test_watchdog_abandoned_attempt_never_marks_done(tmp_path, corpus, monkeypatch):
    """An attempt that outlives its timeout and then completes must discard
    its results — not write features + a done record for a video the run
    already counted as failed (regression: double-bookkeeping both manifests)."""
    monkeypatch.setenv("VFT_FAULTS", "extract:hang(1.5):vid0:1")
    ex = StreamCounter(_cfg(tmp_path, video_timeout=0.3, retries=0))
    assert ex.run(corpus[:1]) == 0
    time.sleep(2.5)  # let the abandoned thread wake up past the hang
    assert load_done_set(ex.output_dir) == set()
    assert not any(n.endswith(".npy") for n in os.listdir(ex.output_dir))
    (rec,) = load_failures(ex.output_dir).values()
    assert rec["error_class"] == "VideoTimeoutError"


def test_retry_failed_reprocesses_exactly_the_failed_set(
        tmp_path, corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid3")
    ex = StreamCounter(_cfg(tmp_path))
    assert ex.run(corpus) == len(corpus) - 1
    assert set(load_failures(ex.output_dir)) == {os.path.abspath(corpus[3])}

    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    failed = sorted(load_failures(ex.output_dir))
    assert failed == [os.path.abspath(corpus[3])]
    assert ex.run(failed) == 1
    # the success pruned its record; the empty manifest file is removed
    assert load_failures(ex.output_dir) == {}
    assert not os.path.exists(failed_manifest_path(ex.output_dir))
    assert len(load_done_set(ex.output_dir)) == len(corpus)


def test_circuit_breaker_aborts_on_max_failures(tmp_path, corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent")
    ex = StreamCounter(_cfg(tmp_path, max_failures=1))
    with pytest.raises(CircuitBreakerTripped, match="max_failures"):
        ex.run(corpus)
    # the two tolerated-then-tripping failures are on record for --retry_failed
    assert len(load_failures(ex.output_dir)) == 2


def test_decode_pool_worker_crash_surfaces_classified(tmp_path, corpus, monkeypatch):
    """A worker crashing inside the pool (not in open_video) must surface as a
    classified error at the barrier and not deadlock the remaining videos."""
    monkeypatch.setenv("VFT_FAULTS", "pool_worker:raise:vid4")
    ex = StreamCounter(_cfg(tmp_path, decode_workers=2, retries=1))
    t0 = time.monotonic()
    assert ex.run(corpus) == len(corpus) - 1
    assert time.monotonic() - t0 < 30.0  # no deadlock
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(corpus[4])}
    assert failures[os.path.abspath(corpus[4])]["error_class"] == "DecodeError"


def test_kill_mid_write_leaves_no_partial_npy(tmp_path):
    """SIGKILL between tmp-write and rename: the final .npy must not exist,
    resume must not count the video done, and a rerun completes the write."""
    out = str(tmp_path / "out")
    code = (
        "import os\n"
        "os.environ['VFT_FAULTS'] = 'save:kill'\n"
        "import numpy as np\n"
        "from video_features_tpu.io.output import action_on_extraction\n"
        f"action_on_extraction({{'feat': np.arange(100000)}}, 'vidX.mp4', {out!r}, 'save_numpy')\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 137, proc.stderr
    final = os.path.join(out, "vidX_feat.npy")
    assert not os.path.exists(final)  # never a truncated readable .npy
    assert load_done_set(out) == set()  # resume will redo this video

    action = (
        "import numpy as np\n"
        "from video_features_tpu.io.output import action_on_extraction\n"
        f"action_on_extraction({{'feat': np.arange(100000)}}, 'vidX.mp4', {out!r}, 'save_numpy')\n"
    )
    env.pop("VFT_FAULTS", None)
    proc = subprocess.run([sys.executable, "-c", action], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    np.testing.assert_array_equal(np.load(final), np.arange(100000))


def test_run_main_exit_codes_and_counts(tmp_path, corpus, monkeypatch, capsys):
    """Real CLI job (ResNet-50, random weights): a fault-injected run where
    2/6 videos fail exits 1 with correct manifests; --retry_failed with the
    faults cleared reprocesses exactly those 2 and exits 0."""
    from video_features_tpu.run import main as run_main

    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    monkeypatch.setenv("VFT_FAULTS",
                       "extract:raise_permanent:vid1;extract:raise_permanent:vid4")
    out, tmp = str(tmp_path / "o"), str(tmp_path / "t")
    argv = ["--feature_type", "resnet50", "--video_paths", *corpus,
            "--on_extraction", "save_numpy", "--output_path", out,
            "--tmp_path", tmp, "--num_devices", "1", "--batch_size", "4",
            "--retries", "1", "--retry_backoff", "0.01"]
    assert run_main(argv) == 1
    feat_dir = os.path.join(out, "resnet50")
    assert len(load_done_set(feat_dir)) == 4
    assert set(load_failures(feat_dir)) == {
        os.path.abspath(corpus[1]), os.path.abspath(corpus[4])}
    assert "2 video(s) failed" in capsys.readouterr().out

    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    assert run_main(argv + ["--retry_failed"]) == 0
    assert len(load_done_set(feat_dir)) == 6
    assert load_failures(feat_dir) == {}
    # every saved output is loadable — no partial files anywhere
    for name in os.listdir(feat_dir):
        if name.endswith(".npy"):
            np.load(os.path.join(feat_dir, name))
    assert not any(n.endswith(".tmp") for n in os.listdir(feat_dir))
