"""Correlation implementations: Pallas kernel and on-demand RAFT lookup must
match the parity-proven defaults (reference CUDA semantics:
correlation.py:44-112, corr.py:12-91)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


import jax.numpy as jnp

from video_features_tpu.ops.pallas_corr import corr81, corr81_pallas, corr81_xla


@pytest.fixture
def fmaps(rng):
    f1 = rng.normal(size=(2, 12, 16, 32)).astype(np.float32)
    f2 = rng.normal(size=(2, 12, 16, 32)).astype(np.float32)
    return jnp.asarray(f1), jnp.asarray(f2)


def test_corr81_xla_semantics(fmaps):
    """Channel k=(dy+4)*9+(dx+4) is the mean-over-channels shifted product."""
    f1, f2 = fmaps
    out = np.asarray(corr81_xla(f1, f2))
    assert out.shape == (2, 12, 16, 81)
    # spot-check the zero-displacement tap (k=40) and one shifted tap
    np.testing.assert_allclose(
        out[..., 40], np.mean(np.asarray(f1) * np.asarray(f2), -1), rtol=1e-5
    )
    dy, dx = 1, -2  # k = (1+4)*9 + (-2+4) = 47
    f2p = np.pad(np.asarray(f2), ((0, 0), (4, 4), (4, 4), (0, 0)))
    shifted = f2p[:, 4 + dy : 16 + dy, 4 + dx : 20 + dx, :]
    np.testing.assert_allclose(out[..., 47], np.mean(np.asarray(f1) * shifted, -1),
                               rtol=1e-5, atol=1e-6)


def test_corr81_pallas_matches_xla(fmaps):
    """The tile kernel (interpreter mode on CPU) equals the XLA formulation."""
    f1, f2 = fmaps
    ref = np.asarray(corr81_xla(f1, f2))
    out = np.asarray(corr81_pallas(f1, f2, interpret=True))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_corr81_dispatcher(fmaps):
    f1, f2 = fmaps
    ref = np.asarray(corr81(f1, f2, "xla"))
    out = np.asarray(corr81(f1, f2, "pallas_interpret"))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    with pytest.raises(ValueError):
        corr81(f1, f2, "cuda")


def test_corr81_pallas_bf16(fmaps):
    """bf16 features: both kernels accumulate fp32 in-kernel and store bf16 —
    must match the XLA formulation's bf16 output within bf16 rounding."""
    from video_features_tpu.ops.pallas_corr import corr81_pallas_tiled

    f1, f2 = (x.astype(jnp.bfloat16) for x in fmaps)
    ref = np.asarray(corr81_xla(f1, f2), dtype=np.float32)
    out = np.asarray(corr81_pallas(f1, f2, interpret=True))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.float32(out), ref, rtol=0.02, atol=0.02)
    big1 = jnp.concatenate([f1, f1], axis=1)  # 24 rows: forces the tiled path
    big2 = jnp.concatenate([f2, f2], axis=1)
    ref_big = np.asarray(corr81_xla(big1, big2), dtype=np.float32)
    out_big = np.asarray(corr81_pallas_tiled(big1, big2, interpret=True))
    assert out_big.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.float32(out_big), ref_big, rtol=0.02, atol=0.02)


def test_corr81_auto_dispatch(fmaps):
    """'auto' must be accepted and equal xla on CPU (non-TPU falls back)."""
    f1, f2 = fmaps
    np.testing.assert_array_equal(
        np.asarray(corr81(f1, f2, "auto")), np.asarray(corr81(f1, f2, "xla")))


def test_warp_corr81_fused_matches_composition(rng):
    """Fused warp+corr kernel (interpreter) == warp_backward → corr81_xla,
    including out-of-bounds flow (partial-tap zeroing) and a non-multiple-of-
    16 geometry (tile padding)."""
    from video_features_tpu.ops.pallas_corr import warp_corr81, warp_corr81_pallas
    from video_features_tpu.ops.warp import warp_backward

    for h, w in ((24, 40), (20, 28)):
        f1 = jnp.asarray(rng.normal(size=(2, h, w, 16)).astype(np.float32))
        f2 = jnp.asarray(rng.normal(size=(2, h, w, 16)).astype(np.float32))
        # flows spanning in-bounds, fractional, and far out-of-bounds targets
        flow = jnp.asarray(rng.uniform(-10, 10, (2, h, w, 2)).astype(np.float32))
        ref = np.asarray(corr81_xla(f1, warp_backward(f2, flow)))
        out = np.asarray(warp_corr81_pallas(f1, f2, flow, interpret=True))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)
        # dispatcher: xla impl is the composition; interpret impl the kernel
        np.testing.assert_allclose(
            np.asarray(warp_corr81(f1, f2, flow, "xla")), ref, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(warp_corr81(f1, f2, flow, "pallas_interpret")), ref,
            rtol=1e-4, atol=1e-5)


def test_warp_corr81_fused_bf16(rng):
    """bf16 features through the fused kernel: fp32 accumulation in-kernel,
    bf16 store — matches the bf16 composition within bf16 rounding."""
    from video_features_tpu.ops.pallas_corr import warp_corr81_pallas
    from video_features_tpu.ops.warp import warp_backward

    f1 = jnp.asarray(rng.normal(size=(1, 24, 24, 16))).astype(jnp.bfloat16)
    f2 = jnp.asarray(rng.normal(size=(1, 24, 24, 16))).astype(jnp.bfloat16)
    flow = jnp.asarray(rng.uniform(-6, 6, (1, 24, 24, 2)).astype(np.float32))
    ref = np.asarray(corr81_xla(f1, warp_backward(f2, flow)), dtype=np.float32)
    out = np.asarray(warp_corr81_pallas(f1, f2, flow, interpret=True))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.float32(out), ref, rtol=0.03, atol=0.03)


def test_warp_corr81_zero_flow_is_plain_corr(rng):
    """Zero flow degenerates to corr81 of (f1, f2) away from the border (the
    warp zeroes nothing in-bounds; border pixels differ only where corr taps
    read beyond the image, which both paths zero-pad identically)."""
    from video_features_tpu.ops.pallas_corr import warp_corr81_pallas

    f1 = jnp.asarray(rng.normal(size=(1, 32, 32, 8)).astype(np.float32))
    f2 = jnp.asarray(rng.normal(size=(1, 32, 32, 8)).astype(np.float32))
    flow = jnp.zeros((1, 32, 32, 2), jnp.float32)
    ref = np.asarray(corr81_xla(f1, f2))
    out = np.asarray(warp_corr81_pallas(f1, f2, flow, interpret=True))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_pwc_forward_pallas_corr_matches(rng):
    """End-to-end PWC flow with the Pallas cost volume == XLA cost volume."""
    from video_features_tpu.models.pwc import pwc_forward, pwc_init_params

    params = pwc_init_params(seed=0)
    im1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32))
    im2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 64, 3)).astype(np.float32))
    ref = np.asarray(pwc_forward(params, im1, im2, corr_impl="xla"))
    # interpret-mode Pallas via monkeypatched dispatch is unwieldy inside jit;
    # on CPU the pallas impl falls back through corr81's VMEM check only on
    # size, so call the interpreter variant explicitly through corr_impl
    out = np.asarray(pwc_forward(params, im1, im2, corr_impl="pallas_interpret"))
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_raft_on_demand_lookup_matches_volume(rng):
    """⟨f1, pool(f2)⟩ on-demand lookup == lookup of the pooled volume."""
    from video_features_tpu.models.raft import (
        _build_f2_pyramid,
        _build_pyramid,
        _lookup,
        _lookup_on_demand,
    )

    f1 = jnp.asarray(rng.normal(size=(2, 16, 16, 32)).astype(np.float32))
    f2 = jnp.asarray(rng.normal(size=(2, 16, 16, 32)).astype(np.float32))
    coords = jnp.asarray(
        rng.uniform(-2, 18, (2, 16, 16, 2)).astype(np.float32)  # incl. out-of-bounds
    )
    ref = np.asarray(_lookup(_build_pyramid(f1, f2), coords))
    out = np.asarray(_lookup_on_demand(f1, _build_f2_pyramid(f2), coords))
    assert out.shape == ref.shape == (2, 16, 16, 324)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_raft_forward_on_demand_matches_volume(rng):
    """Full RAFT forward, both correlation implementations (4 iterations —
    random-weight chaos grows with depth)."""
    from video_features_tpu.models.raft import raft_forward, raft_init_params

    params = raft_init_params(seed=0)
    im1 = jnp.asarray(rng.uniform(0, 255, (1, 64, 72, 3)).astype(np.float32))
    im2 = jnp.asarray(rng.uniform(0, 255, (1, 64, 72, 3)).astype(np.float32))
    ref = np.asarray(raft_forward(params, im1, im2, iters=4, corr_impl="volume"))
    out = np.asarray(raft_forward(params, im1, im2, iters=4, corr_impl="on_demand"))
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)
