"""Co-resident models on one mesh (--serve_models): two-model daemon byte
parity vs single-model runs, unknown/malformed-model rejection records,
global cross-model tenant fairness + EDF preemption, the scaled staging-ring
geometry cap, per-model stats, cache fingerprint isolation, breaker
isolation across models, and the packer's (model, geometry) round-robin
dispatch — through the same lightweight jitted extractors as
tests/test_packer.py (shared program shapes, trivial CPU compiles)."""

import glob
import json
import os
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from test_packer import ToyPacked, _write_video

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.vftlint.locks import LockOrderWatch  # noqa: E402
from tools.vftlint.rules.lock_order import LOCK_ORDER  # noqa: E402

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import derive_model_config
from video_features_tpu.parallel.packer import CorpusPacker, PackSpec
from video_features_tpu.parallel.pipeline import HostStagingRing
from video_features_tpu.reliability import reset_faults
from video_features_tpu.serve import (
    ExtractionService,
    RequestQueue,
    RequestRejected,
    SpoolWatcher,
)
from video_features_tpu.serve.request import ServiceRequest

PRIMARY = "resnet50"  # ToyPacked's model name
SECOND = "r21d_rgb"   # ToyPackedB's model name (toy stands in for the real net)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Six decodable tiny videos of mixed lengths."""
    d = tmp_path_factory.mktemp("mm_corpus")
    return [_write_video(d / f"vid{i}.mp4", n)
            for i, n in enumerate((3, 5, 9, 2, 4, 7))]


class ToyPackedB(ToyPacked):
    """A second co-residable toy model: different feature function AND a
    different batch size, so its (model, geometry) buckets never share a
    program with ToyPacked's."""

    BATCH = 3

    def __init__(self, cfg):
        super().__init__(cfg)

        def fwd(params, frames_u8):
            x = frames_u8.astype(jnp.float32)
            return jnp.stack([x.min(axis=(1, 2, 3)), x.std(axis=(1, 2, 3)),
                              x.mean(axis=(1, 2, 3))], axis=-1)

        self._step = self.runner.jit(fwd)

    def extract(self, video_path):
        feats = super().extract(video_path)
        return feats  # shape differs via _step; (n, 3) rows

    def pack_spec(self):
        spec = super().pack_spec()
        spec.empty_row_shape = (3,)
        return spec


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    kw.setdefault("feature_type", PRIMARY)
    if kw.get("serve"):
        kw.setdefault("spool_dir", str(tmp_path / sub / "spool"))
        kw.setdefault("idle_flush_sec", 0.0)
        os.makedirs(kw["spool_dir"], exist_ok=True)
    return ExtractionConfig(
        on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


# runtime LOCK_ORDER cross-check: every multi-model daemon test runs with
# the named locks wrapped by vftlint's LockOrderWatch (see tests/
# test_service.py — the multi-model layer shares the same lock topology,
# and a violation only its traffic pattern provokes must fail HERE)
_WATCHES = []


@pytest.fixture(autouse=True)
def _lock_order_watched():
    _WATCHES.clear()
    yield
    for watch in _WATCHES:
        watch.assert_clean()
    _WATCHES.clear()


def _service(tmp_path, sub, **kw):
    kw.setdefault("serve_models", (SECOND,))
    cfg = _cfg(tmp_path, sub, serve=True, **kw)
    ex = ToyPacked(cfg)

    def factory(model):
        assert model == SECOND
        return ToyPackedB(derive_model_config(cfg, model))

    svc = ExtractionService(ex, poll_interval=0.001, factory=factory)
    _WATCHES.append(LockOrderWatch(LOCK_ORDER).instrument_service(svc))
    return svc


def _outputs(tmp_path, sub, model):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(str(tmp_path / sub / model / "*.npy"))}


def _assert_bytes_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].tobytes() == b[k].tobytes(), k


# ---- acceptance: two-model daemon == two single-model runs -----------------


def test_two_model_daemon_matches_single_model_runs(tmp_path, corpus):
    vids_a, vids_b = corpus[:3], corpus[3:]
    ex_a = ToyPacked(_cfg(tmp_path, "batch"))
    assert ex_a.run(vids_a) == 3
    ex_b = ToyPackedB(derive_model_config(_cfg(tmp_path, "batch"), SECOND))
    assert ex_b.run(vids_b) == 3

    svc = _service(tmp_path, "serve")
    ra = svc.submit({"tenant": "alice", "videos": vids_a})  # default model
    rb = svc.submit({"tenant": "bob", "videos": vids_b,
                     "feature_type": SECOND})
    assert ra.feature_type == PRIMARY  # admission resolved the default
    assert rb.feature_type == SECOND
    svc.request_drain()
    assert svc.run() == 0
    assert ra.state == "done" and rb.state == "done"
    _assert_bytes_equal(_outputs(tmp_path, "serve", PRIMARY),
                        _outputs(tmp_path, "batch", PRIMARY))
    _assert_bytes_equal(_outputs(tmp_path, "serve", SECOND),
                        _outputs(tmp_path, "batch", SECOND))
    # result records carry the model; per-model manifests are separate
    for r, model, vids in ((ra, PRIMARY, vids_a), (rb, SECOND, vids_b)):
        path = os.path.join(svc.notify_dir, f"{r.request_id}.result.json")
        with open(path) as f:
            record = json.load(f)
        assert record["feature_type"] == model
        assert sorted(record["done"]) == sorted(
            os.path.abspath(v) for v in vids)
    # the shared packer dispatched BOTH models' buckets, scoped by name
    stats = svc.packer.model_stats()
    assert set(stats) == {PRIMARY, SECOND}
    assert all(s["dispatched_slots"] > 0 for s in stats.values())


def test_shared_mesh_staging_and_writer_across_models(tmp_path, corpus):
    svc = _service(tmp_path, "shared")
    r = svc.submit({"videos": corpus[:1]})
    rb = svc.submit({"videos": corpus[3:4], "feature_type": SECOND})
    for _ in range(400):
        svc.step()
        if r.complete and rb.complete:
            break
    assert r.state == "done" and rb.state == "done"
    ex2 = svc.sessions.peek_extractor(SECOND)
    assert ex2 is not None  # lazily constructed on first traffic
    assert ex2.runner is svc.ex.runner  # one mesh
    assert ex2._staging is svc.ex._staging  # one staging ring
    assert ex2.clock is svc.ex.clock  # one service clock
    assert ex2._writer is svc.ex._writer  # one async writer
    # the ring's geometry cap scales with the loaded model count
    assert (svc.ex._staging._max_geometries
            == HostStagingRing.DEFAULT_MAX_GEOMETRIES * 2)
    assert svc.packer._staging is svc.ex._staging
    svc.request_drain()
    assert svc.run() == 0


def test_lazy_construction_skips_untrafficked_models(tmp_path, corpus):
    svc = _service(tmp_path, "lazy")
    r = svc.submit({"videos": corpus[:1]})  # primary-only traffic
    svc.request_drain()
    assert svc.run() == 0 and r.state == "done"
    assert svc.sessions.peek_extractor(SECOND) is None


# ---- rejection: unknown / malformed models ---------------------------------


def test_unknown_model_rejected_cleanly(tmp_path, corpus):
    svc = _service(tmp_path, "reject")
    with pytest.raises(RequestRejected, match="not loaded"):
        svc.submit({"videos": corpus[:1], "feature_type": "vggish"})
    with pytest.raises(RequestRejected, match="non-empty string"):
        svc.submit({"videos": corpus[:1], "feature_type": 7})
    with pytest.raises(RequestRejected, match="non-empty string"):
        svc.submit({"videos": corpus[:1], "feature_type": ""})
    # spool path: the daemon records the rejection where the submitter looks
    spool = svc.cfg.spool_dir
    with open(os.path.join(spool, "bad_model.json"), "w") as f:
        json.dump({"videos": corpus[:1], "feature_type": "vggish"}, f)
    watcher = SpoolWatcher(spool, svc)
    assert watcher.scan_once() == 1
    assert os.path.exists(os.path.join(spool, "bad_model.json.rejected"))
    result = os.path.join(svc.notify_dir, "bad_model.result.json")
    with open(result) as f:
        record = json.load(f)
    assert record["state"] == "rejected" and "not loaded" in record["reason"]
    # the daemon keeps serving loaded models after the rejection
    r = svc.submit({"videos": corpus[:1]})
    svc.request_drain()
    assert svc.run() == 0 and r.state == "done"


def test_model_construction_failure_fails_job_not_daemon(tmp_path, corpus):
    """A co-loaded model whose lazy construction dies (missing weights,
    bad derived config) fails ITS videos cleanly — classified in the
    request record and the model's failure manifest, exit code 1 — while
    the primary model keeps serving."""
    from video_features_tpu.reliability import load_failures

    cfg = _cfg(tmp_path, "ctorfail", serve=True, serve_models=(SECOND,),
               retries=0)

    def broken_factory(model):
        raise RuntimeError("checkpoint store unreachable")

    svc = ExtractionService(ToyPacked(cfg), poll_interval=0.001,
                            factory=broken_factory)
    rb = svc.submit({"videos": corpus[3:4], "feature_type": SECOND})
    ra = svc.submit({"videos": corpus[:1]})
    svc.request_drain()
    assert svc.run() == 1  # the construction failure keeps the exit honest
    assert ra.state == "done"
    assert rb.state == "failed"
    assert "checkpoint store unreachable" in rb.failed[0]["message"]
    # manifested under the FAILED model's own output tree
    failures = load_failures(os.path.join(str(tmp_path / "ctorfail"), SECOND))
    assert set(failures) == {os.path.abspath(corpus[3])}


def test_inflight_path_resubmission_rejected_across_models(tmp_path, corpus):
    """A popped-but-unfinished video (rows/writes pending) is invisible to
    the scheduler's queued-duplicate check; admission must still reject a
    resubmission — same or another model — or the second begin() would
    discard the first attempt's in-flight assembly."""
    svc = _service(tmp_path, "inflight")
    r = svc.submit({"videos": corpus[:1]})
    # simulate the popped-but-pending window: the job is in _jobs, gone
    # from the scheduler queue
    job = svc.queue.next_job()
    svc._jobs[job.path] = job
    with pytest.raises(RequestRejected, match="in flight"):
        svc.submit({"videos": corpus[:1], "feature_type": SECOND,
                    "request_id": "dup"})
    with pytest.raises(RequestRejected, match="in flight"):
        svc.submit({"videos": corpus[:1], "request_id": "dup2"})
    # release the window: the path completes normally afterwards
    svc.queue.requeue(job)
    del svc._jobs[job.path]
    svc.request_drain()
    assert svc.run() == 0 and r.state == "done"


def test_single_model_daemon_rejects_other_models(tmp_path, corpus):
    cfg = _cfg(tmp_path, "single", serve=True)
    svc = ExtractionService(ToyPacked(cfg), poll_interval=0.001)
    with pytest.raises(RequestRejected, match="not loaded"):
        svc.submit({"videos": corpus[:1], "feature_type": SECOND})
    svc.request_drain()
    assert svc.run() == 0
    svc.close()


# ---- global fairness and EDF across models ---------------------------------


def _req(tenant, videos, feature_type=None, deadline=None):
    return ServiceRequest(f"r-{tenant}-{len(videos)}", tenant, tuple(videos),
                          deadline=deadline, feature_type=feature_type)


def test_fairness_is_global_across_models():
    """Equal-weight tenants on DIFFERENT models alternate pops — fairness
    never silos per model."""
    q = RequestQueue()
    q.submit(_req("alice", [f"/a{i}" for i in range(4)], feature_type="m_a"))
    q.submit(_req("bob", [f"/b{i}" for i in range(4)], feature_type="m_b"))
    order = [q.next_job().feature_type for _ in range(8)]
    assert order[:2] in (["m_a", "m_b"], ["m_b", "m_a"])
    assert order.count("m_a") == order.count("m_b") == 4
    # strict alternation under equal weights
    assert all(order[i] != order[i + 1] for i in range(7))


def test_edf_urgent_model_b_preempts_queued_model_a():
    import time as _time

    q = RequestQueue()
    q.submit(_req("slow", ["/a0", "/a1", "/a2"], feature_type="m_a"))
    q.submit(_req("urgent", ["/b0"], feature_type="m_b",
                  deadline=_time.time() + 5))
    job = q.next_job()
    assert job.feature_type == "m_b" and job.path == "/b0"


def test_service_interleaves_completions_across_models(tmp_path, corpus):
    """Two equal-weight tenants on two models: the daemon's ingest order
    alternates models (the scheduler is model-agnostic), so neither model's
    queue monopolizes the mesh."""
    svc = _service(tmp_path, "fair")
    ingests = []
    orig = svc.session.ingest

    def spy(path, model, retries=None):
        ingests.append(model)
        return orig(path, model, retries=retries)

    svc.session.ingest = spy
    ra = svc.submit({"tenant": "alice", "videos": corpus[:3]})
    rb = svc.submit({"tenant": "bob", "videos": corpus[3:],
                     "feature_type": SECOND})
    svc.request_drain()
    assert svc.run() == 0
    assert ra.state == "done" and rb.state == "done"
    assert len(ingests) == 6
    # stride scheduling at equal weights: strict model alternation
    assert all(ingests[i] != ingests[i + 1] for i in range(5))


def test_breaker_isolation_across_models(tmp_path, corpus, monkeypatch):
    """alice's poisoned model-A videos trip HER breaker; bob's model-B
    traffic keeps completing on the same daemon."""
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid0")
    svc = _service(tmp_path, "poison", tenant_max_failures=0)
    ra = svc.submit({"tenant": "alice", "videos": corpus[:2]})
    rb = svc.submit({"tenant": "bob", "videos": corpus[3:],
                     "feature_type": SECOND})
    svc.request_drain()
    assert svc.run() == 1
    assert ra.state in ("failed", "partial")
    assert rb.state == "done"
    assert svc.breaker.tripped("alice") and not svc.breaker.tripped("bob")


# ---- feature cache composition ---------------------------------------------


def test_cache_fingerprints_isolate_models(tmp_path, corpus):
    """The same video bytes served under both models produce two distinct
    cache entries (the fingerprint includes the model config) and replay as
    hits only within their own model."""
    cache_dir = str(tmp_path / "cache")
    svc = _service(tmp_path, "cachemm", cache_dir=cache_dir)
    vid = corpus[0]
    ra = svc.submit({"videos": [vid], "request_id": "a1"})
    for _ in range(400):
        svc.step()
        if ra.complete:
            break
    assert ra.state == "done" and ra.cache_hits == 0
    # same bytes, other model: a MISS (different fingerprint), fresh extract
    rb = svc.submit({"videos": [vid], "feature_type": SECOND,
                     "request_id": "b1"})
    for _ in range(400):
        svc.step()
        if rb.complete:
            break
    assert rb.state == "done" and rb.cache_hits == 0
    # replay under the primary model: a pure hit now
    ra2 = svc.submit({"videos": [vid], "request_id": "a2"})
    for _ in range(400):
        svc.step()
        if ra2.complete:
            break
    assert ra2.state == "done" and ra2.cache_hits == 1
    svc.request_drain()
    assert svc.run() == 0
    # the two models' outputs differ (different feature functions) and each
    # landed in its own subtree
    a = _outputs(tmp_path, "cachemm", PRIMARY)
    b = _outputs(tmp_path, "cachemm", SECOND)
    stem = os.path.basename(vid).replace(".mp4", "")
    assert a[f"{stem}_feat.npy"].shape[1] == 2
    assert b[f"{stem}_feat.npy"].shape[1] == 3


# ---- long-run residue (multi-model soak) -----------------------------------


def test_multimodel_soak_no_residue(tmp_path, corpus):
    svc = _service(tmp_path, "soak")
    for i in range(3):
        ra = svc.submit({"tenant": "a", "videos": corpus[:2],
                         "request_id": f"sa{i}"})
        rb = svc.submit({"tenant": "b", "videos": corpus[3:5],
                         "feature_type": SECOND, "request_id": f"sb{i}"})
        for _ in range(800):
            svc.step()
            if ra.complete and rb.complete:
                break
        assert ra.state == "done" and rb.state == "done"
        packer = svc.packer
        assert not packer.has_pending()
        assert (len(packer.video_clips), len(packer._video_keys),
                len(packer._video_model), len(packer._finished),
                len(svc._requests), len(svc._jobs),
                svc.sessions.pending_writes(),
                len(svc.sessions._ex_for_path)) == (0,) * 8
    svc.close()


# ---- packer engine: (model, geometry) keys + round-robin dispatch ----------


def _spec(batch, tag):
    calls = []

    def step(batch_arr):
        calls.append(tag)
        return batch_arr.sum(axis=tuple(range(1, batch_arr.ndim)),
                             keepdims=True)[:, 0]

    return PackSpec(batch_size=batch, empty_row_shape=(1,), open_clips=None,
                    step=step, finalize=None), calls


def test_packer_multi_spec_batch_sizes_and_stats():
    spec_a, calls_a = _spec(2, "a")
    spec_b, calls_b = _spec(3, "b")
    packer = CorpusPacker()
    packer.register_model("a", spec_a)
    packer.register_model("b", spec_b)
    packer.begin("va", {}, model="a")
    packer.begin("vb", {}, model="b")
    for _ in range(2):
        packer.add("va", np.ones((2, 2), np.float32))  # fills a's batch of 2
    for _ in range(3):
        packer.add("vb", np.ones((2, 2), np.float32))  # fills b's batch of 3
    assert calls_a == ["a"] and calls_b == ["b"]
    packer.finish("va")
    packer.finish("vb")
    packer.flush()
    done = {a.video: a for a in (packer.pop_completed(model="a")
                                 + packer.pop_completed(model="b"))}
    assert set(done) == {"va", "vb"}
    # same geometry, distinct (model, geometry) buckets with scoped names
    stats = packer.bucket_stats()
    assert set(stats) == {"a:2x2", "b:2x2"}
    assert stats["a:2x2"]["dispatched_slots"] == 2
    assert stats["b:2x2"]["dispatched_slots"] == 3
    per_model = packer.model_stats()
    assert per_model["a"]["occupancy"] == 1.0
    assert per_model["b"]["real_slots"] == 3


def test_packer_pop_completed_scopes_by_model():
    spec_a, _ = _spec(4, "a")
    spec_b, _ = _spec(4, "b")
    packer = CorpusPacker()
    packer.register_model("a", spec_a)
    packer.register_model("b", spec_b)
    for name, model in (("va", "a"), ("vb", "b")):
        packer.begin(name, {}, model=model)
        packer.add(name, np.ones((2,), np.float32))
        packer.finish(name)
    packer.flush()
    assert [a.video for a in packer.pop_completed(model="a")] == ["va"]
    assert [a.video for a in packer.pop_completed(model="b")] == ["vb"]


def test_packer_flush_round_robins_across_models():
    """Model a holds ready batches in TWO geometry buckets, model b in one:
    the corpus flush serves one batch per model per round (a, b, a) instead
    of draining a's whole backlog before b's ready batch dispatches."""
    order = []

    def step_for(tag):
        def step(batch_arr):
            order.append(tag)
            return batch_arr.sum(axis=tuple(range(1, batch_arr.ndim)),
                                 keepdims=True)[:, 0]
        return step

    spec_a = PackSpec(batch_size=4, empty_row_shape=(1,), open_clips=None,
                      step=step_for("a"), finalize=None)
    spec_b = PackSpec(batch_size=4, empty_row_shape=(1,), open_clips=None,
                      step=step_for("b"), finalize=None)
    packer = CorpusPacker()
    packer.register_model("a", spec_a)
    packer.register_model("b", spec_b)
    packer.begin("va", {}, model="a")
    packer.add("va", np.ones((2, 2), np.float32))  # a bucket 1 (partial)
    packer.add("va", np.ones((3, 3), np.float32))  # a bucket 2 (partial)
    packer.begin("vb", {}, model="b")
    packer.add("vb", np.ones((2, 2), np.float32))  # b bucket (partial)
    packer.finish("va")
    packer.finish("vb")
    packer.flush()
    assert order == ["a", "b", "a"]
    assert {a.video for a in (packer.pop_completed(model="a")
                              + packer.pop_completed(model="b"))} == {
        "va", "vb"}


def test_packer_register_unknown_model_begin_raises():
    spec_a, _ = _spec(2, "a")
    packer = CorpusPacker(spec_a)
    with pytest.raises(KeyError, match="not registered"):
        packer.begin("v", {}, model="nope")


# ---- staging ring geometry cap (satellite unit test) -----------------------


def test_staging_ring_geometry_cap_is_constructor_scaled():
    ring = HostStagingRing(depth=1, max_geometries=2)
    ring.stage([np.ones((2, 2), np.uint8)])
    ring.stage([np.ones((3, 3), np.uint8)])
    assert ring.evicted_geometries == 0
    ring.stage([np.ones((4, 4), np.uint8)])  # third geometry: evicts LRU
    assert ring.evicted_geometries == 1
    big = HostStagingRing(depth=1, max_geometries=4)
    for n in (2, 3, 4, 5):
        big.stage([np.ones((n, n), np.uint8)])
    assert big.evicted_geometries == 0
    assert HostStagingRing.DEFAULT_MAX_GEOMETRIES == 8


# ---- config/CLI surface ----------------------------------------------------


def test_serve_models_config_validation(tmp_path):
    cfg = _cfg(tmp_path, "vcfg", serve=True, serve_models=(SECOND,))
    cfg.validate()
    with pytest.raises(ValueError, match="needs --serve"):
        _cfg(tmp_path, "vcfg2", serve_models=(SECOND,)).validate()
    with pytest.raises(ValueError, match="unknown serve_models"):
        cfg.replace(serve_models=("nope",)).validate()


def test_serve_models_cli_round_trip(tmp_path):
    from video_features_tpu.cli import parse_args

    spool = str(tmp_path / "spool")
    os.makedirs(spool, exist_ok=True)
    cfg = parse_args([
        "--feature_type", PRIMARY, "--on_extraction", "save_numpy",
        "--serve", "--spool_dir", spool,
        "--serve_models", SECOND, "vggish"])
    assert cfg.serve_models == (SECOND, "vggish")


def test_derive_model_config_resets_per_model_defaults(tmp_path):
    from video_features_tpu.config import resolve_model_defaults

    cfg = _cfg(tmp_path, "derive", feature_type="i3d", serve=True,
               serve_models=(SECOND,), extraction_fps=5, side_size=300)
    resolved = resolve_model_defaults(cfg)
    assert resolved.stack_size == 64  # i3d's default
    derived = resolve_model_defaults(
        derive_model_config(resolved, SECOND))
    assert derived.feature_type == SECOND
    assert derived.stack_size == 16  # r21d's own default, not i3d's 64
    # primary-only model-scoped flags do NOT leak: r21d would reject the
    # inherited extraction_fps outright at daemon startup
    assert derived.extraction_fps is None and derived.side_size is None
    derived.validate()


def test_primary_only_extraction_fps_does_not_block_co_model(tmp_path,
                                                             corpus):
    """--extraction_fps on the primary must not make the daemon refuse to
    start because a co-loaded r21d (which rejects the flag) inherits it."""
    svc = _service(tmp_path, "fpsleak", extraction_fps=5)
    assert svc.models == (PRIMARY, SECOND)
    svc.request_drain()
    assert svc.run() == 0


def test_decode_hints_never_construct_a_model(tmp_path, corpus):
    svc = _service(tmp_path, "hintlazy", decode_workers=2)
    assert svc.sessions.peek_extractor(SECOND) is None
    svc.sessions.schedule_decode(corpus[3], SECOND)  # hint for unbuilt model
    assert svc.sessions.peek_extractor(SECOND) is None  # still unbuilt
    svc.request_drain()
    assert svc.run() == 0
