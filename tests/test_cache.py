"""Content-addressed feature cache (--cache_dir, docs/caching.md): key
stability and fingerprint pinning (every config flag owns a keying decision),
CAS store round-trips / corrupt-entry quarantine / LRU eviction, cache-hit
semantics in both run loops (byte parity, ZERO device dispatches, done-
manifest entries so --resume composes), and the serving daemon's in-flight
coalescing (N identical submissions → one extraction, waiter requeue on
leader failure) — through the same lightweight jitted extractor as
tests/test_packer.py."""

import dataclasses
import glob
import os
import shutil

import numpy as np
import pytest

from test_packer import ToyPacked, _write_video

from video_features_tpu.cache import (
    EXECUTION_FIELDS,
    FINGERPRINT_FIELDS,
    FeatureCache,
    InflightCoalescer,
    cache_key,
    config_fingerprint,
    file_digest,
    fingerprint_digest,
)
from video_features_tpu.config import ExtractionConfig
from video_features_tpu.io.output import load_done_set
from video_features_tpu.reliability import load_failures, reset_faults
from video_features_tpu.serve import ExtractionService


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Four decodable tiny videos of mixed lengths (3, 5, 9, 2 frames)."""
    d = tmp_path_factory.mktemp("cache_corpus")
    return [_write_video(d / f"vid{i}.mp4", n)
            for i, n in enumerate((3, 5, 9, 2))]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    kw.setdefault("cache_dir", str(tmp_path / "cache"))
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


def _outputs(tmp_path, sub):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(str(tmp_path / sub / "resnet50" / "*.npy"))}


def _assert_bytes_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].dtype == b[k].dtype and a[k].shape == b[k].shape, k
        assert a[k].tobytes() == b[k].tobytes(), k


class Counting(ToyPacked):
    """ToyPacked with a jit-dispatch counter: every device-step invocation
    (per-video loop and packed loop share self._step) increments it, so
    'a cache hit costs zero device steps' is a checkable number."""

    def __init__(self, cfg):
        super().__init__(cfg)
        self.dispatches = 0
        inner = self._step

        def counted(params, frames):
            self.dispatches += 1
            return inner(params, frames)

        self._step = counted


# ---- keying: every flag owns a decision ------------------------------------


def test_every_config_field_has_a_keying_decision():
    """THE PIN: each ExtractionConfig field appears in exactly one of
    FINGERPRINT_FIELDS (feeds the cache key) or EXECUTION_FIELDS (declared
    numerics-neutral). Adding a flag without classifying it fails here —
    that is the point: an unclassified flag could silently serve features
    computed under different numerics."""
    fields = {f.name for f in dataclasses.fields(ExtractionConfig)}
    fp, ex = set(FINGERPRINT_FIELDS), set(EXECUTION_FIELDS)
    assert not fp & ex, f"fields classified twice: {sorted(fp & ex)}"
    assert fp | ex == fields, (
        f"unclassified: {sorted(fields - (fp | ex))}; "
        f"stale: {sorted((fp | ex) - fields)} — decide in cache/key.py")


def test_fingerprint_tracks_numeric_fields_and_ignores_execution_fields():
    base = ExtractionConfig(feature_type="resnet50")
    assert fingerprint_digest(base) == fingerprint_digest(base)  # stable
    assert (fingerprint_digest(base.replace(dtype="bfloat16"))
            != fingerprint_digest(base))
    assert (fingerprint_digest(base.replace(extraction_fps=5))
            != fingerprint_digest(base))
    # execution knobs reshuffle HOW, not WHAT: same key, cache still hits
    same = base.replace(batch_size=32, output_path="./elsewhere",
                        decode_workers=4, retries=7, async_writer=False)
    assert fingerprint_digest(same) == fingerprint_digest(base)


def test_flow_padding_knobs_collapse_for_non_flow_configs():
    """pack_corpus/pack_buckets/shape_bucket perturb numerics only where a
    flow net sees replicate-padded frames; RGB/audio parity is pinned
    byte-identical, so their fingerprints must SHARE entries across the
    packed and per-video loops."""
    rgb = ExtractionConfig(feature_type="resnet50")
    assert (fingerprint_digest(rgb.replace(pack_corpus=True, pack_buckets=2))
            == fingerprint_digest(rgb))
    flow = ExtractionConfig(feature_type="raft")
    assert (fingerprint_digest(flow.replace(pack_corpus=True))
            != fingerprint_digest(flow))


def test_default_i3d_resolves_like_explicit_two_stream():
    """Keying decisions see RESOLVED configs: streams=None means BOTH i3d
    streams, so (1) the raw and explicit spellings share one fingerprint,
    (2) the flow-padding knobs count (a merged-bucket packed run must not
    share entries with an unpacked one), and (3) the sandwich's flow-net
    checkpoint is part of the weights version — swapping raft/pwc weights
    invalidates default-i3d entries too."""
    from video_features_tpu.cache import weights_fingerprint

    raw = ExtractionConfig(feature_type="i3d")
    explicit = raw.replace(streams=("rgb", "flow"), stack_size=64,
                           step_size=64)
    assert fingerprint_digest(raw) == fingerprint_digest(explicit)
    assert (fingerprint_digest(raw.replace(pack_corpus=True))
            != fingerprint_digest(raw))  # flow stream runs by default
    assert "sintel" in weights_fingerprint(raw)  # pwc/raft checkpoint keyed
    rgb_only = raw.replace(streams=("rgb",))
    assert "sintel" not in weights_fingerprint(rgb_only)
    assert fingerprint_digest(rgb_only) != fingerprint_digest(raw)


def test_use_ffmpeg_resolves_to_unused_without_fps_resampling():
    base = ExtractionConfig(feature_type="resnet50")
    fp = config_fingerprint(base)
    assert fp["use_ffmpeg"] == "unused"
    assert (fingerprint_digest(base.replace(use_ffmpeg="never"))
            == fingerprint_digest(base))


def test_content_digest_is_content_addressed(tmp_path, corpus):
    dup = str(tmp_path / "dup.mp4")
    shutil.copyfile(corpus[0], dup)
    assert file_digest(dup) == file_digest(corpus[0])  # path-independent
    assert file_digest(corpus[0]) != file_digest(corpus[1])
    key = cache_key(file_digest(corpus[0]), "fp")
    assert key == cache_key(file_digest(dup), "fp")
    assert key != cache_key(file_digest(corpus[0]), "fp2")


def test_cache_max_bytes_requires_cache_dir(tmp_path):
    with pytest.raises(ValueError, match="cache_max_bytes"):
        _cfg(tmp_path, "v", cache_dir=None, cache_max_bytes=10).validate()
    with pytest.raises(ValueError, match="cache_max_bytes"):
        _cfg(tmp_path, "v", cache_max_bytes=0).validate()


# ---- CAS store -------------------------------------------------------------


def _entry_files(store):
    return [p for p in glob.glob(os.path.join(store.cache_dir, "*", "*.npz"))
            if os.path.dirname(p) != store.quarantine_dir]


def test_store_round_trip_preserves_dtype_shape_bytes(tmp_path):
    store = FeatureCache(str(tmp_path / "c"))
    feats = {"feat": np.arange(12, dtype=np.float32).reshape(3, 4),
             "timestamps_ms": np.array([0.0, 33.3, 66.6])}
    assert store.put("k" * 64, feats)
    got = store.get("k" * 64)
    _assert_bytes_equal(got, feats)
    assert store.get("m" * 64) is None  # miss
    assert store.stats()["hits"] == 1 and store.stats()["misses"] == 1


def test_store_survives_restart_and_skips_republish(tmp_path):
    store = FeatureCache(str(tmp_path / "c"))
    store.put("k" * 64, {"a": np.ones(3)})
    again = FeatureCache(str(tmp_path / "c"))  # fresh process, same dir
    assert again.stats()["entries"] == 1
    assert again.put("k" * 64, {"a": np.ones(3)})  # no-op republish
    assert again.stats()["puts"] == 0
    assert again.get("k" * 64) is not None


def test_corrupt_entry_quarantined_and_read_as_miss(tmp_path, capsys):
    store = FeatureCache(str(tmp_path / "c"))
    store.put("k" * 64, {"a": np.ones(8)})
    path = _entry_files(store)[0]
    with open(path, "r+b") as f:  # flip bytes mid-file: checksum mismatch
        f.seek(30)
        f.write(b"\xff\xff\xff\xff")
    assert store.get("k" * 64) is None
    assert store.quarantined == 1 and not _entry_files(store)
    q = glob.glob(os.path.join(store.cache_dir, "quarantine", "*.npz"))
    assert len(q) == 1  # kept for the operator, invisible to lookups
    assert "CacheError" in capsys.readouterr().err
    # the key is publishable again (extraction repairs the cache)
    assert store.put("k" * 64, {"a": np.ones(8)})
    assert store.get("k" * 64) is not None


def test_lru_eviction_honors_byte_cap_and_hit_recency(tmp_path):
    arr = {"a": np.zeros(64, np.float64)}  # ~1 KB serialized
    store = FeatureCache(str(tmp_path / "c"))
    store.put("a" * 64, arr)
    entry = store.stats()["total_bytes"]
    capped = FeatureCache(str(tmp_path / "cap"),
                          max_bytes=int(entry * 2.5))  # room for 2 entries
    def _age(key_char, mtime):  # deterministic ages, immune to fs clock
        d = os.path.join(capped.cache_dir, key_char * 2)
        for name in os.listdir(d):
            os.utime(os.path.join(d, name), (mtime, mtime))

    now = 1_000_000_000
    capped.put("a" * 64, arr)
    _age("a", now)
    capped.put("b" * 64, arr)
    _age("b", now + 10)
    assert capped.get("a" * 64) is not None  # refreshes a's recency (utime)
    capped.put("c" * 64, arr)  # over cap: LRU (b) evicted, a survived
    assert capped.evictions == 1
    assert capped.get("b" * 64) is None
    assert capped.get("a" * 64) is not None
    assert capped.get("c" * 64) is not None
    assert capped.stats()["total_bytes"] <= capped.max_bytes


def test_oversized_single_entry_degrades_to_pass_through(tmp_path):
    store = FeatureCache(str(tmp_path / "c"), max_bytes=16)
    assert store.put("a" * 64, {"a": np.zeros(64)})  # alone over the cap
    assert store.get("a" * 64) is not None  # never evicts the only entry


# ---- run-loop integration: zero device steps, manifests, resume ------------


def test_cache_hit_zero_dispatch_byte_parity_and_done_manifest(tmp_path,
                                                              corpus):
    """Acceptance: a hit produces byte-identical .npy output to a cold
    extraction with ZERO jit dispatches, and still writes done-manifest
    entries — pinned so --resume and the cache interact deterministically."""
    cold = Counting(_cfg(tmp_path, "cold"))
    assert cold.run(corpus) == len(corpus)
    assert cold.dispatches > 0
    assert cold._cache.stats()["puts"] == len(corpus)

    warm = Counting(_cfg(tmp_path, "warm"))
    assert warm.run(corpus) == len(corpus)
    assert warm.dispatches == 0  # the whole point of the subsystem
    assert warm._cache.stats()["hits"] == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "warm"), _outputs(tmp_path, "cold"))
    # cache-hit videos are marked done exactly like extracted ones …
    done = load_done_set(str(tmp_path / "warm" / "resnet50"))
    assert done == {os.path.abspath(p) for p in corpus}
    # … so a --resume rerun of the SAME tree skips them without a single
    # cache lookup (resume wins before the consult; deterministic layering)
    resumed = Counting(_cfg(tmp_path, "warm", resume=True))
    assert resumed.run(corpus) == len(corpus)
    assert resumed.dispatches == 0
    assert resumed._cache.stats()["hits"] == 0
    assert resumed._cache.stats()["misses"] == 0


def test_packed_loop_consults_cache_before_decode(tmp_path, corpus):
    cold = Counting(_cfg(tmp_path, "pcold", pack_corpus=True))
    assert cold.run(corpus) == len(corpus)
    warm = Counting(_cfg(tmp_path, "pwarm", pack_corpus=True))
    assert warm.run(corpus) == len(corpus)
    assert warm.dispatches == 0
    assert warm._pack_stats["dispatched_slots"] == 0  # nothing entered the packer
    _assert_bytes_equal(_outputs(tmp_path, "pwarm"),
                        _outputs(tmp_path, "pcold"))


def test_unhashable_video_is_a_plain_miss_with_classified_failure(tmp_path,
                                                                  corpus):
    missing = str(tmp_path / "gone.mp4")
    ex = Counting(_cfg(tmp_path, "miss"))
    assert ex.run([corpus[0], missing]) == 1
    assert ex._cache.stats()["misses"] == 1  # only the real video consulted
    assert os.path.abspath(missing) in load_failures(ex.output_dir)


def test_cache_disabled_is_the_default(tmp_path, corpus):
    ex = Counting(_cfg(tmp_path, "off", cache_dir=None))
    assert ex._cache is None
    assert ex.run(corpus[:1]) == 1
    assert ex.dispatches > 0


# ---- serving daemon: in-flight coalescing ----------------------------------


class TracingToy(ToyPacked):
    """Records every clip-stream open — the daemon-side 'extraction ran'
    probe (a coalesced waiter must never open its stream)."""

    def __init__(self, cfg):
        super().__init__(cfg)
        self.opened = []

    def pack_spec(self):
        spec = super().pack_spec()
        inner = spec.open_clips

        def open_clips(path):
            self.opened.append(os.path.abspath(path))
            return inner(path)

        spec.open_clips = open_clips
        return spec


def _service(tmp_path, sub, ex_cls=TracingToy, **kw):
    kw.setdefault("spool_dir", str(tmp_path / sub / "spool"))
    kw.setdefault("idle_flush_sec", 0.0)
    os.makedirs(kw["spool_dir"], exist_ok=True)
    ex = ex_cls(_cfg(tmp_path, sub, serve=True, **kw))
    return ExtractionService(ex, poll_interval=0.001)


def _dup_corpus(tmp_path, corpus):
    """alice.mp4 and bob.mp4: different paths, identical container bytes."""
    a = str(tmp_path / "alice.mp4")
    b = str(tmp_path / "bob.mp4")
    shutil.copyfile(corpus[1], a)
    shutil.copyfile(corpus[1], b)
    return a, b


def test_concurrent_identical_requests_extract_once_byte_parity(tmp_path,
                                                                corpus):
    """Acceptance: two tenants submit the same bytes concurrently → ONE
    extraction runs; both receive done result records and byte-identical
    outputs (each under its own stem)."""
    a, b = _dup_corpus(tmp_path, corpus)
    svc = _service(tmp_path, "co")
    ra = svc.submit({"tenant": "alice", "videos": [a]})
    rb = svc.submit({"tenant": "bob", "videos": [b]})
    svc.request_drain()
    assert svc.run() == 0
    assert ra.state == "done" and rb.state == "done"
    opened = svc.ex.opened
    assert len([p for p in opened if p in (os.path.abspath(a),
                                           os.path.abspath(b))]) == 1, opened
    assert svc._coalescer.coalesced == 1
    assert ra.cache_hits + rb.cache_hits == 1  # the waiter replayed as a hit
    outs = _outputs(tmp_path, "co")
    assert outs["alice_feat.npy"].tobytes() == outs["bob_feat.npy"].tobytes()
    # parity against a clean batch extraction of the same content
    ref = ToyPacked(_cfg(tmp_path, "co_ref"))
    assert ref.run([a]) == 1
    assert (outs["alice_feat.npy"].tobytes()
            == _outputs(tmp_path, "co_ref")["alice_feat.npy"].tobytes())


def test_leader_failure_requeues_waiters_not_their_breakers(tmp_path, corpus,
                                                            monkeypatch):
    """alice's extraction (the coalesce leader) fails permanently; bob's
    identical waiter must requeue, lead its own extraction, and succeed —
    with NOTHING charged to bob's breaker (failure attribution)."""
    a, b = _dup_corpus(tmp_path, corpus)
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:alice")
    svc = _service(tmp_path, "fail", tenant_max_failures=0)
    ra = svc.submit({"tenant": "alice", "videos": [a]})
    rb = svc.submit({"tenant": "bob", "videos": [b]})
    svc.request_drain()
    assert svc.run() == 1  # alice's terminal failure keeps the exit honest
    assert ra.state == "failed" and rb.state == "done"
    assert svc.breaker.tripped("alice") and not svc.breaker.tripped("bob")
    # bob led his own extraction after alice's failed
    assert os.path.abspath(b) in svc.ex.opened
    assert rb.cache_hits == 0
    assert _outputs(tmp_path, "fail")["bob_feat.npy"].size > 0


def test_daemon_stats_expose_cache_and_bucket_occupancy(tmp_path, corpus):
    svc = _service(tmp_path, "stats")
    r = svc.submit({"videos": corpus[:2]})
    for _ in range(300):
        svc.step()
        if r.complete:
            break
    stats = svc.stats()
    assert stats["cache"]["enabled"] is True
    assert stats["cache"]["misses"] == 2 and "hit_rate" in stats["cache"]
    assert stats["cache"]["coalesced"] == 0
    assert "buckets" in stats["packing"]
    for bucket in stats["packing"]["buckets"].values():
        assert {"real_slots", "dispatched_slots",
                "occupancy", "stale_flushes"} <= set(bucket)
    # resubmit the same content under new paths: pure hits
    a, b = _dup_corpus(tmp_path, corpus)
    shutil.copyfile(corpus[0], a)  # a = content of corpus[0] (cached above)
    r2 = svc.submit({"videos": [a]})
    for _ in range(300):
        svc.step()
        if r2.complete:
            break
    assert r2.state == "done" and r2.cache_hits == 1
    assert svc.stats()["cache"]["hits"] == 1
    svc.close()


def test_coalescer_unit():
    c = InflightCoalescer()
    c.lead("k1", "/a")
    assert c.leader_of("k1") == "/a"
    assert c.wait("k1", "job-b") and c.wait("k1", "job-c")
    assert not c.wait("k2", "job-d")  # nothing in flight for k2
    assert c.waiting() == 2 and c.coalesced == 2
    assert c.finish("/a") == ["job-b", "job-c"]
    assert c.finish("/a") == []  # idempotent
    assert c.waiting() == 0 and c.leader_of("k1") is None
