"""Always-on extraction service (--serve): enqueue→output parity with the
batch CLI, tenant fairness under contention, poisoned-tenant breaker
isolation, drain/reload lifecycle, ingest transports (spool + socket), the
decode autoscaler, and the long-run memory bound — through the same
lightweight jitted extractor as tests/test_packer.py (shared program shape,
one trivial CPU compile)."""

import glob
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from test_packer import ToyPacked, _write_video

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.vftlint.locks import LockOrderWatch  # noqa: E402
from tools.vftlint.rules.lock_order import LOCK_ORDER  # noqa: E402

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.io.output import load_done_set
from video_features_tpu.reliability import (
    DeviceError,
    TenantBreaker,
    load_failures,
    reset_faults,
)
from video_features_tpu.serve import (
    DecodeAutoscaler,
    ExtractionService,
    RequestQueue,
    RequestRejected,
    SocketAPI,
    SpoolWatcher,
    parse_request,
    socket_request,
)
from video_features_tpu.serve.request import ServiceRequest


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Four decodable tiny videos of mixed lengths (3, 5, 9, 2 frames)."""
    d = tmp_path_factory.mktemp("serve_corpus")
    return [_write_video(d / f"vid{i}.mp4", n)
            for i, n in enumerate((3, 5, 9, 2))]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    if kw.get("serve"):
        kw.setdefault("spool_dir", str(tmp_path / sub / "spool"))
        kw.setdefault("idle_flush_sec", 0.0)
        os.makedirs(kw["spool_dir"], exist_ok=True)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


# every daemon constructed through _service runs under a LockOrderWatch:
# the named locks (service/queue/registry/clock/journal) are wrapped with
# the runtime twin of vftlint's lock-order rule, and the autouse fixture
# below asserts the declared LOCK_ORDER held for every acquisition the test
# actually performed — the static table and reality cannot drift silently
_WATCHES = []


@pytest.fixture(autouse=True)
def _lock_order_watched():
    _WATCHES.clear()
    yield
    for watch in _WATCHES:
        watch.assert_clean()
    _WATCHES.clear()


def _service(tmp_path, sub, **kw):
    ex = ToyPacked(_cfg(tmp_path, sub, serve=True, **kw))
    svc = ExtractionService(ex, poll_interval=0.001)
    _WATCHES.append(LockOrderWatch(LOCK_ORDER).instrument_service(svc))
    return svc


def _outputs(tmp_path, sub):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(str(tmp_path / sub / "resnet50" / "*.npy"))}


def _assert_bytes_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].tobytes() == b[k].tobytes(), k


def _result(svc, request_id):
    path = os.path.join(svc.notify_dir, f"{request_id}.result.json")
    with open(path) as f:
        return json.load(f)


# ---- acceptance: two-tenant daemon run == per-tenant batch runs ------------


def test_two_tenant_daemon_matches_per_tenant_batch_runs(tmp_path, corpus):
    ex_a = ToyPacked(_cfg(tmp_path, "batch_a"))
    assert ex_a.run(corpus[:2]) == 2
    ex_b = ToyPacked(_cfg(tmp_path, "batch_b"))
    assert ex_b.run(corpus[2:]) == 2

    svc = _service(tmp_path, "serve")
    ra = svc.submit({"tenant": "alice", "videos": corpus[:2]})
    rb = svc.submit({"tenant": "bob", "videos": corpus[2:]})
    svc.request_drain()
    assert svc.run() == 0
    assert ra.state == "done" and rb.state == "done"
    _assert_bytes_equal(
        _outputs(tmp_path, "serve"),
        {**_outputs(tmp_path, "batch_a"), **_outputs(tmp_path, "batch_b")})
    assert len(load_done_set(svc.ex.output_dir)) == len(corpus)
    for r in (ra, rb):
        record = _result(svc, r.request_id)
        assert record["state"] == "done"
        assert len(record["done"]) == 2 and record["failed"] == []


def test_lock_order_watch_sees_real_nesting(tmp_path, corpus):
    """Instrumentation sanity for the runtime LOCK_ORDER cross-check: a
    busy daemon run must actually exercise nested acquisitions (submit and
    step nest the queue lock under the service lock), every observed edge
    must run WITH the declared order, and no violation may be recorded.
    A watch that silently saw nothing would make the autouse teardown
    assertion vacuous — this test pins that it bites."""
    svc = _service(tmp_path, "watched")
    svc.submit({"tenant": "alice", "videos": corpus[:2]})
    svc.request_drain()
    assert svc.run() == 0
    watch = _WATCHES[-1]
    assert ("service", "queue") in watch.edges
    rank = {name: i for i, name in enumerate(LOCK_ORDER)}
    for outer, inner in watch.edges:
        assert rank[outer] < rank[inner], (outer, inner)
    assert watch.violations == []


def test_status_answers_during_result_publish_window(tmp_path, corpus):
    """Result records are written OUTSIDE the service lock; between a
    request leaving _requests and its record landing on disk, status() must
    answer from the in-memory record (never 'unknown request_id' for a
    request that just completed) and submit() must still reject the id."""
    svc = _service(tmp_path, "pubwin")
    r = svc.submit({"tenant": "a", "videos": [corpus[0]]})
    with svc._lock:
        finished = svc._finish_request_locked(r, force=True)
    # the publish-window state: popped from _requests, record not on disk
    st = svc.status(r.request_id)
    assert st["ok"] is True and st["state"] == "aborted"
    with pytest.raises(RequestRejected):
        svc.submit({"tenant": "a", "videos": [corpus[1]]},
                   request_id=r.request_id)
    svc._publish_result(finished)
    st = svc.status(r.request_id)  # now served from the disk record
    assert st["ok"] is True and st["state"] == "aborted"
    svc.close()


def test_idle_flush_completes_requests_without_drain(tmp_path, corpus):
    """With the queue idle and partial slot queues pending, the daemon
    pad-flushes after idle_flush_sec so the request completes NOW — requests
    must not wait for a future burst to fill their tail batch."""
    svc = _service(tmp_path, "idle")
    r = svc.submit({"tenant": "a", "videos": [corpus[0]]})  # 3 frames < batch 4
    for _ in range(50):
        svc.step()
        if r.complete:
            break
    assert r.state == "done"
    # queues stay live after the flush: a second request still packs
    r2 = svc.submit({"tenant": "a", "videos": [corpus[3]]})
    svc.request_drain()
    assert svc.run() == 0
    assert r2.state == "done"


# ---- poisoned-tenant isolation (acceptance) --------------------------------


def test_poisoned_tenant_trips_only_its_breaker(tmp_path, corpus, monkeypatch):
    """vid1 (alice) is poisoned: alice's breaker opens, her queued videos
    fail fast without decoding, her new submissions are rejected — while
    bob's request completes byte-identical to a clean batch run."""
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    svc = _service(tmp_path, "poison", tenant_max_failures=0)
    ra = svc.submit({"tenant": "alice", "videos": [corpus[1], corpus[0]]})
    rb = svc.submit({"tenant": "bob", "videos": corpus[2:]})
    svc.request_drain()
    assert svc.run() == 1  # alice's failures make the exit code honest
    assert rb.state == "done"
    assert ra.state == "failed"
    classes = {f["video"]: f["error_class"] for f in ra.failed}
    assert classes[os.path.abspath(corpus[1])] == "InjectedDeviceError"
    assert classes[os.path.abspath(corpus[0])] == "TenantBreakerOpen"
    assert svc.breaker.tripped("alice") and not svc.breaker.tripped("bob")
    # bob's outputs are byte-identical to his own batch run
    ex_b = ToyPacked(_cfg(tmp_path, "poison_batch"))
    assert ex_b.run(corpus[2:]) == 2
    got = {k: v for k, v in _outputs(tmp_path, "poison").items()
           if k.startswith(("vid2", "vid3"))}
    _assert_bytes_equal(got, _outputs(tmp_path, "poison_batch"))
    # every failure is manifested for --retry_failed-style reprocessing
    assert set(load_failures(svc.ex.output_dir)) == {
        os.path.abspath(corpus[1]), os.path.abspath(corpus[0])}


def test_open_breaker_rejects_submissions_until_reload(tmp_path, corpus,
                                                       monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    svc = _service(tmp_path, "breaker", tenant_max_failures=0)
    svc.submit({"tenant": "alice", "videos": [corpus[1]]})
    while svc.step():
        pass
    assert svc.breaker.tripped("alice")
    with pytest.raises(RequestRejected, match="breaker is open"):
        svc.submit({"tenant": "alice", "videos": [corpus[0]]})
    svc.reload()  # SIGHUP: operator fixed the inputs, let alice back in
    assert not svc.breaker.tripped("alice")
    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    r = svc.submit({"tenant": "alice", "videos": [corpus[0]]})
    svc.request_drain()
    assert svc.run() == 1  # vid1's terminal failure still counts
    assert r.state == "done"
    svc.close()


def test_transient_failure_requeues_through_the_scheduler(tmp_path, corpus,
                                                          monkeypatch,
                                                          capsys):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_transient:vid2:1")
    svc = _service(tmp_path, "transient", retries=2)
    r = svc.submit({"tenant": "a", "videos": corpus})
    svc.request_drain()
    assert svc.run() == 0
    assert r.state == "done"
    assert "re-enqueued" in capsys.readouterr().out
    assert load_failures(svc.ex.output_dir) == {}


def test_copacked_batch_failure_victims_requeue_not_breaker(tmp_path, corpus):
    """A transient device fault on ONE dispatched batch loses every
    co-resident video's rows; the daemon re-enqueues the victims through the
    scheduler (same retry budget) instead of failing them terminally — and
    an innocent tenant's breaker must not count a neighbour's batch fault."""
    calls = []

    class BatchPoison(ToyPacked):
        def pack_spec(self):
            spec = super().pack_spec()
            inner = spec.step

            def step(batch):
                calls.append(1)
                if len(calls) == 2:  # second dispatched batch, exactly once
                    raise DeviceError("injected transient device fault")
                return inner(batch)

            spec.step = step
            return spec

    cfg = _cfg(tmp_path, "victims", serve=True, retries=2,
               tenant_max_failures=0)
    svc = ExtractionService(BatchPoison(cfg), poll_interval=0.001)
    ra = svc.submit({"tenant": "alice", "videos": corpus[:2]})
    rb = svc.submit({"tenant": "bob", "videos": corpus[2:]})
    svc.request_drain()
    assert svc.run() == 0  # every victim recovered: no terminal failures
    assert ra.state == "done" and rb.state == "done"
    assert not svc.breaker.open_tenants()
    assert load_failures(svc.ex.output_dir) == {}
    ex_c = ToyPacked(_cfg(tmp_path, "victims_clean"))
    assert ex_c.run(corpus) == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "victims"),
                        _outputs(tmp_path, "victims_clean"))


# ---- scheduler: quotas, fairness, deadlines --------------------------------


def _req(tenant, videos, deadline=None):
    return ServiceRequest(f"r-{tenant}-{len(videos)}", tenant,
                          tuple(videos), deadline=deadline)


def test_weighted_fair_interleave_under_contention():
    q = RequestQueue(tenants={"tenants": {"alice": {"weight": 2.0}}})
    q.submit(_req("alice", [f"/a{i}" for i in range(6)]))
    q.submit(_req("bob", [f"/b{i}" for i in range(6)]))
    order = [q.next_job().request.tenant for _ in range(9)]
    # stride scheduling: alice (weight 2) gets two pops per bob's one
    assert order.count("alice") == 6 and order.count("bob") == 3


def test_uncontended_tenant_runs_at_full_speed_and_idle_banks_no_credit():
    q = RequestQueue()
    q.submit(_req("alice", ["/a0", "/a1", "/a2"]))
    assert [q.next_job().path for _ in range(3)] == ["/a0", "/a1", "/a2"]
    # alice ran alone for a while; bob waking now must not be starved by
    # her accumulated vtime, nor alice by bob's zero clock
    q.submit(_req("alice", ["/a3", "/a4"]))
    q.submit(_req("bob", ["/b0", "/b1"]))
    order = [q.next_job().request.tenant for _ in range(4)]
    assert sorted(order[:2]) == ["alice", "bob"]  # strict alternation


def test_deadline_wins_across_tenants():
    q = RequestQueue()
    q.submit(_req("slow", ["/s0", "/s1"]))
    q.submit(_req("urgent", ["/u0"], deadline=time.time() + 5))
    assert q.next_job().path == "/u0"


def test_quota_rejects_all_or_nothing():
    q = RequestQueue(default_quota=3)
    q.submit(_req("a", ["/1", "/2"]))
    with pytest.raises(RequestRejected, match="over quota"):
        q.submit(_req("a", ["/3", "/4"]))
    assert q.pending("a") == 2  # nothing from the rejected request queued
    q.submit(_req("a", ["/3"]))
    assert q.pending("a") == 3


def test_duplicate_inflight_path_rejected():
    q = RequestQueue()
    q.submit(_req("a", ["/x"]))
    with pytest.raises(RequestRejected, match="already queued"):
        q.submit(_req("b", ["/x"]))


def test_held_jobs_invisible_until_release_but_reserved():
    """The WAL ack barrier's scheduler half: hold=True assigns seqs and
    reserves quota/duplicate slots, but the serving loop cannot pop the
    jobs until release() — otherwise a pop-dispatch-crash could beat the
    admission record to disk and lose the request."""
    q = RequestQueue(default_quota=3)
    jobs = q.submit(_req("a", ["/1", "/2"]), hold=True)
    assert [j.seq for j in jobs] == [1, 2]
    assert q.next_job() is None  # not poppable yet
    assert q.peek_jobs(4) == []
    with pytest.raises(RequestRejected, match="already queued"):
        q.submit(_req("b", ["/1"]))  # reserved against duplicates
    with pytest.raises(RequestRejected, match="over quota"):
        q.submit(_req("a", ["/3", "/4"]))  # held jobs count toward quota
    q.release(jobs)
    assert q.pending("a") == 2
    assert [q.next_job().path for _ in range(2)] == ["/1", "/2"]
    q.submit(_req("a", ["/5", "/6", "/7"]))  # quota reservation released


def test_requeue_keeps_admission_order_and_drain_tenant_empties():
    q = RequestQueue()
    q.submit(_req("a", ["/1", "/2"]))
    job = q.next_job()
    q.submit(_req("a", ["/3"]))
    q.requeue(job)  # retry schedules ahead of the later submission
    assert [q.next_job().path for _ in range(3)] == ["/1", "/2", "/3"]
    q.submit(_req("a", ["/4", "/5"]))
    assert [j.path for j in q.drain_tenant("a")] == ["/4", "/5"]
    assert q.pending() == 0


def test_reload_configure_applies_new_weights_and_quotas():
    q = RequestQueue(default_quota=2)
    q.submit(_req("a", ["/1", "/2"]))
    q.configure({"default": {"quota": 8},
                 "tenants": {"a": {"weight": 3, "quota": 4}}})
    q.submit(_req("a", ["/3", "/4"]))  # over the old quota, under the new
    with pytest.raises(RequestRejected, match="over quota"):
        q.submit(_req("a", ["/5"]))
    with pytest.raises(ValueError, match="weight must be > 0"):
        q.configure({"tenants": {"a": {"weight": 0}}})


def test_bad_reload_config_leaves_previous_config_fully_intact():
    """A failed configure (zero weight, non-numeric quota, quota < 1) must
    not half-apply: the next pop and the next admission still run on the
    previous config."""
    q = RequestQueue(default_quota=2)
    q.submit(_req("a", ["/1", "/2"]))
    for bad in ({"default": {"weight": 2, "quota": None}},
                {"default": {"weight": 2, "quota": "lots"}},
                {"tenants": {"a": {"weight": 0}}},
                {"tenants": {"a": {"quota": 0}}},
                "not an object"):
        with pytest.raises(ValueError):
            q.configure(bad)
    with pytest.raises(RequestRejected, match="over quota"):
        q.submit(_req("a", ["/3"]))  # still the old quota of 2
    assert q.next_job().path == "/1"  # weighted pop still works (weight 1)


# ---- request parsing -------------------------------------------------------


def test_parse_request_validation():
    r = parse_request({"tenant": "t", "videos": ["/a"], "deadline_sec": 10})
    assert r.tenant == "t" and r.deadline > time.time()
    for bad in (["not an object"], {"videos": []}, {"videos": ["/a", "/a"]},
                {"videos": ["/a"], "deadline_sec": -1},
                {"videos": [1, 2]}, {"tenant": "", "videos": ["/a"]}):
        with pytest.raises(RequestRejected):
            parse_request(bad)
    assert parse_request({"videos": ["/a"]}).tenant == "default"


# ---- tenant breaker (unit) -------------------------------------------------


def test_tenant_breaker_threshold_and_reset():
    b = TenantBreaker(max_failures=1)
    assert not b.record_failure("a")  # 1 failure: at the threshold, closed
    assert b.record_failure("a")  # 2nd: trips, True exactly once
    assert not b.record_failure("a")
    assert b.tripped("a") and not b.tripped("b")
    assert list(b.open_tenants()) == ["a"]
    b.reset("a")
    assert not b.tripped("a") and b.failures("a") == 0
    assert TenantBreaker(None).record_failure("x") is False  # never trips


# ---- ingest: spool directory + socket API ----------------------------------


def test_spool_ingest_accepts_rejects_and_skips_tenants_json(tmp_path,
                                                             corpus):
    svc = _service(tmp_path, "spool")
    spool = svc.cfg.spool_dir
    with open(os.path.join(spool, "tenants.json"), "w") as f:
        json.dump({"default": {"weight": 1}}, f)
    with open(os.path.join(spool, "good.json"), "w") as f:
        json.dump({"tenant": "alice", "videos": corpus[:2]}, f)
    with open(os.path.join(spool, "bad.json"), "w") as f:
        f.write("{not json")
    with open(os.path.join(spool, "empty.json"), "w") as f:
        json.dump({"tenant": "alice", "videos": []}, f)
    watcher = SpoolWatcher(spool, svc)
    assert watcher.scan_once() == 3  # tenants.json untouched
    names = sorted(os.listdir(spool))
    assert names == ["admission.wal", "bad.json.rejected",
                     "empty.json.rejected", "good.json.accepted", "results",
                     "tenants.json"]
    assert _result(svc, "bad")["state"] == "rejected"
    assert _result(svc, "empty")["state"] == "rejected"
    svc.request_drain()
    assert svc.run() == 0
    assert _result(svc, "good")["state"] == "done"
    assert len(_outputs(tmp_path, "spool")) == 4  # 2 videos × (feat, ts)
    # spool hygiene: the claimed .accepted file is gone once the result
    # record published; rejects are kept (their records say why)
    names = sorted(os.listdir(spool))
    assert "good.json.accepted" not in names
    assert "bad.json.rejected" in names


def test_socket_api_round_trip(tmp_path, corpus):
    svc = _service(tmp_path, "sock")
    sock = os.path.join(svc.cfg.spool_dir, "control.sock")
    api = SocketAPI(sock, svc)
    api.start()
    try:
        assert socket_request(sock, {"op": "ping"}) == {"ok": True}
        resp = socket_request(sock, {"op": "submit", "tenant": "alice",
                                     "videos": corpus[:1],
                                     "request_id": "batch-7"})
        assert resp["ok"] and resp["request_id"] == "batch-7"
        status = socket_request(sock, {"op": "status",
                                       "request_id": "batch-7"})
        assert status["ok"] and status["state"] == "pending"
        stats = socket_request(sock, {"op": "stats"})
        assert stats["queued_videos"] == 1 and "alice" in stats["tenants"]
        assert socket_request(
            sock, {"op": "submit", "videos": []})["ok"] is False
        assert socket_request(sock, {"op": "nope"})["ok"] is False
        assert socket_request(sock, {"op": "drain"})["draining"] is True
    finally:
        api.stop()
    assert svc.run() == 0
    final = svc.status("batch-7")
    assert final["ok"] and final["state"] == "done"
    assert not os.path.exists(sock)  # stop() unlinks


def test_draining_service_rejects_new_requests(tmp_path, corpus):
    svc = _service(tmp_path, "drainrej")
    svc.request_drain()
    with pytest.raises(RequestRejected, match="draining"):
        svc.submit({"videos": corpus[:1]})
    assert svc.run() == 0


def test_resume_skips_done_videos_at_admission(tmp_path, corpus):
    svc = _service(tmp_path, "resume")
    r = svc.submit({"videos": corpus[:2]})
    svc.request_drain()
    assert svc.run() == 0 and r.state == "done"
    svc2 = _service(tmp_path, "resume", resume=True)
    r2 = svc2.submit({"videos": corpus})
    assert svc2.queue.pending() == 2  # only the two new videos queued
    svc2.request_drain()
    assert svc2.run() == 0
    assert r2.state == "done" and len(r2.done) == len(corpus)


# ---- long-run memory bound (soak) ------------------------------------------


def test_soak_no_per_request_growth(tmp_path, corpus):
    """A stream of requests leaves no residue: per-video packer bookkeeping,
    request/job maps, pending writes, and finished assemblies are all empty
    after each request completes (FeatureAssembly.release + packer.forget)."""
    svc = _service(tmp_path, "soak")
    sizes = []
    for i in range(4):
        r = svc.submit({"tenant": f"t{i % 2}", "videos": corpus,
                        "request_id": f"soak-{i}"})
        for _ in range(500):
            svc.step()
            if r.complete:
                break
        assert r.state == "done"
        packer = svc.packer
        assert not packer.has_pending()
        sizes.append((len(packer.video_clips), len(packer._video_keys),
                      len(packer._finished), len(svc._requests),
                      len(svc._jobs), len(svc.ex._pending_writes),
                      len(packer.flush_errors)))
    assert sizes == [(0, 0, 0, 0, 0, 0, 0)] * 4
    svc.close()


def test_assembly_release_drops_row_buffers():
    from video_features_tpu.io.output import FeatureAssembly

    asm = FeatureAssembly("v", {})
    asm.reserve()
    asm.put(0, np.ones((4,), np.float32))
    asm.finish()
    stacked = asm.stacked((4,))
    asm.release()
    assert asm._rows == {} and stacked.shape == (1, 4)  # copy survives


# ---- decode autoscaler -----------------------------------------------------


def test_autoscaler_grows_on_starvation_shrinks_on_idle():
    a = DecodeAutoscaler(min_workers=1, max_workers=4)
    # starved: low occupancy AND decode dominating wall
    assert a.decide(0.5, decode_seconds=5.0, wall_seconds=10.0,
                    current=2, dispatched_slots=16) == 3
    assert a.decide(0.5, 5.0, 10.0, current=4, dispatched_slots=16) == 4
    # decode nearly free: shrink
    assert a.decide(0.95, 0.2, 10.0, current=2, dispatched_slots=16) == 1
    assert a.decide(0.95, 0.2, 10.0, current=1, dispatched_slots=16) == 1
    # healthy interval or too little evidence: hold
    assert a.decide(0.95, 3.0, 10.0, current=2, dispatched_slots=16) == 2
    assert a.decide(0.2, 9.0, 10.0, current=2, dispatched_slots=2) == 2
    assert a.decide(0.2, 9.0, 0.0, current=2, dispatched_slots=16) == 2


def test_decode_pool_resize_live(tmp_path, corpus):
    """decode_workers=0 resolves to an auto pool the daemon can resize while
    work flows; a shrink never cancels a mid-decode video."""
    svc = _service(tmp_path, "auto", decode_workers=0)
    pool = svc.ex._decode_pool
    assert pool is not None and pool.workers >= 2
    assert svc._autoscaler is not None
    pool.resize(pool.workers + 2)
    grown = pool.workers
    r = svc.submit({"videos": corpus})
    svc.step()
    pool.resize(1)  # shrink under load: debt, not cancellation
    assert pool.workers == 1 < grown
    svc.request_drain()
    assert svc.run() == 0
    assert r.state == "done"


def test_serve_rejects_batch_only_flags(tmp_path):
    cfg = _cfg(tmp_path, "vcfg", serve=True)
    cfg.validate()  # the serve base config itself is valid
    for kw, msg in ((dict(max_failures=3), "tenant_max_failures"),
                    (dict(retry_failed=True), "batch-run flag"),
                    (dict(show_pred=True, num_devices=1), "batch-only"),
                    (dict(on_extraction="print"), "save_numpy"),
                    (dict(spool_dir=None), "spool_dir"),
                    (dict(decode_workers=-1), "auto")):
        with pytest.raises(ValueError, match=msg):
            cfg.replace(**kw).validate()


def test_service_requires_a_packing_path(tmp_path):
    class NoPack(ToyPacked):
        def pack_spec(self):
            return None

    ex = NoPack(_cfg(tmp_path, "nopack", serve=True))
    with pytest.raises(ValueError, match="packing path"):
        ExtractionService(ex)


# ---- signal-driven lifecycle (in-process, real daemon thread) --------------


def test_spool_watcher_thread_feeds_a_live_daemon(tmp_path, corpus):
    """The full daemon wiring minus signals: watcher thread ingests a spool
    file while run() serves, a socket drain ends the run cleanly."""
    svc = _service(tmp_path, "live", spool_poll_sec=0.01)
    spool = svc.cfg.spool_dir
    watcher = SpoolWatcher(spool, svc, poll_interval=0.01)
    watcher.start()
    runner = threading.Thread(target=lambda: setattr(
        svc, "_rc", svc.run()), daemon=True)
    runner.start()
    try:
        tmp_file = os.path.join(spool, ".r1.json.tmp")
        with open(tmp_file, "w") as f:
            json.dump({"tenant": "alice", "videos": corpus[:2]}, f)
        os.replace(tmp_file, os.path.join(spool, "r1.json"))  # atomic drop
        deadline = time.time() + 30
        while time.time() < deadline:
            if os.path.exists(os.path.join(svc.notify_dir, "r1.result.json")):
                break
            time.sleep(0.02)
        assert _result(svc, "r1")["state"] == "done"
    finally:
        svc.request_drain()
        runner.join(timeout=30)
        watcher.stop()
    assert svc._rc == 0
    assert len(_outputs(tmp_path, "live")) == 4


# ---- durable serving: WAL, crash recovery, watchdog (docs/serving.md) ------


def _wal_records(svc):
    with open(os.path.join(svc.cfg.spool_dir, "admission.wal")) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_submit_is_wal_logged_before_ack_and_resolved_at_publish(tmp_path,
                                                                 corpus):
    svc = _service(tmp_path, "waltrail")
    r = svc.submit({"tenant": "alice", "videos": corpus[:2],
                    "request_id": "w-1", "deadline_sec": 3600})
    # ack barrier: by the time submit returned, the admitted record — id,
    # tenant, paths, model, deadline, admission seqs — is on disk
    recs = _wal_records(svc)
    assert [rec["rec"] for rec in recs] == ["admitted"]
    assert recs[0]["request"] == "w-1" and recs[0]["tenant"] == "alice"
    assert recs[0]["feature_type"] == "resnet50"
    assert recs[0]["deadline"] == r.deadline
    assert recs[0]["videos"] == [os.path.abspath(v) for v in corpus[:2]]
    assert recs[0]["seqs"] == [1, 2]
    assert svc.stats()["wal"]["unresolved"] == 1
    svc.request_drain()
    assert svc.run() == 0
    # publication resolved the entry and the all-resolved log compacted
    assert _wal_records(svc) == []


def test_crash_recovery_exactly_once_byte_parity(tmp_path, corpus):
    """The tentpole acceptance: a daemon that dies mid-corpus loses nothing
    and duplicates nothing — the restarted daemon replays the WAL entry,
    dedupes the videos that landed pre-crash, finishes the rest, and the
    outputs are byte-identical to an uninterrupted run."""
    ex_ref = ToyPacked(_cfg(tmp_path, "crash_ref"))
    assert ex_ref.run(corpus) == len(corpus)

    svc = _service(tmp_path, "crash")
    r = svc.submit({"tenant": "alice", "videos": corpus,
                    "request_id": "crash-1", "deadline_sec": 3600})
    # partially serve: land at least one video, then "crash" before the
    # request completes (close() flushes the log but never resolves a live
    # entry — exactly the disk state a SIGKILL leaves)
    for _ in range(500):
        svc.step()
        if r.done:
            break
    assert r.done and not r.complete
    pre_crash_done = len(r.done)
    svc.close()
    assert not os.path.exists(
        os.path.join(svc.notify_dir, "crash-1.result.json"))

    svc2 = _service(tmp_path, "crash")
    entries = svc2._wal.replayable()
    assert [e["request"] for e in entries] == ["crash-1"]
    assert svc2.recover() == 1
    # survivors re-enter with their ORIGINAL admission seqs, and the
    # scheduler's counter fast-forwarded past them so a fresh submission
    # can never mint a colliding seq
    replayed_seqs = {j.seq for j in svc2.queue.peek_jobs(len(corpus))}
    assert replayed_seqs and replayed_seqs <= set(entries[0]["seqs"])
    assert svc2.queue._seq >= max(entries[0]["seqs"])
    svc2.request_drain()
    assert svc2.run() == 0

    record = _result(svc2, "crash-1")
    assert record["state"] == "done"
    assert len(record["done"]) == len(corpus) and record["failed"] == []
    # exactly once: byte parity with the uninterrupted run, one
    # done-manifest entry per video, and the recovered request's pre-crash
    # videos were deduped (not re-extracted)
    _assert_bytes_equal(_outputs(tmp_path, "crash"),
                        _outputs(tmp_path, "crash_ref"))
    manifest = os.path.join(str(tmp_path / "crash"), "resnet50",
                            ".done_manifest.jsonl")
    with open(manifest) as f:
        done_paths = [json.loads(line)["video"] for line in f if line.strip()]
    assert len(done_paths) == len(set(done_paths)) == len(corpus)
    assert len(r.done) == pre_crash_done  # the dead request object is dead
    # the replayed entry resolved at publish; the log compacted back
    assert _wal_records(svc2) == []


def test_recovery_skips_already_published_requests(tmp_path, corpus):
    """Crash BETWEEN publish and resolve: the submitter already has its
    result record, so recovery resolves the entry without re-admitting."""
    svc = _service(tmp_path, "dup")
    svc.submit({"videos": corpus[:1], "request_id": "dup-1"})
    svc.request_drain()
    assert svc.run() == 0
    wal = os.path.join(svc.cfg.spool_dir, "admission.wal")
    with open(wal, "a") as f:  # resurrect the entry, as if resolve was lost
        f.write(json.dumps({"rec": "admitted", "request": "dup-1",
                            "tenant": "default", "feature_type": "resnet50",
                            "videos": [os.path.abspath(corpus[0])],
                            "seqs": [1]}) + "\n")
    svc2 = _service(tmp_path, "dup")
    assert svc2._wal.replayable()
    assert svc2.recover() == 0
    assert svc2.queue.pending() == 0
    assert svc2._wal.unresolved_count() == 0
    svc2.close()


def test_no_recover_drops_unresolved_entries(tmp_path, corpus):
    spool = str(tmp_path / "norec" / "spool")
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, "admission.wal"), "w") as f:
        f.write(json.dumps({"rec": "admitted", "request": "old-1",
                            "tenant": "t", "feature_type": "resnet50",
                            "videos": [os.path.abspath(corpus[0])],
                            "seqs": [3]}) + "\n")
    svc = _service(tmp_path, "norec", recover=False)
    assert svc.recover() == 0
    assert svc.queue.pending() == 0
    assert svc._wal.unresolved_count() == 0
    svc.close()


def test_recovery_drops_entries_for_unloaded_models(tmp_path, corpus):
    spool = str(tmp_path / "unloaded" / "spool")
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, "admission.wal"), "w") as f:
        f.write(json.dumps({"rec": "admitted", "request": "old-1",
                            "tenant": "t", "feature_type": "i3d",
                            "videos": [os.path.abspath(corpus[0])],
                            "seqs": [1]}) + "\n")
    svc = _service(tmp_path, "unloaded")
    assert svc.recover() == 0  # i3d is not loaded by this daemon
    assert svc.queue.pending() == 0
    assert svc._wal.unresolved_count() == 0
    svc.close()


def test_failed_publish_keeps_wal_entry_for_recovery(tmp_path, corpus,
                                                     monkeypatch):
    """The post-extract/pre-publish seam: a result-record write failure must
    leave the WAL entry live, and the next daemon re-publishes from the
    done-manifests without re-running a single video."""
    monkeypatch.setenv("VFT_FAULTS", "publish:raise:rec-1:1")
    reset_faults()
    svc = _service(tmp_path, "pubfail")
    svc.submit({"videos": corpus[:1], "request_id": "rec-1"})
    svc.request_drain()
    assert svc.run() == 0  # the videos landed; only the notification failed
    assert not os.path.exists(
        os.path.join(svc.notify_dir, "rec-1.result.json"))
    assert [rec["request"] for rec in _wal_records(svc)
            if rec["rec"] == "admitted"] == ["rec-1"]

    svc2 = _service(tmp_path, "pubfail")
    assert svc2.recover() == 1  # all videos deduped → published immediately
    record = _result(svc2, "rec-1")
    assert record["state"] == "done" and len(record["done"]) == 1
    assert svc2._wal.unresolved_count() == 0
    svc2.close()


def test_degraded_wal_daemon_keeps_serving(tmp_path, corpus, monkeypatch):
    """ENOSPC in the WAL (injected at the wal_append seam) degrades
    durability — loudly, via healthz — but admission and extraction keep
    working; the daemon never crashes."""
    monkeypatch.setenv("VFT_FAULTS", "wal_append:raise")
    reset_faults()
    svc = _service(tmp_path, "degraded")
    r = svc.submit({"videos": corpus[:1], "request_id": "deg-1"})
    h = svc.healthz()
    assert h["wal"]["enabled"] is True and h["wal"]["durable"] is False
    svc.request_drain()
    assert svc.run() == 0
    assert r.state == "done"
    assert _result(svc, "deg-1")["state"] == "done"


def test_wal_disabled_with_none(tmp_path, corpus):
    svc = _service(tmp_path, "waloff", wal_path="none")
    assert svc._wal is None
    r = svc.submit({"videos": corpus[:1]})
    assert r.wal_logged is False
    assert svc.healthz()["wal"] == {"enabled": False}
    assert svc.recover() == 0
    svc.request_drain()
    assert svc.run() == 0
    assert not os.path.exists(os.path.join(svc.cfg.spool_dir,
                                           "admission.wal"))


def test_healthz_threshold_configurable_and_wal_section(tmp_path, corpus):
    svc = _service(tmp_path, "hz", healthz_stale_sec=0.01)
    h = svc.healthz()
    assert h["stale_threshold_sec"] == 0.01
    assert h["wal"]["durable"] is True and h["wal"]["unresolved"] == 0
    svc.submit({"videos": corpus[:1], "request_id": "hz-1"})
    assert svc.healthz()["wal"]["unresolved"] == 1
    svc._last_step = time.monotonic() - 1.0
    assert svc.healthz()["stale"] is True
    svc._last_step = time.monotonic()
    assert svc.healthz()["stale"] is False
    svc.request_drain()
    assert svc.run() == 0


def test_watchdog_monitor_flags_stale_loop(tmp_path, corpus):
    svc = _service(tmp_path, "wdmon", step_watchdog_sec=0.05)
    svc._last_step = time.monotonic() - 1.0
    mon = threading.Thread(target=svc._watchdog_loop, daemon=True)
    mon.start()
    deadline = time.time() + 5
    while time.time() < deadline and not svc._stalled.is_set():
        time.sleep(0.01)
    assert svc._stalled.is_set()
    svc._watchdog_stop.set()
    mon.join(timeout=2)
    svc.close()


def test_watchdog_trip_requeues_inflight_transiently(tmp_path, corpus):
    """A tripped watchdog turns the stall into a transient batch failure:
    the in-flight videos requeue through the slot-attribution machinery (no
    breaker charge, same retry budget) and the request still completes."""
    svc = _service(tmp_path, "wdreq", step_watchdog_sec=30.0, retries=2)
    r = svc.submit({"tenant": "alice", "videos": corpus[:1],
                    "request_id": "wd-1"})
    svc.step()  # pop + ingest: the video is now in flight
    assert svc._jobs
    job = next(iter(svc._jobs.values()))
    svc._stalled.set()  # as the monitor would on a wedged step
    svc.step()  # clears the flag, fails the stalled batch transiently —
    # the victim requeues with its original seq and THIS step pops it again
    assert not svc._stalled.is_set()
    assert job.attempts == 1  # one transient attempt burned, not terminal
    assert not r.failed
    assert not svc.breaker.tripped("alice")
    svc.request_drain()
    assert svc.run() == 0
    assert r.state == "done"
    assert _result(svc, "wd-1")["state"] == "done"


def test_spool_retain_keeps_accepted_files(tmp_path, corpus):
    svc = _service(tmp_path, "retain", spool_retain=True)
    spool = svc.cfg.spool_dir
    with open(os.path.join(spool, "keep.json"), "w") as f:
        json.dump({"videos": corpus[:1]}, f)
    SpoolWatcher(spool, svc).scan_once()
    svc.request_drain()
    assert svc.run() == 0
    assert _result(svc, "keep")["state"] == "done"
    assert os.path.exists(os.path.join(spool, "keep.json.accepted"))
