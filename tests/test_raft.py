"""JAX RAFT numerical parity vs a torch functional mirror (random weights)."""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import jax
import jax.numpy as jnp
import torch

from torch_mirrors import raft_random_state_dict, raft_torch_forward
from video_features_tpu.models.raft import (
    pad_to_multiple_of_8,
    raft_forward,
    raft_init_params,
    unpad,
)
from video_features_tpu.weights.convert_torch import convert_raft


@pytest.fixture(scope="module")
def converted():
    sd = raft_random_state_dict(seed=7)
    # exercise the module-prefix strip path like the real checkpoints
    params = convert_raft({f"module.{k}": v for k, v in sd.items()})
    return sd, params


def test_param_tree_matches_init_structure(converted):
    _, params = converted
    init = raft_init_params(seed=0)
    p1 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
    p2 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(init)[0]}
    assert p1 == p2


def test_flow_parity(converted):
    sd, params = converted
    rng = np.random.default_rng(0)
    # ≥128px so the coarsest corr level is ≥2×2: the reference's grid normalization
    # divides by (W−1), which NaNs on 1×1 levels it never sees in practice
    img1 = rng.uniform(0, 255, (1, 128, 128, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 128, 128, 3)).astype(np.float32)
    ref = raft_torch_forward(
        sd, torch.from_numpy(img1).permute(0, 3, 1, 2), torch.from_numpy(img2).permute(0, 3, 1, 2)
    ).permute(0, 2, 3, 1).numpy()
    out = np.asarray(raft_forward(params, jnp.asarray(img1), jnp.asarray(img2)))
    assert out.shape == ref.shape == (1, 128, 128, 2)
    # Random weights make the recurrence chaotic (|flow| explodes to ~400 px by
    # iter 20, ~e^t amplification of fp32 noise), so deep parity is checked at a
    # stable depth and the full 20 iters at a scale-relative tolerance.
    np.testing.assert_allclose(out, ref, atol=5e-2 * np.abs(ref).max())
    for it, atol in ((1, 1e-3), (4, 2e-3), (8, 5e-2)):
        r = raft_torch_forward(
            sd, torch.from_numpy(img1).permute(0, 3, 1, 2),
            torch.from_numpy(img2).permute(0, 3, 1, 2), iters=it,
        ).permute(0, 2, 3, 1).numpy()
        o = np.asarray(raft_forward(params, jnp.asarray(img1), jnp.asarray(img2), iters=it))
        np.testing.assert_allclose(o, r, atol=atol)


def test_fewer_iters_differ(converted):
    """The scan really iterates: 1 vs 20 iterations give different flows."""
    _, params = converted
    rng = np.random.default_rng(1)
    img1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 32, 3)).astype(np.float32))
    img2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 32, 3)).astype(np.float32))
    f1 = np.asarray(raft_forward(params, img1, img2, iters=1))
    f20 = np.asarray(raft_forward(params, img1, img2, iters=20))
    assert not np.allclose(f1, f20)


def test_pad_unpad_roundtrip():
    x = np.arange(2 * 30 * 41 * 3, dtype=np.float32).reshape(2, 30, 41, 3)
    padded, pads = pad_to_multiple_of_8(x)
    assert padded.shape[1] % 8 == 0 and padded.shape[2] % 8 == 0
    np.testing.assert_array_equal(unpad(padded, pads), x)
    # sintel mode: symmetric split, replicate values
    t = torch.nn.functional.pad(
        torch.from_numpy(x).permute(0, 3, 1, 2),
        [pads[2], pads[3], pads[0], pads[1]], mode="replicate")
    np.testing.assert_array_equal(padded, t.permute(0, 2, 3, 1).numpy())


def test_bilinear_sample_matches_grid_sample():
    from torch_mirrors import _raft_bilinear
    from video_features_tpu.ops.warp import bilinear_sample

    rng = np.random.default_rng(2)
    img = rng.standard_normal((3, 9, 11, 4)).astype(np.float32)
    # include out-of-bounds and exact-integer coords
    coords = np.stack(
        [rng.uniform(-3, 13, (3, 5, 6)), rng.uniform(-3, 11, (3, 5, 6))], axis=-1
    ).astype(np.float32)
    coords[0, 0, 0] = [0.0, 0.0]
    coords[0, 0, 1] = [10.0, 8.0]
    coords[0, 0, 2] = [-1.0, 4.5]
    ref = _raft_bilinear(
        torch.from_numpy(img).permute(0, 3, 1, 2), torch.from_numpy(coords)
    ).permute(0, 2, 3, 1).numpy()
    out = np.asarray(bilinear_sample(jnp.asarray(img), jnp.asarray(coords)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_matmul_lookup_bitwise_matches_gather(converted):
    """The MXU one-hot-matmul window lookup is the same bits as the gather
    lookup (fp32 CPU; the matmul runs at Precision.HIGHEST by construction)."""
    _, params = converted
    rng = np.random.default_rng(3)
    f1 = rng.uniform(0, 255, (2, 48, 64, 3)).astype(np.float32)
    f2 = rng.uniform(0, 255, (2, 48, 64, 3)).astype(np.float32)
    mm = np.asarray(raft_forward(params, f1, f2, iters=6, corr_impl="volume"))
    ga = np.asarray(raft_forward(params, f1, f2, iters=6, corr_impl="volume_gather"))
    np.testing.assert_array_equal(mm, ga)


def test_matmul_lookup_zero_padding_out_of_bounds(converted):
    """Window centers pushed far outside the frame: all-zero one-hot rows must
    reproduce the gather path's zero-padding exactly (not clamp-to-edge)."""
    from video_features_tpu.models.raft import _build_pyramid, _lookup

    rng = np.random.default_rng(4)
    f1 = jnp.asarray(rng.standard_normal((1, 8, 8, 32)).astype(np.float32))
    f2 = jnp.asarray(rng.standard_normal((1, 8, 8, 32)).astype(np.float32))
    pyr = _build_pyramid(f1, f2)
    # coords straddling every boundary case incl. fully outside
    coords = jnp.asarray(
        rng.uniform(-6.0, 13.0, (1, 8, 8, 2)).astype(np.float32))
    mm = np.asarray(_lookup(pyr, coords, "matmul"))
    ga = np.asarray(_lookup(pyr, coords, "gather"))
    np.testing.assert_array_equal(mm, ga)
