"""End-to-end ResNet-50 extraction on a real sample video (random weights, CPU)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.resnet import ExtractResNet50


@pytest.fixture(scope="module")
def extractor(tmp_path_factory):
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    out = tmp_path_factory.mktemp("out")
    cfg = ExtractionConfig(
        feature_type="resnet50",
        on_extraction="save_numpy",
        output_path=str(out),
        batch_size=64,
    )
    yield ExtractResNet50(cfg)
    mp.undo()


def test_extract_sample(extractor, sample_video):
    feats = extractor.extract(sample_video)
    assert feats["resnet50"].shape == (355, 2048)
    assert feats["timestamps_ms"].shape == (355,)
    assert float(feats["fps"]) == pytest.approx(19.62, abs=0.01)
    assert np.isfinite(feats["resnet50"]).all()


def test_tail_padding_does_not_leak(extractor):
    """Rows of a padded tail batch must equal the same frames run as a full batch."""
    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (64, 224, 224, 3), dtype=np.uint8)
    full = np.asarray(extractor._step(extractor.params, frames))
    from video_features_tpu.extractors.base import pad_batch

    tail = pad_batch(frames[:5], 64)
    padded = np.asarray(extractor._step(extractor.params, tail))[:5]
    np.testing.assert_allclose(padded, full[:5], rtol=1e-5, atol=1e-5)


def test_run_fault_barrier(extractor, sample_video, capsys):
    ok = extractor.run([sample_video, "/tmp/missing_video.mp4"])
    out = capsys.readouterr().out
    assert ok == 1
    assert "Extraction failed at: /tmp/missing_video.mp4" in out
