"""End-to-end ResNet-50 extraction on a real sample video (random weights, CPU)."""

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.resnet import ExtractResNet50


@pytest.fixture(scope="module")
def extractor(tmp_path_factory, monkeypatch_session=None):
    import os

    os.environ["VFT_ALLOW_RANDOM_WEIGHTS"] = "1"
    out = tmp_path_factory.mktemp("out")
    cfg = ExtractionConfig(
        feature_type="resnet50",
        on_extraction="save_numpy",
        output_path=str(out),
        batch_size=64,
    )
    return ExtractResNet50(cfg)


def test_extract_sample(extractor, sample_video):
    feats = extractor.extract(sample_video)
    assert feats["resnet50"].shape == (355, 2048)
    assert feats["timestamps_ms"].shape == (355,)
    assert float(feats["fps"]) == pytest.approx(19.62, abs=0.01)
    assert np.isfinite(feats["resnet50"]).all()
    # padding must not leak: re-running a prefix with a different tail gives same rows
    # (batch 64 → last batch has 355 % 64 = 35 valid rows)


def test_run_fault_barrier(extractor, sample_video, capsys):
    ok = extractor.run([sample_video, "/tmp/missing_video.mp4"])
    out = capsys.readouterr().out
    assert ok == 1
    assert "Extraction failed at: /tmp/missing_video.mp4" in out
