"""Measured bf16 flow-net drift (--flow_dtype bfloat16) vs the fp32 path.

Round-2 review: fp32-only flow was an *asserted* precision claim
("iterative flow refinement is precision-sensitive") with no measurement.
These tests quantify the drift and pin the bound that makes bf16 flow safe
for the I3D sandwich: the reference quantizes flow to uint8 at 40/255 ≈ 0.157
px per step (``extract_i3d.py:59-72``), so flow errors well under half a step
(~0.078 px) are absorbed or flip at most border pixels by ±1 level.

CPU runs bf16 in emulation — slow but bit-faithful; shapes stay small.
"""
# fast-registry: default tier — bf16 drift measurement over flow compiles

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.models.pwc import pwc_forward, pwc_init_params
from video_features_tpu.models.raft import raft_forward, raft_init_params


@pytest.fixture(scope="module")
def frames(rng_mod=np.random.default_rng(21)):
    # smooth synthetic frames + a shifted copy: realistic small flows, not
    # white noise (white noise makes correlation windows degenerate)
    base = rng_mod.uniform(0, 255, (1, 40, 48, 3)).astype(np.float32)
    from scipy.ndimage import gaussian_filter, shift

    base = gaussian_filter(base, sigma=(0, 3, 3, 0))
    nxt = shift(base, (0, 1.3, -0.8, 0), order=1, mode="nearest")
    return jnp.asarray(base), jnp.asarray(nxt)


def test_pwc_bf16_drift_bounded(frames):
    x1, x2 = frames
    params = pwc_init_params(0)
    f32 = np.asarray(pwc_forward(params, x1, x2))
    bf16 = np.asarray(pwc_forward(params, x1, x2, dtype=jnp.bfloat16))
    err = np.abs(bf16 - f32)
    scale = np.abs(f32).max() + 1e-6
    # bf16 has ~3 decimal digits; one conv stack + refiner accumulates to
    # sub-percent relative error in practice — bound at 2% of peak flow
    assert err.max() <= 0.02 * scale + 1e-3, (err.max(), scale)


def test_raft_bf16_drift_bounded(frames):
    x1, x2 = frames
    params = raft_init_params(0)
    f32 = np.asarray(raft_forward(params, x1, x2, iters=8))
    bf16 = np.asarray(raft_forward(params, x1, x2, iters=8, dtype=jnp.bfloat16))
    err = np.abs(bf16 - f32)
    scale = np.abs(f32).max() + 1e-6
    # the fp32 coords carry keeps per-iteration bf16 conv noise from
    # compounding multiplicatively; bound at 5% of peak flow for 8 iterations
    assert err.max() <= 0.05 * scale + 1e-3, (err.max(), scale)


def test_bf16_flow_quantizes_like_fp32(frames):
    """The I3D sandwich's uint8 quantization absorbs bf16 flow drift: quantized
    planes agree within ±1 level on ≥99% of pixels."""
    from video_features_tpu.models.i3d import i3d_preprocess_flow

    x1, x2 = frames
    params = pwc_init_params(0)
    f32 = pwc_forward(params, x1, x2)
    bf16 = pwc_forward(params, x1, x2, dtype=jnp.bfloat16)
    q32 = np.asarray(i3d_preprocess_flow(f32[:, None]))
    qbf = np.asarray(i3d_preprocess_flow(bf16[:, None]))
    # levels are 2/255 apart after ScaleTo1_1
    level = 2.0 / 255.0
    diff_levels = np.abs(q32 - qbf) / level
    assert (diff_levels <= 1.0 + 1e-6).mean() >= 0.99, diff_levels.max()


def test_flow_dtype_plumbs_through_extractor(tmp_path, monkeypatch):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.flow import ExtractFlow

    rng = np.random.default_rng(5)
    fr = rng.uniform(0, 255, (4, 40, 48, 3)).astype(np.float32)
    outs = {}
    for fd in ("float32", "bfloat16"):
        cfg = ExtractionConfig(feature_type="pwc", batch_size=3, num_devices=1,
                               flow_dtype=fd,
                               output_path=str(tmp_path / f"o{fd}"),
                               tmp_path=str(tmp_path / f"t{fd}"))
        ex = ExtractFlow(cfg)
        outs[fd] = ex._run_pairs(fr)
    assert outs["float32"].shape == outs["bfloat16"].shape
    # different dtypes must actually change the numerics (plumbing is live)...
    assert not np.array_equal(outs["float32"], outs["bfloat16"])
    # ...but only slightly
    scale = np.abs(outs["float32"]).max() + 1e-6
    assert np.abs(outs["float32"] - outs["bfloat16"]).max() <= 0.05 * scale


def test_raft_on_demand_matmul_bf16_drift_bounded(frames):
    """bf16 on_demand_matmul (bf16 vol-einsum inputs, fp32 accumulation) vs
    the fp32 gather on-demand path: same drift class as the volume path's
    bf16 pyramid storage — one bf16 rounding of the lookup input."""
    x1, x2 = frames
    params = raft_init_params(0)
    f32 = np.asarray(raft_forward(params, x1, x2, iters=8,
                                  corr_impl="on_demand"))
    bf16 = np.asarray(raft_forward(params, x1, x2, iters=8,
                                   corr_impl="on_demand_matmul",
                                   dtype=jnp.bfloat16))
    err = np.abs(bf16 - f32)
    scale = np.abs(f32).max() + 1e-6
    assert err.max() <= 0.05 * scale + 1e-3, (err.max(), scale)
    # the dtype plumbing is LIVE: a direct lookup in bf16 must differ from
    # fp32 (else a silent revert of the bf16 vol-einsum passes the bound
    # above on conv drift alone)
    from video_features_tpu.models.raft import (
        _build_f2_pyramid, _lookup_on_demand, _encoder, coords_grid)

    f1 = _encoder(params["fnet"], 2.0 * (x1 / 255.0) - 1.0, "instance")
    f2 = _encoder(params["fnet"], 2.0 * (x2 / 255.0) - 1.0, "instance")
    pyr = _build_f2_pyramid(f2.astype(jnp.float32))
    coords = coords_grid(*f1.shape[:3])
    a = np.asarray(_lookup_on_demand(f1, pyr, coords, "matmul"))
    b = np.asarray(_lookup_on_demand(f1, pyr, coords, "matmul",
                                     dtype=jnp.bfloat16))
    assert np.abs(a - b).max() > 0, "bf16 vol-einsum plumbing is dead"
    assert np.allclose(a, b, rtol=0.03, atol=0.03 * np.abs(a).max())
