"""AsyncOutputWriter: ordering, failure attribution, and the PR-1 kill-mid-
write invariants on the asynchronous path.

The writer overlaps ``.npy`` serialization with the next video's compute;
these tests pin the contract that overlap must not weaken: strict submission
order, write-before-done per video, atomic tmp+rename under SIGKILL
(``VFT_FAULTS=save:kill`` extended to the writer thread), per-video failure
attribution through the run loop, and the --sync_writer escape hatch.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import Extractor
from video_features_tpu.io.output import (
    AsyncOutputWriter,
    load_done_set,
    manifest_path,
)
from video_features_tpu.reliability import (
    OutputError,
    RetryPolicy,
    load_failures,
    reset_faults,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


def test_writer_writes_before_done_in_submission_order(tmp_path):
    out = str(tmp_path)
    w = AsyncOutputWriter(depth=2)
    handles = [
        w.submit({"feat": np.full(4, i, np.float32)}, f"v{i}.mp4", out)
        for i in range(4)
    ]
    for h in handles:
        assert h.wait(timeout=60)
    w.close()
    # every .npy present and loadable before its done record existed
    for i in range(4):
        np.testing.assert_array_equal(
            np.load(os.path.join(out, f"v{i}_feat.npy")), np.full(4, i))
    assert load_done_set(out) == {os.path.abspath(f"v{i}.mp4") for i in range(4)}
    # single queue + single thread: manifest records appear in submission order
    with open(manifest_path(out)) as f:
        videos = [json.loads(line)["video"] for line in f]
    assert videos == [os.path.abspath(f"v{i}.mp4") for i in range(4)]


def test_writer_failure_lands_on_its_own_handle(tmp_path, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "save:raise:v1")
    out = str(tmp_path)
    w = AsyncOutputWriter(depth=2)  # no retry: the injected fault must surface
    h0 = w.submit({"feat": np.arange(3, dtype=np.float32)}, "v0.mp4", out)
    h1 = w.submit({"feat": np.arange(3, dtype=np.float32)}, "v1.mp4", out)
    h2 = w.submit({"feat": np.arange(3, dtype=np.float32)}, "v2.mp4", out)
    assert h0.wait(timeout=60)
    with pytest.raises(OutputError):
        h1.wait(timeout=60)
    assert h2.wait(timeout=60)  # the writer survives a failed job
    w.close()
    done = load_done_set(out)
    assert os.path.abspath("v0.mp4") in done and os.path.abspath("v2.mp4") in done
    assert os.path.abspath("v1.mp4") not in done  # failed: never marked done
    assert not os.path.exists(os.path.join(out, "v1_feat.npy"))


def test_writer_retries_transient_save_failures(tmp_path, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "save:raise_transient::1")  # first save only
    w = AsyncOutputWriter(depth=2, retry=RetryPolicy(attempts=3, base_delay=0.01))
    h = w.submit({"feat": np.arange(5, dtype=np.float32)}, "vr.mp4", str(tmp_path))
    assert h.wait(timeout=60)  # retry absorbed the transient failure
    w.close()
    np.testing.assert_array_equal(
        np.load(os.path.join(str(tmp_path), "vr_feat.npy")), np.arange(5))
    assert load_done_set(str(tmp_path)) == {os.path.abspath("vr.mp4")}


class DictExtractor(Extractor):
    """Extraction stub: the run loop + writer without decode or a model."""

    def extract(self, video_path):
        return {"feat": np.arange(4, dtype=np.float32)}


def _cfg(tmp_path, **kw):
    kw.setdefault("retries", 0)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"), **kw)


def test_run_loop_attributes_async_write_failure_to_its_video(tmp_path, monkeypatch):
    """A write that fails on the writer thread is accounted exactly like a
    compute failure: classified in the failure manifest under ITS video, the
    other videos complete, and the return count excludes it."""
    monkeypatch.setenv("VFT_FAULTS", "save:raise_permanent:vid1")
    ex = DictExtractor(_cfg(tmp_path))
    paths = [f"vid{i}.mp4" for i in range(3)]
    assert ex.run(paths) == 2
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath("vid1.mp4")}
    assert "OutputError" in failures[os.path.abspath("vid1.mp4")]["error_class"]
    assert load_done_set(ex.output_dir) == {
        os.path.abspath("vid0.mp4"), os.path.abspath("vid2.mp4")}


def test_run_loop_write_failures_count_toward_circuit_breaker(tmp_path, monkeypatch):
    from video_features_tpu.reliability import CircuitBreakerTripped

    monkeypatch.setenv("VFT_FAULTS", "save:raise_permanent")
    ex = DictExtractor(_cfg(tmp_path, max_failures=0))
    with pytest.raises(CircuitBreakerTripped, match="max_failures"):
        ex.run([f"vid{i}.mp4" for i in range(4)])


def test_sync_writer_flag_reverts_to_inline_writes(tmp_path):
    ex = DictExtractor(_cfg(tmp_path, async_writer=False))
    assert ex.run(["vid0.mp4"]) == 1
    assert ex._writer is None  # never constructed
    assert load_done_set(ex.output_dir) == {os.path.abspath("vid0.mp4")}


def test_async_writer_kill_mid_write_leaves_no_partial_npy(tmp_path):
    """SIGKILL between the writer thread's tmp-write and rename: identical
    invariants to the synchronous kill-mid-write test — no final .npy, no
    done record, a rerun completes the write."""
    out = str(tmp_path / "out")
    code = (
        "import os\n"
        "os.environ['VFT_FAULTS'] = 'save:kill'\n"
        "import numpy as np\n"
        "from video_features_tpu.io.output import AsyncOutputWriter\n"
        "w = AsyncOutputWriter()\n"
        f"h = w.submit({{'feat': np.arange(100000)}}, 'vidX.mp4', {out!r})\n"
        "h.wait(timeout=60)\n"
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 137, proc.stderr
    assert not os.path.exists(os.path.join(out, "vidX_feat.npy"))
    assert load_done_set(out) == set()  # resume will redo this video

    rerun = (
        "import numpy as np\n"
        "from video_features_tpu.io.output import AsyncOutputWriter\n"
        "w = AsyncOutputWriter()\n"
        f"w.submit({{'feat': np.arange(100000)}}, 'vidX.mp4', {out!r})\n"
        "w.close(wait=True)\n"
    )
    env.pop("VFT_FAULTS", None)
    proc = subprocess.run([sys.executable, "-c", rerun], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    np.testing.assert_array_equal(
        np.load(os.path.join(out, "vidX_feat.npy")), np.arange(100000))
    assert load_done_set(out) == {os.path.abspath("vidX.mp4")}


def test_writer_discards_job_cancelled_after_submit(tmp_path):
    """A watchdog cancellation landing AFTER the attempt's pre-submit check
    must still discard the enqueued write before anything touches disk —
    the job carries the cancel event and re-checks it at the same two
    points the inline path does."""
    import threading

    from video_features_tpu.reliability import VideoTimeoutError

    cancel = threading.Event()
    cancel.set()  # cancelled in the check-to-submit window
    w = AsyncOutputWriter(depth=2)
    h = w.submit({"feat": np.arange(3, dtype=np.float32)}, "vc.mp4",
                 str(tmp_path), cancelled=cancel)
    with pytest.raises(VideoTimeoutError):
        h.wait(timeout=60)
    w.close()
    assert not os.path.exists(os.path.join(str(tmp_path), "vc_feat.npy"))
    assert load_done_set(str(tmp_path)) == set()


def test_interrupted_run_still_prunes_drained_writes(tmp_path):
    """An interrupt landing while a video's write is still on the writer
    thread: the shutdown drain completes the write, and the video — which
    previously failed and was being retried — must still be pruned from the
    failure manifest (it would otherwise sit in both manifests forever,
    since later --resume runs skip it via the done set)."""
    from video_features_tpu.reliability import record_failure

    ex = DictExtractor(_cfg(tmp_path))
    # pre-seed a stale failure record for vid0, as after a failed first run
    os.makedirs(ex.output_dir, exist_ok=True)
    record_failure(ex.output_dir, "vid0.mp4", RuntimeError("old failure"), 1)
    assert load_failures(ex.output_dir) != {}

    def interrupting_progress(done, total):
        raise KeyboardInterrupt  # lands before vid0's write is reaped

    with pytest.raises(KeyboardInterrupt):
        ex.run(["vid0.mp4"], progress=interrupting_progress)
    # the drain completed the write + done record AND converged the manifest
    assert load_done_set(ex.output_dir) == {os.path.abspath("vid0.mp4")}
    assert load_failures(ex.output_dir) == {}


def test_writer_close_drains_queued_jobs(tmp_path):
    w = AsyncOutputWriter(depth=2)
    handles = [
        w.submit({"feat": np.arange(2, dtype=np.float32)}, f"c{i}.mp4",
                 str(tmp_path))
        for i in range(3)
    ]
    w.close(wait=True)  # drains everything already queued
    assert all(h.done() for h in handles)
    assert len(load_done_set(str(tmp_path))) == 3
    with pytest.raises(OutputError, match="closed"):
        w.submit({"feat": np.zeros(1)}, "late.mp4", str(tmp_path))
