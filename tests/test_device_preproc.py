"""--device_preproc: device-side preprocessing everywhere.

The numerics contracts, per model class (config.py / cache/key.py):

- flow (raft/pwc): the geometry pad moves on-device
  (``models/raft.device_pad_to_shape``) — replicate-pad on the uint8 wire is
  arithmetic-free, so outputs are BYTE-identical to the host pad
  (execution-only in the cache key);
- vggish: the log-mel DSP runs as a fused jitted prologue
  (``ops/audio.log_mel_examples``) over raw PCM slabs — float32 device math
  vs the float64 numpy oracle, pinned ≤ 2e-5 (fingerprints);
- i3d: the PIL edge resize moves on-device
  (``ops/image.device_edge_resize_hwc``) — tolerance-gated like resnet50's
  ``--device_resize`` (≤ 2 uint8 levels max, ≤ 1 mean; fingerprints);
- resnet50: the flag IS ``--device_resize`` (one key component);
- r21d: documented no-op (the transform has been device-fused since the
  port).

Compile budget: the host-side contracts (pad bytes, slab framing, key
resolution, routing) run stub-level; the model-level pins compile one tiny
RAFT geometry (shared between the per-video and packed runs) and the small
VGGish net.
"""

# fast-registry: default tier — device-preproc parity over real-model compiles

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig


@pytest.fixture(scope="module", autouse=True)
def _random_weights():
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    yield
    mp.undo()


def _cfg(tmp_path, feature_type, **kw):
    return ExtractionConfig(
        feature_type=feature_type, num_devices=1,
        output_path=str(tmp_path / "out"), tmp_path=str(tmp_path / "tmp"),
        **kw)


def _write_video(path, n_frames, size=(24, 16), seed=7):
    import cv2

    wr = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"),
                         10.0, size)
    rng = np.random.default_rng(seed)
    for _ in range(n_frames):
        wr.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    wr.release()
    return str(path)


# ---- device pad: byte-exact vs the host oracle ------------------------------


def test_device_pad_byte_identical_to_host_pad():
    """device_pad_to_shape == pad_to_shape bit for bit on the uint8 wire —
    replicate-pad is pure copying, which is WHY the flag is execution-only
    for flow in cache/key.py."""
    from video_features_tpu.models.raft import (
        device_pad_to_shape, pad_split, pad_to_shape)

    rng = np.random.default_rng(0)
    frames = rng.integers(0, 256, (3, 13, 17, 3), dtype=np.uint8)
    for target in ((16, 24), (13, 17), (14, 17), (13, 20)):
        host = np.stack([pad_to_shape(f, target)[0] for f in frames])
        dev = np.asarray(device_pad_to_shape(jnp.asarray(frames), target))
        assert dev.dtype == np.uint8
        np.testing.assert_array_equal(dev, host)
        # the host keeps only the arithmetic: pad_split matches what
        # pad_to_shape reported, so finalize's unpad stays correct
        assert pad_split(13, 17, *target) == pad_to_shape(frames[0], target)[1]
    with pytest.raises(ValueError, match="cannot pad"):
        device_pad_to_shape(jnp.asarray(frames), (8, 8))


# ---- vggish: slab framing + jitted log-mel ----------------------------------


def test_pcm_slab_count_matches_example_count():
    """The wire-format equivalence: framing raw 16 kHz samples with
    (15600, 15360) yields exactly one slab per host log-mel example — both
    tail-dropping framing stages admit example k iff n ≥ k·15360 + 15600."""
    from video_features_tpu.audio import melspec

    rng = np.random.default_rng(1)
    for n in (0, 100, 15599, 15600, 15601, 30959, 30960, 30961, 46320, 50000):
        wav = rng.standard_normal(n)
        n_examples = melspec.waveform_to_examples(wav, 16000).shape[0]
        slabs = melspec.waveform_to_pcm_slabs(wav, 16000)
        assert slabs.shape == (n_examples, melspec.SAMPLES_PER_EXAMPLE), n
        assert slabs.dtype == np.float32
        # each slab IS the raw window the host DSP consumed for that example
        for k in range(n_examples):
            start = k * melspec.EXAMPLE_HOP_SAMPLES
            np.testing.assert_array_equal(
                slabs[k],
                wav[start:start + melspec.SAMPLES_PER_EXAMPLE]
                .astype(np.float32))


def test_log_mel_examples_matches_host_oracle_within_2e5():
    """The jitted log-mel (f32 framing→|rfft|→mel matmul→log) vs the numpy
    f64 oracle over resampled audio: ≤ 2e-5 everywhere. The floor is the
    complex64 FFT's cancellation noise on high-dynamic-range spectra
    (~1.1e-5 worst observed on the noise+tone case; wire quantization and
    the HIGHEST-precision mel matmul each contribute < 1e-6); the quiet
    pure tone covers off-band bins near the log-offset floor."""
    from video_features_tpu.audio import melspec
    from video_features_tpu.ops.audio import log_mel_examples

    rng = np.random.default_rng(2)
    n = 44100 * 2 + 1234  # 44.1 kHz source: exercises the resample front half
    t = np.arange(n) / 44100.0
    cases = (
        0.1 * rng.standard_normal(n) + 0.5 * np.sin(2 * np.pi * 440 * t),
        0.01 * np.sin(2 * np.pi * 3000 * t),  # quiet pure tone
    )
    for wav in cases:
        host = melspec.waveform_to_examples(wav, 44100)
        slabs = melspec.waveform_to_pcm_slabs(wav, 44100)
        assert host.shape[0] == slabs.shape[0] > 0
        dev = np.asarray(log_mel_examples(jnp.asarray(slabs)))
        assert dev.shape == host.shape
        assert np.abs(dev - host).max() <= 2e-5


def test_vggish_device_preproc_embedding_parity(tmp_path):
    """End to end through the real VGG stack: --device_preproc embeddings
    track the host-DSP embeddings to float32-noise levels (the ≤1e-5 log-mel
    drift does not amplify through the conv stack)."""
    from scipy.io import wavfile

    from video_features_tpu.extractors.vggish import ExtractVGGish

    rng = np.random.default_rng(3)
    n = 16000 * 2  # 2 s at 16 kHz → 2 examples
    wav = (0.2 * rng.standard_normal(n)).clip(-1, 1)
    wav_path = str(tmp_path / "a.wav")
    wavfile.write(wav_path, 16000, (wav * 32767).astype(np.int16))

    host = ExtractVGGish(_cfg(tmp_path / "h", "vggish")).extract(wav_path)
    dev_ex = ExtractVGGish(_cfg(tmp_path / "d", "vggish",
                                device_preproc=True))
    dev = dev_ex.extract(wav_path)
    assert dev["vggish"].shape == host["vggish"].shape == (2, 128)
    np.testing.assert_allclose(dev["vggish"], host["vggish"],
                               atol=5e-4, rtol=0)
    # routing: the packed seam ships (N, 15600) raw PCM slots under the flag
    info, clips = dev_ex.pack_spec().open_clips(wav_path)
    rows = list(clips)
    assert rows and rows[0].shape == (15600,)


# ---- i3d: device edge resize ------------------------------------------------


def test_i3d_device_edge_resize_within_documented_tolerance():
    """device_edge_resize_hwc over a clip stack vs per-frame PIL: the same
    ≤ 2 uint8 levels max / ≤ 1 mean gate as resnet50's --device_resize, for
    both down- and up-scaling, with crop-free geometry (the i3d flow stream
    crops only after the flow net)."""
    from video_features_tpu.ops.image import (
        device_edge_resize_hwc, edge_resize_size, pil_edge_resize)

    rng = np.random.default_rng(5)
    for geom in ((37, 53), (20, 28)):  # downscale and upscale to edge 32
        stack = rng.integers(0, 256, (2, 4) + geom + (3,), dtype=np.uint8)
        host = np.stack([[pil_edge_resize(f, 32) for f in clip]
                         for clip in stack]).astype(np.float32)
        dev = np.asarray(device_edge_resize_hwc(jnp.asarray(stack), 32))
        ow, oh = edge_resize_size(geom[1], geom[0], 32)
        assert dev.shape == (2, 4, oh, ow, 3) and dev.dtype == np.float32
        diff = np.abs(host - dev)
        assert diff.max() <= 2.0, f"{geom}: max drift {diff.max()}"
        assert diff.mean() <= 1.0, f"{geom}: mean drift {diff.mean()}"


# ---- routing + notices ------------------------------------------------------


def test_device_preproc_routing_and_notices(tmp_path, capsys):
    """Every feature type supports the flag (raw host transforms where a
    device path exists, documented no-op for r21d), so no ignored-flag
    notice prints; the base-class notice still fires for a model that opts
    out; and --device_preproc implies resnet50's device resize."""
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.extractors.i3d import ExtractI3D
    from video_features_tpu.extractors.r21d import ExtractR21D
    from video_features_tpu.extractors.resnet import ExtractResNet50

    raw = np.random.default_rng(0).integers(
        0, 256, (30, 40, 3), dtype=np.uint8)
    rn = ExtractResNet50(_cfg(tmp_path / "rn", "resnet50",
                              device_preproc=True))
    assert rn._device_resize and rn._host_transform(raw) is raw
    i3 = ExtractI3D(_cfg(tmp_path / "i3", "i3d", streams=("rgb",),
                         i3d_pre_crop_size=64, i3d_crop_size=32,
                         device_preproc=True))
    assert i3._host_transform(raw) is raw
    i3_host = ExtractI3D(_cfg(tmp_path / "i3h", "i3d", streams=("rgb",),
                              i3d_pre_crop_size=64, i3d_crop_size=32))
    assert i3_host._host_transform(raw).shape[0] == 64  # smaller edge → 64
    ExtractR21D(_cfg(tmp_path / "r2", "r21d_rgb", device_preproc=True))
    ExtractFlow(_cfg(tmp_path / "fl", "pwc", batch_size=2,
                     device_preproc=True))
    assert "--device_preproc ignored" not in capsys.readouterr().out

    # the base-class notice fires for models without a device path
    class _OptedOut(ExtractFlow):
        supports_device_preproc = False

    _OptedOut(_cfg(tmp_path / "oo", "pwc", batch_size=2,
                   device_preproc=True))
    assert "--device_preproc ignored" in capsys.readouterr().out


def test_flow_window_stages_raw_geometry(tmp_path):
    """--device_preproc flow windows stage at the RAW decoded geometry (the
    staging ring keys by decode size, not the padded target) and dispatch
    through the per-pad-target step with the host keeping only the pad
    arithmetic for the final unpad."""
    from video_features_tpu.extractors.flow import ExtractFlow

    ex = ExtractFlow(_cfg(tmp_path, "raft", batch_size=2,
                          device_preproc=True))
    seen = {}

    def fake_step(params, dev):
        seen["shape"] = tuple(dev.shape)
        seen["dtype"] = str(dev.dtype)
        # the per-target step's contract: flow comes back at the PADDED target
        return jnp.zeros((dev.shape[0] - 1, 16, 24, 2), jnp.float32)

    ex._frames_step_for = lambda target, sharded: (
        seen.setdefault("target", (tuple(target), sharded)) and None
        or fake_step)
    window = list(np.random.default_rng(1).integers(
        0, 256, (3, 13, 17, 3), dtype=np.uint8))
    flow, n_pairs, pads = ex._dispatch_window(window)
    assert seen["shape"] == (3, 13, 17, 3)  # raw geometry on the wire
    assert seen["dtype"] == "uint8"
    assert seen["target"] == ((16, 24), False)  # /8 pad target, single-device
    assert n_pairs == 2 and pads == (1, 2, 3, 4)  # centered /8 split
    assert (3, 13, 17, 3) in {k[0] for k in ex._staging._rings}


def test_cache_key_resolution_for_device_preproc():
    """The keying decision, per model: fingerprints where the device
    preprocess drifts (i3d, vggish), folds into device_resize for resnet50,
    and never splits keys for the byte-exact (raft/pwc) or no-op (r21d)
    paths."""
    from video_features_tpu.cache.key import config_fingerprint

    def fp(ft, **kw):
        return config_fingerprint(ExtractionConfig(feature_type=ft, **kw))

    for ft in ("raft", "pwc", "r21d_rgb"):
        on, off = fp(ft, device_preproc=True), fp(ft)
        assert on["device_preproc"] is False and on == off, ft
    for ft in ("i3d", "vggish"):
        assert fp(ft, device_preproc=True) != fp(ft), ft
        assert fp(ft, device_preproc=True)["device_preproc"] is True
    # resnet50: one key component for both spellings
    assert (fp("resnet50", device_preproc=True)
            == fp("resnet50", device_resize=True))
    assert fp("resnet50", device_preproc=True)["device_resize"] is True
    assert fp("resnet50", device_preproc=True)["device_preproc"] is False
    assert fp("resnet50", device_preproc=True) != fp("resnet50")


# ---- model-level parity pins ------------------------------------------------


def test_raft_device_pad_byte_parity_per_video_and_packed(tmp_path):
    """The acceptance pin for flow: --device_preproc outputs are
    byte-identical to the host-pad path through the real RAFT net, in both
    the per-video loop and a packed run (which reuses the same per-target
    jit signature: raw (18, 30) input, (24, 32) pad target)."""
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.io.output import feature_output_dir

    # 30×18 frames: both axes off the /8 contract, so the pad is real
    corpus = [_write_video(tmp_path / f"v{i}.mp4", n, size=(30, 18),
                           seed=10 + i) for i, n in enumerate((4, 3))]

    def run(sub, **kw):
        cfg = ExtractionConfig(
            feature_type="raft", batch_size=2, num_devices=1,
            on_extraction="save_numpy",
            output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "tmp"),
            **kw)
        ex = ExtractFlow(cfg)
        assert ex.run(corpus) == len(corpus)
        return ex, {os.path.basename(f): np.load(f) for f in
                    glob.glob(str(tmp_path / sub / "raft" / "*.npy"))}

    _, host = run("host")
    dev_ex, dev = run("dev", device_preproc=True)
    assert set(host) == set(dev) and host
    for k in host:
        assert host[k].shape == dev[k].shape, k
        assert host[k].tobytes() == dev[k].tobytes(), k
    # packed run through the same instance: raw-wire pairs, same programs
    dev_ex.cfg = dev_ex.cfg.replace(pack_corpus=True,
                                    output_path=str(tmp_path / "devp"))
    dev_ex.output_dir = feature_output_dir(str(tmp_path / "devp"), "raft")
    assert dev_ex.run(corpus) == len(corpus)
    packed = {os.path.basename(f): np.load(f) for f in
              glob.glob(str(tmp_path / "devp" / "raft" / "*.npy"))}
    assert set(packed) == set(host)
    for k in host:
        assert host[k].tobytes() == packed[k].tobytes(), k
    # raw decode size keys the rings; the /8 target exists only on device
    assert any(k[0][1:3] == (18, 30) for k in dev_ex._staging._rings)


def test_resnet_device_preproc_paged_raw_wire(tmp_path):
    """resnet50 raw-wire frames now ride the PAGED dispatch path (the old
    per-model opt-out was overcautious — queues key by geometry, so pages
    never co-host mixed shapes): a mixed-geometry corpus under
    --device_preproc pages per-queue and matches the per-video loop to
    float32 ulp level. NOT byte-for-byte: pages run the forward at
    page_rows (≠ the per-video batch), and XLA makes no cross-shape bitwise
    guarantee for the f32 resize prologue — consistent with the flag's
    fingerprint classification (measured ~2e-7 relative; pinned 1e-5)."""
    from video_features_tpu.extractors.resnet import ExtractResNet50
    from video_features_tpu.io.output import feature_output_dir

    corpus = [_write_video(tmp_path / "a.mp4", 3, size=(24, 16), seed=1),
              _write_video(tmp_path / "b.mp4", 3, size=(16, 24), seed=2)]
    cfg = ExtractionConfig(
        feature_type="resnet50", batch_size=2, num_devices=1,
        on_extraction="save_numpy", device_preproc=True,
        output_path=str(tmp_path / "u"), tmp_path=str(tmp_path / "tmp"))
    ex = ExtractResNet50(cfg)
    assert ex.run(corpus) == len(corpus)
    ex.cfg = ex.cfg.replace(pack_corpus=True,
                            output_path=str(tmp_path / "p"))
    ex.output_dir = feature_output_dir(str(tmp_path / "p"), "resnet50")
    assert ex.run(corpus) == len(corpus)

    def load(sub):
        return {os.path.basename(f): np.load(f) for f in
                glob.glob(str(tmp_path / sub / "resnet50" / "*.npy"))}

    unpacked, packed = load("u"), load("p")
    assert set(unpacked) == set(packed) and unpacked
    for k in unpacked:
        u, p = unpacked[k], packed[k]
        assert u.shape == p.shape, k
        if "resnet50" in k:  # feature rows: ulp-level, not byte-for-byte
            scale = max(1.0, float(np.abs(u).max()))
            assert np.abs(u - p).max() <= 1e-5 * scale, k
        else:  # fps/timestamps sidecars stay byte-exact
            assert u.tobytes() == p.tobytes(), k
    # the paged path carried the raw-wire slots: one queue per raw geometry,
    # pages dispatched for both
    assert ex._pack_stats["pages_dispatched"] > 0
    assert len(ex._pack_stats["buckets"]) == 2  # (16,24) and (24,16) queues
