"""Degenerate inputs: videos shorter than a window must not crash.

Reference behavior: the per-video fault barrier hides most failures with a
print-and-continue; here short inputs are DEFINED — empty feature arrays with
correct trailing dimensions — so downstream tooling sees consistent shapes.
"""

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig


@pytest.fixture(autouse=True)
def _random_weights(monkeypatch):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")


@pytest.fixture(scope="module")
def tiny_video(tmp_path_factory):
    """3 frames, 64×48 — shorter than every clip window."""
    import cv2

    p = str(tmp_path_factory.mktemp("vid") / "tiny.mp4")
    w = cv2.VideoWriter(p, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, (64, 48))
    rng = np.random.default_rng(0)
    for _ in range(3):
        w.write(rng.integers(0, 256, (48, 64, 3), dtype=np.uint8))
    w.release()
    return p


@pytest.fixture(scope="module")
def one_frame_video(tmp_path_factory):
    import cv2

    p = str(tmp_path_factory.mktemp("vid1") / "one.mp4")
    w = cv2.VideoWriter(p, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, (64, 48))
    w.write(np.full((48, 64, 3), 128, np.uint8))
    w.release()
    return p


def _cfg(tmp_path, feature_type, **kw):
    return ExtractionConfig(
        feature_type=feature_type, num_devices=1,
        output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"), **kw,
    )


def test_i3d_video_shorter_than_stack(tmp_path, tiny_video):
    from video_features_tpu.extractors.i3d import ExtractI3D

    ex = ExtractI3D(_cfg(tmp_path, "i3d", streams=("rgb",), stack_size=16, step_size=16))
    feats = ex.extract(tiny_video)
    assert feats["rgb"].shape == (0, 1024)
    assert feats["timestamps_ms"].shape == (0,)


def test_r21d_video_shorter_than_clip(tmp_path, tiny_video):
    from video_features_tpu.extractors.r21d import ExtractR21D

    ex = ExtractR21D(_cfg(tmp_path, "r21d_rgb"))
    feats = ex.extract(tiny_video)
    assert feats["r21d_rgb"].shape == (0, 512)


def test_flow_single_frame_video(tmp_path, one_frame_video):
    """One frame → zero pairs → empty flow with the frame's geometry."""
    from video_features_tpu.extractors.flow import ExtractFlow

    ex = ExtractFlow(_cfg(tmp_path, "pwc", batch_size=4))
    feats = ex.extract(one_frame_video)
    assert feats["pwc"].shape[0] == 0
    assert feats["pwc"].ndim == 4


def test_resnet_tiny_video(tmp_path, tiny_video):
    """Frames still flow through resize→crop→features (3 frames < batch)."""
    from video_features_tpu.extractors.resnet import ExtractResNet50

    ex = ExtractResNet50(_cfg(tmp_path, "resnet50", batch_size=8))
    feats = ex.extract(tiny_video)
    assert feats["resnet50"].shape == (3, 2048)
    assert np.isfinite(feats["resnet50"]).all()
