"""End-to-end RAFT flow extraction on a real sample video (random weights, CPU)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.flow import ExtractFlow
from video_features_tpu.utils.windows import pair_batch_plan


@pytest.fixture(scope="module")
def extractor(tmp_path_factory):
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    out = tmp_path_factory.mktemp("out")
    cfg = ExtractionConfig(
        feature_type="raft",
        on_extraction="save_numpy",
        output_path=str(out),
        batch_size=16,
        side_size=128,  # keep CPU work bounded; exercises the resize path
        extraction_fps=4,
    )
    yield ExtractFlow(cfg)
    mp.undo()


def test_extract_sample(extractor, sample_video):
    feats = extractor.extract(sample_video)
    flow = feats["raft"]
    # 355 frames @19.62fps ≈ 18.1s → 4fps resample ≈ 72 frames → 71 pairs; the
    # native resampler may differ by ±1 frame from ffmpeg at the tail
    n = len(feats["timestamps_ms"])
    assert flow.shape == (n - 1, 2, 128, 170)
    assert 68 <= n - 1 <= 75
    assert flow.dtype == np.float32
    assert np.isfinite(flow).all()


def test_pair_batching_consistency(extractor):
    """Carried-frame batching must give identical flow to one big batch."""
    rng = np.random.default_rng(0)
    frames = rng.uniform(0, 255, (9, 64, 72, 3)).astype(np.float32)
    whole = extractor._run_pairs(frames)
    # emulate the decode loop with batch_size pairs per flush
    bs = 4
    parts = []
    for s, e in pair_batch_plan(len(frames), bs):
        parts.append(extractor._run_pairs(frames[s : e + 1]))
    chunked = np.concatenate(parts, axis=0)
    assert chunked.shape == whole.shape == (8, 2, 64, 72)
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-5)


def test_transfer_dtype_float16(extractor, tmp_path):
    """--transfer_dtype float16: fp32 .npy output within the documented
    quantization of the bit-parity path; async pending queue drains fully."""
    import os

    os.environ.setdefault("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    cfg = ExtractionConfig(
        feature_type="raft",
        output_path=str(tmp_path),
        batch_size=4,
        transfer_dtype="float16",
    )
    ex16 = ExtractFlow(cfg)
    rng = np.random.default_rng(1)
    frames = rng.uniform(0, 255, (9, 64, 72, 3)).astype(np.float32)
    # fp32 reference path (module extractor: batch 16, pads the 4-pair window)
    ref = extractor._run_pairs(frames[:5])
    out = ex16._run_pairs(frames[:5])
    assert out.dtype == np.float32
    scale = max(np.abs(ref).max(), 1.0)
    np.testing.assert_allclose(out, ref, atol=2e-3 * scale)


def test_pending_queue_multi_batch(tmp_path, sample_video):
    """Queue depth must not change results: extract() with many in-flight
    windows (prefetch_depth 3) is bit-identical to depth 1 — the double-
    buffered fetch must not reorder, drop, or double-collect windows.
    (Same batch size → same jitted program → exact equality.)"""
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    try:
        shallow = ExtractFlow(ExtractionConfig(
            feature_type="raft", output_path=str(tmp_path / "a"),
            batch_size=4, side_size=96, extraction_fps=2, prefetch_depth=1))
        deep = ExtractFlow(ExtractionConfig(
            feature_type="raft", output_path=str(tmp_path / "b"),
            batch_size=4, side_size=96, extraction_fps=2, prefetch_depth=3))
        fa = shallow.extract(sample_video)["raft"]
        fb = deep.extract(sample_video)["raft"]
        assert fa.shape == fb.shape and fa.shape[0] > 8  # several windows
        np.testing.assert_array_equal(fa, fb)
    finally:
        mp.undo()


def test_extract_sample_pwc(tmp_path, sample_video):
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    try:
        cfg = ExtractionConfig(
            feature_type="pwc",
            on_extraction="save_numpy",
            output_path=str(tmp_path),
            batch_size=16,
            side_size=128,
            extraction_fps=2,
        )
        ex = ExtractFlow(cfg)
        feats = ex.extract(sample_video)
        n = len(feats["timestamps_ms"])
        assert feats["pwc"].shape == (n - 1, 2, 128, 170)
        assert 30 <= n - 1 <= 40
        assert np.isfinite(feats["pwc"]).all()
    finally:
        mp.undo()


def test_flow_viz_wheel():
    from video_features_tpu.utils.flow_viz import flow_to_image, make_colorwheel

    wheel = make_colorwheel()
    assert wheel.shape == (55, 3)
    assert wheel.max() <= 255 and wheel.min() >= 0
    flow = np.zeros((4, 5, 2), np.float32)
    flow[..., 0] = 1.0
    img = flow_to_image(flow)
    assert img.shape == (4, 5, 3) and img.dtype == np.uint8
    # pure rightward flow → angle π → single uniform color
    assert (img == img[0, 0]).all()


def test_raft_on_demand_corr_through_extractor(tmp_path):
    """--raft_corr on_demand plumbs through ExtractFlow and matches the volume
    path (same numerics up to fp reduction order, amplified by 20 iterations)."""
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    try:
        rng = np.random.default_rng(2)
        frames = rng.uniform(0, 255, (5, 64, 72, 3)).astype(np.float32)
        kw = dict(feature_type="raft", batch_size=4, output_path=str(tmp_path / "o"),
                  tmp_path=str(tmp_path / "t"), num_devices=1)
        vol = ExtractFlow(ExtractionConfig(**kw))
        ond = ExtractFlow(ExtractionConfig(raft_corr="on_demand", **kw))
        f_vol = vol._run_pairs(frames)
        f_ond = ond._run_pairs(frames)
        assert f_ond.shape == f_vol.shape == (4, 2, 64, 72)
        np.testing.assert_allclose(f_ond, f_vol, rtol=5e-2, atol=5e-2)
    finally:
        mp.undo()


def test_show_pred_saves_viz_headless(tmp_path):
    """--show_pred on a headless host writes frame+flow PNGs next to outputs."""
    import os

    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    mp.delenv("DISPLAY", raising=False)
    mp.delenv("WAYLAND_DISPLAY", raising=False)
    try:
        cfg = ExtractionConfig(
            feature_type="pwc", batch_size=2, show_pred=True, num_devices=1,
            output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"),
        )
        ex = ExtractFlow(cfg)
        frames = np.random.default_rng(0).uniform(0, 255, (3, 64, 64, 3)).astype(np.float32)
        flow = ex._run_pairs(frames)
        ex._show(frames[:-1], flow, "/videos/clip.mp4")
        viz = ex.output_dir + "_viz"
        pngs = sorted(os.listdir(viz))
        assert pngs == ["clip_00000.png", "clip_00001.png"]
    finally:
        mp.undo()


def test_shape_bucket_bounds_compiles(tmp_path, monkeypatch):
    """--shape_bucket 64: two different frame geometries pad into ONE bucket →
    one compiled program; outputs keep the original (unpadded) shapes."""
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    cfg = ExtractionConfig(
        feature_type="raft", output_path=str(tmp_path / "o"),
        tmp_path=str(tmp_path / "t"), batch_size=2, shape_bucket=64)
    ex = ExtractFlow(cfg)
    rng = np.random.default_rng(0)
    flow_a = ex._run_pairs(rng.uniform(0, 255, (3, 40, 56, 3)).astype(np.float32))
    flow_b = ex._run_pairs(rng.uniform(0, 255, (3, 48, 34, 3)).astype(np.float32))
    assert flow_a.shape == (2, 2, 40, 56)
    assert flow_b.shape == (2, 2, 48, 34)
    # both geometries hit the 64x64 bucket → ONE compiled program on the
    # routed step (the encode-once sharded step on this default 8-device mesh)
    assert ex._frames_step_sharded._cache_size() == 1


def test_shape_bucket_validation():
    with pytest.raises(ValueError, match="shape_bucket"):
        ExtractionConfig(feature_type="raft", shape_bucket=12).validate()
