"""JAX PWC-Net numerical parity vs a torch functional mirror (random weights)."""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import jax
import jax.numpy as jnp
import torch

from torch_mirrors import _pwc_corr, _pwc_warp, pwc_random_state_dict, pwc_torch_forward
from video_features_tpu.models.pwc import (
    correlation_81,
    pwc_forward,
    pwc_init_params,
)
from video_features_tpu.ops.warp import warp_backward
from video_features_tpu.weights.convert_torch import convert_pwc


@pytest.fixture(scope="module")
def converted():
    sd = pwc_random_state_dict(seed=11)
    return sd, convert_pwc(sd)


def test_param_tree_matches_init_structure(converted):
    _, params = converted
    init = pwc_init_params(seed=0)
    p1 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
    p2 = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(init)[0]}
    assert p1 == p2


def test_correlation_matches_torch():
    rng = np.random.default_rng(0)
    f1 = rng.standard_normal((2, 10, 12, 7)).astype(np.float32)
    f2 = rng.standard_normal((2, 10, 12, 7)).astype(np.float32)
    ref = _pwc_corr(torch.from_numpy(f1).permute(0, 3, 1, 2),
                    torch.from_numpy(f2).permute(0, 3, 1, 2)).permute(0, 2, 3, 1).numpy()
    out = np.asarray(correlation_81(jnp.asarray(f1), jnp.asarray(f2)))
    assert out.shape == (2, 10, 12, 81)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_warp_matches_torch():
    rng = np.random.default_rng(1)
    img = rng.standard_normal((2, 8, 9, 5)).astype(np.float32)
    flow = (rng.standard_normal((2, 8, 9, 2)) * 2).astype(np.float32)
    ref = _pwc_warp(torch.from_numpy(img).permute(0, 3, 1, 2),
                    torch.from_numpy(flow).permute(0, 3, 1, 2)).permute(0, 2, 3, 1).numpy()
    out = np.asarray(warp_backward(jnp.asarray(img), jnp.asarray(flow)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_flow_parity(converted):
    sd, params = converted
    rng = np.random.default_rng(0)
    # non-/64 size exercises both bilinear resizes (in and out)
    img1 = rng.uniform(0, 255, (1, 96, 120, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 96, 120, 3)).astype(np.float32)
    ref = pwc_torch_forward(
        sd, torch.from_numpy(img1).permute(0, 3, 1, 2), torch.from_numpy(img2).permute(0, 3, 1, 2)
    ).permute(0, 2, 3, 1).numpy()
    out = np.asarray(pwc_forward(params, jnp.asarray(img1), jnp.asarray(img2)))
    assert out.shape == ref.shape == (1, 96, 120, 2)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=2e-3)
    cos = np.sum(out * ref) / (np.linalg.norm(out) * np.linalg.norm(ref))
    assert cos > 1 - 1e-5


def test_pwc_forward_onehot_warp_matches_default(converted, monkeypatch):
    """Whole-model guard for VFT_WARP_IMPL=onehot: the MXU selector warp must
    reproduce the gather-warp forward through all five decoder levels (the
    lowering the production `auto` path would take if the default flips)."""
    _, params = converted
    rng = np.random.default_rng(2)
    img1 = rng.uniform(0, 255, (1, 96, 128, 3)).astype(np.float32)
    img2 = rng.uniform(0, 255, (1, 96, 128, 3)).astype(np.float32)
    ref = np.asarray(pwc_forward(params, jnp.asarray(img1), jnp.asarray(img2)))
    monkeypatch.setenv("VFT_WARP_IMPL", "onehot")
    out = np.asarray(pwc_forward(params, jnp.asarray(img1), jnp.asarray(img2)))
    # per-op drift is ≤1 ulp; five decoder levels + the 20× output scaling
    # amplify it — bound well under a hundredth of a pixel
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=5e-3)
