"""Fast direct tests of the TPU kernel lowerings (default test subset).

Covers the production paths a slow-marked file would hide from the default
run: the spatially tiled Pallas cost-volume kernel (interpret mode) and the
bf16 TapConv3D lowering every bf16 I3D conv takes.
"""
# fast-registry: default tier — kernel parity vs torch mirrors

import numpy as np

import jax
import jax.numpy as jnp

def test_corr81_pallas_tiled_matches_xla():
    """The spatially tiled kernel (interpret mode on CPU) must match the XLA
    formulation at sizes beyond the 16² single-block cap, including non-/16
    sizes exercising the pad-and-slice path."""
    from video_features_tpu.ops.pallas_corr import corr81_pallas_tiled, corr81_xla

    rng = np.random.default_rng(7)
    for h, w, c in ((32, 32, 8), (24, 40, 4), (18, 23, 5)):
        f1 = jnp.asarray(rng.standard_normal((2, h, w, c)).astype(np.float32))
        f2 = jnp.asarray(rng.standard_normal((2, h, w, c)).astype(np.float32))
        ref = np.asarray(corr81_xla(f1, f2))
        out = np.asarray(corr81_pallas_tiled(f1, f2, interpret=True))
        assert out.shape == ref.shape
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_tap_conv3d_matches_direct_conv():
    """The bf16 tap lowering must equal nn.Conv's conv3d (same TF-SAME pads);
    checked in fp32 where equality is tight (bf16 only reassociates further)."""
    import flax.linen as fnn

    from video_features_tpu.models.layers import TapConv3D, tf_same_pads

    rng = np.random.default_rng(3)
    for kernel, stride in (((7, 7, 7), (2, 2, 2)), ((3, 3, 3), (1, 1, 1)),
                           ((1, 1, 1), (1, 1, 1))):
        x = jnp.asarray(rng.standard_normal((2, 8, 12, 12, 4)).astype(np.float32))
        tap = TapConv3D(6, kernel, stride, dtype=jnp.float32)
        params = tap.init(jax.random.PRNGKey(0), x)
        out = tap.apply(params, x)
        kern = params["params"]["kernel"]
        ref = fnn.Conv(6, kernel, strides=stride,
                       padding=tf_same_pads(kernel, stride), use_bias=False,
                       dtype=jnp.float32).apply({"params": {"kernel": kern}}, x)
        assert out.shape == ref.shape
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_tap_fp32_flag_routes_joint_extent_only(monkeypatch):
    """VFT_I3D_TAP_FP32=1: fp32 convs with joint spatio-temporal extent take
    the tap lowering (same numerics to ~1e-6); factored kernels stay direct."""
    import flax.linen as fnn

    from video_features_tpu.models.layers import TapConv3D, conv3d_module

    monkeypatch.setenv("VFT_I3D_TAP_FP32", "1")
    pads = ((1, 1), (1, 1), (1, 1))
    joint = conv3d_module(6, (3, 3, 3), (1, 1, 1), pads, jnp.float32, "c")
    assert isinstance(joint, TapConv3D)
    factored = conv3d_module(6, (3, 1, 1), (1, 1, 1),
                             ((1, 1), (0, 0), (0, 0)), jnp.float32, "c")
    assert isinstance(factored, fnn.Conv)
    monkeypatch.delenv("VFT_I3D_TAP_FP32")
    off = conv3d_module(6, (3, 3, 3), (1, 1, 1), pads, jnp.float32, "c")
    assert isinstance(off, fnn.Conv)

    # full-model numerics under the flag: same params, ~fp32-tight agreement
    monkeypatch.setenv("VFT_I3D_TAP_FP32", "1")
    from video_features_tpu.models.i3d import I3D

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.uniform(-1, 1, (1, 16, 32, 32, 3)).astype(np.float32))
    model = I3D(modality="rgb")
    params = model.init(jax.random.PRNGKey(0), x, features=True)
    tap_out = np.asarray(model.apply(params, x, features=True))
    monkeypatch.delenv("VFT_I3D_TAP_FP32")
    ref_out = np.asarray(model.apply(params, x, features=True))
    np.testing.assert_allclose(tap_out, ref_out, rtol=1e-4, atol=1e-5)


def test_tap_conv3d_explicit_pads_match_direct_conv():
    """The explicit-padding branch (torch-style R21D pads, incl. asymmetric)
    at the tight kernel-level tolerance — the end-to-end 5% feature test could
    absorb a boundary-only lo/hi swap."""
    import flax.linen as fnn

    from video_features_tpu.models.layers import TapConv3D

    rng = np.random.default_rng(5)
    cases = (
        ((1, 7, 7), (1, 2, 2), ((0, 0), (3, 3), (3, 3))),  # r21d stem
        ((3, 1, 1), (2, 1, 1), ((1, 1), (0, 0), (0, 0))),  # strided temporal
        ((3, 3, 3), (1, 1, 1), ((0, 1), (1, 2), (2, 0))),  # asymmetric pads
    )
    for kernel, stride, pads in cases:
        x = jnp.asarray(rng.standard_normal((2, 7, 13, 13, 4)).astype(np.float32))
        tap = TapConv3D(6, kernel, stride, dtype=jnp.float32, padding=pads)
        params = tap.init(jax.random.PRNGKey(1), x)
        out = tap.apply(params, x)
        kern = params["params"]["kernel"]
        ref = fnn.Conv(6, kernel, strides=stride, padding=pads, use_bias=False,
                       dtype=jnp.float32).apply({"params": {"kernel": kern}}, x)
        assert out.shape == ref.shape, (kernel, stride, pads)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_i3d_bf16_tap_path_close_to_fp32():
    """dtype=bfloat16 now routes convs through TapConv3D; features must stay
    near the fp32 model (same params)."""
    from video_features_tpu.models.i3d import I3D
    from video_features_tpu.weights.store import random_params_like

    m32 = I3D(modality="rgb", dtype=jnp.float32)
    mbf = I3D(modality="rgb", dtype=jnp.bfloat16)
    x = jnp.asarray(np.random.default_rng(4).uniform(-1, 1, (1, 16, 64, 64, 3))
                    .astype(np.float32))
    p = random_params_like(lambda r, d: m32.init(r, d, features=True),
                           jax.random.PRNGKey(0), x)["params"]
    f32 = np.asarray(m32.apply({"params": p}, x, features=True))
    fbf = np.asarray(mbf.apply({"params": p}, x, features=True))
    scale = np.abs(f32).max() + 1e-6
    assert np.abs(f32 - fbf).max() <= 0.05 * scale


def test_resolve_corr_impl_auto_switches_on_volume_size(monkeypatch):
    from video_features_tpu.models.raft import resolve_corr_impl

    # ambient escape-hatch exports must not leak into these assertions
    monkeypatch.delenv("VFT_RAFT_ON_DEMAND_IMPL", raising=False)
    # 16 pairs at 256²: pyramid 16·(32·32)²·4 B·1.328 ≈ 89 MB → volume
    assert resolve_corr_impl("auto", 16, 256, 256) == "volume"
    # 16 pairs at 1080p: 16·(135·240)²·4 B·1.328 ≈ 89 GB — several times
    # HBM; the GATHER on-demand path is the big-frame default (ADVICE r5:
    # the matmul remat's FLOPs scale with frame area and its win was only
    # measured at 64×64 on CPU), with the env escape hatch opting into the
    # remat once a committed 1080p TPU sweep justifies the flip
    assert resolve_corr_impl("auto", 16, 1080, 1920) == "on_demand"
    monkeypatch.setenv("VFT_RAFT_ON_DEMAND_IMPL", "matmul")
    assert resolve_corr_impl("auto", 16, 1080, 1920) == "on_demand_matmul"
    monkeypatch.delenv("VFT_RAFT_ON_DEMAND_IMPL")
    # explicit choices pass through untouched
    for impl in ("volume", "volume_gather", "on_demand", "on_demand_matmul"):
        assert resolve_corr_impl(impl, 16, 1080, 1920) == impl
    # bf16 halves the volume: a geometry just past the fp32 budget fits
    monkeypatch.setenv("VFT_RAFT_VOLUME_BUDGET", str(16 * (32 * 32) ** 2 * 4))
    assert resolve_corr_impl("auto", 16, 256, 256) == "on_demand"
    # mesh-sharded step: the budget is per DEVICE — 8 devices hold 2 pairs
    # each, so the same global batch fits (advisor round-3 finding)
    assert resolve_corr_impl("auto", 16, 256, 256, n_devices=8) == "volume"
    assert resolve_corr_impl("auto", 16, 256, 256, jnp.bfloat16) == "volume"


def test_raft_forward_accepts_auto():
    from video_features_tpu.models.raft import raft_forward, raft_init_params

    rng = np.random.default_rng(9)
    params = raft_init_params(0)
    x1 = jnp.asarray(rng.uniform(0, 255, (1, 32, 40, 3)).astype(np.float32))
    x2 = jnp.asarray(rng.uniform(0, 255, (1, 32, 40, 3)).astype(np.float32))
    auto = raft_forward(params, x1, x2, iters=2, corr_impl="auto")
    vol = raft_forward(params, x1, x2, iters=2, corr_impl="volume")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(vol))


def test_r21d_bf16_close_to_fp32():
    """R(2+1)D bf16 (direct conv3d — its factored convs are NOT hit by the
    conv3d-bf16 pathology, and the tap lowering measured slower there; see
    models/r21d.py::_conv3d) must stay near the fp32 model on shared params."""
    from video_features_tpu.models.r21d import R2Plus1D18
    from video_features_tpu.weights.store import random_params_like

    m32 = R2Plus1D18(dtype=jnp.float32)
    mbf = R2Plus1D18(dtype=jnp.bfloat16)
    x = jnp.asarray(np.random.default_rng(8).uniform(-2, 2, (1, 4, 56, 56, 3))
                    .astype(np.float32))
    p = random_params_like(lambda r, d: m32.init(r, d, features=True),
                           jax.random.PRNGKey(0), x)["params"]
    f32 = np.asarray(m32.apply({"params": p}, x, features=True))
    fbf = np.asarray(mbf.apply({"params": p}, x, features=True))
    scale = np.abs(f32).max() + 1e-6
    assert np.abs(f32 - fbf).max() <= 0.05 * scale


def test_warp_onehot_matches_gather():
    """MXU one-hot selector warp == gather warp (ops/warp.bilinear_sample_onehot):
    same zero-padding semantics (OOB taps fall off the iota), ≤ 1-ulp fp
    association differences, incl. far-OOB flows and edge-exact coords."""
    from video_features_tpu.ops.warp import (bilinear_sample, bilinear_sample_onehot, warp_backward)

    rng = np.random.default_rng(3)
    img = rng.standard_normal((2, 11, 15, 6)).astype(np.float32)
    flow = (rng.uniform(-12, 12, (2, 11, 15, 2))).astype(np.float32)
    ref = np.asarray(warp_backward(jnp.asarray(img), jnp.asarray(flow), impl="gather"))
    out = np.asarray(warp_backward(jnp.asarray(img), jnp.asarray(flow), impl="onehot"))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # raw sampler: edge-exact + OOB coords, and the chunked path (chunk < P)
    coords = rng.uniform(-4, 18, (2, 5, 7, 2)).astype(np.float32)
    coords[0, 0, 0] = [0.0, 0.0]
    coords[0, 0, 1] = [14.0, 10.0]   # exact far corner
    coords[0, 0, 2] = [-1.0, -1.0]   # fully OOB → 0
    a = np.asarray(bilinear_sample(jnp.asarray(img), jnp.asarray(coords)))
    b = np.asarray(bilinear_sample_onehot(jnp.asarray(img), jnp.asarray(coords),
                                          chunk_budget=15 * 6 * 3))
    np.testing.assert_allclose(b, a, rtol=1e-5, atol=1e-6)


def test_warp_onehot_bf16_within_budget():
    """bf16 one-hot warp error vs the fp32 gather path stays within ~2× the
    bf16 VALUE-rounding floor (selector-weight rounding adds ~0.4%·|v|);
    the keep-mask is fp32 closed-form, so no spurious border zeroing."""
    from video_features_tpu.ops.warp import warp_backward

    rng = np.random.default_rng(4)
    img = rng.standard_normal((2, 16, 16, 8)).astype(np.float32)
    flow = rng.uniform(-5, 5, (2, 16, 16, 2)).astype(np.float32)
    ref = np.asarray(warp_backward(jnp.asarray(img), jnp.asarray(flow), impl="gather"))
    out = np.asarray(warp_backward(jnp.asarray(img).astype(jnp.bfloat16),
                                   jnp.asarray(flow), impl="onehot"))
    # identical zero-set (mask parity) and bounded value drift
    np.testing.assert_array_equal(out == 0, np.abs(ref) < 1e-7)
    np.testing.assert_allclose(out, ref, rtol=0.02, atol=0.02)


def test_raft_on_demand_matmul_matches_gather():
    """The gather-free on-demand lookup (per-iteration MXU volume remat +
    one-hot window selection, models/raft._lookup_on_demand impl='matmul')
    must match the gather formulation, incl. OOB windows and the chunked
    query path (chunk < H·W)."""
    from video_features_tpu.models.raft import (
        _build_f2_pyramid, _lookup_on_demand, coords_grid)

    rng = np.random.default_rng(5)
    b, h, w, d = 2, 16, 24, 12
    f1 = jnp.asarray(rng.standard_normal((b, h, w, d)).astype(np.float32))
    f2 = jnp.asarray(rng.standard_normal((b, h, w, d)).astype(np.float32))
    pyr = _build_f2_pyramid(f2)
    # coords: grid + big random flow so plenty of windows leave the image
    coords = coords_grid(b, h, w) + jnp.asarray(
        rng.uniform(-10, 10, (b, h, w, 2)).astype(np.float32))
    ref = np.asarray(_lookup_on_demand(f1, pyr, coords, "gather"))
    out = np.asarray(_lookup_on_demand(f1, pyr, coords, "matmul"))
    assert out.shape == ref.shape == (b, h, w, 4 * 81)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
    # forced tiny chunks exercise the scan + tail-pad path
    out_c = np.asarray(_lookup_on_demand(f1, pyr, coords, "matmul",
                                         chunk_budget=h * w * 7))
    np.testing.assert_allclose(out_c, ref, rtol=1e-4, atol=1e-4)
