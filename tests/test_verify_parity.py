"""The real-weights parity runbook must keep working without checkpoints:
its --self_test mode runs the identical convert→mirror-compare pipeline on
seeded mirror weights (tools/verify_parity.py; VERDICT r3 Missing #2)."""

import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


def test_self_test_subset_passes(capsys):
    from tools.verify_parity import run

    rc = run(self_test=True, models=["resnet50", "pwc-sintel"])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.count("PASS") == 2


def test_missing_checkpoints_lists_what_to_supply(tmp_path, capsys):
    from tools.verify_parity import EXPECTED_FILES, run

    rc = run(ckpt_dir=str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 0  # missing is SKIPPED, not failure
    assert "No checkpoints found" in out
    for model in EXPECTED_FILES:
        assert model in out


def test_real_checkpoint_file_roundtrip(tmp_path, capsys):
    """A state dict saved to an expected filename is picked up, converted via
    the production converter, and verified — the with-checkpoints code path,
    exercised with seeded mirror weights standing in for the real blob."""
    import torch

    from tools.torch_mirrors import pwc_random_state_dict
    from tools.verify_parity import run

    torch.save(pwc_random_state_dict(seed=3), tmp_path / "network-default.pytorch")
    rc = run(ckpt_dir=str(tmp_path), models=["pwc-sintel"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "PASS" in out
