"""Telemetry subsystem (docs/observability.md): journal discipline (bounded,
drops counted, span pairing), metrics registry + Prometheus exposition,
Chrome-trace export, and the acceptance path — a two-tenant daemon whose
journal exports a complete admitted→done span chain per request with
stats/metrics-op latency histograms consistent with the journal."""

import json
import os
import threading

import numpy as np
import pytest

from test_packer import ToyPacked, _write_video

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.obs import Histogram, MetricsRegistry, SpanJournal
from video_features_tpu.obs.export import (
    load_journal,
    main as export_main,
    to_chrome_trace,
)
from video_features_tpu.reliability import reset_faults
from video_features_tpu.serve import ExtractionService


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("obs_corpus")
    return [_write_video(d / f"vid{i}.mp4", n)
            for i, n in enumerate((3, 5, 9, 2))]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    if kw.get("serve"):
        kw.setdefault("spool_dir", str(tmp_path / sub / "spool"))
        kw.setdefault("idle_flush_sec", 0.0)
        os.makedirs(kw["spool_dir"], exist_ok=True)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


def _events_by_name(events):
    by = {}
    for e in events:
        by.setdefault(e["event"], []).append(e)
    return by


# ---- journal discipline ----------------------------------------------------


def test_journal_writes_jsonl_with_open_close_records(tmp_path):
    j = SpanJournal(str(tmp_path / "e.jsonl"))
    assert j.emit("hello", video="/v", skipped_none=None)
    with j.span("work", video="/v") as sid:
        pass
    j.close()
    events, corrupt = load_journal(j.path)
    assert corrupt == 0
    names = [e["event"] for e in events]
    assert names[0] == "journal_open" and names[-1] == "journal_close"
    assert "wall" in events[0] and events[-1]["dropped"] == 0
    hello = next(e for e in events if e["event"] == "hello")
    assert hello["video"] == "/v" and "skipped_none" not in hello
    start = next(e for e in events if e["event"] == "work_start")
    end = next(e for e in events if e["event"] == "work_end")
    assert start["span"] == end["span"] == sid
    assert end["ts"] >= start["ts"]
    # timestamps are monotone within the journal
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts)


def test_journal_bounded_queue_drops_and_counts(tmp_path):
    """A stalled writer must never block the hot path: past the bound,
    emits drop and the close record says how many."""
    j = SpanJournal(str(tmp_path / "e.jsonl"), capacity=4, autostart=False)
    for i in range(10):
        j.emit("x", i=i)
    assert j.emitted == 4 and j.dropped == 6
    j.close()  # starts the writer, drains the backlog, appends the summary
    events, _ = load_journal(j.path)
    assert sum(1 for e in events if e["event"] == "x") == 4
    assert events[-1]["event"] == "journal_close"
    assert events[-1]["dropped"] == 6 and events[-1]["emitted"] == 4
    assert j.stats()["written"] == 6  # open + 4 + close


def test_journal_emit_is_thread_safe(tmp_path):
    j = SpanJournal(str(tmp_path / "e.jsonl"), capacity=10000)
    threads = [threading.Thread(
        target=lambda t=t: [j.emit("tick", t=t) for _ in range(500)])
        for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    j.close()
    assert j.emitted + j.dropped == 2000
    events, corrupt = load_journal(j.path)
    assert corrupt == 0
    assert sum(1 for e in events if e["event"] == "tick") == j.emitted


def test_journal_emit_after_close_is_a_noop(tmp_path):
    j = SpanJournal(str(tmp_path / "e.jsonl"))
    j.close()
    assert j.emit("late") is False
    events, _ = load_journal(j.path)
    assert all(e["event"] != "late" for e in events)


def test_journal_unwritable_path_degrades_to_counted_errors(tmp_path,
                                                            capsys):
    j = SpanJournal(str(tmp_path / "nope" / "x" / "e.jsonl"))
    # the parent dirs were created; sabotage by pointing at a directory
    j2 = SpanJournal(str(tmp_path))  # path IS a directory: open fails
    j2.emit("x")
    j2.close()
    assert j2.stats()["write_errors"] >= 1
    j.close()


# ---- registry --------------------------------------------------------------


def test_registry_counters_gauges_and_prometheus_text():
    r = MetricsRegistry()
    r.inc("videos_ok_total", model="resnet50")
    r.inc("videos_ok_total", 2, model="resnet50")
    r.set_gauge("queue_depth", 5, tenant="a")
    for v in (0.01, 0.2, 3.0):
        r.observe("e2e_latency_seconds", v, tenant="a", model="m")
    assert r.counter_value("videos_ok_total", model="resnet50") == 3
    snap = r.snapshot()
    assert {"counters", "gauges", "histograms"} <= set(snap)
    hist = snap["histograms"][0]
    assert hist["count"] == 3 and hist["buckets"][-1][0] == "+Inf"
    text = r.prometheus_text()
    assert '# TYPE vft_videos_ok_total counter' in text
    assert 'vft_queue_depth{tenant="a"} 5' in text
    assert 'vft_e2e_latency_seconds_count{model="m",tenant="a"} 3' in text
    assert 'le="+Inf"} 3' in text


def test_prometheus_escapes_client_supplied_label_values():
    """Tenant names are arbitrary client strings; a quote/backslash/newline
    in one must not corrupt the whole exposition for every tenant."""
    r = MetricsRegistry()
    r.set_gauge("queue_depth", 1, tenant='evil"name\\x\nboom')
    text = r.prometheus_text()
    line = next(ln for ln in text.splitlines() if ln.startswith("vft_queue"))
    assert line == 'vft_queue_depth{tenant="evil\\"name\\\\x\\nboom"} 1'
    assert "\nboom" not in text  # the newline never splits a line


def test_prometheus_counters_render_full_precision():
    """%g would quantize a long-lived daemon's monotone counter to 6
    significant digits — past 1e6 it would read frozen between 10-unit
    quanta and rate() over the exposition would show zero-then-burst."""
    r = MetricsRegistry()
    r.inc("stage_seconds_total", 1000001.5, stage="decode")
    r.observe("e2e_latency_seconds", 1000001.5, tenant="a")
    text = r.prometheus_text()
    assert "vft_stage_seconds_total" in text and "1000001.5" in text
    assert 'vft_e2e_latency_seconds_sum{tenant="a"} 1000001.5' in text
    assert "1e+06" not in text


def test_registry_summaries_roll_up_per_label_set():
    r = MetricsRegistry()
    for v in (0.1, 0.2):
        r.observe("e2e_latency_seconds", v, tenant="a", model="m")
    r.observe("e2e_latency_seconds", 9.0, tenant="b", model="m")
    summaries = {s["labels"]["tenant"]: s
                 for s in r.summaries("e2e_latency_seconds")}
    assert summaries["a"]["count"] == 2 and summaries["b"]["count"] == 1
    assert summaries["a"]["p99"] <= 0.25 and summaries["b"]["p50"] > 5.0


# ---- export ----------------------------------------------------------------


def _mk(ts, event, **fields):
    return {"ts": ts, "event": event, **fields}


def test_export_derives_lifecycle_and_request_spans():
    events = [
        _mk(0.0, "request_admitted", request="r1", tenant="a"),
        _mk(0.1, "video_queued", video="/v1", request="r1", tenant="a"),
        _mk(0.2, "video_popped", video="/v1", request="r1"),
        _mk(0.3, "extract_start", span=7, video="/v1"),
        _mk(0.9, "extract_end", span=7, video="/v1"),
        _mk(1.0, "video_done", video="/v1"),
        _mk(1.1, "request_done", request="r1", state="done"),
    ]
    trace = to_chrome_trace(events)
    xs = {e["name"]: e for e in trace["traceEvents"] if e.get("ph") == "X"}
    assert set(xs) == {"queue_wait", "process", "extract", "request"}
    assert xs["queue_wait"]["dur"] == pytest.approx(1e5, rel=0.01)
    assert xs["extract"]["dur"] == pytest.approx(6e5, rel=0.01)
    assert xs["request"]["dur"] == pytest.approx(1.1e6, rel=0.01)
    # instants keep every milestone visible even when unpaired
    instants = {e["name"] for e in trace["traceEvents"]
                if e.get("ph") == "i"}
    assert "video_done" in instants
    # thread_name metadata labels the tracks
    tracks = {e["args"]["name"] for e in trace["traceEvents"]
              if e.get("ph") == "M"}
    assert "/v1" in tracks and "request r1" in tracks


def test_export_requeue_restarts_queue_wait_and_failed_closes_process():
    events = [
        _mk(0.0, "video_queued", video="/v"),
        _mk(0.1, "video_popped", video="/v"),
        _mk(0.2, "video_requeued", video="/v"),
        _mk(0.5, "video_popped", video="/v"),
        _mk(0.6, "video_failed", video="/v", error_class="DecodeError"),
    ]
    trace = to_chrome_trace(events)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    waits = sorted(e["dur"] for e in xs if e["name"] == "queue_wait")
    assert waits == [pytest.approx(1e5, rel=0.01),
                     pytest.approx(3e5, rel=0.01)]
    proc = [e for e in xs if e["name"] == "process"]
    assert len(proc) == 1 and proc[0]["args"]["state"] == "video_failed"


def test_export_never_pairs_spans_across_journal_sessions():
    """The journal accumulates across runs (append mode) and span ids
    restart per session: a run killed mid-span leaves its start UNPAIRED —
    it must not pair with an unrelated later session's end, nor may two
    different span names share an id within a session."""
    events = [
        _mk(0.0, "journal_open", wall=100.0),
        _mk(0.1, "decode_start", span=7, video="/v1"),  # killed mid-decode
        _mk(5.0, "journal_open", wall=200.0),           # next run, ids reset
        _mk(5.1, "extract_start", span=7, video="/v2"),
        _mk(5.4, "extract_end", span=7, video="/v2"),
    ]
    trace = to_chrome_trace(events)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert [e["name"] for e in xs] == ["extract"]
    assert xs[0]["dur"] == pytest.approx(3e5, rel=0.01)
    assert trace["otherData"]["unpaired_spans"] == 0  # cleared per session
    # same-session id collision across NAMES also never pairs
    mixed = [
        _mk(0.0, "decode_start", span=3, video="/a"),
        _mk(0.5, "extract_end", span=3, video="/b"),
    ]
    assert not [e for e in to_chrome_trace(mixed)["traceEvents"]
                if e.get("ph") == "X"]


def test_export_cli_writes_parseable_trace(tmp_path, capsys):
    j = SpanJournal(str(tmp_path / "events.jsonl"))
    with j.span("decode", video="/v"):
        pass
    j.close()
    out = str(tmp_path / "trace.json")
    assert export_main([j.path, "-o", out]) == 0
    with open(out) as f:
        trace = json.load(f)
    assert any(e.get("ph") == "X" and e["name"] == "decode"
               for e in trace["traceEvents"])
    assert "perfetto" in capsys.readouterr().out
    # a directory argument resolves to its events.jsonl
    assert export_main([str(tmp_path), "-o", out]) == 0


def test_export_skips_corrupt_lines(tmp_path):
    p = str(tmp_path / "events.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"ts": 0.0, "event": "a"}) + "\n")
        f.write("{torn line\n")
        # valid JSON but a non-numeric ts: would crash the ts sort if it
        # slipped through — it is a corrupt line too, counted not fatal
        f.write(json.dumps({"ts": "1.5", "event": "bad"}) + "\n")
        f.write(json.dumps({"ts": True, "event": "bad2"}) + "\n")
        f.write(json.dumps({"ts": 1.0, "event": "b"}) + "\n")
    events, corrupt = load_journal(p)
    assert [e["event"] for e in events] == ["a", "b"] and corrupt == 3


# ---- batch loops journal (--telemetry_dir without --serve) -----------------


@pytest.mark.parametrize("pack", [False, True])
def test_batch_run_journals_per_video_lifecycle(tmp_path, corpus, pack):
    sub = f"batch_{'packed' if pack else 'loop'}"
    ex = ToyPacked(_cfg(tmp_path, sub, pack_corpus=pack,
                        telemetry_dir=str(tmp_path / sub / "tel")))
    assert ex.run(corpus) == len(corpus)
    assert ex._journal is not None and ex._journal.closed
    events, corrupt = load_journal(ex._journal.path)
    assert corrupt == 0
    by = _events_by_name(events)
    assert len(by["video_done"]) == len(corpus)
    assert len(by["extract_start"]) == len(by["extract_end"]) == len(corpus)
    if pack:
        assert by["dispatch"]  # packed batches journal their dispatches
        assert len(by["device_start"]) == len(by["device_end"])
    # the registry counted what the journal says
    assert ex._metrics.counter_value("videos_ok_total",
                                     model="resnet50") == len(corpus)


def test_batch_failure_journals_video_failed(tmp_path, corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    ex = ToyPacked(_cfg(tmp_path, "batch_fail", retries=0,
                        telemetry_dir=str(tmp_path / "batch_fail" / "tel")))
    assert ex.run(corpus) == len(corpus) - 1
    events, _ = load_journal(ex._journal.path)
    by = _events_by_name(events)
    assert len(by["video_failed"]) == 1
    assert by["video_failed"][0]["error_class"] == "InjectedDeviceError"
    assert len(by["video_done"]) == len(corpus) - 1


def test_decode_pool_emits_decode_spans(tmp_path, corpus):
    ex = ToyPacked(_cfg(tmp_path, "batch_pool", decode_workers=2,
                        telemetry_dir=str(tmp_path / "batch_pool" / "tel")))
    assert ex.run(corpus) == len(corpus)
    events, _ = load_journal(ex._journal.path)
    by = _events_by_name(events)
    assert len(by["decode_start"]) == len(by["decode_end"]) == len(corpus)
    trace = to_chrome_trace(events)
    decode = [e for e in trace["traceEvents"]
              if e.get("ph") == "X" and e["name"] == "decode"]
    assert len(decode) == len(corpus)


# ---- acceptance: two-tenant daemon → journal/trace/histogram consistency --


def test_two_tenant_daemon_trace_chain_and_histogram_consistency(tmp_path,
                                                                 corpus):
    tel = str(tmp_path / "svc" / "tel")
    svc = ExtractionService(
        ToyPacked(_cfg(tmp_path, "svc", serve=True, telemetry_dir=tel)),
        poll_interval=0.001)
    ra = svc.submit({"tenant": "alice", "videos": corpus[:2],
                     "request_id": "ra"})
    rb = svc.submit({"tenant": "bob", "videos": corpus[2:],
                     "request_id": "rb"})
    svc.request_drain()
    assert svc.run() == 0
    assert ra.state == "done" and rb.state == "done"

    stats = svc.stats()
    assert stats["schema"] == 1
    assert stats["telemetry"]["dropped"] == 0

    events, corrupt = load_journal(os.path.join(tel, "events.jsonl"))
    assert corrupt == 0
    by = _events_by_name(events)
    # every request has a complete admitted→done chain, every video a
    # queued→popped→done chain
    assert {e["request"] for e in by["request_admitted"]} == {"ra", "rb"}
    assert {e["request"] for e in by["request_done"]} == {"ra", "rb"}
    for name in ("video_queued", "video_popped", "video_done"):
        assert {os.path.basename(e["video"]) for e in by[name]} == \
            {os.path.basename(p) for p in corpus}, name
    trace = to_chrome_trace(events)
    xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    assert sum(1 for e in xs if e["name"] == "request") == 2
    assert sum(1 for e in xs if e["name"] == "queue_wait") == len(corpus)
    assert sum(1 for e in xs if e["name"] == "process") == len(corpus)

    # stats-op latency histograms: per tenant and per model, and consistent
    # (±1 bucket) with the journal-derived queued→done latencies
    e2e = {s["labels"]["tenant"]: s for s in stats["latency"]["e2e"]}
    assert set(e2e) == {"alice", "bob"}
    for s in e2e.values():
        assert s["labels"]["model"] == "resnet50" and s["count"] == 2
        assert 0 < s["p50"] <= s["p95"] <= s["p99"]
    queued_ts = {e["video"]: e["ts"] for e in by["video_queued"]}
    done_ts = {e["video"]: e["ts"] for e in by["video_done"]}
    tenants = {e["video"]: e["tenant"] for e in by["video_queued"]}
    for video, t_done in done_ts.items():
        tenant = tenants[video]
        hist = svc.metrics.histogram("e2e_latency_seconds", tenant=tenant,
                                     model="resnet50")
        journal_latency = t_done - queued_ts[video]
        assert abs(hist.bucket_index(journal_latency)
                   - hist.bucket_index(hist.quantile(0.5))) <= 1, \
            (video, journal_latency, hist.quantile(0.5))
    # queue-wait histograms observed per pop, tenant-labeled
    qw = {s["labels"]["tenant"]: s for s in stats["latency"]["queue_wait"]}
    assert set(qw) == {"alice", "bob"}
    assert all(s["count"] == 2 for s in qw.values())


def test_daemon_without_telemetry_dir_still_serves_metrics(tmp_path, corpus):
    """The registry (stats/metrics ops) is always on under --serve; only
    the journal is gated on --telemetry_dir."""
    svc = ExtractionService(ToyPacked(_cfg(tmp_path, "nom", serve=True)),
                            poll_interval=0.001)
    r = svc.submit({"videos": corpus[:1]})
    svc.request_drain()
    assert svc.run() == 0 and r.state == "done"
    stats = svc.stats()
    assert stats["schema"] == 1
    assert stats["telemetry"] == {"enabled": False}
    assert stats["latency"]["e2e"][0]["count"] == 1
    m = svc.handle_op({"op": "metrics"})
    assert m["ok"] and "vft_e2e_latency_seconds_count" in m["prometheus"]


# ---- healthz / metrics / profile socket ops --------------------------------


def test_healthz_reports_liveness_and_staleness(tmp_path, corpus):
    svc = ExtractionService(ToyPacked(_cfg(tmp_path, "hz", serve=True)),
                            poll_interval=0.001)
    h = svc.handle_op({"op": "healthz"})
    assert h["ok"] and h["schema"] == 1 and not h["stale"]
    assert h["uptime_sec"] >= 0 and h["profiling"] is None
    svc._last_step -= 60  # a wedged daemon thread ages the stamp
    assert svc.handle_op({"op": "healthz"})["stale"] is True
    svc.step()  # stepping refreshes it
    assert svc.handle_op({"op": "healthz"})["stale"] is False
    svc.request_drain()
    assert svc.run() == 0


def test_profile_op_start_stop_cycle(tmp_path, corpus):
    tel = str(tmp_path / "prof" / "tel")
    svc = ExtractionService(
        ToyPacked(_cfg(tmp_path, "prof", serve=True, telemetry_dir=tel)),
        poll_interval=0.001)
    assert svc.handle_op({"op": "profile"})["ok"] is False  # no action
    assert svc.handle_op({"op": "profile", "action": "stop"})["ok"] is False
    started = svc.handle_op({"op": "profile", "action": "start"})
    assert started["ok"], started
    assert started["profiling"] == os.path.join(tel, "profile")
    # double-start is rejected while a session is live
    assert svc.handle_op({"op": "profile", "action": "start"})["ok"] is False
    r = svc.submit({"videos": corpus[:1]})
    for _ in range(200):
        svc.step()
        if r.complete:
            break
    stopped = svc.handle_op({"op": "profile", "action": "stop"})
    assert stopped["ok"], stopped
    assert os.path.isdir(stopped["trace_dir"])
    # a fresh cycle can start after a stop
    assert svc.handle_op({"op": "profile", "action": "start"})["ok"]
    assert svc.handle_op({"op": "profile", "action": "stop"})["ok"]
    svc.request_drain()
    assert svc.run() == 0


def test_profile_failed_stop_stays_retryable(tmp_path, corpus, monkeypatch):
    """A stop that fails mid-export (full trace disk) must leave the op
    recoverable: the session flag stays set so a retried stop can succeed
    — never a dead end where start says 'already profiling' and stop says
    'not profiling' until a daemon restart."""
    import jax

    svc = ExtractionService(ToyPacked(_cfg(tmp_path, "profr", serve=True)),
                            poll_interval=0.001)
    assert svc.handle_op({"op": "profile", "action": "start",
                          "dir": str(tmp_path / "profr" / "tr")})["ok"]

    real_stop = jax.profiler.stop_trace
    calls = []

    def failing_stop():
        calls.append(1)
        if len(calls) == 1:
            raise OSError("disk full during trace export")
        return real_stop()

    monkeypatch.setattr(jax.profiler, "stop_trace", failing_stop)
    resp = svc.handle_op({"op": "profile", "action": "stop"})
    assert resp["ok"] is False and "disk full" in resp["error"]
    retry = svc.handle_op({"op": "profile", "action": "stop"})  # retryable
    assert retry["ok"], retry
    # and a session jax reports as already gone clears the flag for start
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: (_ for _ in ()).throw(
                            RuntimeError("No profile started")))
    assert svc.handle_op({"op": "profile", "action": "start",
                          "dir": str(tmp_path / "profr" / "tr2")})["ok"]
    assert svc.handle_op({"op": "profile", "action": "stop"})["ok"] is False
    assert svc._profiling is None  # 'no profile' response cleared it
    # the second start opened a REAL jax session; close it so later tests
    # (and this process) are not left with a live global profile
    monkeypatch.setattr(jax.profiler, "stop_trace", real_stop)
    real_stop()
    svc.request_drain()
    assert svc.run() == 0


def test_profile_op_without_any_dir_is_a_clean_error(tmp_path, corpus):
    svc = ExtractionService(ToyPacked(_cfg(tmp_path, "prof2", serve=True)),
                            poll_interval=0.001)
    resp = svc.handle_op({"op": "profile", "action": "start"})
    assert resp["ok"] is False and "trace dir" in resp["error"]
    # an explicit dir in the op works without daemon flags
    resp = svc.handle_op({"op": "profile", "action": "start",
                          "dir": str(tmp_path / "prof2" / "explicit")})
    assert resp["ok"], resp
    assert svc.handle_op({"op": "profile", "action": "stop"})["ok"]
    svc.request_drain()
    assert svc.run() == 0


# ---- daemon event coverage: breaker + requeue + cache hits -----------------


def test_daemon_journals_breaker_failed_and_requeue_events(tmp_path, corpus,
                                                           monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    tel = str(tmp_path / "brk" / "tel")
    svc = ExtractionService(
        ToyPacked(_cfg(tmp_path, "brk", serve=True, telemetry_dir=tel,
                       tenant_max_failures=0)),
        poll_interval=0.001)
    svc.submit({"tenant": "alice", "videos": [corpus[1], corpus[0]]})
    svc.request_drain()
    assert svc.run() == 1
    events, _ = load_journal(os.path.join(tel, "events.jsonl"))
    by = _events_by_name(events)
    assert by["breaker_open"][0]["tenant"] == "alice"
    classes = {e["error_class"] for e in by["video_failed"]}
    assert classes == {"InjectedDeviceError", "TenantBreakerOpen"}
    assert svc.metrics.counter_value("breaker_trips_total",
                                     tenant="alice") == 1


def test_lazy_model_construction_failure_journals_video_failed(tmp_path,
                                                               corpus):
    """A co-loaded model whose lazy construction fails has NO extractor to
    run the usual accounting — the daemon arm must still terminate the
    journal lifecycle and keep the failure counter agreeing with it."""
    tel = str(tmp_path / "lazy" / "tel")
    cfg = _cfg(tmp_path, "lazy", serve=True, telemetry_dir=tel, retries=0,
               serve_models=("vggish",))

    def factory(model):
        raise RuntimeError(f"no weights for {model}")

    svc = ExtractionService(ToyPacked(cfg), poll_interval=0.001,
                            factory=factory)
    r = svc.submit({"videos": corpus[:1], "feature_type": "vggish",
                    "request_id": "rl"})
    svc.request_drain()
    assert svc.run() == 1  # the construction failure keeps the exit honest
    assert r.state == "failed"
    events, _ = load_journal(os.path.join(tel, "events.jsonl"))
    by = _events_by_name(events)
    failed = [e for e in by["video_failed"] if e.get("model") == "vggish"]
    assert len(failed) == 1 and failed[0]["error_class"] == "RuntimeError"
    assert svc.metrics.counter_value("videos_failed_total", model="vggish",
                                     error_class="RuntimeError") == 1


def test_daemon_journals_cache_hits(tmp_path, corpus):
    tel = str(tmp_path / "ch" / "tel")
    svc = ExtractionService(
        ToyPacked(_cfg(tmp_path, "ch", serve=True, telemetry_dir=tel,
                       cache_dir=str(tmp_path / "ch" / "cache"))),
        poll_interval=0.001)
    r1 = svc.submit({"videos": corpus[:2], "request_id": "r1"})
    for _ in range(500):
        svc.step()
        if r1.complete:
            break
    r2 = svc.submit({"videos": corpus[:2], "request_id": "r2"})
    svc.request_drain()
    assert svc.run() == 0
    assert r2.cache_hits == 2
    events, _ = load_journal(os.path.join(tel, "events.jsonl"))
    by = _events_by_name(events)
    assert len(by["cache_hit"]) == 2
    # cache-hit videos still close their lifecycle chain
    assert len(by["video_done"]) == 4
