"""End-to-end I3D extraction on a real sample video (random weights, CPU).

Small stack_size keeps the CPU runtime sane; geometry/windowing semantics are
identical to the 64-frame default.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.i3d import ExtractI3D


@pytest.fixture(autouse=True)
def _random_weights():
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    yield
    mp.undo()


def test_extract_rgb_only(tmp_path, sample_video):
    cfg = ExtractionConfig(
        feature_type="i3d",
        streams=("rgb",),
        stack_size=16,
        step_size=16,
        extraction_fps=4,
        on_extraction="save_numpy",
        output_path=str(tmp_path),
    )
    ex = ExtractI3D(cfg)
    feats = ex.extract(sample_video)
    # ~72 frames at 4fps → 73 decoded… (72+1 window) → 4 stacks of 17 frames
    n = feats["rgb"].shape[0]
    assert feats["rgb"].shape == (n, 1024)
    assert 3 <= n <= 5
    assert feats["timestamps_ms"].shape == (n,)
    assert np.isfinite(feats["rgb"]).all()


def test_extract_two_stream_pwc(tmp_path, sample_video):
    cfg = ExtractionConfig(
        feature_type="i3d",
        stack_size=16,
        step_size=16,
        extraction_fps=3,
        flow_type="pwc",
        on_extraction="save_numpy",
        output_path=str(tmp_path),
    )
    ex = ExtractI3D(cfg)
    feats = ex.extract(sample_video)
    n = feats["rgb"].shape[0]
    assert n >= 2
    assert feats["rgb"].shape == (n, 1024)
    assert feats["flow"].shape == (n, 1024)
    assert np.isfinite(feats["flow"]).all()
    # the two streams are different networks on different inputs
    assert not np.allclose(feats["rgb"], feats["flow"])


def test_shrunk_geometry_runs_production_steps(tmp_path):
    """cfg.i3d_pre_crop_size/i3d_crop_size shrink the SAME jitted two-stream
    programs (the driver dryrun contract, __graft_entry__.dryrun_multichip)."""
    cfg = ExtractionConfig(
        feature_type="i3d",
        stack_size=16,
        step_size=16,
        flow_type="pwc",
        i3d_pre_crop_size=96,
        i3d_crop_size=64,
        output_path=str(tmp_path),
    )
    ex = ExtractI3D(cfg)
    stacks = np.random.default_rng(0).integers(
        0, 256, (ex.clips_per_batch, 17, 96, 96, 3), dtype=np.uint8)
    dev = ex.runner.put(stacks)
    for stream in ("rgb", "flow"):
        step = ex._rgb_step if stream == "rgb" else ex._flow_step
        feats, _ = step(ex.i3d_params[stream], dev)
        assert np.asarray(feats).shape == (ex.clips_per_batch, 1024)
        assert np.isfinite(np.asarray(feats)).all()


def test_sliding_window_overlap(tmp_path, sample_video):
    """step < stack: windows overlap, count follows the flow_stack_plan math."""
    from video_features_tpu.utils.windows import flow_stack_plan

    cfg = ExtractionConfig(
        feature_type="i3d",
        streams=("rgb",),
        stack_size=12,
        step_size=6,
        extraction_fps=4,
        output_path=str(tmp_path),
    )
    ex = ExtractI3D(cfg)
    feats = ex.extract(sample_video)
    n_frames = 73  # 4fps resample of the 18.1s sample (native sampler)
    expected = len(flow_stack_plan(n_frames, 12, 6))
    assert abs(feats["rgb"].shape[0] - expected) <= 1
