"""Sharded-vs-single-device consistency on the virtual 8-device CPU mesh.

The property under test: every extractor's device step is a pure SPMD program, so
running it over an N-device mesh (batch axis sharded) must produce the same numbers
as a 1-device mesh. conftest.py forces ``xla_force_host_platform_device_count=8``,
the TPU answer to testing multi-chip topologies without hardware (SURVEY.md §4).
"""

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # multi-minute on CPU: whole-model parity / full-video extract


from video_features_tpu.config import ExtractionConfig


@pytest.fixture(autouse=True)
def _random_weights():
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    yield
    mp.undo()


def _cfg(tmp_path, feature_type, num_devices, **kw):
    return ExtractionConfig(
        feature_type=feature_type,
        num_devices=num_devices,
        output_path=str(tmp_path / f"out{num_devices}"),
        tmp_path=str(tmp_path / f"tmp{num_devices}"),
        **kw,
    )


def test_mesh_runner_rounding():
    from video_features_tpu.parallel import MeshRunner

    r = MeshRunner(num_devices=8)
    assert r.num_devices == 8
    assert [r.device_batch(b) for b in (1, 7, 8, 9, 16)] == [8, 8, 8, 16, 16]
    assert MeshRunner(num_devices=1).device_batch(3) == 3


def test_num_devices_changes_placement():
    """--num_devices must actually change how batches land on devices."""
    from video_features_tpu.parallel import MeshRunner

    batch = np.zeros((8, 4, 4, 3), np.float32)
    on1 = MeshRunner(num_devices=1).put(batch)
    on8 = MeshRunner(num_devices=8).put(batch)
    assert len(on1.sharding.device_set) == 1
    assert len(on8.sharding.device_set) == 8
    # 8-way sharded: each device holds one row of the batch
    assert on8.addressable_shards[0].data.shape == (1, 4, 4, 3)


def test_resnet_sharded_matches_single(tmp_path, rng):
    from video_features_tpu.extractors.resnet import ExtractResNet50

    frames = rng.integers(0, 256, (16, 64, 64, 3), dtype=np.uint8)
    ex1 = ExtractResNet50(_cfg(tmp_path, "resnet50", 1, batch_size=16))
    ex8 = ExtractResNet50(_cfg(tmp_path, "resnet50", 8, batch_size=16))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(frames)))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(frames)))
    assert f8.shape == (16, 2048)
    # random He weights with identity BN let residual sums grow to O(1e3);
    # tolerance scales with the feature magnitude (fp32 noise × reorder)
    np.testing.assert_allclose(f8, f1, rtol=1e-4, atol=1e-5 * np.abs(f1).max())


def test_r21d_sharded_matches_single(tmp_path, rng):
    from video_features_tpu.extractors.r21d import ExtractR21D

    clips = rng.integers(0, 256, (8, 2, 48, 48, 3), dtype=np.uint8)
    ex1 = ExtractR21D(_cfg(tmp_path, "r21d_rgb", 1, stack_size=2, step_size=2))
    ex8 = ExtractR21D(_cfg(tmp_path, "r21d_rgb", 8, stack_size=2, step_size=2))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(clips)))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(clips)))
    assert f8.shape == (8, 512)
    np.testing.assert_allclose(f8, f1, rtol=1e-5, atol=1e-5)


def test_pwc_flow_sharded_matches_single(tmp_path, rng):
    from video_features_tpu.extractors.flow import ExtractFlow

    frames = rng.uniform(0, 255, (9, 64, 64, 3)).astype(np.float32)
    ex1 = ExtractFlow(_cfg(tmp_path, "pwc", 1, batch_size=8))
    ex8 = ExtractFlow(_cfg(tmp_path, "pwc", 8, batch_size=8))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(frames[:-1]), ex1.runner.put(frames[1:])))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(frames[:-1]), ex8.runner.put(frames[1:])))
    assert f8.shape == (8, 64, 64, 2)
    np.testing.assert_allclose(f8, f1, rtol=1e-5, atol=1e-4)


def test_vggish_sharded_matches_single(tmp_path, rng):
    from video_features_tpu.extractors.vggish import ExtractVGGish

    examples = rng.normal(size=(8, 96, 64)).astype(np.float32)
    ex1 = ExtractVGGish(_cfg(tmp_path, "vggish", 1))
    ex8 = ExtractVGGish(_cfg(tmp_path, "vggish", 8))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(examples)))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(examples)))
    assert f8.shape == (8, 128)
    np.testing.assert_allclose(f8, f1, rtol=1e-5, atol=1e-5)


def test_i3d_rgb_sharded_matches_single(tmp_path, rng):
    """I3D stack step over a 4-device mesh (224² is CPU-heavy; 4 clips keep it sane)."""
    from video_features_tpu.extractors.i3d import ExtractI3D

    stacks = rng.integers(0, 256, (4, 17, 224, 224, 3), dtype=np.uint8)
    kw = dict(streams=("rgb",), stack_size=16, step_size=16, clips_per_batch=4)
    ex1 = ExtractI3D(_cfg(tmp_path, "i3d", 1, **kw))
    ex4 = ExtractI3D(_cfg(tmp_path, "i3d", 4, **kw))
    f1, _ = ex1._rgb_step(ex1.i3d_params["rgb"], ex1.runner.put(stacks))
    f4, _ = ex4._rgb_step(ex4.i3d_params["rgb"], ex4.runner.put(stacks))
    f1, f4 = np.asarray(f1), np.asarray(f4)
    assert f4.shape == (4, 1024)
    np.testing.assert_allclose(f4, f1, rtol=1e-4, atol=1e-4)


def test_matmul_precision_plumbs(tmp_path, rng):
    """--matmul_precision traces and matches default numerics on CPU (where
    fp32 is already exact; on TPU 'highest' switches off the bf16 MXU passes)."""
    from video_features_tpu.extractors.resnet import ExtractResNet50

    frames = rng.integers(0, 256, (8, 64, 64, 3), dtype=np.uint8)
    ex_d = ExtractResNet50(_cfg(tmp_path, "resnet50", 1, batch_size=8))
    ex_h = ExtractResNet50(
        _cfg(tmp_path / "h", "resnet50", 1, batch_size=8, matmul_precision="highest")
    )
    f_d = np.asarray(ex_d._step(ex_d.params, ex_d.runner.put(frames)))
    f_h = np.asarray(ex_h._step(ex_h.params, ex_h.runner.put(frames)))
    np.testing.assert_allclose(f_h, f_d, rtol=1e-5, atol=1e-5 * np.abs(f_d).max())


def test_raft_extract_end_to_end_sharded(tmp_path, sample_video):
    """Full extract() pipeline (decode → pairs → sharded RAFT → unpad → collect)
    gives identical flow on 1- and 8-device meshes."""
    from video_features_tpu.extractors.flow import ExtractFlow

    kw = dict(batch_size=8, side_size=64, extraction_fps=2)
    ex1 = ExtractFlow(_cfg(tmp_path, "raft", 1, **kw))
    ex8 = ExtractFlow(_cfg(tmp_path, "raft", 8, **kw))
    f1 = ex1.extract(sample_video)
    f8 = ex8.extract(sample_video)
    assert f1["raft"].shape == f8["raft"].shape
    assert f1["raft"].shape[0] >= 30
    # Tolerance note: sharding changes XLA fusion/reduction order; with random
    # weights RAFT's 20 recurrent iterations chaotically amplify those last-ulp
    # differences (observed: 0.4% of elements off by ≤4% — single-iteration steps
    # like PWC/ResNet/I3D match at 1e-5 above). Bit-parity across mesh sizes is
    # asserted there; here we bound the amplified drift.
    np.testing.assert_allclose(f8["raft"], f1["raft"], rtol=5e-2, atol=5e-2)


def test_i3d_clip_batching_consistency(tmp_path, rng):
    """clips_per_batch changes throughput, not results: a 4-clip batched step must
    equal four 1-clip steps (padded to the mesh multiple)."""
    from video_features_tpu.extractors.i3d import ExtractI3D

    stacks = rng.integers(0, 256, (4, 17, 224, 224, 3), dtype=np.uint8)
    kw = dict(streams=("rgb",), stack_size=16, step_size=16)
    ex = ExtractI3D(_cfg(tmp_path, "i3d", 1, clips_per_batch=4, **kw))
    batched, _ = ex._rgb_step(ex.i3d_params["rgb"], ex.runner.put(stacks))
    ex1 = ExtractI3D(_cfg(tmp_path / "one", "i3d", 1, clips_per_batch=1, **kw))
    singles = [
        np.asarray(ex1._rgb_step(ex1.i3d_params["rgb"], ex1.runner.put(stacks[i : i + 1]))[0])
        for i in range(4)
    ]
    np.testing.assert_allclose(
        np.asarray(batched), np.concatenate(singles), rtol=1e-4, atol=1e-4
    )


def test_pwc_onehot_warp_sharded_matches_single(tmp_path, rng):
    """The one-hot selector warp (pwc_warp=onehot) under the 8-device mesh:
    the selector einsums and lax.map chunking batch over the sharded pair
    axis, so mesh size must not change the numbers."""
    from video_features_tpu.extractors.flow import ExtractFlow

    frames = rng.uniform(0, 255, (9, 64, 64, 3)).astype(np.float32)
    ex1 = ExtractFlow(_cfg(tmp_path, "pwc", 1, batch_size=8, pwc_warp="onehot"))
    ex8 = ExtractFlow(_cfg(tmp_path, "pwc", 8, batch_size=8, pwc_warp="onehot"))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(frames[:-1]),
                              ex1.runner.put(frames[1:])))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(frames[:-1]),
                              ex8.runner.put(frames[1:])))
    np.testing.assert_allclose(f8, f1, rtol=1e-5, atol=1e-4)


def test_raft_on_demand_matmul_sharded_matches_single(tmp_path, rng):
    """raft_corr=on_demand_matmul under the 8-device mesh: the per-chunk
    volume remat einsums batch over the sharded pair axis.

    Tolerance note: RANDOM weights make the 20-iteration GRU loop chaotic
    (|flow| ≈ 800 px at this geometry), so mesh-size-dependent XLA reduction
    order amplifies to ~5e-3 px — measured IDENTICALLY for volume,
    on_demand, and on_demand_matmul (round-5 sweep), i.e. a property of the
    loop under random weights, not of any lookup lowering. Bound at 4× the
    measured max."""
    from video_features_tpu.extractors.flow import ExtractFlow

    frames = rng.uniform(0, 255, (9, 48, 48, 3)).astype(np.float32)
    ex1 = ExtractFlow(_cfg(tmp_path, "raft", 1, batch_size=8,
                           raft_corr="on_demand_matmul"))
    ex8 = ExtractFlow(_cfg(tmp_path, "raft", 8, batch_size=8,
                           raft_corr="on_demand_matmul"))
    f1 = np.asarray(ex1._step(ex1.params, ex1.runner.put(frames[:-1]),
                              ex1.runner.put(frames[1:])))
    f8 = np.asarray(ex8._step(ex8.params, ex8.runner.put(frames[:-1]),
                              ex8.runner.put(frames[1:])))
    np.testing.assert_allclose(f8, f1, rtol=1e-4, atol=0.02)
