"""Reliability subsystem units: taxonomy, retry/backoff, watchdog, manifests.

End-to-end fault-injected runs live in tests/test_fault_injection.py; this
module pins the building blocks' contracts.
"""

import json
import os
import time

import numpy as np
import pytest

from video_features_tpu.io import ffmpeg as ffmpeg_io
from video_features_tpu.io.output import (
    action_on_extraction,
    load_done_set,
    manifest_path,
    mark_done,
)
from video_features_tpu.io.video import open_video, probe_video
from video_features_tpu.reliability import (
    DecodeError,
    DeviceError,
    ExtractionError,
    FfmpegError,
    OutputError,
    RetryPolicy,
    VideoTimeoutError,
    classify,
    failed_manifest_path,
    load_failures,
    prune_failures,
    record_failure,
    retry_call,
    run_with_timeout,
    traceback_digest,
)


# ---- taxonomy -------------------------------------------------------------


def test_transient_tags():
    assert not DecodeError("x").transient
    assert not VideoTimeoutError("x").transient
    assert FfmpegError("x").transient
    assert DeviceError("x").transient
    assert OutputError("x").transient
    for cls in (DecodeError, FfmpegError, DeviceError, OutputError, VideoTimeoutError):
        assert issubclass(cls, ExtractionError)


def test_classify_taxonomy_and_unknown():
    assert classify(FfmpegError("a")) == ("FfmpegError", True)
    assert classify(DecodeError("a")) == ("DecodeError", False)
    assert classify(ValueError("a")) == ("ValueError", False)


def test_classify_xla_runtime_error_is_device_fault():
    exc = type("XlaRuntimeError", (RuntimeError,), {})("DEADLINE_EXCEEDED")
    assert classify(exc) == ("DeviceError", True)


def test_traceback_digest_groups_by_site_not_message():
    def boom(msg):
        raise DecodeError(msg)

    digests = []
    for msg in ("video_a.mp4 bad", "video_b.mp4 bad"):
        try:
            boom(msg)
        except DecodeError as e:
            digests.append(traceback_digest(e))
    assert digests[0] == digests[1]
    assert len(digests[0]) == 12


# ---- retry ---------------------------------------------------------------


def test_retry_policy_delays_exponential_capped():
    p = RetryPolicy(attempts=5, base_delay=1.0, max_delay=3.0)
    assert list(p.delays()) == [1.0, 2.0, 3.0, 3.0]
    with pytest.raises(ValueError):
        RetryPolicy(attempts=0)


def test_retry_transient_succeeds_with_backoff():
    calls, slept = [], []

    def fn():
        calls.append(1)
        if len(calls) < 3:
            raise FfmpegError("child died")
        return "ok"

    out = retry_call(fn, RetryPolicy(attempts=3, base_delay=0.25), sleep=slept.append)
    assert out == "ok" and len(calls) == 3
    assert slept == [0.25, 0.5]


def test_retry_permanent_raises_immediately_with_attempt_count():
    calls = []

    def fn():
        calls.append(1)
        raise DecodeError("corrupt")

    with pytest.raises(DecodeError) as ei:
        retry_call(fn, RetryPolicy(attempts=4, base_delay=0.0), sleep=lambda d: None)
    assert len(calls) == 1
    assert ei.value.attempts == 1


def test_retry_exhaustion_reports_attempts():
    def fn():
        raise DeviceError("flaky")

    with pytest.raises(DeviceError) as ei:
        retry_call(fn, RetryPolicy(attempts=3, base_delay=0.0), sleep=lambda d: None)
    assert ei.value.attempts == 3


def test_retry_on_retry_callback_sees_delay():
    seen = []

    def fn():
        if len(seen) < 1:
            raise OutputError("disk")
        return 1

    retry_call(
        fn,
        RetryPolicy(attempts=2, base_delay=0.125),
        sleep=lambda d: None,
        on_retry=lambda exc, attempt, delay: seen.append((type(exc).__name__, attempt, delay)),
    )
    assert seen == [("OutputError", 1, 0.125)]


# ---- watchdog ------------------------------------------------------------


def test_watchdog_passthrough_and_errors():
    assert run_with_timeout(lambda: 7, None) == 7
    assert run_with_timeout(lambda: 7, 5.0) == 7
    with pytest.raises(DecodeError, match="inner"):
        run_with_timeout(lambda: (_ for _ in ()).throw(DecodeError("inner")), 5.0)


def test_watchdog_cancels_hang():
    t0 = time.monotonic()
    with pytest.raises(VideoTimeoutError, match="video_timeout"):
        run_with_timeout(lambda: time.sleep(10), 0.3, "wedged.mp4")
    assert time.monotonic() - t0 < 5.0
    assert not VideoTimeoutError("x").transient  # watchdog hits are not retried


# ---- failure manifest ----------------------------------------------------


def test_failure_manifest_roundtrip(tmp_path):
    out = str(tmp_path)
    rec = record_failure(out, "a.mp4", DecodeError("corrupt"), attempts=2)
    assert rec["error_class"] == "DecodeError" and rec["transient"] is False
    record_failure(out, "b.mp4", FfmpegError("died"), attempts=3)
    failures = load_failures(out)
    assert set(failures) == {os.path.abspath("a.mp4"), os.path.abspath("b.mp4")}
    assert failures[os.path.abspath("b.mp4")]["attempts"] == 3
    prune_failures(out, ["a.mp4"])
    assert set(load_failures(out)) == {os.path.abspath("b.mp4")}
    prune_failures(out, ["b.mp4"])
    assert load_failures(out) == {}
    # pruning the last record removes the file: "no manifest" == "no failures"
    assert not os.path.exists(failed_manifest_path(out))


def test_failure_manifest_last_record_wins(tmp_path):
    out = str(tmp_path)
    record_failure(out, "a.mp4", FfmpegError("first"), attempts=1)
    record_failure(out, "a.mp4", DecodeError("second"), attempts=2)
    failures = load_failures(out)
    assert failures[os.path.abspath("a.mp4")]["error_class"] == "DecodeError"


def test_failure_manifest_warns_on_corrupt_lines(tmp_path, capsys):
    out = str(tmp_path)
    record_failure(out, "a.mp4", DecodeError("x"))
    with open(failed_manifest_path(out), "a") as f:
        f.write("{truncated\n[]\n")
    failures = load_failures(out)
    assert set(failures) == {os.path.abspath("a.mp4")}
    assert "2 corrupt line(s)" in capsys.readouterr().err


# ---- done-manifest corruption (satellite) --------------------------------


def test_load_done_set_warns_on_corrupt_lines(tmp_path, capsys):
    out = str(tmp_path)
    mark_done(out, "good.mp4", ["rgb"])
    with open(manifest_path(out), "a") as f:
        f.write('{"video": "half\n')  # crash mid-append
        f.write("not json at all\n")
    done = load_done_set(out)
    assert done == {os.path.abspath("good.mp4")}
    err = capsys.readouterr().err
    assert "2 corrupt line(s)" in err and "re-extracted" in err


# ---- atomic save ---------------------------------------------------------


def test_atomic_save_no_tmp_left_behind(tmp_path):
    saved = action_on_extraction(
        {"k": np.arange(5)}, "v.mp4", str(tmp_path), "save_numpy")
    assert os.path.exists(saved["k"])
    assert not os.path.exists(saved["k"] + ".tmp")
    np.testing.assert_array_equal(np.load(saved["k"]), np.arange(5))


def test_atomic_save_injected_fault_cleans_tmp(tmp_path, monkeypatch):
    """An injected OutputError between write and rename must not leave the
    .npy.tmp behind (chaos drills would otherwise accumulate clutter)."""
    monkeypatch.setenv("VFT_FAULTS", "save:raise")
    with pytest.raises(OutputError, match="injected"):
        action_on_extraction({"k": np.arange(5)}, "v.mp4", str(tmp_path), "save_numpy")
    assert list(tmp_path.iterdir()) == []  # no final .npy, no .tmp


def test_atomic_save_failure_classified_and_tmp_cleaned(tmp_path, monkeypatch):
    def bad_replace(src, dst):
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(os, "replace", bad_replace)
    with pytest.raises(OutputError, match="No space left"):
        action_on_extraction({"k": np.arange(5)}, "v.mp4", str(tmp_path), "save_numpy")
    assert not any(p.name.endswith(".tmp") for p in tmp_path.iterdir())
    assert OutputError("x").transient  # disk pressure is worth retrying


# ---- classified decode errors --------------------------------------------


@pytest.fixture
def garbage_mp4(tmp_path):
    p = tmp_path / "garbage.mp4"
    p.write_bytes(b"\x00\x01junk" * 1024)
    return str(p)


def test_probe_corrupt_container_raises_decode_error(garbage_mp4):
    with pytest.raises(DecodeError, match="cannot open|corrupt"):
        probe_video(garbage_mp4)


def test_open_corrupt_container_raises_decode_error(garbage_mp4):
    with pytest.raises(DecodeError):
        meta, frames = open_video(garbage_mp4)
        list(frames)


def test_open_missing_video_still_file_not_found(tmp_path):
    with pytest.raises(FileNotFoundError):
        open_video(str(tmp_path / "nope.mp4"))


# ---- ffmpeg classification + graceful degradation ------------------------


def test_run_checked_classifies_spawn_failure(tmp_path):
    with pytest.raises(FfmpegError, match="spawn"):
        ffmpeg_io._run_checked(
            [str(tmp_path / "no_such_ffmpeg")], "src.mp4", str(tmp_path / "out.mp4"))


def test_run_checked_input_caused_exit_is_permanent(tmp_path, monkeypatch):
    """Deterministic input failures (corrupt container, no audio stream) must
    not burn the retry budget; environmental exits stay transient."""
    class FakeProc:
        def __init__(self, rc, stderr):
            self.returncode, self.stderr = rc, stderr

    for rc, stderr, want_transient in [
        (1, "x.mp4: moov atom not found", False),
        (1, "Output file #0 does not contain any stream", False),
        (1, "Invalid data found when processing input", False),
        (1, "Cannot allocate memory", True),     # environmental
        (-9, "", True),                           # killed by a signal
    ]:
        monkeypatch.setattr(
            ffmpeg_io.subprocess, "run",
            lambda cmd, capture_output, text, _p=FakeProc(rc, stderr): _p)
        with pytest.raises(FfmpegError) as ei:
            ffmpeg_io._run_checked(["ffmpeg"], "src.mp4", str(tmp_path / "o.mp4"))
        from video_features_tpu.reliability import classify
        assert classify(ei.value) == ("FfmpegError", want_transient), stderr


@pytest.fixture
def tiny_video(tmp_path):
    import cv2

    p = str(tmp_path / "tiny.mp4")
    w = cv2.VideoWriter(p, cv2.VideoWriter_fourcc(*"mp4v"), 10.0, (32, 24))
    rng = np.random.default_rng(0)
    for _ in range(12):
        w.write(rng.integers(0, 256, (24, 32, 3), dtype=np.uint8))
    w.release()
    return p


def test_ffmpeg_transient_retry_then_success(tiny_video, tmp_path, monkeypatch):
    """First re-encode attempt dies, the bounded retry succeeds — the video
    takes the (faked) ffmpeg path, not the fallback."""
    import shutil

    calls = []

    def fake_reencode(video_path, tmp_dir, fps):
        calls.append(1)
        if len(calls) == 1:
            raise FfmpegError("child OOM-killed")
        os.makedirs(tmp_dir, exist_ok=True)
        copy = os.path.join(tmp_dir, "reencoded.mp4")
        shutil.copy(video_path, copy)
        return copy

    monkeypatch.setattr(ffmpeg_io, "have_ffmpeg", lambda: True)
    monkeypatch.setattr(ffmpeg_io, "reencode_video_with_diff_fps", fake_reencode)
    meta, frames = open_video(
        tiny_video, extraction_fps=10, tmp_path=str(tmp_path / "t"),
        retries=2, retry_backoff=0.0)
    assert len(calls) == 2
    assert meta.fps == 10.0
    assert len(list(frames)) == 12


def test_ffmpeg_permanent_failure_degrades_to_native_sampler(
        tiny_video, tmp_path, monkeypatch, capsys):
    """All re-encode attempts fail under use_ffmpeg='auto' → the native
    sampler takes over instead of killing the video."""
    def always_fail(video_path, tmp_dir, fps):
        raise FfmpegError("no tmp space")

    monkeypatch.setattr(ffmpeg_io, "have_ffmpeg", lambda: True)
    monkeypatch.setattr(ffmpeg_io, "reencode_video_with_diff_fps", always_fail)
    meta, frames = open_video(
        tiny_video, extraction_fps=5, tmp_path=str(tmp_path / "t"),
        use_ffmpeg="auto", retries=1, retry_backoff=0.0)
    got = list(frames)
    assert meta.fps == 5.0 and 5 <= len(got) <= 7  # 12 frames @10fps → ~6 @5fps
    assert "falling back to the native fps sampler" in capsys.readouterr().err

    with pytest.raises(FfmpegError):  # 'always' must not degrade silently
        open_video(tiny_video, extraction_fps=5, tmp_path=str(tmp_path / "t"),
                   use_ffmpeg="always", retries=0, retry_backoff=0.0)


# ---- config validation ---------------------------------------------------


def test_reliability_config_validation():
    from video_features_tpu.config import ExtractionConfig

    base = dict(feature_type="resnet50")
    ExtractionConfig(**base, retries=0, video_timeout=1.5, max_failures=0).validate()
    with pytest.raises(ValueError, match="retries"):
        ExtractionConfig(**base, retries=-1).validate()
    with pytest.raises(ValueError, match="video_timeout"):
        ExtractionConfig(**base, video_timeout=0).validate()
    with pytest.raises(ValueError, match="max_failures"):
        ExtractionConfig(**base, max_failures=-2).validate()
    with pytest.raises(ValueError, match="retry_backoff"):
        ExtractionConfig(**base, retry_backoff=-0.5).validate()


def test_cli_reliability_flags():
    from video_features_tpu.cli import parse_args

    cfg = parse_args([
        "--feature_type", "resnet50", "--video_paths", "a.mp4",
        "--retries", "5", "--retry_backoff", "0.1",
        "--video_timeout", "30", "--max_failures", "10", "--retry_failed",
    ])
    assert cfg.retries == 5 and cfg.retry_backoff == 0.1
    assert cfg.video_timeout == 30.0 and cfg.max_failures == 10
    assert cfg.retry_failed is True


def test_failed_manifest_is_json_lines(tmp_path):
    out = str(tmp_path)
    record_failure(out, "x.mp4", OutputError("disk full"), attempts=4)
    with open(failed_manifest_path(out)) as f:
        rec = json.loads(f.readline())
    assert set(rec) == {"video", "error_class", "transient", "attempts",
                        "message", "traceback_digest"}
