"""Shared-frame flow forwards vs the pair-split forwards (fast subset).

These four small-shape tests are the direct check of the shared-frame encoding
the production I3D sandwich and single-device ExtractFlow run on
(raft_forward_frames / pwc_forward_frames): per-frame features sliced into
pairs must reproduce the pair-split forward, and clip batches must never pair
across clip boundaries. Kept OUT of the slow-marked parity files so the
default `pytest` run still covers the production flow path.
"""
# fast-registry: default tier — shared-frame flow forward parity (flow compiles)

import numpy as np

import jax.numpy as jnp

from video_features_tpu.models.pwc import pwc_forward, pwc_forward_frames, pwc_init_params
from video_features_tpu.models.raft import raft_forward, raft_forward_frames, raft_init_params

def test_raft_forward_frames_matches_pair_forward():
    """Shared-frame encoding (fnet once per frame) must reproduce the
    pair-split forward; also covers the fused GRU gate convs."""
    rng = np.random.default_rng(11)
    params = raft_init_params(0)
    frames = jnp.asarray(rng.uniform(0, 255, (4, 48, 56, 3)).astype(np.float32))
    pair = raft_forward(params, frames[:-1], frames[1:], iters=4)
    shared = raft_forward_frames(params, frames, iters=4)
    assert shared.shape == (3, 48, 56, 2)
    np.testing.assert_allclose(np.asarray(shared), np.asarray(pair),
                               rtol=1e-4, atol=1e-4)


def test_raft_forward_frames_clip_batch_no_cross_clip_pairs():
    """(N, F, H, W, 3) clip batches pair only within a clip."""
    rng = np.random.default_rng(12)
    params = raft_init_params(0)
    clips = jnp.asarray(rng.uniform(0, 255, (2, 3, 32, 40, 3)).astype(np.float32))
    batched = np.asarray(raft_forward_frames(params, clips, iters=3))
    assert batched.shape == (2, 2, 32, 40, 2)
    for i in range(2):
        single = np.asarray(raft_forward_frames(params, clips[i], iters=3))
        np.testing.assert_allclose(batched[i], single, rtol=1e-4, atol=1e-4)


def test_pwc_forward_frames_matches_pair_forward():
    """Shared-pyramid encoding must reproduce the pair-split forward."""
    rng = np.random.default_rng(13)
    params = pwc_init_params(0)
    frames = jnp.asarray(rng.uniform(0, 255, (4, 96, 128, 3)).astype(np.float32))
    pair = pwc_forward(params, frames[:-1], frames[1:])
    shared = pwc_forward_frames(params, frames)
    assert shared.shape == (3, 96, 128, 2)
    np.testing.assert_allclose(np.asarray(shared), np.asarray(pair),
                               rtol=1e-4, atol=1e-4)


def test_pwc_forward_frames_clip_batch_no_cross_clip_pairs():
    rng = np.random.default_rng(14)
    params = pwc_init_params(0)
    clips = jnp.asarray(rng.uniform(0, 255, (2, 3, 64, 64, 3)).astype(np.float32))
    batched = np.asarray(pwc_forward_frames(params, clips))
    assert batched.shape == (2, 2, 64, 64, 2)
    for i in range(2):
        single = np.asarray(pwc_forward_frames(params, clips[i]))
        np.testing.assert_allclose(batched[i], single, rtol=1e-4, atol=1e-4)


def test_pwc_pair_chunk_matches_unchunked():
    """lax.map pair chunking must reproduce the single-piece decode exactly
    (the shared pyramid is identical; only decoder batching changes)."""
    rng = np.random.default_rng(15)
    params = pwc_init_params(0)
    frames = jnp.asarray(rng.uniform(0, 255, (5, 64, 64, 3)).astype(np.float32))
    whole = np.asarray(pwc_forward_frames(params, frames))
    chunked = np.asarray(pwc_forward_frames(params, frames, pair_chunk=2))
    assert chunked.shape == whole.shape == (4, 64, 64, 2)
    # 1e-4: conv reduction order varies with the decoder batch size (same
    # tolerance as the other batch-variant equivalence tests in this file)
    np.testing.assert_allclose(chunked, whole, rtol=1e-4, atol=1e-4)
    # non-divisible chunk zero-pads the pair axis and slices — the HBM
    # protection must never silently disengage on an odd pair count
    padded = np.asarray(pwc_forward_frames(params, frames, pair_chunk=3))
    np.testing.assert_allclose(padded, whole, rtol=1e-4, atol=1e-4)
    # chunk >= total degenerates to the single-piece decode
    big = np.asarray(pwc_forward_frames(params, frames, pair_chunk=64))
    np.testing.assert_array_equal(big, whole)
