"""Checkpoint store resolution: every reference checkpoint format must be
consumable end-to-end (VERDICT round-1 missing #2 / weak #18).

Formats the reference loads: torch ``.pt``/``.pth`` state_dicts (I3D, RAFT, PWC,
torchvision ResNet/R21D — some ``module.``-prefixed), a TF-slim checkpoint for
VGGish (here: its variables dumped to ``.npz``), and this store's own converted
``.npz``. Round-trips assert tree equality with direct conversion."""
# fast-registry: default tier — checkpoint store roundtrips

import os
import subprocess
import sys

import numpy as np
import pytest

from video_features_tpu.weights.store import (
    flatten_params,
    looks_like_tf_vars,
    resolve_params,
    save_params_npz,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trees_equal(a, b):
    fa, fb = flatten_params(a), flatten_params(b)
    assert set(fa) == set(fb)
    for k in fa:
        np.testing.assert_array_equal(fa[k], fb[k], err_msg=k)


@pytest.fixture
def ckpt_dir(tmp_path, monkeypatch):
    d = tmp_path / "ckpts"
    d.mkdir()
    monkeypatch.setenv("VFT_CHECKPOINT_DIR", str(d))
    monkeypatch.delenv("VFT_ALLOW_RANDOM_WEIGHTS", raising=False)
    return d


def test_torch_pt_roundtrip_through_store(ckpt_dir):
    """Reference-named r21d .pt → resolve_params == direct conversion."""
    import torch

    from tools.torch_mirrors import r21d_random_state_dict

    from video_features_tpu.weights.convert_torch import convert_r21d

    sd = r21d_random_state_dict(seed=3)
    torch.save(sd, ckpt_dir / "r2plus1d_18.pt")
    resolved = resolve_params("r2plus1d_18", convert_torch_fn=convert_r21d)
    _trees_equal(resolved, convert_r21d(sd))


def test_module_prefixed_checkpoint(ckpt_dir):
    """RAFT checkpoints carry the DataParallel 'module.' prefix
    (extract_raft.py:58-59); the export tool strips it."""
    import torch

    from tools.export_weights import convert_torch_checkpoint
    from tools.torch_mirrors import raft_random_state_dict

    from video_features_tpu.weights.convert_torch import convert_raft

    sd = raft_random_state_dict(seed=1)
    prefixed = {f"module.{k}": v for k, v in sd.items()}
    src = ckpt_dir / "raft-sintel.pth"
    torch.save(prefixed, src)
    params = convert_torch_checkpoint("raft-sintel", str(src))
    _trees_equal(params, convert_raft(sd))


def test_tf_vars_npz_resolves_for_vggish(ckpt_dir):
    """A raw TF-variables npz in the .npz slot must route through
    convert_tf_vggish, not the flat-params unflattener (round-1 weak #18)."""
    from video_features_tpu.models.vggish import convert_tf_vggish, vggish_init_params

    ref = vggish_init_params(seed=7)
    tf_vars = {}
    for module, leaves in ref.items():
        scope = f"conv3/{module}" if module.startswith("conv3_") else module
        scope = f"conv4/{module}" if module.startswith("conv4_") else scope
        scope = f"fc1/{module}" if module.startswith("fc1_") else scope
        tf_vars[f"vggish/{scope}/weights"] = leaves["kernel"]
        tf_vars[f"vggish/{scope}/biases"] = leaves["bias"]
    assert looks_like_tf_vars(tf_vars)
    np.savez(ckpt_dir / "vggish.npz", **tf_vars)

    resolved = resolve_params("vggish", convert_tf_fn=convert_tf_vggish)
    _trees_equal(resolved, ref)


def test_store_npz_not_mistaken_for_tf(ckpt_dir):
    """Store-format flat params in the same slot still load unconverted."""
    from video_features_tpu.models.vggish import convert_tf_vggish, vggish_init_params

    ref = vggish_init_params(seed=2)
    save_params_npz(str(ckpt_dir / "vggish.npz"), ref)
    resolved = resolve_params("vggish", convert_tf_fn=convert_tf_vggish)
    _trees_equal(resolved, ref)


def test_export_weights_cli_end_to_end(ckpt_dir, tmp_path):
    """CLI: torch .pt → .npz → resolve_params loads it without torch converters."""
    import torch

    from tools.torch_mirrors import i3d_random_state_dict

    from video_features_tpu.weights.convert_torch import convert_i3d

    sd = i3d_random_state_dict("rgb", seed=5)
    src = tmp_path / "i3d_rgb.pt"
    torch.save(sd, src)
    subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "export_weights.py"),
         "--model", "i3d_rgb", "--src", str(src), "--out_dir", str(ckpt_dir)],
        check=True, cwd=REPO,
    )
    resolved = resolve_params("i3d_rgb")  # no converter needed: pre-converted npz
    _trees_equal(resolved, convert_i3d(sd))


def test_exported_weights_drive_the_model(ckpt_dir):
    """Converted-and-stored weights produce the same features as direct-path
    weights through the actual extractor step."""
    import torch

    from tools.torch_mirrors import i3d_random_state_dict

    from video_features_tpu.extractors.i3d import ExtractI3D
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.weights.convert_torch import convert_i3d

    sd = i3d_random_state_dict("rgb", seed=9)
    save_params_npz(str(ckpt_dir / "i3d_rgb.npz"), convert_i3d(sd))
    cfg = ExtractionConfig(feature_type="i3d", streams=("rgb",), stack_size=16,
                           step_size=16, num_devices=1,
                           output_path=str(ckpt_dir / "o"), tmp_path=str(ckpt_dir / "t"))
    ex = ExtractI3D(cfg)
    _trees_equal(ex.i3d_params["rgb"], convert_i3d(sd))
    stacks = np.random.default_rng(0).integers(0, 256, (1, 17, 224, 224, 3), dtype=np.uint8)
    feats, _ = ex._rgb_step(ex.i3d_params["rgb"], ex.runner.put(stacks))
    assert np.isfinite(np.asarray(feats)).all()


def test_missing_checkpoint_raises_without_random_flag(ckpt_dir):
    with pytest.raises(FileNotFoundError):
        resolve_params("resnet50")


def test_orbax_roundtrip_through_store(tmp_path):
    """Orbax checkpoint directories resolve through the store like .npz files."""
    pytest.importorskip("orbax.checkpoint")
    from video_features_tpu.weights.store import load_params_orbax, save_params_orbax

    params = {"conv1": {"kernel": np.arange(12, dtype=np.float32).reshape(2, 2, 3),
                        "bias": np.zeros(3, np.float32)},
              "bn": {"scale": np.ones(3, np.float32)}}
    path = save_params_orbax(str(tmp_path / "model.orbax"), params)
    got = load_params_orbax(path)
    assert set(got) == {"conv1", "bn"}
    np.testing.assert_array_equal(got["conv1"]["kernel"], params["conv1"]["kernel"])
    via_store = resolve_params("model", checkpoint_path=path)
    np.testing.assert_array_equal(via_store["conv1"]["bias"], params["conv1"]["bias"])
