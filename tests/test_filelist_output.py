"""File-list forming/sharding and output actions."""

import os

import numpy as np
import pytest

from video_features_tpu.io.filelist import form_video_list, shard_round_robin, write_shard_files
from video_features_tpu.io.output import (
    action_on_extraction,
    feature_output_dir,
    load_done_set,
    mark_done,
)


def test_form_video_list_from_file(tmp_path):
    f = tmp_path / "paths.txt"
    f.write_text("a.mp4\n\nb.mp4\n")
    out = form_video_list(file_with_video_paths=str(f), warn_missing=False)
    assert out == ["a.mp4", "b.mp4"]


def test_form_video_list_explicit():
    assert form_video_list(["x.mp4", "y.mp4"], warn_missing=False) == ["x.mp4", "y.mp4"]


def test_file_wins_over_explicit(tmp_path):
    f = tmp_path / "paths.txt"
    f.write_text("a.mp4\n")
    out = form_video_list(["z.mp4"], file_with_video_paths=str(f), warn_missing=False)
    assert out == ["a.mp4"]


def test_shard_round_robin():
    paths = [f"v{i}.mp4" for i in range(7)]
    shards = [shard_round_robin(paths, k, 3) for k in range(3)]
    assert shards[0] == ["v0.mp4", "v3.mp4", "v6.mp4"]
    assert shards[1] == ["v1.mp4", "v4.mp4"]
    assert shards[2] == ["v2.mp4", "v5.mp4"]
    # partition property
    assert sorted(sum(shards, [])) == sorted(paths)


def test_write_shard_files(tmp_path):
    vdir = tmp_path / "videos"
    vdir.mkdir()
    for i in range(5):
        (vdir / f"v{i}.mp4").touch()
    out = write_shard_files(str(vdir), str(tmp_path / "lists"), 2)
    assert len(out) == 2
    lines0 = open(out[0]).read().splitlines()
    lines1 = open(out[1]).read().splitlines()
    assert len(lines0) == 3 and len(lines1) == 2


def test_save_numpy_naming(tmp_path):
    feats = {"rgb": np.ones((2, 4), np.float32), "fps": np.array(25.0)}
    out_dir = feature_output_dir(str(tmp_path / "out"), "i3d")
    saved = action_on_extraction(feats, "/data/my_video.mp4", out_dir, "save_numpy")
    assert set(saved) == {"rgb", "fps"}
    assert saved["rgb"].endswith(os.path.join("out", "i3d", "my_video_rgb.npy"))
    np.testing.assert_array_equal(np.load(saved["rgb"]), feats["rgb"])


def test_print_action(capsys):
    feats = {"rgb": np.arange(4, dtype=np.float32)}
    action_on_extraction(feats, "v.mp4", ".", "print")
    out = capsys.readouterr().out
    assert "rgb" in out
    assert "max: 3.00000000; mean: 1.50000000; min: 0.00000000" in out


def test_unknown_action():
    with pytest.raises(NotImplementedError):
        action_on_extraction({"a": np.zeros(1)}, "v.mp4", ".", "save_pickle")


def test_done_manifest(tmp_path):
    out = str(tmp_path)
    assert load_done_set(out) == set()
    mark_done(out, "a.mp4", ["rgb"])
    mark_done(out, "b.mp4", ["rgb", "flow"])
    done = load_done_set(out)
    assert os.path.abspath("a.mp4") in done and os.path.abspath("b.mp4") in done
