"""Per-layer activation parity: every stage of I3D and RAFT must match the
independent torch mirror at fp32 noise level (SURVEY.md §4's layer-diff plan).

End-to-end parity can hide a topology error behind pooling; this localizes any
divergence to the first wrong layer. Runs on CPU (conftest) — fp32 exact."""

import pytest

pytestmark = pytest.mark.slow  # whole-model parity: minutes on CPU

from tools.layer_diff import i3d_layer_diff, raft_layer_diff


@pytest.mark.parametrize("modality", ["rgb", "flow"])
def test_i3d_every_layer_matches(modality):
    rows = i3d_layer_diff(modality, shape=(1, 16, 64, 64))
    assert len(rows) == 12  # 4 stem convs/pools named + 9 mixed − pools untapped
    for name, diff, scale in rows:
        assert diff <= 1e-4 + 1e-5 * max(scale, 1.0), f"{name} diverges: {diff} (scale {scale})"


def test_raft_every_stage_matches():
    rows = raft_layer_diff(shape=(1, 128, 128), iters=4)
    names = [r[0] for r in rows]
    assert {"fnet1", "fnet2", "cnet", "corr_l0"} <= set(names)
    assert sum(n.startswith("flow_iter") for n in names) == 4
    for name, diff, scale in rows:
        # recurrent iterations amplify fp noise ~2× per step; bound generously
        tol = 1e-3 if name.startswith("flow_iter") else 1e-4
        assert diff <= tol * max(scale, 1.0), f"{name} diverges: {diff} (scale {scale})"
