"""vftlint: the repo is clean, and every rule both fires and suppresses.

Two layers:

- **tier-1 guard**: the full rule suite over this checkout returns zero
  findings (any unannotated regression in jit-purity / host-sync /
  thread-shared-state / explicit-dtype / fault-barrier / fast-registry /
  lock-order / guarded-by / blocking-under-lock / use-after-donate /
  recompile-hygiene / wire-dtype / telemetry-schema fails this module);
- **fixture tests**: per rule, a seeded violation in a tmp tree fires and
  the annotated/clean form stays quiet — the acceptance contract that no
  rule is satisfied by blanket allowlisting.

Also pinned here: the parse-once budget (every source parsed exactly once
per run regardless of rule count, plus a generous wall-clock ceiling) and
the :class:`LockOrderWatch` runtime shim the daemon tests wrap their named
locks with.

Pure AST work, no jax import, no compiles — registered in _FAST_MODULES.
"""

import os
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from tools.vftlint import all_rules, run_lint  # noqa: E402
from tools.vftlint.__main__ import main as vftlint_main  # noqa: E402
from tools.vftlint.locks import LockOrderWatch  # noqa: E402
from tools.vftlint.rules import fast_registry, lock_order  # noqa: E402

ALL_RULE_IDS = {
    "blocking-under-lock", "explicit-dtype", "fast-registry",
    "fault-barrier", "guarded-by", "host-sync", "jit-purity",
    "lock-order", "recompile-hygiene", "telemetry-schema",
    "thread-shared-state", "use-after-donate", "wire-dtype",
}


def write(root, rel, text):
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(text))
    return path


def lint(root, rule):
    return [str(f) for f in run_lint(str(root), [rule])]


# ---- tier-1 guard ---------------------------------------------------------


def test_registry_ships_all_rules():
    assert set(all_rules()) == ALL_RULE_IDS


def test_repo_is_clean():
    findings = run_lint(REPO)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_cli_clean_exit(capsys):
    assert vftlint_main([REPO]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_unknown_rule_is_usage_error(capsys):
    assert vftlint_main(["--rule", "no-such-rule", REPO]) == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_findings_exit(tmp_path, capsys):
    write(tmp_path, "video_features_tpu/models/m.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    assert vftlint_main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "explicit-dtype" in out and "models/m.py:2" in out


def test_cli_list_rules(capsys):
    assert vftlint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in ALL_RULE_IDS:
        assert rule_id in out


# ---- jit-purity -----------------------------------------------------------

JIT_IMPURE = """
    import time
    import jax

    @jax.jit
    def step(x):
        print("tracing", x.shape)
        t = time.time()
        return x * t
"""

JIT_WRAPPED = """
    class E:
        def make(self):
            def step(params, x):
                import random
                return x * random.random()
            return self.runner.jit(step)
"""


def test_jit_purity_fires_on_decorated(tmp_path):
    write(tmp_path, "video_features_tpu/bad.py", JIT_IMPURE)
    found = lint(tmp_path, "jit-purity")
    assert any("'print()'" in f and "bad.py:7" in f for f in found)
    assert any("time.time" in f for f in found)


def test_jit_purity_fires_through_runner_jit(tmp_path):
    write(tmp_path, "video_features_tpu/bad.py", JIT_WRAPPED)
    found = lint(tmp_path, "jit-purity")
    assert any("stdlib 'random.random()'" in f for f in found)


def test_jit_purity_fires_through_shard_map(tmp_path):
    write(tmp_path, "video_features_tpu/bad.py", """
        def fwd(params, frames, mesh):
            def local(p, fr):
                print(fr.shape)
                return fr
            return shard_map(local, mesh=mesh)(params, frames)
    """)
    assert any("'print()'" in f for f in lint(tmp_path, "jit-purity"))


def test_jit_purity_quiet_on_clean_and_untraced(tmp_path):
    write(tmp_path, "video_features_tpu/ok.py", """
        import jax

        @jax.jit
        def step(x):
            return x * 2

        def host_loop(xs):  # not traced: host effects are fine here
            for x in xs:
                print(x)
    """)
    assert lint(tmp_path, "jit-purity") == []


def test_jit_purity_annotation_suppresses_with_reason(tmp_path):
    write(tmp_path, "video_features_tpu/ok.py", """
        import jax

        @jax.jit
        def step(x):
            # jit-purity: trace-time banner, deliberately prints once per compile
            print("compiling")
            return x
    """)
    assert lint(tmp_path, "jit-purity") == []


def test_empty_annotation_reason_is_a_finding(tmp_path):
    write(tmp_path, "video_features_tpu/bad.py", """
        import jax

        @jax.jit
        def step(x):
            print("hi")  # jit-purity:
            return x
    """)
    found = lint(tmp_path, "jit-purity")
    assert any("no reason" in f for f in found)
    assert any("'print()'" in f for f in found)  # not suppressed either


# ---- host-sync ------------------------------------------------------------

HOST_SYNC_BAD = """
    import numpy as np

    class E:
        def extract(self, path):
            feats = self._step(self.params, path)
            a = np.asarray(feats)
            b = float(feats)
            c = feats.item()
            return a, b, c
"""

HOST_SYNC_OK = """
    import numpy as np

    class E:
        def extract(self, path):
            feats = self._step(self.params, path)
            host = self._wait(feats)          # the accounted site
            meta_fps = np.asarray([25.0])     # host data: not flagged
            return host, meta_fps
"""


def test_host_sync_fires_on_unaccounted_sinks(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/bad.py", HOST_SYNC_BAD)
    found = lint(tmp_path, "host-sync")
    assert any("np.asarray()" in f for f in found)
    assert any("float()" in f for f in found)
    assert any(".item()" in f for f in found)


def test_host_sync_quiet_when_routed_through_wait(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", HOST_SYNC_OK)
    assert lint(tmp_path, "host-sync") == []


def test_host_sync_tracks_params_and_unpacking(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        import numpy as np

        class E:
            def extract(self, x):
                feats, logits = self._flow_step(self.params, x)
                fc = self.params["fc"]
                a = np.asarray(logits)   # tainted via tuple unpack
                b = np.asarray(fc["kernel"])  # tainted via *params attr
                return a @ b
    """)
    found = lint(tmp_path, "host-sync")
    assert len([f for f in found if "np.asarray()" in f]) == 2


def test_host_sync_fires_inside_traced_body(tmp_path):
    write(tmp_path, "video_features_tpu/models/bad.py", """
        import numpy as np
        import jax

        @jax.jit
        def step(x):
            return np.asarray(x) * 2
    """)
    assert any("mid-trace" in f for f in lint(tmp_path, "host-sync"))


def test_host_sync_branch_rewait_is_not_flagged(tmp_path):
    """A value re-assigned from _wait INSIDE a branch is host there — the
    sink check must see the in-branch state, not the pre-block taint."""
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import numpy as np

        class E:
            def extract(self, x, debug):
                feats = self._step(self.params, x)
                if debug:
                    feats = self._wait(feats)
                    logits = np.asarray(feats) * 2.0
                return feats
    """)
    assert lint(tmp_path, "host-sync") == []


def test_host_sync_else_branch_keeps_pre_branch_taint(tmp_path):
    """The if-arm's _wait kill must not leak into the else arm."""
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        import numpy as np

        class E:
            def extract(self, x, debug):
                feats = self._step(self.params, x)
                if debug:
                    feats = self._wait(feats)
                else:
                    feats = np.asarray(feats)
                return feats
    """)
    assert any("np.asarray()" in f for f in lint(tmp_path, "host-sync"))


def test_host_sync_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import numpy as np

        class E:
            def warm(self, x):
                # host-sync: warmup thread, off the critical path
                np.asarray(self._step(self.params, x))
    """)
    assert lint(tmp_path, "host-sync") == []


# ---- thread-shared-state --------------------------------------------------


def test_thread_rule_fires_on_undeclared_module(tmp_path):
    write(tmp_path, "video_features_tpu/sneaky.py", """
        import threading

        def go(fn):
            threading.Thread(target=fn, daemon=True).start()
    """)
    found = lint(tmp_path, "thread-shared-state")
    assert any("no declared threading seam" in f for f in found)


def test_thread_rule_fires_on_unannotated_shared_store(tmp_path):
    # declared module path, declared site — but the annotation is missing
    write(tmp_path, "video_features_tpu/io/output.py", """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                handle = self._q.get()
                handle._error = ValueError("x")
    """)
    found = lint(tmp_path, "thread-shared-state")
    assert any("without a '# thread-shared-state:" in f for f in found)
    # declared in SHARED_WRITES, so no 'not declared' finding for this site
    assert not any("not declared" in f for f in found)


def test_thread_rule_fires_on_undeclared_shared_store(tmp_path):
    write(tmp_path, "video_features_tpu/io/output.py", """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                handle = self._q.get()
                handle._error = 1  # thread-shared-state: before the Event
                handle._extra = 2  # thread-shared-state: sounds legit
    """)
    found = lint(tmp_path, "thread-shared-state")
    undeclared = [f for f in found if "not declared in SHARED_WRITES" in f]
    assert len(undeclared) == 1 and "handle._extra" in undeclared[0]


def test_thread_rule_exempts_thread_private_objects(tmp_path):
    """Stores to an object constructed inside the thread entry are
    thread-private until published — not shared state."""
    write(tmp_path, "video_features_tpu/io/output.py", """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                handle = self._q.get()
                handle._error = 1  # thread-shared-state: before the Event
                meta = Thing()
                meta.count = 0
                self._q2.put(meta)
    """)
    found = lint(tmp_path, "thread-shared-state")
    assert not any("meta.count" in f for f in found)
    assert found == []  # handle._error annotated + declared; nothing else


def test_thread_rule_empty_annotation_reason_message(tmp_path):
    """A reasonless annotation reports 'no reason', not 'without a ...
    annotation' — the developer already wrote the comment."""
    write(tmp_path, "video_features_tpu/io/output.py", """
        import threading

        class W:
            def start(self):
                threading.Thread(target=self._drain).start()

            def _drain(self):
                handle = self._q.get()
                handle._error = 1  # thread-shared-state:
    """)
    found = lint(tmp_path, "thread-shared-state")
    assert any("no reason" in f for f in found)
    assert not any("without a" in f for f in found)


def test_thread_rule_reports_stale_declarations(tmp_path):
    # the declared module spawns a thread whose target stores nothing:
    # every declared site for it is stale
    write(tmp_path, "video_features_tpu/io/output.py", """
        import threading

        def start(fn):
            threading.Thread(target=fn).start()
    """)
    found = lint(tmp_path, "thread-shared-state")
    assert any("stale declaration" in f and "handle._error" in f
               for f in found)


def test_thread_rule_quiet_on_threadless_module(tmp_path):
    write(tmp_path, "video_features_tpu/plain.py",
          "def f(x):\n    return x + 1\n")
    assert lint(tmp_path, "thread-shared-state") == []


# ---- explicit-dtype -------------------------------------------------------


def test_explicit_dtype_fires_in_models_and_ops(tmp_path):
    write(tmp_path, "video_features_tpu/models/m.py", """
        import jax.numpy as jnp
        MEAN = jnp.asarray([0.43, 0.39, 0.37])
        Z = jnp.zeros((3, 3))
        R = jnp.arange(10)
    """)
    found = lint(tmp_path, "explicit-dtype")
    assert len(found) == 3
    assert all("explicit-dtype" in f for f in found)


def test_explicit_dtype_quiet_on_dtyped_and_like(tmp_path):
    write(tmp_path, "video_features_tpu/ops/o.py", """
        import jax.numpy as jnp

        def f(x):
            a = jnp.asarray([1.0], jnp.float32)       # positional dtype
            b = jnp.zeros((2, 2), dtype=jnp.int32)    # keyword dtype
            c = jnp.arange(4, dtype=jnp.int32)
            d = jnp.zeros_like(x)                     # inherits dtype
            return a, b, c, d
    """)
    assert lint(tmp_path, "explicit-dtype") == []


def test_explicit_dtype_out_of_scope_dirs_are_ignored(tmp_path):
    # host-side code (io/, utils/) may promote freely
    write(tmp_path, "video_features_tpu/io/h.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    assert lint(tmp_path, "explicit-dtype") == []


def test_explicit_dtype_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/models/m.py", """
        import jax.numpy as jnp
        # explicit-dtype: promotion wanted — follows the input's dtype knob
        MEAN = jnp.asarray([0.43])
    """)
    assert lint(tmp_path, "explicit-dtype") == []


# ---- fault-barrier (migrated rule) ----------------------------------------


def test_fault_barrier_rule_fires_via_framework(tmp_path):
    write(tmp_path, "video_features_tpu/sneaky.py",
          "try:\n    pass\nexcept Exception:\n    pass\n")
    found = lint(tmp_path, "fault-barrier")
    assert any("fault-barrier" in f and "sneaky.py:3" in f for f in found)
    assert any("no declared barriers" in f for f in found)


def test_fault_barrier_rule_quiet_on_clean_tree(tmp_path):
    write(tmp_path, "video_features_tpu/fine.py",
          "try:\n    pass\nexcept ValueError:\n    pass\n")
    assert lint(tmp_path, "fault-barrier") == []


def test_shim_still_works():
    """python tools/lint_fault_barrier.py keeps its PR-1 contract."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lint_fault_barrier.py")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "no strays" in proc.stdout


# ---- fast-registry --------------------------------------------------------


def _tiered_tree(tmp_path):
    write(tmp_path, "tests/conftest.py",
          '_FAST_MODULES = {\n    "test_a",\n}\n')
    write(tmp_path, "tests/test_a.py", "def test_x():\n    pass\n")
    write(tmp_path, "tests/test_b.py",
          "import pytest\npytestmark = pytest.mark.slow\n")


def test_fast_registry_quiet_on_tiered_modules(tmp_path, monkeypatch):
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER", {})
    _tiered_tree(tmp_path)
    assert lint(tmp_path, "fast-registry") == []


def test_fast_registry_fires_on_untiered_module(tmp_path, monkeypatch):
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER", {})
    _tiered_tree(tmp_path)
    write(tmp_path, "tests/test_c.py", "def test_y():\n    pass\n")
    found = lint(tmp_path, "fast-registry")
    assert len(found) == 1 and "'test_c' is in no tier" in found[0]


def test_fast_registry_default_tier_needs_annotation(tmp_path, monkeypatch):
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER",
                        {"test_c": "mid-weight"})
    _tiered_tree(tmp_path)
    write(tmp_path, "tests/test_c.py", "def test_y():\n    pass\n")
    found = lint(tmp_path, "fast-registry")
    assert len(found) == 1 and "carries no" in found[0]
    # the annotated form is quiet
    write(tmp_path, "tests/test_c.py",
          "# fast-registry: mid-weight, compiles too heavy for fast\n"
          "def test_y():\n    pass\n")
    assert lint(tmp_path, "fast-registry") == []


def test_fast_registry_rejects_reasonless_annotation(tmp_path, monkeypatch):
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER",
                        {"test_c": "mid-weight"})
    _tiered_tree(tmp_path)
    write(tmp_path, "tests/test_c.py",
          "# fast-registry:\ndef test_y():\n    pass\n")
    found = lint(tmp_path, "fast-registry")
    assert len(found) == 1 and "has no reason" in found[0]


def test_fast_registry_reports_stale_default_tier_entry(tmp_path, monkeypatch):
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER", {"test_gone": "x"})
    _tiered_tree(tmp_path)
    found = lint(tmp_path, "fast-registry")
    assert any("no such test module" in f for f in found)


def test_fast_registry_missing_conftest(tmp_path):
    write(tmp_path, "tests/test_a.py", "def test_x():\n    pass\n")
    found = lint(tmp_path, "fast-registry")
    assert any("registry is missing" in f for f in found)


# ---- lock-order -----------------------------------------------------------

TWO_LOCKS = """
    import threading

    class S:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()
"""
A = "video_features_tpu/locky.py:S._a"
B = "video_features_tpu/locky.py:S._b"


def _locky(tmp_path, body):
    # body joins TWO_LOCKS *inside* class S (8 = the class-body indent in
    # the raw fixture string, which write() dedents by 4)
    write(tmp_path, "video_features_tpu/locky.py",
          TWO_LOCKS + textwrap.indent(textwrap.dedent(body), "        "))


def test_lock_order_fires_on_inversion(tmp_path, monkeypatch):
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [B, A])
    _locky(tmp_path, """
        def fwd(self):
            with self._a:
                with self._b:
                    pass
    """)
    found = lint(tmp_path, "lock-order")
    assert len(found) == 1 and "inversion" in found[0]
    assert "S._a" in found[0] and "S._b" in found[0]


def test_lock_order_quiet_when_order_matches(tmp_path, monkeypatch):
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [A, B])
    _locky(tmp_path, """
        def fwd(self):
            with self._a:
                with self._b:
                    pass
    """)
    assert lint(tmp_path, "lock-order") == []


def test_lock_order_fires_on_cycle(tmp_path, monkeypatch):
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [A, B])
    _locky(tmp_path, """
        def fwd(self):
            with self._a:
                with self._b:
                    pass

        def rev(self):
            with self._b:
                with self._a:
                    pass
    """)
    found = lint(tmp_path, "lock-order")
    assert any("cycle" in f for f in found)
    assert any("inversion" in f for f in found)  # rev() also inverts


def test_lock_order_follows_helper_calls(tmp_path, monkeypatch):
    """Interprocedural: the nested acquisition lives two frames down."""
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [B, A])
    _locky(tmp_path, """
        def outer(self):
            with self._a:
                self._inner()

        def _inner(self):
            self._innermost()

        def _innermost(self):
            with self._b:
                pass
    """)
    found = lint(tmp_path, "lock-order")
    assert len(found) == 1 and "inversion" in found[0] and "via" in found[0]
    # the declared direction is quiet
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [A, B])
    assert lint(tmp_path, "lock-order") == []


def test_lock_order_unordered_nesting_is_a_finding(tmp_path):
    # no monkeypatch: the fixture locks have no LOCK_ORDER position, and
    # nesting is exactly the moment a lock must be named and ordered
    _locky(tmp_path, """
        def fwd(self):
            with self._a:
                with self._b:
                    pass
    """)
    found = lint(tmp_path, "lock-order")
    assert any("no LOCK_ORDER position" in f for f in found)


def test_lock_order_self_deadlock_on_plain_lock(tmp_path, monkeypatch):
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [A, B])
    _locky(tmp_path, """
        def f(self):
            with self._a:
                with self._a:
                    pass
    """)
    found = lint(tmp_path, "lock-order")
    assert len(found) == 1 and "self-deadlock" in found[0]


def test_lock_order_rlock_reentry_is_fine(tmp_path):
    write(tmp_path, "video_features_tpu/locky.py", """
        import threading

        class S:
            def __init__(self):
                self._r = threading.RLock()

            def f(self):
                with self._r:
                    with self._r:
                        pass
    """)
    assert lint(tmp_path, "lock-order") == []


def test_lock_order_annotation_suppresses(tmp_path, monkeypatch):
    monkeypatch.setattr(lock_order, "LOCK_ORDER", [B, A])
    _locky(tmp_path, """
        def fwd(self):
            with self._a:
                # lock-order: teardown-only path; b's owner thread is joined
                with self._b:
                    pass
    """)
    assert lint(tmp_path, "lock-order") == []


# ---- guarded-by -----------------------------------------------------------

JOURNAL_OK = """
    import threading

    class SpanJournal:
        def __init__(self):
            self._lock = threading.Lock()
            self.emitted = 0
            self.dropped = 0

        def emit(self, rec):
            with self._lock:
                self.emitted += 1
                self.dropped += 0
"""


def test_guarded_by_quiet_on_locked_access(tmp_path):
    write(tmp_path, "video_features_tpu/obs/journal.py", JOURNAL_OK)
    assert lint(tmp_path, "guarded-by") == []


def test_guarded_by_fires_on_off_lock_read(tmp_path):
    write(tmp_path, "video_features_tpu/obs/journal.py", JOURNAL_OK + """
        def stats(self):
            return {"emitted": self.emitted}
""")
    found = lint(tmp_path, "guarded-by")
    assert len(found) == 1
    assert "self.emitted" in found[0] and "'journal'" in found[0]


def test_guarded_by_fires_on_off_lock_dict_iteration(tmp_path):
    write(tmp_path, "video_features_tpu/obs/metrics.py", """
        import threading

        class MetricsRegistry:
            def __init__(self):
                self._lock = threading.Lock()
                self._counters = {}
                self._gauges = {}
                self._hists = {}

            def inc(self, k):
                with self._lock:
                    self._counters[k] = self._gauges.get(k, 0)
                    self._hists[k] = 1

            def snapshot(self):
                return sorted(self._counters.items())
    """)
    found = lint(tmp_path, "guarded-by")
    assert len(found) == 1 and "self._counters" in found[0]
    assert "snapshot" in found[0]


def test_guarded_by_locked_suffix_is_exempt(tmp_path):
    write(tmp_path, "video_features_tpu/obs/journal.py", JOURNAL_OK + """
        def stats_locked(self):
            return self.emitted + self.dropped
""")
    assert lint(tmp_path, "guarded-by") == []


def test_guarded_by_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/obs/journal.py", JOURNAL_OK + """
        def stats(self):
            # guarded-by: GIL-atomic monotone int; off-by-one-moment is fine
            return self.emitted
""")
    assert lint(tmp_path, "guarded-by") == []


def test_guarded_by_reports_stale_declaration(tmp_path):
    write(tmp_path, "video_features_tpu/obs/journal.py", """
        import threading

        class SpanJournal:
            def __init__(self):
                self._lock = threading.Lock()
                self.emitted = 0

            def emit(self):
                with self._lock:
                    self.emitted += 1
    """)
    found = lint(tmp_path, "guarded-by")
    assert len(found) == 1
    assert "stale" in found[0] and "self.dropped" in found[0]


# ---- blocking-under-lock --------------------------------------------------

MU = """
    import threading
    import time

    class S:
        def __init__(self):
            self._mu = threading.Lock()
            self._q = None
"""


def _blocky(tmp_path, body):
    # body joins MU *inside* class S (see _locky)
    write(tmp_path, "video_features_tpu/blocky.py",
          MU + textwrap.indent(textwrap.dedent(body), "        "))


def test_blocking_fires_on_sleep_under_lock(tmp_path):
    _blocky(tmp_path, """
        def bad(self):
            with self._mu:
                time.sleep(0.1)
    """)
    found = lint(tmp_path, "blocking-under-lock")
    assert len(found) == 1 and "time.sleep()" in found[0]


def test_blocking_quiet_outside_lock(tmp_path):
    _blocky(tmp_path, """
        def ok(self):
            with self._mu:
                x = 1
            time.sleep(0.1)
            return x
    """)
    assert lint(tmp_path, "blocking-under-lock") == []


def test_blocking_follows_helper_calls(tmp_path):
    _blocky(tmp_path, """
        def bad(self):
            with self._mu:
                self._flush()

        def _flush(self):
            with open("/tmp/x") as f:
                return f.read()
    """)
    found = lint(tmp_path, "blocking-under-lock")
    assert len(found) == 1
    assert "via S._flush" in found[0] and "open()" in found[0]


def test_blocking_queue_put_vs_put_nowait(tmp_path):
    _blocky(tmp_path, """
        def bad(self, item):
            with self._mu:
                self._q.put(item)

        def ok(self, item):
            with self._mu:
                self._q.put_nowait(item)
    """)
    found = lint(tmp_path, "blocking-under-lock")
    assert len(found) == 1 and "queue .put()" in found[0]
    assert "bad" in found[0]


def test_blocking_device_sync_under_lock(tmp_path):
    _blocky(tmp_path, """
        def bad(self, feats):
            with self._mu:
                return self._wait(feats)
    """)
    found = lint(tmp_path, "blocking-under-lock")
    assert len(found) == 1 and "._wait()" in found[0]


def test_blocking_nested_def_is_not_under_the_lock(tmp_path):
    """A def/lambda created under a lock runs later, lock-free."""
    _blocky(tmp_path, """
        def ok(self):
            with self._mu:
                def worker():
                    time.sleep(1.0)
                self._worker = worker
    """)
    assert lint(tmp_path, "blocking-under-lock") == []


def test_blocking_annotation_suppresses(tmp_path):
    _blocky(tmp_path, """
        def shutdown(self):
            with self._mu:
                # blocking-under-lock: teardown path; no producer is live
                time.sleep(0.01)
    """)
    assert lint(tmp_path, "blocking-under-lock") == []


# ---- use-after-donate -----------------------------------------------------

# the PR-13 wiring shape: jit_paged forwards its fn into sharded_apply,
# which donates argnum 2 — discovered (not hardcoded) by prepare()
DONATE_MESH = """
    import jax

    def sharded_apply(mesh, fn, donate_argnums=()):
        return jax.jit(fn, donate_argnums=donate_argnums)

    class MeshRunner:
        def jit_paged(self, fn):
            return sharded_apply(self.mesh, fn, donate_argnums=(2,))
"""


def test_donate_fires_on_read_after_direct_donation(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/bad.py", """
        import jax

        class R:
            def run(self, step, x):
                fn = jax.jit(step, donate_argnums=(0,))
                buf = self.runner.put(x)
                out = fn(buf)
                return out + buf
    """)
    found = lint(tmp_path, "use-after-donate")
    assert len(found) == 1
    assert "'buf' is read after its buffer was donated" in found[0]
    assert "jax.jit(donate_argnums=(0,))" in found[0]


def test_donate_fires_through_helper_frame_naming_the_chain(tmp_path):
    """Donation through the discovered wiring wrapper: the finding names
    the via-call chain jit_paged → sharded_apply."""
    write(tmp_path, "video_features_tpu/parallel/mesh.py", DONATE_MESH)
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        class E:
            def pack_spec(self, step, rows, page):
                fn = self.runner.jit_paged(step)
                table = self.runner.put(rows)
                out = fn(self.params, page, table)
                return self._wait(table)
    """)
    found = lint(tmp_path, "use-after-donate")
    assert len(found) == 1 and "bad.py:7" in found[0]
    assert "donated at line 6" in found[0]
    assert "jit_paged → sharded_apply(donate_argnums=(2,))" in found[0]
    assert "video_features_tpu/parallel/mesh.py" in found[0]


def test_donate_quiet_when_rebound_from_output(tmp_path):
    """The paged contract: the donated table comes back as an output —
    rebinding the name to the returned buffer is the sanctioned idiom."""
    write(tmp_path, "video_features_tpu/parallel/mesh.py", DONATE_MESH)
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        class E:
            def pack_spec(self, step, rows, page):
                fn = self.runner.jit_paged(step)
                table = self.runner.put(rows)
                out, table = fn(self.params, page, table)
                return self._wait(table)
    """)
    assert lint(tmp_path, "use-after-donate") == []


def test_donate_host_values_are_not_tracked(tmp_path):
    """Passing a host array donates the transient device copy; the host
    original stays valid (the packer's row-table path relies on this)."""
    write(tmp_path, "video_features_tpu/parallel/ok.py", """
        import jax
        import numpy as np

        class R:
            def run(self, step, rows):
                fn = jax.jit(step, donate_argnums=(0,))
                host = np.stack(rows)
                out = fn(host)
                return out, host.shape
    """)
    assert lint(tmp_path, "use-after-donate") == []


def test_donate_fires_on_loop_without_restage(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/bad.py", """
        import jax

        class R:
            def drain(self, step, x, pages):
                fn = jax.jit(step, donate_argnums=(1,))
                buf = self.runner.put(x)
                for page in pages:
                    out = fn(page, buf)
    """)
    found = lint(tmp_path, "use-after-donate")
    assert len(found) == 1
    assert "donated inside a loop without being re-staged" in found[0]


def test_donate_quiet_on_loop_with_restage(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/ok.py", """
        import jax

        class R:
            def drain(self, step, x, pages):
                fn = jax.jit(step, donate_argnums=(1,))
                buf = self.runner.put(x)
                for page in pages:
                    out = fn(page, buf)
                    buf = self.runner.put(out)
    """)
    assert lint(tmp_path, "use-after-donate") == []


def test_donate_pair_check_fires_when_param_not_returned(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/bad.py", """
        import jax

        def paged(params, page, table):
            return params @ page

        def build():
            return jax.jit(paged, donate_argnums=(2,))
    """)
    found = lint(tmp_path, "use-after-donate")
    assert len(found) == 1
    assert "donated parameter 'table' of 'paged' is not returned" in found[0]


def test_donate_pair_check_quiet_on_passthrough(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/ok.py", """
        import jax

        def paged(params, page, table):
            return params @ page, table

        def build():
            return jax.jit(paged, donate_argnums=(2,))
    """)
    assert lint(tmp_path, "use-after-donate") == []


def test_donate_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/parallel/ok.py", """
        import jax

        class R:
            def run(self, step, x):
                fn = jax.jit(step, donate_argnums=(0,))
                buf = self.runner.put(x)
                out = fn(buf)
                # use-after-donate: shape probe reads metadata, not storage
                return out, buf.shape
    """)
    assert lint(tmp_path, "use-after-donate") == []


# ---- recompile-hygiene ----------------------------------------------------


def test_recompile_fires_on_jit_in_loop(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        import jax

        class E:
            def warm(self, fns):
                for fn in fns:
                    step = jax.jit(fn)
    """)
    found = lint(tmp_path, "recompile-hygiene")
    assert len(found) == 1
    assert "constructed inside a loop" in found[0]


def test_recompile_fires_on_reachable_from_extract_with_chain(tmp_path):
    """Construction two frames below extract(): the finding names the
    via-call chain through the name-based call graph."""
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        import jax

        class E:
            def extract(self, path):
                return self._build()(path)

            def _build(self):
                return jax.jit(self._fwd)
    """)
    found = lint(tmp_path, "recompile-hygiene")
    assert len(found) == 1
    assert "constructed per call" in found[0]
    assert "E.extract → E._build" in found[0]


def test_recompile_quiet_when_memoized_into_declared_table(tmp_path):
    """The _paged_fields pattern: a construction dominated by a miss on a
    declared memo table runs once per key."""
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import jax

        class E:
            def extract(self, path):
                return self._step_for(path.depth)(path)

            def _step_for(self, key):
                cache = self.__dict__.setdefault("_paged_programs", {})
                if key not in cache:
                    step = jax.jit(self._fwd)
                    cache[key] = step
                return cache[key]
    """)
    assert lint(tmp_path, "recompile-hygiene") == []


def test_recompile_quiet_in_init_and_cached_property(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import jax
        from functools import cached_property

        class E:
            def __init__(self, fwd):
                self._step = jax.jit(fwd)

            @cached_property
            def paged(self):
                return jax.jit(self._paged_fwd)

            def extract(self, path):
                return self._step(path)
    """)
    assert lint(tmp_path, "recompile-hygiene") == []


def test_recompile_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import jax

        class E:
            def extract(self, path):
                # recompile-hygiene: one-shot CLI path, process exits after
                step = jax.jit(self._fwd)
                return step(path)
    """)
    assert lint(tmp_path, "recompile-hygiene") == []


# ---- wire-dtype -----------------------------------------------------------


def test_wire_dtype_fires_on_float_cast_to_staging(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/bad.py", """
        import numpy as np

        class E:
            def stage(self, frames):
                batch = frames.astype(np.float32)
                return self._put(batch)
    """)
    found = lint(tmp_path, "wire-dtype")
    assert len(found) == 1
    assert "float-cast value reaches staging sink" in found[0]


def test_wire_dtype_fires_through_sink_alias(tmp_path):
    """`put = self.runner.put` then `put(batch)` is still a staging sink."""
    write(tmp_path, "video_features_tpu/parallel/bad.py", """
        class P:
            def dispatch(self, frames, timed):
                put = self._put if timed else self.runner.put
                batch = frames.astype("float32")
                return put(batch)
    """)
    found = lint(tmp_path, "wire-dtype")
    assert len(found) == 1 and "staging sink" in found[0]


def test_wire_dtype_quiet_behind_declared_escape(tmp_path):
    """Both escape shapes: the `wire = f32 if cfg.float32_wire else u8`
    IfExp, and a cast lexically inside `if cfg.float32_wire:`."""
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import numpy as np

        class E:
            def stage(self, frames):
                wire = np.float32 if self.cfg.float32_wire else np.uint8
                batch = frames.astype(wire)
                return self._put(batch)

            def stage_parity(self, frames):
                if self.cfg.float32_wire:
                    batch = frames.astype(np.float32)
                    return self._put(batch)
                return self._put(frames)
    """)
    assert lint(tmp_path, "wire-dtype") == []


def test_wire_dtype_uint8_wire_is_quiet(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import numpy as np

        class E:
            def stage(self, frames):
                batch = np.ascontiguousarray(frames.astype(np.uint8))
                return self._put(batch)
    """)
    assert lint(tmp_path, "wire-dtype") == []


def test_wire_dtype_vggish_is_exempt_wholesale(tmp_path):
    # float PCM audio wire by design — there is no uint8 wire for waveforms
    write(tmp_path, "video_features_tpu/extractors/vggish.py", """
        import numpy as np

        class V:
            def stage(self, pcm):
                return self._put(pcm.astype(np.float32))
    """)
    assert lint(tmp_path, "wire-dtype") == []


def test_wire_dtype_annotation_suppresses(tmp_path):
    write(tmp_path, "video_features_tpu/extractors/ok.py", """
        import numpy as np

        class E:
            def stage(self, frames):
                batch = frames.astype(np.float32)
                # wire-dtype: one-off fp32 calibration, not a serving path
                return self._put(batch)
    """)
    assert lint(tmp_path, "wire-dtype") == []


# ---- telemetry-schema -----------------------------------------------------

OBS_DOC = """
    ### Event catalogue

    | Event | Emitted by | Fields (beyond `ts`/`event`) |
    |---|---|---|
    | `video_done` | run loops | `video`, `model` |
    | `video_failed` | terminal accounting | `video`, `model`, `error_class` |
"""


def test_telemetry_fires_on_catalogue_missing_event(tmp_path):
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/serve/s.py", """
        class S:
            def run(self, v):
                self._journal.emit("mystery_event", video=v)
    """)
    found = lint(tmp_path, "telemetry-schema")
    assert len(found) == 1
    assert "'mystery_event' is not in the docs/observability.md" in found[0]


def test_telemetry_fires_through_forwarding_wrapper(tmp_path):
    """The Extractor._emit shape: the wrapper forwards its event parameter
    and injects fields; call sites are classified through it."""
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/extractors/base.py", """
        class E:
            def _emit(self, event, **fields):
                if self._journal is not None:
                    self._journal.emit(event, model=self.name, **fields)

            def extract(self, v):
                self._emit("mystery_event", video=v)
    """)
    found = lint(tmp_path, "telemetry-schema")
    assert len(found) == 1
    assert "'mystery_event'" in found[0] and "base.py:8" in found[0]


def test_telemetry_fires_on_undocumented_field(tmp_path):
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/serve/s.py", """
        class S:
            def run(self, v):
                self._journal.emit("video_done", video=v, model="m",
                                   surprise=1)
    """)
    found = lint(tmp_path, "telemetry-schema")
    assert len(found) == 1
    assert "undocumented field(s) surprise" in found[0]


def test_telemetry_quiet_on_documented_events(tmp_path):
    """Literal and branch-resolved event names, documented fields only."""
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/serve/s.py", """
        class S:
            def run(self, v, ok):
                name = "video_done" if ok else "video_failed"
                self._journal.emit(name, video=v, model="m")
    """)
    assert lint(tmp_path, "telemetry-schema") == []


def test_telemetry_unresolvable_event_name_is_a_finding(tmp_path):
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/serve/s.py", """
        class S:
            def run(self):
                self._journal.emit(self.event_name, video=1)
    """)
    found = lint(tmp_path, "telemetry-schema")
    assert len(found) == 1
    assert "not statically resolvable" in found[0]


def test_telemetry_stats_schema_two_way(tmp_path):
    write(tmp_path, "docs/serving.md", """
        ## The `stats` payload (schema 1)

        | Field | Meaning |
        |---|---|
        | `ok`, `schema` | op success; payload version |
        | `packing.{real_slots}` | packer totals |
        | `ghost` | documented but never emitted |
    """)
    write(tmp_path, "video_features_tpu/serve/daemon.py", """
        class S:
            def stats(self):
                return {
                    "ok": True,
                    "schema": 1,
                    "packing": {"real_slots": 1, "occupancy": 0.5},
                    "extra_top": 2,
                }
    """)
    found = lint(tmp_path, "telemetry-schema")
    assert any("undocumented top-level field 'extra_top'" in f
               for f in found)
    assert any("'packing.occupancy' is not in the" in f for f in found)
    assert any("documents 'ghost' but the stats op no longer emits"
               in f for f in found)
    assert len(found) == 3


def test_telemetry_stats_quiet_when_documented(tmp_path):
    write(tmp_path, "docs/serving.md", """
        ## The `stats` payload (schema 1)

        | Field | Meaning |
        |---|---|
        | `ok`, `schema` | op success; payload version |
        | `packing.{real_slots, occupancy}` | packer totals |
        | `tenants.<name>.{pending}` | not enumerable: wildcard subs |
    """)
    write(tmp_path, "video_features_tpu/serve/daemon.py", """
        class S:
            def stats(self):
                return {
                    "ok": True,
                    "schema": 1,
                    "packing": {"real_slots": 1, "occupancy": 0.5},
                    "tenants": self.queue.stats(),
                }
    """)
    assert lint(tmp_path, "telemetry-schema") == []


def test_telemetry_annotation_suppresses(tmp_path):
    write(tmp_path, "docs/observability.md", OBS_DOC)
    write(tmp_path, "video_features_tpu/serve/s.py", """
        class S:
            def run(self, v):
                # telemetry-schema: staging-only probe, stripped pre-release
                self._journal.emit("probe_event", video=v)
    """)
    assert lint(tmp_path, "telemetry-schema") == []


# ---- stale-suppression reconciliation -------------------------------------


def test_stale_suppression_is_flagged(tmp_path):
    """An annotation nothing consumed this run is dead weight — the same
    reconciliation stale lock declarations get."""
    write(tmp_path, "video_features_tpu/models/m.py", """
        import jax.numpy as jnp
        # explicit-dtype: promotion wanted (the violation is long gone)
        x = jnp.zeros((2,), dtype=jnp.float32)
    """)
    found = lint(tmp_path, "explicit-dtype")
    assert len(found) == 1
    assert "stale '# explicit-dtype:' suppression" in found[0]


def test_live_suppression_is_not_stale(tmp_path):
    # consumed by the rule → no stale finding, no violation finding
    write(tmp_path, "video_features_tpu/models/m.py", """
        import jax.numpy as jnp
        # explicit-dtype: promotion wanted here
        x = jnp.asarray([1.0])
    """)
    assert lint(tmp_path, "explicit-dtype") == []


def test_fast_registry_comment_outside_default_tier_is_stale(
        tmp_path, monkeypatch):
    """fast-registry's grammar is file-level (annotation_live override):
    the comment is live only while the module sits in DEFAULT_TIER."""
    monkeypatch.setattr(fast_registry, "DEFAULT_TIER", {})
    _tiered_tree(tmp_path)
    write(tmp_path, "tests/test_a.py",
          "# fast-registry: left over from a previous tier\n"
          "def test_x():\n    pass\n")
    found = lint(tmp_path, "fast-registry")
    assert len(found) == 1 and "stale" in found[0]


# ---- --changed / --suppressions -------------------------------------------


def test_cli_changed_mode_reports_only_the_diff(tmp_path, capsys):
    import subprocess

    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args],
                       check=True, capture_output=True)

    # committed baseline has a violation; the new (untracked) file has
    # another — --changed --base HEAD reports only the new one
    write(tmp_path, "video_features_tpu/models/old.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    git("init", "-q")
    git("add", "-A")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "base")
    write(tmp_path, "video_features_tpu/models/new.py",
          "import jax.numpy as jnp\ny = jnp.arange(3)\n")
    assert vftlint_main(["--changed", "--base", "HEAD", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "new.py" in out and "old.py" not in out


def test_cli_changed_mode_clean_when_no_diff(tmp_path, capsys):
    import subprocess

    def git(*args):
        subprocess.run(["git", "-C", str(tmp_path), *args],
                       check=True, capture_output=True)

    write(tmp_path, "video_features_tpu/models/old.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    git("init", "-q")
    git("add", "-A")
    git("-c", "user.email=t@t", "-c", "user.name=t", "commit", "-qm", "base")
    assert vftlint_main(["--changed", "--base", "HEAD", str(tmp_path)]) == 0
    assert "no files changed" in capsys.readouterr().out


def test_cli_changed_outside_git_lints_everything(tmp_path, capsys):
    write(tmp_path, "video_features_tpu/models/m.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    assert vftlint_main(["--changed", str(tmp_path)]) == 1
    err = capsys.readouterr().err
    assert "needs a git checkout" in err


def test_cli_suppressions_lists_annotations(tmp_path, capsys):
    write(tmp_path, "video_features_tpu/models/m.py", """
        import jax.numpy as jnp
        # explicit-dtype: promotion deliberate here
        x = jnp.asarray([1.0])
    """)
    assert vftlint_main(["--suppressions", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert ("video_features_tpu/models/m.py:3 explicit-dtype "
            "promotion deliberate here") in out


def test_suppression_ledger_matches_docs():
    """The (file, rule, count) ledger in docs/static-analysis.md mirrors
    `--suppressions` exactly — adding or removing an annotation without
    updating the ledger fails here."""
    from tools.vftlint.core import collect_suppressions

    counts = {}
    for rel, _line, rule, _reason in collect_suppressions(REPO):
        counts[(rel, rule)] = counts.get((rel, rule), 0) + 1

    doc = open(os.path.join(REPO, "docs", "static-analysis.md"),
               encoding="utf-8").read()
    assert "### Suppression ledger" in doc
    section = doc.split("### Suppression ledger", 1)[1]
    section = section.split("\n## ")[0].split("\n### ")[0]
    documented = {}
    for line in section.splitlines():
        if not line.startswith("|") or set(line) <= {"|", "-", " "}:
            continue
        cells = [c.strip().strip("`") for c in line.strip("|").split("|")]
        if len(cells) >= 3 and cells[2].isdigit():
            documented[(cells[0], cells[1])] = int(cells[2])
    assert documented == counts


# ---- LockOrderWatch (runtime cross-check shim) -----------------------------


def test_lock_order_watch_records_edges_and_violations():
    import threading

    watch = LockOrderWatch(["a", "b"])
    la = watch.wrap(threading.Lock(), "a")
    lb = watch.wrap(threading.Lock(), "b")
    with la:
        with lb:
            pass
    assert ("a", "b") in watch.edges and watch.violations == []
    watch.assert_clean()
    with lb:
        with la:
            pass
    assert len(watch.violations) == 1
    assert "'a' while holding 'b'" in watch.violations[0]
    with pytest.raises(AssertionError):
        watch.assert_clean()


def test_lock_order_watch_rlock_reentry_is_not_an_edge():
    import threading

    watch = LockOrderWatch(["a"])
    la = watch.wrap(threading.RLock(), "a")
    with la:
        with la:
            pass
    assert watch.edges == set() and watch.violations == []


# ---- parse-once budget ----------------------------------------------------


def test_sources_parsed_once_per_run(monkeypatch):
    """9+ rules must not re-parse per rule: each file is constructed into a
    SourceFile exactly once per run_lint call."""
    import tools.vftlint.core as core

    counts = {}
    orig = core.SourceFile.__init__

    def counting(self, root, rel):
        counts[rel] = counts.get(rel, 0) + 1
        orig(self, root, rel)

    monkeypatch.setattr(core.SourceFile, "__init__", counting)
    assert run_lint(REPO) == []
    assert counts, "no sources scanned?"
    multi = {rel: n for rel, n in counts.items() if n != 1}
    assert multi == {}, f"re-parsed per rule: {multi}"


def test_full_run_wall_clock_budget():
    """The full 13-rule suite stays within ~25% over the measured baseline
    (~3.5 s on this class of machine after the dataflow rules landed) — the
    budget guards against O(files x rules) parse regressions and against a
    new interprocedural pass quietly re-deriving the shared analyses, not
    against small constant cost. Best-of-3 so a loaded machine measures the
    lint, not the contention."""
    import time

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        run_lint(REPO)
        best = min(best, time.perf_counter() - t0)
        if best < 4.5:
            break
    assert best < 4.5


def test_changed_mode_single_file_is_fast():
    """--changed on a one-file diff stays a pre-commit-speed loop: the tree
    is still parsed and prepare()d (the interprocedural rules need it), but
    per-file checks run only on the diff. Best-of-3 — a wall-clock pin under
    a loaded full-suite run measures contention, not the lint."""
    import time

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        found = run_lint(REPO, only={"video_features_tpu/serve/wal.py"})
        best = min(best, time.perf_counter() - t0)
        assert found == []
        if best < 2.0:
            break
    assert best < 2.0


# ---- --format json / github ------------------------------------------------


def test_cli_json_format(tmp_path, capsys):
    import json

    write(tmp_path, "video_features_tpu/models/m.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    assert vftlint_main(["--format", "json", str(tmp_path)]) == 1
    data = json.loads(capsys.readouterr().out)
    assert len(data) == 1
    rec = data[0]
    assert rec["file"] == "video_features_tpu/models/m.py"
    assert rec["line"] == 2 and rec["rule"] == "explicit-dtype"
    assert "dtype" in rec["message"]
    assert rec["suppression"] == "# explicit-dtype: <reason>"


def test_cli_json_clean_is_empty_array(capsys):
    import json

    assert vftlint_main(["--format", "json", REPO]) == 0
    assert json.loads(capsys.readouterr().out) == []


def test_cli_github_format(tmp_path, capsys):
    write(tmp_path, "video_features_tpu/models/m.py",
          "import jax.numpy as jnp\nx = jnp.asarray([1.0])\n")
    assert vftlint_main(["--format", "github", str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert out.startswith("::error file=video_features_tpu/models/m.py,"
                          "line=2,title=vftlint explicit-dtype::")


# ---- framework ------------------------------------------------------------


def test_parse_error_is_reported_once(tmp_path):
    write(tmp_path, "video_features_tpu/broken.py", "def f(:\n")
    findings = run_lint(str(tmp_path))
    parse = [f for f in findings if f.rule == "parse-error"]
    assert len(parse) == 1


def test_findings_format():
    from tools.vftlint import Finding

    f = Finding("pkg/mod.py", 7, "host-sync", "boom")
    assert str(f) == "pkg/mod.py:7 host-sync boom"
    assert str(Finding("pkg/mod.py", 0, "r", "m")) == "pkg/mod.py r m"


@pytest.mark.parametrize("rule_id", sorted(ALL_RULE_IDS))
def test_each_rule_runs_standalone_on_repo(rule_id):
    assert run_lint(REPO, [rule_id]) == []
