"""Flax I3D numerical parity vs a torch functional mirror (random weights)."""

import os
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # whole-model parity: minutes on CPU

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import jax
import jax.numpy as jnp
import torch

from torch_mirrors import i3d_forward, i3d_random_state_dict
from video_features_tpu.models.i3d import I3D, i3d_preprocess_flow, i3d_preprocess_rgb
from video_features_tpu.weights.convert_torch import convert_i3d

# 224 spatial is what the extractor feeds; tests use 64x64 so CPU runtime stays sane.
# Temporal dim follows the reference's stack geometry scaled down (T=16 -> T'=2 after
# the /8 temporal stride, matching the i3d_net.py:256 comment for T=24).
T, S = 16, 64


@pytest.fixture(scope="module", params=["rgb", "flow"])
def modality(request):
    return request.param


@pytest.fixture(scope="module")
def converted(modality):
    sd = i3d_random_state_dict(modality=modality, seed=5)
    params = convert_i3d(sd)
    return sd, params


def test_param_tree_matches_model(converted, modality):
    sd, params = converted
    c = {"rgb": 3, "flow": 2}[modality]
    model = I3D(modality=modality)
    init = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16, S, S, c)), features=False)["params"]
    init_paths = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(init)[0]}
    conv_paths = {jax.tree_util.keystr(p) for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]}
    assert init_paths == conv_paths


def test_features_parity(converted, modality):
    sd, params = converted
    c = {"rgb": 3, "flow": 2}[modality]
    rng = np.random.default_rng(0)
    x = rng.uniform(-1, 1, (1, T, S, S, c)).astype(np.float32)
    ref = i3d_forward(sd, torch.from_numpy(x).permute(0, 4, 1, 2, 3), features=True).numpy()
    out = np.asarray(I3D(modality=modality).apply({"params": params}, jnp.asarray(x), features=True))
    assert out.shape == ref.shape == (1, 1024)
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=5e-4)
    cos = np.sum(out * ref) / (np.linalg.norm(out) * np.linalg.norm(ref))
    assert cos > 1 - 1e-6


def test_logits_parity(converted, modality):
    sd, params = converted
    c = {"rgb": 3, "flow": 2}[modality]
    rng = np.random.default_rng(1)
    x = rng.uniform(-1, 1, (1, T, S, S, c)).astype(np.float32)
    ref_probs, ref_logits = i3d_forward(sd, torch.from_numpy(x).permute(0, 4, 1, 2, 3), features=False)
    probs, logits = I3D(modality=modality).apply({"params": params}, jnp.asarray(x), features=False)
    np.testing.assert_allclose(np.asarray(logits), ref_logits.numpy(), rtol=1e-3, atol=5e-4)
    np.testing.assert_allclose(np.asarray(probs), ref_probs.numpy(), rtol=1e-3, atol=1e-5)


def test_preprocess_rgb_matches_reference():
    u8 = np.arange(0, 256, dtype=np.uint8).reshape(1, 1, 16, 16, 1).repeat(3, -1)
    out = np.asarray(i3d_preprocess_rgb(jnp.asarray(u8)))
    ref = 2 * u8.astype(np.float32) / 255 - 1
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_preprocess_flow_matches_reference():
    # Clamp(-20,20) -> round(128 + 255/40 f) (half-to-even, unclipped) -> 2x/255 - 1
    f = np.array([-25.0, -20.0, -0.1, 0.0, 0.1, 19.9, 20.0, 25.0], np.float32).reshape(1, 1, 1, 4, 2)
    t = torch.from_numpy(f).clamp(-20, 20)
    ref = (2 * (128 + 255 / 40 * t).round() / 255 - 1).numpy()
    out = np.asarray(i3d_preprocess_flow(jnp.asarray(f)))
    np.testing.assert_allclose(out, ref, rtol=0, atol=0)  # must be bit-exact
    assert out.max() > 1.0  # the 256 quirk survives


def test_maxpool_tf_same_matches_torch_ceilmode():
    """Odd input sizes exercise the ceil-mode overhang path."""
    from torch_mirrors import _tf_same_pad_5d
    from video_features_tpu.models.layers import max_pool_tf_same

    rng = np.random.default_rng(2)
    x = rng.standard_normal((1, 7, 9, 11, 4)).astype(np.float32)
    for kernel, stride in [((1, 3, 3), (1, 2, 2)), ((3, 3, 3), (2, 2, 2)), ((2, 2, 2), (2, 2, 2)),
                           ((3, 3, 3), (1, 1, 1))]:
        t = torch.nn.functional.pad(
            torch.from_numpy(x).permute(0, 4, 1, 2, 3), _tf_same_pad_5d(kernel, stride))
        ref = torch.nn.functional.max_pool3d(t, kernel, stride, ceil_mode=True)
        out = np.asarray(max_pool_tf_same(jnp.asarray(x), kernel, stride))
        np.testing.assert_allclose(out, ref.permute(0, 2, 3, 4, 1).numpy(), rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("t,h,w", [(16, 224, 224), (16, 63, 57)])
def test_s2d_stem_matches_direct_conv(converted, modality, t, h, w, monkeypatch):
    """Space-to-depth stem lowering == direct stem conv (same params; the
    folded taps only add zero products, so fp32 CPU agrees to ~1e-5).

    Pins VFT_I3D_TAP_FP32 off: this asserts the DEFAULT fp32 lowering pair;
    under the tap flag the conv3ds reassociate and the measured drift
    (max rel ~3e-5, round 5) is exactly what the flag's docs warn about."""
    monkeypatch.delenv("VFT_I3D_TAP_FP32", raising=False)
    _, params = converted
    c = {"rgb": 3, "flow": 2}[modality]
    x = jnp.asarray(
        np.random.default_rng(5).uniform(-1, 1, (1, t, h, w, c)).astype(np.float32))
    direct = I3D(modality=modality).apply({"params": params}, x, features=True)
    s2d = I3D(modality=modality, s2d_stem=True).apply({"params": params}, x,
                                                      features=True)
    np.testing.assert_allclose(np.asarray(s2d), np.asarray(direct),
                               rtol=1e-5, atol=1e-5)
