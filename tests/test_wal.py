"""Write-ahead admission log unit layer (serve/wal.py): append/resolve
round-trips, compaction, torn-tail tolerance, the resolve-before-append
race, ENOSPC degrade (via the VFT_FAULTS harness), and replay bookkeeping —
no daemon, no device, pure file + thread mechanics."""

import json
import os

import pytest

from video_features_tpu.reliability import reset_faults
from video_features_tpu.serve.wal import WAL_NAME, AdmissionLog, wal_path


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


def _admit(log, rid, videos=("/a.mp4",), seqs=None, **kw):
    return log.append_admitted({
        "request": rid, "tenant": kw.pop("tenant", "t"),
        "feature_type": "resnet50", "deadline": kw.pop("deadline", None),
        "source": "api", "videos": list(videos),
        "seqs": list(seqs if seqs is not None
                     else range(1, len(videos) + 1)), **kw,
    })


def _lines(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_append_is_durable_before_ack(tmp_path):
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    assert _admit(log, "r1", ["/a.mp4", "/b.mp4"], seqs=[1, 2]) is True
    # the ack barrier: by the time append_admitted returned, the record is
    # on disk — no close/flush needed to observe it
    recs = _lines(log.path)
    assert len(recs) == 1
    assert recs[0]["rec"] == "admitted" and recs[0]["request"] == "r1"
    assert recs[0]["videos"] == ["/a.mp4", "/b.mp4"]
    assert recs[0]["seqs"] == [1, 2]
    assert log.unresolved_count() == 1
    log.close()


def test_resolve_and_replay_round_trip(tmp_path):
    path = str(tmp_path / "spool" / WAL_NAME)
    log = AdmissionLog(path)
    _admit(log, "r1", seqs=[1])
    _admit(log, "r2", ["/b.mp4", "/c.mp4"], seqs=[2, 3], deadline=99.5)
    log.resolve("r1", "done")
    log.close()

    # a second process opens the same log: only r2 is replayable, with its
    # original seqs and deadline intact
    log2 = AdmissionLog(path)
    entries = log2.replayable()
    assert [e["request"] for e in entries] == ["r2"]
    assert entries[0]["seqs"] == [2, 3]
    assert entries[0]["deadline"] == 99.5
    assert log2.max_seq() == 3
    assert log2.unresolved_count() == 1
    assert log2.corrupt_lines == 0
    log2.close()


def test_replay_orders_by_admission_seq(tmp_path):
    path = str(tmp_path / WAL_NAME)
    log = AdmissionLog(path)
    _admit(log, "late", seqs=[7])
    _admit(log, "early", seqs=[2])
    log.close()
    log2 = AdmissionLog(path)
    assert [e["request"] for e in log2.replayable()] == ["early", "late"]
    log2.close()


def test_compaction_rewrites_empty_when_all_resolved(tmp_path):
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    _admit(log, "r1", seqs=[1])
    _admit(log, "r2", seqs=[2])
    log.resolve("r1")
    log.resolve("r2", "failed")
    log.close()
    assert log.compactions == 1
    assert _lines(log.path) == []  # compacted back to empty, file kept
    log2 = AdmissionLog(log.path)
    assert log2.replayable() == []
    log2.close()


def test_torn_tail_line_tolerated_not_fatal(tmp_path):
    path = str(tmp_path / WAL_NAME)
    log = AdmissionLog(path)
    _admit(log, "r1", seqs=[1])
    log.close()
    # simulate a crash mid-append: a truncated JSON tail
    with open(path, "a") as f:
        f.write('{"rec": "admitted", "request": "r2", "vid')
    log2 = AdmissionLog(path)
    assert log2.corrupt_lines == 1
    assert [e["request"] for e in log2.replayable()] == ["r1"]
    # the log keeps appending cleanly after the torn tail
    assert _admit(log2, "r3", seqs=[5]) is True
    log2.close()


def test_malformed_records_counted_as_corrupt(tmp_path):
    path = str(tmp_path / WAL_NAME)
    with open(path, "w") as f:
        f.write(json.dumps({"rec": "admitted"}) + "\n")  # no request id
        f.write(json.dumps({"rec": "admitted", "request": "r1",
                            "videos": "not-a-list"}) + "\n")
        f.write(json.dumps({"rec": "bogus", "request": "r2"}) + "\n")
        f.write(json.dumps(["not", "a", "dict"]) + "\n")
    log = AdmissionLog(path)
    assert log.replayable() == []
    assert log.corrupt_lines == 4
    log.close()


def test_resolve_before_append_annihilates(tmp_path):
    """The daemon thread can publish a request's result before the submit
    thread's WAL append lands: the early resolve must annihilate the
    admission (no unresolved entry, nothing stuck for replay)."""
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    log.resolve("fast")  # unknown id: remembered, not an error
    assert _admit(log, "fast", seqs=[1]) is True
    assert log.unresolved_count() == 0
    log.close()
    log2 = AdmissionLog(log.path)
    assert log2.replayable() == []
    log2.close()


def test_enospc_degrades_loudly_never_crashes(tmp_path, monkeypatch, capsys):
    """A write failure (the ENOSPC drill, injected at the wal_append seam)
    turns the log non-durable: append_admitted returns False but STILL
    returns (no hang, no crash), healthz carries the flag, and the entry
    stays tracked in memory."""
    monkeypatch.setenv("VFT_FAULTS", "wal_append:raise")
    reset_faults()
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    assert _admit(log, "r1", seqs=[1]) is False
    assert log.degraded is True
    health = log.health()
    assert health["durable"] is False
    assert "degraded_reason" in health
    assert log.unresolved_count() == 1  # memory still serves healthz/stats
    # subsequent appends and resolves keep acking without I/O
    assert _admit(log, "r2", seqs=[2]) is False
    log.resolve("r1")
    assert log.unresolved_count() == 1
    log.close()
    assert "WAL DEGRADED" in capsys.readouterr().err


def test_degraded_log_reports_in_stats(tmp_path, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "wal_append:raise")
    reset_faults()
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    _admit(log, "r1", seqs=[1])
    stats = log.stats()
    assert stats["enabled"] is True and stats["durable"] is False
    assert stats["appended"] == 0
    assert stats["unresolved"] == 1
    log.close()


def test_unwritable_directory_degrades_at_open(tmp_path, capsys):
    target = tmp_path / "blocked"
    target.write_text("a file where the log wants a directory parent")
    # path's parent is a FILE: open() fails, the log degrades instead of
    # raising out of the daemon's constructor
    log = AdmissionLog(str(target / WAL_NAME))
    assert _admit(log, "r1", seqs=[1]) is False
    assert log.degraded is True
    log.close()


def test_fsync_batching_still_acks_every_record(tmp_path):
    log = AdmissionLog(str(tmp_path / WAL_NAME), fsync_sec=30.0)
    for i in range(5):
        assert _admit(log, f"r{i}", seqs=[i + 1]) is True
    # every record is WRITTEN at ack time even when the fsync is batched
    assert len(_lines(log.path)) == 5
    assert log.appended == 5
    log.close()


def test_wal_path_helper(tmp_path):
    assert wal_path(str(tmp_path)) == os.path.join(str(tmp_path), WAL_NAME)


def test_resolve_rejects_unknown_state(tmp_path):
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    with pytest.raises(ValueError):
        log.resolve("r1", "exploded")
    log.close()


def test_close_is_idempotent_and_keeps_unresolved(tmp_path):
    log = AdmissionLog(str(tmp_path / WAL_NAME))
    _admit(log, "r1", seqs=[1])
    log.close()
    log.close()
    # unresolved entries survive close — they are the recovery surface
    assert [r["request"] for r in _lines(log.path)] == ["r1"]
