"""Config dataclass + CLI shim behavior (reference flag surface)."""

import pytest

from video_features_tpu.cli import parse_args
from video_features_tpu.config import ExtractionConfig, resolve_model_defaults


def test_i3d_defaults():
    cfg = resolve_model_defaults(ExtractionConfig(feature_type="i3d"))
    assert cfg.stack_size == 64 and cfg.step_size == 64
    assert cfg.streams == ("rgb", "flow")


def test_r21d_defaults():
    cfg = resolve_model_defaults(ExtractionConfig(feature_type="r21d_rgb"))
    assert cfg.stack_size == 16 and cfg.step_size == 16


def test_user_override_kept():
    cfg = resolve_model_defaults(ExtractionConfig(feature_type="i3d", stack_size=24, step_size=8))
    assert cfg.stack_size == 24 and cfg.step_size == 8


def test_same_out_tmp_rejected():
    cfg = ExtractionConfig(feature_type="i3d", output_path="./x", tmp_path="./x")
    with pytest.raises(ValueError, match="same path"):
        cfg.validate()


def test_r21d_fps_rejected():
    cfg = ExtractionConfig(feature_type="r21d_rgb", extraction_fps=5)
    with pytest.raises(ValueError, match="original fps"):
        cfg.validate()


def test_cli_parse_reference_flags():
    cfg = parse_args([
        "--feature_type", "i3d",
        "--video_paths", "a.mp4", "b.mp4",
        "--stack_size", "24",
        "--step_size", "24",
        "--flow_type", "raft",
        "--on_extraction", "save_numpy",
    ])
    assert cfg.feature_type == "i3d"
    assert cfg.video_paths == ("a.mp4", "b.mp4")
    assert cfg.stack_size == 24
    assert cfg.flow_type == "raft"
    assert cfg.on_extraction == "save_numpy"


def test_cli_device_ids_maps_to_num_devices():
    cfg = parse_args(["--feature_type", "resnet50", "--video_paths", "a.mp4",
                      "--device_ids", "0", "1", "2"])
    assert cfg.num_devices == 3


def test_cli_show_pred_forces_one_device():
    cfg = parse_args(["--feature_type", "resnet50", "--video_paths", "a.mp4",
                      "--device_ids", "0", "1", "--show_pred"])
    assert cfg.num_devices == 1


def test_cli_larger_edge_flag():
    cfg = parse_args(["--feature_type", "raft", "--video_paths", "a.mp4",
                      "--resize_to_larger_edge", "--side_size", "256"])
    assert cfg.resize_to_smaller_edge is False
    assert cfg.side_size == 256


def test_cli_tpu_knobs_round2():
    cfg = parse_args([
        "--feature_type", "raft", "--video_paths", "a.mp4",
        "--raft_corr", "on_demand", "--pwc_corr", "pallas",
        "--pwc_warp", "onehot",
        "--matmul_precision", "highest", "--profile_dir", "/tmp/trace",
        "--clips_per_batch", "8", "--dtype", "bfloat16",
    ])
    assert cfg.raft_corr == "on_demand"
    assert cfg.pwc_corr == "pallas"
    assert cfg.pwc_warp == "onehot"
    assert cfg.matmul_precision == "highest"
    assert cfg.profile_dir == "/tmp/trace"
    assert cfg.clips_per_batch == 8
    assert cfg.dtype == "bfloat16"


def test_config_rejects_bad_round2_values():
    import pytest

    from video_features_tpu.config import ExtractionConfig

    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="raft", raft_corr="cuda").validate()
    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="pwc", pwc_corr="cupy").validate()
    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="pwc", pwc_warp="bilinear").validate()
    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="i3d", matmul_precision="bf16").validate()


def test_cli_decode_and_bucket_knobs():
    cfg = parse_args([
        "--feature_type", "raft", "--video_paths", "a.mp4",
        "--decode_workers", "3", "--shape_bucket", "64",
        "--raft_corr", "volume_gather",
    ])
    assert cfg.decode_workers == 3
    assert cfg.shape_bucket == 64
    assert cfg.raft_corr == "volume_gather"


def test_cli_vggish_postprocess_flag():
    cfg = parse_args(["--feature_type", "vggish", "--video_paths", "a.wav",
                      "--vggish_postprocess"])
    assert cfg.vggish_postprocess is True
    assert parse_args(["--feature_type", "vggish", "--video_paths", "a.wav"]
                      ).vggish_postprocess is False


def test_cli_flow_dtype_and_use_ffmpeg():
    cfg = parse_args(["--feature_type", "pwc", "--video_paths", "a.mp4",
                      "--flow_dtype", "bfloat16", "--use_ffmpeg", "never"])
    assert cfg.flow_dtype == "bfloat16"
    assert cfg.use_ffmpeg == "never"
    d = parse_args(["--feature_type", "pwc", "--video_paths", "a.mp4"])
    assert d.flow_dtype == "float32" and d.use_ffmpeg == "auto"


def test_cli_transfer_dtype():
    cfg = parse_args(["--feature_type", "raft", "--video_paths", "a.mp4",
                      "--transfer_dtype", "float16"])
    assert cfg.transfer_dtype == "float16"
    assert parse_args(["--feature_type", "raft", "--video_paths", "a.mp4"]
                      ).transfer_dtype == "float32"
    import pytest

    from video_features_tpu.config import ExtractionConfig

    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="raft", transfer_dtype="int8").validate()


def test_cli_i3d_geometry_knobs():
    cfg = parse_args(["--feature_type", "i3d", "--video_paths", "a.mp4",
                      "--i3d_pre_crop_size", "96", "--i3d_crop_size", "64"])
    assert cfg.i3d_pre_crop_size == 96
    assert cfg.i3d_crop_size == 64
    d = parse_args(["--feature_type", "i3d", "--video_paths", "a.mp4"])
    assert d.i3d_pre_crop_size == 256 and d.i3d_crop_size == 224


def test_config_rejects_bad_i3d_geometry():
    import pytest

    from video_features_tpu.config import ExtractionConfig

    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="i3d", i3d_crop_size=16).validate()
    with pytest.raises(ValueError):
        ExtractionConfig(
            feature_type="i3d", i3d_pre_crop_size=64, i3d_crop_size=96
        ).validate()


def test_config_warns_on_non_multiple_of_32_crop(capsys):
    """112 is a common I3D crop: non-multiple-of-32 values >= 32 validate
    with a warning instead of raising (ADVICE r5 — the multiple-of-32
    tightening rejected previously-working configs)."""
    from video_features_tpu.config import ExtractionConfig

    ExtractionConfig(feature_type="i3d", i3d_crop_size=112).validate()
    err = capsys.readouterr().err
    assert "i3d_crop_size 112" in err and "multiple of 32" in err
    # multiples of 32 stay silent
    ExtractionConfig(feature_type="i3d", i3d_crop_size=224).validate()
    assert "i3d_crop_size" not in capsys.readouterr().err


def test_config_rejects_bad_flow_dtype_and_ffmpeg():
    import pytest

    from video_features_tpu.config import ExtractionConfig

    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="pwc", flow_dtype="fp16").validate()
    with pytest.raises(ValueError):
        ExtractionConfig(feature_type="pwc", use_ffmpeg="maybe").validate()
