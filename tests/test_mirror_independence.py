"""Oracle independence: the torch mirrors must not share spec tables with the
Flax models, and both sides must match shapes known from real checkpoints.

Round-1 review finding: the parity oracles imported I3D_STEM / _conv_shapes /
pwc_conv_shapes / r21d_conv_shapes from the Flax models, so a wrong channel
count produced identical wrong architectures on both sides and parity still
passed. Now the mirror tables are transcribed independently from the reference
source; these tests (a) forbid re-introducing the import, (b) cross-check the
two independently-authored tables against each other, and (c) anchor both to
hard-coded shapes that real pretrained checkpoints are known to have.
"""

import os



def test_mirrors_do_not_import_flax_specs():
    import ast

    src_path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "tools", "torch_mirrors.py")
    with open(src_path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            assert not (node.module or "").startswith("video_features_tpu"), node.module
        elif isinstance(node, ast.Import):
            for alias in node.names:
                assert not alias.name.startswith("video_features_tpu"), alias.name


def test_i3d_tables_agree():
    from tools.torch_mirrors import I3D_LAYERS

    from video_features_tpu.models.i3d import I3D_STEM

    assert tuple(I3D_LAYERS) == tuple(I3D_STEM)


def test_raft_tables_agree():
    from tools.torch_mirrors import raft_conv_shapes

    from video_features_tpu.models.raft import _conv_shapes

    assert raft_conv_shapes() == _conv_shapes()


def test_pwc_tables_agree():
    from tools import torch_mirrors as tm

    from video_features_tpu.models import pwc as flax_pwc

    assert tm.pwc_conv_shapes() == flax_pwc.pwc_conv_shapes()
    assert tm.LEVEL_NAMES == flax_pwc.LEVEL_NAMES
    assert tm.DEC_BACKWARD == flax_pwc.DEC_BACKWARD


def test_r21d_tables_agree():
    from tools.torch_mirrors import r21d_conv_shapes

    from video_features_tpu.models.r21d import r21d_conv_shapes as flax_shapes

    assert r21d_conv_shapes() == flax_shapes()


# ---------------------------------------------------------------------------
# Anchors: shapes a REAL pretrained checkpoint is known to have (transcribed
# from torchvision r2plus1d_18 / RAFT-sintel / I3D-Kinetics / PWC state_dicts).
# These catch the case where both independently-written tables err identically.
# ---------------------------------------------------------------------------

R21D_KNOWN = {
    # torchvision r2plus1d_18: block-level midplanes — (inplanes, planes) once
    # per block, shared by conv1 AND conv2 (ADVICE.md round-1 high finding)
    "layer2.0.conv1.0.0.weight": (230, 64, 1, 3, 3),
    "layer2.0.conv2.0.0.weight": (230, 128, 1, 3, 3),
    "layer3.0.conv2.0.0.weight": (460, 256, 1, 3, 3),
    "layer4.0.conv2.0.0.weight": (921, 512, 1, 3, 3),
    "layer1.0.conv1.0.0.weight": (144, 64, 1, 3, 3),
    "stem.0.weight": (45, 3, 1, 7, 7),
    "fc.weight": (400, 512),
}

RAFT_KNOWN = {
    "fnet.conv2.weight": (256, 128, 1, 1),
    "cnet.conv2.weight": (256, 128, 1, 1),
    "update_block.encoder.convc1.weight": (256, 324, 1, 1),
    "update_block.encoder.conv.weight": (126, 256, 3, 3),
    "update_block.gru.convz1.weight": (128, 384, 1, 5),
    "update_block.mask.2.weight": (576, 256, 1, 1),
}

I3D_KNOWN = {
    "mixed_4f.branch_1.0.conv3d.weight": (160, 528, 1, 1, 1),
    "mixed_5c.branch_0.conv3d.weight": (384, 832, 1, 1, 1),
    "conv3d_0c_1x1.conv3d.weight": (400, 1024, 1, 1, 1),
}

PWC_KNOWN = {
    "moduleTwo.moduleOne.0.weight": (128, 117, 3, 3),
    "moduleSix.moduleOne.0.weight": (128, 81, 3, 3),
    "moduleRefiner.moduleMain.0.weight": (128, 565, 3, 3),
    "moduleThr.moduleUpfeat.weight": (181 + 448, 2, 4, 4),
}


def test_r21d_known_checkpoint_shapes():
    from tools.torch_mirrors import r21d_random_state_dict

    sd = r21d_random_state_dict()
    for name, shape in R21D_KNOWN.items():
        assert tuple(sd[name].shape) == shape, name


def test_raft_known_checkpoint_shapes():
    from tools.torch_mirrors import raft_random_state_dict

    sd = raft_random_state_dict()
    for name, shape in RAFT_KNOWN.items():
        assert tuple(sd[name].shape) == shape, name


def test_i3d_known_checkpoint_shapes():
    from tools.torch_mirrors import i3d_random_state_dict

    sd = i3d_random_state_dict("rgb")
    for name, shape in I3D_KNOWN.items():
        assert tuple(sd[name].shape) == shape, name
    # flow I3D differs only in the stem input channels
    assert tuple(i3d_random_state_dict("flow")["conv3d_1a_7x7.conv3d.weight"].shape) == (
        64, 2, 7, 7, 7,
    )


def test_pwc_known_checkpoint_shapes():
    from tools.torch_mirrors import pwc_random_state_dict

    sd = pwc_random_state_dict()
    for name, shape in PWC_KNOWN.items():
        assert tuple(sd[name].shape) == shape, name


def test_flax_params_match_known_shapes():
    """The Flax models themselves (via converted random torch weights) must
    carry the same known-checkpoint geometry — anchoring the framework side,
    not just the mirrors."""
    import numpy as np

    from tools.torch_mirrors import r21d_random_state_dict

    from video_features_tpu.weights.convert_torch import convert_r21d

    import jax

    params = convert_r21d(r21d_random_state_dict())
    # spatial conv of layer2.0's Conv2Plus1D #2: HWIO (1, 3, 3, 128, 230) in Flax
    shapes = {tuple(np.shape(l)) for l in jax.tree_util.tree_leaves(params)}
    assert (1, 3, 3, 128, 230) in shapes
    assert (3, 1, 1, 230, 128) in shapes  # its temporal half
    assert (1, 3, 3, 64, 230) in shapes   # layer2.0.conv1 spatial half
