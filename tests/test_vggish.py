"""VGGish DSP frontend golden tests + network parity + postprocessor."""
# fast-registry: default tier — vggish DSP + forward parity

import importlib.util
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import torch
import torch.nn.functional as F

from video_features_tpu.audio import melspec
from video_features_tpu.models.vggish import (
    Postprocessor,
    VGGish,
    convert_tf_vggish,
    vggish_init_params,
)

REF_DSP = "/root/reference/models/vggish/vggish_src/mel_features.py"


@pytest.fixture(scope="module")
def ref_mel():
    """The reference's own pure-numpy DSP, loaded as a golden oracle."""
    if not os.path.exists(REF_DSP):
        pytest.skip("reference DSP unavailable")
    spec = importlib.util.spec_from_file_location("ref_mel_features", REF_DSP)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_log_mel_matches_reference_dsp(ref_mel):
    rng = np.random.default_rng(0)
    wav = rng.uniform(-1, 1, 16000 * 2)  # 2 s of noise at 16 kHz
    ref = ref_mel.log_mel_spectrogram(
        wav, audio_sample_rate=16000, log_offset=0.01,
        window_length_secs=0.025, hop_length_secs=0.010,
        num_mel_bins=64, lower_edge_hertz=125, upper_edge_hertz=7500)
    out = melspec.log_mel_spectrogram(wav)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, rtol=1e-9, atol=1e-9)


def test_examples_shape_and_count():
    rng = np.random.default_rng(1)
    wav = rng.uniform(-1, 1, int(16000 * 3.5)).astype(np.float64)
    ex = melspec.waveform_to_examples(wav, 16000)
    # 3.5 s → 3 full 0.96 s examples
    assert ex.shape == (3, 96, 64)
    assert ex.dtype == np.float32


def test_resample_path():
    t = np.arange(44100) / 44100.0
    wav = np.sin(2 * np.pi * 440 * t)
    ex = melspec.waveform_to_examples(wav, 44100)
    assert ex.shape[0] == 1
    # 440 Hz peak: mel bin with max mean energy sits in the low third
    assert ex[0].mean(0).argmax() < 21


def test_stereo_to_mono():
    rng = np.random.default_rng(2)
    mono = rng.uniform(-1, 1, 16000)
    stereo = np.stack([mono, mono], axis=1)
    np.testing.assert_allclose(
        melspec.waveform_to_examples(stereo, 16000),
        melspec.waveform_to_examples(mono, 16000))


def test_network_parity_vs_torch():
    """Flax VGGish vs a torch functional mirror on the same weights."""
    params = convert_tf_vggish(_as_tf_vars(vggish_init_params(seed=3)))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 96, 64)).astype(np.float32) * 2
    out = np.asarray(VGGish().apply({"params": params}, jnp.asarray(x)))

    t = torch.from_numpy(x)[:, None]  # (N, 1, 96, 64)
    with torch.no_grad():
        for name in ("conv1", "conv2", "conv3_1", "conv3_2", "conv4_1", "conv4_2"):
            w = torch.from_numpy(np.transpose(params[name]["kernel"], (3, 2, 0, 1)))
            b = torch.from_numpy(params[name]["bias"])
            t = F.relu(F.conv2d(t, w, b, 1, 1))
            if name in ("conv1", "conv2", "conv3_2", "conv4_2"):
                t = F.max_pool2d(t, 2, 2)
        t = t.permute(0, 2, 3, 1).reshape(2, -1)  # TF NHWC flatten
        for name in ("fc1_1", "fc1_2", "fc2"):
            w = torch.from_numpy(params[name]["kernel"])
            b = torch.from_numpy(params[name]["bias"])
            t = F.relu(t @ w + b)
    assert out.shape == (2, 128)
    np.testing.assert_allclose(out, t.numpy(), rtol=1e-4, atol=1e-4)


def _as_tf_vars(params):
    """Re-expand flat params into TF-style names to exercise the converter."""
    scope = {"conv3_1": "conv3/", "conv3_2": "conv3/", "conv4_1": "conv4/",
             "conv4_2": "conv4/", "fc1_1": "fc1/", "fc1_2": "fc1/"}
    out = {}
    for mod, leaves in params.items():
        prefix = f"vggish/{scope.get(mod, '')}{mod}"
        out[f"{prefix}/weights"] = leaves["kernel"]
        out[f"{prefix}/biases"] = leaves["bias"]
    return out


def test_postprocessor_roundtrip(tmp_path):
    rng = np.random.default_rng(4)
    # orthonormal eigenvectors for a well-conditioned check
    q, _ = np.linalg.qr(rng.standard_normal((128, 128)))
    means = rng.standard_normal(128)
    path = tmp_path / "pca.npz"
    np.savez(path, pca_eigen_vectors=q, pca_means=means)
    pp = Postprocessor(str(path))
    emb = rng.standard_normal((5, 128)).astype(np.float32)
    out = pp.postprocess(emb)
    assert out.shape == (5, 128) and out.dtype == np.uint8
    ref = np.clip((q @ (emb.T - means.reshape(-1, 1))).T, -2, 2)
    ref = ((ref + 2) * (255.0 / 4.0)).astype(np.uint8)
    np.testing.assert_array_equal(out, ref)


def test_extract_wav(tmp_path, sample_video):
    from scipy.io import wavfile

    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.vggish import ExtractVGGish

    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    try:
        rng = np.random.default_rng(5)
        wav = (rng.uniform(-0.5, 0.5, 16000 * 3) * 32767).astype(np.int16)
        wav_path = str(tmp_path / "test.wav")
        wavfile.write(wav_path, 16000, wav)
        cfg = ExtractionConfig(
            feature_type="vggish",
            on_extraction="save_numpy",
            output_path=str(tmp_path / "out"),
        )
        ex = ExtractVGGish(cfg)
        feats = ex.extract(wav_path)
        assert feats["vggish"].shape == (3, 128)
        assert np.isfinite(feats["vggish"]).all()
    finally:
        mp.undo()


def test_postprocessor_real_audioset_pca_params():
    """The genuine AudioSet PCA params the reference ships
    (``models/vggish/checkpoints/vggish_pca_params.npz``, vendored in
    ``sample/``) load and quantize correctly — the one reference checkpoint
    small enough to test against for real."""
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "sample",
                        "vggish_pca_params.npz")
    pp = Postprocessor(path)
    rng = np.random.default_rng(6)
    emb = rng.standard_normal((3, 128)).astype(np.float32)
    out = pp.postprocess(emb)
    assert out.shape == (3, 128) and out.dtype == np.uint8
    z = np.load(path)
    ref = np.clip((z["pca_eigen_vectors"] @ (emb.T - z["pca_means"].reshape(-1, 1))).T,
                  -2, 2)
    ref = ((ref + 2) * (255.0 / 4.0)).astype(np.uint8)
    np.testing.assert_array_equal(out, ref)


def test_vendored_pca_params_match_sample_fixture(monkeypatch):
    """--vggish_postprocess resolves the vendored package copy, which must stay
    byte-identical to the sample/ fixture (itself byte-identical to the
    reference's AudioSet checkpoint)."""
    import os

    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extractors.vggish import ExtractVGGish

    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")

    repo = os.path.join(os.path.dirname(__file__), "..")
    vendored = os.path.join(repo, "video_features_tpu", "weights", "data",
                            "vggish_pca_params.npz")
    fixture = os.path.join(repo, "sample", "vggish_pca_params.npz")
    with open(vendored, "rb") as a, open(fixture, "rb") as b:
        assert a.read() == b.read()

    cfg = ExtractionConfig(feature_type="vggish", vggish_postprocess=True,
                           output_path="/tmp/vft_pca_out", tmp_path="/tmp/vft_pca_tmp")
    ex = ExtractVGGish(cfg)
    assert ex.postprocessor is not None
    emb = np.zeros((2, 128), np.float32)
    out = ex.postprocessor.postprocess(emb)
    assert out.shape == (2, 128) and out.dtype == np.uint8
