"""Spatially-sharded convolution == unsharded SAME conv, on the 8-device mesh.

The halo exchange (ppermute over ICI in production; the virtual CPU mesh here)
must be numerically invisible: zero-pad boundaries, neighbor rows in between.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax import lax

from video_features_tpu.parallel import local_mesh
from video_features_tpu.parallel.spatial import sharded_conv_stack, sharded_same_conv2d


def _ref_conv(x, k):
    return lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(k), (1, 1), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


@pytest.mark.parametrize("kh,kw", [(3, 3), (5, 3), (1, 1), (7, 5)])
def test_sharded_conv_matches_unsharded(rng, kh, kw):
    mesh = local_mesh(8)
    x = rng.standard_normal((2, 64, 16, 8)).astype(np.float32)
    k = rng.standard_normal((kh, kw, 8, 4)).astype(np.float32) * 0.1
    ref = np.asarray(_ref_conv(x, k))
    out = np.asarray(sharded_same_conv2d(mesh, jnp.asarray(x), jnp.asarray(k)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_sharded_conv_stack_stays_sharded(rng):
    mesh = local_mesh(8)
    x = rng.standard_normal((1, 64, 16, 8)).astype(np.float32)
    ks = [rng.standard_normal((3, 3, 8, 8)).astype(np.float32) * 0.1 for _ in range(3)]
    out = sharded_conv_stack(mesh, jnp.asarray(x), [jnp.asarray(k) for k in ks])
    # reference: plain chain
    ref = jnp.asarray(x)
    for k in ks:
        ref = jnp.maximum(_ref_conv(ref, jnp.asarray(k)), 0.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)
    # activations really are H-sharded across all 8 devices
    assert len(out.sharding.device_set) == 8


def test_sharded_conv_rejects_thin_shards(rng):
    mesh = local_mesh(8)
    x = jnp.asarray(rng.standard_normal((1, 16, 8, 4)).astype(np.float32))  # 2 rows/dev
    k = jnp.asarray(rng.standard_normal((7, 3, 4, 4)).astype(np.float32))  # halo 3
    with pytest.raises(ValueError, match="halo"):
        sharded_same_conv2d(mesh, x, k)


def test_single_device_mesh_degenerates_to_plain_conv(rng):
    mesh = local_mesh(1)
    x = rng.standard_normal((1, 12, 10, 3)).astype(np.float32)
    k = rng.standard_normal((3, 3, 3, 2)).astype(np.float32)
    out = np.asarray(sharded_same_conv2d(mesh, jnp.asarray(x), jnp.asarray(k)))
    np.testing.assert_allclose(out, np.asarray(_ref_conv(x, k)), rtol=1e-5, atol=1e-6)
