"""Ragged paged batching (parallel/pages.py + the packer's paged dispatch
mode): page geometry and row-table semantics, the masked paged program, the
ONE legal buffer donation (the int32 row table through MeshRunner.jit_paged)
vs the uint8-wire steps declining donation, depth-2 paged-vs-bucketed byte
parity at matched jit signatures for the real models (resnet50 / r21d_rgb /
i3d-rgb over a mixed-geometry corpus), >=2 pages in flight observable in the
--telemetry_dir journal, and slot-level fault attribution for co-hosted
pages (a poisoned video fails only itself; --retry_failed reprocesses it;
the corpus-flush partial page stays byte-exact)."""
# fast-registry: default tier — paged dispatch parity (jit compiles)

import dataclasses
import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from test_packer import ToyPacked, _write_video

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.io.output import load_done_set
from video_features_tpu.obs.export import load_journal
from video_features_tpu.parallel.mesh import MeshRunner
from video_features_tpu.parallel.pages import (
    PAD_ROW,
    build_row_table,
    mask_rows,
    page_rows_for,
    paged_program,
)
from video_features_tpu.reliability import load_failures, reset_faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


@pytest.fixture(scope="module", autouse=True)
def _random_weights():
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    yield
    mp.undo()


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 0)
    kw.setdefault("retry_backoff", 0.01)
    kw.setdefault("pack_corpus", True)
    return ExtractionConfig(
        on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


# ---- page geometry + row tables (host side) ---------------------------------


def test_page_rows_for_splits_the_batch_budget_by_depth():
    # depth pages of ceil(batch/depth) rows = one bucketed batch in flight
    assert page_rows_for(4, 2) == 2
    assert page_rows_for(5, 2) == 3
    assert page_rows_for(4, 8) == 1  # never below one row
    # the mesh multiple rounds the page up, exactly like a bucketed batch
    assert page_rows_for(6, 2, device_batch=lambda n: -(-n // 4) * 4) == 4
    with pytest.raises(ValueError):
        page_rows_for(4, 0)


def test_build_row_table_fills_pads_and_reuses_buffers():
    t = build_row_table([(7, 0), (7, 1), (9, 4)], 5)
    assert t.dtype == np.int32 and t.shape == (5, 3)
    np.testing.assert_array_equal(t[:3], [[7, 0, 1], [7, 1, 1], [9, 4, 1]])
    np.testing.assert_array_equal(t[3:], [PAD_ROW, PAD_ROW])
    # staging-ring reuse: a dirty `out` buffer is overwritten in place
    out = np.full((5, 3), 99, np.int32)
    t2 = build_row_table([(1, 2)], 5, out=out)
    assert t2 is out
    np.testing.assert_array_equal(t2[0], [1, 2, 1])
    np.testing.assert_array_equal(t2[1:], [PAD_ROW] * 4)
    with pytest.raises(ValueError):
        build_row_table([(0, 0)] * 6, 5)


def test_mask_rows_zeroes_pads_exactly_and_keeps_dtypes():
    valid = jnp.asarray(np.array([1, 0, 1], np.int32))
    rows = {"f32": jnp.asarray(np.arange(6, dtype=np.float32).reshape(3, 2)),
            "i32": jnp.asarray(np.arange(3, dtype=np.int32))}
    m = mask_rows(rows, valid)
    # x1.0 on real rows is exact; x0.0 zeroes the pad row; dtypes survive
    np.testing.assert_array_equal(np.asarray(m["f32"]),
                                  [[0.0, 1.0], [0.0, 0.0], [4.0, 5.0]])
    assert m["i32"].dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(m["i32"]), [0, 0, 2])


def test_paged_program_masks_by_table_and_passes_it_through():
    def fwd(params, page):
        return page.astype(jnp.float32) + params["b"]

    table = jnp.asarray(build_row_table([(3, 0), (3, 1), (5, 0)], 4))
    page = jnp.asarray(np.arange(8, dtype=np.uint8).reshape(4, 2))
    out, t_out = paged_program(fwd)({"b": jnp.float32(1.0)}, page, table)
    ref = np.arange(8, dtype=np.float32).reshape(4, 2) + 1.0
    ref[3] = 0.0  # the pad row is zeroed on device
    np.testing.assert_array_equal(np.asarray(out), ref)
    assert t_out is table  # the identity pass-through donation relies on


# ---- buffer donation through the mesh seam ----------------------------------


def test_jit_paged_donates_the_table_and_uint8_steps_decline():
    """The int32 row table is the one in/out-identical buffer on the dispatch
    path: jit_paged donates it and XLA aliases it in place (the donated
    device value is deleted). The uint8 page and every plain-jit uint8-wire
    step keep their inputs alive — they donate nothing (mesh.sharded_apply's
    default), because no output matches their shape/dtype."""
    runner = MeshRunner(num_devices=1)

    def fwd(params, page):
        return page.astype(jnp.float32) * params["w"]

    params = runner.put_replicated({"w": np.ones((1,), np.float32)})
    paged = runner.jit_paged(paged_program(fwd))
    page = runner.put(np.arange(12, dtype=np.uint8).reshape(4, 3))
    table = runner.put(build_row_table([(0, 0), (0, 1), (1, 0)], 4))
    out, t_out = paged(params, page, table)
    assert table.is_deleted()        # donated: aliased into t_out
    assert not page.is_deleted()     # uint8 in, fp32 out: never aliases
    np.testing.assert_array_equal(np.asarray(t_out),
                                  build_row_table([(0, 0), (0, 1), (1, 0)], 4))
    np.testing.assert_array_equal(
        np.asarray(out),
        np.arange(12, dtype=np.float32).reshape(4, 3) * [[1.0]] *
        np.array([[1.0], [1.0], [1.0], [0.0]], np.float32))

    plain = runner.jit(fwd)
    page2 = runner.put(np.arange(12, dtype=np.uint8).reshape(4, 3))
    plain(params, page2)
    assert not page2.is_deleted()    # non-paged steps decline donation


# ---- paged vs bucketed byte parity at matched jit signatures ---------------
#
# The acceptance bar: depth-2 paged dispatch (batch budget 2N -> two N-row
# pages in flight) produces byte-identical outputs to the bucketed loop run
# at batch_size N — the page and the bucketed batch share ONE jit signature
# per family, so the numerics are the same compiled program either way.


def _load_outputs(root, feature_type):
    out = {os.path.basename(f): np.load(f)
           for f in glob.glob(str(root / feature_type / "*.npy"))}
    assert out
    return out


def _assert_bytes_equal(paged, bucketed):
    assert set(paged) == set(bucketed)
    for k in paged:
        assert paged[k].dtype == bucketed[k].dtype, k
        assert paged[k].shape == bucketed[k].shape, k
        assert paged[k].tobytes() == bucketed[k].tobytes(), k


def test_resnet50_paged_matches_bucketed_across_mixed_geometry(tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet50

    # two source geometries; the host resize+crop normalizes both to 224^2,
    # so the whole mixed corpus is ONE page family / one compiled program
    corpus = [_write_video(tmp_path / "a.mp4", 3),
              _write_video(tmp_path / "b.mp4", 2, size=(48, 36)),
              _write_video(tmp_path / "c.mp4", 4)]
    px = ExtractResNet50(_cfg(tmp_path, "paged", feature_type="resnet50",
                              batch_size=4, pages_in_flight=2))
    assert px.run(corpus) == len(corpus)
    bx = ExtractResNet50(_cfg(tmp_path, "buck", feature_type="resnet50",
                              batch_size=2, paged_batching=False))
    assert bx.run(corpus) == len(corpus)
    _assert_bytes_equal(_load_outputs(tmp_path / "paged", "resnet50"),
                        _load_outputs(tmp_path / "buck", "resnet50"))
    # shared jit signature: 2-row pages == the bucketed batch shape, and the
    # mixed source geometries collapsed into a single family
    assert len(px._pack_stats["buckets"]) == 1
    assert px._pack_stats["pages_dispatched"] == 5  # 9 frames over 2-row pages
    assert px._pack_stats["max_in_flight"] >= 2
    assert bx._pack_stats["pages_dispatched"] == 0
    assert bx._pack_stats["max_in_flight"] == 1


def test_r21d_paged_matches_bucketed_across_mixed_geometry(tmp_path):
    from video_features_tpu.extractors.r21d import ExtractR21D

    # native-resolution slots: two decoded geometries = two page families,
    # each paged under its own compiled program
    corpus = [_write_video(tmp_path / "a.mp4", 6),
              _write_video(tmp_path / "b.mp4", 4),
              _write_video(tmp_path / "c.mp4", 6, size=(48, 32))]
    kw = dict(feature_type="r21d_rgb", stack_size=2, step_size=2)
    px = ExtractR21D(_cfg(tmp_path, "paged", clips_per_batch=4,
                          pages_in_flight=2, **kw))
    assert px.run(corpus) == len(corpus)
    bx = ExtractR21D(_cfg(tmp_path, "buck", clips_per_batch=2,
                          paged_batching=False, **kw))
    assert bx.run(corpus) == len(corpus)
    _assert_bytes_equal(_load_outputs(tmp_path / "paged", "r21d_rgb"),
                        _load_outputs(tmp_path / "buck", "r21d_rgb"))
    assert len(px._pack_stats["buckets"]) == 2
    assert px._pack_stats["pages_dispatched"] > 0
    assert px._pack_stats["max_in_flight"] >= 2


def test_i3d_rgb_paged_matches_bucketed(tmp_path):
    from video_features_tpu.extractors.i3d import ExtractI3D

    # mixed source geometries normalize through the i3d host resize/crop
    corpus = [_write_video(tmp_path / "a.mp4", 17, size=(64, 48)),
              _write_video(tmp_path / "b.mp4", 34, size=(80, 64))]
    kw = dict(feature_type="i3d", streams=("rgb",), stack_size=16,
              step_size=16, i3d_pre_crop_size=64, i3d_crop_size=32)
    px = ExtractI3D(_cfg(tmp_path, "paged", clips_per_batch=4,
                         pages_in_flight=2, **kw))
    assert px.run(corpus) == len(corpus)
    bx = ExtractI3D(_cfg(tmp_path, "buck", clips_per_batch=2,
                         paged_batching=False, **kw))
    assert bx.run(corpus) == len(corpus)
    _assert_bytes_equal(_load_outputs(tmp_path / "paged", "i3d"),
                        _load_outputs(tmp_path / "buck", "i3d"))
    assert px._pack_stats["pages_dispatched"] > 0


# ---- engine-level paged dispatch: toy model --------------------------------


class ToyPaged(ToyPacked):
    """ToyPacked with its pack spec switched to ragged paged dispatch (the
    per-row toy forward is batch-shape exact, so paged pages must reproduce
    the per-video loop's bytes whatever the page size)."""

    def _forward(self, params, frames_u8):
        x = frames_u8.astype(jnp.float32)
        return jnp.stack([x.mean(axis=(1, 2, 3)), x.max(axis=(1, 2, 3))],
                         axis=-1)

    def pack_spec(self):
        spec = super().pack_spec()
        paged = self._paged_fields(self._forward, self._params, self.BATCH)
        return dataclasses.replace(spec, **paged) if paged else spec


def _toy_corpus(tmp_path, counts=(3, 5, 9, 2)):
    return [_write_video(tmp_path / f"vid{i}.mp4", n)
            for i, n in enumerate(counts)]


def test_toy_paged_partial_flush_page_matches_per_video_loop(tmp_path):
    """19 frames over 2-row pages: nine full pages plus the corpus-flush
    partial page (one real row + one pad row) — byte-identical to the
    per-video loop, with the pad waste bounded by the single tail page."""
    corpus = _toy_corpus(tmp_path)
    ex = ToyPaged(_cfg(tmp_path, "loop", feature_type="resnet50",
                       pack_corpus=False))
    assert ex.run(corpus) == len(corpus)
    ex.cfg = ex.cfg.replace(pack_corpus=True,
                            output_path=str(tmp_path / "paged"))
    from video_features_tpu.io.output import feature_output_dir

    ex.output_dir = feature_output_dir(str(tmp_path / "paged"), "resnet50")
    assert ex.run(corpus) == len(corpus)
    _assert_bytes_equal(_load_outputs(tmp_path / "paged", "resnet50"),
                        _load_outputs(tmp_path / "loop", "resnet50"))
    stats = ex._pack_stats
    assert stats["real_slots"] == 19
    assert stats["dispatched_slots"] == 20  # one pad row, in the flush page
    assert stats["pages_dispatched"] == 10
    assert stats["max_in_flight"] == 2
    (bucket,) = stats["buckets"].values()
    assert bucket["pages_dispatched"] == 10
    assert bucket["occupancy"] == 0.95


def test_toy_paged_journal_shows_two_pages_in_flight(tmp_path):
    """The depth-2 ring is observable: dispatch events journal paged=True
    with the per-bucket in-flight depth, and it reaches 2."""
    ex = ToyPaged(_cfg(tmp_path, "tel", feature_type="resnet50",
                       telemetry_dir=str(tmp_path / "tel" / "t")))
    corpus = _toy_corpus(tmp_path)
    assert ex.run(corpus) == len(corpus)
    events, corrupt = load_journal(ex._journal.path)
    assert corrupt == 0
    dispatches = [e for e in events if e["event"] == "dispatch"]
    assert dispatches and all(e["paged"] for e in dispatches)
    assert max(e["inflight"] for e in dispatches) >= 2
    assert ex._pack_stats["max_in_flight"] >= 2


def test_poisoned_video_in_a_co_hosted_page_fails_only_itself(
        tmp_path, monkeypatch):
    """Slot-level fault attribution survives paged dispatch: pages co-host
    rows from several videos, yet a poisoned video fails alone, its page
    neighbours complete with full outputs, and --retry_failed reprocesses
    exactly the manifest set."""
    corpus = _toy_corpus(tmp_path)
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    ex = ToyPaged(_cfg(tmp_path, "pz", feature_type="resnet50"))
    assert ex.run(corpus) == len(corpus) - 1
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(corpus[1])}
    assert len(load_done_set(ex.output_dir)) == len(corpus) - 1
    ok = {os.path.basename(p)
          for p in glob.glob(str(tmp_path / "pz" / "resnet50" / "*_feat.npy"))}
    assert ok == {"vid0_feat.npy", "vid2_feat.npy", "vid3_feat.npy"}

    # --retry_failed semantics: reprocess exactly the manifest set
    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    failed = sorted(load_failures(ex.output_dir))
    assert ex.run(failed) == 1
    assert load_failures(ex.output_dir) == {}
    assert len(load_done_set(ex.output_dir)) == len(corpus)
