"""Native fps-resampler conformance with ffmpeg's ``fps=`` filter.

Two tiers: an independent brute-force model of the documented vf_fps.c slot
semantics (always runs), and a true conformance check against the actual ffmpeg
binary on the sample video (skipped where ffmpeg is not installed — e.g. this
TPU image; runs in CI)."""

import numpy as np
import pytest

from video_features_tpu.io import ffmpeg as ffmpeg_io
from video_features_tpu.io.video import _resampled_frames, decode_all, resample_slots


def _labeled(n):
    """n synthetic frames whose pixel value encodes the source index."""
    return iter([(np.full((1, 1, 3), i, np.uint8), i * 10.0) for i in range(n)])


def _brute_force_selection(n_src, src_fps, dst_fps):
    """Independent model: slot j shows the last source frame whose rounded
    output pts (half-away-from-zero, AV_ROUND_NEAR_INF) is <= j; the final
    source frame emits exactly once."""
    pts = [int(np.floor(i * dst_fps / src_fps + 0.5)) for i in range(n_src)]
    n_slots = pts[-1] + 1 if n_src else 0
    sel = []
    for j in range(n_slots):
        cands = [i for i in range(n_src) if pts[i] <= j]
        sel.append(max(cands))
    # frames after the last source frame's slot never exist; trailing dup-slots
    # beyond pts[-1] are not emitted (EOF flush emits the last frame once)
    return sel


@pytest.mark.parametrize(
    "n_src,src_fps,dst_fps",
    [
        (20, 10.0, 4.0),    # downsample, non-integral ratio
        (20, 10.0, 5.0),    # exact 2:1 drop
        (12, 4.0, 10.0),    # upsample (duplication)
        (30, 19.62, 4.0),   # the sample video's real ratio
        (7, 25.0, 25.0),    # identity
        (1, 30.0, 10.0),    # single frame
    ],
)
def test_native_selection_matches_brute_force(n_src, src_fps, dst_fps):
    out = list(_resampled_frames(_labeled(n_src), src_fps, dst_fps))
    expected = _brute_force_selection(n_src, src_fps, dst_fps)
    got = [int(frame[0, 0, 0]) for frame, _ in out]
    assert got == expected
    # timestamps follow the decode convention: slot j → (j+1)/dst ms
    ts = [t for _, t in out]
    assert ts == pytest.approx([(j + 1) / dst_fps * 1000.0 for j in range(len(out))])


def test_slot_rounding_is_half_away_from_zero():
    # i*dst/src = 0.5 must round UP (AV_ROUND_NEAR_INF), unlike Python's
    # banker's rounding (round(0.5) == 0)
    assert resample_slots(1, 10.0, 5.0) == 1
    assert resample_slots(1, 4.0, 2.0) == 1
    assert resample_slots(2, 10.0, 4.0) == 1  # 0.8 → 1
    assert resample_slots(1, 10.0, 4.0) == 0  # 0.4 → 0


@pytest.mark.skipif(not ffmpeg_io.have_ffmpeg(), reason="ffmpeg binary not installed")
def test_native_matches_real_ffmpeg_on_sample(tmp_path, sample_video):
    """True conformance: frames selected by the native sampler must equal the
    frames ffmpeg's re-encode emits (modulo codec noise)."""
    meta_n, frames_n, _ = decode_all(sample_video, extraction_fps=4,
                                     tmp_path=str(tmp_path), use_ffmpeg="never")
    meta_f, frames_f, _ = decode_all(sample_video, extraction_fps=4,
                                     tmp_path=str(tmp_path), use_ffmpeg="always")
    assert abs(len(frames_n) - len(frames_f)) <= 1
    n = min(len(frames_n), len(frames_f))
    # per-frame mean abs diff: identical source-frame selection re-encodes to
    # ~2-4 gray levels of codec noise; an off-by-one selection jumps to 20+
    diffs = [
        float(np.mean(np.abs(frames_n[i].astype(int) - frames_f[i].astype(int))))
        for i in range(n)
    ]
    assert np.median(diffs) < 8.0, diffs
