"""Corpus clip packing (--pack_corpus): engine invariants, byte-identical
parity with the per-video loop, slot-level fault attribution, retries,
resume, occupancy accounting, and the unsupported-path fallback — through a
lightweight jitted frame-stream extractor (the real-model packed parity runs
live in tests/test_packer_models.py)."""

import glob
import os

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import Extractor, pad_batch
from video_features_tpu.io.output import FeatureAssembly, load_done_set
from video_features_tpu.parallel.packer import CorpusPacker, PackSpec
from video_features_tpu.reliability import (
    DecodeError,
    load_failures,
    reset_faults,
)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


def _write_video(path, frames, size=(32, 24)):
    import cv2

    w = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(frames)  # content varies with length
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return str(path)


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Four decodable tiny videos of mixed lengths (3, 5, 9, 2 frames)."""
    d = tmp_path_factory.mktemp("pack_corpus")
    return [_write_video(d / f"vid{i}.mp4", n)
            for i, n in enumerate((3, 5, 9, 2))]


class ToyPacked(Extractor):
    """Minimal frame-stream model implementing BOTH loops: per-slot features
    are a pure function of the frame, so packed and unpacked outputs must
    match bit for bit."""

    uses_frame_stream = True
    BATCH = 4

    def __init__(self, cfg):
        super().__init__(cfg)

        def fwd(params, frames_u8):  # (B, H, W, 3) uint8
            x = frames_u8.astype(jnp.float32)
            return jnp.stack([x.mean(axis=(1, 2, 3)), x.max(axis=(1, 2, 3))],
                             axis=-1)

        self._step = self.runner.jit(fwd)
        self._params = self.runner.put_replicated(
            {"w": np.zeros((1,), np.float32)})

    def extract(self, video_path):
        # the per-video loop's shape: batch, pad the tail, trim, concat
        meta, frames = self._open_video(video_path)
        ts, valid, batch, outs = [], [], [], []
        for rgb, pos in self._timed_frames(frames):
            ts.append(pos)
            batch.append(rgb)
            if len(batch) == self.BATCH:
                valid.append(len(batch))
                outs.append(self._step(self._params,
                                       self.runner.put(np.stack(batch))))
                batch = []
        if batch:
            valid.append(len(batch))
            outs.append(self._step(self._params, self.runner.put(
                pad_batch(np.stack(batch), self.BATCH))))
        rows = [self._wait(o)[:v] for o, v in zip(outs, valid)]
        feats = np.concatenate(rows) if rows else np.zeros((0, 2), np.float32)
        return {"feat": feats, "timestamps_ms": np.array(ts)}

    def pack_spec(self):
        def open_clips(path):
            meta, frames = self._open_video(path)
            info = {"timestamps_ms": []}

            def clips():
                for rgb, pos in self._timed_frames(frames):
                    info["timestamps_ms"].append(pos)
                    yield rgb

            return info, clips()

        def step(batch):
            return self._step(self._params, self.runner.put(batch))

        def finalize(path, rows, info):
            return {"feat": rows,
                    "timestamps_ms": np.array(info["timestamps_ms"])}

        return PackSpec(batch_size=self.BATCH, empty_row_shape=(2,),
                        open_clips=open_clips, step=step, finalize=finalize)


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub), tmp_path=str(tmp_path / "t"), **kw)


def _outputs(tmp_path, sub):
    return {os.path.basename(p): np.load(p)
            for p in glob.glob(str(tmp_path / sub / "resnet50" / "*.npy"))}


def _assert_bytes_equal(a, b):
    assert set(a) == set(b)
    for k in a:
        assert a[k].dtype == b[k].dtype and a[k].shape == b[k].shape, k
        assert a[k].tobytes() == b[k].tobytes(), k


# ---- parity / occupancy ----------------------------------------------------


def test_packed_outputs_byte_identical_to_unpacked(tmp_path, corpus):
    ex_u = ToyPacked(_cfg(tmp_path, "u", pack_corpus=False))
    assert ex_u.run(corpus) == len(corpus)
    ex_p = ToyPacked(_cfg(tmp_path, "p", pack_corpus=True))
    assert ex_p.run(corpus) == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "u"), _outputs(tmp_path, "p"))
    assert len(load_done_set(ex_p.output_dir)) == len(corpus)


def test_occupancy_beats_tail_padding(tmp_path, corpus):
    """3+5+9+2 = 19 frames over batch 4: packed dispatches 5 batches
    (20 slots), the per-video loop 7 (28 slots)."""
    ex = ToyPacked(_cfg(tmp_path, "o", pack_corpus=True))
    assert ex.run(corpus) == len(corpus)
    stats = ex._pack_stats
    assert stats["real_slots"] == 19
    assert stats["dispatched_slots"] == 20
    clip_counts = stats["video_clips"].values()
    unpacked_slots = sum(-(-c // ex.BATCH) * ex.BATCH for c in clip_counts)
    assert unpacked_slots == 28
    assert stats["occupancy"] > 19 / 28


def test_packed_resume_skips_done_videos(tmp_path, corpus):
    ex = ToyPacked(_cfg(tmp_path, "r", pack_corpus=True))
    assert ex.run(corpus[:2]) == 2
    ex2 = ToyPacked(_cfg(tmp_path, "r", pack_corpus=True, resume=True))
    assert ex2.run(corpus) == len(corpus)
    # only the two new videos dispatched clips (9 + 2 over batch 4 → 12 slots)
    assert ex2._pack_stats["real_slots"] == 11
    assert len(load_done_set(ex2.output_dir)) == len(corpus)


# ---- fault attribution (acceptance: VFT_FAULTS poisons ONE video) ----------


def test_fault_poisons_only_its_video_and_resume_works(
        tmp_path, corpus, monkeypatch):
    """Poisoning vid1 mid-corpus fails only vid1; co-packed neighbours
    complete byte-identical to a clean unpacked run, and --retry_failed-style
    reprocessing converges the manifests."""
    ex_clean = ToyPacked(_cfg(tmp_path, "clean"))
    assert ex_clean.run(corpus) == len(corpus)

    monkeypatch.setenv("VFT_FAULTS", "extract:raise_permanent:vid1")
    ex = ToyPacked(_cfg(tmp_path, "f", pack_corpus=True))
    assert ex.run(corpus) == len(corpus) - 1
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(corpus[1])}
    assert len(load_done_set(ex.output_dir)) == len(corpus) - 1
    got = _outputs(tmp_path, "f")
    want = {k: v for k, v in _outputs(tmp_path, "clean").items()
            if not k.startswith("vid1_")}
    _assert_bytes_equal(got, want)

    # resume: reprocess exactly the failed set with the fault cleared
    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    failed = sorted(load_failures(ex.output_dir))
    assert ex.run(failed) == 1
    assert load_failures(ex.output_dir) == {}
    assert len(load_done_set(ex.output_dir)) == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "f"), _outputs(tmp_path, "clean"))


def test_transient_failure_retries_and_corpus_completes(
        tmp_path, corpus, monkeypatch, capsys):
    monkeypatch.setenv("VFT_FAULTS", "extract:raise_transient:vid2:1")
    ex = ToyPacked(_cfg(tmp_path, "tr", pack_corpus=True, retries=2))
    assert ex.run(corpus) == len(corpus)
    assert load_failures(ex.output_dir) == {}
    out = capsys.readouterr().out
    assert "attempt 1 failed" in out and "retrying in" in out
    ex_clean = ToyPacked(_cfg(tmp_path, "trc"))
    assert ex_clean.run(corpus) == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "tr"), _outputs(tmp_path, "trc"))


def test_mid_stream_decode_failure_attributes_to_its_video(tmp_path, corpus):
    """A clip stream that dies AFTER some of its clips were already packed
    (possibly co-dispatched with neighbours) fails only its video."""

    class MidStreamPoison(ToyPacked):
        def pack_spec(self):
            spec = super().pack_spec()
            inner_open = spec.open_clips

            def open_clips(path):
                info, clips = inner_open(path)
                if "vid2" not in path:
                    return info, clips

                def poisoned():
                    for i, clip in enumerate(clips):
                        if i == 2:  # vid2 has 9 frames; die after 2 clips
                            raise DecodeError(f"{path}: injected mid-stream")
                        yield clip

                return info, poisoned()

            spec.open_clips = open_clips
            return spec

    ex = MidStreamPoison(_cfg(tmp_path, "m", pack_corpus=True, retries=0))
    assert ex.run(corpus) == len(corpus) - 1
    assert set(load_failures(ex.output_dir)) == {os.path.abspath(corpus[2])}
    ex_clean = ToyPacked(_cfg(tmp_path, "mc"))
    assert ex_clean.run([p for p in corpus if "vid2" not in p]) == 3
    _assert_bytes_equal(_outputs(tmp_path, "m"), _outputs(tmp_path, "mc"))


def test_flush_batch_device_failure_stays_inside_the_barrier(tmp_path, corpus):
    """A device-step failure on the corpus-flush tail batch must not escape
    run(): every video whose rows were lost lands classified in the failure
    manifest (transient — --retry_failed reprocesses it) and videos already
    complete stay succeeded."""

    class FlushPoison(ToyPacked):
        def pack_spec(self):
            spec = super().pack_spec()
            inner_step = spec.step
            calls = []

            def step(batch):
                calls.append(1)
                # 19 frames over batch 4: calls 1-4 stream, call 5 = flush
                if len(calls) == 5:
                    raise DecodeError("injected device failure at flush")
                return inner_step(batch)

            spec.step = step
            return spec

    ex = FlushPoison(_cfg(tmp_path, "fl", pack_corpus=True, retries=0))
    ok = ex.run(corpus)  # must return, not raise
    failures = load_failures(ex.output_dir)
    # the flush batch held vid2's last clip and all of vid3
    assert set(failures) == {os.path.abspath(corpus[2]),
                             os.path.abspath(corpus[3])}
    for rec in failures.values():
        assert rec["error_class"] == "DeviceError"
        assert "injected device failure at flush" in rec["message"]
    assert ok == 2
    done = load_done_set(ex.output_dir)
    assert done == {os.path.abspath(corpus[0]), os.path.abspath(corpus[1])}


def test_decode_pool_packed_matches_inline(tmp_path, corpus):
    ex = ToyPacked(_cfg(tmp_path, "w", pack_corpus=True, decode_workers=2))
    assert ex.run(corpus) == len(corpus)
    ex_u = ToyPacked(_cfg(tmp_path, "wu"))
    assert ex_u.run(corpus) == len(corpus)
    _assert_bytes_equal(_outputs(tmp_path, "w"), _outputs(tmp_path, "wu"))


def test_unsupported_model_falls_back_with_notice(tmp_path, corpus, capsys):
    class NoPack(ToyPacked):
        def pack_spec(self):
            return None

    ex = NoPack(_cfg(tmp_path, "nb", pack_corpus=True))
    assert ex.run(corpus[:2]) == 2
    assert "--pack_corpus ignored" in capsys.readouterr().out
    assert ex._pack_stats is None  # the per-video loop ran
    assert len(load_done_set(ex.output_dir)) == 2


# ---- engine unit tests (no extractor, host-only spec) ----------------------


def _host_spec(batch_size=3):
    return PackSpec(
        batch_size=batch_size,
        empty_row_shape=(1,),
        open_clips=None,  # engine tests drive begin/add/finish directly
        step=lambda batch: batch.sum(axis=tuple(range(1, batch.ndim)),
                                     keepdims=False)[:, None].astype(np.float32),
        finalize=None,
    )


def test_engine_packs_across_videos_and_pads_only_at_flush():
    packer = CorpusPacker(_host_spec(3), wait=np.asarray)
    clip = lambda v: np.full((2, 2), v, np.float32)  # noqa: E731
    packer.begin("a", {})
    for v in (1, 2):  # a: 2 clips — queue not full
        packer.add("a", clip(v))
    packer.finish("a")
    assert packer.pop_completed() == []  # tail of `a` waits for `b`
    packer.begin("b", {})
    packer.add("b", clip(10))  # fills the batch: [a0, a1, b0] dispatches
    packer.add("b", clip(20))
    packer.finish("b")
    packer.flush()  # partial [b1] zero-padded
    done = {a.video: a for a in packer.pop_completed()}
    assert set(done) == {"a", "b"}
    np.testing.assert_array_equal(done["a"].stacked((1,)), [[4.0], [8.0]])
    np.testing.assert_array_equal(done["b"].stacked((1,)), [[40.0], [80.0]])
    assert packer.real_slots == 4 and packer.dispatched_slots == 6


def test_engine_shape_keyed_queues_never_mix_geometries():
    seen = []

    def step(batch):
        seen.append(batch.shape)
        return batch.reshape(batch.shape[0], -1)[:, :1]

    spec = PackSpec(batch_size=2, empty_row_shape=(1,), open_clips=None,
                    step=step, finalize=None)
    packer = CorpusPacker(spec, wait=np.asarray)
    packer.begin("a", {})
    packer.add("a", np.ones((2, 2), np.float32))
    packer.add("a", np.ones((3, 3), np.float32))  # different geometry
    packer.add("a", np.ones((2, 2), np.float32))  # completes the (2,2) batch
    packer.finish("a")
    packer.flush()
    (done,) = packer.pop_completed()
    assert done.complete
    assert sorted(seen) == [(2, 2, 2), (2, 3, 3)]


def test_engine_discard_unlinks_pending_and_orphans_inflight_rows():
    packer = CorpusPacker(_host_spec(2), wait=np.asarray)
    packer.begin("a", {})
    packer.add("a", np.ones((2,), np.float32))
    packer.begin("b", {})
    packer.add("b", np.ones((2,), np.float32))  # dispatches [a0, b0]
    packer.add("b", np.full((2,), 2, np.float32))
    packer.discard("a")  # a's dispatched row must not resurface
    # retry of `a` under a fresh assembly
    packer.begin("a", {})
    packer.add("a", np.full((2,), 5, np.float32))
    packer.finish("a")
    packer.finish("b")
    packer.flush()
    done = {a.video: a for a in packer.pop_completed()}
    assert set(done) == {"a", "b"}
    np.testing.assert_array_equal(done["a"].stacked((1,)), [[10.0]])
    np.testing.assert_array_equal(done["b"].stacked((1,)), [[2.0], [4.0]])
    assert packer.drain_incomplete() == []


def test_engine_zero_clip_video_completes_empty():
    packer = CorpusPacker(_host_spec(2), wait=np.asarray)
    packer.begin("empty", {})
    packer.finish("empty")
    (done,) = packer.pop_completed()
    assert done.complete and done.expected == 0
    rows = done.stacked((7,))
    assert rows.shape == (0, 7) and rows.dtype == np.float32


def test_feature_assembly_out_of_order_rows_stack_in_order():
    asm = FeatureAssembly("v", {})
    idx = [asm.reserve() for _ in range(3)]
    assert idx == [0, 1, 2]
    asm.put(2, np.array([2.0]))
    asm.put(0, np.array([0.0]))
    assert not asm.complete
    asm.finish()
    assert not asm.complete  # row 1 still missing
    asm.put(1, np.array([1.0]))
    assert asm.complete
    np.testing.assert_array_equal(asm.stacked((1,)), [[0.0], [1.0], [2.0]])
