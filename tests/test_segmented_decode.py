"""Segmented intra-video decode: planner, byte parity, pool, policy.

Pins the tentpole invariant — the stitched segment stream is byte-identical
to sequential decode (frames AND timestamps, raw and fps-resampled) — plus
the scheduling/reliability story around it: all-permits-up-front
reservation, in-order reassembly, poisoned-segment fault attribution,
cooperative timeouts, live resize, and the autoscaler's segment-before-grow
preference. ffmpeg fast-seek is exercised through a fake binary (the image
has no ffmpeg; cv2 is the production backend tier-1 actually decodes with).
"""
# fast-registry: default tier — real-sleep pool concurrency + e2e parity runs

import hashlib
import os
import time

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.extractors.base import Extractor
from video_features_tpu.io import ffmpeg as ffmpeg_io
from video_features_tpu.io.output import load_done_set
from video_features_tpu.io.video import (
    VideoMeta,
    _resampled_frames,
    _require_nonempty,
    _seeked_capture,
    _segment_resampled,
    _segment_source_frames,
    open_video,
    open_video_segment,
    plan_segments,
    probe_video,
)
from video_features_tpu.parallel.pipeline import DecodePrefetcher
from video_features_tpu.reliability import load_failures, reset_faults
from video_features_tpu.reliability.errors import DecodeError, FfmpegError
from video_features_tpu.serve.autoscale import DecodeAutoscaler


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("VFT_FAULTS", raising=False)
    reset_faults()
    yield
    reset_faults()


def _write_video(path, frames=25, size=(32, 24), fps=10.0):
    import cv2

    w = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"), fps, size)
    rng = np.random.default_rng(frames)
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return str(path)


# ---------------------------------------------------------------------------
# planner


def test_plan_segments_partitions_source_range():
    meta = VideoMeta(path="v.mp4", fps=10.0, frame_count=25, width=8, height=6)
    plan = plan_segments(meta, 4)
    assert len(plan.bounds) == 4
    assert plan.bounds[0][0] == 0 and plan.bounds[-1][1] == 25
    for (_, e0), (s1, _) in zip(plan.bounds, plan.bounds[1:]):
        assert e0 == s1  # contiguous, no gap/overlap
    assert all(e - s >= 2 for s, e in plan.bounds)
    assert plan.meta.frame_count == 25 and plan.meta.fps == 10.0


def test_plan_segments_resampled_meta_matches_open_video():
    meta = VideoMeta(path="v.mp4", fps=10.0, frame_count=25, width=8, height=6)
    plan = plan_segments(meta, 3, extraction_fps=4)
    assert plan.meta.fps == 4.0
    assert plan.meta.frame_count == int(round(25 * 4 / 10.0))
    assert plan.extraction_fps == 4.0


def test_plan_segments_declines_short_or_degenerate():
    short = VideoMeta(path="v", fps=10.0, frame_count=3, width=8, height=6)
    assert plan_segments(short, 4) is None  # 3 // 2 = 1 segment -> no split
    for bad in (
        VideoMeta(path="v", fps=0.0, frame_count=100, width=8, height=6),
        VideoMeta(path="v", fps=10.0, frame_count=0, width=8, height=6),
        VideoMeta(path="v", fps=10.0, frame_count=100, width=0, height=6),
    ):
        assert plan_segments(bad, 4) is None
    assert plan_segments(short, 4, min_segment_frames=1) is not None


def test_plan_narrow_reslices_for_fewer_permits():
    meta = VideoMeta(path="v.mp4", fps=10.0, frame_count=24, width=8, height=6)
    plan = plan_segments(meta, 6, extraction_fps=5)
    narrowed = plan.narrow(2)
    assert len(narrowed.bounds) == 2
    assert narrowed.bounds[0][0] == 0 and narrowed.bounds[-1][1] == 24
    assert narrowed.meta == plan.meta  # output meta is split-invariant


# ---------------------------------------------------------------------------
# resample math across segment boundaries (pure, no decode)


@pytest.mark.parametrize("n,src,dst", [
    (20, 10.0, 4.0),    # downsample
    (20, 10.0, 5.0),    # exact divisor
    (12, 4.0, 10.0),    # upsample (slot gaps duplicate frames)
    (30, 19.62, 4.0),   # irrational-ish ratio
    (7, 25.0, 25.0),    # identity rate
    (40, 30.0, 10.0),
])
@pytest.mark.parametrize("k", [2, 3, 5])
def test_segment_resample_stitches_to_sequential(n, src, dst, k):
    if k > n:
        pytest.skip("fewer frames than segments")
    frames = [(np.full((2, 2, 3), i % 251, np.uint8), float(i)) for i in range(n)]
    seq = list(_resampled_frames(iter(frames), src, dst))
    stitched = []
    for j in range(k):
        s, e = n * j // k, n * (j + 1) // k
        stitched += list(_segment_resampled(
            iter(frames[s:e]), s, src, dst, j == k - 1, e))
    assert len(stitched) == len(seq)
    for (rgb_a, ts_a), (rgb_b, ts_b) in zip(seq, stitched):
        np.testing.assert_array_equal(rgb_a, rgb_b)
        assert ts_a == ts_b  # exact: both are (slot+1)/dst arithmetic


# ---------------------------------------------------------------------------
# segment source stream: lead-in, first-frame workaround, strict middles


class _FakeCap:
    """Scripted cv2.VideoCapture: a list of (ok, bgr) read results."""

    def __init__(self, results):
        self._results = list(results)
        self.released = False

    def read(self):
        return self._results.pop(0) if self._results else (False, None)

    def get(self, _prop):
        return 0.0

    def release(self):
        self.released = True


def _bgr(i):
    return np.full((2, 2, 3), i % 251, np.uint8)


def test_first_frame_drop_tolerated_at_segment_zero_only():
    hiccup = [(False, None)] + [(True, _bgr(i)) for i in range(2)]
    cap = _FakeCap(hiccup)
    got = list(_segment_source_frames(cap, 0, 2, True, "v.mp4", 0))
    assert len(got) == 2 and cap.released

    cap = _FakeCap(list(hiccup))
    with pytest.raises(DecodeError, match="underran after 0 frames"):
        list(_segment_source_frames(cap, 0, 2, False, "v.mp4", 10))
    assert cap.released


def test_middle_segment_underrun_raises_stitch_error():
    cap = _FakeCap([(True, _bgr(0))])
    with pytest.raises(DecodeError, match="underran after 1 frames"):
        list(_segment_source_frames(cap, 0, 3, False, "v.mp4", 8))


def test_eof_during_lead_in_raises():
    cap = _FakeCap([(True, _bgr(0))])
    with pytest.raises(DecodeError, match="EOF during seek lead-in"):
        list(_segment_source_frames(cap, 3, 2, False, "v.mp4", 12))


def test_final_segment_must_yield_at_least_one_frame():
    with pytest.raises(DecodeError, match="found no frames"):
        list(_require_nonempty(iter(()), "v.mp4", 20))
    passthrough = [(np.zeros((1, 1, 3), np.uint8), 0.0)]
    assert len(list(_require_nonempty(iter(passthrough), "v.mp4", 20))) == 1


def test_cv2_seek_is_frame_exact_on_mp4v(tmp_path):
    """The cv2 POS_FRAMES backend lands exactly on mp4v containers — the
    property that makes 'auto' parity-safe without ffmpeg installed."""
    path = _write_video(tmp_path / "seek.mp4", frames=30)
    _, seq = open_video(path)
    frames = [rgb for rgb, _ in seq]
    cap, lead_in = _seeked_capture(path, 13)
    assert cap is not None
    got = list(_segment_source_frames(cap, lead_in, 5, False, path, 13))
    assert len(got) == 5
    for off, (rgb, _ts) in enumerate(got):
        np.testing.assert_array_equal(rgb, frames[13 + off])


# ---------------------------------------------------------------------------
# stitched parity on real containers (the acceptance invariant)


@pytest.mark.parametrize("efps", [None, 4, 25])
@pytest.mark.parametrize("k", [2, 3])
def test_stitched_stream_byte_identical_to_sequential(tmp_path, efps, k):
    path = _write_video(tmp_path / f"par_{efps}_{k}.mp4", frames=25)
    meta, frames = open_video(path, extraction_fps=efps, use_ffmpeg="never")
    seq = list(frames)
    plan = plan_segments(probe_video(path), k, extraction_fps=efps)
    assert len(plan.bounds) == k
    assert (plan.meta.fps, plan.meta.frame_count) == (meta.fps, meta.frame_count)
    stitched = [item for j in range(k) for item in open_video_segment(plan, j)]
    assert len(stitched) == len(seq)
    for (rgb_a, ts_a), (rgb_b, ts_b) in zip(seq, stitched):
        np.testing.assert_array_equal(rgb_a, rgb_b)
        assert ts_a == ts_b


def test_stitched_parity_with_host_transform(tmp_path):
    path = _write_video(tmp_path / "tr.mp4", frames=20)
    transform = lambda rgb: rgb[::2, ::2].astype(np.float32) / 255.0  # noqa: E731
    _, frames = open_video(path, transform=transform)
    seq = list(frames)
    plan = plan_segments(probe_video(path), 3)
    stitched = [item for j in range(3)
                for item in open_video_segment(plan, j, transform=transform)]
    for (rgb_a, ts_a), (rgb_b, ts_b) in zip(seq, stitched):
        np.testing.assert_array_equal(rgb_a, rgb_b)
        assert ts_a == ts_b


def test_open_video_segment_validates_inputs(tmp_path):
    plan = plan_segments(
        VideoMeta(path="v", fps=10.0, frame_count=20, width=2, height=2), 2)
    with pytest.raises(ValueError, match="segment index"):
        open_video_segment(plan, 2)
    with pytest.raises(ValueError, match="seek must be"):
        open_video_segment(plan, 0, seek="bogus")


# ---------------------------------------------------------------------------
# ffmpeg fast-seek streamer (fake binary — the image ships no ffmpeg)


def _install_fake_ffmpeg(tmp_path, monkeypatch, body):
    d = tmp_path / "bin"
    d.mkdir(exist_ok=True)
    script = d / "ffmpeg"
    script.write_text("#!/bin/sh\n" + body)
    script.chmod(0o755)
    monkeypatch.setenv("PATH", f"{d}:{os.environ.get('PATH', '')}")
    return d


def test_segment_frames_requires_ffmpeg(tmp_path, monkeypatch):
    empty = tmp_path / "nobin"
    empty.mkdir()
    monkeypatch.setenv("PATH", str(empty))
    assert not ffmpeg_io.have_ffmpeg()
    with pytest.raises(RuntimeError, match="cv2 seek backend"):
        next(ffmpeg_io.segment_frames("v.mp4", 0, 2, 10.0, 4, 4))


def test_segment_frames_command_and_rawvideo_parse(tmp_path, monkeypatch):
    d = _install_fake_ffmpeg(
        tmp_path, monkeypatch,
        f'echo "$@" > {tmp_path}/args\nhead -c 96 /dev/zero\n')
    assert ffmpeg_io.which_ffmpeg() == str(d / "ffmpeg")
    frames = list(ffmpeg_io.segment_frames("vid.mp4", 6, 2, 10.0, 4, 4))
    assert len(frames) == 2
    assert all(f.shape == (4, 4, 3) and f.dtype == np.uint8 for f in frames)
    args = (tmp_path / "args").read_text().split()
    # fast seek: -ss half a frame before the target, BEFORE -i
    assert args.index("-ss") < args.index("-i")
    assert float(args[args.index("-ss") + 1]) == pytest.approx(0.55)
    assert args[args.index("-frames:v") + 1] == "2"
    assert args[args.index("-pix_fmt") + 1] == "rgb24"
    assert "-nostdin" in args and args[-1] == "pipe:1"


def test_segment_frames_no_seek_flag_for_segment_zero(tmp_path, monkeypatch):
    _install_fake_ffmpeg(
        tmp_path, monkeypatch,
        f'echo "$@" > {tmp_path}/args\nhead -c 48 /dev/zero\n')
    assert len(list(ffmpeg_io.segment_frames("vid.mp4", 0, None, 10.0, 4, 4))) == 1
    args = (tmp_path / "args").read_text().split()
    assert "-ss" not in args and "-frames:v" not in args


def test_segment_frames_classifies_input_error_permanent(tmp_path, monkeypatch):
    _install_fake_ffmpeg(
        tmp_path, monkeypatch,
        'echo "vid.mp4: moov atom not found" >&2\nexit 1\n')
    with pytest.raises(FfmpegError, match="moov atom") as ei:
        list(ffmpeg_io.segment_frames("vid.mp4", 3, 2, 10.0, 4, 4))
    assert ei.value.transient is False


def test_segment_frames_underrun_is_a_stitch_error(tmp_path, monkeypatch):
    _install_fake_ffmpeg(tmp_path, monkeypatch, "head -c 48 /dev/zero\n")
    with pytest.raises(FfmpegError, match="frame count unreliable"):
        list(ffmpeg_io.segment_frames("vid.mp4", 3, 2, 10.0, 4, 4))


# ---------------------------------------------------------------------------
# decode pool: reservation, reassembly, faults, resize


def _pool_fixture(workers, n_frames=12, poison=None, delay=0.0):
    """Pool + fake segmenter over a synthetic frame-index stream."""
    meta = VideoMeta(path="v.mp4", fps=10.0, frame_count=n_frames,
                     width=4, height=4)

    def open_seq(path):
        return meta, iter([(np.full((4, 4, 3), i % 251, np.uint8), float(i))
                           for i in range(n_frames)])

    def planner(path, max_segments):
        return plan_segments(meta, max_segments)

    def open_segment(plan, index):
        if poison is not None and index == poison:
            raise DecodeError(f"{plan.source_meta.path}#seg{index}: poisoned")

        def gen():
            s, e = plan.bounds[index]
            for i in range(s, e):
                if delay:
                    time.sleep(delay)
                yield np.full((4, 4, 3), i % 251, np.uint8), float(i)

        return gen()

    pool = DecodePrefetcher(open_seq, workers=workers)
    pool.set_segmenter(planner, open_segment)
    return pool


def _wait_for(cond, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return cond()


def test_pool_segmented_reassembly_in_order():
    pool = _pool_fixture(workers=4, n_frames=12)
    try:
        pool.schedule("v.mp4")
        meta, frames = pool.get("v.mp4")
        got = list(frames)
        assert meta.frame_count == 12
        assert [int(ts) for _rgb, ts in got] == list(range(12))
        for rgb, ts in got:
            assert int(rgb[0, 0, 0]) == int(ts) % 251
        pool.release("v.mp4")
        assert pool.segment_stats() == (1, 4)
        # every segment worker hands its permit back
        assert _wait_for(lambda: pool.spare_permits() == 4)
    finally:
        pool.shutdown()


def test_pool_declines_segmentation_without_two_spare_permits():
    calls = []
    pool = _pool_fixture(workers=1)
    planner = pool._planner
    pool.set_segmenter(lambda p, m: calls.append(m) or planner(p, m),
                       pool._segment_open)
    try:
        pool.schedule("v.mp4")
        _meta, frames = pool.get("v.mp4")
        assert len(list(frames)) == 12
        assert calls == []  # spare < 2: planner never consulted
        assert pool.segment_stats() == (0, 0)
    finally:
        pool.shutdown()


def test_pool_poisoned_segment_fails_only_at_its_offset():
    pool = _pool_fixture(workers=4, n_frames=12, poison=1)
    try:
        pool.schedule("v.mp4")
        _meta, frames = pool.get("v.mp4")
        got = []
        with pytest.raises(DecodeError, match="seg1: poisoned"):
            for item in frames:
                got.append(item)
        # segment 0's frames streamed clean before the error surfaced
        assert [int(ts) for _rgb, ts in got] == list(range(3))
        pool.release("v.mp4")
        assert _wait_for(lambda: pool.spare_permits() == 4)
        # the pool is healthy for the next video
        pool2 = _pool_fixture(workers=4)
    finally:
        pool.shutdown()
    try:
        pool2.schedule("v.mp4")
        assert len(list(pool2.get("v.mp4")[1])) == 12
    finally:
        pool2.shutdown()


def test_pool_release_fans_out_to_all_segment_workers():
    pool = _pool_fixture(workers=4, n_frames=12, delay=0.02)
    try:
        pool.schedule("v.mp4")
        _meta, frames = pool.get("v.mp4")
        next(frames)  # consume one item, then abandon mid-stream
        pool.release("v.mp4")
        assert _wait_for(lambda: pool.spare_permits() == 4)
    finally:
        pool.shutdown()


def test_pool_shrink_never_cancels_mid_flight_segments():
    pool = _pool_fixture(workers=4, n_frames=12, delay=0.01)
    try:
        pool.schedule("v.mp4")
        _wait_for(lambda: pool.spare_permits() == 0, timeout=1.0)
        pool.resize(2)  # shrink while all four segments are in flight
        _meta, frames = pool.get("v.mp4")
        got = [int(ts) for _rgb, ts in frames]
        assert got == list(range(12))  # parity survives the shrink
        pool.release("v.mp4")
        assert pool.segment_stats() == (1, 4)  # all four completed clean
        assert _wait_for(lambda: pool.spare_permits() == 2)
    finally:
        pool.shutdown()


def test_pool_spare_permits_reserved_synchronously_at_schedule():
    pool = _pool_fixture(workers=4, delay=0.05)
    try:
        assert pool.spare_permits() == 4
        pool.schedule("v.mp4")  # segmented: reserves all permits up front
        assert pool.spare_permits() == 0
        list(pool.get("v.mp4")[1])
        pool.release("v.mp4")
        assert _wait_for(lambda: pool.spare_permits() == 4)
    finally:
        pool.shutdown()


# ---------------------------------------------------------------------------
# autoscaler interplay: segment-before-grow


def test_starved_interval_with_spare_permits_segments_instead_of_growing():
    scaler = DecodeAutoscaler(min_workers=1, max_workers=8)
    starved = dict(occupancy=0.5, decode_seconds=6.0, wall_seconds=10.0,
                   dispatched_slots=16, current=4)
    assert scaler.decide(**starved, spare_permits=2) == 4
    assert scaler.decide(**starved, spare_permits=0) == 5


def test_idle_interval_still_shrinks_regardless_of_spare():
    scaler = DecodeAutoscaler(min_workers=1, max_workers=8)
    idle = dict(occupancy=0.95, decode_seconds=0.2, wall_seconds=10.0,
                dispatched_slots=16, current=4)
    assert scaler.decide(**idle, spare_permits=3) == 3
    assert scaler.decide(**idle, spare_permits=0) == 3


# ---------------------------------------------------------------------------
# end-to-end: byte parity through the run loop for two extractor shapes


class StreamHasher(Extractor):
    """Frame-stream consumer that fingerprints the exact decoded bytes."""

    uses_frame_stream = True

    def extract(self, video_path):
        h = hashlib.sha256()
        _meta, frames = self._open_video(video_path)
        for rgb, pos in frames:
            h.update(np.ascontiguousarray(rgb).tobytes())
            h.update(np.float64(pos).tobytes())
        return {"feat": np.frombuffer(h.digest(), np.uint8).astype(np.float32)}


class FlowPairHasher(Extractor):
    """Flow-style consumer: fingerprints consecutive frame PAIRS, the stream
    shape the optical-flow extractors feed their models."""

    uses_frame_stream = True

    def extract(self, video_path):
        h = hashlib.sha256()
        _meta, frames = self._open_video(video_path)
        prev = None
        for rgb, _pos in frames:
            if prev is not None:
                h.update(np.ascontiguousarray(prev).tobytes())
                h.update(np.ascontiguousarray(rgb).tobytes())
            prev = rgb
        return {"feat": np.frombuffer(h.digest(), np.uint8).astype(np.float32)}


@pytest.fixture(scope="module")
def seg_corpus(tmp_path_factory):
    d = tmp_path_factory.mktemp("seg_corpus")
    return [_write_video(d / f"vid{i}.mp4", frames=24) for i in range(4)]


def _cfg(tmp_path, sub, **kw):
    kw.setdefault("retries", 1)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        feature_type="resnet50", on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / sub / "o"),
        tmp_path=str(tmp_path / sub / "t"), **kw)


def _digests(out_dir):
    return {name: np.load(os.path.join(out_dir, name)).tobytes()
            for name in sorted(os.listdir(out_dir)) if name.endswith(".npy")}


@pytest.mark.parametrize("extractor_cls", [StreamHasher, FlowPairHasher])
@pytest.mark.parametrize("efps", [None, 4])
def test_e2e_segmented_run_matches_sequential(
        tmp_path, seg_corpus, extractor_cls, efps):
    seq = extractor_cls(_cfg(tmp_path, "seq", decode_segments=1,
                             extraction_fps=efps, use_ffmpeg="never"))
    assert seq.run(seg_corpus) == len(seg_corpus)
    segd = extractor_cls(_cfg(tmp_path, "seg", decode_workers=4,
                              decode_segments=3, extraction_fps=efps,
                              use_ffmpeg="never"))
    assert segd.run(seg_corpus) == len(seg_corpus)
    a, b = _digests(seq.output_dir), _digests(segd.output_dir)
    assert set(a) == set(b) and len(a) == len(seg_corpus)
    assert a == b  # byte-identical features <=> byte-identical streams


def test_e2e_poisoned_segment_fails_only_its_video_and_retries(
        tmp_path, seg_corpus, monkeypatch):
    monkeypatch.setenv("VFT_FAULTS", "decode_segment:raise:vid2.mp4#seg1")
    ex = StreamHasher(_cfg(tmp_path, "a", decode_workers=4, decode_segments=2))
    assert ex.run(seg_corpus) == len(seg_corpus) - 1
    failures = load_failures(ex.output_dir)
    assert set(failures) == {os.path.abspath(seg_corpus[2])}
    assert failures[os.path.abspath(seg_corpus[2])]["error_class"] == "DecodeError"

    # --retry_failed semantics: faults cleared, exactly the failed set reruns
    monkeypatch.delenv("VFT_FAULTS")
    reset_faults()
    failed = sorted(load_failures(ex.output_dir))
    assert ex.run(failed) == 1
    assert load_failures(ex.output_dir) == {}
    assert len(load_done_set(ex.output_dir)) == len(seg_corpus)

    # and the recovered video's digest matches a sequential decode
    seq = StreamHasher(_cfg(tmp_path, "b", decode_segments=1))
    assert seq.run([seg_corpus[2]]) == 1
    a, b = _digests(ex.output_dir), _digests(seq.output_dir)
    assert all(a[name] == b[name] for name in b)


def test_e2e_video_timeout_cooperative_across_segments(
        tmp_path, seg_corpus, monkeypatch):
    """A wedged segment worker trips the per-video watchdog; the failure is
    attributed to its video only and the released permits let the rest of
    the corpus finish promptly."""
    monkeypatch.setenv("VFT_FAULTS", "decode_segment:hang(5):vid1.mp4#seg1")
    ex = StreamHasher(_cfg(tmp_path, "a", decode_workers=4, decode_segments=2,
                           video_timeout=0.5, retries=0))
    t0 = time.monotonic()
    assert ex.run(seg_corpus) == len(seg_corpus) - 1
    assert time.monotonic() - t0 < 30.0
    (rec,) = load_failures(ex.output_dir).values()
    assert rec["video"] == os.path.abspath(seg_corpus[1])
    assert rec["error_class"] == "VideoTimeoutError"
