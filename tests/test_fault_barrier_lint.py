"""Tier-1 guard: the fault-barrier lint keeps the error taxonomy from eroding."""

import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import lint_fault_barrier  # noqa: E402


def test_repo_is_clean():
    findings, counts = lint_fault_barrier.scan(REPO)
    assert findings == []
    assert sum(counts.values()) == sum(lint_fault_barrier.ALLOWED.values())


def test_main_exit_code_clean(capsys):
    assert lint_fault_barrier.main([REPO]) == 0
    assert "no strays" in capsys.readouterr().out


@pytest.fixture
def fake_repo(tmp_path):
    pkg = tmp_path / "video_features_tpu"
    pkg.mkdir()
    return tmp_path, pkg


def test_detects_unmarked_broad_except(fake_repo):
    root, pkg = fake_repo
    (pkg / "sneaky.py").write_text(
        "try:\n    pass\nexcept Exception:\n    pass\n")
    findings, _ = lint_fault_barrier.scan(str(root))
    assert any("without a 'fault-barrier:'" in f for f in findings)


def test_detects_undeclared_file_even_with_marker(fake_repo):
    root, pkg = fake_repo
    (pkg / "undeclared.py").write_text(
        "try:\n    pass\nexcept Exception:  # fault-barrier: sounds legit\n    pass\n")
    findings, _ = lint_fault_barrier.scan(str(root))
    assert any("no declared barriers" in f for f in findings)


def test_detects_bare_except_and_base_exception(fake_repo):
    root, pkg = fake_repo
    (pkg / "bare.py").write_text(
        "try:\n    pass\nexcept:\n    pass\n"
        "try:\n    pass\nexcept BaseException:\n    pass\n")
    findings, _ = lint_fault_barrier.scan(str(root))
    assert len([f for f in findings if "without a 'fault-barrier:'" in f]) == 2


def test_clean_fake_repo_passes(fake_repo):
    root, pkg = fake_repo
    (pkg / "fine.py").write_text(
        "try:\n    pass\nexcept ValueError:\n    pass\n")
    findings, counts = lint_fault_barrier.scan(str(root))
    assert findings == [] and counts == {}
