"""Golden end-to-end parity: real decoded video frames through the production
extractor step must match the torch mirror given identical converted weights.

Closes the loop SURVEY.md §4 asks for: the per-model parity tests feed random
arrays; these feed REAL frames through the host transform chain (native decode
→ PIL resize → crop) and compare the full device step — so a host/device
preprocessing drift (resize semantics, layout, normalization) fails here even
when the network-only tests pass."""

import itertools

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.io.video import open_video


@pytest.fixture
def ckpt_dir(tmp_path, monkeypatch):
    d = tmp_path / "ckpts"
    d.mkdir()
    monkeypatch.setenv("VFT_CHECKPOINT_DIR", str(d))
    monkeypatch.delenv("VFT_ALLOW_RANDOM_WEIGHTS", raising=False)
    return d


def _cfg(tmp_path, **kw):
    return ExtractionConfig(
        output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"),
        num_devices=1, **kw,
    )


def test_resnet_real_frames_match_torch(ckpt_dir, tmp_path, sample_video):
    import torch

    from tools.torch_mirrors import ResNet50 as TorchResNet50, random_init_

    from video_features_tpu.extractors.resnet import ExtractResNet50
    from video_features_tpu.models.resnet import IMAGENET_MEAN, IMAGENET_STD

    tm = random_init_(TorchResNet50(), seed=4)
    torch.save(tm.state_dict(), ckpt_dir / "resnet50.pt")
    ex = ExtractResNet50(_cfg(tmp_path, feature_type="resnet50", batch_size=8))

    _, frames_iter = open_video(sample_video, transform=ex._host_transform)
    frames = np.stack([rgb for rgb, _ in itertools.islice(frames_iter, 8)])
    assert frames.shape == (8, 224, 224, 3) and frames.dtype == np.uint8

    ours = np.asarray(ex._step(ex.params, ex.runner.put(frames)))

    x = frames.astype(np.float32) / 255.0
    x = ((x - np.asarray(IMAGENET_MEAN)) / np.asarray(IMAGENET_STD)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2)), features=True).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4 * np.abs(ref).max())


def test_i3d_real_stack_matches_torch(ckpt_dir, tmp_path, sample_video):
    import torch

    from tools.torch_mirrors import i3d_forward, i3d_random_state_dict

    from video_features_tpu.extractors.i3d import ExtractI3D
    from video_features_tpu.ops.image import pil_edge_resize

    sd = i3d_random_state_dict("rgb", seed=6)
    torch.save(sd, ckpt_dir / "i3d_rgb.pt")
    ex = ExtractI3D(_cfg(tmp_path, feature_type="i3d", streams=("rgb",),
                         stack_size=16, step_size=16))

    _, frames_iter = open_video(
        sample_video, transform=lambda rgb: pil_edge_resize(rgb, 256)
    )
    stack = np.stack([rgb for rgb, _ in itertools.islice(frames_iter, 17)])
    assert stack.shape[0] == 17

    feats, _ = ex._rgb_step(ex.i3d_params["rgb"], ex.runner.put(stack[None]))
    ours = np.asarray(feats)

    # torch path: the reference transform chain on the same decoded frames —
    # drop last frame, center-crop 224 (floor offsets), scale to [-1, 1], NCTHW
    h, w = stack.shape[1:3]
    fh, fw = (h - 224) // 2, (w - 224) // 2
    crop = stack[:-1, fh : fh + 224, fw : fw + 224, :]
    x = 2.0 * crop.astype(np.float32) / 255.0 - 1.0
    xt = torch.from_numpy(x.transpose(3, 0, 1, 2)[None])  # (1, C, T, H, W)
    ref = i3d_forward(sd, xt, features=True).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-4, atol=1e-4 * np.abs(ref).max())
