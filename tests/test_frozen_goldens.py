"""Production ``extract()`` vs FROZEN golden fixtures (tests/goldens/*.npz).

Unlike the live-oracle parity tests (which recompute the torch mirror at test
time and thus drift in lockstep with shared-constant edits or torch upgrades),
these compare against arrays frozen at generation time by
``tools/make_goldens.py`` — the suite fails if any feature drifts from the
committed values, whatever the cause.

Each fixture stores a weight fingerprint; if the deterministically re-seeded
state dict no longer matches it, the golden is STALE (torch RNG changed) and
the test fails with a regeneration hint instead of a misleading numeric diff.

Decode determinism: extraction runs with ``use_ffmpeg="never"`` so hosts with
and without ffmpeg resample fps identically (the fixtures were generated that
way). cv2/PIL version bumps that change decoded pixels require regeneration —
that is the point of a frozen fixture.
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.slow  # full-video extract() on CPU: minutes

import torch  # noqa: E402

from video_features_tpu.config import ExtractionConfig  # noqa: E402

REPO = os.path.join(os.path.dirname(__file__), "..")
GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "goldens")

import sys  # noqa: E402

sys.path.insert(0, os.path.join(REPO, "tools"))

from make_goldens import SAMPLES, fingerprint, state_dict_for, synth_wav  # noqa: E402


def _load(name):
    path = os.path.join(GOLDEN_DIR, f"{name}.npz")
    if not os.path.exists(path):
        pytest.skip(f"golden fixture {name} not generated")
    return dict(np.load(path))


def _check_fp(golden, key, model):
    sd = state_dict_for(model)
    fp = fingerprint(sd)
    if not np.allclose(fp, golden[key], rtol=1e-10):
        pytest.fail(
            f"STALE GOLDEN: deterministic weights for {model} no longer match the "
            f"fingerprint recorded in the fixture (torch RNG changed?). Regenerate "
            f"with: JAX_PLATFORMS=cpu python tools/make_goldens.py"
        )
    return sd


def _ckpt_dir(tmp_path, monkeypatch, **models):
    d = tmp_path / "ckpts"
    d.mkdir()
    for fname, sd in models.items():
        torch.save(sd, d / f"{fname}.pt")
    monkeypatch.setenv("VFT_CHECKPOINT_DIR", str(d))
    monkeypatch.delenv("VFT_ALLOW_RANDOM_WEIGHTS", raising=False)
    return d


def _cfg(tmp_path, **kw):
    kw.setdefault("use_ffmpeg", "never")
    kw.setdefault("num_devices", 1)
    return ExtractionConfig(
        output_path=str(tmp_path / "o"), tmp_path=str(tmp_path / "t"), **kw
    )


@pytest.mark.parametrize("vid", ["v1", "v2"])
def test_resnet50_frozen(vid, tmp_path, monkeypatch):
    from video_features_tpu.extractors.resnet import ExtractResNet50

    g = _load(f"resnet50_{vid}")
    sd = _check_fp(g, "fp", "resnet50")
    _ckpt_dir(tmp_path, monkeypatch, resnet50=sd)
    ex = ExtractResNet50(_cfg(tmp_path, feature_type="resnet50", batch_size=8,
                              extraction_fps=int(g["cfg_extraction_fps"])))
    out = ex.extract(SAMPLES[vid])["resnet50"][:: int(g["stride0"])]
    ref = g["features"]
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3 * np.abs(ref).max())


@pytest.mark.parametrize("vid", ["v1", "v2"])
def test_r21d_frozen(vid, tmp_path, monkeypatch):
    from video_features_tpu.extractors.r21d import ExtractR21D

    g = _load(f"r21d_{vid}")
    sd = _check_fp(g, "fp", "r21d")
    _ckpt_dir(tmp_path, monkeypatch, r2plus1d_18=sd)
    ex = ExtractR21D(_cfg(tmp_path, feature_type="r21d_rgb"))
    out = ex.extract(SAMPLES[vid])["r21d_rgb"]
    ref = g["features"]
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3 * np.abs(ref).max())


@pytest.mark.parametrize("kind,vid", [("raft", "v1"), ("raft", "v2"),
                                      ("pwc", "v1"), ("pwc", "v2")])
def test_flow_frozen(kind, vid, tmp_path, monkeypatch):
    from video_features_tpu.extractors.flow import ExtractFlow

    g = _load(f"{kind}_{vid}")
    sd = _check_fp(g, "fp", kind)
    _ckpt_dir(tmp_path, monkeypatch, **{f"{kind}-sintel": sd})
    ex = ExtractFlow(_cfg(tmp_path, feature_type=kind, batch_size=8,
                          side_size=int(g["cfg_side_size"]),
                          extraction_fps=int(g["cfg_extraction_fps"])))
    out = ex.extract(SAMPLES[vid])[kind]
    s0, shw = int(g["stride0"]), int(g["stride_hw"])
    out = out[::s0, :, ::shw, ::shw]
    ref = g["features"]
    assert out.shape == ref.shape
    if kind == "pwc":
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3 * np.abs(ref).max())
    else:
        # RAFT's 20 recurrent iterations chaotically amplify last-ulp backend
        # differences at ambiguous-correlation pixels: with random weights
        # ~0.3% of pixels converge to different fixed points entirely (observed
        # max |Δ| ≈ 39 px on an otherwise matching field). A real regression
        # shifts the whole field; bound the bulk and the typical error instead
        # of every element.
        err = np.abs(out - ref)
        scale = np.abs(ref).max() + 1e-6
        within = (err <= 5e-2 * scale + 5e-2).mean()
        assert within >= 0.99, f"only {within:.4f} of flow within tolerance"
        assert np.median(err) <= 1e-3 * scale + 1e-3, np.median(err)


@pytest.mark.parametrize("vid", ["v1", "v2"])
def test_i3d_two_stream_frozen(vid, tmp_path, monkeypatch):
    from video_features_tpu.extractors.i3d import ExtractI3D

    g = _load(f"i3d_{vid}")
    sd_rgb = _check_fp(g, "fp_rgb", "i3d_rgb")
    sd_flow = _check_fp(g, "fp_flow", "i3d_flow")
    sd_pwc = _check_fp(g, "fp_pwc", "pwc")
    _ckpt_dir(tmp_path, monkeypatch, i3d_rgb=sd_rgb, i3d_flow=sd_flow,
              **{"pwc-sintel": sd_pwc})
    ex = ExtractI3D(_cfg(tmp_path, feature_type="i3d", stack_size=16, step_size=16,
                         flow_type="pwc",
                         extraction_fps=int(g["cfg_extraction_fps"])))
    out = ex.extract(SAMPLES[vid])
    for stream in ("rgb", "flow"):
        ref = g[stream]
        assert out[stream].shape == ref.shape
        np.testing.assert_allclose(
            out[stream], ref, rtol=1e-3, atol=1e-3 * np.abs(ref).max(),
            err_msg=f"{stream} stream drifted from the frozen golden")


def test_vggish_frozen(tmp_path, monkeypatch):
    from video_features_tpu.extractors.vggish import ExtractVGGish
    from video_features_tpu.models.vggish import vggish_init_params
    from video_features_tpu.weights.store import save_params_npz

    g = _load("vggish_tone")
    params = vggish_init_params(seed=3)
    flat_sum = np.float64(sum(float(leaf.sum()) for mod in params.values()
                              for leaf in mod.values()))
    flat_abs = np.float64(sum(float(np.abs(leaf).sum()) for mod in params.values()
                              for leaf in mod.values()))
    n = sum(leaf.size for mod in params.values() for leaf in mod.values())
    if not np.allclose(np.array([flat_sum, flat_abs, n]), g["fp"], rtol=1e-10):
        pytest.fail("STALE GOLDEN: vggish deterministic params changed; regenerate "
                    "with tools/make_goldens.py")

    d = tmp_path / "ckpts"
    d.mkdir()
    save_params_npz(str(d / "vggish.npz"), params)
    monkeypatch.setenv("VFT_CHECKPOINT_DIR", str(d))
    monkeypatch.delenv("VFT_ALLOW_RANDOM_WEIGHTS", raising=False)

    wav = str(tmp_path / "tone.wav")
    synth_wav(wav)
    ex = ExtractVGGish(_cfg(tmp_path, feature_type="vggish"))
    out = ex.extract(wav)["vggish"]
    ref = g["features"]
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3 * np.abs(ref).max())
