"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-device sharding tests run without TPU hardware via
``--xla_force_host_platform_device_count`` (the TPU answer to testing multi-chip
topologies on one host). Env vars must be set before jax is imported anywhere.
"""

import os

# hard override: the session environment presets JAX_PLATFORMS=axon (TPU tunnel);
# tests always run on the virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize pre-registers the axon TPU backend and pins
# jax_platforms before conftest runs, so the env var alone is not enough —
# force the config through the API as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

_REPO_SAMPLE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "sample")
_SAMPLE_DIR = _REPO_SAMPLE if os.path.isdir(_REPO_SAMPLE) else "/root/reference/sample"
SAMPLE_VIDEO = os.path.join(_SAMPLE_DIR, "v_GGSY1Qvo990.mp4")
SAMPLE_VIDEO_2 = os.path.join(_SAMPLE_DIR, "v_ZNVhz7ctTq0.mp4")


@pytest.fixture(scope="session")
def sample_video():
    if not os.path.exists(SAMPLE_VIDEO):
        pytest.skip("sample video unavailable")
    return SAMPLE_VIDEO


@pytest.fixture(scope="session")
def sample_video_2():
    if not os.path.exists(SAMPLE_VIDEO_2):
        pytest.skip("sample video unavailable")
    return SAMPLE_VIDEO_2


@pytest.fixture
def rng():
    return np.random.default_rng(0)
