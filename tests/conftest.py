"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-device sharding tests run without TPU hardware via
``--xla_force_host_platform_device_count`` (the TPU answer to testing multi-chip
topologies on one host). Env vars must be set before jax is imported anywhere.
"""

import os

# hard override: the session environment presets JAX_PLATFORMS=axon (TPU tunnel);
# tests always run on the virtual CPU mesh
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (prev + " --xla_force_host_platform_device_count=8").strip()

import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The image's sitecustomize pre-registers the axon TPU backend and pins
# jax_platforms before conftest runs, so the env var alone is not enough —
# force the config through the API as well.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

_REPO_SAMPLE = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "sample")
_SAMPLE_DIR = _REPO_SAMPLE if os.path.isdir(_REPO_SAMPLE) else "/root/reference/sample"
SAMPLE_VIDEO = os.path.join(_SAMPLE_DIR, "v_GGSY1Qvo990.mp4")
SAMPLE_VIDEO_2 = os.path.join(_SAMPLE_DIR, "v_ZNVhz7ctTq0.mp4")


@pytest.fixture(scope="session")
def sample_video():
    if not os.path.exists(SAMPLE_VIDEO):
        pytest.skip("sample video unavailable")
    return SAMPLE_VIDEO


@pytest.fixture(scope="session")
def sample_video_2():
    if not os.path.exists(SAMPLE_VIDEO_2):
        pytest.skip("sample video unavailable")
    return SAMPLE_VIDEO_2


@pytest.fixture
def rng():
    return np.random.default_rng(0)


# ---- test tiers -----------------------------------------------------------
# fast: the pure-math/unit layer — `pytest -m fast` gives pre-commit signal in
# under a minute on a 1-core host (round-4 review: the full non-slow tier no
# longer fits a quick review budget). Membership is by module (measured
# per-module wall times, /tmp-tier sweep round 5); new quick modules should be
# added here. `slow` stays the parity/e2e layer; everything else is the
# default `not slow` tier.
_FAST_MODULES = {
    "test_async_writer",
    "test_cache",
    "test_config_cli",
    "test_edge_cases",
    "test_fault_barrier_lint",
    "test_filelist_output",
    "test_flow_sharded",
    "test_fps_resampler",
    "test_golden_pipeline",
    "test_ingest",
    "test_mirror_independence",
    "test_multimodel",
    "test_obs",
    "test_packer",
    "test_packer_buckets",
    "test_parallel",
    "test_reliability",
    "test_resample",
    "test_resnet_extractor",
    "test_service",
    "test_spatial",
    "test_vftlint",
    "test_video_decode",
    "test_wal",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if (item.module.__name__ in _FAST_MODULES
                and "slow" not in item.keywords):
            item.add_marker(pytest.mark.fast)
