"""Real-model packed parity: --pack_corpus over a mixed-length corpus must
produce byte-identical .npy outputs to the per-video loop through the
production ResNet-50 / R(2+1)D / I3D (rgb + pwc flow sandwich) / RAFT dense
flow / VGGish device steps.

Budget discipline: each test builds ONE extractor (random weights, tiny
geometry) and runs both loops through the SAME instance — the packed batches
have the same static shapes as the per-video loop's padded batches, so the
second run reuses every jit signature and nothing recompiles."""
# fast-registry: default tier — real-model packed parity (jit compiles)

import glob
import os

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig


def _write_video(path, frames, size=(32, 24)):
    import cv2

    w = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"), 10.0, size)
    rng = np.random.default_rng(frames)
    for _ in range(frames):
        w.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    w.release()
    return str(path)


@pytest.fixture(scope="module", autouse=True)
def _random_weights():
    mp = pytest.MonkeyPatch()
    mp.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")
    yield
    mp.undo()


def _cfg(tmp_path, **kw):
    return ExtractionConfig(
        on_extraction="save_numpy", num_devices=1,
        output_path=str(tmp_path / "u"), tmp_path=str(tmp_path / "tmp"), **kw)


def _both_runs(ex, tmp_path, corpus, feature_type):
    """Per-video loop, then --pack_corpus, through the same instance."""
    assert ex.run(corpus) == len(corpus)
    # same instance → shared jit signatures; rebind cfg/output for run 2
    ex.cfg = ex.cfg.replace(pack_corpus=True,
                            output_path=str(tmp_path / "p"))
    from video_features_tpu.io.output import feature_output_dir

    ex.output_dir = feature_output_dir(str(tmp_path / "p"), feature_type)
    assert ex.run(corpus) == len(corpus)

    def load(sub):
        return {os.path.basename(f): np.load(f) for f in
                glob.glob(str(tmp_path / sub / feature_type / "*.npy"))}

    unpacked, packed = load("u"), load("p")
    assert set(unpacked) == set(packed) and unpacked
    for k in unpacked:
        assert unpacked[k].dtype == packed[k].dtype, k
        assert unpacked[k].shape == packed[k].shape, k
        assert unpacked[k].tobytes() == packed[k].tobytes(), k
    return ex


def test_resnet50_packed_parity(tmp_path):
    from video_features_tpu.extractors.resnet import ExtractResNet50

    corpus = [_write_video(tmp_path / f"v{i}.mp4", n)
              for i, n in enumerate((5, 3, 6))]
    ex = ExtractResNet50(_cfg(tmp_path, feature_type="resnet50", batch_size=4))
    ex = _both_runs(ex, tmp_path, corpus, "resnet50")
    # 14 frames, batch budget 4 → paged dispatch ships 7 full 2-row pages
    # (page_rows = ceil(4 / pages_in_flight)) — zero pad waste, vs 16 slots
    # bucketed and 24 unpacked
    assert ex._pack_stats["real_slots"] == 14
    assert ex._pack_stats["dispatched_slots"] == 14
    assert ex._pack_stats["pages_dispatched"] == 7


def test_r21d_packed_parity(tmp_path):
    from video_features_tpu.extractors.r21d import ExtractR21D

    # native-resolution slots: all videos share one (2, 24, 32, 3) shape key.
    # pages_in_flight=1 keeps the page shape equal to the per-video loop's
    # batch shape: 3-D conv accumulation is NOT batch-shape invariant under
    # the test mesh's virtual-device CPU client (unlike the 2-D resnet /
    # vggish nets), so the per-video-loop parity bar needs shared jit
    # signatures; depth-2 paged-vs-bucketed parity at matched shapes is
    # pinned in tests/test_paged.py
    corpus = [_write_video(tmp_path / f"v{i}.mp4", n)
              for i, n in enumerate((3, 5, 4))]
    ex = ExtractR21D(_cfg(tmp_path, feature_type="r21d_rgb", stack_size=2,
                          step_size=2, clips_per_batch=2, pages_in_flight=1))
    ex = _both_runs(ex, tmp_path, corpus, "r21d_rgb")
    # clips 1+2+2 = 5 over 2-row pages → 6 slots packed vs 8 unpacked
    assert ex._pack_stats["real_slots"] == 5
    assert ex._pack_stats["dispatched_slots"] == 6
    assert ex._pack_stats["pages_dispatched"] == 3


def test_i3d_rgb_packed_parity(tmp_path):
    from video_features_tpu.extractors.i3d import ExtractI3D

    corpus = [_write_video(tmp_path / f"v{i}.mp4", n)
              for i, n in enumerate((17, 18, 34))]
    # pages_in_flight=1: shared jit signatures with the per-video loop (the
    # i3d conv3d stack, like r21d's, is not batch-shape invariant on the
    # test mesh; depth-2 parity at matched shapes lives in test_paged.py)
    ex = ExtractI3D(_cfg(tmp_path, feature_type="i3d", streams=("rgb",),
                         stack_size=16, step_size=16, clips_per_batch=2,
                         i3d_pre_crop_size=64, i3d_crop_size=32,
                         pages_in_flight=1))
    ex = _both_runs(ex, tmp_path, corpus, "i3d")
    # stacks 1+1+2 = 4 over 2-row pages → 4 slots packed vs 6 unpacked
    assert ex._pack_stats["real_slots"] == 4
    assert ex._pack_stats["dispatched_slots"] == 4
    assert ex._pack_stats["pages_dispatched"] == 2


def test_raft_packed_parity(tmp_path):
    """Dense-flow packing through the collate seam: frame-pair slots chained
    back into shared-frame windows must reproduce the per-video loop's bytes
    (each pair's flow is a pure function of its two frames under the one
    jitted program both loops dispatch)."""
    from video_features_tpu.extractors.flow import ExtractFlow

    corpus = [_write_video(tmp_path / f"v{i}.mp4", n)
              for i, n in enumerate((4, 3, 6))]
    ex = ExtractFlow(_cfg(tmp_path, feature_type="raft", batch_size=2))
    ex = _both_runs(ex, tmp_path, corpus, "raft")
    # pairs 3+2+5 = 10 over 2-pair windows → 5 full + 1 padded at flush
    assert ex._pack_stats["real_slots"] == 10
    assert ex._pack_stats["dispatched_slots"] == 12
    # single geometry: one bucket, keyed by the (2, H, W, 3) pair-slot shape
    assert list(ex._pack_stats["buckets"]) == ["2x24x32x3"]


def test_i3d_two_stream_pwc_sandwich_packed_parity(tmp_path):
    """The i3d flow sandwich packs as self-contained stack slots, and a
    two-stream job feeds both streams from one co-packed device batch —
    byte-identical to the per-video loop for both output keys."""
    from video_features_tpu.extractors.i3d import ExtractI3D

    corpus = [_write_video(tmp_path / f"v{i}.mp4", n)
              for i, n in enumerate((17, 18, 34))]
    ex = ExtractI3D(_cfg(tmp_path, feature_type="i3d",
                         streams=("rgb", "flow"), flow_type="pwc",
                         stack_size=16, step_size=16, clips_per_batch=2,
                         i3d_pre_crop_size=64, i3d_crop_size=32,
                         pages_in_flight=1))
    ex = _both_runs(ex, tmp_path, corpus, "i3d")
    # stacks 1+1+2 = 4 over 2-row pages (the two-stream composite forward
    # runs paged as ONE compiled program) vs 6 slots unpacked
    assert ex._pack_stats["real_slots"] == 4
    assert ex._pack_stats["dispatched_slots"] == 4
    assert ex._pack_stats["pages_dispatched"] == 2


def test_vggish_packed_parity(tmp_path):
    """Audio packs as fixed (96, 64) log-mel slabs — the corpus shares one
    shape queue and embeddings match the per-video loop bit for bit."""
    from scipy.io import wavfile

    from video_features_tpu.extractors.vggish import ExtractVGGish

    rng = np.random.default_rng(0)
    corpus = []
    for i, secs in enumerate((2.5, 1.2, 4.0)):
        p = str(tmp_path / f"a{i}.wav")
        wav = (rng.uniform(-0.5, 0.5, int(16000 * secs)) * 32767).astype(np.int16)
        wavfile.write(p, 16000, wav)
        corpus.append(p)
    ex = ExtractVGGish(_cfg(tmp_path, feature_type="vggish"))
    ex = _both_runs(ex, tmp_path, corpus, "vggish")
    # 2+1+4 = 7 examples pack into one padded 16-row page at corpus flush
    # (page_rows = ceil(32 / pages_in_flight); the per-video loop dispatches
    # three padded 32-slot batches = 96 slots, bucketed packing one of 32)
    assert ex._pack_stats["real_slots"] == 7
    assert ex._pack_stats["dispatched_slots"] == 16
    assert ex._pack_stats["pages_dispatched"] == 1


def test_pack_seam_fallbacks(tmp_path):
    """The only per-video fallbacks left: --show_pred (both extractors) and
    the single-clip frame-sharded flow sandwich — asserted at the config seam
    without building models."""
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.extractors.i3d import ExtractI3D

    from video_features_tpu.parallel.mesh import MeshRunner

    ex = ExtractI3D.__new__(ExtractI3D)  # seam check only: no weights/compile
    ex.streams = ("rgb", "flow")
    ex.clips_per_batch = 2
    ex.cfg = _cfg(tmp_path, feature_type="i3d")
    # the paged-dispatch fields need the mesh geometry and a params handle
    # (jit_paged is lazy — nothing traces or compiles here)
    ex.runner = MeshRunner(num_devices=1)
    ex.i3d_params = {"rgb": {}, "flow": {}}
    ex._flow_frame_sharded = True  # one clip fills the mesh: nothing to pack
    assert ex.pack_spec() is None
    ex._flow_frame_sharded = False
    spec = ex.pack_spec()
    assert spec is not None  # two-stream packs now
    assert spec.paged_step is not None  # ...and pages by default
    ex.cfg = ex.cfg.replace(show_pred=True)
    assert ex.pack_spec() is None

    fx = ExtractFlow.__new__(ExtractFlow)
    fx.cfg = _cfg(tmp_path, feature_type="raft", show_pred=True)
    assert fx.pack_spec() is None
