"""Uint8 ingest fast path: wire-dtype invariants, staging-ring discipline,
transfer accounting, and the --device_resize numerics gate.

The tentpole contract (docs/performance.md "ingest fast path"): decoded
frames ride host→device as uint8 end-to-end — the u8→fp32 scale is the
jitted step's first fused op, an EXACT cast, so outputs are byte-identical
to the retired float32 host staging at a quarter of the staged bytes — and
device batches are assembled into reusable staging-ring buffers that are
never rewritten while their ``device_put`` is pending.

Compile budget: everything here runs on stubbed steps or pure host code
except the one model-level byte-parity pin (a single tiny PWC geometry,
whose u8/f32 twin programs share almost all of their XLA work).
"""

import os

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.parallel.pipeline import HostStagingRing
from video_features_tpu.utils.metrics import StageClock


@pytest.fixture(autouse=True)
def _random_weights(monkeypatch):
    monkeypatch.setenv("VFT_ALLOW_RANDOM_WEIGHTS", "1")


def _cfg(tmp_path, feature_type, **kw):
    return ExtractionConfig(
        feature_type=feature_type, num_devices=1,
        output_path=str(tmp_path / "out"), tmp_path=str(tmp_path / "tmp"),
        **kw)


def _write_video(path, n_frames, size=(24, 16)):
    import cv2

    wr = cv2.VideoWriter(str(path), cv2.VideoWriter_fourcc(*"mp4v"),
                         10.0, size)
    rng = np.random.default_rng(7)
    for _ in range(n_frames):
        wr.write(rng.integers(0, 256, (size[1], size[0], 3), dtype=np.uint8))
    wr.release()
    return str(path)


class _FakeDev:
    """A committable 'device value': records whether the ring awaited it."""

    def __init__(self):
        self.blocked = False

    def block_until_ready(self):
        self.blocked = True


# ---- host padding into staging rows -----------------------------------------


def test_pad_to_shape_into_matches_pad_to_shape_uint8_round_trip():
    """The in-place staging pad is byte-identical to pad_to_shape (uint8
    stays uint8 on the wire) and unpad recovers the original frame."""
    from video_features_tpu.models.raft import (
        pad_to_shape, pad_to_shape_into, unpad)

    rng = np.random.default_rng(0)
    frame = rng.integers(0, 256, (13, 17, 3), dtype=np.uint8)
    for target in ((16, 24), (13, 17), (14, 17), (13, 20)):
        ref, ref_pads = pad_to_shape(frame, target)
        out = np.full(target + (3,), 99, np.uint8)  # poisoned: full overwrite
        pads = pad_to_shape_into(frame, out)
        assert pads == ref_pads
        np.testing.assert_array_equal(out, ref)
        assert out.dtype == np.uint8
        np.testing.assert_array_equal(unpad(out, pads), frame)
    with pytest.raises(ValueError, match="cannot pad"):
        pad_to_shape_into(frame, np.empty((8, 8, 3), np.uint8))


def test_pad_batch_preserves_uint8_zero_pad():
    from video_features_tpu.extractors.base import pad_batch

    arr = np.full((2, 4, 4, 3), 200, np.uint8)
    padded = pad_batch(arr, 5)
    assert padded.dtype == np.uint8 and padded.shape[0] == 5
    np.testing.assert_array_equal(padded[:2], arr)
    assert not padded[2:].any()


# ---- staging ring -----------------------------------------------------------


def test_staging_ring_reuses_buffers_and_guards_inflight_transfers():
    """The bounded-ring discipline: ≤ depth buffers per geometry, recycled
    least-recently-acquired first, and a buffer is handed out again only
    AFTER its committed transfer reported ready (the in-flight guard)."""
    waits = []
    ring = HostStagingRing(depth=2, on_wait=waits.append)
    b1 = ring.acquire((2, 3), np.uint8)
    d1 = _FakeDev()
    ring.commit(b1, d1)
    b2 = ring.acquire((2, 3), np.uint8)
    d2 = _FakeDev()
    ring.commit(b2, (d2,))  # pytree device values supported (sharded puts)
    assert b2 is not b1 and ring.allocated == 2
    # wrap-around: the oldest buffer comes back, but only after its transfer
    # was awaited — d1 must be blocked on, d2 (still newest) must not
    b3 = ring.acquire((2, 3), np.uint8)
    assert b3 is b1
    assert d1.blocked and not d2.blocked
    assert len(waits) == 1 and ring.wait_seconds >= 0.0
    # distinct geometries/dtypes keep distinct rings
    other = ring.acquire((2, 3), np.float32)
    assert other is not b1 and other.dtype == np.float32
    assert ring.allocated == 3


def test_staging_ring_bounds_geometries_with_lru_eviction():
    """Long-run memory bound: past max_geometries distinct staged shapes,
    the least-recently-acquired geometry's ring is dropped — its pending
    transfer awaited first — so a daemon staging an open-ended geometry mix
    holds at most cap × depth buffers (the ring analogue of packer.forget)."""
    ring = HostStagingRing(depth=2, max_geometries=2)
    b1 = ring.acquire((2, 2), np.uint8)
    d1 = _FakeDev()
    ring.commit(b1, d1)
    ring.acquire((3, 3), np.uint8)
    ring.acquire((4, 4), np.uint8)  # over the cap: evicts the (2,2) ring
    assert ring.evicted_geometries == 1
    assert d1.blocked  # the evicted geometry's in-flight transfer was awaited
    assert set(k[0] for k in ring._rings) == {(3, 3), (4, 4)}
    # the evicted geometry still works — it just re-allocates
    b1b = ring.acquire((2, 2), np.uint8)
    assert b1b is not b1 and ring.evicted_geometries == 2


def test_staging_ring_commit_tolerates_foreign_buffers():
    """commit() is a no-op for batches the ring does not own (pad_batch
    tails, frame-sharded view tuples) — callers need not track which
    dispatched batches were ring-staged."""
    ring = HostStagingRing(depth=2)
    ring.commit(np.zeros((4, 4), np.uint8), _FakeDev())  # unknown geometry
    buf = ring.acquire((4, 4), np.uint8)
    ring.commit(np.zeros((4, 4), np.uint8), _FakeDev())  # same geometry, foreign
    ring.commit((np.zeros(3),), _FakeDev())  # non-array (view tuple)
    # the owned buffer is still free (no stray device value attached)
    d = _FakeDev()
    ring.commit(buf, d)
    ring.acquire((4, 4), np.uint8)
    b3 = ring.acquire((4, 4), np.uint8)
    assert b3 is buf and d.blocked


# ---- flow wire format + transfer accounting ---------------------------------


def _stubbed_flow(tmp_path, sub, **cfg_kw):
    """ExtractFlow whose jitted step is replaced by a host stub recording
    every dispatched window's dtype/shape — zero XLA compiles, so the wire
    and byte-accounting invariants stay fast-tier."""
    from video_features_tpu.extractors.flow import ExtractFlow

    cfg = ExtractionConfig(
        feature_type="raft", batch_size=2, num_devices=1,
        output_path=str(tmp_path / sub / "out"),
        tmp_path=str(tmp_path / sub / "tmp"), **cfg_kw)
    ex = ExtractFlow(cfg)
    seen = {"dtypes": [], "shapes": [], "bufs": []}

    def fake_step(params, dev):
        seen["dtypes"].append(str(dev.dtype))
        seen["shapes"].append(tuple(dev.shape))
        return jnp.zeros((dev.shape[0] - 1,) + tuple(dev.shape[1:3]) + (2,),
                         jnp.float32)

    ex.__dict__["_frames_step"] = fake_step  # cached_property override
    return ex, seen


def test_flow_windows_ride_uint8_and_staged_bytes_drop_4x(tmp_path):
    """The byte-accounting acceptance pin: per-video flow windows dispatch
    as uint8 (quarter the host→device bytes of the --float32_wire escape
    hatch, exactly), the 'transfer' stage records the staged payload, and
    the staging ring reuses its buffers instead of allocating per batch."""
    video = _write_video(tmp_path / "v.mp4", 7)

    ex, seen = _stubbed_flow(tmp_path, "u8")
    ex.clock = StageClock()
    ex.extract(video)
    assert set(seen["dtypes"]) == {"uint8"}
    # 6 frames decoded at (16, 24) → windows of batch_size+1 = 3 frames
    frame_bytes = 16 * 24 * 3
    u8_bytes = ex.clock.bytes["transfer"]
    assert u8_bytes == sum(int(np.prod(s)) for s in seen["shapes"])
    assert u8_bytes > 0 and u8_bytes % frame_bytes == 0
    assert ex.clock.counts["transfer"] == len(seen["shapes"])
    # ring reuse: one buffer per in-flight window, NOT one per batch
    assert ex._staging.allocated <= ex.cfg.prefetch_depth + 2
    assert ex._staging.acquires == len(seen["shapes"])

    ex32, seen32 = _stubbed_flow(tmp_path, "f32", float32_wire=True)
    ex32.clock = StageClock()
    ex32.extract(video)
    assert set(seen32["dtypes"]) == {"float32"}
    assert ex32.clock.bytes["transfer"] == 4 * u8_bytes


def test_packed_collate_stages_uint8_windows(tmp_path):
    """Packed-collate dtype invariant: the shared-frame window the flow
    collate assembles is a ring-staged uint8 buffer (float32 only under the
    --float32_wire escape hatch), with the chain/row-map semantics of the
    retired np.stack path."""
    from video_features_tpu.extractors.flow import ExtractFlow

    ex = ExtractFlow(_cfg(tmp_path, "raft", batch_size=4, pack_corpus=True))
    spec = ex.pack_spec()
    rng = np.random.default_rng(3)
    frames = rng.integers(0, 256, (4, 16, 24, 3), dtype=np.uint8)
    clips = [np.stack([frames[0], frames[1]]),   # stream 1, idx 0
             np.stack([frames[1], frames[2]]),   # stream 1, idx 1 (chained)
             np.stack([frames[2], frames[3]])]   # stream 2 (chain break)
    keys = [(1, 0), (1, 1), (2, 5)]
    batch, n_used, row_of = spec.collate(clips, keys)
    assert batch.dtype == np.uint8
    assert batch.shape == (5, 16, 24, 3)  # capacity = batch_size + 1
    assert n_used == 3 and list(row_of) == [0, 1, 3]
    # chained pair shares the middle frame; the break re-stages its source
    np.testing.assert_array_equal(batch[0], frames[0])
    np.testing.assert_array_equal(batch[1], frames[1])
    np.testing.assert_array_equal(batch[2], frames[2])
    np.testing.assert_array_equal(batch[3], frames[2])
    np.testing.assert_array_equal(batch[4], frames[3])
    assert ex._staging.allocated == 1  # ring-staged, not np.stack'd

    ex32 = ExtractFlow(_cfg(tmp_path / "f32", "raft", batch_size=4,
                            pack_corpus=True, float32_wire=True))
    batch32, _, _ = ex32.pack_spec().collate(clips, keys)
    assert batch32.dtype == np.float32  # escape hatch: exact upcast staging
    np.testing.assert_array_equal(batch32, batch.astype(np.float32))


def test_packer_default_path_stages_uint8_and_accounts_bytes():
    """The no-collate packer path: clip slots stack into a ring buffer at
    their own (uint8) dtype, zero-padded tails included, and staged_bytes
    counts every dispatched batch's host payload."""
    from video_features_tpu.parallel.packer import CorpusPacker, PackSpec

    staged = []

    def step(batch):
        staged.append(batch)
        return np.asarray(batch, np.float32).reshape(batch.shape[0], -1)

    ring = HostStagingRing(depth=2)
    spec = PackSpec(batch_size=2, empty_row_shape=(12,), open_clips=None,
                    step=step, finalize=None)
    packer = CorpusPacker(spec, wait=np.asarray, staging=ring)
    packer.begin("a", {})
    for v in (10, 20, 30):
        packer.add("a", np.full((2, 2, 3), v, np.uint8))
    packer.finish("a")
    packer.flush()
    assert [b.dtype for b in staged] == [np.uint8, np.uint8]
    assert not staged[1][1].any()  # zero-padded tail slot, uint8 zeros
    assert ring.allocated <= 2  # ring-staged, committed against step output
    assert packer.staged_bytes == sum(b.nbytes for b in staged)
    (done,) = packer.pop_completed()
    np.testing.assert_array_equal(
        done.stacked((12,))[:, 0], [10.0, 20.0, 30.0])


# ---- transfer-dtype upcast hoist --------------------------------------------


def test_transfer_dtype_upcast_decision_hoisted_and_output_fp32(tmp_path):
    """The reap-path upcast is decided once from the config (not re-inspected
    per batch), and fetched float16/bfloat16 flow upcasts to float32 — the
    fast-tier output-dtype assertion for the sub-fp32 transfer dtypes."""
    from video_features_tpu.extractors.flow import ExtractFlow

    for td, dev_dtype, expects_upcast in (
            ("float32", jnp.float32, False),
            ("float16", jnp.float16, True),
            ("bfloat16", jnp.bfloat16, True)):
        ex = ExtractFlow(_cfg(tmp_path / td, "raft", batch_size=2,
                              transfer_dtype=td))
        assert ex._upcast is expects_upcast
        # fake dispatched handle: (device flow, n_pairs, pads) — no compile
        handle = (jnp.zeros((3, 16, 24, 2), dev_dtype), 2, (0, 0, 0, 0))
        flow = ex._collect_pairs(handle)
        assert flow.dtype == np.float32
        assert flow.shape == (2, 2, 16, 24)
        # packed finalize shares the hoisted decision
        spec_final = ex.pack_spec().finalize
        rows = np.zeros((2, 16, 24, 2),
                        np.float16 if expects_upcast else np.float32)
        out = spec_final("v", rows, {"fps": 10.0, "timestamps_ms": [0, 1],
                                     "pads": (0, 0, 0, 0),
                                     "native_hw": (16, 24)})
        assert out["raft"].dtype == np.float32


# ---- model-level byte parity (the acceptance pin) ---------------------------


def test_uint8_wire_is_byte_identical_to_float32_wire_pwc():
    """uint8 frames through the real net == the same frames pre-cast to
    float32 on the host, bit for bit: the u8→fp32 scale inside the step is
    an exact cast, so the wire format cannot move output bytes. One tiny
    PWC geometry (the cheapest whole flow net) pins it at model level;
    tests/test_packer_models.py pins the loop-level parity end to end."""
    from video_features_tpu.models.pwc import pwc_forward_frames, pwc_init_params

    params = pwc_init_params(0)
    frames = np.random.default_rng(1).integers(
        0, 256, (3, 16, 16, 3), dtype=np.uint8)
    out_u8 = np.asarray(pwc_forward_frames(params, jnp.asarray(frames)))
    out_f32 = np.asarray(pwc_forward_frames(
        params, jnp.asarray(frames.astype(np.float32))))
    np.testing.assert_array_equal(out_u8, out_f32)


# ---- --device_resize --------------------------------------------------------


def test_device_resize_parity_within_documented_tolerance():
    """jax.image.resize edge-resize+crop vs the PIL host path: NOT bit
    identical (PIL interpolates in uint8 with its own rounding), but within
    the documented tolerance — ≤ 2 uint8 levels max, ≤ 1 mean — for both
    down- and up-scaling geometries (docs/performance.md numerics note)."""
    from video_features_tpu.ops.image import (
        device_resize_crop_hwc, np_center_crop_hwc, pil_edge_resize)

    rng = np.random.default_rng(5)
    for geom in ((37, 53), (20, 28)):  # downscale and upscale to edge 32
        frames = rng.integers(0, 256, (3,) + geom + (3,), dtype=np.uint8)
        host = np.stack([
            np_center_crop_hwc(pil_edge_resize(f, 32), 24, 24)
            for f in frames]).astype(np.float32)
        dev = np.asarray(device_resize_crop_hwc(jnp.asarray(frames), 32, 24))
        assert dev.shape == host.shape and dev.dtype == np.float32
        diff = np.abs(host - dev)
        assert diff.max() <= 2.0, f"{geom}: max drift {diff.max()}"
        assert diff.mean() <= 1.0, f"{geom}: mean drift {diff.mean()}"


def test_device_resize_routing_and_fallback_notice(tmp_path, capsys):
    """--device_resize ships RAW frames from the host on resnet50 (the step
    owns resize+crop) and prints an ignored-flag notice on feature types
    without a device-resize path."""
    from video_features_tpu.extractors.flow import ExtractFlow
    from video_features_tpu.extractors.resnet import ExtractResNet50

    ex = ExtractResNet50(_cfg(tmp_path, "resnet50", device_resize=True))
    raw = np.random.default_rng(0).integers(
        0, 256, (30, 40, 3), dtype=np.uint8)
    assert ex._host_transform(raw) is raw  # raw decoded frame on the wire
    host_ex = ExtractResNet50(_cfg(tmp_path / "h", "resnet50"))
    assert host_ex._host_transform(raw).shape == (224, 224, 3)
    capsys.readouterr()
    ExtractFlow(_cfg(tmp_path / "f", "raft", batch_size=2,
                     device_resize=True))
    assert "--device_resize ignored" in capsys.readouterr().out


# ---- starvation signal ------------------------------------------------------


def test_starvation_warning_distinguishes_transfer_bound():
    """The PR 5 starvation signal now tells decode-bound from
    transfer-bound: low occupancy + decode-dominated wall keeps the
    --decode_workers nudge; low occupancy + transfer-dominated wall names
    the transfer pipe instead; healthy runs stay silent."""
    from video_features_tpu.utils.metrics import decode_starvation_warning

    decode = decode_starvation_warning(
        occupancy=0.5, decode_seconds=6.0, wall=10.0)
    assert decode is not None and "--decode_workers" in decode
    transfer = decode_starvation_warning(
        occupancy=0.5, decode_seconds=1.0, wall=10.0, transfer_seconds=6.0)
    assert transfer is not None and "transfer" in transfer
    assert "--decode_workers" not in transfer
    assert decode_starvation_warning(
        occupancy=0.95, decode_seconds=6.0, wall=10.0,
        transfer_seconds=6.0) is None
    assert decode_starvation_warning(
        occupancy=0.5, decode_seconds=1.0, wall=10.0,
        transfer_seconds=1.0) is None
