"""Split a video directory into N round-robin file lists for N independent jobs.

Drop-in equivalent of the reference's helper (``/root/reference/gen_file_list.py:6-21``),
same flags; delegates to :func:`video_features_tpu.io.filelist.write_shard_files`.
On a multi-host TPU deployment the same round-robin split runs implicitly via
``parallel.pipeline.shard_video_list`` — this script exists for the reference's
explicit launch-N-processes workflow.

    python gen_file_list.py -p ./videos -o ./file_lists -n 4
"""

import argparse
import os

from video_features_tpu.io.filelist import write_shard_files


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("-p", "--path", type=str, required=True,
                        help="directory whose entries become the video list")
    parser.add_argument("-o", "--output_path", type=str, default="./file_lists",
                        help="directory for the shard .txt files")
    parser.add_argument("-n", "--num_split", type=int, default=1)
    args = parser.parse_args()

    out_files = write_shard_files(args.path, args.output_path, args.num_split)
    total = sum(1 for p in out_files for _ in open(p))
    print(f"wrote {len(out_files)} shard lists covering {total} files under "
          f"{os.path.abspath(args.output_path)}")


if __name__ == "__main__":
    main()
