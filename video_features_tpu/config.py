"""Typed configuration for extraction jobs.

The reference passes a raw argparse ``Namespace`` into every extractor
(``/root/reference/main.py:86``, ``utils/utils.py:88-105``). Here the configuration is a
frozen dataclass: one shared ``ExtractionConfig`` covering the full reference flag
surface (``main.py:52-84``) plus TPU-specific knobs, with per-model defaults resolved by
``resolve_model_defaults``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

FEATURE_TYPES = ("i3d", "vggish", "r21d_rgb", "resnet50", "raft", "pwc")
ON_EXTRACTION = ("print", "save_numpy")
FLOW_TYPES = ("raft", "pwc")
STREAMS = ("rgb", "flow")


@dataclass(frozen=True)
class ExtractionConfig:
    """One extraction job: which model, which videos, how to run, where results go.

    Field names intentionally match the reference CLI flags (``main.py:52-84``) so the
    CLI shim is a 1:1 mapping.
    """

    feature_type: str
    video_paths: Tuple[str, ...] = ()
    file_with_video_paths: Optional[str] = None
    tmp_path: str = "./tmp"
    keep_tmp_files: bool = False
    on_extraction: str = "print"
    output_path: str = "./output"
    extraction_fps: Optional[int] = None
    stack_size: Optional[int] = None
    step_size: Optional[int] = None
    streams: Optional[Tuple[str, ...]] = None  # subset of ("rgb", "flow"); None = both
    flow_type: str = "pwc"
    batch_size: int = 1
    resize_to_smaller_edge: bool = True
    side_size: Optional[int] = None
    show_pred: bool = False

    # --- TPU-native knobs (no reference equivalent) ---
    # Compute dtype for model forwards; fp32 gives bit-parity with the torch
    # reference, bf16 maps better onto the MXU.
    dtype: str = "float32"
    # Clips per device step: batches sliding windows into one jit call so the MXU
    # stays busy (the reference runs one 64-frame stack at a time).
    clips_per_batch: int = 1
    # Data-parallel sharding: number of devices in the mesh (None = all local).
    num_devices: Optional[int] = None
    # Resume: skip videos whose outputs are recorded in the done-manifest.
    resume: bool = False
    # Host→HBM prefetch depth (double buffering by default).
    prefetch_depth: int = 2
    # Cross-video decode parallelism: background threads decoding upcoming
    # videos while the device computes (the reference gets this implicitly from
    # thread-per-GPU; SPMD centralizes devices, so decode streams are explicit).
    # 1 = inline decode. Frame-stream models only (resnet50, raft, pwc, i3d).
    decode_workers: int = 1
    # Segmented intra-video decode: split one video into seek-aligned
    # segments decoded concurrently by the pool and streamed back in order —
    # byte-identical to sequential decode by construction (io/video.py
    # plan_segments; docs/performance.md "Segmented decode"). 0 = auto
    # (segment only long videos, and only when the pool has wholly idle
    # permits); 1 = off; N >= 2 caps the split. Needs --decode_workers > 1.
    # The ffmpeg RE-ENCODE resample path (--extraction_fps with ffmpeg
    # installed and use_ffmpeg auto/always) is never segmented — it decodes
    # a different, re-encoded container whose parity anchor is sequential.
    decode_segments: int = 0
    # How a non-first segment lands frame-exact on its start frame: "auto"
    # seeks with cv2 CAP_PROP_POS_FRAMES when the backend's landing verifies
    # (same decoder as sequential decode — the byte-parity guarantee), falls
    # back to the ffmpeg -ss fast-seek rawvideo streamer (keyframe snap +
    # lead-in drop) for resampled streams it cannot land on, else to an
    # exact decode-and-drop rescan. "cv2"/"ffmpeg" force a backend.
    segment_seek: str = "auto"
    # Corpus-level clip packing (--pack_corpus): fill every fixed-shape device
    # batch with clips from however many videos are ready (the tail batch of
    # video N packs with the head of video N+1) instead of zero-padding each
    # video's tail — continuous batching for short-clip corpora
    # (parallel/packer.py, docs/performance.md). Every extractor packs: the
    # RGB paths (resnet50, r21d_rgb, i3d) pack stacked clip slots, the flow
    # extractors (raft/pwc and the i3d flow sandwich) pack frame-pair /
    # sandwich-stack slots, vggish packs fixed log-mel slabs, and mixed
    # geometries pack into ≤ pack_buckets padded shape buckets. The one
    # documented per-video fallback is --show_pred (its per-batch prints
    # assume video order; a notice is printed), plus the single-clip
    # frame-sharded flow sandwich, where one clip already fills the mesh.
    # Per-video fault attribution, resume, and retries are preserved, and
    # features are byte-identical to the per-video loop EXCEPT where a
    # merged flow bucket replicate-pads frames (the pack_buckets /
    # --shape_bucket border caveat; single-geometry corpora always match);
    # --video_timeout becomes a cooperative per-stream bound.
    pack_corpus: bool = False
    # --pack_corpus, flow extractors: cluster the corpus's probed (padded)
    # geometries into at most this many shape buckets before decode starts
    # (parallel/packer.py ShapeBuckets) — a mixed-resolution corpus compiles
    # ≤ K programs and co-packs inside each bucket instead of filling one
    # queue per distinct geometry. Merged buckets replicate-pad frames up to
    # the bucket geometry, which carries --shape_bucket's documented
    # border-perturbation caveat; single-geometry corpora are unaffected.
    pack_buckets: int = 4
    # --pack_corpus anti-starvation flush: dispatch a shape bucket's partial
    # queue (zero-padded) once this many videos have finished while it sat
    # waiting, so a rare geometry cannot strand its videos until corpus end.
    # Trades padding (occupancy) for latency on rare buckets; 0 disables
    # (partial queues then flush only at corpus end, the PR 4 behavior).
    pack_flush_age: int = 8
    # --pack_corpus ragged paged dispatch (parallel/pages.py,
    # docs/performance.md): default ON for the shape-compatible RGB/audio
    # paths (resnet50, r21d, i3d clip stacks, vggish slabs) — buckets ship
    # fixed (page_rows, ...) pages plus an int32 row table instead of
    # batch_size padded batches, keep pages_in_flight pages in flight per
    # bucket, and donate the row table's device buffer (mesh.py jit_paged).
    # Outputs stay byte-identical to bucketed dispatch (tests/test_paged.py);
    # pad waste drops to at most one partial page per flush. Raw-pixels wire
    # formats (--device_resize / --device_preproc) page too — queues key by
    # decoded geometry, so pages never co-host mixed shapes (ulp-level vs
    # the per-video loop, tests/test_device_preproc.py). Models that collate
    # their own windows (raft/pwc, the i3d flow sandwich) opt out per
    # PackSpec and dispatch bucketed exactly as before.
    paged_batching: bool = True
    # Paged in-flight depth per bucket: the host refills page k+1's staging
    # buffer while the device chews on page k (>= 2 = double-buffered
    # dispatch; page_rows = ceil(batch budget / depth), so total in-flight
    # rows stay at one bucketed batch regardless of depth).
    pages_in_flight: int = 2
    # Flow-net (RAFT/PWC) conv compute + correlation storage dtype, independent
    # of `dtype` (which governs the feature networks): bfloat16 halves flow-net
    # HBM traffic and MXU passes; correlation ACCUMULATION and coordinate math
    # stay fp32 either way. float32 (default) is the reference-parity path.
    # Measured bf16 drift: tests/test_flow_bf16.py and docs/architecture.md.
    flow_dtype: str = "float32"
    # RAFT correlation: "auto" (default) materializes the all-pairs pyramid
    # (reference default path, same numerics) unless the volume would outgrow
    # HBM for the frame geometry, then switches to "on_demand" (the
    # alt_cuda_corr equivalent — O(H·W·D) memory; VFT_RAFT_ON_DEMAND_IMPL=
    # matmul opts into the MXU volume remat once a committed 1080p TPU sweep
    # justifies it — models/raft.py resolve_corr_impl, ADVICE r5); explicit
    # "volume"/"volume_gather"/"on_demand"/"on_demand_matmul" force a path.
    raft_corr: str = "auto"
    # PWC cost volume: "auto" (default) picks the Pallas tile kernel where its
    # VMEM gates admit the shape (measured faster at production shapes,
    # bench_details.json pwc_pairs_*) and the fused XLA formulation elsewhere;
    # "xla"/"pallas" force a path (ops/pallas_corr).
    pwc_corr: str = "auto"
    # PWC backward-warp lowering: "gather" (take_along_axis corner taps) or
    # "onehot" (MXU selector matmuls, ops/warp.bilinear_sample_onehot —
    # covers the levels the Mosaic compile cliff bars from the fused
    # kernel). "auto" (default) defers to VFT_WARP_IMPL, unset -> gather,
    # pending the TPU decision sweep (tools/profile_warp_corr.py --forward).
    pwc_warp: str = "auto"
    # I3D flow sandwich: decode the PWC pairs in sub-batches of this size
    # under lax.map to bound peak decoder memory (the 64-pair stack at the
    # sample videos' 256×341 geometry exceeds HBM in one piece). None = auto
    # (chunk to 16 when pairs × flow-grid area is large); 0 = never chunk.
    flow_pair_chunk: Optional[int] = None
    # Flow models: replicate-pad frames up to multiples of this size before the
    # device step (flow unpadded after), so a mixed-resolution corpus compiles
    # one program per BUCKET instead of one per distinct video geometry (tunnel
    # compiles cost 20-100s each). Numerics caveat: like the reference's own /8
    # pad, edge padding perturbs flow near borders — parity runs leave it off.
    shape_bucket: Optional[int] = None
    # --extraction_fps resampling backend: "auto" re-encodes through ffmpeg
    # when installed (exact reference parity, utils/utils.py:147-169) and
    # falls back to the native vf_fps-semantics sampler; "never" forces the
    # native sampler (deterministic across hosts with/without ffmpeg — the
    # frozen-golden tests pin this); "always" errors without ffmpeg.
    use_ffmpeg: str = "auto"
    # VGGish: apply the AudioSet PCA-whiten + uint8 quantize postprocessor
    # (vendored params). Off by default — the reference constructs the
    # postprocessor but never applies it (extract_vggish.py:57,104-116).
    vggish_postprocess: bool = False
    # Persistent XLA compilation cache directory (jax_compilation_cache_dir):
    # TPU compiles for large flow geometries cost 20-100 s each over the
    # tunnel; a shared cache directory lets reruns and restarts skip straight
    # to execution (compiles longer than ~1 s are cached). None = disabled.
    compilation_cache: Optional[str] = None
    # Flow extractors: as soon as a video's container is probed (its decoded
    # geometry is then known), warm the jitted device program for that
    # (bucketed) geometry in a background thread while the host decodes —
    # a mixed-resolution corpus overlaps its serial mid-run recompiles with
    # decode instead of stalling the mesh on each new geometry. Combine with
    # --shape_bucket to bound the geometry count and --compilation_cache to
    # persist the results across runs.
    precompile: bool = False
    # Overlap feature serialization with the next video's compute: .npy
    # writes and done-manifest records run on a bounded single-writer thread
    # (io/output.py AsyncOutputWriter) that preserves the atomic tmp+rename
    # and write-before-done ordering; write failures surface classified per
    # video (docs/performance.md). False = write inline in the video loop.
    async_writer: bool = True
    # jax.profiler trace directory; also enables the per-video stage report
    # (decode vs device_wait vs overlapped time). VFT_METRICS=1 enables the
    # report without tracing.
    profile_dir: Optional[str] = None
    # Telemetry directory (docs/observability.md): a structured span/event
    # journal (<dir>/events.jsonl) records every request and video lifecycle
    # (queued → popped → decode → dispatched → device → done/failed, plus
    # cache hits, stale flushes, autoscale resizes, breaker trips) with
    # monotonic timestamps, appended by a bounded single-writer thread that
    # NEVER blocks the hot path (a full queue drops the event and counts the
    # drop). Export to a Chrome/Perfetto trace with
    # `python -m video_features_tpu.obs.export <dir>/events.jsonl`. Works in
    # batch runs and the --serve daemon (which also serves healthz/metrics/
    # profile socket ops from the same subsystem). None = off (no journal;
    # the daemon's in-memory metrics registry stays on regardless).
    telemetry_dir: Optional[str] = None
    # TPU fp32 convs default to bf16 MXU passes; "highest" gives true-fp32
    # accumulation for the bit-parity path (None = XLA default).
    matmul_precision: Optional[str] = None
    # Host wire-format escape hatch (flow extractors): stage frame windows as
    # float32 on the host — the pre-uint8 behavior — instead of shipping the
    # decoded uint8 bytes and casting inside the jitted step. 4× the
    # host→device bytes and host staging churn for IDENTICAL output bytes
    # (the u8→fp32 cast is exact; pinned by tests/test_ingest.py); exists as
    # the A/B baseline for the bench uint8_ingest_flow scenario and as an
    # escape hatch if a backend ever mishandles uint8 transfers.
    float32_wire: bool = False
    # Device-side resize (resnet50): ship RAW decoded frames and run the
    # smaller-edge bilinear resize + center crop inside the jitted step
    # (jax.image.resize) instead of per-frame host PIL — removes the largest
    # remaining host CPU cost per frame (ROADMAP item 4). NOT bit-identical
    # to the PIL host path (PIL's uint8 rounding vs XLA's float bilinear —
    # tolerance pinned in tests/test_ingest.py, documented in
    # docs/performance.md), so off by default per the ops/image.py parity
    # contract. Packed runs queue slots per decoded geometry (like i3d);
    # other feature types route the same idea through --device_preproc.
    device_resize: bool = False
    # Device-side preprocessing everywhere (generalizes --device_resize
    # from resnet50 to every feature type — ROADMAP item 4 completed): each
    # model ships its RAWEST wire format and runs the remaining host-side
    # transform as a fused prologue op inside the jitted step, so the
    # CPU-bound decode pool stops paying per-frame PIL/numpy costs.
    # Per model: resnet50 behaves exactly as --device_resize; i3d moves the
    # PIL edge resize on device (ops/image.device_edge_resize_hwc,
    # tolerance-gated like resnet's — fingerprints); raft/pwc ship RAW
    # decoded frames and replicate-pad to the /8 (or bucket) geometry on
    # device (models/raft.device_pad_to_shape on the uint8 wire — BYTE-exact
    # vs the host pad, execution-only); vggish ships raw PCM slabs and runs
    # the log-mel STFT/mel pipeline on device (ops/audio.log_mel_examples,
    # ≤2e-5 vs the numpy oracle — fingerprints); r21d's transform has been
    # fully device-fused since its port (the flag is a documented no-op
    # there). The bench `device_preproc` scenario records the decode-seconds
    # vs host→device-bytes trade; parity pins live in
    # tests/test_device_preproc.py.
    device_preproc: bool = False
    # Dense-flow D2H transfer dtype (raft/pwc extractors): the device casts
    # the flow before the host fetch and the host upcasts back to fp32 (.npy
    # outputs stay fp32). "float16" halves the fetched bytes at ≤0.01 px
    # quantization for |flow| ≤ 32; "bfloat16" at ≤0.16 px for |flow| ≈ 20.
    # "float32" (default) is bit-parity.
    transfer_dtype: str = "float32"
    # --- reliability knobs (docs/reliability.md) ---
    # Bounded retry for transient per-video failures (FfmpegError, DeviceError,
    # OutputError): number of RE-attempts after the first failure. Permanent
    # classes (DecodeError, VideoTimeoutError) never retry.
    retries: int = 2
    # First backoff delay in seconds; doubles per retry (capped at 30 s).
    retry_backoff: float = 0.5
    # Per-video watchdog: cancel and classify any video whose attempt exceeds
    # this many seconds (a wedged cv2 read or ffmpeg child must not stall the
    # fleet). None (default) = no timeout.
    video_timeout: Optional[float] = None
    # Circuit breaker: abort the run (exit code 2) once MORE THAN this many
    # videos have terminally failed — a job drowning in errors usually has a
    # systemic cause (bad mount, dead device) and burning the rest of the
    # corpus hides it. None = never abort. 0 = abort on the first failure.
    max_failures: Optional[int] = None
    # Reprocess exactly the videos recorded in the failure manifest
    # (.failed_manifest.jsonl beside the done-manifest) instead of the given
    # video list; retried entries are pruned and re-append only if they fail
    # again.
    retry_failed: bool = False
    # --- serving knobs (--serve daemon, docs/serving.md) ---
    # Run the always-on extraction service instead of the batch loop: watch
    # --spool_dir for per-tenant request files (plus a local-socket API),
    # schedule videos weighted-fair + deadline across tenants, and keep the
    # corpus packer's slot queues warm across requests (serve/daemon.py).
    serve: bool = False
    # Watched request directory (required with --serve): tenants drop
    # <request_id>.json files here; <spool_dir>/tenants.json holds per-tenant
    # weights/quotas (SIGHUP re-reads it).
    spool_dir: Optional[str] = None
    # Unix socket for the submit/status/stats/drain/reload API. None =
    # <spool_dir>/control.sock; "none" disables the socket listener.
    socket_path: Optional[str] = None
    # Where per-request .result.json completion records land. None =
    # <spool_dir>/results.
    notify_dir: Optional[str] = None
    # Default per-tenant pending-video quota: a submission that would push a
    # tenant past it is rejected at admission (tenants.json overrides).
    tenant_quota: int = 64
    # Per-tenant circuit breaker: once MORE THAN this many of a tenant's
    # videos have terminally failed, its queued videos fail fast and new
    # submissions are rejected until a SIGHUP reload — other tenants keep
    # flowing. None = never trip (the batch --max_failures analogue, scoped
    # to one tenant instead of the run).
    tenant_max_failures: Optional[int] = None
    # Idle flush latency: with the ingest queue empty and partial slot
    # queues pending, wait this long for more work before pad-flushing so
    # in-flight requests complete (latency over occupancy when there is
    # nothing to pack with).
    idle_flush_sec: float = 0.5
    # Spool directory poll interval.
    spool_poll_sec: float = 0.25
    # Co-resident serving models (--serve only): additional feature types to
    # serve from the SAME daemon and mesh. --feature_type stays the default
    # for requests that omit "feature_type"; each co-loaded model's
    # extractor is constructed lazily on first traffic with its own
    # reference stack/step/stream defaults (explicit per-model overrides
    # apply only to the primary), its own output subtree and manifests, and
    # its own cache fingerprint — while sharing the mesh, the staging ring,
    # the decode pool, the output writer, and the packer's interleaved
    # (model, geometry) dispatch (docs/serving.md). None/empty = the
    # single-model daemon.
    serve_models: Optional[Tuple[str, ...]] = None
    # --- serving durability (serve/wal.py, docs/serving.md "Crash
    # recovery") ---
    # Write-ahead admission log path. None = <spool_dir>/admission.wal
    # (durable admission on by default whenever there is a spool to serve);
    # "none" disables the WAL entirely — an acknowledged submit then lives
    # only in process memory and dies with the daemon.
    wal_path: Optional[str] = None
    # WAL group-commit window: admissions acknowledged within this many
    # seconds of the last fsync share one (batched) fsync. 0 (default) =
    # fsync every appended record before acknowledging — strongest
    # durability; set ~0.05 under high submit rates (the bench scenario
    # budget assumes batching on).
    wal_fsync_sec: float = 0.0
    # Replay unresolved WAL admissions at startup (--no_recover disables):
    # each entry is deduped against published result records and per-model
    # done-manifests, survivors re-enter the scheduler with their original
    # admission seqs and deadlines. With recovery off, unresolved entries
    # are resolved failed and dropped (loudly).
    recover: bool = True
    # healthz `stale` threshold: the op flags the daemon once the serving
    # loop has not stepped for this many seconds (wedged, or a legitimately
    # long first-traffic compile — both mean "not serving right now").
    healthz_stale_sec: float = 10.0
    # Keep claimed <id>.json.accepted spool files after their request's
    # result record publishes (debugging aid); default removes them — the
    # result record is the durable trace.
    spool_retain: bool = False
    # Hung-step watchdog: when the serving loop has not stepped for this
    # many seconds, fail the in-flight videos transiently so they requeue
    # (slot attribution charges no tenant's breaker) instead of waiting out
    # a stalled device step forever. None (default) = off. Set it well above
    # the worst expected compile time.
    step_watchdog_sec: Optional[float] = None
    # --- feature cache (docs/caching.md) ---
    # Content-addressed feature cache directory: sha256(container bytes) ×
    # model-config fingerprint → finished feature dict. A hit skips decode
    # AND the device entirely (outputs + done-manifest entry still written,
    # so --resume composes deterministically); both the batch loops and the
    # --serve daemon consult it before decode, and the daemon additionally
    # coalesces in-flight identical requests (cache/ package). None = off.
    cache_dir: Optional[str] = None
    # Byte cap for the cache directory: publishing past it evicts the
    # least-recently-hit entries (a hit refreshes recency). None = unbounded.
    cache_max_bytes: Optional[int] = None
    # I3D geometry: smaller-edge resize target and center-crop size. The
    # reference hard-codes 256/224 (extract_i3d.py:25 + transforms); these stay
    # the parity defaults. Overriding shrinks the SAME jitted two-stream
    # programs for CI/dry runs (the driver's dryrun_multichip runs the real
    # sandwich at 96/64 so it fits a 1-core host's wall-clock budget).
    i3d_pre_crop_size: int = 256
    i3d_crop_size: int = 224

    def validate(self) -> None:
        """Mirror the reference ``sanity_check`` (``utils/utils.py:88-105``)."""
        import os

        if self.feature_type not in FEATURE_TYPES:
            raise ValueError(
                f"unknown feature_type {self.feature_type!r}; expected one of {FEATURE_TYPES}"
            )
        if self.on_extraction not in ON_EXTRACTION:
            raise ValueError(f"on_extraction must be one of {ON_EXTRACTION}")
        if self.flow_type not in FLOW_TYPES:
            raise ValueError(f"flow_type must be one of {FLOW_TYPES}")
        if self.streams is not None:
            bad = set(self.streams) - set(STREAMS)
            if bad:
                raise ValueError(f"unknown streams {sorted(bad)}; expected subset of {STREAMS}")
        if os.path.relpath(self.output_path) == os.path.relpath(self.tmp_path):
            raise ValueError("The same path for out & tmp")
        if self.feature_type == "r21d_rgb" and self.extraction_fps is not None:
            raise ValueError(
                "r21d_rgb only supports extraction at the original fps; remove extraction_fps"
            )
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if self.clips_per_batch < 1:
            raise ValueError("clips_per_batch must be >= 1")
        if self.flow_dtype not in ("float32", "bfloat16"):
            raise ValueError("flow_dtype must be float32|bfloat16")
        if self.raft_corr not in ("auto", "volume", "volume_gather", "on_demand",
                                  "on_demand_matmul"):
            raise ValueError(
                "raft_corr must be auto|volume|volume_gather|on_demand|on_demand_matmul")
        if self.pwc_corr not in ("auto", "xla", "pallas"):
            raise ValueError("pwc_corr must be auto|xla|pallas")
        if self.pwc_warp not in ("auto", "gather", "onehot"):
            raise ValueError("pwc_warp must be auto|gather|onehot")
        if self.matmul_precision not in (None, "default", "high", "highest"):
            raise ValueError("matmul_precision must be default|high|highest")
        if self.decode_workers < 0:
            raise ValueError("decode_workers must be >= 1, or 0 for auto "
                             "(start small; the --serve daemon resizes the "
                             "pool live from the measured decode-starvation "
                             "signal)")
        if self.decode_segments < 0:
            raise ValueError("decode_segments must be >= 2 to cap the split, "
                             "1 to disable, or 0 for auto")
        if self.segment_seek not in ("auto", "ffmpeg", "cv2"):
            raise ValueError("segment_seek must be auto|ffmpeg|cv2")
        if self.pack_buckets < 1:
            raise ValueError("pack_buckets must be >= 1")
        if self.pack_flush_age < 0:
            raise ValueError("pack_flush_age must be >= 0 (0 = flush only at "
                             "corpus end)")
        if self.pages_in_flight < 1:
            raise ValueError("pages_in_flight must be >= 1 (2 = the "
                             "double-buffered default)")
        if self.retries < 0:
            raise ValueError("retries must be >= 0")
        if self.retry_backoff < 0:
            raise ValueError("retry_backoff must be >= 0")
        if self.video_timeout is not None and self.video_timeout <= 0:
            raise ValueError("video_timeout must be > 0 seconds (omit to disable)")
        if self.max_failures is not None and self.max_failures < 0:
            raise ValueError("max_failures must be >= 0 (0 = abort on first failure)")
        if self.flow_pair_chunk is not None and self.flow_pair_chunk < 0:
            raise ValueError("flow_pair_chunk must be >= 0 (0 = never chunk)")
        if self.use_ffmpeg not in ("auto", "always", "never"):
            raise ValueError("use_ffmpeg must be auto|always|never")
        if self.shape_bucket is not None and (
            self.shape_bucket < 8 or self.shape_bucket % 8
        ):
            raise ValueError("shape_bucket must be a multiple of 8 (RAFT /8 contract)")
        if self.transfer_dtype not in ("float32", "float16", "bfloat16"):
            raise ValueError("transfer_dtype must be float32|float16|bfloat16")
        if self.i3d_crop_size < 32:
            raise ValueError("i3d_crop_size must be >= 32 (five /2 stages)")
        if self.i3d_crop_size % 32:
            # five stride-2 stages: a non-multiple-of-32 crop produces odd
            # intermediate dims (implementation-defined pooling geometry).
            # Legal — 112 is a common I3D crop — so warn instead of rejecting
            # (ADVICE r5); README documents that features may drift across
            # backends at such sizes.
            import sys

            print(f"warning: i3d_crop_size {self.i3d_crop_size} is not a "
                  "multiple of 32; five stride-2 stages produce odd "
                  "intermediate dims (implementation-defined pooling "
                  "geometry) — features may differ across backends",
                  file=sys.stderr)
        if self.i3d_pre_crop_size < self.i3d_crop_size:
            raise ValueError("i3d_pre_crop_size must be >= i3d_crop_size")
        if self.tenant_quota < 1:
            raise ValueError("tenant_quota must be >= 1")
        if self.tenant_max_failures is not None and self.tenant_max_failures < 0:
            raise ValueError("tenant_max_failures must be >= 0 (0 = trip on "
                             "the first failure)")
        if self.idle_flush_sec < 0:
            raise ValueError("idle_flush_sec must be >= 0")
        if self.cache_max_bytes is not None and self.cache_max_bytes < 1:
            raise ValueError("cache_max_bytes must be >= 1 (omit for an "
                             "unbounded cache)")
        if self.cache_max_bytes is not None and self.cache_dir is None:
            raise ValueError("cache_max_bytes needs --cache_dir (it caps the "
                             "cache directory)")
        if self.spool_poll_sec <= 0:
            raise ValueError("spool_poll_sec must be > 0")
        if self.wal_fsync_sec < 0:
            raise ValueError("wal_fsync_sec must be >= 0 (0 = fsync every "
                             "record)")
        if self.healthz_stale_sec <= 0:
            raise ValueError("healthz_stale_sec must be > 0")
        if self.step_watchdog_sec is not None and self.step_watchdog_sec <= 0:
            raise ValueError("step_watchdog_sec must be > 0 (omit to disable "
                             "the watchdog)")
        if self.serve_models:
            if not self.serve:
                raise ValueError("--serve_models co-loads models into the "
                                 "serving daemon; it needs --serve")
            bad = set(self.serve_models) - set(FEATURE_TYPES)
            if bad:
                raise ValueError(f"unknown serve_models {sorted(bad)}; "
                                 f"expected a subset of {FEATURE_TYPES}")
        if self.serve:
            if not self.spool_dir:
                raise ValueError("--serve requires --spool_dir (the watched "
                                 "request directory)")
            if self.on_extraction != "save_numpy":
                raise ValueError("--serve requires --on_extraction "
                                 "save_numpy: the service's product is saved "
                                 "features plus per-request result records")
            if self.retry_failed:
                raise ValueError("--retry_failed is a batch-run flag; the "
                                 "--serve daemon re-enqueues transient "
                                 "failures through its scheduler instead")
            if self.max_failures is not None:
                raise ValueError("--max_failures aborts the whole RUN — a "
                                 "policy that crosses tenant boundaries; "
                                 "use --tenant_max_failures, the per-tenant "
                                 "breaker, with --serve")
            if self.show_pred:
                raise ValueError("--show_pred is batch-only (per-batch "
                                 "prints assume video order; no packing "
                                 "path)")

    def replace(self, **kw) -> "ExtractionConfig":
        return dataclasses.replace(self, **kw)


# Per-model defaults; reference keeps these as module constants
# (extract_i3d.py:21-29, extract_r21d.py:15-20, extract_resnet50.py:17-20).
MODEL_DEFAULTS = {
    "i3d": dict(stack_size=64, step_size=64),
    "r21d_rgb": dict(stack_size=16, step_size=16),
    "resnet50": dict(),
    "raft": dict(),
    "pwc": dict(),
    "vggish": dict(),
}


def resolve_model_defaults(cfg: ExtractionConfig) -> ExtractionConfig:
    """Fill in per-model stack/step defaults when the user did not override them."""
    defaults = MODEL_DEFAULTS.get(cfg.feature_type, {})
    updates = {k: v for k, v in defaults.items() if getattr(cfg, k) is None}
    streams = cfg.streams
    if cfg.feature_type == "i3d" and streams is None:
        streams = ("rgb", "flow")
    if streams is not None:
        updates["streams"] = tuple(streams)
    return cfg.replace(**updates) if updates else cfg


def config_from_namespace(ns) -> ExtractionConfig:
    """Build an ExtractionConfig from an argparse namespace using reference flag names."""
    fields = {f.name for f in dataclasses.fields(ExtractionConfig)}
    kw = {}
    for key, value in vars(ns).items():
        if key not in fields:
            continue
        if key in ("video_paths", "streams", "serve_models") and value is not None:
            value = tuple(value)
        kw[key] = value
    if kw.get("video_paths") is None:
        kw["video_paths"] = ()
    cfg = ExtractionConfig(**kw)
    cfg = resolve_model_defaults(cfg)
    cfg.validate()
    return cfg
