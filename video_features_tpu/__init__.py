"""tpu-video-features: a TPU-native (JAX/XLA/Flax/Pallas) video feature extraction framework.

Capabilities mirror the reference toolkit `yhZhai/video_features` (see SURVEY.md):
given a list of videos, extract per-clip / per-frame / per-audio-window features from one
of six pretrained networks — I3D (rgb+flow), R(2+1)D-18, ResNet-50, RAFT, PWC-Net,
VGGish — and print them or save them as ``.npy``.

Architecture (TPU-first, not a port):

- Video decode, PIL-semantics resizing and DSP run on the CPU host
  (``video_features_tpu.io``); fixed-shape clip batches stream into HBM with async
  prefetch (``video_features_tpu.parallel.prefetch``).
- All model forwards are Flax modules compiled once under ``jax.jit`` with static
  shapes (``video_features_tpu.models``); hot custom ops (PWC 9x9 cost volume, RAFT
  correlation lookup) are Pallas kernels or pure-XLA formulations
  (``video_features_tpu.ops``).
- Parallelism is expressed over a ``jax.sharding.Mesh``: data-parallel clip sharding
  over ICI, optional tensor-parallel channel sharding, and temporal context
  parallelism with ``ppermute`` halo exchange for long videos
  (``video_features_tpu.parallel``).
"""

__version__ = "0.1.0"

from .config import FEATURE_TYPES as SUPPORTED_FEATURE_TYPES  # noqa: E402
