"""Class-label maps and top-k prediction printing (``--show_pred``).

Reproduces ``show_predictions_on_dataset`` (``utils/utils.py:15-42``): top-5 classes
with logit and softmax scores, one block per batch row. Label lists are bundled as
JSON data (Kinetics-400 / ImageNet-1k class names — public dataset metadata).
"""

from __future__ import annotations

import json
import os
from functools import lru_cache
from typing import List

import numpy as np

_DATA_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "data")
_FILES = {
    "kinetics": "kinetics400_labels.json",
    "imagenet": "imagenet1k_labels.json",
}


@lru_cache(maxsize=None)
def class_names(dataset: str) -> List[str]:
    if dataset not in _FILES:
        raise NotImplementedError(f"no label map for dataset {dataset!r}")
    with open(os.path.join(_DATA_DIR, _FILES[dataset])) as f:
        return json.load(f)


def show_predictions_on_dataset(logits: np.ndarray, dataset: str, k: int = 5) -> None:
    """Print top-k ``<logit> <softmax> <class>`` lines per row (reference format)."""
    logits = np.asarray(logits, np.float64)
    names = class_names(dataset)
    # row-wise softmax
    z = logits - logits.max(axis=-1, keepdims=True)
    softmax = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
    top_idx = np.argsort(-softmax, axis=-1)[:, :k]
    for row, idx in enumerate(top_idx):
        for i in idx:
            print(f"{logits[row, i]:.3f} {softmax[row, i]:.3f} {names[i]}")
        print()
