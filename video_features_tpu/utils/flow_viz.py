"""Middlebury color-wheel optical-flow visualization (numpy).

Behavioral spec: ``/root/reference/models/raft/raft_src/utils/flow_viz.py`` (duplicated
byte-identically under pwc_src — SURVEY.md §2.1 #20): a 55-entry RY/YG/GC/CB/BM/MR
color wheel, flow angle selects the hue by linear interpolation, radius saturates
toward the wheel color, out-of-range radii darken by 0.75. Used by ``--show_pred`` for
raft/pwc and available as a public util.
"""

from __future__ import annotations

import numpy as np

_SEGMENTS = (  # (count, base channel pattern) — RY, YG, GC, CB, BM, MR
    (15, (255, "up", 0)),
    (6, ("down", 255, 0)),
    (4, (0, 255, "up")),
    (11, (0, "down", 255)),
    (13, ("up", 0, 255)),
    (6, (255, 0, "down")),
)


def make_colorwheel() -> np.ndarray:
    """(55, 3) uint-valued float RGB color wheel."""
    total = sum(n for n, _ in _SEGMENTS)
    wheel = np.zeros((total, 3), np.float64)
    row = 0
    for count, pattern in _SEGMENTS:
        ramp = np.floor(255 * np.arange(count) / count)
        for ch, spec in enumerate(pattern):
            if spec == "up":
                wheel[row : row + count, ch] = ramp
            elif spec == "down":
                wheel[row : row + count, ch] = 255 - ramp
            else:
                wheel[row : row + count, ch] = spec
        row += count
    return wheel


def flow_to_image(flow_uv: np.ndarray, clip_flow: float | None = None,
                  convert_to_bgr: bool = False) -> np.ndarray:
    """(H, W, 2) flow → (H, W, 3) uint8 color image.

    Flow is normalized by its maximum radius (plus epsilon) before coloring, as the
    reference does, so the visualization is per-frame relative.
    """
    assert flow_uv.ndim == 3 and flow_uv.shape[2] == 2, flow_uv.shape
    if clip_flow is not None:
        flow_uv = np.clip(flow_uv, 0, clip_flow)
    u = flow_uv[:, :, 0].astype(np.float64)
    v = flow_uv[:, :, 1].astype(np.float64)
    rad = np.sqrt(u * u + v * v)
    rad_max = rad.max() if rad.size else 0.0
    eps = 1e-5
    u = u / (rad_max + eps)
    v = v / (rad_max + eps)

    wheel = make_colorwheel()
    ncols = wheel.shape[0]
    rad = np.sqrt(u * u + v * v)
    a = np.arctan2(-v, -u) / np.pi  # [-1, 1]
    fk = (a + 1) / 2 * (ncols - 1)  # map to wheel index space
    k0 = np.floor(fk).astype(np.int32)
    k1 = (k0 + 1) % ncols
    f = fk - k0

    img = np.zeros((*u.shape, 3), np.uint8)
    for ch in range(3):
        col0 = wheel[k0, ch] / 255.0
        col1 = wheel[k1, ch] / 255.0
        col = (1 - f) * col0 + f * col1
        small = rad <= 1
        col[small] = 1 - rad[small] * (1 - col[small])  # saturate toward white center
        col[~small] = col[~small] * 0.75  # out of range: darken
        img[:, :, 2 - ch if convert_to_bgr else ch] = np.floor(255 * col)
    return img
