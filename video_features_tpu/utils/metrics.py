"""Per-stage timing and profiler hooks (SURVEY.md §5: the reference has tqdm
bars and nothing else; diagnosing whether decode, transfer, or compute bounds a
run is the whole perf game on TPU).

Opt-in: ``--profile_dir DIR`` wraps the run in a ``jax.profiler`` trace (view
with TensorBoard/XProf) and enables the per-video stage report; ``VFT_METRICS=1``
enables the report alone.

Stage semantics (async device dispatch makes naive timing lie):
- ``decode``: host time blocked pulling frames from the decoder/transform
  iterator — real decode-bound time.
- ``device_wait``: host time blocked on device results (``np.asarray`` /
  ``block_until_ready``) — compute-bound time NOT hidden by prefetch.
- ``transfer``: host time staging batches onto the mesh (``device_put``
  dispatch plus any staging-ring wait for a pending host→device copy to
  finish before its buffer is rewritten), with the staged payload bytes
  attached — the report derives host→device MB/s from them, so a run can be
  told apart as decode-bound vs transfer-bound (docs/performance.md ingest
  fast path).
- ``wall``: end-to-end per video. ``wall − decode − device_wait`` ≈ host
  stacking/bookkeeping overlapped with device work.
"""

from __future__ import annotations

import collections
import contextlib
import os
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, Optional


def metrics_enabled(profile_dir=None) -> bool:
    return bool(profile_dir) or os.environ.get("VFT_METRICS") == "1"


# decode-starvation heuristic (--pack_corpus): warn when the packer burned a
# lot of padding (occupancy below the threshold) while the run spent most of
# its wall blocked pulling frames — the decode pool, not the mesh, was the
# ceiling (ROADMAP item 4). Thresholds are deliberately loose: this is a
# "look at --decode_workers" nudge, not an SLO.
STARVED_OCCUPANCY = 0.8
STARVED_DECODE_FRACTION = 0.4
STARVED_TRANSFER_FRACTION = 0.4


def decode_starvation_warning(occupancy: float, decode_seconds: float,
                              wall: float, stale_flushes: int = 0,
                              transfer_seconds: float = 0.0,
                              ) -> Optional[str]:
    """Message when a packed run's padding is decode- (or transfer-)
    starvation, else None.

    ``occupancy``: real clips / dispatched device slots for the whole corpus.
    ``decode_seconds``: host time blocked on the frame stream ('decode' stage).
    ``wall``: packed-run wall-clock. ``stale_flushes``: anti-starvation
    flushes taken (each one trades padding for latency, so a high count with
    low occupancy strengthens the signal — it is reported, not gated on).
    ``transfer_seconds``: host time blocked staging batches onto the mesh
    ('transfer' stage) — when the padding is burned waiting on the host→device
    pipe rather than on decode, raising --decode_workers would do nothing, so
    the message names the right lever instead.
    """
    if wall <= 0 or occupancy >= STARVED_OCCUPANCY:
        return None
    decode_fraction = decode_seconds / wall
    flushes = (f" and {stale_flushes} anti-starvation flush(es)"
               if stale_flushes else "")
    if decode_fraction >= STARVED_DECODE_FRACTION:
        return (f"warning: packing occupancy {occupancy:.1%} with "
                f"{decode_fraction:.0%} of wall blocked on decode"
                + flushes
                + " — the decode pool is starving the mesh; raise "
                "--decode_workers (docs/performance.md)")
    transfer_fraction = transfer_seconds / wall
    if transfer_fraction >= STARVED_TRANSFER_FRACTION:
        return (f"warning: packing occupancy {occupancy:.1%} with "
                f"{transfer_fraction:.0%} of wall blocked on host→device "
                "transfer" + flushes
                + " — the transfer pipe, not decode, is starving the mesh; "
                "check the transfer-stage MB/s and drop --float32_wire if "
                "set (docs/performance.md)")
    return None


class StageClock:
    """Accumulates seconds per named stage.

    Thread-safe: increments arrive from the run-loop/daemon thread
    (``timed_iter``, ``stage``), the staging ring's commit hooks, and the
    async writer's reap concurrently, so every mutation holds ``_lock`` — a
    lost ``+=`` would silently skew the report and the starvation heuristic.
    The accumulator dicts are declared under the ``clock`` lock in vftlint's
    ``GUARDED_BY`` map (docs/static-analysis.md), which mechanizes exactly
    that bug class within this module; the daemon's cross-module
    ``clock.seconds.get`` peeks are deliberate dirty reads of defaultdict
    floats, documented at their sites.

    ``registry``/``labels``: an optional :class:`..obs.MetricsRegistry` that
    every accumulation is mirrored into (``stage_seconds_total``,
    ``stage_bytes_total``, ``stage_units_total``, labeled ``stage=<name>``
    plus ``labels``) — the serving daemon's long-lived clock feeds the
    ``metrics`` socket op and the Prometheus exposition through this seam
    (docs/observability.md).
    """

    def __init__(self, registry=None, labels: Optional[Dict] = None):
        self.seconds: Dict[str, float] = collections.defaultdict(float)
        self.counts: Dict[str, int] = collections.defaultdict(int)
        # dimensionless counters (no time attached), e.g. the packed loop's
        # dispatched device slots vs real clips (packing occupancy)
        self.units: Dict[str, int] = collections.defaultdict(int)
        # payload bytes attributed per stage (timed_iter bytes_of): the report
        # derives stage throughput (MB/s) from bytes/seconds — decode MB/s is
        # the ingest-rate signal the starvation heuristic keys on
        self.bytes: Dict[str, int] = collections.defaultdict(int)
        self._lock = threading.Lock()
        self._registry = registry
        self._labels = dict(labels) if labels else {}

    def _feed(self, metric: str, stage: str, value) -> None:
        if self._registry is not None:
            self._registry.inc(metric, value, stage=stage, **self._labels)

    def add_units(self, name: str, n: int = 1) -> None:
        """Accumulate a dimensionless counter reported alongside the stages."""
        with self._lock:
            self.units[name] += n
        self._feed("stage_units_total", name, n)

    def add_seconds(self, name: str, seconds: float) -> None:
        """Attribute externally-measured blocked time to a stage (e.g. the
        staging ring's wait for a pending host→device copy)."""
        with self._lock:
            self.seconds[name] += seconds
        self._feed("stage_seconds_total", name, seconds)

    def add_bytes(self, name: str, n: int) -> None:
        """Attribute payload bytes to a stage measured via :meth:`stage`
        (timed_iter's ``bytes_of`` does this for iterator stages)."""
        with self._lock:
            self.bytes[name] += n
        self._feed("stage_bytes_total", name, n)

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.seconds[name] += dt
                self.counts[name] += 1
            self._feed("stage_seconds_total", name, dt)

    # registry mirroring from timed_iter is batched: the iterator runs per
    # FRAME on the decode hot path, and a per-item registry inc (label-key
    # build + the registry lock, contended against the stats API thread)
    # would tax exactly the path telemetry promises not to. The local dicts
    # stay per-item-exact under _lock; the mirror flushes every N items and
    # on generator exit (StopIteration, abandonment, GC close — the finally
    # runs for all of them), so the registry lags by at most one flush.
    _FEED_EVERY = 64

    def timed_iter(self, it: Iterable, name: str,
                   bytes_of: Optional[Callable] = None) -> Iterator:
        """Wrap an iterator, attributing time blocked in ``next()`` to ``name``.

        ``bytes_of(item)``, when given, accounts each item's payload size so
        the report can state the stage's throughput (e.g. decoded MB/s).
        """
        it = iter(it)
        pending_s = 0.0
        pending_b = 0
        pending_n = 0
        try:
            while True:
                t0 = time.perf_counter()
                try:
                    item = next(it)
                except StopIteration:
                    dt = time.perf_counter() - t0
                    with self._lock:
                        self.seconds[name] += dt
                    pending_s += dt
                    return
                dt = time.perf_counter() - t0
                nbytes = bytes_of(item) if bytes_of is not None else 0
                with self._lock:
                    self.seconds[name] += dt
                    self.counts[name] += 1
                    if nbytes:
                        self.bytes[name] += nbytes
                pending_s += dt
                pending_b += nbytes
                pending_n += 1
                if pending_n >= self._FEED_EVERY:
                    self._feed("stage_seconds_total", name, pending_s)
                    if pending_b:
                        self._feed("stage_bytes_total", name, pending_b)
                    pending_s, pending_b, pending_n = 0.0, 0, 0
                yield item
        finally:
            if pending_s:
                self._feed("stage_seconds_total", name, pending_s)
            if pending_b:
                self._feed("stage_bytes_total", name, pending_b)

    def report(self, label: str, wall: float) -> str:
        with self._lock:
            seconds = dict(self.seconds)
            counts = dict(self.counts)
            nbytes = dict(self.bytes)
            units = dict(self.units)
        parts = [f"{label}: wall {wall:.2f}s"]
        for name in sorted(seconds):
            stage = f"{name} {seconds[name]:.2f}s/{counts.get(name, 0)}"
            if nbytes.get(name) and seconds[name] > 0:
                mbps = nbytes[name] / seconds[name] / 1e6
                stage += f" ({mbps:.1f} MB/s)"
            parts.append(stage)
        accounted = sum(seconds.values())
        parts.append(f"overlapped/other {max(wall - accounted, 0.0):.2f}s")
        for name in sorted(units):
            parts.append(f"{name}={units[name]}")
        if units.get("packed_slots"):
            # packing-occupancy stage: real clips per dispatched device slot
            occ = units["packed_clips"] / units["packed_slots"]
            parts.append(f"pack_occupancy {occ:.1%}")
        return " | ".join(parts)


@contextlib.contextmanager
def maybe_profiler(profile_dir=None):
    """``jax.profiler`` trace context when a directory is given, else no-op."""
    if not profile_dir:
        yield
        return
    import jax

    os.makedirs(profile_dir, exist_ok=True)
    with jax.profiler.trace(profile_dir):
        yield
