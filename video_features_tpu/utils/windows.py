"""Pure window/slice index math for clip and frame-pair pipelines.

The reference interleaves this arithmetic with its decode loops
(``utils/utils.py:76-85`` ``form_slices``; the I3D B+1-frame sliding window
``extract_i3d.py:188-219``; RAFT's carry-last-frame batching
``extract_raft.py:122-151``). Here it is pure index planning: given a frame count,
produce static index arrays up front. Static plans are what let the device side run
fixed-shape, jit-once batches instead of data-dependent Python loops.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np


def form_slices(size: int, stack_size: int, step_size: int) -> List[Tuple[int, int]]:
    """(start, end) index pairs of every *full* stack (reference ``utils/utils.py:76-85``).

    Trailing frames that don't fill a stack are dropped, matching the reference.
    """
    if stack_size <= 0 or step_size <= 0:
        raise ValueError("stack_size and step_size must be positive")
    slices = []
    full_stack_num = (size - stack_size) // step_size + 1
    for i in range(max(full_stack_num, 0)):
        start = i * step_size
        slices.append((start, start + stack_size))
    return slices


def slice_starts(size: int, stack_size: int, step_size: int) -> np.ndarray:
    """Start indices of every full stack as an int32 array (device-friendly plan)."""
    return np.asarray([s for s, _ in form_slices(size, stack_size, step_size)], np.int32)


def flow_stack_plan(num_frames: int, stack_size: int, step_size: int) -> np.ndarray:
    """Frame-window starts for flow-fed clip models (I3D).

    Each window covers ``stack_size + 1`` frames: B consecutive frame pairs give B flow
    maps, and the rgb stream uses the first B frames of the window so both streams stay
    temporally aligned (reference ``extract_i3d.py:144-156,207-213``: reads 65 frames,
    drops the last rgb frame, keeps ``stack[step_size:]`` as overlap).

    Returns start indices of shape (num_stacks,); window w covers frames
    ``[start, start + stack_size]`` inclusive.
    """
    return slice_starts(max(num_frames - 1, 0), stack_size, step_size)


def pair_batch_plan(num_frames: int, batch_size: int) -> List[Tuple[int, int]]:
    """(start, end) frame ranges for frame-pair (optical flow) batches.

    Reproduces RAFT/PWC batching semantics (``extract_raft.py:122-151``): the decoder
    accumulates ``batch_size + 1`` frames, computes flow between ``batch[:-1]`` and
    ``batch[1:]``, then carries the last frame into the next batch; a final partial
    batch runs if it holds at least one pair. Range (start, end) is inclusive of end;
    it yields ``end - start`` flow maps for pairs (start, start+1) ... (end-1, end).
    """
    if batch_size < 1:
        raise ValueError("batch_size must be >= 1")
    ranges = []
    start = 0
    while start + 1 <= num_frames - 1:
        end = min(start + batch_size, num_frames - 1)
        ranges.append((start, end))
        start = end
    return ranges


def frame_batch_plan(num_frames: int, batch_size: int) -> List[Tuple[int, int]]:
    """(start, end) half-open ranges for frame-wise models (ResNet-50).

    The reference flushes every ``batch_size`` frames and once more for the partial
    tail (``extract_resnet50.py:118-143``).
    """
    if batch_size < 1:
        raise ValueError("batch_size must be >= 1")
    return [(s, min(s + batch_size, num_frames)) for s in range(0, num_frames, batch_size)]


def timestamps_ms(starts: np.ndarray, stack_size: int, fps: float) -> np.ndarray:
    """Timestamp (ms) of the last decoded frame of each window.

    The reference logs ``cap.get(CAP_PROP_POS_MSEC)`` when a stack completes
    (``extract_i3d.py:215``); the last frame decoded for window ``start`` is index
    ``start + stack_size`` (the +1-th frame of the flow pair window). Under cv2 >= 4,
    ``POS_MSEC`` after reading frame k is ``k / fps * 1000`` (frame 0 → 0.0), so the
    completed-stack timestamp is ``(start + stack_size) / fps * 1000``. Prefer the
    decoder's actual per-frame positions when available (variable-fps containers);
    this helper is the constant-fps plan used for pre-decoded arrays.
    """
    starts = np.asarray(starts, np.float64)
    return (starts + stack_size) / float(fps) * 1000.0
