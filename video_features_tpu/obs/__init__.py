"""Observability subsystem: span journal, metrics registry, trace export.

The serving daemon (PRs 6-9) turned the batch pipeline into an always-on,
multi-tenant, multi-model system — but its observability stayed batch-shaped:
a per-run stage-clock line, an opt-in ``jax.profiler`` wrapper, and a
point-in-time ``stats`` snapshot. This package adds the durable record of
*what happened when* (docs/observability.md):

- :class:`SpanJournal` — structured lifecycle events (admitted → queued →
  popped → decode → dispatched → device → write → done/failed, plus cache
  hits, coalesces, stale flushes, autoscale resizes, breaker trips) appended
  as JSONL by a bounded single-writer thread. Drops are counted, the hot
  path never blocks — the ``AsyncOutputWriter`` discipline applied to
  telemetry.
- :class:`MetricsRegistry` — named counters/gauges and fixed-bucket
  :class:`Histogram`\\ s (queue-wait, end-to-end latency, decode/device/
  transfer seconds) labeled by tenant and model, with p50/p95/p99 summaries
  and a Prometheus text exposition.
- :mod:`.export` — a Chrome-trace/Perfetto converter for the journal
  (``python -m video_features_tpu.obs.export <events.jsonl>``).

Enable with ``--telemetry_dir DIR`` (batch runs and the ``--serve`` daemon;
the daemon additionally serves ``healthz``/``metrics``/``profile`` socket
ops and keeps the registry on regardless).
"""

from .journal import SpanJournal
from .metrics import DEFAULT_BUCKETS, Histogram, MetricsRegistry

__all__ = [
    "DEFAULT_BUCKETS",
    "Histogram",
    "MetricsRegistry",
    "SpanJournal",
]
