"""Span/event journal: a durable, non-blocking record of what happened when.

Every record is one JSON line::

    {"ts": 12345.678901, "event": "video_done", "model": "resnet50",
     "video": "/abs/a.mp4"}

``ts`` is ``time.monotonic()`` seconds — monotone within the process, immune
to wall-clock steps; the writer's first record (``journal_open``) carries the
``wall`` epoch anchor so exporters can map to wall time. Span events come in
``<name>_start`` / ``<name>_end`` pairs sharing a ``span`` id; the exporter
(:mod:`.export`) folds them into complete Chrome-trace slices.

Discipline (the ``AsyncOutputWriter`` idea applied to telemetry): producers
— the daemon loop, decode workers, the packer — call :meth:`SpanJournal.emit`
which does a single non-blocking queue put. A full queue DROPS the event and
counts the drop; the serving/extraction hot path never waits on telemetry
disk. One writer thread owns the file; a failing disk degrades to counted
``write_errors``, never an exception on a producer.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import queue
import sys
import threading
import time
from typing import Dict, Optional

DEFAULT_CAPACITY = 4096
JOURNAL_NAME = "events.jsonl"


class SpanJournal:
    """Bounded single-writer JSONL event journal (never blocks producers)."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY,
                 autostart: bool = True):
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self.path = path
        self._q: queue.Queue = queue.Queue(maxsize=max(capacity, 1))
        # guards the producer-side counters (emit is called from the daemon
        # loop, decode workers, and the output-writer reap concurrently)
        self._lock = threading.Lock()
        self.emitted = 0
        self.dropped = 0
        self._written = 0
        self._write_errors = 0
        self._spans = itertools.count(1)
        # the open record carries construction-time stamps, not writer-
        # thread start time: producers may emit before the thread is
        # scheduled, and the journal must still sort open-first by ts
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self._closed = False
        self._started = False
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="telemetry-journal")
        if autostart:
            self.start()

    # --- producer side (any thread) ------------------------------------------

    def emit(self, event: str, **fields) -> bool:
        """Append one event record; returns False when it was dropped.

        None-valued fields are omitted (callers pass optional context
        unconditionally). Values must be JSON-friendly scalars/strings —
        the writer serializes with ``default=str`` so a stray object
        degrades to its repr rather than killing the record.
        """
        if self._closed:
            return False
        rec: Dict[str, object] = {"ts": round(time.monotonic(), 6),
                                  "event": event}
        for key, value in fields.items():
            if value is not None:
                rec[key] = value
        try:
            self._q.put_nowait(rec)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            return False
        with self._lock:
            self.emitted += 1
        return True

    def begin(self, name: str, **fields) -> int:
        """Open a span: emits ``<name>_start`` and returns the span id to
        pass to :meth:`end`. For code whose control flow does not fit a
        ``with`` block (e.g. the decode worker's try/finally ladder)."""
        sid = next(self._spans)
        self.emit(f"{name}_start", span=sid, **fields)
        return sid

    def end(self, name: str, sid: int, **fields) -> None:
        self.emit(f"{name}_end", span=sid, **fields)

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Emit a ``<name>_start`` / ``<name>_end`` pair around the body,
        sharing a fresh ``span`` id — the exporter pairs them into one
        complete trace slice. Yields the span id."""
        sid = self.begin(name, **fields)
        try:
            yield sid
        finally:
            self.end(name, sid, **fields)

    # --- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._thread.start()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def written(self) -> int:
        """Records the writer thread has landed on disk."""
        return self._written

    def stats(self) -> Dict[str, object]:
        # emitted/dropped are lock-guarded producer counters (GUARDED_BY);
        # _written/_write_errors are the single-writer-thread counters whose
        # GIL-atomic monotone reads need no lock (SHARED_WRITES discipline)
        with self._lock:
            emitted, dropped = self.emitted, self.dropped
        return {
            "path": self.path,
            "emitted": emitted,
            "dropped": dropped,
            "written": self._written,
            "write_errors": self._write_errors,
            "closed": self._closed,
        }

    def close(self, wait: bool = True) -> None:
        """Stop accepting events; drain the queue and append the close
        record (cumulative emitted/dropped counts) before returning."""
        if self._closed:
            return
        self._closed = True
        if not self._started:
            self.start()  # someone must consume the backlog + sentinel
        self._q.put(None)
        if wait:
            self._thread.join()

    # --- writer thread --------------------------------------------------------

    def _open_file(self):
        try:
            return open(self.path, "a", buffering=1)  # line-buffered
        except OSError as e:
            print(f"warning: telemetry journal disabled "
                  f"(cannot open {self.path}): {e}", file=sys.stderr)
            return None

    def _drain(self) -> None:
        f = self._open_file()

        def write_rec(rec: dict) -> None:
            """One record to disk; a failing disk counts, never raises."""
            if f is None:
                self._write_errors += 1  # thread-shared-state: written only by this single writer thread; readers see a monotone int (GIL-atomic load)
                return
            try:
                f.write(json.dumps(rec, default=str) + "\n")
            except (OSError, ValueError) as e:
                self._write_errors += 1  # thread-shared-state: written only by this single writer thread; readers see a monotone int (GIL-atomic load)
                if self._write_errors == 1:
                    print(f"warning: telemetry journal write failed "
                          f"({self.path}): {e}", file=sys.stderr)
                return
            self._written += 1  # thread-shared-state: written only by this single writer thread; readers see a monotone int (GIL-atomic load)

        write_rec({"ts": round(self._t0_mono, 6), "event": "journal_open",
                   "wall": round(self._t0_wall, 6), "pid": os.getpid()})
        while True:
            item = self._q.get()
            if item is None:
                break
            write_rec(item)
        with self._lock:
            emitted, dropped = self.emitted, self.dropped
        write_rec({"ts": round(time.monotonic(), 6), "event": "journal_close",
                   "emitted": emitted, "dropped": dropped})
        if f is not None:
            try:
                f.close()
            except OSError:
                pass
