"""Metrics registry: named counters, gauges, and fixed-bucket histograms.

The serving daemon's point-in-time ``stats`` op can say what the queues look
like *now*; it cannot answer "why was tenant B's p99 bad at 14:00?". The
registry accumulates the distributions that question needs — queue-wait,
end-to-end latency, per-video decode/transfer seconds, per-batch device
seconds — labeled by tenant and model, with p50/p95/p99 summaries and a
Prometheus text exposition (the ``metrics`` socket op) for external scrapers.

Histograms are fixed-bucket (Prometheus ``le`` semantics: bucket *i* counts
values ``<= bounds[i]``, one overflow bucket past the last bound), so an
observation is O(log buckets) and a snapshot is race-free arithmetic over
monotone counters. Quantiles interpolate linearly inside the crossing bucket
— exact at bucket boundaries, bounded by bucket width in between; the
overflow bucket reports the last bound (the registry cannot know better).

Thread posture: one lock covers all mutation and snapshotting. Producers are
the daemon loop, the scheduler (ingest threads submit), the stage clock, and
the packer; consumers are the socket API thread's ``stats``/``metrics`` ops.
The series dicts are declared in vftlint's ``GUARDED_BY`` map under the
``registry`` lock (docs/static-analysis.md), so an off-lock touch — or
iterating them without snapshotting first — fails lint, not production.
"""

from __future__ import annotations

import bisect
import threading
from typing import Dict, List, Optional, Tuple

# Latency-shaped default bounds (seconds): sub-ms decode waits through
# multi-minute flow videos. Shared by every histogram unless the first
# observation names its own bounds.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)


class Histogram:
    """Fixed-bucket histogram with monotone cumulative-friendly counters."""

    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Tuple[float, ...] = DEFAULT_BUCKETS):
        bounds = tuple(float(b) for b in bounds)
        if not bounds or list(bounds) != sorted(set(bounds)):
            raise ValueError("histogram bounds must be sorted and distinct")
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)  # +1: overflow (> last bound)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        # Prometheus `le` semantics: bucket i counts value <= bounds[i]
        self.counts[bisect.bisect_left(self.bounds, value)] += 1
        self.sum += value
        self.count += 1

    def bucket_index(self, value: float) -> int:
        """The bucket a value lands in (tests assert ±1-bucket consistency
        between journal-derived latencies and the live histogram)."""
        return bisect.bisect_left(self.bounds, value)

    def quantile(self, q: float) -> float:
        """The q-quantile (0..1), linearly interpolated inside its bucket."""
        if not self.count:
            return 0.0
        rank = q * self.count
        cum = 0
        for i, c in enumerate(self.counts):
            cum += c
            if c and cum >= rank:
                if i >= len(self.bounds):  # overflow: no finite upper edge
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i else 0.0
                hi = self.bounds[i]
                frac = (rank - (cum - c)) / c
                return lo + frac * (hi - lo)
        return self.bounds[-1]

    def snapshot(self) -> dict:
        cum, buckets = 0, []
        for bound, c in zip(self.bounds, self.counts):
            cum += c
            buckets.append([bound, cum])
        buckets.append(["+Inf", cum + self.counts[-1]])
        return {
            "count": self.count,
            "sum": round(self.sum, 6),
            "p50": round(self.quantile(0.50), 6),
            "p95": round(self.quantile(0.95), 6),
            "p99": round(self.quantile(0.99), 6),
            "buckets": buckets,
        }


def _label_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _escape_label(value: str) -> str:
    """Prometheus exposition-format escaping for label VALUES: backslash,
    double quote, and newline. Label values here include client-supplied
    tenant names — one odd name must not corrupt the whole scrape."""
    return (value.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + body + "}"


def _fmt_value(v) -> str:
    """Full-precision sample rendering (what prometheus_client does).

    ``%g`` would quantize to 6 significant digits — a long-lived daemon's
    monotone counter past 1e6 would read frozen between 10-unit quanta,
    making ``rate()`` over the exposition show zero-then-burst."""
    return repr(v) if isinstance(v, float) else str(v)


class MetricsRegistry:
    """Labeled counters/gauges/histograms behind one lock."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        self._hists: Dict[Tuple[str, tuple], Histogram] = {}

    # --- mutation -------------------------------------------------------------

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[(name, _label_key(labels))] = value

    def observe(self, name: str, value: float,
                buckets: Optional[Tuple[float, ...]] = None, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(buckets or DEFAULT_BUCKETS)
            h.observe(value)

    # --- reads ----------------------------------------------------------------

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def histogram(self, name: str, **labels) -> Optional[Histogram]:
        """The live histogram series (tests / consistency checks)."""
        with self._lock:
            return self._hists.get((name, _label_key(labels)))

    def _copy_series(self):
        """(counters, gauges, histogram copies), snapshotted under the lock.

        Readers (``stats``/``metrics`` ops on the API thread) format OUTSIDE
        the lock: producers observe from hot paths — including inside the
        scheduler's queue lock — so a scrape holding this lock for a full
        string-formatting pass would stall job pops and, transitively,
        request admission. The copy is O(series); formatting is the
        expensive part and runs lock-free on detached data.
        """
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = [(n, lk, h.bounds, list(h.counts), h.sum, h.count)
                     for (n, lk), h in sorted(self._hists.items())]
        return counters, gauges, hists

    @staticmethod
    def _copied_hist(bounds, counts, hsum, count) -> Histogram:
        h = Histogram(bounds)
        h.counts = counts
        h.sum = hsum
        h.count = count
        return h

    def summaries(self, name: str) -> List[dict]:
        """Per-label-set p50/p95/p99 rollup for one histogram family — the
        shape the daemon's ``stats`` op embeds under ``latency``."""
        _counters, _gauges, hists = self._copy_series()
        out = []
        for n, lk, bounds, counts, hsum, count in hists:
            if n != name:
                continue
            h = self._copied_hist(bounds, counts, hsum, count)
            out.append({"labels": dict(lk), "count": count,
                        "sum": round(hsum, 6),
                        "p50": round(h.quantile(0.50), 6),
                        "p95": round(h.quantile(0.95), 6),
                        "p99": round(h.quantile(0.99), 6)})
        return out

    def export(self, prefix: str = "vft_") -> Tuple[dict, str]:
        """(structured snapshot, Prometheus text) from ONE series copy —
        the ``metrics`` socket op serves both per call, and a second
        independent copy would double the scrape's contention window
        against hot-path producers (the scheduler observes inside its
        queue lock)."""
        series = self._copy_series()
        return self._snapshot_from(series), self._text_from(series, prefix)

    def snapshot(self) -> dict:
        """JSON-friendly dump of every series (the ``metrics`` socket op)."""
        return self._snapshot_from(self._copy_series())

    @classmethod
    def _snapshot_from(cls, series) -> dict:
        counters, gauges, hists = series
        return {
            "counters": [
                {"name": n, "labels": dict(lk), "value": round(v, 6)}
                for (n, lk), v in counters],
            "gauges": [
                {"name": n, "labels": dict(lk), "value": v}
                for (n, lk), v in gauges],
            "histograms": [
                {"name": n, "labels": dict(lk),
                 **cls._copied_hist(bounds, cts, hsum, count).snapshot()}
                for n, lk, bounds, cts, hsum, count in hists],
        }

    def prometheus_text(self, prefix: str = "vft_") -> str:
        """Prometheus text exposition (one scrape-ready string); formatted
        outside the registry lock (see :meth:`_copy_series`)."""
        return self._text_from(self._copy_series(), prefix)

    @staticmethod
    def _text_from(series, prefix: str) -> str:
        counters, gauges, hists = series
        lines: List[str] = []
        names_seen = set()
        for (name, lk), value in counters:
            full = prefix + name
            if full not in names_seen:
                names_seen.add(full)
                lines.append(f"# TYPE {full} counter")
            lines.append(f"{full}{_label_str(lk)} {_fmt_value(value)}")
        for (name, lk), value in gauges:
            full = prefix + name
            if full not in names_seen:
                names_seen.add(full)
                lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full}{_label_str(lk)} {_fmt_value(value)}")
        for name, lk, bounds, counts, hsum, count in hists:
            full = prefix + name
            if full not in names_seen:
                names_seen.add(full)
                lines.append(f"# TYPE {full} histogram")
            cum = 0
            for bound, c in zip(bounds, counts):
                cum += c
                blk = _label_str(lk + (("le", f"{bound:g}"),))
                lines.append(f"{full}_bucket{blk} {cum}")
            blk = _label_str(lk + (("le", "+Inf"),))
            lines.append(f"{full}_bucket{blk} {count}")
            lines.append(f"{full}_sum{_label_str(lk)} {_fmt_value(hsum)}")
            lines.append(f"{full}_count{_label_str(lk)} {count}")
        return "\n".join(lines) + "\n"
