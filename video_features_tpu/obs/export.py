"""Journal → Chrome-trace (Perfetto / ``chrome://tracing``) converter.

The span journal is an append-only event log; this module folds it into the
Chrome trace-event JSON that Perfetto (https://ui.perfetto.dev) and
``chrome://tracing`` load directly — the "per-op timeline an operator can
actually look at" layer (docs/observability.md has the walkthrough)::

    python -m video_features_tpu.obs.export <telemetry_dir>/events.jsonl \
        -o trace.json

Three kinds of trace slices come out:

- **explicit spans** — ``<name>_start`` / ``<name>_end`` pairs sharing a
  ``span`` id (``decode``, ``extract``, ``device``) become complete ``"X"``
  events with real durations;
- **derived lifecycle spans** — per video, ``video_queued``/``video_requeued``
  → ``video_popped`` becomes a ``queue_wait`` slice and ``video_popped`` →
  ``video_done``/``video_failed`` a ``process`` slice; per request,
  ``request_admitted`` → ``request_done`` becomes a ``request`` slice. These
  are exactly the latency histograms' definitions, so trace and histograms
  cross-check;
- **instants** — everything else (cache hits, stale flushes, autoscale
  resizes, breaker trips) becomes a thread-scoped instant marker.

Tracks (``tid``): one per video, one per request, one catch-all ``daemon``
track; ``thread_name`` metadata labels them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple

_PID = 1
# journal records that are bookkeeping, not timeline content
_META_EVENTS = {"journal_open", "journal_close"}


def load_journal(path: str) -> Tuple[List[dict], int]:
    """(events sorted by ts, corrupt-line count). Corrupt lines — a torn
    tail from a kill mid-append — are counted and skipped, never fatal."""
    events: List[dict] = []
    corrupt = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if (not isinstance(rec, dict)
                        or not isinstance(rec.get("ts"), (int, float))
                        or isinstance(rec.get("ts"), bool)
                        or "event" not in rec):
                    # a non-numeric ts would crash the sort below — that is
                    # a corrupt line too, counted not fatal
                    raise ValueError("not an event record")
            except ValueError:
                corrupt += 1
                continue
            events.append(rec)
    events.sort(key=lambda e: e["ts"])
    return events, corrupt


def _track_of(ev: dict) -> str:
    video = ev.get("video")
    if video is not None:
        return str(video)
    request = ev.get("request")
    if request is not None:
        return f"request {request}"
    return "daemon"


class _Tracks:
    """Stable small-int tid per track name, first-seen order."""

    def __init__(self):
        self._tids: Dict[str, int] = {}

    def tid(self, name: str) -> int:
        tid = self._tids.get(name)
        if tid is None:
            tid = self._tids[name] = len(self._tids) + 1
        return tid

    def metadata(self) -> List[dict]:
        return [{"ph": "M", "pid": _PID, "tid": tid, "name": "thread_name",
                 "args": {"name": name}}
                for name, tid in self._tids.items()]


def _args_of(ev: dict) -> dict:
    return {k: v for k, v in ev.items()
            if k not in ("ts", "event", "span")}


def to_chrome_trace(events: Sequence[dict]) -> dict:
    """Fold journal events into a Chrome trace-event document."""
    timeline = [e for e in events if e["event"] not in _META_EVENTS]
    t0 = min((e["ts"] for e in timeline), default=0.0)

    def us(ts: float) -> float:
        return round((ts - t0) * 1e6, 1)

    tracks = _Tracks()
    out: List[dict] = []

    def slice_event(name: str, begin: dict, end: dict,
                    track: Optional[str] = None) -> None:
        out.append({
            "ph": "X", "pid": _PID,
            "tid": tracks.tid(track or _track_of(begin)),
            "name": name, "cat": "vft",
            "ts": us(begin["ts"]),
            "dur": max(us(end["ts"]) - us(begin["ts"]), 0.1),
            "args": {**_args_of(begin),
                     **({"state": end["event"]}
                        if end["event"] != begin["event"] else {})},
        })

    # explicit spans: pair *_start / *_end on (span NAME, span id) — ids
    # restart at 1 per journal session, so the id alone is not unique
    open_spans: Dict[object, dict] = {}
    # lifecycle milestones per video / request
    queued_at: Dict[str, dict] = {}
    popped_at: Dict[str, dict] = {}
    admitted_at: Dict[str, dict] = {}
    paired = 0

    for ev in events:
        name = ev["event"]
        if name in _META_EVENTS:
            if name == "journal_open":
                # a new journal session (the file accumulates across runs in
                # append mode, and span ids restart with it): a run killed
                # mid-span must leave its start UNPAIRED, not pair it with
                # an unrelated later session's end
                open_spans.clear()
            continue
        sid = ev.get("span")
        if sid is not None and name.endswith("_start"):
            open_spans[(name[: -len("_start")], sid)] = ev
            continue
        if sid is not None and name.endswith("_end"):
            begin = open_spans.pop((name[: -len("_end")], sid), None)
            if begin is not None:
                slice_event(name[: -len("_end")], begin, ev)
                paired += 1
            continue
        video = ev.get("video")
        if name in ("video_queued", "video_requeued") and video is not None:
            queued_at[video] = ev
        elif name == "video_popped" and video is not None:
            begin = queued_at.pop(video, None)
            if begin is not None:
                slice_event("queue_wait", begin, ev)
            popped_at[video] = ev
        elif name in ("video_done", "video_failed") and video is not None:
            begin = popped_at.pop(video, None)
            if begin is not None:
                slice_event("process", begin, ev)
        elif name == "request_admitted" and ev.get("request") is not None:
            admitted_at[str(ev["request"])] = ev
        elif name == "request_done" and ev.get("request") is not None:
            begin = admitted_at.pop(str(ev["request"]), None)
            if begin is not None:
                slice_event("request", begin, ev)
        # every milestone/instant is also a marker on its own track
        out.append({"ph": "i", "pid": _PID, "tid": tracks.tid(_track_of(ev)),
                    "name": name, "cat": "vft", "s": "t",
                    "ts": us(ev["ts"]), "args": _args_of(ev)})

    trace = {
        "traceEvents": tracks.metadata() + out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "video_features_tpu.obs",
            "events": len(timeline),
            "paired_spans": paired,
            "unpaired_spans": len(open_spans),
        },
    }
    return trace


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m video_features_tpu.obs.export",
        description="Convert a telemetry span journal (events.jsonl) into a "
                    "Chrome/Perfetto trace (docs/observability.md)")
    parser.add_argument("journal", help="path to the events.jsonl journal "
                                        "(or the --telemetry_dir holding it)")
    parser.add_argument("-o", "--output", default=None,
                        help="trace output path (default: "
                             "<journal>.trace.json)")
    ns = parser.parse_args(argv)
    path = ns.journal
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    try:
        events, corrupt = load_journal(path)
    except OSError as e:
        print(f"cannot read journal: {e}", file=sys.stderr)
        return 2
    trace = to_chrome_trace(events)
    out_path = ns.output or (path + ".trace.json")
    with open(out_path, "w") as f:
        json.dump(trace, f)
    meta = trace["otherData"]
    spans = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    print(f"{out_path}: {meta['events']} journal events → {spans} spans "
          f"({meta['paired_spans']} explicit, "
          f"{meta['unpaired_spans']} unpaired)"
          + (f"; {corrupt} corrupt line(s) skipped" if corrupt else "")
          + " — load in https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
