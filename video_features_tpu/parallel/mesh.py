"""Device-mesh data parallelism for the clip pipeline.

The reference's only parallel axis is inter-video data parallelism via one Python
thread per GPU (``/root/reference/main.py:37-47``). The TPU-native design replaces
threads with SPMD over a ``jax.sharding.Mesh``: a batch of clips/frames/pairs is
sharded along the leading axis across devices (``data`` axis over ICI), params are
replicated, and a single jitted program runs everywhere. No collectives are
semantically required for inference; XLA inserts only the initial shard/replicate
transfers and the output gather when results return to host.

Every extractor owns a :class:`MeshRunner` (built from ``cfg.num_devices``) and
routes its batched device step through :meth:`MeshRunner.jit`; batch sizes are
rounded up to a multiple of the mesh size with :meth:`MeshRunner.device_batch` so
the leading axis always divides evenly (static shapes — one compile per geometry).

Multi-host (DCN) scaling uses the same code: each host builds a mesh over its local
devices and processes its shard of the *video list*
(:func:`video_features_tpu.parallel.pipeline.shard_video_list`), mirroring the
embarrassingly-parallel split the reference documents via ``gen_file_list.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def local_mesh(num_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over local devices: the clip-batch data-parallel axis."""
    if devices is None:
        devices = jax.local_devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis across the data axis.

    The PartitionSpec names only axis 0, so the same sharding serves every batch
    rank in the framework: (B, F) features, (B, H, W, C) frames, (B, T, H, W, C)
    clip stacks, (B, H, W, 2) flow fields.
    """
    return NamedSharding(mesh, P(DATA_AXIS))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_apply(mesh: Mesh, fn: Callable, n_batch_args: int = 1,
                  matmul_precision: Optional[str] = None,
                  n_replicated_args: int = 0,
                  donate_argnums: Tuple[int, ...] = ()):
    """jit ``fn(params, *batches)`` with params replicated and batches sharded on axis 0.

    Each batch argument's leading axis must be divisible by the mesh size — callers
    round their batch size up via :meth:`MeshRunner.device_batch` and zero-pad the
    tail (:func:`video_features_tpu.extractors.base.pad_batch`). Output shardings
    are left to XLA (batch-preserving steps keep rows sharded; ``np.asarray``
    gathers them to host).

    ``donate_argnums``: XLA input-output aliasing needs an output of
    IDENTICAL shape/dtype/layout to reuse a donated buffer, and with the
    uint8 wire format no frame-path *step* has one: every step consumes a
    uint8 frame buffer (4× smaller than any float activation or output) and
    emits fp32 (or ``--transfer_dtype``) features/flow, so donating those
    would only emit XLA's "donated buffer could not be aliased" warning per
    compile — the non-paged steps therefore donate nothing (default ``()``).
    The one genuinely matching pair is the paged dispatch mode's int32 row
    table (same shape/dtype in and out — :meth:`MeshRunner.jit_paged`), the
    path this seam was documented for; ``tests/test_paged.py`` pins the
    aliasing actually happening (donated table deleted) AND the uint8 steps
    still declining donation.

    ``matmul_precision``: TPU fp32 convs/matmuls default to bf16 MXU passes;
    ``"highest"`` traces the step under true-fp32 accumulation for the
    bit-parity path (≈3× the matmul cost; irrelevant on CPU).

    ``n_replicated_args``: trailing non-param arguments placed replicated
    rather than batch-sharded — the encode-once flow steps pass the window's
    final frame this way (a (1, H, W, 3) array cannot shard over the mesh).
    """
    if matmul_precision is not None:
        inner = fn

        def fn(*args):  # noqa: F811 — precision must be active at trace time
            with jax.default_matmul_precision(matmul_precision):
                return inner(*args)

    in_shardings = ((replicate(mesh),)
                    + (batch_sharding(mesh),) * n_batch_args
                    + (replicate(mesh),) * n_replicated_args)
    return jax.jit(fn, in_shardings=in_shardings,
                   donate_argnums=donate_argnums)


def enable_compilation_cache(cache_dir: str, min_compile_secs: float = 1.0) -> bool:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    TPU compiles for large flow geometries cost 20-100 s each (tunnel
    compiles, docs/budgets.md); a persistent cache directory lets reruns,
    restarts, and the driver's bench skip straight to execution. Safe to call
    repeatedly (last directory wins). Returns True when the cache was
    enabled; a JAX build without the option warns and returns False instead
    of failing the job.
    """
    import os
    import sys

    try:
        jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
    except (AttributeError, ValueError) as e:
        print(f"warning: could not enable the persistent compilation cache at "
              f"{cache_dir}: {e}", file=sys.stderr)
        return False
    return True


class MeshRunner:
    """Per-extractor data-parallel execution context.

    Replaces the reference's thread-per-GPU ``replicate``/``scatter``/
    ``parallel_apply`` (``/root/reference/main.py:43-47``): instead of replicating a
    Python module across devices and scattering video indices, the model params are
    replicated onto a mesh once and every device step is a single SPMD program over
    a sharded batch.
    """

    def __init__(self, num_devices: Optional[int] = None,
                 matmul_precision: Optional[str] = None):
        self.mesh = local_mesh(num_devices)
        self.num_devices = int(self.mesh.devices.size)
        self.batch_sharding = batch_sharding(self.mesh)
        self.replicated = replicate(self.mesh)
        self.matmul_precision = matmul_precision

    def device_batch(self, requested: int) -> int:
        """Smallest multiple of the mesh size ≥ ``requested``."""
        return -(-requested // self.num_devices) * self.num_devices

    def jit(self, fn: Callable, n_batch_args: int = 1, n_replicated_args: int = 0):
        return sharded_apply(self.mesh, fn, n_batch_args, self.matmul_precision,
                             n_replicated_args)

    def jit_paged(self, paged_fn: Callable):
        """jit a paged step ``paged_fn(params, page, table) -> (out, table)``
        with the int32 row table DONATED (``parallel/pages.py``).

        The table is the one buffer on the dispatch path whose output is
        identical in shape/dtype/layout to its input (int32 ``(page_rows, 3)``
        in, passed through unchanged), so XLA aliases it in place — the
        legal-donation seam :func:`sharded_apply` documents. Pages themselves
        stay undonated: uint8 in, fp32 features out never alias.

        This wiring is statically checked: vftlint's ``use-after-donate``
        rule discovers the ``jit_paged → sharded_apply(donate_argnums=…)``
        forwarding chain (not hardcoded — docs/static-analysis.md), so a
        caller that reads its table after dispatch, loops without
        re-staging, or a paged fn that stops returning the table, fails
        lint with this chain named in the finding."""
        return sharded_apply(self.mesh, paged_fn, n_batch_args=2,
                             matmul_precision=self.matmul_precision,
                             donate_argnums=(2,))

    def put(self, arr):
        """Transfer a host batch onto the mesh, sharded along axis 0."""
        return jax.device_put(arr, self.batch_sharding)

    def put_replicated(self, tree):
        """Place a param pytree on the mesh, replicated, ONCE.

        Host-numpy params passed into a jitted call are re-transferred every
        call (a full weight-tree H2D copy per batch); extractors must pin their
        params here at construction.
        """
        return jax.device_put(tree, self.replicated)
