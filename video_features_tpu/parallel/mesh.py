"""Device-mesh data parallelism for the clip pipeline.

The reference's only parallel axis is inter-video data parallelism via one Python
thread per GPU (``/root/reference/main.py:37-47``). The TPU-native design replaces
threads with SPMD over a ``jax.sharding.Mesh``: a batch of clips is sharded along the
leading axis across devices (``data`` axis over ICI), params are replicated, and a
single jitted program runs everywhere. No collectives are semantically required for
inference; XLA inserts only the initial shard/replicate transfers.

Multi-host (DCN) scaling uses the same code: each host builds a mesh over its local
devices and processes its shard of the *video list*
(:func:`video_features_tpu.parallel.pipeline.shard_video_list`), mirroring the
embarrassingly-parallel split the reference documents via ``gen_file_list.py``.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def local_mesh(num_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over local devices: the clip-batch data-parallel axis."""
    if devices is None:
        devices = jax.local_devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def shard_along(mesh: Mesh, ndim: int, axis: int = 0) -> NamedSharding:
    """NamedSharding that splits array axis ``axis`` across the data axis."""
    spec = [None] * ndim
    spec[axis] = DATA_AXIS
    return NamedSharding(mesh, P(*spec))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def sharded_apply(mesh: Mesh, fn: Callable, batch_ndim: int, donate_batch: bool = True):
    """jit ``fn(params, batch)`` with params replicated and batch sharded on axis 0.

    The batch's leading axis must be divisible by the mesh size (callers pad with
    :func:`video_features_tpu.extractors.base.pad_batch` — static shapes, one compile).
    Donating the input batch lets XLA reuse its HBM for activations.
    """
    in_shardings = (replicate(mesh), shard_along(mesh, batch_ndim))
    out_shardings = shard_along(mesh, 2)  # (N, feat) features stay row-sharded
    return jax.jit(
        fn,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(1,) if donate_batch else (),
    )
