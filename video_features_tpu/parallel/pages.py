"""Ragged paged batching: fixed-size device pages + int32 row tables.

The bucketed packer (:mod:`.packer`) fills fixed ``(batch_size, …)`` batches
per ``(model, slot-shape)`` bucket and keeps ONE batch in flight per bucket —
every corpus-flush or anti-starvation tail pays up to ``batch_size - 1``
padding rows, and the host round trip per dispatched batch is the
serialization point. The Ragged Paged Attention kernel work (PAPERS.md,
arXiv:2604.15464) shows the TPU-native fix: pack variable-length work into
fixed-size **pages** with a **row table** indexing the real rows, so one
compiled program per bucket *family* serves clips from any number of videos
(and any source geometry the host path normalizes into the family), with pad
waste bounded by one partial page instead of one partial batch.

Three pieces live here; the dispatch mechanics stay in
:class:`.packer.CorpusPacker` (its paged mode):

- **page geometry** — :func:`page_rows_for` sizes the page per family from
  the model's batch budget and the in-flight depth: ``depth`` pages of
  ``ceil(batch_size / depth)`` rows (rounded up to the mesh multiple) keep
  the same total rows in flight as one bucketed batch while the flush tail
  wastes at most ``page_rows - 1`` rows.
- **row tables** — :func:`build_row_table` maps each page row to
  ``(video, clip, valid)``: monotonically-assigned int32 video ids (host
  side, observability + device mask), the clip's index within its video, and
  a validity bit; padding rows are ``(-1, -1, 0)``. The table ships with the
  page and the jitted program masks by it.
- **the paged program** — :func:`paged_program` wraps a model's pure forward
  ``fn(params, page) -> rows`` into ``(params, page, table) ->
  (masked_rows, table)``. Masking multiplies every leading-axis output leaf
  by the validity column (×1.0 for real rows — exact, byte-preserving;
  ×0.0 zeroes padding rows on device). Passing the table through unchanged
  is what makes **buffer donation legal**: int32 ``(page_rows, 3)`` in and
  out, so :meth:`..parallel.mesh.MeshRunner.jit_paged` donates it and XLA
  aliases the buffer in place — the one dispatch-path donation the uint8
  wire format admits (``mesh.py::sharded_apply``'s documented seam).

Host scatter never reads the table (slots carry their assembly references —
slot-level fault attribution is unchanged); the table is the device-side
contract plus the journal/bench's occupancy ground truth.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import numpy as np

# row-table columns: page row -> (video id, clip idx, valid bit)
TABLE_COLS = 3
PAD_ROW = (-1, -1, 0)


def page_rows_for(batch_size: int, depth: int,
                  device_batch: Callable[[int], int] = lambda n: n) -> int:
    """Rows per page for a family with ``batch_size`` total rows budgeted
    across ``depth`` in-flight pages, rounded up to the mesh multiple via
    ``device_batch`` (:meth:`..parallel.mesh.MeshRunner.device_batch`)."""
    if depth < 1:
        raise ValueError("pages_in_flight depth must be >= 1")
    return device_batch(max(1, -(-batch_size // depth)))


def build_row_table(entries: Sequence[Tuple[int, int]], page_rows: int,
                    out: np.ndarray = None) -> np.ndarray:
    """int32 ``(page_rows, 3)`` row table for one page.

    ``entries`` are the occupied rows' ``(video_id, clip_idx)`` pairs in page
    order; rows past ``len(entries)`` are padding (``(-1, -1, 0)``). ``out``
    reuses a staging-ring buffer when given (the host's per-page work is a
    fill, not an allocation)."""
    n = len(entries)
    if n > page_rows:
        raise ValueError(f"{n} entries exceed the {page_rows}-row page")
    table = np.empty((page_rows, TABLE_COLS), np.int32) if out is None else out
    for i, (vid, idx) in enumerate(entries):
        table[i, 0] = vid
        table[i, 1] = idx
        table[i, 2] = 1
    table[n:] = PAD_ROW
    return table


def mask_rows(rows: Any, valid) -> Any:
    """Multiply every leading-axis leaf of ``rows`` by the validity column.

    ``valid`` is the table's int32 valid bit; the multiply is ×1.0 for real
    rows (exact — packed outputs stay byte-identical to the bucketed loop)
    and ×0.0 for padding rows. Pytree-aware for multi-output forwards."""
    import jax

    def mask(leaf):
        v = valid.astype(leaf.dtype).reshape((-1,) + (1,) * (leaf.ndim - 1))
        return leaf * v

    return jax.tree_util.tree_map(mask, rows)


def paged_program(forward: Callable[[Any, Any], Any]) -> Callable:
    """Wrap a pure per-row ``forward(params, page)`` into the paged step
    ``(params, page, table) -> (masked_rows, table)``.

    The returned callable is what :meth:`..parallel.mesh.MeshRunner.jit_paged`
    compiles ONCE per family: the row table (not the trace signature) carries
    which rows are real, so every page of the family — whatever mix of videos
    and source geometries filled it — runs this single program."""

    def paged(params, page, table):
        rows = forward(params, page)
        return mask_rows(rows, table[:, 2]), table

    return paged
