"""Cross-video clip packing: a corpus-level continuous-batching scheduler.

The per-video loop (:meth:`..extractors.base.Extractor._run_loop`) pays a
zero-padded tail batch per video (``pad_batch``) and drains the mesh between
videos — on a corpus of short clips a large fraction of device steps are
padding or idle. Fixed-shape continuous batching is the standard TPU answer
to ragged workloads (Ragged Paged Attention, arXiv:2604.15464), and
decoupling producers from fixed-shape device batches is the Podracer recipe
(arXiv:2104.06272): here, decoded clips stream into **shape-keyed slot
queues** and every dispatched ``(batch_size, …)`` device batch is filled with
clips from however many videos are ready — the tail of video N packs with the
head of video N+1. Per-clip results scatter back to per-video assembly
buffers (:class:`..io.output.FeatureAssembly`) that the run loop flushes
through the output writer as each video's last clip lands.

Four generalizations beyond the original RGB-only packer:

- **collate seam** — a :class:`PackSpec` may supply ``collate`` to build the
  device batch itself (and decide how many queued slots actually fit). The
  flow extractors use it to chain stream-consecutive frame-*pair* slots into
  one ``(batch_size + 1)``-frame shared-frame window: each video boundary
  inside a window burns one frame position, and the returned row map tells
  the scatter which output row belongs to which slot.
- **shape buckets** — :class:`ShapeBuckets` clusters the corpus's probed
  (padded) geometries into ≤ K buckets before decode starts, so a mixed
  720p/1080p corpus compiles K programs and co-packs inside each bucket
  instead of filling one queue per distinct geometry.
- **per-bucket dispatch** — each shape key keeps its own one-batch-in-flight
  pipeline (batch *k* is fetched when that bucket's batch *k+1* dispatches),
  and an anti-starvation flush dispatches a bucket's partial queue once
  ``flush_age`` videos have finished while it sat waiting — a rare geometry
  cannot strand its videos until corpus end.
- **co-resident models** — the bucket key is really ``(model, geometry)``:
  :meth:`CorpusPacker.register_model` adds further :class:`PackSpec`\\ s (one
  per feature type, each with its own step callable and batch size) to one
  packer, so a mixed resnet50/i3d/vggish request stream feeds ONE mesh and
  the device never drains while *any* model has backlog (ROADMAP item 2 —
  a model is "just" another bucket dimension). Whenever more than one
  model's queues are ready to dispatch (the corpus/idle flush, the
  anti-starvation flush, collate leftovers), batches interleave round-robin
  across models so no single model's backlog monopolizes the device;
  in-stream, arrival order already interleaves models because the serving
  scheduler pops videos tenant-fair, not model-grouped. One-batch-in-flight
  overlap, flush-age aging, occupancy stats, and slot-level fault
  attribution all hold per ``(model, geometry)`` key unchanged.

Threading model — deliberately single-threaded: the packed run loop (one
consumer) pulls each video's clip stream in corpus order and calls
:meth:`CorpusPacker.add`; decode parallelism comes from the
``DecodePrefetcher`` worker threads *upstream* of the clip stream. Every
cross-thread store therefore stays inside the already-declared
``parallel/pipeline.py`` / ``io/output.py`` seams (vftlint
``thread-shared-state``), and the packer itself needs no locks.

The packer makes NO corpus-end assumption: :meth:`flush` drains the partial
queues whenever the caller decides (the batch loop calls it once after the
last video; the serving daemon — :mod:`..serve` — calls it when the ingest
queue goes idle and again at graceful drain) and the queues keep accepting
slots afterwards, so one packer instance serves a daemon's whole lifetime
with the tail of request N packing into the head of request N+1. Long-run
callers bound per-video bookkeeping with :meth:`forget` and clear consumed
flush causes with :meth:`clear_flush_causes`.

Fault attribution is slot-level, not batch-level: a poisoned clip stream
fails only its contributing video. Slots reference their attempt's assembly
object directly (not the video path), so a retry opens a fresh assembly and
stale in-flight rows from the failed attempt land in the orphaned object and
die with it.
"""

from __future__ import annotations

import heapq
import sys
import time
from collections import Counter, deque
from dataclasses import dataclass
from typing import (
    Any,
    Callable,
    Dict,
    Iterable,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

import numpy as np

from ..io.output import FeatureAssembly
from ..reliability.faults import fault_point
from .pages import TABLE_COLS, build_row_table


@dataclass
class PackSpec:
    """How one model plugs into the corpus packer (``Extractor.pack_spec``).

    ``open_clips(path)`` returns ``(info, clip_iter)``: a mutable per-video
    info dict the stream fills as it decodes (fps, timestamps) and an iterator
    of fixed-shape uint8 clip arrays — one device-batch *slot* each. Clips of
    equal shape co-pack; each distinct shape fills its own queue (the flow
    extractors bound the shape count with :class:`ShapeBuckets`).

    ``step(batch)`` runs the model's existing jitted device step on a full
    host batch and returns the per-slot device features; the packer fetches
    them through the extractor's device_wait-accounted ``_wait``.
    ``finalize(path, rows, info)`` assembles the video's output dict from the
    in-order ``(n_clips, *row)`` host feature array.

    ``empty_row_shape`` shapes the zero-clip video output (e.g. ``(2048,)``
    for ResNet-50), matching the per-video loop's empty result.

    ``collate(clips, stream_keys)``, when given, replaces the default
    ``np.stack + pad_batch`` batch assembly: it receives up to ``batch_size``
    queued clips plus their ``(stream_id, clip_idx)`` continuity keys
    (consecutive iff same stream and ``idx + 1``) and returns
    ``(batch, n_used, row_of)`` — the device batch, how many of the offered
    slots it consumed (≥ 1), and for each consumed slot the row of
    ``step(batch)``'s output holding its features.

    ``prepare(paths)``, when given, runs once before the packed loop starts —
    the flow extractors use it to probe the corpus's container geometries and
    plan the shape buckets.

    ``paged_step(page, table)``, when given (and ``collate`` is not — the
    flow extractors' window chaining is its own dispatch shape), switches the
    model's buckets to **ragged paged dispatch** (:mod:`.pages`): batches are
    fixed ``(page_rows, …)`` pages shipped with an int32 row table, the step
    returns ``(device_rows, device_table)`` with padding rows masked on
    device, and each bucket keeps ``pages_in_flight`` pages in flight (the
    depth-k generalization of the one-batch-in-flight pipeline). NOT setting
    ``paged_step`` is the per-model opt-out: the extractor's ``pack_spec``
    omits it (``--paged_batching`` off, ``--show_pred``-adjacent fallbacks,
    the flow collate seam) and the bucket dispatches exactly as before.
    Raw-pixels wire formats (``--device_resize``/``--device_preproc``) DO
    page — their slot queues key by decoded geometry, so every page is
    shape-homogeneous and runs that geometry's compiled family.
    """

    batch_size: int
    empty_row_shape: Tuple[int, ...]
    open_clips: Callable[[str], Tuple[dict, Iterator[np.ndarray]]]
    step: Callable[[Any], Any]
    finalize: Callable[[str, np.ndarray, dict], Dict[str, np.ndarray]]
    collate: Optional[
        Callable[[List[np.ndarray], List[Tuple[int, int]]],
                 Tuple[Any, int, Sequence[int]]]] = None
    prepare: Optional[Callable[[Sequence[str]], None]] = None
    # ragged paged dispatch (parallel/pages.py): (page, row_table) ->
    # (device_rows, device_table); None = bucketed dispatch (the opt-out)
    paged_step: Optional[Callable[[Any, np.ndarray], Tuple[Any, Any]]] = None
    # rows per page (defaults to batch_size when unset); the extractor sizes
    # it per family via pages.page_rows_for (batch budget / depth, rounded
    # up to the mesh multiple)
    page_rows: Optional[int] = None
    # in-flight pages per bucket under paged dispatch (≥ 2 = the host
    # refills page k+1 while the device chews on page k AND k-1's scatter
    # overlaps); bucketed dispatch always keeps exactly 1
    pages_in_flight: int = 2


class ShapeBuckets:
    """Cluster probed (padded) geometries into at most ``max_buckets``.

    Built from the corpus's container probes before decode starts. Each
    bucket is the elementwise max of its member geometries; merging is
    greedy — while over the cap, merge the pair whose union adds the least
    video-weighted padding area. ``bucket_for`` maps a geometry to the
    smallest covering bucket (a geometry no planned bucket covers — e.g. a
    video whose probe failed — becomes its own ad-hoc bucket, preserving
    correctness at the cost of one extra compiled program).
    """

    def __init__(self, geometries: Iterable[Tuple[int, int]],
                 max_buckets: int):
        if max_buckets < 1:
            raise ValueError("max_buckets must be >= 1")
        counts = Counter(tuple(g) for g in geometries)
        # {id: (h, w, weight)} working set; weight = videos whose padding the
        # bucket's growth would touch. The greedy merge (pop the cheapest
        # union while over the cap) runs on a lazily-invalidated pair-cost
        # heap — a dead id (already merged) just skips — so planning a very
        # heterogeneous corpus costs O(G^2 log G), not O(G^3) rescans of
        # every pair per round.
        alive: Dict[int, Tuple[int, int, int]] = {
            k: (h, w, n) for k, ((h, w), n) in enumerate(counts.items())}
        next_id = len(alive)

        def pair_cost(a, b):
            ha, wa, na = alive[a]
            hb, wb, nb = alive[b]
            mh, mw = max(ha, hb), max(wa, wb)
            return (mh * mw * (na + nb) - ha * wa * na - hb * wb * nb,
                    (mh, mw, na + nb))

        heap = []
        if len(alive) > max_buckets:
            ids = list(alive)
            for x, a in enumerate(ids):
                for b in ids[x + 1:]:
                    heap.append((pair_cost(a, b)[0], a, b))
            heapq.heapify(heap)
        while len(alive) > max_buckets:
            cost, a, b = heapq.heappop(heap)
            if a not in alive or b not in alive:
                continue  # a stale pair: one side was merged away
            _, merged = pair_cost(a, b)
            del alive[a], alive[b]
            alive[next_id] = merged
            for other in list(alive):
                if other != next_id:
                    heapq.heappush(
                        heap, (pair_cost(other, next_id)[0], other, next_id))
            next_id += 1
        self.buckets: List[Tuple[int, int]] = sorted(
            (h, w) for h, w, _n in alive.values())

    def bucket_for(self, geometry: Tuple[int, int]) -> Tuple[int, int]:
        h, w = geometry
        covering = [(bh * bw, (bh, bw)) for bh, bw in self.buckets
                    if bh >= h and bw >= w]
        if not covering:
            return (h, w)
        return min(covering)[1]


class _Slot:
    """One occupied device-batch slot: a clip and where its row scatters.

    ``vid`` is the attempt's monotonic video id (assigned per ``begin()``) —
    the row table's first column under paged dispatch; a retry's fresh
    attempt gets a fresh id, so stale rows of a discarded attempt can never
    be confused with the retry's in any journaled table."""

    __slots__ = ("assembly", "idx", "clip", "vid")

    def __init__(self, assembly: FeatureAssembly, idx: int, clip: np.ndarray,
                 vid: int = -1):
        self.assembly = assembly
        self.idx = idx
        self.clip = clip
        self.vid = vid


class CorpusPacker:
    """Shape-keyed continuous batching across videos.

    Each shape key keeps a depth-k ring of dispatched batches in flight
    (k = ``PackSpec.pages_in_flight`` under paged dispatch, 1 bucketed): a
    key's batch *k* results are fetched (and scattered) only when the ring is
    full at its next dispatch, at an anti-starvation flush, or at
    :meth:`flush` — so host decode/stacking of the next batch overlaps device
    compute of the in-flight ones, the packed loop's analogue of the
    per-video loop's prefetch + ``_throttle`` backpressure (bounded unfetched
    batches per bucket; the bucket planner bounds the bucket count).

    **Paged dispatch** (``PackSpec.paged_step``, :mod:`.pages`): instead of
    ``batch_size`` padded batches, the bucket ships fixed ``page_rows`` pages
    plus an int32 row table mapping page rows → (video id, clip idx, valid);
    the jitted paged program masks by the table and passes it through (the
    donation-legal pair — ``mesh.py::MeshRunner.jit_paged``). The host's only
    per-page work is refilling a staging-ring buffer (page + table) and the
    ``device_put`` inside ``paged_step``; with ``pages_in_flight >= 2`` the
    scatter of page k overlaps the device chewing on page k+1. Slot-level
    fault attribution, stale flushes, round-robin fairness, and the stats
    surface are unchanged — a page is just a smaller, table-carrying batch.

    ``flush_age`` > 0 arms the anti-starvation flush: when a key's queue has
    sat non-empty while ``flush_age`` videos finished their streams, its
    partial queue is dispatched zero-padded and resolved eagerly, so a rare
    bucket's videos complete (and their writes land) mid-run instead of at
    corpus end.
    """

    def __init__(self, spec: Optional[PackSpec] = None,
                 wait: Callable[[Any], np.ndarray] = np.asarray,
                 clock=None, flush_age: int = 0, staging=None,
                 journal=None, metrics=None):
        # model name -> PackSpec. Single-model callers (the batch loop, the
        # engine tests) pass one spec, registered under None; the multi-model
        # serving layer constructs spec-less and register_model()s each
        # feature type — every internal key is (model, clip shape) either way
        self._specs: Dict[Optional[str], PackSpec] = {}
        if spec is not None:
            self._specs[None] = spec
        self._video_model: Dict[str, Optional[str]] = {}
        self._rr_last: Optional[str] = None  # last model dispatched (RR seed)
        self._wait = wait
        self._clock = clock  # optional StageClock: packed_slots/packed_clips units
        self._flush_age = flush_age
        # telemetry (docs/observability.md): the span journal gets a
        # 'dispatch' instant per dispatched batch, a 'device' span around
        # each batch fetch, and 'stale_flush' instants; the metrics registry
        # gets per-bucket occupancy gauges and the device_batch_seconds
        # histogram. Both optional and emit-only — never block dispatch.
        self._journal = journal
        self._metrics = metrics
        # optional HostStagingRing: the default (no-collate) batch assembly
        # fills a reusable per-geometry buffer instead of np.stack+pad_batch
        # allocating per dispatch; the buffer is committed against the step's
        # device output (output ready ⟹ the input transfer was consumed), so
        # it is never rewritten while the device may still read it. Collate
        # specs (flow) stage into the ring themselves.
        self._staging = staging
        self._pending: Dict[tuple, List[_Slot]] = {}
        self._open: Dict[str, FeatureAssembly] = {}
        self._finished: List[FeatureAssembly] = []
        # per shape key: ring of (slots, row_of, fetchable) unfetched
        # batches, oldest first — depth 1 bucketed, PackSpec.pages_in_flight
        # under paged dispatch
        self._inflight: Dict[tuple, deque] = {}
        # per-attempt monotonic video ids (the row table's first column);
        # a retry's begin() assigns a fresh id
        self._video_ids: Dict[str, int] = {}
        self._vid_seq = 0
        # per shape key: videos-finished count when its queue last became
        # non-empty (anti-starvation age base)
        self._queue_born: Dict[tuple, int] = {}
        self._videos_finished = 0
        self.real_slots = 0  # clips dispatched
        self.dispatched_slots = 0  # clips + padding/boundary slots dispatched
        self.staged_bytes = 0  # host bytes staged per dispatched device batch
        self.pages_dispatched = 0  # paged-mode dispatches (bench/stats)
        self.max_in_flight = 0  # deepest observed in-flight ring (any key)
        self.video_clips: Dict[str, int] = {}  # per finished video
        # per shape key: {"real_slots", "dispatched_slots", "stale_flushes"}
        self._bucket_stats: Dict[tuple, Dict[str, int]] = {}
        # device failures contained by the anti-starvation flush barrier,
        # failed-flush causes (anti-starvation or corpus-end), keyed by shape
        # bucket — the run loop attributes each drained victim only its own
        # buckets' causes
        self.flush_errors: Dict[tuple, List[str]] = {}
        # per open/finished video: the shape keys its slots were queued
        # under (cause attribution for stale-flush failures)
        self._video_keys: Dict[str, set] = {}

    # --- model registry ------------------------------------------------------

    def register_model(self, model: Optional[str], spec: PackSpec) -> None:
        """Co-locate another feature type's spec on this packer.

        Each model keeps its own step callable, batch size, and
        ``(model, geometry)`` bucket keys; nothing co-packs ACROSS models
        (their rows are different programs) — co-residency keeps the device
        fed when any one model's queue drains."""
        self._specs[model] = spec

    @property
    def models(self) -> Tuple[Optional[str], ...]:
        return tuple(self._specs)

    def _spec_for(self, key: tuple) -> PackSpec:
        return self._specs[key[0]]

    @staticmethod
    def _bucket_name(key: tuple) -> str:
        model, shape = key
        dims = "x".join(str(d) for d in shape)
        return dims if model is None else f"{model}:{dims}"

    # --- per-video lifecycle -------------------------------------------------

    def begin(self, path: str, info: dict,
              model: Optional[str] = None) -> None:
        """Open a fresh attempt for ``path`` (replacing any failed prior one).

        ``model`` routes the video's clips to that registered spec's
        ``(model, geometry)`` buckets; None is the single-spec default."""
        if model not in self._specs:
            raise KeyError(f"model {model!r} is not registered with this "
                           f"packer (have: {sorted(map(str, self._specs))})")
        self.discard(path)
        self._video_model[path] = model
        self._vid_seq += 1
        self._video_ids[path] = self._vid_seq
        self._open[path] = FeatureAssembly(path, info)

    def add(self, path: str, clip: np.ndarray) -> None:
        """Queue one clip; dispatches device batches when queues fill."""
        asm = self._open[path]
        slot = _Slot(asm, asm.reserve(), clip, vid=self._video_ids[path])
        key = (self._video_model[path], clip.shape)
        self._video_keys.setdefault(path, set()).add(key)
        queue = self._pending.setdefault(key, [])
        # a bucket receiving slots is being fed, not stranded: age counts
        # from its last activity (slot arrival or dispatch), so a slowly
        # filling common bucket is never padded-flushed mid-corpus
        self._queue_born[key] = self._videos_finished
        queue.append(slot)
        self._pump()

    @staticmethod
    def _paged(spec: PackSpec) -> bool:
        """Paged dispatch is active for a spec that ships a paged step and
        does not collate (window chaining owns its own dispatch shape)."""
        return spec.paged_step is not None and spec.collate is None

    def _batch_rows(self, spec: PackSpec) -> int:
        """Rows per dispatched batch: the page size under paged dispatch,
        the padded batch size bucketed."""
        if self._paged(spec):
            return spec.page_rows or spec.batch_size
        return spec.batch_size

    def _full(self, key: tuple) -> bool:
        queue = self._pending.get(key)
        return bool(queue) and len(queue) >= self._batch_rows(
            self._spec_for(key))

    def _pump(self) -> None:
        """Dispatch every full queue, one batch per key per round,
        round-robin across models between rounds.

        Single-model this is the old ``while full: dispatch`` loop (a
        collate may consume fewer than batch_size slots per dispatch — flow
        windows burn a frame position per video boundary — so the queue can
        stay full across rounds). Multi-model, whenever several models have
        full queues at once, the round order starts after the last-served
        model so one model's deep backlog cannot dispatch twice before
        another model's ready batch dispatches once."""
        while True:
            ready = [k for k in self._pending if self._full(k)]
            if not ready:
                return
            for key in self._one_per_model(ready):
                if self._full(key):
                    self._dispatch(key)

    def _rr_order(self, keys: List[tuple]) -> List[tuple]:
        """``keys`` ordered round-robin by model starting after the last
        dispatched model (deterministic string order within a model)."""
        models = sorted({k[0] for k in keys}, key=str)
        start = 0
        if self._rr_last is not None:
            for i, m in enumerate(models):
                if str(m) > str(self._rr_last):
                    start = i
                    break
        order = {m: i for i, m in enumerate(models[start:] + models[:start])}
        return sorted(keys, key=lambda k: (order[k[0]], str(k)))

    def _one_per_model(self, ready: List[tuple]) -> List[tuple]:
        """One ready key PER MODEL, round-robin ordered — the dispatch round
        shape: with several models ready, each round serves each model one
        batch, so no model's multi-bucket backlog dispatches twice before
        another model's ready batch dispatches once."""
        out, seen = [], set()
        for key in self._rr_order(ready):
            if key[0] not in seen:
                seen.add(key[0])
                out.append(key)
        return out

    def finish(self, path: str) -> None:
        """Mark ``path``'s stream complete; it finalizes once all rows land."""
        asm = self._open.pop(path)
        asm.finish()
        self.video_clips[path] = asm.expected or 0
        self._finished.append(asm)
        self._videos_finished += 1
        self._flush_stale()

    def forget(self, path: str) -> None:
        """Drop a COMPLETED video's bookkeeping (clip counts, bucket keys).

        Batch runs keep these for the end-of-run stats; the serving daemon
        calls this after each video's output lands so the per-video dicts
        stay bounded over an unbounded request stream (the soak test in
        tests/test_service.py pins this)."""
        self.video_clips.pop(path, None)
        self._video_keys.pop(path, None)
        self._video_model.pop(path, None)
        self._video_ids.pop(path, None)

    def discard(self, path: str) -> None:
        """Drop every trace of ``path``'s current attempt (failure/retry).

        Pending slots are unlinked; slots already dispatched (including the
        in-flight batches) still hold the dead attempt's assembly and scatter
        harmlessly into it — slot-level attribution needs no batch rollback.
        """
        asm = self._open.pop(path, None)
        self.video_clips.pop(path, None)
        self._video_keys.pop(path, None)
        self._video_model.pop(path, None)
        self._video_ids.pop(path, None)
        self._finished = [a for a in self._finished if a.video != path]
        if asm is None:
            return
        for queue in self._pending.values():
            queue[:] = [s for s in queue if s.assembly is not asm]

    # --- dispatch ------------------------------------------------------------

    def _dispatch(self, key: tuple) -> None:
        spec = self._spec_for(key)
        paged = self._paged(spec)
        queue = self._pending[key]
        batch_size = self._batch_rows(spec)
        candidates = queue[:batch_size]
        if spec.collate is not None:
            batch, n_used, row_of = spec.collate(
                [s.clip for s in candidates],
                [(id(s.assembly), s.idx) for s in candidates])
            slots = candidates[:n_used]
            del queue[:n_used]  # in place: flush() iterates this same list
        else:
            slots = candidates
            del queue[:batch_size]
            batch = self._stage_batch([s.clip for s in slots], batch_size)
            row_of = range(len(slots))
        # depth-k ring: resolve this bucket's OLDEST unfetched batch only
        # when the ring is full, so scatter of batch k overlaps the device
        # chewing on k+1..k+depth (bucketed depth is 1 — the original
        # one-batch-in-flight behavior, scatter-then-step)
        depth = spec.pages_in_flight if paged else 1
        ring = self._inflight.setdefault(key, deque())
        while len(ring) >= max(1, depth):
            self._scatter_oldest(key)
        # mid-batch chaos seam (docs/reliability.md): a `kill` here dies with
        # a full batch assembled but never stepped — recovery must replay
        # every co-packed video of every admitted request
        fault_point("device", str(key))
        if paged:
            table = self._stage_table(slots, batch_size)
            out = spec.paged_step(batch, table)
            fetchable = out[0]  # device rows; the donated table out is dropped
        else:
            out = spec.step(batch)
            fetchable = out
        self._rr_last = key[0]  # round-robin seed: the model just served
        if self._staging is not None:
            # no-op for batches the ring does not own (collate specs commit
            # their own buffers at device_put time, inside step)
            self._staging.commit(batch, out)
            if paged:
                self._staging.commit(table, out)
        self.staged_bytes += int(getattr(batch, "nbytes", 0))
        ring.append((slots, row_of, fetchable))
        self.max_in_flight = max(self.max_in_flight, len(ring))
        # a bucket being served is not starving: age counts from its last
        # activity (dispatch here, slot arrival in add())
        self._queue_born[key] = self._videos_finished
        self.real_slots += len(slots)
        self.dispatched_slots += batch_size
        stats = self._bucket_stats.setdefault(
            key, {"real_slots": 0, "dispatched_slots": 0, "stale_flushes": 0,
                  "pages_dispatched": 0})
        stats.setdefault("pages_dispatched", 0)
        stats["real_slots"] += len(slots)
        stats["dispatched_slots"] += batch_size
        if paged:
            stats["pages_dispatched"] += 1
            self.pages_dispatched += 1
        if self._clock is not None:
            self._clock.add_units("packed_slots", batch_size)
            self._clock.add_units("packed_clips", len(slots))
        if self._journal is not None:
            self._journal.emit("dispatch", bucket=self._bucket_name(key),
                               real_slots=len(slots), batch_slots=batch_size,
                               paged=paged, inflight=len(ring))
        if self._metrics is not None:
            occ = round(stats["real_slots"] / stats["dispatched_slots"], 4)
            self._metrics.set_gauge("bucket_occupancy", occ,
                                    bucket=self._bucket_name(key))
            if paged:
                # the page-level win (real rows / page rows, cumulative per
                # bucket): pad waste beyond the final partial page shows up
                # here before it shows up in the bench
                self._metrics.set_gauge("page_occupancy", occ,
                                        bucket=self._bucket_name(key))

    def _stage_table(self, slots: List[_Slot], page_rows: int) -> np.ndarray:
        """Row table for one page — (video id, clip idx, valid) per row,
        filled into a reusable staging-ring buffer when a ring is wired
        (the table rides the wire next to its page; the ring guards both
        until the step's device values resolve)."""
        entries = [(s.vid, s.idx) for s in slots]
        if self._staging is None:
            return build_row_table(entries, page_rows)
        buf = self._staging.acquire((page_rows, TABLE_COLS), np.int32)
        return build_row_table(entries, page_rows, out=buf)

    def _stage_batch(self, clips: List[np.ndarray],
                     batch_size: int) -> np.ndarray:
        """Default batch assembly: clips stacked (zero-padded to the static
        batch shape) into a reusable staging-ring buffer when a ring is
        wired, else the original fresh ``np.stack`` + ``pad_batch``. Dtype
        follows the clips — uint8 frame slots stay uint8 on the wire."""
        from ..extractors.base import pad_batch  # runtime: avoids an import cycle

        if self._staging is None:
            return pad_batch(np.stack(clips), batch_size)
        return self._staging.stage(clips, batch_size)

    def _scatter_inflight(self, key: Optional[tuple] = None) -> None:
        """Resolve EVERY unfetched batch of ``key`` (or of every key),
        oldest first — the flush-time drain of the depth-k rings."""
        keys = [key] if key is not None else list(self._inflight)
        for k in keys:
            ring = self._inflight.get(k)
            while ring:
                self._scatter_oldest(k)

    def _scatter_oldest(self, key: tuple) -> None:
        """Fetch and scatter one key's oldest unfetched batch. A fetch
        failure drops only that batch's rows (its entry was popped) — the
        younger in-flight entries still resolve at the flush arms."""
        ring = self._inflight.get(key)
        if not ring:
            return
        slots, row_of, fetchable = ring.popleft()
        host = self._fetch_batch(key, fetchable)
        for i, slot in enumerate(slots):
            slot.assembly.put(slot.idx, host[row_of[i]])

    def _fetch_batch(self, key: tuple, out) -> np.ndarray:
        """Fetch one batch's device output through the extractor's
        device_wait-accounted ``_wait``, with the blocked time journaled as
        a per-batch 'device' span and observed into the
        ``device_batch_seconds`` histogram (labeled by model — the
        per-BATCH device distribution; per-video device attribution does
        not exist under packing, where a batch mixes videos)."""
        if self._journal is None and self._metrics is None:
            return self._wait(out)
        t0 = time.perf_counter()
        if self._journal is not None:
            with self._journal.span("device", bucket=self._bucket_name(key)):
                host = self._wait(out)
        else:
            host = self._wait(out)
        if self._metrics is not None:
            model = key[0] if key[0] is not None else "default"
            self._metrics.observe("device_batch_seconds",
                                  time.perf_counter() - t0, model=model)
        return host

    def _flush_stale(self) -> None:
        """Anti-starvation: dispatch (and resolve) buckets whose partial
        queues sat idle (no slot arrival, no dispatch) for ``flush_age``
        video completions — latency over overlap for geometries too rare to
        fill their own batches."""
        if not self._flush_age:
            return
        stale = [key for key, queue in self._pending.items()
                 if queue and (self._videos_finished - self._queue_born[key]
                               >= self._flush_age)]
        if not stale:
            return
        failed = set()
        while True:
            # same one-batch-per-model rounds as _pump/flush: several
            # models' stale buckets interleave instead of one model
            # draining its whole backlog first
            ready = [k for k in stale
                     if k not in failed and self._pending.get(k)]
            if not ready:
                break
            for key in self._one_per_model(ready):
                if not self._pending.get(key):
                    continue
                try:
                    self._dispatch(key)
                except KeyboardInterrupt:
                    raise
                except Exception as e:  # noqa: BLE001 — fault-barrier: the stale-flush arm of the per-video isolation point — the flushed batch may hold ZERO slots of the video whose finish() triggered it, so letting this escape would retry/fail the wrong (healthy) video; victims resolve via drain_incomplete with this cause
                    self._record_stale_failure(key, e)
                    failed.add(key)
        for key in stale:
            if key in failed:
                continue
            try:
                self._scatter_inflight(key)  # rare bucket: complete now
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — fault-barrier: the scatter arm of the stale flush — same victim attribution as the dispatch arm above
                self._record_stale_failure(key, e)
                continue
            self._bucket_stats[key]["stale_flushes"] += 1
            if self._journal is not None:
                self._journal.emit("stale_flush",
                                   bucket=self._bucket_name(key))
            if self._metrics is not None:
                self._metrics.inc("stale_flushes_total",
                                  bucket=self._bucket_name(key))

    def _record_stale_failure(self, key: tuple, e: BaseException) -> None:
        msg = (f"anti-starvation flush of bucket "
               f"{self._bucket_name(key)} failed: {e}")
        self.flush_errors.setdefault(key, []).append(msg)
        print(f"[pack] {msg}; its videos will be failed (retryable) "
              "when the corpus drains", file=sys.stderr)

    def flush(self) -> None:
        """Dispatch every partial shape queue (padded) and resolve in-flight.

        Per-bucket fault isolation: one bucket's device failure must not
        abort the other buckets' dispatch/scatter — healthy buckets still
        resolve, and the failed bucket's contributors drain incomplete
        wearing only their own bucket's recorded cause.

        Multi-model packers drain ROUND-ROBIN across models, one batch per
        key per round, so one model's deep backlog cannot monopolize the
        device while another model's ready tail waits.
        """
        keys = set(self._pending) | set(self._inflight)
        failed = set()
        while True:
            ready = [k for k in keys
                     if k not in failed and self._pending.get(k)]
            if not ready:
                break
            for key in self._one_per_model(ready):
                if not self._pending.get(key):
                    continue
                try:
                    self._dispatch(key)
                except KeyboardInterrupt:
                    raise
                except Exception as e:  # noqa: BLE001 — fault-barrier: the corpus-flush arm of the per-video isolation point — a tail batch holds rows of whichever videos' slots it packed, so letting one bucket's failure escape would fail every other bucket's (healthy) pending videos with the wrong cause; victims resolve via drain_incomplete with this cause
                    self._record_flush_failure(key, e)
                    failed.add(key)
        for key in sorted(keys - failed, key=str):
            try:
                self._scatter_inflight(key)
            except KeyboardInterrupt:
                raise
            except Exception as e:  # noqa: BLE001 — fault-barrier: the scatter arm of the corpus flush — same per-bucket containment as the dispatch arm above
                self._record_flush_failure(key, e)

    def _record_flush_failure(self, key: tuple, e: BaseException) -> None:
        msg = f"corpus flush of bucket {self._bucket_name(key)} failed: {e}"
        self.flush_errors.setdefault(key, []).append(msg)
        print(f"[pack] {msg}; its videos will be failed (retryable)",
              file=sys.stderr)

    # --- results -------------------------------------------------------------

    def pop_completed(self, model: Optional[str] = None
                      ) -> List[FeatureAssembly]:
        """Assemblies whose stream finished AND whose every row has landed.

        ``model`` scopes the pop to one registered model's videos (each
        multi-model session finalizes with its OWN spec); the single-spec
        default None matches everything a single-spec packer holds."""
        done = [a for a in self._finished
                if a.complete and self._video_model.get(a.video) == model]
        if done:
            popped = set(map(id, done))
            self._finished = [a for a in self._finished
                              if id(a) not in popped]
        return done

    def drain_incomplete(self, model: Optional[str] = None
                         ) -> List[FeatureAssembly]:
        """Finished-stream videos still missing rows after :meth:`flush` —
        their slots were lost to a co-packed batch's device failure; the run
        loop fails them explicitly so they land in the failure manifest.
        ``model`` scopes the drain exactly like :meth:`pop_completed`."""
        out = [a for a in self._finished
               if not a.complete and self._video_model.get(a.video) == model]
        drained = set(map(id, out))
        self._finished = [a for a in self._finished if id(a) not in drained]
        return out

    def clear_flush_causes(self) -> None:
        """Reset recorded flush failures once their victims were attributed.

        A long-lived packer (the serving daemon) must not blame a video that
        joins a bucket *tomorrow* with a flush failure that already failed
        its victims today."""
        self.flush_errors.clear()

    def has_pending(self) -> bool:
        """True while any slot is queued or any dispatched batch is unfetched
        — the daemon's 'an idle flush would do work' signal."""
        return (any(self._pending.values())
                or any(self._inflight.values()))

    def flush_causes(self, path: str) -> List[str]:
        """Flush-failure messages (anti-starvation or corpus-end) for the
        buckets ``path``'s slots were queued under — a drained victim is
        blamed only with its own buckets' causes, never a co-resident
        healthy bucket's."""
        keys = self._video_keys.get(path, ())
        return [msg for key in sorted(keys, key=str)
                for msg in self.flush_errors.get(key, [])]

    @property
    def occupancy(self) -> float:
        """Real clips / dispatched device slots (1.0 = no padding dispatched)."""
        if not self.dispatched_slots:
            return 0.0
        return self.real_slots / self.dispatched_slots

    @property
    def stale_flushes(self) -> int:
        # list() snapshots atomically (C-level, under the GIL): the serve
        # socket's stats op reads this from the API thread while the daemon
        # thread registers new buckets — Python-level iteration over the
        # live dict could raise "changed size during iteration"
        return sum(s["stale_flushes"] for s in list(self._bucket_stats.values()))

    def bucket_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-shape-key occupancy accounting (JSON-friendly keys).

        Safe to call from the serve socket's API thread concurrently with
        the packing thread: both dict levels are snapshotted with atomic
        C-level copies before any Python-level iteration.
        """
        out: Dict[str, Dict[str, float]] = {}
        for key, live in sorted(dict(self._bucket_stats).items(), key=str):
            s = dict(live)
            out[self._bucket_name(key)] = {
                "real_slots": s["real_slots"],
                "dispatched_slots": s["dispatched_slots"],
                "occupancy": round(
                    s["real_slots"] / s["dispatched_slots"], 4)
                if s["dispatched_slots"] else 0.0,
                "stale_flushes": s["stale_flushes"],
                "pages_dispatched": s.get("pages_dispatched", 0),
            }
        return out

    def model_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-model occupancy rollup of :meth:`bucket_stats` (the serve
        stats op's ``packing.models`` section — operators watch one model's
        queue starving without decoding bucket names). Same atomic-snapshot
        discipline: safe from the API thread."""
        agg: Dict[str, Dict[str, int]] = {}
        for key, live in sorted(dict(self._bucket_stats).items(), key=str):
            s = dict(live)
            name = key[0] if key[0] is not None else "default"
            a = agg.setdefault(name, {"real_slots": 0, "dispatched_slots": 0,
                                      "stale_flushes": 0})
            a["real_slots"] += s["real_slots"]
            a["dispatched_slots"] += s["dispatched_slots"]
            a["stale_flushes"] += s["stale_flushes"]
        return {
            name: {**a, "occupancy":
                   round(a["real_slots"] / a["dispatched_slots"], 4)
                   if a["dispatched_slots"] else 0.0}
            for name, a in agg.items()
        }
