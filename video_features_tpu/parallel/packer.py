"""Cross-video clip packing: a corpus-level continuous-batching scheduler.

The per-video loop (:meth:`..extractors.base.Extractor._run_loop`) pays a
zero-padded tail batch per video (``pad_batch``) and drains the mesh between
videos — on a corpus of short clips a large fraction of device steps are
padding or idle. Fixed-shape continuous batching is the standard TPU answer
to ragged workloads (Ragged Paged Attention, arXiv:2604.15464), and
decoupling producers from fixed-shape device batches is the Podracer recipe
(arXiv:2104.06272): here, decoded clips stream into **shape-keyed slot
queues** and every dispatched ``(batch_size, …)`` device batch is filled with
clips from however many videos are ready — the tail of video N packs with the
head of video N+1. Per-clip results scatter back to per-video assembly
buffers (:class:`..io.output.FeatureAssembly`) that the run loop flushes
through the output writer as each video's last clip lands.

Threading model — deliberately single-threaded: the packed run loop (one
consumer) pulls each video's clip stream in corpus order and calls
:meth:`CorpusPacker.add`; decode parallelism comes from the
``DecodePrefetcher`` worker threads *upstream* of the clip stream. Every
cross-thread store therefore stays inside the already-declared
``parallel/pipeline.py`` / ``io/output.py`` seams (vftlint
``thread-shared-state``), and the packer itself needs no locks.

Fault attribution is slot-level, not batch-level: a poisoned clip stream
fails only its contributing video. Slots reference their attempt's assembly
object directly (not the video path), so a retry opens a fresh assembly and
stale in-flight rows from the failed attempt land in the orphaned object and
die with it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..io.output import FeatureAssembly


@dataclass
class PackSpec:
    """How one model plugs into the corpus packer (``Extractor.pack_spec``).

    ``open_clips(path)`` returns ``(info, clip_iter)``: a mutable per-video
    info dict the stream fills as it decodes (fps, timestamps) and an iterator
    of fixed-shape uint8 clip arrays — one device-batch *slot* each. Clips of
    equal shape co-pack; a mixed-geometry corpus fills one queue per shape.

    ``step(batch)`` runs the model's existing jitted device step on a full
    host batch ``(batch_size, *clip_shape)`` and returns the per-slot device
    features; the packer fetches them through the extractor's device_wait-
    accounted ``_wait``. ``finalize(path, rows, info)`` assembles the video's
    output dict from the in-order ``(n_clips, *row)`` host feature array.

    ``empty_row_shape`` shapes the zero-clip video output (e.g. ``(2048,)``
    for ResNet-50), matching the per-video loop's empty result.
    """

    batch_size: int
    empty_row_shape: Tuple[int, ...]
    open_clips: Callable[[str], Tuple[dict, Iterator[np.ndarray]]]
    step: Callable[[np.ndarray], Any]
    finalize: Callable[[str, np.ndarray, dict], Dict[str, np.ndarray]]


class _Slot:
    """One occupied device-batch slot: a clip and where its row scatters."""

    __slots__ = ("assembly", "idx", "clip")

    def __init__(self, assembly: FeatureAssembly, idx: int, clip: np.ndarray):
        self.assembly = assembly
        self.idx = idx
        self.clip = clip


class CorpusPacker:
    """Shape-keyed continuous batching across videos.

    One dispatched batch is kept in flight: batch *k*'s results are fetched
    (and scattered) only when batch *k+1* dispatches or at :meth:`flush`, so
    host decode/stacking of the next batch overlaps device compute of the
    current one — the packed loop's analogue of the per-video loop's
    prefetch + ``_throttle`` backpressure (at most one unfetched batch).
    """

    def __init__(self, spec: PackSpec, wait: Callable[[Any], np.ndarray],
                 clock=None):
        self._spec = spec
        self._wait = wait
        self._clock = clock  # optional StageClock: packed_slots/packed_clips units
        self._pending: Dict[tuple, List[_Slot]] = {}
        self._open: Dict[str, FeatureAssembly] = {}
        self._finished: List[FeatureAssembly] = []
        self._inflight: Optional[Tuple[List[_Slot], Any]] = None
        self.real_slots = 0  # clips dispatched
        self.dispatched_slots = 0  # clips + zero padding dispatched
        self.video_clips: Dict[str, int] = {}  # per finished video

    # --- per-video lifecycle -------------------------------------------------

    def begin(self, path: str, info: dict) -> None:
        """Open a fresh attempt for ``path`` (replacing any failed prior one)."""
        self.discard(path)
        self._open[path] = FeatureAssembly(path, info)

    def add(self, path: str, clip: np.ndarray) -> None:
        """Queue one clip; dispatches a device batch when its shape queue fills."""
        asm = self._open[path]
        slot = _Slot(asm, asm.reserve(), clip)
        queue = self._pending.setdefault(clip.shape, [])
        queue.append(slot)
        if len(queue) >= self._spec.batch_size:
            self._dispatch(clip.shape)

    def finish(self, path: str) -> None:
        """Mark ``path``'s stream complete; it finalizes once all rows land."""
        asm = self._open.pop(path)
        asm.finish()
        self.video_clips[path] = asm.expected or 0
        self._finished.append(asm)

    def discard(self, path: str) -> None:
        """Drop every trace of ``path``'s current attempt (failure/retry).

        Pending slots are unlinked; slots already dispatched (including the
        in-flight batch) still hold the dead attempt's assembly and scatter
        harmlessly into it — slot-level attribution needs no batch rollback.
        """
        asm = self._open.pop(path, None)
        self.video_clips.pop(path, None)
        self._finished = [a for a in self._finished if a.video != path]
        if asm is None:
            return
        for queue in self._pending.values():
            queue[:] = [s for s in queue if s.assembly is not asm]

    # --- dispatch ------------------------------------------------------------

    def _dispatch(self, shape: tuple) -> None:
        from ..extractors.base import pad_batch  # runtime: avoids an import cycle

        queue = self._pending[shape]
        batch_size = self._spec.batch_size
        slots = queue[:batch_size]
        del queue[:batch_size]  # in place: flush() iterates this same list
        batch = pad_batch(np.stack([s.clip for s in slots]), batch_size)
        self._scatter_inflight()  # resolve batch k before dispatching k+1
        out = self._spec.step(batch)
        self._inflight = (slots, out)
        self.real_slots += len(slots)
        self.dispatched_slots += batch_size
        if self._clock is not None:
            self._clock.add_units("packed_slots", batch_size)
            self._clock.add_units("packed_clips", len(slots))

    def _scatter_inflight(self) -> None:
        if self._inflight is None:
            return
        slots, out = self._inflight
        self._inflight = None
        host = self._wait(out)
        for i, slot in enumerate(slots):
            slot.assembly.put(slot.idx, host[i])

    def flush(self) -> None:
        """Dispatch every partial shape queue (zero-padded) and resolve in-flight."""
        for shape, queue in list(self._pending.items()):
            while queue:
                self._dispatch(shape)
        self._scatter_inflight()

    # --- results -------------------------------------------------------------

    def pop_completed(self) -> List[FeatureAssembly]:
        """Assemblies whose stream finished AND whose every row has landed."""
        done = [a for a in self._finished if a.complete]
        if done:
            self._finished = [a for a in self._finished if not a.complete]
        return done

    def drain_incomplete(self) -> List[FeatureAssembly]:
        """Finished-stream videos still missing rows after :meth:`flush` —
        their slots were lost to a co-packed batch's device failure; the run
        loop fails them explicitly so they land in the failure manifest."""
        out = [a for a in self._finished if not a.complete]
        self._finished = [a for a in self._finished if a.complete]
        return out

    @property
    def occupancy(self) -> float:
        """Real clips / dispatched device slots (1.0 = no padding dispatched)."""
        if not self.dispatched_slots:
            return 0.0
        return self.real_slots / self.dispatched_slots
