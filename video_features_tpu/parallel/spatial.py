"""Spatially-sharded convolution: halo exchange over the mesh with ppermute.

The reference never scales BEYOND one GPU per video — a frame too large for one
device's memory is simply unsupported. The TPU-native answer is model-axis
sharding: split the image's H axis across the mesh, keep every conv local, and
exchange only the kernel-halo rows with mesh neighbors over ICI
(``lax.ppermute`` inside ``shard_map``). This module provides the building
block and a reference composition; conv-stack models (ResNet stem, I3D) can be
laid over it when frames outgrow HBM (e.g. 8K video dense flow).

Semantics: an unsharded stride-1 SAME convolution. Boundary devices receive
zeros from ``ppermute`` (devices without a send partner), which is exactly SAME
zero padding at the image border. Tests assert numerical equality (1e-5)
against the unsharded op on the virtual 8-device CPU mesh — not bitwise: the
halo path lowers as a VALID-on-H conv, so XLA may reduce in a different order
(tests/test_spatial.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # moved out of experimental in newer JAX
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from .mesh import DATA_AXIS


def _halo_pad_rows(x: jnp.ndarray, halo: int, n_dev: int) -> jnp.ndarray:
    """Pad the local H shard with ``halo`` rows from each mesh neighbor.

    ``x``: (N, H_local, W, C) per-device block. Edge devices get zero rows —
    ppermute delivers zeros to devices no one sends to — matching the SAME
    zero-pad of the unsharded op.
    """
    if halo == 0 or n_dev == 1:
        pad = ((0, 0), (halo, halo), (0, 0), (0, 0))
        return jnp.pad(x, pad) if halo else x
    # rows flowing "down" (device i → i+1): my top halo comes from above
    from_above = lax.ppermute(
        x[:, -halo:], DATA_AXIS, [(i, i + 1) for i in range(n_dev - 1)]
    )
    # rows flowing "up" (device i → i-1): my bottom halo comes from below
    from_below = lax.ppermute(
        x[:, :halo], DATA_AXIS, [(i + 1, i) for i in range(n_dev - 1)]
    )
    return jnp.concatenate([from_above, x, from_below], axis=1)


def sharded_same_conv2d(mesh: Mesh, x: jnp.ndarray, kernel: jnp.ndarray) -> jnp.ndarray:
    """Stride-1 SAME conv2d with the H axis sharded across the mesh.

    ``x``: (N, H, W, C) NHWC with H divisible by the mesh size and per-device
    H ≥ the halo (kh // 2). ``kernel``: (kh, kw, C, O) HWIO, odd kh/kw.
    Output matches ``lax.conv_general_dilated(..., padding='SAME')`` exactly.
    """
    kh, kw = kernel.shape[0], kernel.shape[1]
    if kh % 2 == 0 or kw % 2 == 0:
        raise ValueError(f"odd kernel sizes required, got {(kh, kw)}")
    n_dev = mesh.devices.size
    halo = kh // 2
    if (x.shape[1] // n_dev) < halo:
        raise ValueError(
            f"per-device H {x.shape[1] // n_dev} smaller than halo {halo}; "
            f"use fewer devices or larger inputs"
        )

    def local(xb, k):
        xb = _halo_pad_rows(xb, halo, n_dev)
        # halo rows replace SAME padding on H (VALID there); SAME on W
        return lax.conv_general_dilated(
            xb, k, (1, 1),
            padding=((0, 0), (kw // 2, kw // 2)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )

    fn = shard_map(
        local, mesh=mesh,
        in_specs=(P(None, DATA_AXIS), P()),
        out_specs=P(None, DATA_AXIS),
    )
    return fn(x, kernel)


def shard_spatial(mesh: Mesh) -> NamedSharding:
    """NamedSharding splitting axis 1 (H of NHWC) across the mesh."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def sharded_conv_stack(mesh: Mesh, x: jnp.ndarray, kernels) -> jnp.ndarray:
    """ReLU conv chain, H-sharded end to end — activations never gather.

    Demonstrates the composition property: each layer halo-exchanges only its
    own kernel radius; intermediate activations stay sharded on device.
    """
    y = jax.device_put(x, shard_spatial(mesh))
    for k in kernels:
        y = sharded_same_conv2d(mesh, y, k)
        y = jnp.maximum(y, 0.0)
    return y
