"""Host→device feeding: async prefetch and multi-host work sharding.

Replaces the reference's synchronous per-stack ``.to(device)`` copies
(``/root/reference/models/i3d/extract_i3d.py:140``) with double-buffered
``device_put``: while the device chews on batch *k*, the host decodes and transfers
batch *k+1*. Dispatch in JAX is async already; the prefetcher simply keeps a bounded
queue of in-flight device buffers so decode, PCIe/ICI transfer, and compute overlap.

Multi-host: the reference shards work across *jobs* by splitting file lists
(``gen_file_list.py:6-21``). Here each process takes a deterministic round-robin
shard of the video list — same semantics, no coordinator, resumable per host.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..reliability import fault_point


def maybe_initialize_distributed() -> bool:
    """Join a multi-host JAX job when one is configured; no-op otherwise.

    The reference has no multi-host story beyond manually split file lists
    (``gen_file_list.py``); the TPU runtime's DCN mechanism is
    ``jax.distributed.initialize`` (SURVEY.md §2.3/§5). Trigger: ``VFT_MULTIHOST=1``
    (values from the standard JAX env vars / TPU metadata) or an explicit
    coordinator address in ``JAX_COORDINATOR_ADDRESS``. Must run before the first
    device access. Returns True when running multi-process.
    """
    # NB: must not touch jax.process_count()/jax.devices() before deciding —
    # any backend-initializing call makes a later jax.distributed.initialize()
    # raise. Detect an already-initialized service via the distributed client.
    try:
        from jax._src import distributed  # noqa: PLC2701 — no public probe exists

        already = distributed.global_state.client is not None
    except Exception:  # fault-barrier: private-API probe; absence means "not initialized"
        already = False
    if already:
        return jax.process_count() > 1
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if os.environ.get("VFT_MULTIHOST") == "1" or coord:
        # On TPU pods initialize() self-configures from the metadata service;
        # elsewhere (and in the loopback test) the standard JAX env vars name
        # the job shape, but this jax version only auto-reads them for known
        # cluster environments — pass them through explicitly when set.
        kwargs = {}
        if coord:
            kwargs["coordinator_address"] = coord
        n_proc = os.environ.get("JAX_NUM_PROCESSES")
        proc_id = os.environ.get("JAX_PROCESS_ID")
        if bool(n_proc) != bool(proc_id):
            # a half-specified pair makes initialize() fail or hang with no
            # hint at the cause; fail fast with the fix instead
            raise RuntimeError(
                "JAX_NUM_PROCESSES and JAX_PROCESS_ID must be set together "
                f"(got JAX_NUM_PROCESSES={n_proc!r}, JAX_PROCESS_ID={proc_id!r})"
            )
        if n_proc:
            kwargs["num_processes"] = int(n_proc)
            kwargs["process_id"] = int(proc_id)
        jax.distributed.initialize(**kwargs)
        return jax.process_count() > 1
    return False


def shard_video_list(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """Round-robin shard of ``paths`` owned by this process (DCN axis).

    Round-robin (not contiguous) matches ``gen_file_list.py`` and balances mixed
    video lengths across hosts.
    """
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return list(paths[process_index::process_count])


def _item_bytes(item) -> int:
    """Approximate host bytes of one queued frame item (``(rgb, pos)``)."""
    if isinstance(item, tuple):
        return sum(int(getattr(x, "nbytes", 0)) for x in item)
    return int(getattr(item, "nbytes", 0))


class DecodePrefetcher:
    """Cross-video decode parallelism: background threads decode upcoming
    videos while the device chews on the current one.

    The reference gets decode parallelism implicitly — one Python thread per
    GPU, each running its own decode loop (``/root/reference/main.py:43-47``).
    The SPMD design centralizes devices behind one process, so when decode is
    slower than compute (the common case: one cv2 stream decodes a few hundred
    fps, the mesh consumes thousands), extra decode streams must be explicit.
    cv2/ffmpeg/PIL release the GIL in their C cores, so threads parallelize.

    ``open_fn(path) -> (meta, frames_iter)``; each worker drains one video's
    iterator into a bounded queue, and :meth:`get` hands back
    ``(meta, iterator)`` draining that queue. The buffer is bounded TWICE and
    the tighter bound governs: ``max_buffered`` caps the frame COUNT (the
    right bound for small frames, where per-item overhead dominates) and
    ``max_buffered_bytes`` caps the payload BYTES — without it a mixed
    corpus's 1080p videos (~6 MB/frame) could pin ``workers × 512`` frames
    ≈ tens of GB of host RAM under the count bound alone. Paths are
    scheduled by the run loop at most ``workers`` ahead of the consume cursor,
    so the totals stay ≤ workers · bound. Decode errors are re-raised at
    consume time — the per-video fault barrier sees them exactly as inline
    decode would.
    """

    _DONE = object()

    def __init__(self, open_fn: Callable, workers: int, max_buffered: int = 512,
                 max_buffered_bytes: int = 512 << 20, journal=None):
        if workers < 1:
            raise ValueError("decode workers must be >= 1")
        self._open = open_fn
        self._max = max_buffered
        self._max_bytes = max_buffered_bytes
        # optional ..obs.SpanJournal: each worker wraps its video in a
        # 'decode' span (emit is a non-blocking queue put — thread-safe and
        # never the decode path's problem). The span covers the worker's full
        # occupancy of a decode slot: open + frame production, INCLUDING time
        # blocked on a full buffer (consumer backpressure) — it answers "what
        # was this decode slot doing", not "how fast is cv2".
        self._journal = journal
        self._slots: dict = {}  # scheduled, not yet consumed
        self._handed: dict = {}  # handed to a consumer via get(), not released
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sem = threading.Semaphore(workers)
        # live resize (serve/autoscale.py): permits added via release(),
        # removed by non-blocking acquires — shortfall becomes _debt that
        # finishing workers absorb instead of re-releasing their permit
        self._workers = workers
        self._resize_lock = threading.Lock()
        self._debt = 0
        # segmented intra-video decode (io/video.py plan_segments): a long
        # video may occupy several permits, one per segment worker. Extras
        # beyond the video's baseline permit are reserved NON-blockingly at
        # schedule time under the invariant extras ≤ free − pending_baselines,
        # so a segment can never consume the permit an already-scheduled
        # video's baseline worker is entitled to (that blocking acquire is
        # today's liveness guarantee and stays untouched).
        self._planner = None  # optional (path, max_segments) -> SegmentPlan
        self._segment_open = None  # optional (plan, index) -> frames iter
        self._busy = 0  # permits acquired or reserved
        self._pending_baselines = 0  # scheduled slots whose worker has not acquired yet
        self._videos_segmented = 0  # videos decoded as >1 segment (stats)
        self._segments_decoded = 0  # segment workers finished clean (stats)

    @property
    def workers(self) -> int:
        """Current concurrency target (also the run loops' schedule window)."""
        return self._workers

    def set_opener(self, open_fn: Callable) -> None:
        """Replace the per-path decode callable.

        The multi-model serving layer (``extractors/base.py``) shares ONE
        pool across co-resident models and reroutes it through a path→model
        router so each scheduled video decodes with its own model's host
        transform. Must be called before any :meth:`schedule` whose decode
        should route — workers read the opener at decode start."""
        self._open = open_fn

    def set_segmenter(self, planner: Callable, open_segment: Callable) -> None:
        """Enable segmented intra-video decode through this pool.

        ``planner(path, max_segments) -> SegmentPlan | None`` decides whether
        (and how finely) to split a video — None means decode sequentially.
        ``open_segment(plan, index) -> frames_iter`` decodes one segment
        (``io.video.open_video_segment`` with the extractor's transform).
        Like :meth:`set_opener`, the multi-model layer reroutes both per path.
        """
        self._planner = planner
        self._segment_open = open_segment

    def spare_permits(self) -> int:
        """Permits neither held by a worker nor owed to a scheduled video.

        This is the headroom segmentation may consume, and the signal the
        autoscaler reads to prefer segmenting the current video over growing
        the pool (idle permits mean width is not the bottleneck).
        """
        with self._resize_lock:
            return max(0, self._workers - self._busy - self._pending_baselines)

    def segment_stats(self) -> Tuple[int, int]:
        """(videos decoded segmented, segment workers completed clean)."""
        with self._resize_lock:
            return self._videos_segmented, self._segments_decoded

    def resize(self, workers: int) -> None:
        """Grow or shrink the concurrent-decode budget without a restart.

        Growing releases permits immediately; shrinking takes free permits
        now and records the remainder as debt consumed as busy workers
        finish (a mid-decode video is never cancelled by a shrink).
        """
        if workers < 1:
            raise ValueError("decode workers must be >= 1")
        with self._resize_lock:
            delta = workers - self._workers
            self._workers = workers
            if delta > 0:
                for _ in range(delta):
                    if self._debt:
                        self._debt -= 1
                    else:
                        self._sem.release()
            else:
                for _ in range(-delta):
                    if not self._sem.acquire(blocking=False):
                        self._debt += 1

    def _release_permit(self) -> None:
        with self._resize_lock:
            self._busy -= 1
            if self._debt:
                self._debt -= 1
            else:
                self._sem.release()

    def _acquire_baseline(self) -> None:
        """Blocking acquire of a scheduled video's one guaranteed permit."""
        self._sem.acquire()  # at most `workers` decode streams concurrently
        with self._resize_lock:
            self._busy += 1
            self._pending_baselines -= 1

    def _reserve_permits(self, want: int) -> int:
        """Non-blockingly reserve up to ``want`` SPARE permits for segments.

        Never takes a permit a pending baseline worker is entitled to — a
        segmented video only forms when the WHOLE split (all k workers) fits
        in genuinely idle headroom, so every earlier-scheduled video keeps
        its one-permit entitlement by counting and the consumer draining
        videos in schedule order can always make progress (deadlock-free:
        permit holders are only ever workers of videos at or before the
        consumer's cursor, or of videos some independent loop is draining).
        """
        got = 0
        with self._resize_lock:
            spare = self._workers - self._busy - self._pending_baselines
            while got < min(want, max(0, spare)):
                if not self._sem.acquire(blocking=False):
                    break
                got += 1
            self._busy += got
        return got

    def _new_slot(self, maxsize: int, max_bytes: int) -> dict:
        slot = {
            "q": queue.Queue(maxsize=maxsize),
            "meta": None,
            "err": None,
            "bytes": 0,  # buffered payload bytes (max_buffered_bytes bound)
            # per-slot share of the byte budget: a segmented video's k slots
            # split the video's budget so its TOTAL buffered payload honors
            # the same bound as an unsegmented decode
            "max_bytes": max_bytes,
            # guards the bytes counter (vftlint GUARDED_BY: slot['bytes']
            # under the 'slot' lock)
            "lock": threading.Lock(),
            "ready": threading.Event(),
            "stop": threading.Event(),  # per-video cancel (release())
        }
        return slot

    @staticmethod
    def _group_slots(slot: dict) -> List[dict]:
        """The per-queue slots behind one scheduled path (1 or k segments)."""
        return slot["segments"] if "segments" in slot else [slot]

    def schedule(self, path: str) -> None:
        """Start decoding ``path`` in the background (no-op if scheduled).

        When a segmenter is installed (:meth:`set_segmenter`) and spare
        permits exist, the video may be split into seek-aligned segments
        decoded concurrently — planning runs on the calling thread (header
        probe only) and any planner failure falls back to sequential decode:
        scheduling never raises, the real open classifies bad containers.
        """
        if path in self._slots or path in self._handed or self._stop.is_set():
            return
        self._threads = [t for t in self._threads if t.is_alive()]
        plan = self._plan_for(path)
        if plan is not None and self._schedule_segments(path, plan):
            return
        self._schedule_single(path)

    def _schedule_single(self, path: str) -> None:
        slot = self._new_slot(self._max, self._max_bytes)
        self._slots[path] = slot
        with self._resize_lock:
            self._pending_baselines += 1
        t = threading.Thread(
            target=self._pump,
            args=(path, slot, lambda: self._open(path), False, None, None),
            daemon=True)
        self._threads.append(t)
        t.start()

    def _plan_for(self, path: str):
        if self._planner is None or self._segment_open is None:
            return None
        with self._resize_lock:
            spare = self._workers - self._busy - self._pending_baselines
        if spare < 2:
            return None  # a split needs at least two wholly-idle permits
        try:
            plan = self._planner(path, spare)
        except Exception:  # noqa: BLE001 — fault-barrier: planning must never fail a video
            return None
        if plan is None or len(plan.bounds) < 2:
            return None
        return plan

    def _schedule_segments(self, path: str, plan) -> bool:
        # every segment worker's permit — INCLUDING segment 0's — is secured
        # up front: a segmented video must never block on the baseline
        # semaphore while its own sibling segments hold permits waiting for
        # the consumer to reach them (that cycle is a deadlock)
        got = self._reserve_permits(len(plan.bounds))
        if got < 2:
            for _ in range(got):
                self._release_permit()
            return False  # the headroom evaporated since planning
        if got < len(plan.bounds):
            plan = plan.narrow(got)
            if plan is None or len(plan.bounds) < 2 or len(plan.bounds) > got:
                for _ in range(got):
                    self._release_permit()
                return False
            for _ in range(got - len(plan.bounds)):
                self._release_permit()
                got -= 1
        k = len(plan.bounds)
        subs = [self._new_slot(max(1, self._max // k),
                               max(1, self._max_bytes // k)) for _ in range(k)]
        group = {"segments": subs, "meta": plan.meta, "plan": plan}
        self._slots[path] = group
        with self._resize_lock:
            self._videos_segmented += 1  # stats counter (segment_stats)
        for j, sub in enumerate(subs):
            t = threading.Thread(
                target=self._pump,
                args=(path, sub,
                      (lambda p=plan, i=j: (p.meta, self._segment_open(p, i))),
                      True, j, k),
                daemon=True)
            self._threads.append(t)
            t.start()
        return True

    def _pump(self, path: str, slot: dict, produce: Callable, reserved: bool,
              segment: Optional[int], segments: Optional[int]) -> None:
        """Worker body shared by whole-video and segment decode streams.

        ``produce() -> (meta, frames_iter)``; ``reserved`` workers arrived
        with a permit pre-reserved at schedule time (segmented videos secure
        every segment's permit up front), others perform the normal blocking
        baseline acquire. ``segment``/``segments`` tag a segment stream's
        journal span and completion counter.
        """

        def stopped() -> bool:
            return self._stop.is_set() or slot["stop"].is_set()

        if not reserved:
            self._acquire_baseline()
        # journal 'decode' span: full occupancy of this decode slot
        sid = None
        if self._journal is not None:
            if segment is None:
                sid = self._journal.begin("decode", video=path)
            else:
                sid = self._journal.begin("decode", video=path,
                                          segment=segment, segments=segments)
        clean = False
        try:
            try:
                if stopped():
                    return
                # crash-injection seam: a worker dying HERE (not inside
                # open_fn) must still surface a classified error at consume
                # time instead of deadlocking the drain — tests prove it
                fault_point("pool_worker", path)
                meta, frames = produce()
                slot["meta"] = meta  # thread-shared-state: published by the ready Event set below
                slot["ready"].set()
                for item in frames:
                    nbytes = _item_bytes(item)
                    # byte bound: wait for buffered-payload room (the frame
                    # COUNT bound is the queue's maxsize below; the tighter
                    # of the two governs). An empty buffer always admits one
                    # item, so a single frame larger than the cap still flows.
                    while not stopped():
                        with slot["lock"]:
                            fits = (slot["bytes"] == 0
                                    or slot["bytes"] + nbytes <= slot["max_bytes"])
                        if fits:
                            break
                        time.sleep(0.05)
                    if stopped():
                        return
                    while not stopped():
                        try:
                            slot["q"].put(item, timeout=0.2)
                            with slot["lock"]:
                                slot["bytes"] += nbytes  # thread-shared-state: guarded by slot['lock'] (consumer decrements under the same lock)
                            break
                        except queue.Full:
                            continue
                    if stopped():
                        return
                clean = not stopped()
            except Exception as e:  # noqa: BLE001 — fault-barrier: re-raised classified at consume time
                slot["err"] = e  # thread-shared-state: published by the ready Event / _DONE sentinel in finally
            finally:
                slot["ready"].set()
                while not stopped():
                    try:
                        slot["q"].put(self._DONE, timeout=0.2)
                        break
                    except queue.Full:  # consumer will drain; retry
                        continue
        finally:
            if sid is not None:
                if segment is None:
                    self._journal.end("decode", sid, video=path)
                else:
                    self._journal.end("decode", sid, video=path,
                                      segment=segment, segments=segments)
            if clean and segment is not None:
                with self._resize_lock:
                    self._segments_decoded += 1  # thread-shared-state: guarded by the 'resize' lock (stats counter, segment_stats reads under it)
            # a shrink may have pre-claimed this permit as debt; the helper
            # settles debt before returning the permit to the pool
            self._release_permit()

    def get(self, path: str):
        """(meta, frames_iter) for ``path`` — prefetched if scheduled, else
        decoded inline. Pair every get() with :meth:`release` (the run loop
        does this in its per-video ``finally``): an abandoned iterator — e.g.
        the per-video fault barrier caught a compute error mid-drain — would
        otherwise pin its worker thread and semaphore permit forever.
        """
        slot = self._slots.pop(path, None)
        if slot is None:
            return self._open(path)
        self._handed[path] = slot
        if "segments" in slot:
            # segmented video: in-order reassembly — stream segment j's queue
            # to the consumer while segments j+1..k-1 keep decoding into
            # theirs. A poisoned segment's error surfaces mid-generator,
            # exactly where a sequential decode error would.
            def reassemble() -> Iterator[Tuple[np.ndarray, float]]:
                for sub in slot["segments"]:
                    for item in self._drain(sub):
                        yield item

            return slot["meta"], reassemble()
        slot["ready"].wait()
        if slot["err"] is not None and slot["meta"] is None:
            raise slot["err"]
        return slot["meta"], self._drain(slot)

    def _drain(self, slot: dict) -> Iterator[Tuple[np.ndarray, float]]:
        while True:
            try:
                item = slot["q"].get(timeout=0.2)
            except queue.Empty:
                # release()/shutdown() with a full queue can drop their
                # _DONE sentinel while the stopped worker never enqueues
                # one — without this check a late consumer blocks forever.
                # A stored worker error must still surface on this exit
                # path (the dropped sentinel would otherwise swallow it).
                if slot["stop"].is_set() or self._stop.is_set():
                    if slot["err"] is not None:
                        raise slot["err"]
                    return
                continue
            if item is self._DONE:
                if slot["err"] is not None:
                    raise slot["err"]
                return
            with slot["lock"]:
                # release the byte budget as soon as the item leaves the
                # buffer (once yielded it is the consumer's memory)
                slot["bytes"] -= _item_bytes(item)
            yield item

    def release(self, path: str) -> None:
        """Cancel/forget a video's decode (no-op for finished or unknown ones).

        For a segmented video the cancel fans out to EVERY segment worker —
        each sub-slot gets its stop flag and a drain-unblocking sentinel.
        """
        slot = self._handed.pop(path, None) or self._slots.pop(path, None)
        if slot is None:
            return
        for sub in self._group_slots(slot):
            sub["stop"].set()
            try:  # a consumer mid-drain must not hang on an exiting worker
                sub["q"].put_nowait(self._DONE)
            except queue.Full:
                pass

    def shutdown(self) -> None:
        self._stop.set()
        for slot in list(self._slots.values()) + list(self._handed.values()):
            for sub in self._group_slots(slot):
                try:  # unblock any drain() consumers
                    sub["q"].put_nowait(self._DONE)
                except queue.Full:
                    pass  # consumer has items to drain before it can block
        for t in self._threads:
            t.join(timeout=2.0)
        self._slots.clear()
        self._handed.clear()


class HostStagingRing:
    """Reusable host staging buffers for ``device_put`` sources.

    Every frame-path device batch used to be assembled into a FRESH
    ``np.stack(...)`` (historically ``.astype(np.float32)``) allocation —
    per-batch host memory churn on exactly the hot path where the uint8 wire
    format just quartered the bytes. The ring hands out a small per-geometry
    set of preallocated buffers instead: callers :meth:`acquire` a
    ``(shape, dtype)`` buffer, fill it in place, ``device_put`` it, and
    :meth:`commit` it back with the resulting device value.

    Discipline (the ``AsyncOutputWriter``'s bounded-ring idea applied to H2D
    staging): a buffer is never rewritten while its ``device_put`` may still
    be reading it. JAX transfers are asynchronous — the sharded CPU path
    copies lazily and TPU DMA reads the host buffer after dispatch returns —
    so :meth:`acquire` blocks on the committed device value's
    ``block_until_ready`` before handing the same buffer out again. That wait
    is the transfer pipe's backpressure and is surfaced through ``on_wait``
    (the extractors attribute it to the 'transfer' stage).

    Single-threaded by design: acquire/fill/commit all run on the run-loop
    thread (like the corpus packer), so the ring needs no locks and vftlint's
    thread-shared-state table gains no entries. Slots never leave their ring
    — a dispatch failure between acquire and commit just leaves the slot's
    previous (already-awaited) device value cleared, so error paths cannot
    leak buffers.

    Memory bound: at most ``max_geometries`` per-geometry rings are kept —
    acquiring a new geometry past the cap evicts the least-recently-acquired
    ring (its pending transfers awaited first), so a long-lived caller (the
    ``--serve`` daemon staging an open-ended mix of video geometries, or
    ``--device_resize`` shipping native-resolution frames) holds at most
    ``max_geometries × depth`` buffers instead of growing forever — the ring
    analogue of ``packer.forget``'s long-run bound. A corpus cycling through
    more concurrent geometries than the cap just re-allocates for the
    evicted ones (correctness unaffected). ``DEFAULT_MAX_GEOMETRIES`` is the
    single-model budget; a multi-model daemon (``--serve_models``) scales it
    by the loaded model count, since each co-resident model brings its own
    working set of batch geometries and would otherwise thrash the shared
    ring's eviction.
    """

    DEFAULT_MAX_GEOMETRIES = 8

    def __init__(self, depth: int = 3, on_wait: Optional[Callable] = None,
                 max_geometries: int = DEFAULT_MAX_GEOMETRIES):
        if depth < 1:
            raise ValueError("staging ring depth must be >= 1")
        if max_geometries < 1:
            raise ValueError("staging ring max_geometries must be >= 1")
        self._depth = depth
        self._on_wait = on_wait
        self._max_geometries = max_geometries
        # (shape, dtype-str) -> deque of {"buf", "dev"} slots, oldest first
        self._rings: dict = {}
        self._last_acquire: dict = {}  # key -> tick of last acquire (LRU)
        self._tick = 0
        self.allocated = 0  # buffers ever allocated (reuse observability)
        self.acquires = 0
        self.evicted_geometries = 0
        self.wait_seconds = 0.0  # cumulative blocked-on-transfer time

    @staticmethod
    def _key(shape, dtype) -> tuple:
        return (tuple(shape), np.dtype(dtype).str)

    def _await(self, slot: dict) -> None:
        """Block until the slot's committed transfer finished (accounted)."""
        if slot["dev"] is None:
            return
        t0 = time.perf_counter()
        for leaf in jax.tree_util.tree_leaves(slot["dev"]):
            ready = getattr(leaf, "block_until_ready", None)
            if ready is not None:
                ready()
        waited = time.perf_counter() - t0
        self.wait_seconds += waited
        if self._on_wait is not None:
            self._on_wait(waited)
        slot["dev"] = None

    def _evict_lru_geometry(self) -> None:
        key = min(self._rings, key=lambda k: self._last_acquire.get(k, 0))
        for slot in self._rings.pop(key):
            # a pending lazy copy may still read the buffer we are about to
            # drop our last reference to — await it before freeing
            self._await(slot)
        self._last_acquire.pop(key, None)
        self.evicted_geometries += 1

    def acquire(self, shape, dtype) -> np.ndarray:
        """A writable staging buffer of ``(shape, dtype)``.

        Allocates until the ring holds ``depth`` buffers for this geometry,
        then recycles the least-recently-acquired one — blocking first until
        its committed transfer has completed (never rewrite a buffer a
        pending ``device_put`` may still read).
        """
        key = self._key(shape, dtype)
        if key not in self._rings and len(self._rings) >= self._max_geometries:
            self._evict_lru_geometry()  # long-run bound: ≤ cap geometries
        ring = self._rings.setdefault(key, collections.deque())
        self.acquires += 1
        self._tick += 1
        self._last_acquire[key] = self._tick
        if len(ring) < self._depth:
            slot = {"buf": np.empty(shape, dtype), "dev": None}
            self.allocated += 1
        else:
            slot = ring.popleft()
            self._await(slot)
        ring.append(slot)  # stays in the ring: error paths cannot leak it
        return slot["buf"]

    def stage(self, rows, total: Optional[int] = None) -> np.ndarray:
        """Stack equal-shape host ``rows`` into an acquired buffer, zero-
        padded to ``total`` leading entries (default ``len(rows)``) — the one
        shared fill discipline for every batch-staging caller
        (``Extractor._stage_rows``, the packer's default batch assembly).
        Dtype follows the rows: uint8 frames stay uint8 on the wire."""
        n = len(rows)
        if total is None:
            total = n
        buf = self.acquire((total,) + rows[0].shape, rows[0].dtype)
        for i, row in enumerate(rows):
            buf[i] = row
        if n < total:
            buf[n:] = 0
        return buf

    def commit(self, buf: np.ndarray, device_value) -> None:
        """Record ``device_value`` (a jax array or pytree of them) as the
        in-flight transfer reading ``buf``; the slot is not recycled until it
        is ready. A ``buf`` the ring does not own is a no-op — callers may
        pass every dispatched batch through here without tracking which ones
        were ring-staged (e.g. a zero-padded tail batch from ``pad_batch``,
        or the frame-sharded I3D path's (frames, last) view tuples).
        """
        if not isinstance(buf, np.ndarray):
            return
        ring = self._rings.get(self._key(buf.shape, buf.dtype))
        if ring is None:
            return
        for slot in ring:
            if slot["buf"] is buf:
                slot["dev"] = device_value
                return


def prefetch_to_device(
    arrays: Iterable[np.ndarray],
    sharding=None,
    depth: int = 2,
    clock=None,
    commit: Optional[Callable] = None,
) -> Iterator[jax.Array]:
    """Iterate device arrays with ``depth`` transfers in flight.

    ``sharding``: optional NamedSharding for the transfer target (mesh-sharded
    batches); default puts on the default device. Items may be pytrees
    (e.g. the frame-sharded I3D flow step's (frames, last_frame) pairs) with
    ``sharding`` a matching pytree of shardings — ``jax.device_put`` accepts
    both.

    ``clock``: optional :class:`..utils.metrics.StageClock` — the put
    dispatch time and the staged payload bytes land on the 'transfer' stage.
    ``commit(host, dev)``: optional hook called right after each put — the
    extractors pass :meth:`HostStagingRing.commit` so ring-staged batches are
    guarded against rewrite until their transfer completes.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    queue: collections.deque = collections.deque()
    it = iter(arrays)

    def put(host):
        if clock is None:
            return jax.device_put(host, sharding)
        with clock.stage("transfer"):
            dev = jax.device_put(host, sharding)
        clock.add_bytes("transfer", sum(
            int(getattr(leaf, "nbytes", 0))
            for leaf in jax.tree_util.tree_leaves(host)))
        return dev

    def enqueue() -> bool:
        try:
            host = next(it)
        except StopIteration:
            return False
        dev = put(host)
        queue.append(dev)
        if commit is not None:
            commit(host, dev)
        return True

    for _ in range(depth):
        if not enqueue():
            break
    while queue:
        out = queue.popleft()
        enqueue()
        yield out
