"""Host→device feeding: async prefetch and multi-host work sharding.

Replaces the reference's synchronous per-stack ``.to(device)`` copies
(``/root/reference/models/i3d/extract_i3d.py:140``) with double-buffered
``device_put``: while the device chews on batch *k*, the host decodes and transfers
batch *k+1*. Dispatch in JAX is async already; the prefetcher simply keeps a bounded
queue of in-flight device buffers so decode, PCIe/ICI transfer, and compute overlap.

Multi-host: the reference shards work across *jobs* by splitting file lists
(``gen_file_list.py:6-21``). Here each process takes a deterministic round-robin
shard of the video list — same semantics, no coordinator, resumable per host.
"""

from __future__ import annotations

import collections
from typing import Iterable, Iterator, List, Optional, Sequence

import jax
import numpy as np


def shard_video_list(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """Round-robin shard of ``paths`` owned by this process (DCN axis).

    Round-robin (not contiguous) matches ``gen_file_list.py`` and balances mixed
    video lengths across hosts.
    """
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return list(paths[process_index::process_count])


def prefetch_to_device(
    arrays: Iterable[np.ndarray],
    sharding=None,
    depth: int = 2,
) -> Iterator[jax.Array]:
    """Iterate device arrays with ``depth`` transfers in flight.

    ``sharding``: optional NamedSharding for the transfer target (mesh-sharded
    batches); default puts on the default device.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    queue: collections.deque = collections.deque()
    it = iter(arrays)

    def enqueue() -> bool:
        try:
            host = next(it)
        except StopIteration:
            return False
        queue.append(jax.device_put(host, sharding))
        return True

    for _ in range(depth):
        if not enqueue():
            break
    while queue:
        out = queue.popleft()
        enqueue()
        yield out
