"""Host→device feeding: async prefetch and multi-host work sharding.

Replaces the reference's synchronous per-stack ``.to(device)`` copies
(``/root/reference/models/i3d/extract_i3d.py:140``) with double-buffered
``device_put``: while the device chews on batch *k*, the host decodes and transfers
batch *k+1*. Dispatch in JAX is async already; the prefetcher simply keeps a bounded
queue of in-flight device buffers so decode, PCIe/ICI transfer, and compute overlap.

Multi-host: the reference shards work across *jobs* by splitting file lists
(``gen_file_list.py:6-21``). Here each process takes a deterministic round-robin
shard of the video list — same semantics, no coordinator, resumable per host.
"""

from __future__ import annotations

import collections
import os
from typing import Iterable, Iterator, List, Optional, Sequence

import jax
import numpy as np


def maybe_initialize_distributed() -> bool:
    """Join a multi-host JAX job when one is configured; no-op otherwise.

    The reference has no multi-host story beyond manually split file lists
    (``gen_file_list.py``); the TPU runtime's DCN mechanism is
    ``jax.distributed.initialize`` (SURVEY.md §2.3/§5). Trigger: ``VFT_MULTIHOST=1``
    (values from the standard JAX env vars / TPU metadata) or an explicit
    coordinator address in ``JAX_COORDINATOR_ADDRESS``. Must run before the first
    device access. Returns True when running multi-process.
    """
    # NB: must not touch jax.process_count()/jax.devices() before deciding —
    # any backend-initializing call makes a later jax.distributed.initialize()
    # raise. Detect an already-initialized service via the distributed client.
    try:
        from jax._src import distributed  # noqa: PLC2701 — no public probe exists

        already = distributed.global_state.client is not None
    except Exception:
        already = False
    if already:
        return jax.process_count() > 1
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if os.environ.get("VFT_MULTIHOST") == "1" or coord:
        # On TPU pods initialize() self-configures from the metadata service;
        # elsewhere (and in the loopback test) the standard JAX env vars name
        # the job shape, but this jax version only auto-reads them for known
        # cluster environments — pass them through explicitly when set.
        kwargs = {}
        if coord:
            kwargs["coordinator_address"] = coord
        if os.environ.get("JAX_NUM_PROCESSES"):
            kwargs["num_processes"] = int(os.environ["JAX_NUM_PROCESSES"])
        if os.environ.get("JAX_PROCESS_ID"):
            kwargs["process_id"] = int(os.environ["JAX_PROCESS_ID"])
        jax.distributed.initialize(**kwargs)
        return jax.process_count() > 1
    return False


def shard_video_list(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """Round-robin shard of ``paths`` owned by this process (DCN axis).

    Round-robin (not contiguous) matches ``gen_file_list.py`` and balances mixed
    video lengths across hosts.
    """
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return list(paths[process_index::process_count])


def prefetch_to_device(
    arrays: Iterable[np.ndarray],
    sharding=None,
    depth: int = 2,
) -> Iterator[jax.Array]:
    """Iterate device arrays with ``depth`` transfers in flight.

    ``sharding``: optional NamedSharding for the transfer target (mesh-sharded
    batches); default puts on the default device.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    queue: collections.deque = collections.deque()
    it = iter(arrays)

    def enqueue() -> bool:
        try:
            host = next(it)
        except StopIteration:
            return False
        queue.append(jax.device_put(host, sharding))
        return True

    for _ in range(depth):
        if not enqueue():
            break
    while queue:
        out = queue.popleft()
        enqueue()
        yield out
