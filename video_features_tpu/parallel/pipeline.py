"""Host→device feeding: async prefetch and multi-host work sharding.

Replaces the reference's synchronous per-stack ``.to(device)`` copies
(``/root/reference/models/i3d/extract_i3d.py:140``) with double-buffered
``device_put``: while the device chews on batch *k*, the host decodes and transfers
batch *k+1*. Dispatch in JAX is async already; the prefetcher simply keeps a bounded
queue of in-flight device buffers so decode, PCIe/ICI transfer, and compute overlap.

Multi-host: the reference shards work across *jobs* by splitting file lists
(``gen_file_list.py:6-21``). Here each process takes a deterministic round-robin
shard of the video list — same semantics, no coordinator, resumable per host.
"""

from __future__ import annotations

import collections
import os
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..reliability import fault_point


def maybe_initialize_distributed() -> bool:
    """Join a multi-host JAX job when one is configured; no-op otherwise.

    The reference has no multi-host story beyond manually split file lists
    (``gen_file_list.py``); the TPU runtime's DCN mechanism is
    ``jax.distributed.initialize`` (SURVEY.md §2.3/§5). Trigger: ``VFT_MULTIHOST=1``
    (values from the standard JAX env vars / TPU metadata) or an explicit
    coordinator address in ``JAX_COORDINATOR_ADDRESS``. Must run before the first
    device access. Returns True when running multi-process.
    """
    # NB: must not touch jax.process_count()/jax.devices() before deciding —
    # any backend-initializing call makes a later jax.distributed.initialize()
    # raise. Detect an already-initialized service via the distributed client.
    try:
        from jax._src import distributed  # noqa: PLC2701 — no public probe exists

        already = distributed.global_state.client is not None
    except Exception:  # fault-barrier: private-API probe; absence means "not initialized"
        already = False
    if already:
        return jax.process_count() > 1
    coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if os.environ.get("VFT_MULTIHOST") == "1" or coord:
        # On TPU pods initialize() self-configures from the metadata service;
        # elsewhere (and in the loopback test) the standard JAX env vars name
        # the job shape, but this jax version only auto-reads them for known
        # cluster environments — pass them through explicitly when set.
        kwargs = {}
        if coord:
            kwargs["coordinator_address"] = coord
        n_proc = os.environ.get("JAX_NUM_PROCESSES")
        proc_id = os.environ.get("JAX_PROCESS_ID")
        if bool(n_proc) != bool(proc_id):
            # a half-specified pair makes initialize() fail or hang with no
            # hint at the cause; fail fast with the fix instead
            raise RuntimeError(
                "JAX_NUM_PROCESSES and JAX_PROCESS_ID must be set together "
                f"(got JAX_NUM_PROCESSES={n_proc!r}, JAX_PROCESS_ID={proc_id!r})"
            )
        if n_proc:
            kwargs["num_processes"] = int(n_proc)
            kwargs["process_id"] = int(proc_id)
        jax.distributed.initialize(**kwargs)
        return jax.process_count() > 1
    return False


def shard_video_list(
    paths: Sequence[str],
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[str]:
    """Round-robin shard of ``paths`` owned by this process (DCN axis).

    Round-robin (not contiguous) matches ``gen_file_list.py`` and balances mixed
    video lengths across hosts.
    """
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    return list(paths[process_index::process_count])


def _item_bytes(item) -> int:
    """Approximate host bytes of one queued frame item (``(rgb, pos)``)."""
    if isinstance(item, tuple):
        return sum(int(getattr(x, "nbytes", 0)) for x in item)
    return int(getattr(item, "nbytes", 0))


class DecodePrefetcher:
    """Cross-video decode parallelism: background threads decode upcoming
    videos while the device chews on the current one.

    The reference gets decode parallelism implicitly — one Python thread per
    GPU, each running its own decode loop (``/root/reference/main.py:43-47``).
    The SPMD design centralizes devices behind one process, so when decode is
    slower than compute (the common case: one cv2 stream decodes a few hundred
    fps, the mesh consumes thousands), extra decode streams must be explicit.
    cv2/ffmpeg/PIL release the GIL in their C cores, so threads parallelize.

    ``open_fn(path) -> (meta, frames_iter)``; each worker drains one video's
    iterator into a bounded queue, and :meth:`get` hands back
    ``(meta, iterator)`` draining that queue. The buffer is bounded TWICE and
    the tighter bound governs: ``max_buffered`` caps the frame COUNT (the
    right bound for small frames, where per-item overhead dominates) and
    ``max_buffered_bytes`` caps the payload BYTES — without it a mixed
    corpus's 1080p videos (~6 MB/frame) could pin ``workers × 512`` frames
    ≈ tens of GB of host RAM under the count bound alone. Paths are
    scheduled by the run loop at most ``workers`` ahead of the consume cursor,
    so the totals stay ≤ workers · bound. Decode errors are re-raised at
    consume time — the per-video fault barrier sees them exactly as inline
    decode would.
    """

    _DONE = object()

    def __init__(self, open_fn: Callable, workers: int, max_buffered: int = 512,
                 max_buffered_bytes: int = 512 << 20):
        if workers < 1:
            raise ValueError("decode workers must be >= 1")
        self._open = open_fn
        self._max = max_buffered
        self._max_bytes = max_buffered_bytes
        self._slots: dict = {}  # scheduled, not yet consumed
        self._handed: dict = {}  # handed to a consumer via get(), not released
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._sem = threading.Semaphore(workers)
        # live resize (serve/autoscale.py): permits added via release(),
        # removed by non-blocking acquires — shortfall becomes _debt that
        # finishing workers absorb instead of re-releasing their permit
        self._workers = workers
        self._resize_lock = threading.Lock()
        self._debt = 0

    @property
    def workers(self) -> int:
        """Current concurrency target (also the run loops' schedule window)."""
        return self._workers

    def resize(self, workers: int) -> None:
        """Grow or shrink the concurrent-decode budget without a restart.

        Growing releases permits immediately; shrinking takes free permits
        now and records the remainder as debt consumed as busy workers
        finish (a mid-decode video is never cancelled by a shrink).
        """
        if workers < 1:
            raise ValueError("decode workers must be >= 1")
        with self._resize_lock:
            delta = workers - self._workers
            self._workers = workers
            if delta > 0:
                for _ in range(delta):
                    if self._debt:
                        self._debt -= 1
                    else:
                        self._sem.release()
            else:
                for _ in range(-delta):
                    if not self._sem.acquire(blocking=False):
                        self._debt += 1

    def _release_permit(self) -> None:
        with self._resize_lock:
            if self._debt:
                self._debt -= 1
            else:
                self._sem.release()

    def schedule(self, path: str) -> None:
        """Start decoding ``path`` in the background (no-op if scheduled)."""
        if path in self._slots or path in self._handed or self._stop.is_set():
            return
        self._threads = [t for t in self._threads if t.is_alive()]
        slot = {
            "q": queue.Queue(maxsize=self._max),
            "meta": None,
            "err": None,
            "bytes": 0,  # buffered payload bytes (max_buffered_bytes bound)
            "lock": threading.Lock(),  # guards the bytes counter
            "ready": threading.Event(),
            "stop": threading.Event(),  # per-video cancel (release())
        }
        self._slots[path] = slot
        t = threading.Thread(target=self._worker, args=(path, slot), daemon=True)
        self._threads.append(t)
        t.start()

    def _worker(self, path: str, slot: dict) -> None:
        def stopped() -> bool:
            return self._stop.is_set() or slot["stop"].is_set()

        self._sem.acquire()  # at most `workers` videos decoding concurrently
        try:
            try:
                if stopped():
                    return
                # crash-injection seam: a worker dying HERE (not inside
                # open_fn) must still surface a classified error at consume
                # time instead of deadlocking the drain — tests prove it
                fault_point("pool_worker", path)
                meta, frames = self._open(path)
                slot["meta"] = meta  # thread-shared-state: published by the ready Event set below
                slot["ready"].set()
                for item in frames:
                    nbytes = _item_bytes(item)
                    # byte bound: wait for buffered-payload room (the frame
                    # COUNT bound is the queue's maxsize below; the tighter
                    # of the two governs). An empty buffer always admits one
                    # item, so a single frame larger than the cap still flows.
                    while not stopped():
                        with slot["lock"]:
                            fits = (slot["bytes"] == 0
                                    or slot["bytes"] + nbytes <= self._max_bytes)
                        if fits:
                            break
                        time.sleep(0.05)
                    if stopped():
                        return
                    while not stopped():
                        try:
                            slot["q"].put(item, timeout=0.2)
                            with slot["lock"]:
                                slot["bytes"] += nbytes  # thread-shared-state: guarded by slot['lock'] (consumer decrements under the same lock)
                            break
                        except queue.Full:
                            continue
                    if stopped():
                        return
            except Exception as e:  # noqa: BLE001 — fault-barrier: re-raised classified at consume time
                slot["err"] = e  # thread-shared-state: published by the ready Event / _DONE sentinel in finally
            finally:
                slot["ready"].set()
                while not stopped():
                    try:
                        slot["q"].put(self._DONE, timeout=0.2)
                        break
                    except queue.Full:  # consumer will drain; retry
                        continue
        finally:
            # a shrink may have pre-claimed this permit as debt; the helper
            # settles debt before returning the permit to the pool
            self._release_permit()

    def get(self, path: str):
        """(meta, frames_iter) for ``path`` — prefetched if scheduled, else
        decoded inline. Pair every get() with :meth:`release` (the run loop
        does this in its per-video ``finally``): an abandoned iterator — e.g.
        the per-video fault barrier caught a compute error mid-drain — would
        otherwise pin its worker thread and semaphore permit forever.
        """
        slot = self._slots.pop(path, None)
        if slot is None:
            return self._open(path)
        self._handed[path] = slot
        slot["ready"].wait()
        if slot["err"] is not None and slot["meta"] is None:
            raise slot["err"]

        def drain() -> Iterator[Tuple[np.ndarray, float]]:
            while True:
                try:
                    item = slot["q"].get(timeout=0.2)
                except queue.Empty:
                    # release()/shutdown() with a full queue can drop their
                    # _DONE sentinel while the stopped worker never enqueues
                    # one — without this check a late consumer blocks forever.
                    # A stored worker error must still surface on this exit
                    # path (the dropped sentinel would otherwise swallow it).
                    if slot["stop"].is_set() or self._stop.is_set():
                        if slot["err"] is not None:
                            raise slot["err"]
                        return
                    continue
                if item is self._DONE:
                    if slot["err"] is not None:
                        raise slot["err"]
                    return
                with slot["lock"]:
                    # release the byte budget as soon as the item leaves the
                    # buffer (once yielded it is the consumer's memory)
                    slot["bytes"] -= _item_bytes(item)
                yield item

        return slot["meta"], drain()

    def release(self, path: str) -> None:
        """Cancel/forget a video's decode (no-op for finished or unknown ones)."""
        slot = self._handed.pop(path, None) or self._slots.pop(path, None)
        if slot is not None:
            slot["stop"].set()
            try:  # a consumer mid-drain must not hang on an exiting worker
                slot["q"].put_nowait(self._DONE)
            except queue.Full:
                pass

    def shutdown(self) -> None:
        self._stop.set()
        for slot in list(self._slots.values()) + list(self._handed.values()):
            try:  # unblock any drain() consumers
                slot["q"].put_nowait(self._DONE)
            except queue.Full:
                pass  # consumer has items to drain before it can block
        for t in self._threads:
            t.join(timeout=2.0)
        self._slots.clear()
        self._handed.clear()


def prefetch_to_device(
    arrays: Iterable[np.ndarray],
    sharding=None,
    depth: int = 2,
) -> Iterator[jax.Array]:
    """Iterate device arrays with ``depth`` transfers in flight.

    ``sharding``: optional NamedSharding for the transfer target (mesh-sharded
    batches); default puts on the default device. Items may be pytrees
    (e.g. the frame-sharded I3D flow step's (frames, last_frame) pairs) with
    ``sharding`` a matching pytree of shardings — ``jax.device_put`` accepts
    both.
    """
    if depth < 1:
        raise ValueError("prefetch depth must be >= 1")
    queue: collections.deque = collections.deque()
    it = iter(arrays)

    def enqueue() -> bool:
        try:
            host = next(it)
        except StopIteration:
            return False
        queue.append(jax.device_put(host, sharding))
        return True

    for _ in range(depth):
        if not enqueue():
            break
    while queue:
        out = queue.popleft()
        enqueue()
        yield out
