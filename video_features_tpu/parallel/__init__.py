from .mesh import (
    DATA_AXIS,
    MeshRunner,
    batch_sharding,
    enable_compilation_cache,
    local_mesh,
    replicate,
    sharded_apply,
)
from .pipeline import maybe_initialize_distributed, prefetch_to_device, shard_video_list
from .spatial import shard_spatial, sharded_conv_stack, sharded_same_conv2d

__all__ = [
    "DATA_AXIS",
    "MeshRunner",
    "batch_sharding",
    "enable_compilation_cache",
    "local_mesh",
    "replicate",
    "sharded_apply",
    "maybe_initialize_distributed",
    "prefetch_to_device",
    "shard_spatial",
    "sharded_conv_stack",
    "sharded_same_conv2d",
    "shard_video_list",
]
