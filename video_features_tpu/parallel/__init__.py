from .mesh import local_mesh, replicate, shard_along, sharded_apply
from .pipeline import prefetch_to_device, shard_video_list

__all__ = [
    "local_mesh",
    "replicate",
    "shard_along",
    "sharded_apply",
    "prefetch_to_device",
    "shard_video_list",
]
