from .mesh import (
    DATA_AXIS,
    MeshRunner,
    batch_sharding,
    enable_compilation_cache,
    local_mesh,
    replicate,
    sharded_apply,
)
from .pages import build_row_table, mask_rows, page_rows_for, paged_program
from .pipeline import maybe_initialize_distributed, prefetch_to_device, shard_video_list
from .spatial import shard_spatial, sharded_conv_stack, sharded_same_conv2d

__all__ = [
    "DATA_AXIS",
    "MeshRunner",
    "batch_sharding",
    "enable_compilation_cache",
    "local_mesh",
    "replicate",
    "sharded_apply",
    "build_row_table",
    "mask_rows",
    "page_rows_for",
    "paged_program",
    "maybe_initialize_distributed",
    "prefetch_to_device",
    "shard_spatial",
    "sharded_conv_stack",
    "sharded_same_conv2d",
    "shard_video_list",
]
