from .mesh import (
    DATA_AXIS,
    MeshRunner,
    batch_sharding,
    local_mesh,
    replicate,
    sharded_apply,
)
from .pipeline import maybe_initialize_distributed, prefetch_to_device, shard_video_list

__all__ = [
    "DATA_AXIS",
    "MeshRunner",
    "batch_sharding",
    "local_mesh",
    "replicate",
    "sharded_apply",
    "maybe_initialize_distributed",
    "prefetch_to_device",
    "shard_video_list",
]
