"""Checkpoint resolution: find, convert, and cache model weights.

The reference hard-codes checkpoint paths and downloads torchvision weights on first
use (SURVEY.md §2.1 #25). This image has no network egress, so the store resolves
weights from local files and falls back to deterministic random initialization when
explicitly allowed (smoke tests, benchmarks — feature *values* then differ from the
pretrained reference but shapes, dtypes, and compute are identical).

Resolution order for model key ``<name>``:
1. explicit ``checkpoint_path`` argument
2. ``$VFT_CHECKPOINT_DIR/<name>.npz`` (converted Flax params, flat ``a/b/c`` keys)
3. ``./checkpoints/<name>.npz``
4. a torch file at either location (``<name>.pt``/``.pth``) run through the model's
   converter (requires torch), or an orbax checkpoint directory (``<name>.orbax``)
5. random init iff ``$VFT_ALLOW_RANDOM_WEIGHTS=1`` or ``allow_random=True``
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np

ENV_DIR = "VFT_CHECKPOINT_DIR"
ENV_ALLOW_RANDOM = "VFT_ALLOW_RANDOM_WEIGHTS"


def flatten_params(tree: dict, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_params(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def unflatten_params(flat: Dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def save_params_npz(path: str, params: dict) -> None:
    np.savez(path, **flatten_params(params))


def load_params_npz(path: str) -> dict:
    with np.load(path) as z:
        return unflatten_params({k: z[k] for k in z.files})


def _candidates(name: str):
    dirs = []
    if os.environ.get(ENV_DIR):
        dirs.append(os.environ[ENV_DIR])
    dirs.append("./checkpoints")
    for d in dirs:
        for ext in (".npz", ".pt", ".pth", ".orbax"):
            yield os.path.join(d, name + ext)


def save_params_orbax(dir_path: str, params: dict) -> str:
    """Write ``params`` as an orbax checkpoint directory (``<name>.orbax``).

    The ``.npz`` flat format stays the store's default (single file, no extra
    deps at load time); orbax is the JAX-ecosystem interchange format (sharded,
    async-capable) for pipelines that already speak it (SURVEY.md §5).
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(dir_path)
    ocp.PyTreeCheckpointer().save(path, params, force=True)
    return path


def load_params_orbax(dir_path: str) -> dict:
    import orbax.checkpoint as ocp

    return ocp.PyTreeCheckpointer().restore(os.path.abspath(dir_path))


def random_params_like(init_fn: Callable, *args, seed: int = 0) -> dict:
    """Random params with the tree/shape/dtype structure of ``init_fn(*args)``
    WITHOUT tracing it on a device — ``jax.eval_shape`` only.

    Flax ``model.init`` compiles and runs a full forward pass (minutes of XLA
    compile for the conv3d networks on TPU, all wasted for random weights).
    Leaf semantics follow the param name: BatchNorm ``var``/``scale`` → ones,
    ``mean``/``bias`` → zeros, kernels → He-scaled normals (fan-in from the
    HWIO/(in, out) layout) so deep stacks keep O(1) activations — random-weight
    parity tests then compare numbers of sane magnitude.
    """
    import jax

    shapes = jax.eval_shape(init_fn, *args)
    rng = np.random.default_rng(seed)

    def leaf(path, s):
        name = getattr(path[-1], "key", str(path[-1]))
        if name in ("var", "scale"):
            return np.ones(s.shape, s.dtype)
        if name in ("mean", "bias"):
            return np.zeros(s.shape, s.dtype)
        fan_in = int(np.prod(s.shape[:-1])) or 1
        std = (2.0 / fan_in) ** 0.5
        return (rng.standard_normal(s.shape) * std).astype(s.dtype)

    return jax.tree_util.tree_map_with_path(leaf, shapes)


def looks_like_tf_vars(flat: Dict[str, np.ndarray]) -> bool:
    """TF-slim variable naming (``vggish/conv1/weights``) vs store-format flat
    Flax keys (``conv1/kernel``)."""
    return any(
        k.replace(":0", "").rsplit("/", 1)[-1] in ("weights", "biases") for k in flat
    )


def resolve_params(
    name: str,
    convert_torch_fn: Optional[Callable[[dict], dict]] = None,
    init_fn: Optional[Callable[[], dict]] = None,
    checkpoint_path: Optional[str] = None,
    allow_random: bool = False,
    convert_tf_fn: Optional[Callable[[Dict[str, np.ndarray]], dict]] = None,
) -> dict:
    """Return the Flax param tree for model ``name`` per the resolution order above.

    ``convert_tf_fn``: converter for an ``.npz`` holding RAW TF checkpoint
    variables (the reference VGGish ships as a TF-slim checkpoint,
    ``vggish_slim.py:102-129``); detected by TF-style variable names so a
    TF-vars dump and a store-format params file can share the ``.npz`` slot.
    """
    if checkpoint_path and not os.path.exists(checkpoint_path):
        # an explicit path must not silently degrade to random weights
        raise FileNotFoundError(f"checkpoint_path {checkpoint_path!r} does not exist")
    paths = [checkpoint_path] if checkpoint_path else list(_candidates(name))
    for path in paths:
        if path is None or not os.path.exists(path):
            continue
        if path.endswith(".npz"):
            with np.load(path) as z:
                flat = {k: z[k] for k in z.files}
            if convert_tf_fn is not None and looks_like_tf_vars(flat):
                return convert_tf_fn(flat)
            return unflatten_params(flat)
        if path.endswith(".orbax"):
            return load_params_orbax(path)
        if convert_torch_fn is None:
            raise ValueError(f"{path}: torch checkpoint given but no converter for {name}")
        import torch  # local import: torch is host-side tooling only

        sd = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(sd, dict) and "state_dict" in sd:
            sd = sd["state_dict"]
        return convert_torch_fn(sd)

    if allow_random or os.environ.get(ENV_ALLOW_RANDOM) == "1":
        if init_fn is None:
            raise ValueError(f"no init_fn provided for random weights of {name}")
        return init_fn()
    raise FileNotFoundError(
        f"no checkpoint found for {name!r} (searched {paths}); place converted "
        f"weights at $VFT_CHECKPOINT_DIR/{name}.npz (or {name}.orbax), a torch "
        f"checkpoint at ./checkpoints/{name}.pt, or set {ENV_ALLOW_RANDOM}=1 "
        f"for random weights"
    )
