"""Checkpoint resolution: find, convert, and cache model weights.

The reference hard-codes checkpoint paths and downloads torchvision weights on first
use (SURVEY.md §2.1 #25). This image has no network egress, so the store resolves
weights from local files and falls back to deterministic random initialization when
explicitly allowed (smoke tests, benchmarks — feature *values* then differ from the
pretrained reference but shapes, dtypes, and compute are identical).

Resolution order for model key ``<name>``:
1. explicit ``checkpoint_path`` argument
2. ``$VFT_CHECKPOINT_DIR/<name>.npz`` (converted Flax params, flat ``a/b/c`` keys)
3. ``./checkpoints/<name>.npz``
4. a torch file at either location (``<name>.pt``/``.pth``) run through the model's
   converter (requires torch)
5. random init iff ``$VFT_ALLOW_RANDOM_WEIGHTS=1`` or ``allow_random=True``
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional

import numpy as np

ENV_DIR = "VFT_CHECKPOINT_DIR"
ENV_ALLOW_RANDOM = "VFT_ALLOW_RANDOM_WEIGHTS"


def flatten_params(tree: dict, prefix: str = "") -> Dict[str, np.ndarray]:
    out = {}
    for k, v in tree.items():
        key = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_params(v, key))
        else:
            out[key] = np.asarray(v)
    return out


def unflatten_params(flat: Dict[str, np.ndarray]) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return tree


def save_params_npz(path: str, params: dict) -> None:
    np.savez(path, **flatten_params(params))


def load_params_npz(path: str) -> dict:
    with np.load(path) as z:
        return unflatten_params({k: z[k] for k in z.files})


def _candidates(name: str):
    dirs = []
    if os.environ.get(ENV_DIR):
        dirs.append(os.environ[ENV_DIR])
    dirs.append("./checkpoints")
    for d in dirs:
        for ext in (".npz", ".pt", ".pth"):
            yield os.path.join(d, name + ext)


def resolve_params(
    name: str,
    convert_torch_fn: Optional[Callable[[dict], dict]] = None,
    init_fn: Optional[Callable[[], dict]] = None,
    checkpoint_path: Optional[str] = None,
    allow_random: bool = False,
) -> dict:
    """Return the Flax param tree for model ``name`` per the resolution order above."""
    if checkpoint_path and not os.path.exists(checkpoint_path):
        # an explicit path must not silently degrade to random weights
        raise FileNotFoundError(f"checkpoint_path {checkpoint_path!r} does not exist")
    paths = [checkpoint_path] if checkpoint_path else list(_candidates(name))
    for path in paths:
        if path is None or not os.path.exists(path):
            continue
        if path.endswith(".npz"):
            return load_params_npz(path)
        if convert_torch_fn is None:
            raise ValueError(f"{path}: torch checkpoint given but no converter for {name}")
        import torch  # local import: torch is host-side tooling only

        sd = torch.load(path, map_location="cpu", weights_only=True)
        if isinstance(sd, dict) and "state_dict" in sd:
            sd = sd["state_dict"]
        return convert_torch_fn(sd)

    if allow_random or os.environ.get(ENV_ALLOW_RANDOM) == "1":
        if init_fn is None:
            raise ValueError(f"no init_fn provided for random weights of {name}")
        return init_fn()
    raise FileNotFoundError(
        f"no checkpoint found for {name!r} (searched {paths}); place converted "
        f"weights at $VFT_CHECKPOINT_DIR/{name}.npz or a torch checkpoint at "
        f"./checkpoints/{name}.pt, or set {ENV_ALLOW_RANDOM}=1 for random weights"
    )
