"""Checkpoint conversion: torch / TF checkpoints → Flax param pytrees.

The reference loads torch ``state_dict``s from hard-coded paths
(``extract_i3d.py:98,105``, ``extract_raft.py:60``, ``extract_pwc.py:58``),
torchvision ``pretrained=True`` downloads, and a TF-slim Saver checkpoint for VGGish
(``vggish_slim.py:102-129``). Here every model has a pure name-and-layout converter so
any of those checkpoint files can be turned into the Flax param tree once and stored
via orbax/msgpack.
"""
