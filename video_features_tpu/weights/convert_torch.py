"""torch state_dict → Flax param tree converters (pure numpy; torch not required).

Layout rules:
- conv2d ``(O, I, H, W)`` → ``(H, W, I, O)`` (flax NHWC kernels)
- conv3d ``(O, I, D, H, W)`` → ``(D, H, W, I, O)`` (flax NDHWC kernels)
- linear ``(O, I)`` → ``(I, O)``
- BatchNorm ``weight/bias/running_mean/running_var`` → ``scale/bias/mean/var``

Name rules are per-model; each converter returns the nested dict matching the
corresponding Flax module's ``params`` collection.
"""

from __future__ import annotations

from typing import Dict, Mapping, Tuple

import numpy as np


def to_numpy_state_dict(state_dict: Mapping) -> Dict[str, np.ndarray]:
    """Detach a torch state_dict to plain numpy (accepts numpy passthrough)."""
    out = {}
    for k, v in state_dict.items():
        if hasattr(v, "detach"):
            v = v.detach().cpu().numpy()
        out[k] = np.asarray(v)
    return out


def conv2d_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 1, 0))


def conv3d_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w, (2, 3, 4, 1, 0))


def linear_kernel(w: np.ndarray) -> np.ndarray:
    return np.transpose(w)


def set_path(tree: dict, path: Tuple[str, ...], value: np.ndarray) -> None:
    node = tree
    for p in path[:-1]:
        node = node.setdefault(p, {})
    node[path[-1]] = value


_BN_MAP = {"weight": "scale", "bias": "bias", "running_mean": "mean", "running_var": "var"}


def convert_bn(sd: Mapping[str, np.ndarray], torch_prefix: str, tree: dict,
               flax_path: Tuple[str, ...]) -> None:
    for tname, fname in _BN_MAP.items():
        set_path(tree, flax_path + (fname,), np.asarray(sd[f"{torch_prefix}.{tname}"]))


def convert_resnet50(state_dict: Mapping) -> dict:
    """torchvision ``resnet50`` state_dict → :class:`models.resnet.ResNet50` params."""
    sd = to_numpy_state_dict(state_dict)
    params: dict = {}

    set_path(params, ("conv1", "kernel"), conv2d_kernel(sd["conv1.weight"]))
    convert_bn(sd, "bn1", params, ("bn1",))

    stage_sizes = (3, 4, 6, 3)
    for stage, blocks in enumerate(stage_sizes, start=1):
        for b in range(blocks):
            t = f"layer{stage}.{b}"
            f = f"layer{stage}.{b}"
            for conv in ("conv1", "conv2", "conv3"):
                set_path(params, (f, conv, "kernel"), conv2d_kernel(sd[f"{t}.{conv}.weight"]))
            for bn in ("bn1", "bn2", "bn3"):
                convert_bn(sd, f"{t}.{bn}", params, (f, bn))
            if f"{t}.downsample.0.weight" in sd:
                set_path(params, (f, "downsample.0", "kernel"),
                         conv2d_kernel(sd[f"{t}.downsample.0.weight"]))
                convert_bn(sd, f"{t}.downsample.1", params, (f, "downsample.1"))

    if "fc.weight" in sd:
        set_path(params, ("fc", "kernel"), linear_kernel(sd["fc.weight"]))
        set_path(params, ("fc", "bias"), np.asarray(sd["fc.bias"]))
    return params


def _merge_numeric_tokens(key: str) -> Tuple[str, ...]:
    """Split a torch key on '.', re-joining ``name.<digit>`` pairs into one path
    element (torch flattens Sequential/list indices; the pytrees keep them)."""
    tokens = key.split(".")
    merged = []
    i = 0
    while i < len(tokens):
        if i + 1 < len(tokens) and tokens[i + 1].isdigit():
            merged.append(tokens[i] + "." + tokens[i + 1])
            i += 2
        else:
            merged.append(tokens[i])
            i += 1
    return tuple(merged)


def convert_raft(state_dict: Mapping) -> dict:
    """Reference RAFT checkpoint (``raft-sintel.pth`` et al., keys prefixed
    ``module.`` by the vestigial DataParallel wrap — ``extract_raft.py:58-59``) →
    the param pytree of :func:`video_features_tpu.models.raft.raft_forward`.

    Instance norms carry no params; cnet batch norms map to scale/bias/mean/var.
    ``downsample.1`` keys alias ``norm3`` (the module is registered under both
    names) and fold onto the ``norm3`` path.
    """
    sd = to_numpy_state_dict(state_dict)
    params: dict = {}
    for key, value in sd.items():
        if key.startswith("module."):
            key = key[len("module."):]
        if key.endswith("num_batches_tracked"):
            continue
        *path, leaf = _merge_numeric_tokens(key)
        if path and path[-1] == "downsample.1":
            path[-1] = "norm3"
        if leaf == "weight" and value.ndim == 4:
            set_path(params, (*path, "kernel"), conv2d_kernel(value))
        elif leaf in _BN_MAP and value.ndim == 1 and (
            path and ("norm" in path[-1])
        ):
            set_path(params, (*path, _BN_MAP[leaf]), value)
        elif leaf == "bias":
            set_path(params, (*path, "bias"), value)
        else:
            raise ValueError(f"unrecognized RAFT checkpoint key: {key}")
    return params


def convert_r21d(state_dict: Mapping) -> dict:
    """torchvision ``r2plus1d_18`` state_dict → :class:`models.r21d.R2Plus1D18` params.

    Key shapes disambiguate the leaf kind: 5-dim weight → conv3d kernel, 2-dim →
    fc kernel, 1-dim weight/bias → BatchNorm affine (the only biased layers besides
    fc are BNs).
    """
    sd = to_numpy_state_dict(state_dict)
    params: dict = {}
    for key, value in sd.items():
        if key.endswith("num_batches_tracked"):
            continue
        *path, leaf = _merge_numeric_tokens(key)
        if leaf == "weight" and value.ndim == 5:
            set_path(params, (*path, "kernel"), conv3d_kernel(value))
        elif leaf == "weight" and value.ndim == 2:
            set_path(params, (*path, "kernel"), linear_kernel(value))
        elif leaf in _BN_MAP and value.ndim == 1 and path[-1] != "fc":
            set_path(params, (*path, _BN_MAP[leaf]), value)
        elif leaf == "bias":
            set_path(params, (*path, "bias"), value)
        else:
            raise ValueError(f"unrecognized R(2+1)D checkpoint key: {key}")
    return params


def convert_pwc(state_dict: Mapping) -> dict:
    """Reference PWC checkpoint (``pwc_net_sintel.pt``,
    ``/root/reference/models/pwc/pwc_src/pwc_net.py`` naming) → the param pytree of
    :func:`video_features_tpu.models.pwc.pwc_forward`.

    ``moduleUpflow``/``moduleUpfeat`` are ConvTranspose2d with torch layout
    (in, out, kh, kw); everything else is a regular conv (out, in, kh, kw).
    """
    sd = to_numpy_state_dict(state_dict)
    params: dict = {}
    for key, value in sd.items():
        if key.startswith("module."):
            key = key[len("module."):]
        *path, leaf = key.split(".")
        if leaf == "weight":
            transpose_conv = path[-1] in ("moduleUpflow", "moduleUpfeat")
            kernel = np.transpose(value, (2, 3, 0, 1) if transpose_conv else (2, 3, 1, 0))
            set_path(params, (*path, "kernel"), kernel)
        elif leaf == "bias":
            set_path(params, (*path, "bias"), value)
        else:
            raise ValueError(f"unrecognized PWC checkpoint key: {key}")
    return params


def convert_i3d(state_dict: Mapping) -> dict:
    """Reference I3D checkpoint (``i3d_rgb.pt``/``i3d_flow.pt`` state_dict naming,
    ``/root/reference/models/i3d/i3d_src/i3d_net.py``) → :class:`models.i3d.I3D`
    params.

    The Flax module names mirror the torch names, with one twist: torch flattens
    ``mixed_3b.branch_1.0`` while the Flax submodule is literally named
    ``branch_1.0`` — so ``branch_<i>.<j>`` token pairs re-join into one path element.
    """
    sd = to_numpy_state_dict(state_dict)
    params: dict = {}
    for key, value in sd.items():
        if key.endswith("num_batches_tracked"):
            continue
        *path, module, leaf = _merge_numeric_tokens(key)
        if module == "conv3d":
            if leaf == "weight":
                set_path(params, (*path, "conv3d", "kernel"), conv3d_kernel(value))
            else:
                set_path(params, (*path, "conv3d", "bias"), value)
        elif module == "batch3d":
            set_path(params, (*path, "batch3d", _BN_MAP[leaf]), value)
        else:
            raise ValueError(f"unrecognized I3D checkpoint key: {key}")
    return params
