"""In-flight request coalescing: N identical extractions become one.

With PR 6's always-on daemon, identical content arrives CONCURRENTLY from
different tenants — finished-work dedup (the CAS store) is not enough,
because the second request lands while the first is still on the mesh. This
tracker maps a live cache key to its **leader** (the path whose extraction
is running) and parks every later identical submission as a **waiter**.

Contract (enforced by :mod:`..serve.daemon`, pinned by tests/test_cache.py):

- exactly one extraction runs per (content, fingerprint) at a time;
- when the leader resolves — success OR failure — :meth:`finish` hands the
  waiters back and the daemon re-enqueues them with their original admission
  seq. On success they replay as cache hits (zero device steps, their own
  output stems, done-manifest and result records); on failure the first
  replayed waiter becomes the next leader and extracts on its OWN retry
  budget — a leader's fault is never charged to a waiter's tenant breaker;
- quota and fairness are charged per waiter: each parked video was admitted
  against its tenant's quota and each replay is a scheduler pop that
  advances its tenant's virtual time.

Single-writer by design: only the daemon loop MUTATES this state (no locks;
vftlint thread-shared-state has nothing to declare for cache/). The one
cross-thread read is :meth:`InflightCoalescer.waiting` from the serve
socket's stats op, which snapshots before iterating.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class InflightCoalescer:
    """Leader/waiter tracking keyed by cache key."""

    def __init__(self):
        self._by_key: Dict[str, dict] = {}   # key -> {leader, waiters}
        self._leader_key: Dict[str, str] = {}  # leader path -> key
        self.coalesced = 0  # cumulative waiters parked (stats op)

    def lead(self, key: str, path: str) -> None:
        """Record ``path`` as the one extraction in flight for ``key``."""
        self._by_key[key] = {"leader": path, "waiters": []}
        self._leader_key[path] = key

    def wait(self, key: str, job) -> bool:
        """Park ``job`` behind an in-flight identical extraction; False when
        no extraction is in flight for ``key`` (caller should lead)."""
        entry = self._by_key.get(key)
        if entry is None:
            return False
        entry["waiters"].append(job)
        self.coalesced += 1
        return True

    def finish(self, path: str) -> List:
        """Leader ``path`` resolved: clear the key, return its waiters
        (empty for non-leaders — safe to call for every completed video)."""
        key = self._leader_key.pop(path, None)
        if key is None:
            return []
        entry = self._by_key.pop(key, None)
        return entry["waiters"] if entry else []

    def leader_of(self, key: str) -> Optional[str]:
        entry = self._by_key.get(key)
        return entry["leader"] if entry else None

    def waiting(self) -> int:
        """Currently-parked waiter count (quiescence/stats). The one method
        also read from the serve socket's API thread — list() snapshots the
        live dict atomically before the Python-level iteration."""
        return sum(len(e["waiters"]) for e in list(self._by_key.values()))
