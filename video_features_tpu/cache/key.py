"""Cache keying: content digest × model-config fingerprint × weights version.

A cache hit substitutes a stored array for a device computation, so the key
must cover EVERYTHING that changes the bytes of the output and NOTHING that
doesn't (or the cache never hits). Three components:

1. **content digest** — a streaming SHA-256 of the container bytes
   (:func:`file_digest`). Identical uploads hash identically wherever they
   sit on disk; the video *path* is deliberately not part of the key.
2. **config fingerprint** — the subset of :class:`..config.ExtractionConfig`
   fields that affect feature numerics (:data:`FINGERPRINT_FIELDS`), some
   resolved to their effective value (e.g. ``use_ffmpeg="auto"`` resolves to
   the backend actually used — the same flag value on hosts with and without
   ffmpeg produces different resampled frames). Every dataclass field must
   be classified here or in :data:`EXECUTION_FIELDS`; tests/test_cache.py
   pins the partition, so ADDING A CONFIG FLAG FORCES A KEYING DECISION.
3. **weights version** — pretrained checkpoints have no version string, so
   the fingerprint hashes the resolved checkpoint files for the feature
   type's models (once per extractor, not per video); ``VFT_WEIGHTS_VERSION``
   short-circuits the hashing for operators who pin versions out of band.
   Random-weight runs (``VFT_ALLOW_RANDOM_WEIGHTS``) fingerprint as the
   deterministic seed, never colliding with real weights.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

# Config fields whose values feed the cache key because they change feature
# numerics. Keep the per-field rationale next to the name — the pin test
# makes adding a field here (or to EXECUTION_FIELDS) a reviewed decision.
FINGERPRINT_FIELDS = (
    "feature_type",            # selects the model
    "streams",                 # i3d rgb/flow subset changes the output keys
    "flow_type",               # raft vs pwc flow in the i3d sandwich
    "extraction_fps",          # temporal resampling changes every frame
    "stack_size",              # clip span per feature row
    "step_size",               # stride between feature rows
    "resize_to_smaller_edge",  # spatial geometry (raft/pwc)
    "side_size",               # spatial geometry (raft/pwc)
    "dtype",                   # bf16 feature nets drift from fp32
    "flow_dtype",              # bf16 flow nets drift (tests/test_flow_bf16)
    "transfer_dtype",          # fp16/bf16 D2H quantizes dense flow
    "matmul_precision",        # MXU pass count changes fp32 accumulation
    "use_ffmpeg",              # resolved: ffmpeg re-encode vs native sampler
    "vggish_postprocess",      # PCA-whiten + uint8 quantize on/off
    "shape_bucket",            # resolved: replicate-pad perturbs flow borders
    "pack_corpus",             # resolved: merged flow buckets pad (caveat)
    "pack_buckets",            # resolved: bucket merging geometry
    "i3d_pre_crop_size",       # i3d resize target
    "i3d_crop_size",           # i3d center crop
    "device_resize",           # resolved: jax.image.resize vs PIL drifts
    "device_preproc",          # resolved: fingerprints only where the device
                               # preprocess is inexact vs the host oracle —
                               # i3d (jax.image.resize vs PIL drifts, like
                               # device_resize) and vggish (f32 log-mel vs
                               # the f64 numpy DSP, ≤2e-5 but not byte-
                               # exact). resnet50 folds into device_resize
                               # (same path, one key). raft/pwc resolve
                               # False: replicate-pad on the uint8 wire is
                               # BYTE-exact (tests/test_device_preproc.py).
                               # r21d resolves False: documented no-op.
)

# Fields declared NOT to affect feature bytes. Each carries its reason; the
# byte-parity claims are pinned by the named test suites.
EXECUTION_FIELDS = (
    "video_paths",             # the work list, not the work
    "file_with_video_paths",   # ditto
    "tmp_path",                # scratch location
    "keep_tmp_files",          # scratch retention
    "on_extraction",           # print vs save — same arrays
    "output_path",             # where results land
    "batch_size",              # per-slot parity pinned (tests/test_packer*)
    "float32_wire",            # u8->fp32 cast is exact; staged bytes only
                               # (byte parity pinned by tests/test_ingest.py)
    "show_pred",               # extra prints; features unchanged
    "clips_per_batch",         # batching, parity pinned
    "num_devices",             # data-parallel sharding, parity pinned
    "resume",                  # skip logic
    "prefetch_depth",          # transfer pipelining
    "decode_workers",          # host decode parallelism
    "decode_segments",         # intra-video segmented decode: the stitched
                               # stream is byte-identical to sequential by
                               # construction (pinned by
                               # tests/test_segmented_decode.py)
    "segment_seek",            # seek mechanics for the same coded frames;
                               # every backend the auto policy accepts lands
                               # frame-exact (parity pinned as above)
    "pack_flush_age",          # dispatch timing, not numerics
    "paged_batching",          # dispatch mechanics; page outputs byte-match
                               # bucketed (pinned by tests/test_paged.py)
    "pages_in_flight",         # in-flight depth, not numerics
    "raft_corr",               # impl choice, parity pinned (tests/test_raft)
    "pwc_corr",                # impl choice, parity pinned (test_pallas_corr)
    "pwc_warp",                # impl choice, parity pinned (tests/test_pwc)
    "flow_pair_chunk",         # lax.map chunking, parity pinned
    "compilation_cache",       # XLA cache location
    "precompile",              # compile scheduling
    "async_writer",            # write scheduling, same bytes
    "profile_dir",             # observability
    "telemetry_dir",           # observability: the span journal records the
                               # run, it never touches feature bytes
    "retries",                 # reliability policy
    "retry_backoff",           # reliability policy
    "video_timeout",           # reliability policy
    "max_failures",            # reliability policy
    "retry_failed",            # work-list selection
    "serve",                   # entry point
    "spool_dir",               # serving transport
    "socket_path",             # serving transport
    "notify_dir",              # serving transport
    "tenant_quota",            # admission policy
    "tenant_max_failures",     # per-tenant breaker policy
    "idle_flush_sec",          # dispatch timing
    "spool_poll_sec",          # ingest polling
    "cache_dir",               # the cache's own location
    "cache_max_bytes",         # the cache's own budget
    "serve_models",            # which models a daemon co-loads; each job's
                               # key fingerprints ITS model's derived config
                               # (feature_type et al. above), so co-resident
                               # serving shares entries with single-model
                               # runs — pinned by tests/test_multimodel.py
    "wal_path",                # admission durability, not numerics
    "wal_fsync_sec",           # WAL fsync batching window
    "recover",                 # startup replay policy; replayed extraction
                               # is the same extraction
    "healthz_stale_sec",       # observability threshold
    "spool_retain",            # spool-file retention
    "step_watchdog_sec",       # stall policy; victims requeue, same bytes
)

# checkpoint names each feature type resolves (weights/store.py callers)
_CHECKPOINT_NAMES = {
    "resnet50": ("resnet50",),
    "r21d_rgb": ("r2plus1d_18",),
    "vggish": ("vggish",),
    "raft": ("raft-sintel",),
    "pwc": ("pwc-sintel",),
}


def _resolved(cfg):
    """Per-model defaults resolved before any keying decision: a raw
    ``ExtractionConfig(feature_type='i3d')`` (streams/stack/step still None)
    and its resolved equivalent (both streams, 64/64) describe the SAME
    extraction and must fingerprint identically — and the flow stream that
    ``streams=None`` implies must count as a flow stream below."""
    from ..config import resolve_model_defaults

    return resolve_model_defaults(cfg)


def _flow_affected(cfg) -> bool:
    """Flow-net padding knobs perturb numerics only where a flow net runs
    over replicate-padded frames: the flow extractors themselves, and the
    i3d sandwich when its flow stream is on. ``cfg`` must be resolved
    (``_resolved``) so default two-stream i3d counts."""
    if cfg.feature_type in ("raft", "pwc"):
        return True
    return cfg.feature_type == "i3d" and "flow" in (cfg.streams or ())


def _resolve_use_ffmpeg(cfg) -> str:
    """The backend that will actually resample, not the flag spelling —
    ``auto`` differs between hosts with and without ffmpeg installed."""
    if cfg.extraction_fps is None:
        return "unused"
    if cfg.use_ffmpeg == "never":
        return "native"
    if cfg.use_ffmpeg == "always":
        return "ffmpeg"
    from ..io.ffmpeg import have_ffmpeg

    return "ffmpeg" if have_ffmpeg() else "native"


def config_fingerprint(cfg) -> Dict[str, object]:
    """JSON-able ``{field: effective value}`` over FINGERPRINT_FIELDS.

    Conditional resolution keeps keys shared where parity is pinned:
    the flow-padding knobs (``shape_bucket``/``pack_corpus``/``pack_buckets``)
    collapse to None for configs with no flow net (packed RGB/audio outputs
    are byte-identical to the per-video loop), and ``use_ffmpeg`` resolves
    to the backend actually used.
    """
    cfg = _resolved(cfg)
    fp: Dict[str, object] = {}
    flow = _flow_affected(cfg)
    for name in FINGERPRINT_FIELDS:
        value = getattr(cfg, name)
        if name == "use_ffmpeg":
            value = _resolve_use_ffmpeg(cfg)
        elif name in ("shape_bucket", "pack_corpus", "pack_buckets"):
            value = value if flow else None
        elif name == "device_resize":
            # only resnet50 has a device-resize path; other feature types
            # print a notice and keep the (parity) host resize, so the flag
            # must not split their keys. --device_preproc IS the resize for
            # resnet50 (extractors/resnet.py ORs the two flags), so either
            # spelling lands on this one key component
            value = (bool(value or cfg.device_preproc)
                     if cfg.feature_type == "resnet50" else False)
        elif name == "device_preproc":
            # fingerprints only where the device preprocess drifts from the
            # host oracle (see the FINGERPRINT_FIELDS rationale): i3d's
            # device resize and vggish's f32 log-mel. resnet50 already
            # resolved into device_resize above; raft/pwc's device pad is
            # byte-exact and r21d's is a no-op — their keys must not split
            value = (bool(value)
                     if cfg.feature_type in ("i3d", "vggish") else False)
        elif isinstance(value, tuple):
            value = list(value)
        fp[name] = value
    return fp


def file_digest(path: str, chunk_bytes: int = 1 << 20) -> str:
    """Streaming SHA-256 of a file's bytes (bounded memory for any size).

    Raises ``OSError`` for unreadable paths — the caller treats that as a
    cache miss and lets the normal extraction path classify the failure.
    """
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk_bytes)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def weights_fingerprint(cfg) -> str:
    """Version component for the resolved model weights.

    ``VFT_WEIGHTS_VERSION`` (operator-pinned) wins outright. Otherwise each
    checkpoint the feature type resolves contributes ``name=<sha256[:16]>``
    of its file bytes; a missing checkpoint contributes ``random-seed0``
    when random weights are allowed (they are deterministic) or ``missing``
    (extraction would fail anyway, so the key value is moot). Checkpoint
    directories (``.orbax``) hash their manifest of (relpath, size) — cheap
    and stable for the interchange format's sharded layout.
    """
    pinned = os.environ.get("VFT_WEIGHTS_VERSION")
    if pinned:
        return f"pinned:{pinned}"
    from ..weights.store import ENV_ALLOW_RANDOM, _candidates

    cfg = _resolved(cfg)
    names = list(_CHECKPOINT_NAMES.get(cfg.feature_type, ()))
    if cfg.feature_type == "i3d":
        streams = cfg.streams or ("rgb", "flow")
        names = [f"i3d_{s}" for s in streams]
        if "flow" in streams:
            # the sandwich's flow net: swapping the raft/pwc checkpoint
            # must invalidate default two-stream i3d entries too
            names.append(f"{cfg.flow_type}-sintel")
    parts = []
    allow_random = os.environ.get(ENV_ALLOW_RANDOM) == "1"
    for name in names:
        found: Optional[str] = None
        for cand in _candidates(name):
            if os.path.exists(cand):
                found = cand
                break
        if found is None:
            parts.append(f"{name}=random-seed0" if allow_random
                         else f"{name}=missing")
        elif os.path.isdir(found):
            manifest = sorted(
                (os.path.relpath(os.path.join(dp, fn), found),
                 os.path.getsize(os.path.join(dp, fn)))
                for dp, _dn, fns in os.walk(found) for fn in fns)
            digest = hashlib.sha256(
                json.dumps(manifest).encode()).hexdigest()[:16]
            parts.append(f"{name}={digest}")
        else:
            parts.append(f"{name}={file_digest(found)[:16]}")
    return ";".join(parts) or "none"


def fingerprint_digest(cfg) -> str:
    """One stable hex digest over config fingerprint + weights version."""
    doc = {"config": config_fingerprint(cfg),
           "weights": weights_fingerprint(cfg)}
    return hashlib.sha256(
        json.dumps(doc, sort_keys=True).encode()).hexdigest()


def cache_key(content_digest: str, fp_digest: str) -> str:
    """The CAS key for (container bytes, model fingerprint)."""
    return hashlib.sha256(
        f"{content_digest}\n{fp_digest}".encode()).hexdigest()
