"""On-disk content-addressed store for finished feature dicts.

Layout: ``<cache_dir>/<key[:2]>/<key>.<body_sha[:16]>.npz`` — one file per
entry, the feature dict serialized as an uncompressed ``.npz`` whose byte
checksum is embedded in the FILE NAME. That makes every operation a
single-file primitive:

- **publish** is the ``io/output.py`` discipline — write the body to a tmp
  name, then one atomic ``os.replace``; a crash leaves either no entry or a
  complete one, and concurrent publishers of the same key converge on
  identical bytes.
- **read** re-hashes the body and compares against the name. A mismatch
  (torn write survived a crash, bit rot, manual edits) quarantines the file
  under ``<cache_dir>/quarantine/`` and reports a miss — classified as a
  :class:`..reliability.CacheError` in the warning, NEVER a crash: the
  extraction path simply recomputes and republishes.
- **LRU eviction**: a hit touches the entry's mtime; when a publish pushes
  the tracked total past ``max_bytes``, the oldest-mtime entries are removed
  until the cap holds (the just-published entry is never evicted, so a
  single oversized entry degrades to cache-through rather than thrashing).

Thread/process posture: one store instance is owned by the run-loop (or
daemon) thread — no locks, no threads spawned (vftlint thread-shared-state:
nothing to declare). Across PROCESSES sharing a cache directory, atomic
renames make publishes safe and a reader racing an eviction sees a plain
miss; the byte cap is per-process approximate.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
from typing import Dict, Mapping, Optional

import numpy as np

from ..io.output import atomic_write_bytes
from ..reliability import CacheError, classify

_BODY_DIGEST_LEN = 16


def _entry_rel(key: str, body_digest: str) -> str:
    return os.path.join(key[:2], f"{key}.{body_digest}.npz")


class FeatureCache:
    """Size-capped CAS: ``key → {name: np.ndarray}`` with LRU eviction."""

    def __init__(self, cache_dir: str, max_bytes: Optional[int] = None):
        self.cache_dir = cache_dir
        self.max_bytes = max_bytes
        self.quarantine_dir = os.path.join(cache_dir, "quarantine")
        os.makedirs(cache_dir, exist_ok=True)
        # path -> size for every live entry; seeds the byte cap from disk so
        # restarts keep honoring it
        self._entries: Dict[str, int] = {}
        self._total_bytes = 0
        self._scan()
        # cumulative counters (the run report / serve stats op surface)
        self.hits = 0
        self.misses = 0
        self.hit_bytes = 0
        self.puts = 0
        self.put_bytes = 0
        self.evictions = 0
        self.evicted_bytes = 0
        self.quarantined = 0
        # optional ..obs.SpanJournal (set by the owning extractor once
        # telemetry opens): quarantines and evictions are rare, operator-
        # relevant events — they land in the journal alongside the request
        # lifecycle so "why did the hit rate dip?" is answerable after the
        # fact. Emit-only; a missing journal costs one None check.
        self.journal = None

    # --- read ----------------------------------------------------------------

    def get(self, key: str) -> Optional[Dict[str, np.ndarray]]:
        """The cached feature dict for ``key``, or None (miss). Never raises:
        unreadable and corrupt entries are quarantined misses."""
        path = self._find(key)
        if path is None:
            self.misses += 1
            return None
        try:
            with open(path, "rb") as f:
                data = f.read()
            want = os.path.basename(path).rsplit(".", 2)[1]
            got = hashlib.sha256(data).hexdigest()[:_BODY_DIGEST_LEN]
            if got != want:
                raise CacheError(
                    f"checksum mismatch (name {want}, bytes {got})")
            with np.load(io.BytesIO(data)) as z:
                feats = {name: z[name] for name in z.files}
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — fault-barrier: a cache entry of ANY state must read as a miss, never crash the run
            self._quarantine(path, e)
            self.misses += 1
            return None
        try:  # LRU recency; best-effort (a read-only mount still serves hits)
            os.utime(path)
        except OSError:
            pass
        self.hits += 1
        self.hit_bytes += len(data)
        return feats

    # --- write ---------------------------------------------------------------

    def put(self, key: str, feats_dict: Mapping[str, np.ndarray]) -> bool:
        """Publish ``feats_dict`` under ``key``; True when an entry is live
        afterwards. Never raises: a cache that cannot write degrades to a
        pass-through (warn once per failure), it must not fail the video."""
        existing = self._find(key)
        if existing is not None:
            return True  # same key ⇒ same inputs ⇒ same bytes; keep it
        try:
            buf = io.BytesIO()
            np.savez(buf, **{name: np.asarray(v)
                             for name, v in feats_dict.items()})
            data = buf.getvalue()
            body = hashlib.sha256(data).hexdigest()[:_BODY_DIGEST_LEN]
            path = os.path.join(self.cache_dir, _entry_rel(key, body))
            os.makedirs(os.path.dirname(path), exist_ok=True)
            atomic_write_bytes(path, data)
        except KeyboardInterrupt:
            raise
        except Exception as e:  # noqa: BLE001 — fault-barrier: publish is best-effort; a full/broken cache disk must not fail the video it caches
            err_class, _ = classify(CacheError(str(e)))
            print(f"warning: [{err_class}] could not publish cache entry "
                  f"{key[:12]}…: {e}", file=sys.stderr)
            return False
        self.puts += 1
        self.put_bytes += len(data)
        self._entries[path] = len(data)
        self._total_bytes += len(data)
        self._evict(keep=path)
        return True

    # --- internals -----------------------------------------------------------

    def _find(self, key: str) -> Optional[str]:
        d = os.path.join(self.cache_dir, key[:2])
        try:
            names = os.listdir(d)
        except OSError:
            return None
        prefix = key + "."
        for name in sorted(names):
            if name.startswith(prefix) and name.endswith(".npz"):
                return os.path.join(d, name)
        return None

    def _quarantine(self, path: str, exc: BaseException) -> None:
        err_class, _ = classify(
            exc if isinstance(exc, CacheError) else CacheError(str(exc)))
        dest = os.path.join(self.quarantine_dir, os.path.basename(path))
        try:
            os.makedirs(self.quarantine_dir, exist_ok=True)
            os.replace(path, dest)
            moved = f"quarantined to {dest}"
        except OSError as move_err:
            moved = f"could not quarantine ({move_err})"
        self.quarantined += 1
        self._drop_accounting(path)
        if self.journal is not None:
            self.journal.emit("cache_quarantine",
                              entry=os.path.basename(path))
        print(f"warning: [{err_class}] corrupt cache entry "
              f"{os.path.basename(path)}: {exc}; {moved}; treating as a miss",
              file=sys.stderr)

    def _drop_accounting(self, path: str) -> None:
        size = self._entries.pop(path, None)
        if size is not None:
            self._total_bytes -= size

    def _evict(self, keep: str) -> None:
        """Oldest-mtime entries out until ``max_bytes`` holds (LRU: hits
        touch mtime). ``keep`` (the just-published entry) is exempt."""
        if self.max_bytes is None or self._total_bytes <= self.max_bytes:
            return
        by_age = []
        for path in list(self._entries):
            if path == keep:
                continue
            try:
                by_age.append((os.path.getmtime(path), path))
            except OSError:  # raced an external removal: drop the record
                self._drop_accounting(path)
        for _mtime, path in sorted(by_age):
            if self._total_bytes <= self.max_bytes:
                break
            size = self._entries.get(path, 0)
            try:
                os.remove(path)
            except OSError as e:
                print(f"warning: could not evict cache entry {path}: {e}",
                      file=sys.stderr)
                continue
            self._drop_accounting(path)
            self.evictions += 1
            self.evicted_bytes += size
            if self.journal is not None:
                self.journal.emit("cache_evict",
                                  entry=os.path.basename(path), bytes=size)

    def _scan(self) -> None:
        for dirpath, dirnames, filenames in os.walk(self.cache_dir):
            if os.path.abspath(dirpath).startswith(
                    os.path.abspath(self.quarantine_dir)):
                continue
            dirnames[:] = [d for d in dirnames if d != "quarantine"]
            for name in filenames:
                if not name.endswith(".npz"):
                    continue
                path = os.path.join(dirpath, name)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    continue
                self._entries[path] = size
                self._total_bytes += size

    # --- introspection -------------------------------------------------------

    @property
    def hit_rate(self) -> float:
        lookups = self.hits + self.misses
        return self.hits / lookups if lookups else 0.0

    def stats(self) -> Dict[str, object]:
        return {
            "enabled": True,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate, 4),
            "hit_bytes": self.hit_bytes,
            "puts": self.puts,
            "put_bytes": self.put_bytes,
            "evictions": self.evictions,
            "evicted_bytes": self.evicted_bytes,
            "quarantined": self.quarantined,
            "entries": len(self._entries),
            "total_bytes": self._total_bytes,
            "max_bytes": self.max_bytes,
        }
