"""Content-addressed feature cache (ROADMAP item 5, the production story).

At heavy traffic most uploads are duplicates: the cheapest device step is the
one never dispatched. This package maps ``sha256(container bytes) × model-
config fingerprint → finished feature dict`` so a repeated video costs one
hash and one read — zero decode, zero device steps — the same work-reuse
instinct that drives prefix/page reuse in Ragged Paged Attention and the
persistent artifact stores of production ML systems (PAPERS.md).

Pieces:

- :mod:`.key` — the cache key: a streaming content digest combined with a
  fingerprint over exactly the config fields that affect feature numerics
  (every :class:`..config.ExtractionConfig` field is classified fingerprint
  vs execution, pinned by tests/test_cache.py so adding a flag forces a
  keying decision) plus a weights-version component.
- :mod:`.store` — the on-disk CAS: atomic tmp+rename publish (the
  ``io/output.py`` discipline), checksum-verified reads where a corrupt
  entry is quarantined and treated as a miss (classified
  :class:`..reliability.CacheError`, never a crash), and size-capped LRU
  eviction behind ``--cache_dir`` / ``--cache_max_bytes``.
- :mod:`.coalesce` — in-flight dedup for the serving daemon: N tenants
  submitting identical content run ONE extraction; waiters replay from the
  fresh entry, and a leader failure requeues them instead of poisoning
  innocent tenants' breakers.

Integration lives at both entry points: the batch run loops
(:mod:`..extractors.base`) consult the cache before decode and publish on
the shared output path (cache-hit videos still write done-manifest entries,
so ``--resume`` composes deterministically), and the daemon
(:mod:`..serve.daemon`) adds the coalescing layer. See docs/caching.md.
"""

from .coalesce import InflightCoalescer
from .key import (
    EXECUTION_FIELDS,
    FINGERPRINT_FIELDS,
    cache_key,
    config_fingerprint,
    file_digest,
    fingerprint_digest,
    weights_fingerprint,
)
from .store import FeatureCache

__all__ = [
    "EXECUTION_FIELDS",
    "FINGERPRINT_FIELDS",
    "FeatureCache",
    "InflightCoalescer",
    "cache_key",
    "config_fingerprint",
    "file_digest",
    "fingerprint_digest",
    "weights_fingerprint",
]
