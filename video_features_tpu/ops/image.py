"""Image preprocessing: host-side PIL-semantics resize, device-side crop/normalize.

The reference resizes frames with PIL bilinear (``models/i3d/transforms/
transforms.py:87-137`` ``resize``/``ResizeImproved``) and crops/normalizes in torch.
PIL's resampling differs from XLA's ``jax.image.resize`` in rounding and filter
support, so for bit-parity the aspect-preserving edge resize stays on the host (PIL on
uint8 is exactly what the reference computes); everything after — center crop, scaling
to [-1,1], flow quantization — is pure elementwise math and runs on device inside the
jitted forward (:mod:`video_features_tpu.extractors`), where XLA fuses it into the
first conv. ``--device_resize`` (resnet50) and ``--device_preproc`` (its
every-model generalization — resnet50 frames and i3d clip stacks alike) opt the
edge resize itself onto the device too (:func:`device_resize_crop_hwc` /
:func:`device_edge_resize_hwc`) — raw decoded frames on the wire, the whole
preprocess fused into the step — trading that bit-parity contract for ingest
throughput at a tolerance pinned in tests/test_ingest.py and
tests/test_device_preproc.py.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
from PIL import Image

import jax.numpy as jnp


def edge_resize_size(
    width: int, height: int, size: int, to_smaller_edge: bool = True
) -> Tuple[int, int]:
    """Output (width, height) of the aspect-preserving edge resize.

    Matches the reference's int-truncation arithmetic (``transforms.py:114-125``): the
    chosen edge becomes ``size``, the other ``int(size * other / chosen)``; no-op when
    the chosen edge already equals ``size`` and the image is no larger on the other
    axis than required by PIL semantics.
    """
    w, h = width, height
    if (w <= h and w == size) or (h <= w and h == size):
        return w, h
    if (w < h) == to_smaller_edge:
        return size, int(size * h / w)
    return int(size * w / h), size


def pil_edge_resize(
    rgb_hwc: np.ndarray, size: Optional[int], to_smaller_edge: bool = True
) -> np.ndarray:
    """Resize an RGB uint8 HWC frame so its smaller (or larger) edge equals ``size``.

    PIL bilinear on uint8 — identical bytes to the reference's host path. ``size=None``
    is the identity (RAFT/PWC run at native resolution unless ``--side_size``).
    """
    if size is None:
        return rgb_hwc
    h, w = rgb_hwc.shape[:2]
    ow, oh = edge_resize_size(w, h, size, to_smaller_edge)
    if (ow, oh) == (w, h):
        return rgb_hwc
    return np.asarray(Image.fromarray(rgb_hwc).resize((ow, oh), Image.BILINEAR))


def device_edge_resize_hwc(x: jnp.ndarray, size: int,
                           to_smaller_edge: bool = True) -> jnp.ndarray:
    """Traced aspect-preserving edge resize for (..., H, W, C) frames — the
    crop-free core of the device-side preprocessing fast path
    (docs/performance.md "ingest fast path").

    The host ships RAW decoded uint8 frames (single frames or whole clip
    stacks — any leading dims) and this runs INSIDE the jitted step:
    ``jax.image.resize`` bilinear (antialiased on downscale) to the same
    target the reference's PIL resize computes (``edge_resize_size``
    arithmetic, static at trace time). NOT bit-identical to
    :func:`pil_edge_resize` — PIL interpolates in uint8 with its own filter
    support and rounding, XLA in float — which is exactly why the module
    contract above keeps the host path as the parity default; the drift is
    tolerance-pinned in tests/test_ingest.py and tests/test_device_preproc.py.
    Exposed crop-free because the i3d flow stream computes flow on the
    RESIZED (pre-crop) stack and crops only after the flow net — the crop
    cannot be fused into the resize there. Returns float32 frames in
    [0, 255] at the resized geometry.
    """
    import jax

    h, w = int(x.shape[-3]), int(x.shape[-2])
    ow, oh = edge_resize_size(w, h, size, to_smaller_edge)
    y = x.astype(jnp.float32)
    if (ow, oh) != (w, h):
        y = jax.image.resize(
            y, x.shape[:-3] + (oh, ow, x.shape[-1]), method="bilinear")
    return y


def device_resize_crop_hwc(x: jnp.ndarray, size: int, crop: int,
                           to_smaller_edge: bool = True) -> jnp.ndarray:
    """:func:`device_edge_resize_hwc` + the torchvision round-half center
    crop — the ``--device_resize`` / ``--device_preproc`` resnet50 step
    prologue. Returns float32 frames in [0, 255] (N, crop, crop, C).
    """
    y = device_edge_resize_hwc(x, size, to_smaller_edge)
    oh, ow = int(y.shape[-3]), int(y.shape[-2])
    i = int(round((oh - crop) / 2.0))
    j = int(round((ow - crop) / 2.0))
    return y[..., i : i + crop, j : j + crop, :]


def center_crop(x: jnp.ndarray, crop_size: int) -> jnp.ndarray:
    """Center crop over the trailing two spatial dims (``transforms.py:7-18``)."""
    h, w = x.shape[-2], x.shape[-1]
    fh = (h - crop_size) // 2
    fw = (w - crop_size) // 2
    return x[..., fh : fh + crop_size, fw : fw + crop_size]


def center_crop_hw(x: jnp.ndarray, th: int, tw: int) -> jnp.ndarray:
    """Center crop to (th, tw) with round-half-up offsets (R21D semantics,
    ``r21d/transforms/rgb_transforms.py`` ``center_crop``: ``int(round((h-th)/2))``)."""
    h, w = x.shape[-2], x.shape[-1]
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return x[..., i : i + th, j : j + tw]


def scale_to_pm1(x: jnp.ndarray) -> jnp.ndarray:
    """[0,255] → [-1,1]: ``2x/255 - 1`` (``transforms.py:21-24``)."""
    return 2.0 * x / 255.0 - 1.0


def flow_to_uint8_levels(flow: jnp.ndarray) -> jnp.ndarray:
    """Clamp flow to ±20 and quantize to uint8 levels (kept float).

    ``round(128 + 255/40 * clamp(f, -20, 20))`` — the kinetics-i3d flow preprocessing
    the reference applies before its flow I3D stream (``transforms.py:43-51`` with
    ``Clamp(-20,20)`` from ``extract_i3d.py:65-71``). jnp.round matches torch's
    round-half-to-even.
    """
    clamped = jnp.clip(flow, -20.0, 20.0)
    return jnp.round(128.0 + 255.0 / 40.0 * clamped)


def np_center_crop_hwc(frame: np.ndarray, th: int, tw: int) -> np.ndarray:
    """Host-side center crop of an HWC frame with torchvision's round-half offsets
    (``torchvision.transforms.CenterCrop``: ``crop_top = int(round((h - th) / 2))``)."""
    h, w = frame.shape[:2]
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return frame[i : i + th, j : j + tw]


def imagenet_normalize(x: jnp.ndarray, mean, std) -> jnp.ndarray:
    """Channel-wise (x/255 - mean)/std for CHW or NCHW float input in [0,255]."""
    mean = jnp.asarray(mean, x.dtype).reshape(-1, 1, 1)
    std = jnp.asarray(std, x.dtype).reshape(-1, 1, 1)
    return (x / 255.0 - mean) / std
