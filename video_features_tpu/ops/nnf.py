"""Functional NN primitives on NHWC for the weight-tied flow nets.

RAFT runs one update block 20 times (``/root/reference/models/raft/raft_src/raft.py:151-168``)
— on TPU that is a ``lax.scan`` over a pure function of a param pytree, not a module
graph. These helpers are the conv/norm vocabulary those pure functions are written
in. Param leaves follow Flax conventions (``kernel`` HWIO, ``bias``; norms use
``scale``/``bias``/``mean``/``var``) so converted checkpoints are ordinary pytrees.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import lax

Pad = Union[str, int, Tuple[int, int], Sequence[Tuple[int, int]]]


def conv2d(p: dict, x: jnp.ndarray, stride: int = 1, padding: Pad = 0,
           dilation: int = 1) -> jnp.ndarray:
    """torch ``Conv2d`` numerics on NHWC with an HWIO kernel pytree ``p``."""
    if isinstance(padding, int):
        padding = ((padding, padding), (padding, padding))
    elif isinstance(padding, tuple) and len(padding) == 2 and isinstance(padding[0], int):
        padding = ((padding[0], padding[0]), (padding[1], padding[1]))
    y = lax.conv_general_dilated(
        x,
        p["kernel"].astype(x.dtype),
        window_strides=(stride, stride),
        padding=padding,
        rhs_dilation=(dilation, dilation),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


def conv2d_transpose(p: dict, x: jnp.ndarray, stride: int = 2, padding: int = 1,
                     kernel_size: int = 4) -> jnp.ndarray:
    """torch ``ConvTranspose2d(k, stride, padding)`` numerics on NHWC.

    Implemented as the gradient-of-conv (what torch computes): lhs dilation by
    ``stride`` with padding ``k − 1 − padding`` and a spatially-flipped kernel.
    Kernel pytree stores HWIO of the *forward* conv orientation (converted from
    torch's (in, out, kh, kw) layout).
    """
    k = kernel_size
    pad = k - 1 - padding
    y = lax.conv_general_dilated(
        x,
        jnp.flip(p["kernel"], (0, 1)).astype(x.dtype),
        window_strides=(1, 1),
        padding=((pad, pad), (pad, pad)),
        lhs_dilation=(stride, stride),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if "bias" in p:
        y = y + p["bias"].astype(y.dtype)
    return y


def instance_norm(x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """torch ``InstanceNorm2d`` defaults: no affine, biased variance, per (n, c)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=(1, 2), keepdims=True)
    return ((x32 - mean) / jnp.sqrt(var + eps)).astype(x.dtype)


def batch_norm_eval(p: dict, x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Eval-mode BatchNorm from stored statistics (fp32 affine, cast back)."""
    inv = p["scale"].astype(jnp.float32) / jnp.sqrt(p["var"].astype(jnp.float32) + eps)
    return ((x.astype(jnp.float32) - p["mean"]) * inv + p["bias"]).astype(x.dtype)


def avg_pool2d(x: jnp.ndarray, window: int = 2, stride: Optional[int] = None) -> jnp.ndarray:
    """torch ``F.avg_pool2d`` (VALID, count includes full window) on NHWC."""
    stride = stride or window
    summed = lax.reduce_window(
        x.astype(jnp.float32), 0.0, lax.add,
        (1, window, window, 1), (1, stride, stride, 1), "VALID",
    )
    return (summed / (window * window)).astype(x.dtype)


def leaky_relu(x: jnp.ndarray, negative_slope: float = 0.1) -> jnp.ndarray:
    return jnp.where(x >= 0, x, negative_slope * x)


def extract_patches_3x3(x: jnp.ndarray) -> jnp.ndarray:
    """3×3 zero-padded neighborhoods: (N, H, W, C) → (N, H, W, 9, C), window
    row-major (dy, dx) — torch ``F.unfold(x, [3,3], padding=1)`` tap order."""
    n, h, w, c = x.shape
    xp = jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))
    taps = [
        xp[:, dy : dy + h, dx : dx + w, :]
        for dy in range(3)
        for dx in range(3)
    ]
    return jnp.stack(taps, axis=3)
