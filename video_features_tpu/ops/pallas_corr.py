"""Cost-volume correlation kernels: XLA formulation + hand-tiled Pallas kernel.

The reference implements PWC's 81-tap correlation as four raw CUDA kernels
JIT-compiled through CuPy (``/root/reference/models/pwc/pwc_src/correlation.py:17-242``).
Semantics: pad fmap2 by 4 px, mean-over-channels dot product between each pixel
of fmap1 and its 9×9 neighborhood in fmap2 → ``(B, H, W, 81)`` with channel
``k = (dy+4)·9 + (dx+4)`` (``:79-81``; forward-only — inference framework).

Two TPU implementations, selectable per call (``--pwc_corr``):

- ``xla``: 81 shifted elementwise products + channel mean. XLA fuses the shifts
  into a few HBM passes; this is the parity-proven default.
- ``pallas``: VMEM-resident kernels. Spatial sizes ≤16² run the single-block
  kernel (whole image per grid step); larger sizes run the spatially TILED
  kernel (``corr81_pallas_tiled``: 16×16 output blocks, the haloed f2 held
  VMEM-resident per image) — the axon Mosaic backend rejects >16² compute
  tiles, so tiling is how the 32²/64² PWC levels get in-kernel. Shapes whose
  resident f2 exceeds the VMEM budget (``_pallas_tiled_supported``) and
  non-fp32 dtypes fall back to ``xla``; dispatch is static per call site, so
  one PWC forward may mix kernel and XLA levels.

Both are exercised by tests/test_pallas_corr.py (Pallas in interpreter mode on
CPU, compiled on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

CORR_RADIUS = 4
CORR_CHANNELS = (2 * CORR_RADIUS + 1) ** 2  # 81

# conservative per-core VMEM budget for the tile working set (bytes)
_VMEM_BUDGET = 12 * 1024 * 1024


def corr81_xla(f1: jnp.ndarray, f2: jnp.ndarray) -> jnp.ndarray:
    """Channel-mean cost volume over the 9×9 displacement window (pure XLA).

    Accumulates in fp32 whatever the feature dtype; the result is cast back to
    the input dtype so a bf16 forward stays bf16 downstream (a fp32 volume
    would silently promote every decoder conv through ``concatenate``).
    """
    b, h, w, c = f1.shape
    r = CORR_RADIUS
    dtype = f1.dtype
    f2p = jnp.pad(f2, ((0, 0), (r, r), (r, r), (0, 0)))
    f1 = f1.astype(jnp.float32)
    taps = []
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            shifted = f2p[:, r + dy : r + dy + h, r + dx : r + dx + w, :].astype(jnp.float32)
            taps.append(jnp.mean(f1 * shifted, axis=-1))
    # stack taps on axis 1 then move to the channel position: stacking 81
    # single-channel (…, 1) arrays directly on the minor axis makes XLA pad
    # each temp to the 128-lane tile — a 128× memory blowup that OOM'd the
    # 64-pair I3D sandwich at 256×341 (15.8 GiB of f32[64,64,96,1] copies).
    # With W as the minor dim the temps pad ≤1.34× and one cheap relayout
    # produces the (B, H, W, 81) the decoders consume.
    return jnp.moveaxis(jnp.stack(taps, axis=1), 1, -1).astype(dtype)


def _corr81_kernel(f1_ref, f2p_ref, out_ref):
    """One batch element per grid step; everything VMEM-resident.

    f1 (1, H, W, C), f2p (1, H+8, W+8, C) → out (1, H, W, 81). The 81 window
    taps are unrolled statically; each is a VPU multiply + lane reduction.
    Accumulation is fp32 regardless of the feature dtype; the store casts to
    the output dtype (bf16 forwards keep a bf16 volume downstream).
    """
    f1 = f1_ref[0].astype(jnp.float32)
    h, w, c = f1.shape
    taps = []
    for dy in range(2 * CORR_RADIUS + 1):
        for dx in range(2 * CORR_RADIUS + 1):
            shifted = f2p_ref[0, dy : dy + h, dx : dx + w, :].astype(jnp.float32)
            taps.append(jnp.sum(f1 * shifted, axis=-1) * (1.0 / c))
    out_ref[0] = jnp.stack(taps, axis=-1).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def corr81_pallas(f1: jnp.ndarray, f2: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Pallas tile kernel; grid over the batch axis.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    from jax.experimental import pallas as pl

    b, h, w, c = f1.shape
    r = CORR_RADIUS
    f2p = jnp.pad(f2, ((0, 0), (r, r), (r, r), (0, 0)))
    return pl.pallas_call(
        _corr81_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, w, CORR_CHANNELS), f1.dtype),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h + 2 * r, w + 2 * r, c), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w, CORR_CHANNELS), lambda i: (i, 0, 0, 0)),
        interpret=interpret,
    )(f1, f2p)


_TILE = 16  # largest tile the axon Mosaic backend compiles (>16² → HTTP 500)


def _corr81_kernel_tiled(f1_ref, f2p_ref, out_ref):
    """Spatially tiled kernel: one 16×16 output block per grid step.

    Grid (b, nh, nw). ``f1`` arrives as a (1, 16, 16, C) block; the padded
    ``f2`` arrives as the FULL (1, Hp+8, Wp+8, C) image — its block index is
    constant across (j, k), so Mosaic keeps it VMEM-resident instead of
    re-fetching per step. The 24×24 haloed window for this block is a dynamic
    slice; the 81 taps are static shifts within it.
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    k = pl.program_id(2)
    halo = 2 * CORR_RADIUS
    tile = f2p_ref[0, pl.dslice(j * _TILE, _TILE + halo),
                   pl.dslice(k * _TILE, _TILE + halo), :]
    f1 = f1_ref[0].astype(jnp.float32)
    c = f1.shape[-1]
    taps = []
    for dy in range(2 * CORR_RADIUS + 1):
        for dx in range(2 * CORR_RADIUS + 1):
            shifted = tile[dy : dy + _TILE, dx : dx + _TILE, :].astype(jnp.float32)
            taps.append(jnp.sum(f1 * shifted, axis=-1) * (1.0 / c))
    out_ref[0] = jnp.stack(taps, axis=-1).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def corr81_pallas_tiled(f1: jnp.ndarray, f2: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """Tiled Pallas cost volume for spatial sizes beyond the 16² Mosaic cap.

    Pads H/W to multiples of the tile (zero rows/cols — out-of-bounds f2 taps
    contribute zeros, exactly the reference's zero-padding; the padded f1 rows
    produce extra output rows sliced off afterwards).
    """
    from jax.experimental import pallas as pl

    b, h, w, c = f1.shape
    r = CORR_RADIUS
    ph = (-h) % _TILE
    pw = (-w) % _TILE
    f1p = jnp.pad(f1, ((0, 0), (0, ph), (0, pw), (0, 0)))
    f2p = jnp.pad(f2, ((0, 0), (r, r + ph), (r, r + pw), (0, 0)))
    hp, wp = h + ph, w + pw
    out = pl.pallas_call(
        _corr81_kernel_tiled,
        out_shape=jax.ShapeDtypeStruct((b, hp, wp, CORR_CHANNELS), f1.dtype),
        grid=(b, hp // _TILE, wp // _TILE),
        in_specs=[
            pl.BlockSpec((1, _TILE, _TILE, c), lambda i, j, k: (i, j, k, 0)),
            pl.BlockSpec((1, hp + 2 * r, wp + 2 * r, c), lambda i, j, k: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TILE, _TILE, CORR_CHANNELS),
                               lambda i, j, k: (i, j, k, 0)),
        interpret=interpret,
    )(f1p, f2p)
    return out[:, :h, :w, :]


def _pallas_tiled_supported(b: int, h: int, w: int, c: int, itemsize: int = 4) -> bool:
    """VMEM gate for the tiled kernel: the resident PER-IMAGE f2p + one
    f1/out block pair, double-buffered, must fit the budget.

    Unlike the single-block kernel (whose empirical budget scales with B —
    see ``_pallas_supported``), the tiled call's buffers are streamed per
    block: validated compiled on the axon v5e backend at b=16 × 64² × c32
    (the largest PWC corr level at a 256² input), where a whole-buffer VMEM
    assignment could not possibly fit — so only the per-step working set
    counts here. ``itemsize``: feature bytes (2 for bf16 halves the resident
    f2p and widens the supported set)."""
    r = CORR_RADIUS
    hp = h + (-h) % _TILE
    wp = w + (-w) % _TILE
    f2p_bytes = (hp + 2 * r) * (wp + 2 * r) * c * itemsize
    blk_bytes = _TILE * _TILE * (c + CORR_CHANNELS) * itemsize
    return 2 * (f2p_bytes + blk_bytes) <= _VMEM_BUDGET


def _pallas_supported(b: int, h: int, w: int, c: int, itemsize: int = 4) -> bool:
    """Shape gate for the compiled kernel on the axon v5e backend (observed):

    - XLA's memory-space assignment keeps the pallas call's full operands +
      output in VMEM with double buffering, so the budget must cover
      B × (f1 + padded f2 + out) × 2;
    - tiles larger than 16×16 crash the backend's Mosaic compile subprocess
      (HTTP 500 from tpu_compile_helper); ≤16² compiles and is bit-exact.

    PWC's coarse pyramid levels (4²–16² at a 256² input) take the kernel;
    finer levels fall back to the fused XLA formulation — dispatch is static
    per call site, so a single forward mixes both.
    """
    if h > 16 or w > 16:
        return False
    r = CORR_RADIUS
    per_elem = itemsize * (
        h * w * c + (h + 2 * r) * (w + 2 * r) * c + h * w * CORR_CHANNELS)
    return 2 * b * per_elem <= _VMEM_BUDGET


# feature dtypes the compiled kernels accept (accumulation is fp32 in-kernel
# either way; bf16 was parity-checked on the axon v5e backend the same way
# fp32 was — tests/test_pallas_corr.py exercises both in interpreter mode)
_KERNEL_DTYPES = (jnp.float32, jnp.bfloat16)


# ---------------------------------------------------------------------------
# Fused backward-warp + correlation (PWC decoder levels 5..2)
#
# The reference composes two CUDA stages: grid_sample-style backward warp of
# fmap2 by the upsampled flow (pwc_net.py:23-41) then the 81-tap correlation
# (correlation.py:44-112), materializing the warped fmap2 in HBM between them.
# The XLA composition additionally lowers the warp's 4 corner gathers to
# take_along_axis — scalar-unit bound on TPU (docs/architecture.md: the PWC
# floor). This kernel does both in ONE VMEM pass per 16×16 output tile:
#
# - f2 (full image) and the zero-padded flow stay VMEM-resident per image;
# - the 24×24 haloed warped tile is computed in-kernel: each bilinear corner
#   is an EXACT one-hot selection matmul (rows have a single 1.0, so even a
#   bf16 MXU pass reproduces the gathered value bit-for-bit) and the four
#   fractional weights combine on the VPU — the TPU-native replacement for
#   the gather (same trick as RAFT's measured 15.5× one-hot window lookup);
# - the reference's partial-tap zeroing (warped ones-channel ≤ 0.999 → zero
#   the pixel) falls out of the corner in-bounds weights, no extra pass;
# - out-of-image halo positions get zero weights automatically, reproducing
#   the correlation's zero padding;
# - the 81 taps then run VMEM-resident exactly like _corr81_kernel_tiled.
# ---------------------------------------------------------------------------


def _halo_chunk_rows(hw: int) -> int:
    """Halo rows per one-hot chunk: keep each (rows·24, H·W) fp32 selection
    matrix under ~2 MB of VMEM; 24 = _TILE + 2·CORR_RADIUS halo rows total."""
    halo = _TILE + 2 * CORR_RADIUS
    for rows in (24, 12, 8, 6, 4, 3, 2, 1):
        if rows * halo * hw * 4 <= 2 * 1024 * 1024:
            return rows
    return 1


def _warp_corr81_kernel(f1_ref, f2_ref, flowp_ref, out_ref):
    """Grid (b, nh, nw): one 16×16 output block per step.

    f1 (1, T, T, C) block; f2 (1, H, W, C) full image (constant block index —
    VMEM-resident); flowp (1, Hp+8, Wp+8, 2) full zero-padded scaled flow;
    out (1, T, T, 81).
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    k = pl.program_id(2)
    r = CORR_RADIUS
    halo = _TILE + 2 * r  # 24
    _, h, w, c = f2_ref.shape
    hw = h * w
    f2_flat = f2_ref[0].reshape(hw, c)
    exact = (jax.lax.Precision.HIGHEST if f2_flat.dtype == jnp.float32
             else jax.lax.Precision.DEFAULT)  # bf16 selection is exact as-is
    f1 = f1_ref[0].astype(jnp.float32)

    hc = _halo_chunk_rows(hw)
    chunks = []
    for r0 in range(0, halo, hc):
        rows = min(hc, halo - r0)
        p = rows * halo
        # global warped-image coordinates of this halo chunk (may be < 0 or
        # ≥ H/W on the border tiles — those positions get zero weights below)
        # int32 iota + cast: Mosaic's tpu.iota is integer-only
        iy = jax.lax.broadcasted_iota(jnp.int32, (rows, halo), 0).astype(jnp.float32)
        ix = jax.lax.broadcasted_iota(jnp.int32, (rows, halo), 1).astype(jnp.float32)
        gy = (j * _TILE + r0 - r).astype(jnp.float32) + iy
        gx = (k * _TILE - r).astype(jnp.float32) + ix
        fl = flowp_ref[0, pl.dslice(j * _TILE + r0, rows),
                       pl.dslice(k * _TILE, halo), :].astype(jnp.float32)
        x = gx + fl[..., 0]
        y = gy + fl[..., 1]
        x0 = jnp.floor(x)
        y0 = jnp.floor(y)
        wx = x - x0
        wy = y - y0
        acc = jnp.zeros((rows, halo, c), jnp.float32)
        ones_acc = jnp.zeros((rows, halo), jnp.float32)
        # NB Mosaic reshape rule: only reshapes that PRESERVE the minor (lane)
        # dim compile on this backend — (rows, halo, hw)→(p, hw) and
        # (p, c)→(rows, halo, c) are fine, (rows, halo)→(p, 1) is not.
        iota3 = jax.lax.broadcasted_iota(jnp.int32, (rows, halo, hw), 2)
        for dy, dx, wgt in ((0, 0, (1 - wy) * (1 - wx)), (0, 1, (1 - wy) * wx),
                            (1, 0, wy * (1 - wx)), (1, 1, wy * wx)):
            xi = x0 + dx
            yi = y0 + dy
            inb = ((xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1))
            idx = (jnp.clip(yi, 0, h - 1) * w + jnp.clip(xi, 0, w - 1)
                   ).astype(jnp.int32)
            onehot = (idx[:, :, None] == iota3).astype(f2_flat.dtype)
            sel = jax.lax.dot_general(
                onehot.reshape(p, hw), f2_flat, (((1,), (0,)), ((), ())),
                precision=exact, preferred_element_type=jnp.float32)
            wgt_eff = wgt * inb.astype(jnp.float32)
            acc = acc + wgt_eff[:, :, None] * sel.reshape(rows, halo, c)
            ones_acc = ones_acc + wgt_eff
        # reference partial-tap zeroing: any out-of-bounds leakage (sampled
        # ones ≤ 0.999) zeroes the whole pixel (pwc_net.py:36-40)
        keep = (ones_acc > 0.999).astype(jnp.float32)
        chunks.append(acc * keep[:, :, None])
    warped = jnp.concatenate(chunks, axis=0)  # (24, 24, C) fp32

    taps = []
    for dy in range(2 * r + 1):
        for dx in range(2 * r + 1):
            shifted = warped[dy : dy + _TILE, dx : dx + _TILE, :]
            taps.append(jnp.sum(f1 * shifted, axis=-1) * (1.0 / c))
    out_ref[0] = jnp.stack(taps, axis=-1).astype(out_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def warp_corr81_pallas(f1: jnp.ndarray, f2: jnp.ndarray, flow: jnp.ndarray,
                       interpret: bool = False) -> jnp.ndarray:
    """Fused ``corr81(f1, warp_backward(f2, flow))`` — flow already scaled.

    Pads H/W to tile multiples (padded f1 rows produce sliced-off outputs;
    padded flow/out-of-image warp targets get zero weights in-kernel, which
    IS the correlation's zero padding + the warp's border zeroing).
    """
    from jax.experimental import pallas as pl

    b, h, w, c = f1.shape
    r = CORR_RADIUS
    ph = (-h) % _TILE
    pw = (-w) % _TILE
    hp, wp = h + ph, w + pw
    f1p = jnp.pad(f1, ((0, 0), (0, ph), (0, pw), (0, 0)))
    flowp = jnp.pad(flow.astype(jnp.float32),
                    ((0, 0), (r, r + ph), (r, r + pw), (0, 0)))
    out = pl.pallas_call(
        _warp_corr81_kernel,
        out_shape=jax.ShapeDtypeStruct((b, hp, wp, CORR_CHANNELS), f1.dtype),
        grid=(b, hp // _TILE, wp // _TILE),
        in_specs=[
            pl.BlockSpec((1, _TILE, _TILE, c), lambda i, j, k: (i, j, k, 0)),
            pl.BlockSpec((1, h, w, c), lambda i, j, k: (i, 0, 0, 0)),
            pl.BlockSpec((1, hp + 2 * r, wp + 2 * r, 2),
                         lambda i, j, k: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TILE, _TILE, CORR_CHANNELS),
                               lambda i, j, k: (i, j, k, 0)),
        interpret=interpret,
    )(f1p, f2, flowp)
    return out[:, :h, :w, :]


def _warp_corr_supported(b: int, h: int, w: int, c: int, itemsize: int) -> bool:
    """VMEM gate: resident f2 + padded flow + the per-step working set
    (one-hot chunk, f1/out blocks, warped halo), double-buffered."""
    r = CORR_RADIUS
    hp = h + (-h) % _TILE
    wp = w + (-w) % _TILE
    halo = _TILE + 2 * r
    f2_bytes = h * w * c * itemsize
    flow_bytes = (hp + 2 * r) * (wp + 2 * r) * 2 * 4
    onehot_bytes = _halo_chunk_rows(h * w) * halo * h * w * 4
    # the int32 iota3 comparand materialized alongside the one-hot chunk is
    # the same (rows, halo, h*w) extent at 4 bytes — count it, or a
    # near-budget shape passes the gate and fails VMEM assignment
    iota_bytes = onehot_bytes
    work_bytes = (halo * halo * c * 4  # warped tile
                  + _TILE * _TILE * (c + CORR_CHANNELS) * itemsize)
    return 2 * (f2_bytes + flow_bytes + onehot_bytes + iota_bytes
                + work_bytes) <= _VMEM_BUDGET


def _fused_compile_ok(h: int, w: int, dtype) -> bool:
    """Admission gate for the fused kernel under ``auto`` (axon v5e backend).

    Empirical findings (tools/warp_corr_profile.json, round 4):

    - COMPILE: the Mosaic remote compile helper crashes (HTTP 500, no
      diagnostics) or wedges for 30+ minutes past an undocumented complexity
      cliff — hw ≤ 256 (PWC levels 5/4 at a 256² input) compiles in seconds
      in both dtypes and is bit-exact; 32² fp32 compiled but bf16 WEDGED;
      64² crashes.
    - WIN, so far unproven vs the RIGHT baseline: per-level the fused kernel
      beat the gather-warp + fused-XLA-volume composition at L5 fp32 (+19 %)
      and L4 bf16 (+28 %) — but production ``auto`` falls back to the
      gather-warp + PALLAS-corr composition (round-3's measured winner),
      which those numbers do not compare against.

    Until the whole-forward sweep (``profile_warp_corr.py --forward``: auto
    vs auto_nofused) demonstrates a win over the real fallback, ``auto``
    keeps the fused kernel DISABLED; ``VFT_FUSED_WARP_CORR=1`` enables it
    within the compiling set — dtype-aware: hw ≤ 1024 for fp32 (32²
    compiled), hw ≤ 256 for bf16 (32² bf16 wedged the helper); "0"
    disables even under a future default-on.
    """
    import os

    force = os.environ.get("VFT_FUSED_WARP_CORR")
    if force == "1":
        # dtype-aware cap: 32² (hw=1024) compiled in fp32 but WEDGED the
        # Mosaic helper for 30+ min in bf16 — bf16 stays at the tighter bound
        return h * w <= (1024 if jnp.dtype(dtype) == jnp.float32 else 256)
    return False


def warp_corr81(f1: jnp.ndarray, f2: jnp.ndarray, flow: jnp.ndarray,
                impl: str = "xla", warp_impl: str = "auto") -> jnp.ndarray:
    """Backward-warp ``f2`` by ``flow`` (already level-scaled) and correlate.

    ``impl`` — ``xla``: the two-stage composition (warp → fused-XLA volume).
    ``auto``/``pallas``: the fused kernel where the VMEM gate and the compile
    allowlist admit the shape; otherwise the composition with ``corr81(impl)``
    — which itself takes the tiled Pallas volume kernel where supported (the
    round-3 measured win). ``pallas_interpret``: fused kernel in the Pallas
    interpreter (CPU tests).

    ``warp_impl`` — the composition's warp lowering: ``gather`` | ``onehot``
    (MXU selector matmuls, ops/warp.bilinear_sample_onehot) | ``auto``
    (VFT_WARP_IMPL, unset → gather).
    """
    from .warp import warp_backward

    if impl == "pallas_interpret":
        return warp_corr81_pallas(f1, f2, flow, interpret=True)
    if impl in ("pallas", "auto") and jax.default_backend() == "tpu" \
            and f1.dtype in _KERNEL_DTYPES:
        b, h, w, c = f1.shape
        if _fused_compile_ok(h, w, f1.dtype) and \
                _warp_corr_supported(b, h, w, c, jnp.dtype(f1.dtype).itemsize):
            return warp_corr81_pallas(f1, f2, flow)
    return corr81(f1, warp_backward(f2, flow, warp_impl), impl)


def corr81(f1: jnp.ndarray, f2: jnp.ndarray, impl: str = "xla") -> jnp.ndarray:
    """Dispatch: ``xla`` (default), ``auto``/``pallas``, or ``pallas_interpret``
    (tests). ``auto`` picks the measured winner per shape — the Pallas kernels
    where the VMEM gates admit them (fp32 b2×256²: +43 % over xla, round 3;
    bf16 validated round 4), the fused XLA formulation everywhere else."""
    if impl == "xla":
        return corr81_xla(f1, f2)
    b, h, w, c = f1.shape
    if impl == "pallas_interpret":
        if h > _TILE or w > _TILE:
            return corr81_pallas_tiled(f1, f2, interpret=True)
        return corr81_pallas(f1, f2, interpret=True)
    if impl in ("pallas", "auto"):
        if jax.default_backend() != "tpu" or f1.dtype not in _KERNEL_DTYPES:
            # Mosaic compiles TPU-only (tests use pallas_interpret);
            # unsupported dtypes and non-TPU backends take the XLA path
            return corr81_xla(f1, f2)
        # gate on the LARGER operand itemsize: warp_corr81's fallback feeds a
        # bf16 f1 with an fp32 warped f2, and the resident buffer is f2's
        isz = max(jnp.dtype(f1.dtype).itemsize, jnp.dtype(f2.dtype).itemsize)
        if h <= _TILE and w <= _TILE:
            # small spatial sizes keep the single-block kernel and its
            # empirically calibrated B-scaled budget; shapes it rejects go to
            # XLA (the tiled kernel targets the >16² spatial regime only)
            if _pallas_supported(b, h, w, c, isz):
                return corr81_pallas(f1, f2)
            return corr81_xla(f1, f2)
        if _pallas_tiled_supported(b, h, w, c, isz):
            return corr81_pallas_tiled(f1, f2)
        return corr81_xla(f1, f2)
    raise ValueError(
        f"unknown corr impl {impl!r}; expected xla|auto|pallas|pallas_interpret")
