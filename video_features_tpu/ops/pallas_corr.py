"""Cost-volume correlation kernels: XLA formulation + hand-tiled Pallas kernel.

The reference implements PWC's 81-tap correlation as four raw CUDA kernels
JIT-compiled through CuPy (``/root/reference/models/pwc/pwc_src/correlation.py:17-242``).
Semantics: pad fmap2 by 4 px, mean-over-channels dot product between each pixel
of fmap1 and its 9×9 neighborhood in fmap2 → ``(B, H, W, 81)`` with channel
``k = (dy+4)·9 + (dx+4)`` (``:79-81``; forward-only — inference framework).

Two TPU implementations, selectable per call (``--pwc_corr``):

- ``xla``: 81 shifted elementwise products + channel mean. XLA fuses the shifts
  into a few HBM passes; this is the parity-proven default.
- ``pallas``: VMEM-resident kernels. Spatial sizes ≤16² run the single-block
  kernel (whole image per grid step); larger sizes run the spatially TILED
  kernel (``corr81_pallas_tiled``: 16×16 output blocks, the haloed f2 held
  VMEM-resident per image) — the axon Mosaic backend rejects >16² compute
  tiles, so tiling is how the 32²/64² PWC levels get in-kernel. Shapes whose
  resident f2 exceeds the VMEM budget (``_pallas_tiled_supported``) and
  non-fp32 dtypes fall back to ``xla``; dispatch is static per call site, so
  one PWC forward may mix kernel and XLA levels.

Both are exercised by tests/test_pallas_corr.py (Pallas in interpreter mode on
CPU, compiled on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

CORR_RADIUS = 4
CORR_CHANNELS = (2 * CORR_RADIUS + 1) ** 2  # 81

# conservative per-core VMEM budget for the tile working set (bytes)
_VMEM_BUDGET = 12 * 1024 * 1024


def corr81_xla(f1: jnp.ndarray, f2: jnp.ndarray) -> jnp.ndarray:
    """Channel-mean cost volume over the 9×9 displacement window (pure XLA).

    Accumulates in fp32 whatever the feature dtype; the result is cast back to
    the input dtype so a bf16 forward stays bf16 downstream (a fp32 volume
    would silently promote every decoder conv through ``concatenate``).
    """
    b, h, w, c = f1.shape
    r = CORR_RADIUS
    dtype = f1.dtype
    f2p = jnp.pad(f2, ((0, 0), (r, r), (r, r), (0, 0)))
    f1 = f1.astype(jnp.float32)
    taps = []
    for dy in range(-r, r + 1):
        for dx in range(-r, r + 1):
            shifted = f2p[:, r + dy : r + dy + h, r + dx : r + dx + w, :].astype(jnp.float32)
            taps.append(jnp.mean(f1 * shifted, axis=-1))
    # stack taps on axis 1 then move to the channel position: stacking 81
    # single-channel (…, 1) arrays directly on the minor axis makes XLA pad
    # each temp to the 128-lane tile — a 128× memory blowup that OOM'd the
    # 64-pair I3D sandwich at 256×341 (15.8 GiB of f32[64,64,96,1] copies).
    # With W as the minor dim the temps pad ≤1.34× and one cheap relayout
    # produces the (B, H, W, 81) the decoders consume.
    return jnp.moveaxis(jnp.stack(taps, axis=1), 1, -1).astype(dtype)


def _corr81_kernel(f1_ref, f2p_ref, out_ref):
    """One batch element per grid step; everything VMEM-resident.

    f1 (1, H, W, C), f2p (1, H+8, W+8, C) → out (1, H, W, 81). The 81 window
    taps are unrolled statically; each is a VPU multiply + lane reduction.
    """
    f1 = f1_ref[0].astype(jnp.float32)
    h, w, c = f1.shape
    taps = []
    for dy in range(2 * CORR_RADIUS + 1):
        for dx in range(2 * CORR_RADIUS + 1):
            shifted = f2p_ref[0, dy : dy + h, dx : dx + w, :].astype(jnp.float32)
            taps.append(jnp.sum(f1 * shifted, axis=-1) * (1.0 / c))
    out_ref[0] = jnp.stack(taps, axis=-1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def corr81_pallas(f1: jnp.ndarray, f2: jnp.ndarray, interpret: bool = False) -> jnp.ndarray:
    """Pallas tile kernel; grid over the batch axis.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    from jax.experimental import pallas as pl

    b, h, w, c = f1.shape
    r = CORR_RADIUS
    f2p = jnp.pad(f2, ((0, 0), (r, r), (r, r), (0, 0)))
    return pl.pallas_call(
        _corr81_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, w, CORR_CHANNELS), jnp.float32),
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((1, h + 2 * r, w + 2 * r, c), lambda i: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, w, CORR_CHANNELS), lambda i: (i, 0, 0, 0)),
        interpret=interpret,
    )(f1, f2p)


_TILE = 16  # largest tile the axon Mosaic backend compiles (>16² → HTTP 500)


def _corr81_kernel_tiled(f1_ref, f2p_ref, out_ref):
    """Spatially tiled kernel: one 16×16 output block per grid step.

    Grid (b, nh, nw). ``f1`` arrives as a (1, 16, 16, C) block; the padded
    ``f2`` arrives as the FULL (1, Hp+8, Wp+8, C) image — its block index is
    constant across (j, k), so Mosaic keeps it VMEM-resident instead of
    re-fetching per step. The 24×24 haloed window for this block is a dynamic
    slice; the 81 taps are static shifts within it.
    """
    from jax.experimental import pallas as pl

    j = pl.program_id(1)
    k = pl.program_id(2)
    halo = 2 * CORR_RADIUS
    tile = f2p_ref[0, pl.dslice(j * _TILE, _TILE + halo),
                   pl.dslice(k * _TILE, _TILE + halo), :]
    f1 = f1_ref[0].astype(jnp.float32)
    c = f1.shape[-1]
    taps = []
    for dy in range(2 * CORR_RADIUS + 1):
        for dx in range(2 * CORR_RADIUS + 1):
            shifted = tile[dy : dy + _TILE, dx : dx + _TILE, :].astype(jnp.float32)
            taps.append(jnp.sum(f1 * shifted, axis=-1) * (1.0 / c))
    out_ref[0] = jnp.stack(taps, axis=-1)


@functools.partial(jax.jit, static_argnames=("interpret",))
def corr81_pallas_tiled(f1: jnp.ndarray, f2: jnp.ndarray,
                        interpret: bool = False) -> jnp.ndarray:
    """Tiled Pallas cost volume for spatial sizes beyond the 16² Mosaic cap.

    Pads H/W to multiples of the tile (zero rows/cols — out-of-bounds f2 taps
    contribute zeros, exactly the reference's zero-padding; the padded f1 rows
    produce extra output rows sliced off afterwards).
    """
    from jax.experimental import pallas as pl

    b, h, w, c = f1.shape
    r = CORR_RADIUS
    ph = (-h) % _TILE
    pw = (-w) % _TILE
    f1p = jnp.pad(f1, ((0, 0), (0, ph), (0, pw), (0, 0)))
    f2p = jnp.pad(f2, ((0, 0), (r, r + ph), (r, r + pw), (0, 0)))
    hp, wp = h + ph, w + pw
    out = pl.pallas_call(
        _corr81_kernel_tiled,
        out_shape=jax.ShapeDtypeStruct((b, hp, wp, CORR_CHANNELS), jnp.float32),
        grid=(b, hp // _TILE, wp // _TILE),
        in_specs=[
            pl.BlockSpec((1, _TILE, _TILE, c), lambda i, j, k: (i, j, k, 0)),
            pl.BlockSpec((1, hp + 2 * r, wp + 2 * r, c), lambda i, j, k: (i, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, _TILE, _TILE, CORR_CHANNELS),
                               lambda i, j, k: (i, j, k, 0)),
        interpret=interpret,
    )(f1p, f2p)
    return out[:, :h, :w, :]


def _pallas_tiled_supported(b: int, h: int, w: int, c: int) -> bool:
    """VMEM gate for the tiled kernel: the resident PER-IMAGE f2p + one
    f1/out block pair, double-buffered, must fit the budget.

    Unlike the single-block kernel (whose empirical budget scales with B —
    see ``_pallas_supported``), the tiled call's buffers are streamed per
    block: validated compiled on the axon v5e backend at b=16 × 64² × c32
    (the largest PWC corr level at a 256² input), where a whole-buffer VMEM
    assignment could not possibly fit — so only the per-step working set
    counts here."""
    r = CORR_RADIUS
    hp = h + (-h) % _TILE
    wp = w + (-w) % _TILE
    f2p_bytes = (hp + 2 * r) * (wp + 2 * r) * c * 4
    blk_bytes = _TILE * _TILE * (c + CORR_CHANNELS) * 4
    return 2 * (f2p_bytes + blk_bytes) <= _VMEM_BUDGET


def _pallas_supported(b: int, h: int, w: int, c: int) -> bool:
    """Shape gate for the compiled kernel on the axon v5e backend (observed):

    - XLA's memory-space assignment keeps the pallas call's full operands +
      output in VMEM with double buffering, so the budget must cover
      B × (f1 + padded f2 + out) × 2;
    - tiles larger than 16×16 crash the backend's Mosaic compile subprocess
      (HTTP 500 from tpu_compile_helper); ≤16² compiles and is bit-exact.

    PWC's coarse pyramid levels (4²–16² at a 256² input) take the kernel;
    finer levels fall back to the fused XLA formulation — dispatch is static
    per call site, so a single forward mixes both.
    """
    if h > 16 or w > 16:
        return False
    r = CORR_RADIUS
    per_elem = 4 * (h * w * c + (h + 2 * r) * (w + 2 * r) * c + h * w * CORR_CHANNELS)
    return 2 * b * per_elem <= _VMEM_BUDGET


def corr81(f1: jnp.ndarray, f2: jnp.ndarray, impl: str = "xla") -> jnp.ndarray:
    """Dispatch: ``xla`` (default), ``pallas``, or ``pallas_interpret`` (tests)."""
    if impl == "xla":
        return corr81_xla(f1, f2)
    b, h, w, c = f1.shape
    if impl == "pallas_interpret":
        if h > _TILE or w > _TILE:
            return corr81_pallas_tiled(f1, f2, interpret=True)
        return corr81_pallas(f1, f2, interpret=True)
    if impl == "pallas":
        if jax.default_backend() != "tpu" or f1.dtype != jnp.float32:
            # Mosaic compiles TPU-only (tests use pallas_interpret); non-fp32
            # dtypes and non-TPU backends take the XLA path
            return corr81_xla(f1, f2)
        if h <= _TILE and w <= _TILE:
            # small spatial sizes keep the single-block kernel and its
            # empirically calibrated B-scaled budget; shapes it rejects go to
            # XLA (the tiled kernel targets the >16² spatial regime only)
            if _pallas_supported(b, h, w, c):
                return corr81_pallas(f1, f2)
            return corr81_xla(f1, f2)
        if _pallas_tiled_supported(b, h, w, c):
            return corr81_pallas_tiled(f1, f2)
        return corr81_xla(f1, f2)
    raise ValueError(f"unknown corr impl {impl!r}; expected xla|pallas|pallas_interpret")
