"""Bilinear gather ops replacing torch ``grid_sample`` on TPU.

Both flow nets sample feature maps at fractional pixel coordinates: RAFT's
correlation lookup (``/root/reference/models/raft/raft_src/utils/utils.py:57-71``,
``align_corners=True`` + zero padding) and PWC's backward warp
(``/root/reference/models/pwc/pwc_src/pwc_net.py:23-41``; under the pinned
torch 1.2 ``grid_sample`` also behaves as align_corners=True). Working in *pixel*
coordinates directly — the normalize/denormalize round-trip of grid_sample with
align_corners=True is the identity — keeps the math exact and avoids the (W−1)/2
rescaling noise.

XLA lowers the gathers to dynamic-slice-friendly ops; all shapes static.
"""

from __future__ import annotations

import jax.numpy as jnp


def bilinear_sample(img: jnp.ndarray, coords_xy: jnp.ndarray) -> jnp.ndarray:
    """Sample ``img`` (N, H, W, C) at pixel coords (N, P, Q, 2) (x, y) order.

    Zero padding: out-of-bounds corner taps contribute 0 — per-corner masking,
    matching ``grid_sample(..., padding_mode='zeros', align_corners=True)``.
    Returns (N, P, Q, C) float32.
    """
    n, h, w, c = img.shape
    x = coords_xy[..., 0].astype(jnp.float32)
    y = coords_xy[..., 1].astype(jnp.float32)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    out = None
    flat = img.reshape(n, h * w, c).astype(jnp.float32)
    for dy, dx, wgt in (
        (0, 0, (1 - wy) * (1 - wx)),
        (0, 1, (1 - wy) * wx),
        (1, 0, wy * (1 - wx)),
        (1, 1, wy * wx),
    ):
        xi = x0 + dx
        yi = y0 + dy
        inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xg = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yg = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        idx = (yg * w + xg).reshape(n, -1)
        vals = jnp.take_along_axis(flat, idx[..., None], axis=1).reshape(*x.shape, c)
        contrib = vals * (wgt * inb.astype(jnp.float32))[..., None]
        out = contrib if out is None else out + contrib
    return out


def warp_backward(img: jnp.ndarray, flow: jnp.ndarray) -> jnp.ndarray:
    """PWC backward warp: sample ``img`` at ``base + flow``, zeroing partial taps.

    Reference semantics (``pwc_net.py:23-41``): a ones channel rides along; where its
    sampled value is ≤ 0.999 (any out-of-bounds leakage) the whole output pixel is
    zeroed, otherwise scaled by exactly 1.0.

    ``img`` (N, H, W, C); ``flow`` (N, H, W, 2) in pixels (u, v). Returns (N, H, W, C).
    """
    n, h, w, _ = flow.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    base = jnp.stack([xs, ys], axis=-1)[None]
    coords = base + flow
    ones = jnp.ones(img.shape[:-1] + (1,), jnp.float32)
    sampled = bilinear_sample(jnp.concatenate([img.astype(jnp.float32), ones], -1), coords)
    out, mask = sampled[..., :-1], sampled[..., -1:]
    keep = (mask > 0.999).astype(jnp.float32)
    return out * keep


def coords_grid(n: int, h: int, w: int) -> jnp.ndarray:
    """(N, H, W, 2) grid of (x, y) pixel coordinates (RAFT ``coords_grid``)."""
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    return jnp.broadcast_to(jnp.stack([xs, ys], axis=-1), (n, h, w, 2))


def upsample_bilinear_align(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align_corners=True on (N, H, W, C).

    torch ``F.interpolate(..., mode='bilinear', align_corners=True)``: output pixel i
    maps to input coordinate i·(H−1)/(out−1). In-bounds by construction, so the
    zero-padding masks in :func:`bilinear_sample` never fire.
    """
    n, h, w, _ = img.shape
    sy = (h - 1) / (out_h - 1) if out_h > 1 else 0.0
    sx = (w - 1) / (out_w - 1) if out_w > 1 else 0.0
    ys = jnp.arange(out_h, dtype=jnp.float32) * sy
    xs = jnp.arange(out_w, dtype=jnp.float32) * sx
    gx, gy = jnp.meshgrid(xs, ys)
    coords = jnp.broadcast_to(jnp.stack([gx, gy], -1), (n, out_h, out_w, 2))
    return bilinear_sample(img, coords)


def resize_bilinear_torch(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align_corners=False (torch default), NHWC.

    Source coordinate: (i + 0.5)·scale − 0.5, clamped taps at the border (replicate
    edge — torch clamps the corner indices, it does not zero them).
    """
    n, h, w, c = img.shape
    sy = h / out_h
    sx = w / out_w
    ys = jnp.clip((jnp.arange(out_h, dtype=jnp.float32) + 0.5) * sy - 0.5, 0.0, None)
    xs = jnp.clip((jnp.arange(out_w, dtype=jnp.float32) + 0.5) * sx - 0.5, 0.0, None)
    # clamping low keeps coords ≥ 0; high side handled by corner clipping because
    # weights for the out-of-range corner go to the in-range one only when the
    # coordinate itself is in range — clamp high too for exactness
    ys = jnp.minimum(ys, h - 1)
    xs = jnp.minimum(xs, w - 1)
    gx, gy = jnp.meshgrid(xs, ys)
    coords = jnp.broadcast_to(jnp.stack([gx, gy], -1), (n, out_h, out_w, 2))
    return bilinear_sample(img, coords)
