"""Bilinear gather ops replacing torch ``grid_sample`` on TPU.

Both flow nets sample feature maps at fractional pixel coordinates: RAFT's
correlation lookup (``/root/reference/models/raft/raft_src/utils/utils.py:57-71``,
``align_corners=True`` + zero padding) and PWC's backward warp
(``/root/reference/models/pwc/pwc_src/pwc_net.py:23-41``; under the pinned
torch 1.2 ``grid_sample`` also behaves as align_corners=True). Working in *pixel*
coordinates directly — the normalize/denormalize round-trip of grid_sample with
align_corners=True is the identity — keeps the math exact and avoids the (W−1)/2
rescaling noise.

XLA lowers the gathers to dynamic-slice-friendly ops; all shapes static.
"""

from __future__ import annotations

import math
import os

import jax.numpy as jnp
from jax import lax


def bilinear_sample(img: jnp.ndarray, coords_xy: jnp.ndarray) -> jnp.ndarray:
    """Sample ``img`` (N, H, W, C) at pixel coords (N, P, Q, 2) (x, y) order.

    Zero padding: out-of-bounds corner taps contribute 0 — per-corner masking,
    matching ``grid_sample(..., padding_mode='zeros', align_corners=True)``.
    Returns (N, P, Q, C) float32.
    """
    n, h, w, c = img.shape
    x = coords_xy[..., 0].astype(jnp.float32)
    y = coords_xy[..., 1].astype(jnp.float32)

    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    out = None
    flat = img.reshape(n, h * w, c).astype(jnp.float32)
    for dy, dx, wgt in (
        (0, 0, (1 - wy) * (1 - wx)),
        (0, 1, (1 - wy) * wx),
        (1, 0, wy * (1 - wx)),
        (1, 1, wy * wx),
    ):
        xi = x0 + dx
        yi = y0 + dy
        inb = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xg = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yg = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        idx = (yg * w + xg).reshape(n, -1)
        vals = jnp.take_along_axis(flat, idx[..., None], axis=1).reshape(*x.shape, c)
        contrib = vals * (wgt * inb.astype(jnp.float32))[..., None]
        out = contrib if out is None else out + contrib
    return out


def equalize_chunks(n: int, cap: int) -> tuple[int, int, int]:
    """Split ``n`` items into equal chunks of at most ``cap``.

    Returns ``(n_chunks, chunk, pad)`` with ``chunk ≤ cap`` and
    ``n_chunks · chunk = n + pad``. Equalized (vs bare ceil-capping) so an
    unlucky ``n``/``cap`` ratio cannot nearly double the padded tail's work
    (e.g. n=4096, cap=3787 → two 3787-chunks would be 45 % padding; this
    yields two 2048-chunks). Shared by every budget-chunked query loop
    (the one-hot warp here, RAFT's on-demand matmul lookup)."""
    cap = max(1, min(n, cap))
    n_chunks = -(-n // cap)
    chunk = -(-n // n_chunks)
    return n_chunks, chunk, n_chunks * chunk - n


def bilinear_sample_onehot(img: jnp.ndarray, coords_xy: jnp.ndarray,
                           chunk_budget: int = 8_000_000) -> jnp.ndarray:
    """:func:`bilinear_sample` on the MXU — weighted one-hot selector matmuls
    instead of corner gathers.

    Bilinear interpolation is separable: with ``Sy[p, i] = (1−fy)·[i = y0] +
    fy·[i = y0+1]`` (two adjacent nonzeros per row) and ``Sx`` likewise,
    ``out[p] = Σ_j Sx[p, j] · (Σ_i Sy[p, i] · img[i, j])``. TPU gathers run
    on the scalar unit (the measured PWC floor — docs/architecture.md
    "Data-dependent addressing"); the selector formulation pays
    O(P·H·W·C) MXU MACs instead, the same trade that won 15.5× on RAFT's
    volume lookup (models/raft.py). Zero-padding semantics come for free:
    an out-of-bounds tap index never matches the iota, so its selector row
    weight is zero — identical to grid_sample padding_mode='zeros'
    per-corner masking (the exact per-corner mask: corner (dy, dx) survives
    iff BOTH its row and column are in range).

    Numerics: products are exact (HIGHEST for fp32; bf16 inputs widen into
    an fp32 accumulator); the 4-corner sum associates as
    (vertical lerp) → (horizontal lerp) instead of the gather path's flat
    Σ wᵢ·vᵢ — differences are ≤ 1 ulp of the gather result.

    The (P, W, C) row intermediate is bounded by chunking the query axis to
    ``chunk_budget`` elements per batch element (lax.map over chunks, so one
    buffer is live at a time). Returns (N, P, Q, C) float32.
    """
    n, h, w, c = img.shape
    p_shape = coords_xy.shape[1:-1]
    q = int(math.prod(p_shape)) if p_shape else 1
    x = coords_xy[..., 0].reshape(n, q).astype(jnp.float32)
    y = coords_xy[..., 1].reshape(n, q).astype(jnp.float32)
    y0f = jnp.floor(y)
    x0f = jnp.floor(x)
    fy = y - y0f
    fx = x - x0f
    # int32 tap indices; values far outside [−1, max] simply never match the
    # iota (clip to a sentinel to keep the float→int cast defined for the
    # padded/degenerate coords a static-shape pipeline can produce)
    iy0 = jnp.clip(y0f, -2, h + 1).astype(jnp.int32)
    ix0 = jnp.clip(x0f, -2, w + 1).astype(jnp.int32)

    bf16 = img.dtype == jnp.bfloat16
    sel_dtype = jnp.bfloat16 if bf16 else jnp.float32
    imgf = img if bf16 else img.astype(jnp.float32)
    prec = lax.Precision.DEFAULT if bf16 else lax.Precision.HIGHEST

    # chunk the query axis: the (n, chunk, w, c) row intermediate is the
    # peak buffer; hold it to ~chunk_budget elements per batch element
    n_chunks, chunk, pad = equalize_chunks(q, chunk_budget // max(w * c, 1))

    def prep(a):
        a = jnp.pad(a, ((0, 0), (0, pad)))
        return a.reshape(n, n_chunks, chunk).transpose(1, 0, 2)

    iota_h = jnp.arange(h, dtype=jnp.int32)
    iota_w = jnp.arange(w, dtype=jnp.int32)

    def body(args):
        iy0c, fyc, ix0c, fxc = args  # each (n, chunk)
        sy = ((iy0c[..., None] == iota_h) * (1 - fyc)[..., None]
              + ((iy0c + 1)[..., None] == iota_h) * fyc[..., None])
        sx = ((ix0c[..., None] == iota_w) * (1 - fxc)[..., None]
              + ((ix0c + 1)[..., None] == iota_w) * fxc[..., None])
        rows = jnp.einsum("npi,nijc->npjc", sy.astype(sel_dtype), imgf,
                          precision=prec, preferred_element_type=jnp.float32)
        return jnp.einsum("npj,npjc->npc", sx.astype(sel_dtype), rows,
                          precision=prec, preferred_element_type=jnp.float32)

    out = lax.map(body, (prep(iy0), prep(fy), prep(ix0), prep(fx)))
    out = out.transpose(1, 0, 2, 3).reshape(n, n_chunks * chunk, c)[:, :q]
    return out.reshape((n,) + p_shape + (c,))


def warp_backward(img: jnp.ndarray, flow: jnp.ndarray,
                  impl: str | None = None) -> jnp.ndarray:
    """PWC backward warp: sample ``img`` at ``base + flow``, zeroing partial taps.

    Reference semantics (``pwc_net.py:23-41``): a ones channel rides along; where its
    sampled value is ≤ 0.999 (any out-of-bounds leakage) the whole output pixel is
    zeroed, otherwise scaled by exactly 1.0.

    ``impl``: ``gather`` (the take_along_axis corner taps) or ``onehot``
    (:func:`bilinear_sample_onehot`, MXU selector matmuls). When None or
    ``auto``, ``VFT_WARP_IMPL`` selects (unset → gather).

    ``img`` (N, H, W, C); ``flow`` (N, H, W, 2) in pixels (u, v). Returns (N, H, W, C).
    """
    if impl is None or impl == "auto":
        impl = os.environ.get("VFT_WARP_IMPL", "gather")
    n, h, w, _ = flow.shape
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    base = jnp.stack([xs, ys], axis=-1)[None]
    coords = base + flow
    if impl not in ("gather", "onehot"):
        raise ValueError(f"warp impl must be gather|onehot, got {impl!r}")
    if impl == "onehot":
        # the mask is separable — Σ inb(corner)·w(corner) =
        # (Σᵢ iny·wyᵢ)(Σⱼ inx·wxⱼ) — so compute it closed-form in fp32
        # instead of riding a ones channel through the (possibly bf16)
        # selector matmuls, where weight rounding (~2⁻⁹) straddles the
        # 0.999 keep-threshold and randomly zeroes interior pixels
        out = bilinear_sample_onehot(img, coords)
        x = coords[..., 0].astype(jnp.float32)
        y = coords[..., 1].astype(jnp.float32)
        fy = y - jnp.floor(y)
        fx = x - jnp.floor(x)
        y0 = jnp.floor(y)
        x0 = jnp.floor(x)

        def axis_w(a0, fa, hi):
            in0 = ((a0 >= 0) & (a0 <= hi - 1)).astype(jnp.float32)
            in1 = ((a0 + 1 >= 0) & (a0 + 1 <= hi - 1)).astype(jnp.float32)
            return in0 * (1 - fa) + in1 * fa

        mask = (axis_w(y0, fy, h) * axis_w(x0, fx, w))[..., None]
    else:
        ones = jnp.ones(img.shape[:-1] + (1,), jnp.float32)
        sampled = bilinear_sample(
            jnp.concatenate([img.astype(jnp.float32), ones], -1), coords)
        out, mask = sampled[..., :-1], sampled[..., -1:]
    keep = (mask > 0.999).astype(jnp.float32)
    return out * keep


def coords_grid(n: int, h: int, w: int) -> jnp.ndarray:
    """(N, H, W, 2) grid of (x, y) pixel coordinates (RAFT ``coords_grid``)."""
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    return jnp.broadcast_to(jnp.stack([xs, ys], axis=-1), (n, h, w, 2))


def upsample_bilinear_align(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align_corners=True on (N, H, W, C).

    torch ``F.interpolate(..., mode='bilinear', align_corners=True)``: output pixel i
    maps to input coordinate i·(H−1)/(out−1). In-bounds by construction, so the
    zero-padding masks in :func:`bilinear_sample` never fire.
    """
    n, h, w, _ = img.shape
    sy = (h - 1) / (out_h - 1) if out_h > 1 else 0.0
    sx = (w - 1) / (out_w - 1) if out_w > 1 else 0.0
    ys = jnp.arange(out_h, dtype=jnp.float32) * sy
    xs = jnp.arange(out_w, dtype=jnp.float32) * sx
    gx, gy = jnp.meshgrid(xs, ys)
    coords = jnp.broadcast_to(jnp.stack([gx, gy], -1), (n, out_h, out_w, 2))
    return bilinear_sample(img, coords)


def resize_bilinear_torch(img: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """Bilinear resize with align_corners=False (torch default), NHWC.

    Source coordinate: (i + 0.5)·scale − 0.5, clamped taps at the border (replicate
    edge — torch clamps the corner indices, it does not zero them).
    """
    n, h, w, c = img.shape
    sy = h / out_h
    sx = w / out_w
    ys = jnp.clip((jnp.arange(out_h, dtype=jnp.float32) + 0.5) * sy - 0.5, 0.0, None)
    xs = jnp.clip((jnp.arange(out_w, dtype=jnp.float32) + 0.5) * sx - 0.5, 0.0, None)
    # clamping low keeps coords ≥ 0; high side handled by corner clipping because
    # weights for the out-of-range corner go to the in-range one only when the
    # coordinate itself is in range — clamp high too for exactness
    ys = jnp.minimum(ys, h - 1)
    xs = jnp.minimum(xs, w - 1)
    gx, gy = jnp.meshgrid(xs, ys)
    coords = jnp.broadcast_to(jnp.stack([gx, gy], -1), (n, out_h, out_w, 2))
    return bilinear_sample(img, coords)
