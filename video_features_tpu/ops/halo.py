"""Frame-axis halo exchange for encode-once sharded flow.

A (B+1)-frame flow window holds B consecutive pairs; sharding the B source
frames across a mesh leaves each shard needing ONE feature map it does not
own — its last pair's target frame, which is the NEXT shard's first frame
(or, on the final shard, the window's extra last frame). Re-encoding that
frame per shard would re-introduce a slice of the double-encode the
shared-frame formulation exists to kill; instead the boundary FEATURE map is
exchanged over ICI with ``lax.ppermute`` (one (1, h', w', c) message per
shard per step — bytes that are ~1/64 of one frame's encoder FLOPs' worth of
HBM traffic).

The same pattern as the spatial halo in :mod:`..parallel.spatial`, but along
the batch/frame axis and carrying model features rather than input rows.
Used by :func:`video_features_tpu.models.raft.raft_forward_frames_sharded`
and :func:`video_features_tpu.models.pwc.pwc_forward_frames_sharded` inside
``shard_map``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def frame_axis_mesh(mesh, n_pairs: int):
    """Shared scaffolding for a frame-axis sharded forward:
    ``(shard_map, axis_name, n_dev)`` for ``mesh``, after validating that the
    pair count divides the mesh. Both sharded flow forwards (and any future
    frame-sharded model) go through here so the shard_map import fallback
    and the divisibility contract have one home.
    """
    try:  # moved out of experimental in newer JAX
        from jax import shard_map
    except ImportError:  # pragma: no cover
        from jax.experimental.shard_map import shard_map

    n_dev = int(mesh.devices.size)
    if n_pairs % n_dev:
        raise ValueError(
            f"pair count {n_pairs} must be divisible by the mesh size {n_dev}")
    return shard_map, mesh.axis_names[0], n_dev


def recv_from_next(x: jnp.ndarray, axis_name: str, n_dev: int) -> jnp.ndarray:
    """Each shard receives the NEXT shard's ``x``; the last shard gets zeros
    (``ppermute`` delivers zeros to devices without a send partner)."""
    if n_dev == 1:
        return jnp.zeros_like(x)
    return lax.ppermute(x, axis_name, [(i + 1, i) for i in range(n_dev - 1)])


def boundary_from_next(first_block: jnp.ndarray, last_block: jnp.ndarray,
                       axis_name: str, n_dev: int) -> jnp.ndarray:
    """Per-shard boundary block for pair formation along a sharded frame axis.

    Shard ``i < n_dev-1`` takes shard ``i+1``'s ``first_block`` (one ppermute
    hop); the final shard takes ``last_block`` — the replicated extra frame's
    features, the only frame of the window encoded outside the sharded batch.
    Shapes: both blocks ``(1, ...)`` per shard, returned unchanged.
    """
    if n_dev == 1:
        return last_block
    recv = recv_from_next(first_block, axis_name, n_dev)
    is_last = lax.axis_index(axis_name) == n_dev - 1
    return jnp.where(is_last, last_block, recv)
