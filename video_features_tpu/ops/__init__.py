"""Numerical ops: host image preprocessing and device-side (XLA/Pallas) kernels."""
