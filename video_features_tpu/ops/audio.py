"""Audio preprocessing: the VGGish log-mel frontend as a fused device op.

:mod:`video_features_tpu.audio.melspec` is the host-side numpy oracle (float64,
bit-comparable with the reference's own frontend). Under ``--device_preproc``
the host ships raw (N, 15600) float32 PCM slabs
(:func:`video_features_tpu.audio.melspec.waveform_to_pcm_slabs`) and
:func:`log_mel_examples` runs INSIDE the jitted VGGish step: strided framing as
a static gather, periodic-Hann windowing, ``jnp.fft.rfft`` magnitude, HTK mel
matmul, ``log(mel + 0.01)`` — all fused with the conv stack that follows. The
constants (window, mel filterbank) are precomputed in float64 by the SAME
melspec code paths the parity test compares against, then cast to float32 once
at trace time. Device math is float32 vs the oracle's float64; the dominant
drift is the complex64 FFT's cancellation noise on high-dynamic-range spectra
(~1.1e-5 worst observed in the log domain; the mel matmul sums non-negative
terms and adds nothing, and it runs at HIGHEST precision so an accelerator's
low-precision matmul default cannot widen it). Pinned ≤2e-5 in
tests/test_device_preproc.py — inexact, which is why the flag is
fingerprinted in cache/key.py for vggish.
"""

from __future__ import annotations

import functools

import numpy as np

import jax.numpy as jnp

from ..audio.melspec import (
    LOG_OFFSET,
    MEL_MAX_HZ,
    MEL_MIN_HZ,
    NUM_MEL_BINS,
    SAMPLE_RATE,
    SAMPLES_PER_EXAMPLE,
    STFT_HOP_SECS,
    STFT_WINDOW_SECS,
    periodic_hann,
    spectrogram_to_mel_matrix,
)

STFT_WINDOW = int(round(SAMPLE_RATE * STFT_WINDOW_SECS))  # 400 samples
STFT_HOP = int(round(SAMPLE_RATE * STFT_HOP_SECS))  # 160 samples
FFT_LENGTH = 2 ** int(np.ceil(np.log2(STFT_WINDOW)))  # 512
EXAMPLE_FRAMES = 96  # STFT frames per (96, 64) example

assert SAMPLES_PER_EXAMPLE == (EXAMPLE_FRAMES - 1) * STFT_HOP + STFT_WINDOW


@functools.lru_cache(maxsize=None)
def _constants():
    """Trace-time constants from the oracle's own float64 code paths.

    Returns (frame gather index matrix (96, 400) int32, periodic Hann window
    (400,) float32, HTK mel filterbank (257, 64) float32).
    """
    idx = (
        np.arange(EXAMPLE_FRAMES)[:, None] * STFT_HOP
        + np.arange(STFT_WINDOW)[None, :]
    ).astype(np.int32)
    window = periodic_hann(STFT_WINDOW).astype(np.float32)
    mel = spectrogram_to_mel_matrix(
        NUM_MEL_BINS, FFT_LENGTH // 2 + 1, SAMPLE_RATE, MEL_MIN_HZ, MEL_MAX_HZ
    ).astype(np.float32)
    return idx, window, mel


def log_mel_examples(pcm: jnp.ndarray) -> jnp.ndarray:
    """Traced (..., 15600) float32 PCM slabs → (..., 96, 64) log-mel examples.

    The device half of the ``--device_preproc`` vggish wire: framing is a
    static advanced-indexing gather (XLA lowers it to a cheap dynamic-slice
    loop over 96 frames), then |rfft| → mel matmul → log. Matches
    ``melspec.log_mel_spectrogram`` + example framing over each slab.
    """
    idx, window, mel = _constants()
    frames = pcm[..., jnp.asarray(idx, jnp.int32)]  # (..., 96, 400)
    spectra = jnp.abs(
        jnp.fft.rfft(frames * jnp.asarray(window, jnp.float32), FFT_LENGTH)
    )
    # HIGHEST: on accelerators whose matmul default is low-precision (TPU
    # bf16) the filterbank reduction would otherwise dwarf the FFT's f32
    # noise floor and break the ≤2e-5 parity pin
    mel_energies = jnp.matmul(spectra, jnp.asarray(mel, jnp.float32),
                              precision="highest")
    return jnp.log(mel_energies + LOG_OFFSET)
