"""Admission control and per-tenant scheduling for the extraction daemon.

The scheduling model follows the packer's own (Ragged Paged Attention,
PAPERS.md): variable-length work units — videos of arbitrary clip counts —
feed fixed-shape device batches, one batch always in flight. This module
decides *whose* video feeds the packer's bucket queues next:

- **admission**: each tenant has a pending-video quota; a request that would
  exceed it is rejected at submit time (cheap, synchronous) instead of
  ballooning the queue. Duplicate in-flight paths are rejected too — every
  downstream structure (assemblies, manifests, the decode pool) is keyed by
  video path.
- **deadline first**: a request may carry a deadline (epoch seconds); among
  tenants whose head video has one, the earliest deadline wins outright
  (EDF). Within a tenant, videos order by (deadline, admission order).
- **weighted fair** otherwise: stride scheduling over tenant virtual time —
  popping a video advances its tenant's clock by ``1/weight``, and the
  lowest clock goes next, so a tenant with weight 2 gets two videos per
  competitor's one under contention while an uncontended queue runs at full
  speed. A tenant waking from idle is clamped to the scheduler's clock
  (no hoarding credit while idle).

Thread-safe: ingest threads (:mod:`.ingest`) submit while the daemon's loop
pops; one lock covers all state.

Telemetry (docs/observability.md): the queue is where queue-wait is
measurable, so it owns that signal end to end — every (re)queue and pop
emits a journal lifecycle event (``video_queued`` / ``video_requeued`` /
``video_popped``), each pop observes the job's wait into the
``queue_wait_seconds`` histogram (labeled tenant × model), and per-tenant
``queue_depth`` gauges track backlog. All emit-only and non-blocking.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Dict, List, Optional

from .request import RequestRejected, ServiceRequest, VideoJob

DEFAULT_QUOTA = 64
DEFAULT_WEIGHT = 1.0


class _Tenant:
    __slots__ = ("name", "weight", "quota", "vtime", "heap", "held")

    def __init__(self, name: str, weight: float, quota: int):
        self.name = name
        self.weight = weight
        self.quota = quota
        self.vtime = 0.0
        # (deadline or +inf, seq, job): EDF then FIFO within the tenant
        self.heap: List[tuple] = []
        # jobs admitted with hold=True but not yet release()d: counted
        # against the quota, invisible to next_job/peek
        self.held = 0


class RequestQueue:
    """Tenant-aware pending-video queue with quotas and fair ordering."""

    def __init__(self, default_weight: float = DEFAULT_WEIGHT,
                 default_quota: int = DEFAULT_QUOTA,
                 tenants: Optional[dict] = None,
                 journal=None, metrics=None):
        self._lock = threading.Lock()
        self._journal = journal  # ..obs.SpanJournal (emit-only) or None
        self._metrics = metrics  # ..obs.MetricsRegistry or None
        self._default_weight = default_weight
        self._default_quota = default_quota
        self._overrides: Dict[str, dict] = {}
        self._tenants: Dict[str, _Tenant] = {}
        self._queued_paths: set = set()
        self._vclock = 0.0
        self._seq = 0
        if tenants:
            self.configure(tenants)

    # --- configuration (start + SIGHUP reload) -------------------------------

    def configure(self, tenants_cfg: dict) -> None:
        """Apply a ``tenants.json``-shaped config::

            {"default": {"weight": 1, "quota": 64},
             "tenants": {"alice": {"weight": 2, "quota": 256}}}

        Existing queues keep their entries; weights/quotas take effect on
        the next pop/submit. Unknown keys are ignored (forward compat).
        """
        if not isinstance(tenants_cfg, dict):
            raise ValueError("tenant config must be a JSON object")
        default = tenants_cfg.get("default") or {}
        overrides = dict(tenants_cfg.get("tenants") or {})
        # the current defaults are queue-lock-guarded state (GUARDED_BY):
        # snapshot them under the lock, parse outside it
        with self._lock:
            cur_weight, cur_quota = self._default_weight, self._default_quota
        # parse + validate EVERYTHING before mutating: a bad tenants.json at
        # SIGHUP must leave the previous config fully intact (the daemon
        # catches ValueError and keeps serving), never a half-applied one —
        # TypeError from a null/str value must not escape the catch either
        try:
            new_weight = float(default.get("weight", cur_weight))
            new_quota = int(default.get("quota", cur_quota))
            parsed = {
                name: (float((ov or {}).get("weight", new_weight)),
                       int((ov or {}).get("quota", new_quota)))
                for name, ov in overrides.items()
            }
        except (TypeError, ValueError) as e:
            raise ValueError(f"tenant config has a non-numeric "
                             f"weight/quota: {e}") from e
        for name, (weight, quota) in [("default", (new_weight, new_quota)),
                                      *parsed.items()]:
            if weight <= 0:
                raise ValueError(f"tenant {name!r}: weight must be > 0")
            if quota < 1:
                raise ValueError(f"tenant {name!r}: quota must be >= 1")
        with self._lock:
            self._default_weight = new_weight
            self._default_quota = new_quota
            self._overrides = overrides
            for name, t in self._tenants.items():
                t.weight, t.quota = parsed.get(name, (new_weight, new_quota))

    # --- telemetry (emit-only, non-blocking; module docstring) ---------------

    def _note_queued(self, job: VideoJob, event: str) -> None:
        if self._journal is not None:
            r = job.request
            self._journal.emit(event, video=job.path, request=r.request_id,
                               tenant=r.tenant, model=r.feature_type)

    def _note_popped(self, job: VideoJob) -> None:
        r = job.request
        if self._metrics is not None:
            # queue-wait: admission (or last requeue) → this pop. The same
            # definition the trace exporter derives from the journal's
            # queued→popped pair, so the histogram and the trace cross-check
            self._metrics.observe("queue_wait_seconds",
                                  max(time.monotonic() - job.queued_at, 0.0),
                                  tenant=r.tenant,
                                  model=r.feature_type or "default")
        if self._journal is not None:
            self._journal.emit("video_popped", video=job.path,
                               request=r.request_id, tenant=r.tenant,
                               model=r.feature_type)

    def _gauge_depth_locked(self, t: _Tenant) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("queue_depth", len(t.heap), tenant=t.name)

    def _tenant_locked(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            ov = self._overrides.get(name) or {}
            weight = float(ov.get("weight", self._default_weight))
            quota = int(ov.get("quota", self._default_quota))
            if weight <= 0:
                raise ValueError(f"tenant {name!r}: weight must be > 0")
            t = self._tenants[name] = _Tenant(name, weight, quota)
        return t

    # --- submission ----------------------------------------------------------

    def submit(self, request: ServiceRequest, videos=None,
               hold: bool = False) -> List[VideoJob]:
        """Admit every video of ``request`` or none; returns the jobs queued.

        ``videos``: the subset to actually queue (the daemon strips
        ``--resume``-done paths); defaults to all of the request's videos.
        Raises :class:`RequestRejected` over quota or on a path already
        pending/in flight.

        ``hold``: validate, reserve the paths, and assign admission seqs,
        but do NOT make the jobs poppable — the daemon lands the WAL
        admission record first and then :meth:`release`\\ s them
        (docs/serving.md "Crash recovery": without the hold, the serving
        loop could pop, dispatch, and crash before the record is durable).
        Held jobs count against the quota and the duplicate set.
        """
        import os

        if videos is None:
            videos = request.videos
        with self._lock:
            t = self._tenant_locked(request.tenant)
            if self._pending_locked(t) + len(videos) > t.quota:
                raise RequestRejected(
                    f"tenant {request.tenant!r} over quota: "
                    f"{self._pending_locked(t)} pending + "
                    f"{len(videos)} submitted > {t.quota} "
                    "(raise it in tenants.json and SIGHUP-reload)")
            paths = [os.path.abspath(p) for p in videos]
            dup = [p for p in paths if p in self._queued_paths]
            if dup:
                raise RequestRejected(
                    f"video(s) already queued by a live request: "
                    f"{', '.join(sorted(dup)[:3])}"
                    + ("…" if len(dup) > 3 else ""))
            jobs = []
            for path in paths:
                self._seq += 1
                job = VideoJob(path, request, seq=self._seq)
                self._queued_paths.add(path)
                jobs.append(job)
            if hold:
                t.held += len(jobs)
                return jobs
            self._publish_jobs_locked(t, jobs)
            return jobs

    def release(self, jobs: List[VideoJob]) -> None:
        """Make ``hold``-admitted jobs poppable (the WAL record landed)."""
        with self._lock:
            by_tenant: Dict[str, List[VideoJob]] = {}
            for job in jobs:
                by_tenant.setdefault(job.request.tenant, []).append(job)
            for tenant, batch in by_tenant.items():
                t = self._tenant_locked(tenant)
                t.held = max(t.held - len(batch), 0)
                self._publish_jobs_locked(t, batch)

    def _publish_jobs_locked(self, t: _Tenant, jobs: List[VideoJob]) -> None:
        was_idle = not t.heap
        for job in jobs:
            heapq.heappush(t.heap, (*job.sort_key(), job))
            self._note_queued(job, "video_queued")
        self._gauge_depth_locked(t)
        if was_idle:
            # waking tenant joins at the scheduler clock: idle time is
            # not banked credit against active tenants
            t.vtime = max(t.vtime, self._vclock)

    def advance_seq(self, seq: int) -> None:
        """Fast-forward the admission counter past ``seq`` (crash recovery,
        serve/wal.py): replayed jobs re-enter with their ORIGINAL seqs, and
        a fresh submission must never mint a colliding seq — the tenant
        heaps tiebreak on it, and two equal (deadline, seq) keys would fall
        through to comparing bare :class:`VideoJob` objects."""
        with self._lock:
            self._seq = max(self._seq, int(seq))

    def requeue(self, job: VideoJob) -> None:
        """Re-admit a transiently-failed video (retry budget handled by the
        daemon). Keeps its original admission seq, so it schedules ahead of
        later submissions — a retry should not go to the back of the line."""
        with self._lock:
            self._requeue_locked(job)

    def requeue_all(self, jobs: List[VideoJob]) -> None:
        """Batch :meth:`requeue` under one lock acquisition — how the daemon
        releases a coalesced leader's waiters (cache/coalesce.py): each
        replay keeps its admission seq, so a video that waited on another
        tenant's identical extraction is not also sent to the back."""
        with self._lock:
            for job in jobs:
                self._requeue_locked(job)

    def _requeue_locked(self, job: VideoJob) -> None:
        t = self._tenant_locked(job.request.tenant)
        was_idle = not t.heap
        heapq.heappush(t.heap, (*job.sort_key(), job))
        self._queued_paths.add(job.path)
        # queue-wait restarts here; end-to-end (admitted_at) keeps running
        job.queued_at = time.monotonic()
        self._note_queued(job, "video_requeued")
        self._gauge_depth_locked(t)
        if was_idle:
            t.vtime = max(t.vtime, self._vclock)

    # --- scheduling ----------------------------------------------------------

    def next_job(self) -> Optional[VideoJob]:
        """Pop the next video: earliest head deadline wins across tenants,
        then lowest weighted virtual time, then name (determinism)."""
        with self._lock:
            active = [t for t in self._tenants.values() if t.heap]
            if not active:
                return None
            t = min(active, key=lambda t: (t.heap[0][0], t.vtime, t.name))
            _, _, job = heapq.heappop(t.heap)
            self._queued_paths.discard(job.path)
            self._vclock = t.vtime
            t.vtime += 1.0 / t.weight
            self._note_popped(job)
            self._gauge_depth_locked(t)
            return job

    def peek_jobs(self, n: int) -> List[VideoJob]:
        """Up to ``n`` likely-next jobs (decode-prefetch hints; approximate
        order is fine — the pool buffers whatever is scheduled early). Jobs,
        not bare paths: the multi-model daemon routes each hint to its
        model's decode transform."""
        with self._lock:
            entries = heapq.nsmallest(
                n, (e for t in self._tenants.values() for e in t.heap))
            return [e[2] for e in entries]

    def drain_tenant(self, tenant: str) -> List[VideoJob]:
        """Remove and return every queued job of ``tenant`` (breaker trip)."""
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return []
            jobs = [e[2] for e in sorted(t.heap)]
            t.heap.clear()
            for job in jobs:
                self._queued_paths.discard(job.path)
            self._gauge_depth_locked(t)
            return jobs

    # --- introspection -------------------------------------------------------

    @staticmethod
    def _pending_locked(t: _Tenant) -> int:
        return len(t.heap) + t.held

    def pending(self, tenant: Optional[str] = None) -> int:
        with self._lock:
            if tenant is not None:
                t = self._tenants.get(tenant)
                return len(t.heap) if t else 0
            return sum(len(t.heap) for t in self._tenants.values())

    def stats(self) -> Dict[str, dict]:
        with self._lock:
            return {
                t.name: {"pending": len(t.heap), "weight": t.weight,
                         "quota": t.quota, "vtime": round(t.vtime, 3)}
                for t in sorted(self._tenants.values(), key=lambda t: t.name)
                if t.heap
            }
