"""Service requests: what a tenant submits and how its state is tracked.

One request = one tenant asking for features over a list of videos, with an
optional deadline. Requests arrive as JSON — a file dropped into the spool
directory (the file stem becomes the request id) or a line over the local
socket API (:mod:`.ingest`) — and resolve into a single per-request result
record (:func:`..io.output.write_request_result`) once every video reached a
terminal state.

Schema (all extra keys ignored)::

    {
      "tenant": "alice",               # optional; "default" when omitted
      "videos": ["/abs/a.mp4", ...],   # required, non-empty list of paths
      "feature_type": "i3d",           # optional; the daemon's --feature_type
                                       # when omitted — admission validates it
                                       # against the loaded model set
                                       # (--serve_models, docs/serving.md)
      "deadline": 1767200000.0,        # optional absolute epoch seconds
      "deadline_sec": 30.0,            # optional relative; wins over nothing
      "request_id": "batch-7"          # optional (socket); spool uses the
    }                                  # file stem and ignores this key
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional, Tuple


class RequestRejected(ValueError):
    """Admission control said no (malformed request, quota, open breaker).

    A rejection is terminal and cheap by design: the submitter gets the
    reason synchronously (socket) or in a ``.result.json`` with state
    ``rejected`` (spool) — nothing was queued.
    """


class VideoJob:
    """One schedulable unit: a video owned by a request.

    ``attempts`` counts terminal-attempt failures so transient errors can
    re-enter the queue (:meth:`..serve.scheduler.RequestQueue.requeue`)
    instead of sleeping a backoff inside the serving loop; ``seq`` is the
    queue's global admission counter (FIFO tiebreak within a tenant);
    ``from_cache`` marks a video served from the feature cache (zero device
    steps) so the request's result record can report its hit count.

    ``admitted_at``/``queued_at`` are monotonic timestamps feeding the
    telemetry histograms (docs/observability.md): ``admitted_at`` is fixed
    at admission (end-to-end latency = done − admitted, requeues included),
    while ``queued_at`` resets on every (re)queue so queue-wait measures the
    CURRENT wait, not the sum over retries.
    """

    __slots__ = ("path", "request", "seq", "attempts", "from_cache",
                 "admitted_at", "queued_at")

    def __init__(self, path: str, request: "ServiceRequest", seq: int = 0):
        self.path = path
        self.request = request
        self.seq = seq
        self.attempts = 0
        self.from_cache = False
        self.admitted_at = time.monotonic()
        self.queued_at = self.admitted_at

    @property
    def deadline(self) -> Optional[float]:
        return self.request.deadline

    @property
    def feature_type(self) -> Optional[str]:
        """The request's model (admission resolves None to the daemon's
        default before the job is queued)."""
        return self.request.feature_type

    def sort_key(self) -> Tuple[float, int]:
        """(deadline or +inf, admission order) — EDF within a tenant."""
        d = self.request.deadline
        return (d if d is not None else float("inf"), self.seq)


class ServiceRequest:
    """Parsed, admitted request plus its live completion state."""

    def __init__(self, request_id: str, tenant: str, videos: Tuple[str, ...],
                 deadline: Optional[float] = None, source: str = "api",
                 feature_type: Optional[str] = None):
        self.request_id = request_id
        self.tenant = tenant
        self.videos = videos
        self.deadline = deadline
        self.source = source
        # None until admission resolves it to the daemon's default model;
        # a request naming an unloaded model is rejected at admission
        self.feature_type = feature_type
        self.submitted_at = time.time()
        self.done: List[str] = []
        self.failed: List[Dict] = []  # {video, error_class, transient, message}
        self.cache_hits = 0  # done videos served from the feature cache
        # an `admitted` record for this request is (being) written to the
        # WAL (serve/wal.py) — publication resolves it; all-resumed requests
        # never log one (the result record is their durability)
        self.wal_logged = False

    @property
    def complete(self) -> bool:
        return len(self.done) + len(self.failed) >= len(self.videos)

    @property
    def state(self) -> str:
        if not self.complete:
            return "pending"
        return "done" if not self.failed else (
            "failed" if not self.done else "partial")

    def result_record(self) -> Dict:
        """The per-request done/failed manifest written at completion."""
        return {
            "request_id": self.request_id,
            "tenant": self.tenant,
            "feature_type": self.feature_type,
            "state": self.state,
            "videos": len(self.videos),
            "done": sorted(self.done),
            "cache_hits": self.cache_hits,
            "failed": sorted(self.failed, key=lambda r: r["video"]),
            "deadline": self.deadline,
            "submitted_at": self.submitted_at,
            "completed_at": time.time(),
            "source": self.source,
        }


def parse_request(payload, request_id: Optional[str] = None,
                  source: str = "api") -> ServiceRequest:
    """Validate a submitted JSON object into a :class:`ServiceRequest`.

    Raises :class:`RequestRejected` with an operator-readable reason on any
    schema violation — the ingest layer turns that into a rejection record,
    never a daemon crash.
    """
    if not isinstance(payload, dict):
        raise RequestRejected(f"request must be a JSON object, got "
                              f"{type(payload).__name__}")
    tenant = payload.get("tenant", "default")
    if not isinstance(tenant, str) or not tenant:
        raise RequestRejected("'tenant' must be a non-empty string")
    videos = payload.get("videos")
    if (not isinstance(videos, (list, tuple)) or not videos
            or not all(isinstance(v, str) and v for v in videos)):
        raise RequestRejected("'videos' must be a non-empty list of paths")
    if len(set(videos)) != len(videos):
        raise RequestRejected("'videos' contains duplicate paths (outputs "
                              "are keyed by video path)")
    deadline = payload.get("deadline")
    if deadline is None and payload.get("deadline_sec") is not None:
        rel = payload["deadline_sec"]
        if not isinstance(rel, (int, float)) or rel <= 0:
            raise RequestRejected("'deadline_sec' must be a positive number")
        deadline = time.time() + float(rel)
    elif deadline is not None and not isinstance(deadline, (int, float)):
        raise RequestRejected("'deadline' must be epoch seconds")
    feature_type = payload.get("feature_type")
    if feature_type is not None and (
            not isinstance(feature_type, str) or not feature_type):
        raise RequestRejected("'feature_type' must be a non-empty string "
                              "naming a loaded model (omit for the daemon's "
                              "default)")
    rid = request_id or payload.get("request_id") or uuid.uuid4().hex[:12]
    if not isinstance(rid, str) or not rid:
        raise RequestRejected("'request_id' must be a non-empty string")
    return ServiceRequest(rid, tenant, tuple(videos),
                          deadline=float(deadline) if deadline is not None
                          else None, source=source,
                          feature_type=feature_type)
