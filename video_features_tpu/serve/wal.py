"""Write-ahead admission log: durable accounting for the serving daemon.

A daemon that dies (SIGKILL, OOM, power) must not silently lose the requests
it accepted: admission state lives only in the in-process
:class:`.scheduler.RequestQueue`, so every accepted request is first appended
to this log — one ``admitted`` JSON line carrying everything replay needs
(request id, tenant, video paths, feature type, deadline, and each video's
admission seq) — and the submit is acknowledged only after the record is on
disk. A ``done``/``failed`` line resolves the entry when the request's result
record publishes; once every entry is resolved the log compacts (atomic
tmp + ``os.replace``, the package-wide write discipline) back to empty.

On the next startup :meth:`ExtractionService.recover` reads the log
tolerantly (a torn tail line from a crash mid-append is counted, never
fatal — the same :func:`..reliability.manifest.read_jsonl` contract the
manifests use), dedupes against published result records and the per-model
done-manifests, and re-admits the survivors with their original admission
seqs and deadlines.

Discipline (the ``AsyncOutputWriter``/``SpanJournal`` single-writer idea,
made synchronous where it matters): producers — ingest threads appending
admissions, the daemon thread appending resolutions — queue records; ONE
writer thread owns the file. An admission append blocks its caller on a
per-record event until the writer has written (and synced) it: that wait is
the ack barrier, and because the writer drains the queue in batches,
concurrent admissions share one fsync (group commit). With
``--wal_fsync_sec > 0`` the fsync itself is batched on a clock — an ack may
then precede durability by up to that window, trading a bounded power-loss
window for near-zero steady-state overhead (process death alone loses
nothing: the bytes are in the page cache).

A full disk NEVER crashes the daemon: any write/sync failure degrades the
log to non-durable — a loud ``wal_degraded`` journal event, a warning, and a
``healthz`` flag — and every subsequent append acks immediately without I/O.
The in-memory unresolved set keeps serving ``healthz``/``stats`` either way.
"""

from __future__ import annotations

import json
import os
import queue
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..reliability import OutputError
from ..reliability.faults import fault_point
from ..reliability.manifest import read_jsonl

WAL_NAME = "admission.wal"

# writer-queue sentinels (identity-compared)
_COMPACT = object()
_CLOSE = object()


def wal_path(spool_dir: str) -> str:
    """The daemon's default WAL location: beside the spool it serves."""
    return os.path.join(spool_dir, WAL_NAME)


class AdmissionLog:
    """Append-only JSONL write-ahead log with a single writer thread.

    Record shapes (all extra keys ignored on replay — additive forward
    compat, like every manifest in the package)::

        {"rec": "admitted", "request": "r1", "tenant": "alice",
         "feature_type": "resnet50", "deadline": null, "source": "spool",
         "videos": ["/abs/a.mp4"], "seqs": [7], "wall": 1767200000.0}
        {"rec": "done", "request": "r1"}      # result record published
        {"rec": "failed", "request": "r1"}    # ditto, terminal-failed state

    ``done``/``failed`` resolve identically; the state is kept for operators
    reading the raw log. Resolution order is independent of admission order:
    a resolve for a not-yet-appended id is remembered and annihilates the
    admission when it arrives (the submit thread can lose a race against a
    very fast daemon thread).
    """

    def __init__(self, path: str, fsync_sec: float = 0.0,
                 journal=None, metrics=None):
        parent = os.path.dirname(path)
        if parent:
            try:
                os.makedirs(parent, exist_ok=True)
            except OSError:
                pass  # the writer's open() fails → degraded, never a crash
        self.path = path
        self._fsync_sec = max(fsync_sec, 0.0)
        self._journal = journal  # ..obs.SpanJournal (emit-only) or None
        self._metrics = metrics  # ..obs.MetricsRegistry or None
        # the "wal" lock (vftlint LOCK_NAMES/LOCK_ORDER): guards the
        # unresolved map + degraded flag. A LEAF scope by construction —
        # no I/O and no other lock is ever taken under it.
        self._lock = threading.Lock()
        self._unresolved: Dict[str, dict] = {}  # request id -> admitted rec
        self._early_resolved: set = set()  # resolved before their append
        self._degraded = False
        self._degraded_reason: Optional[str] = None
        self._closed = False
        self.appended = 0  # records the writer landed (writer thread only)
        self.compactions = 0
        self._last_sync = time.monotonic()
        # replay snapshot: the unresolved admissions a PREVIOUS process left
        # behind, read tolerantly at open (torn tail counted, not fatal)
        self._replay, self.corrupt_lines = self._load()
        for rec in self._replay:
            self._unresolved[rec["request"]] = rec
        self._q: queue.Queue = queue.Queue()
        self._thread = threading.Thread(target=self._drain, daemon=True,
                                        name="wal-writer")
        self._thread.start()

    # --- replay (startup, caller thread, nothing else running yet) -----------

    def _load(self) -> Tuple[List[dict], int]:
        records, corrupt = read_jsonl(self.path)
        admitted: Dict[str, dict] = {}
        resolved = set()
        for rec in records:
            rid = rec.get("request")
            kind = rec.get("rec")
            if not isinstance(rid, str) or not rid:
                corrupt += 1
                continue
            if kind == "admitted" and isinstance(rec.get("videos"), list):
                admitted.setdefault(rid, rec)
            elif kind in ("done", "failed"):
                resolved.add(rid)
            else:
                corrupt += 1
        live = [rec for rid, rec in admitted.items() if rid not in resolved]
        live.sort(key=lambda r: min(r["seqs"]) if r.get("seqs") else 0)
        return live, corrupt

    def replayable(self) -> List[dict]:
        """The previous process's unresolved admissions, admission-ordered.
        Each is resolved (or re-admitted, then resolved on completion) by
        :meth:`ExtractionService.recover`; this log keeps appending after
        them, so an entry stays recoverable until it truly resolves."""
        return list(self._replay)

    def max_seq(self) -> int:
        """Highest admission seq in the replay snapshot (the scheduler's
        counter fast-forwards past it so new admissions never collide)."""
        return max((max(rec["seqs"]) for rec in self._replay
                    if rec.get("seqs")), default=0)

    # --- producer side (ingest threads + daemon thread) ----------------------

    def append_admitted(self, record: dict) -> bool:
        """Durably append one admission BEFORE the submit is acknowledged.

        Blocks until the writer thread has written (and, modulo the fsync
        batching window, synced) the record. Returns False when the log is
        degraded — the caller acked a non-durable admission, which healthz
        and the ``wal_degraded`` event already advertise.
        """
        rid = record["request"]
        with self._lock:
            if self._closed:
                return False
            if rid in self._early_resolved:
                # the daemon resolved this request before our append landed:
                # nothing left to recover, so nothing to write
                self._early_resolved.discard(rid)
                return not self._degraded
            self._unresolved[rid] = record
            degraded = self._degraded
        self._gauge()
        if degraded:
            return False
        landed = threading.Event()
        self._q.put((dict(record, rec="admitted"), landed))
        landed.wait()
        with self._lock:
            return not self._degraded

    def resolve(self, request_id: str, state: str = "done") -> None:
        """Mark one admission terminal (its result record published).

        Fire-and-forget: resolution is an optimization (it bounds replay
        work), not an ack barrier — a crash before the resolve record lands
        just means one redundant, deduped replay next startup.
        """
        if state not in ("done", "failed"):
            raise ValueError(f"WAL resolve state must be done/failed, "
                             f"got {state!r}")
        with self._lock:
            if self._closed:
                return
            known = self._unresolved.pop(request_id, None)
            if known is None:
                self._early_resolved.add(request_id)
                return
            empty = not self._unresolved
            degraded = self._degraded
        self._gauge()
        if degraded:
            return
        self._q.put(({"rec": state, "request": request_id}, None))
        if empty:
            self._q.put((_COMPACT, None))

    # --- introspection (any thread; healthz/stats) ---------------------------

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    def unresolved_count(self) -> int:
        with self._lock:
            return len(self._unresolved)

    def health(self) -> dict:
        """The healthz payload's ``wal`` section (docs/serving.md)."""
        with self._lock:
            degraded = self._degraded
            reason = self._degraded_reason
            unresolved = len(self._unresolved)
        out = {
            "enabled": True,
            "durable": not degraded,
            "unresolved": unresolved,
            "last_sync_age_sec": round(
                time.monotonic() - self._last_sync, 3),
        }
        if reason:
            out["degraded_reason"] = reason
        return out

    def stats(self) -> dict:
        """The stats op's ``wal`` section (additive; no schema bump)."""
        return dict(self.health(), path=self.path, appended=self.appended,
                    compactions=self.compactions,
                    corrupt_lines=self.corrupt_lines)

    def _gauge(self) -> None:
        if self._metrics is not None:
            self._metrics.set_gauge("wal_unresolved", self.unresolved_count())

    # --- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        """Flush and stop the writer (idempotent). Unresolved entries stay
        on disk deliberately — they are exactly what the next process's
        recovery pass must see."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._q.put((_CLOSE, None))
        self._thread.join(timeout=10.0)

    # --- writer thread --------------------------------------------------------

    def _degrade(self, exc: BaseException) -> None:
        """ENOSPC (or any write/sync failure) turns the log non-durable —
        loudly — instead of crashing the daemon or blocking admissions."""
        with self._lock:
            if self._degraded:
                return
            self._degraded = True
            self._degraded_reason = str(exc)[:200]
        print(f"[serve] WAL DEGRADED to non-durable ({self.path}): {exc} — "
              "admissions continue un-logged; a crash before this clears "
              "will lose them (healthz carries the flag)", file=sys.stderr)
        if self._journal is not None:
            self._journal.emit("wal_degraded", path=self.path,
                               error=str(exc)[:200])
        if self._metrics is not None:
            self._metrics.inc("wal_degraded_total")

    def _compact_file(self, f):
        """All entries resolved: rewrite the log empty via tmp+replace and
        return a fresh append handle (``None`` after a failure → degrade)."""
        tmp = self.path + ".tmp"
        with self._lock:
            if self._unresolved:  # raced a new admission: keep appending
                return f
        f.close()
        with open(tmp, "w") as t:
            t.flush()
            os.fsync(t.fileno())
        os.replace(tmp, self.path)
        self.compactions += 1  # single-writer discipline: written only by the single writer thread; stats readers take a GIL-atomic monotone int load
        return open(self.path, "a")

    def _drain(self) -> None:
        try:
            self._drain_loop()
        except Exception as e:  # noqa: BLE001 — fault-barrier: a writer-thread death would hang every submitter blocked on its ack event; degrade loudly and keep acking instead
            self._degrade(e)
            while True:
                rec, landed = self._q.get()
                if landed is not None:
                    landed.set()
                if rec is _CLOSE:
                    break

    def _drain_loop(self) -> None:
        try:
            f = open(self.path, "a")
        except OSError as e:
            self._degrade(e)
            f = None
        last_fsync = time.monotonic()
        while True:
            batch = [self._q.get()]
            while True:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            closing = False
            wrote = False
            for rec, landed in batch:
                if rec is _CLOSE:
                    closing = True
                    continue
                if rec is _COMPACT:
                    if f is not None:
                        try:
                            f = self._compact_file(f)
                        except OSError as e:
                            self._degrade(e)
                            f = None
                    continue
                if f is not None:
                    try:
                        fault_point("wal_append", rec.get("request", ""))
                        f.write(json.dumps(rec, default=str) + "\n")
                        wrote = True
                        self.appended += 1  # single-writer discipline: written only by the single writer thread; stats readers take a GIL-atomic monotone int load
                    except (OSError, OutputError) as e:
                        self._degrade(e)
                        f = None
            if f is not None and wrote:
                try:
                    f.flush()
                    # post-accept / pre-WAL-sync chaos seam: a kill here
                    # proves the ack barrier (the submitter was never told
                    # yes, so losing the record is allowed; an acked record
                    # must survive the restart)
                    fault_point("wal_sync", "")
                    now = time.monotonic()
                    if (self._fsync_sec <= 0.0 or closing
                            or now - last_fsync >= self._fsync_sec):
                        os.fsync(f.fileno())
                        last_fsync = now
                        self._last_sync = now  # single-writer discipline: written only by the single writer thread; healthz readers take a GIL-atomic monotone float load
                except (OSError, OutputError) as e:
                    self._degrade(e)
                    f = None
            # ack AFTER the write+sync attempt — degraded appends ack too
            # (the caller checks the flag), a blocked submitter never hangs
            for _, landed in batch:
                if landed is not None:
                    landed.set()
            if closing:
                break
        if f is not None:
            try:
                f.flush()
                os.fsync(f.fileno())
                f.close()
            except OSError:
                pass
